"""Decision-tree kernels: histogram build + split-gain scan + batched predict.

Reference mapping (``core/dtrain/dt/``):
- per-(node,feature,bin) stats accumulation (``DTWorker.java:763-884``, the
  thread-parallel ``impurity.featureUpdate`` hot loop at ``:844-854``) →
  one ``segment_sum`` scatter-add per feature over the whole row shard, all
  features vmapped;
- ``Impurity.computeImpurity`` split scan (``dt/Impurity.java:38-734``:
  Variance:106, FriedmanMSE:255, Entropy:368, Gini:553) → vectorized prefix
  sums over the bin axis for every (node, feature) at once;
- categorical splits sort bins by response rate then scan prefixes
  (``Impurity.java:33`` comment) → per-(node,feature) ``argsort`` + gather;
- trees are complete binary arrays with positional ids (``dt/Node.java``
  ``indexToLevel`` layout): ``split_feat[node]``, per-bin ``left_mask`` —
  one uniform representation for numeric (bin <= k) and categorical
  (bin-subset) splits (``dt/Split.java`` numeric threshold / SimpleBitSet).

Everything is binned (int bins from the cleaned data plane), so a split is
always "bin ∈ left set" — scoring never touches raw floats.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

EPS = 1e-12


# ------------------------------------------------- per-row select lowering
# cap on the [N, n_nodes] one-hot operand width: past this the select
# form's memory (O(N * nodes) f32, materialized for the matmul) outgrows
# its speed win and the gather form takes over (deep trees: MaxDepth can
# go to 20 per config meta — 2^20-wide one-hots would OOM any HBM)
ONEHOT_MAX_NODES = 512


@lru_cache(maxsize=None)
def _onehot_traversal() -> bool:
    """Row-level tree traversal lowering.  XLA serializes per-row gathers
    (``x[idx]`` with a [N]-shaped ``idx``) on TPU — measured ~21 ns/row,
    which put 64% of resident-GBT tree time into ``take_along_axis`` — so
    on TPU the traversal selects through one-hot matmuls/reductions instead
    (MXU/VPU, ~7x at bench shapes).  CPU keeps native gathers (they are
    fast there and the tests run on the virtual CPU mesh).
    ``SHIFU_TREE_ONEHOT=1/0`` overrides; tests pin both paths.  Resolved
    ONCE per process (cached): traced programs bake the lowering in, so a
    mid-process env flip could not reach already-jitted shapes anyway —
    set it before the first traversal."""
    env = os.environ.get("SHIFU_TREE_ONEHOT", "auto")
    if env in ("0", "off"):
        return False
    if env in ("1", "force"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:                                  # pragma: no cover
        return False


def _use_onehot(n_nodes: int) -> bool:
    return _onehot_traversal() and n_nodes <= ONEHOT_MAX_NODES


def _sel_exact(oh, table):
    """``table[idx]`` as a one-hot matmul (``oh`` = one_hot(idx)).  Exact:
    the one-hot operand is 0/1 and every output element sums exactly one
    term; HIGHEST precision keeps selected f32 values bit-identical to a
    gather."""
    return jnp.matmul(oh, table.astype(jnp.float32),
                      precision=jax.lax.Precision.HIGHEST)


def _row_bin_of(bins, feat):
    """``bins[i, feat[i]]`` without a gather: one-hot mask + reduce over
    the (small) feature axis — fused elementwise on the VPU, exact for
    integer bin ids."""
    featoh = jax.nn.one_hot(jnp.maximum(feat, 0), bins.shape[1],
                            dtype=jnp.float32)
    return jnp.round((featoh * bins.astype(jnp.float32)).sum(1)) \
        .astype(jnp.int32)


def _goes_left(lmask, oh, row_bin):
    """``lmask[node[i], row_bin[i]]`` without a gather: select the node's
    bin-mask row by matmul (0/1 operands, exact at any precision), then
    mask-reduce over bins."""
    lrow = jnp.matmul(oh, lmask.astype(jnp.float32))      # [N, B]
    binoh = jax.nn.one_hot(row_bin, lmask.shape[1], dtype=jnp.float32)
    return (lrow * binoh).sum(1) > 0.5


def _level_select(bins, node, feat, lmask):
    """One traversal level's selects for already-clamped node ids [N]
    (callers mask frozen rows themselves): returns (node_feat [N],
    goes_left [N]).  The single place both lowerings live — `_descend`
    (training descent) and `traverse_nodes` (predict/encode) must never
    drift."""
    if _use_onehot(feat.shape[0]):
        # ONE [N, K] one-hot shared by the feature-id and mask-row selects
        oh = jax.nn.one_hot(node, feat.shape[0], dtype=jnp.float32)
        node_feat = jnp.round(_sel_exact(oh, feat)).astype(jnp.int32)
        row_bin = _row_bin_of(bins, node_feat)
        return node_feat, _goes_left(lmask, oh, row_bin)
    node_feat = feat[node]
    row_bin = jnp.take_along_axis(
        bins, jnp.maximum(node_feat, 0)[:, None],
        axis=1)[:, 0].astype(jnp.int32)    # bins may ride the narrow wire
    return node_feat, lmask[node, row_bin]


@dataclass
class TreeArrays:
    """Complete binary tree, node i's children at 2i+1 / 2i+2."""
    split_feat: np.ndarray   # [nodes] int32, -1 = leaf
    left_mask: np.ndarray    # [nodes, n_bins] bool: bin goes left
    leaf_value: np.ndarray   # [nodes] float32
    depth: int

    @property
    def n_nodes(self) -> int:
        return len(self.split_feat)


def n_tree_nodes(depth: int) -> int:
    return (1 << (depth + 1)) - 1


# ------------------------------------------------------------- histograms
@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "use_pallas",
                                   "mesh", "stats_exact"))
def build_histograms(bins, node_idx, stats, n_nodes: int, n_bins: int,
                     use_pallas: bool = False, mesh=None,
                     stats_exact: bool = False):
    """Per-row stats into (node, feature, bin) cells.

    bins: [N, C] any integer dtype — the trainers keep bins in the compact
    uint8/uint16 wire format all the way into HBM (4x the resident-cache
    capacity of int32); the widen to int32 happens here, in-graph, where
    XLA fuses it into the first consumer.  node_idx: [N] int32 level-local
    (-1 = inactive); stats: [N, S] float32 (S stat channels: [w, w*y] for
    binary/regression trees; per-class weight counts for multiclass).
    Returns [n_nodes, C, n_bins, S].

    Two lowerings: ``use_pallas=True`` → MXU one-hot-matmul kernel
    (:mod:`shifu_tpu.ops.hist_pallas`, ~50x on a TPU chip), shard_mapped
    over the mesh's data axis + psum when ``mesh`` spans devices; default
    → ``segment_sum`` scatter-add (CPU tests, or kernel disabled), which
    GSPMD partitions over the data axis on its own.

    ``stats_exact=True`` asserts every stats value is bf16-exact (small
    integer bag counts x 0/1 targets — RF without a weight column): the
    kernel skips its f32-recovery dots, ~1.6x at bench shapes.
    """
    bins = bins.astype(jnp.int32)      # no-op for int32 inputs
    if use_pallas:
        from .hist_pallas import (build_histograms_pallas,
                                  build_histograms_sharded, target_platform)
        # forced-on CPU meshes/tests take interpret mode; dispatch follows
        # where the op runs, not the host's default backend
        interpret = target_platform(mesh) != "tpu"
        if mesh is not None and mesh.size > 1:
            return build_histograms_sharded(bins, node_idx, stats, n_nodes,
                                            n_bins, mesh, interpret,
                                            stats_exact)
        return build_histograms_pallas(bins, node_idx, stats, n_nodes,
                                       n_bins, interpret, stats_exact)
    return _hist_scatter(bins, node_idx, stats, n_nodes, n_bins)


def _hist_scatter(bins, node_idx, stats, n_nodes: int, n_bins: int):
    """segment_sum lowering of the histogram build — the CPU/test path and
    the batched fallback's per-tree body (one implementation, so batched
    and sequential scatter results are bit-identical)."""
    active = node_idx >= 0
    seg_base = jnp.where(active, node_idx, 0) * n_bins
    masked = stats * active[:, None].astype(stats.dtype)

    def per_feature(bcol):
        idx = seg_base + bcol
        return jax.ops.segment_sum(masked, idx, num_segments=n_nodes * n_bins)

    out = jax.vmap(per_feature, in_axes=1)(bins)        # [C, nodes*bins, S]
    c = bins.shape[1]
    return out.reshape(c, n_nodes, n_bins, -1).transpose(1, 0, 2, 3)


# -------------------------------------------------- analytic cost model
# the scatter lowering's hand model, the CPU-side sibling of
# ``hist_pallas.hist_kernel_cost`` (registered under ``tree.scatter_hist``
# with obs.costs): segment_sum does one add per (row, feature, stat
# channel) plus the index arithmetic; output written once
def scatter_hist_cost(rows: int, n_feat: int, n_bins: int, n_nodes: int,
                      n_stats: int = 2, n_trees: int = 1) -> dict:
    flops = float(rows) * n_feat * (n_stats + 2) * n_trees
    read = 4.0 * rows * n_feat + 4.0 * rows * n_stats * n_trees
    write = 4.0 * n_trees * n_nodes * n_feat * n_bins * n_stats
    return {"flops": flops, "bytes_accessed": read + write}


def _register_cost_models() -> None:
    from ..obs import costs
    costs.register_cost_model("tree.scatter_hist", scatter_hist_cost)


_register_cost_models()


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "use_pallas",
                                   "mesh", "stats_exact"))
def build_histograms_batch(bins, node_idx_b, stats_b, n_nodes: int,
                           n_bins: int, use_pallas: bool = False, mesh=None,
                           stats_exact: bool = False):
    """Tree-batched :func:`build_histograms`: B independent trees' level
    histograms in ONE device program / ONE kernel launch.

    bins: [N, C] shared rows (narrow wire dtypes widen here, in-graph);
    node_idx_b: [TB, N] per-tree level-local positions (-1 = inactive);
    stats_b: [TB, N, S] per-tree channels.  Returns
    [TB, n_nodes, C, n_bins, S].

    The MXU lowering shares the bins one-hot across the tree batch
    (:func:`shifu_tpu.ops.hist_pallas.build_histograms_pallas_batch`) —
    one launch instead of TB, with each tree's slice bit-identical to its
    sequential build; the scatter fallback vmaps the shared per-tree body.
    """
    bins = bins.astype(jnp.int32)
    if use_pallas:
        from .hist_pallas import (build_histograms_batch_sharded,
                                  build_histograms_pallas_batch,
                                  target_platform)
        interpret = target_platform(mesh) != "tpu"
        if mesh is not None and mesh.size > 1:
            return build_histograms_batch_sharded(
                bins, node_idx_b, stats_b, n_nodes, n_bins, mesh, interpret,
                stats_exact)
        return build_histograms_pallas_batch(bins, node_idx_b, stats_b,
                                             n_nodes, n_bins, interpret,
                                             stats_exact)
    return jax.vmap(
        lambda ni, st: _hist_scatter(bins, ni, st, n_nodes, n_bins))(
        node_idx_b, stats_b)


# ------------------------------------------------------------- split scan
def _impurity_score(w, wy, kind: str):
    """Per-partition purity score; gain = score_L + score_R - score_P.
    variance uses sum^2/weight (equivalent to SSE reduction — the sum of
    squares cancels out of the gain, so histograms carry only (w, wy));
    entropy/gini use binary class counts (pos = wy, neg = w - wy)."""
    if kind == "variance":
        return wy * wy / jnp.maximum(w, EPS)
    pos = jnp.clip(wy, 0.0, None)
    neg = jnp.clip(w - wy, 0.0, None)
    tot = jnp.maximum(pos + neg, EPS)
    p = pos / tot
    if kind == "entropy":
        h = -(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, EPS)), 0.0)
              + jnp.where(1 - p > 0, (1 - p) * jnp.log2(jnp.maximum(1 - p, EPS)),
                          0.0))
        return -tot * h
    if kind == "gini":
        return -tot * 2.0 * p * (1 - p)
    raise ValueError(f"unknown impurity {kind!r}")


def _class_score(cnt, kind: str):
    """Multi-class purity score from per-class weight counts ``cnt``
    [..., K]; gain = score_L + score_R - score_P (reference multiclass
    Entropy/Gini, ``dt/Impurity.java:368,553``)."""
    tot = jnp.maximum(cnt.sum(-1), EPS)
    p = jnp.clip(cnt, 0.0, None) / tot[..., None]
    if kind == "entropy":
        h = -(jnp.where(p > 0, p * jnp.log2(jnp.maximum(p, EPS)), 0.0)).sum(-1)
        return -tot * h
    if kind == "gini":
        return -tot * (1.0 - (p * p).sum(-1))
    raise ValueError(f"multi-class impurity must be entropy/gini, "
                     f"got {kind!r}")


@partial(jax.jit, static_argnames=("impurity", "n_classes", "has_cat"))
def best_splits(hist, cat_mask, feat_active, impurity: str = "variance",
                min_instances: float = 1.0, min_gain: float = 0.0,
                n_classes: int = 0, has_cat: bool = True):
    """Best split per node from the level histogram.

    hist: [nodes, C, B, 2] (w, wy) — or, when ``n_classes > 2``,
    [nodes, C, B, K] per-class weight counts (multiclass NATIVE mode).
    cat_mask: [C] bool (categorical → bins sorted by response before the
    prefix scan); feat_active: [C] bool (feature sub-sampling, reference
    featureSubsetStrategy).

    Returns (gain [nodes], feat [nodes], left_mask [nodes, B],
             leaf_value [nodes] — or [nodes, K] class distributions when
             multiclass — and node_w [nodes]).
    """
    multiclass = n_classes > 2
    if multiclass:
        cls = hist                                         # [nodes, C, B, K]
        w = cls.sum(-1)
        if has_cat:
            # scalar "response" for categorical ordering: mean class index
            # (equals pos rate for K=2).  Only the categorical sort reads
            # it — ``has_cat=False`` (static) drops the [nodes, C, B, K]
            # reduction entirely (the active impurity never touches wy)
            kidx = jnp.arange(n_classes, dtype=hist.dtype)
            wy = (cls * kidx).sum(-1)
        else:
            wy = w          # placeholder, compiled out (w_o path unused)
    else:
        w, wy = hist[..., 0], hist[..., 1]
    n_nodes, c, b = w.shape

    # ---- per-(node,feat) bin order: natural for numeric, response-sorted
    # for categorical (empty bins pushed last so prefixes skip them).
    # The argsort/gather machinery only matters for categorical features:
    # ``has_cat=False`` (static, trainers know their cat_mask host-side)
    # compiles it out entirely; otherwise a runtime lax.cond still skips
    # the sort when the mask is dynamically empty
    if has_cat:
        nat_order = jnp.broadcast_to(jnp.arange(b), (n_nodes, c, b))

        def _mixed_order():
            rate = wy / jnp.maximum(w, EPS)
            sort_key = jnp.where(w > 0, -rate, jnp.inf)
            cat_order = jnp.argsort(sort_key, axis=-1)    # [nodes, C, B]
            return jnp.where(cat_mask[None, :, None], cat_order, nat_order)

        order = jax.lax.cond(jnp.any(cat_mask), _mixed_order,
                             lambda: nat_order)
        w_o = jnp.take_along_axis(w, order, axis=-1)
        wy_o = jnp.take_along_axis(wy, order, axis=-1)
    else:
        w_o, wy_o = w, wy

    cw = jnp.cumsum(w_o, axis=-1)
    cwy = jnp.cumsum(wy_o, axis=-1)
    tw, twy = cw[..., -1:], cwy[..., -1:]

    if multiclass:
        cls_o = jnp.take_along_axis(cls, order[..., None], axis=2) \
            if has_cat else cls
        ccls = jnp.cumsum(cls_o, axis=2)                  # [nodes, C, B, K]
        tcls = ccls[:, :, -1:, :]
        score_l = _class_score(ccls, impurity)
        score_r = _class_score(tcls - ccls, impurity)
        score_p = _class_score(tcls, impurity)
        gain = score_l + score_r - score_p                 # [nodes, C, B]
    elif impurity == "friedmanmse":
        # Friedman's improvement (reference ``dt/Impurity.java:313-315``):
        # (w_r*s_l - w_l*s_r)^2 / (w_l*w_r*(w_l+w_r))
        wl, wr = cw, tw - cw
        diff = wr * cwy - wl * (twy - cwy)
        gain = diff * diff / jnp.maximum(wl * wr * (wl + wr), EPS)
    else:
        score_l = _impurity_score(cw, cwy, impurity)
        score_r = _impurity_score(tw - cw, twy - cwy, impurity)
        score_p = _impurity_score(tw, twy, impurity)
        gain = score_l + score_r - score_p                 # [nodes, C, B]

    valid = (cw >= min_instances) & (tw - cw >= min_instances)
    valid = valid & feat_active[None, :, None]
    valid = valid.at[..., -1].set(False)                   # full prefix = no split
    gain = jnp.where(valid, gain, -jnp.inf)

    best_k = jnp.argmax(gain, axis=-1)                     # [nodes, C]
    best_gain_f = jnp.take_along_axis(gain, best_k[..., None], axis=-1)[..., 0]
    best_feat = jnp.argmax(best_gain_f, axis=-1)           # [nodes]
    node_gain = jnp.take_along_axis(best_gain_f, best_feat[:, None],
                                    axis=-1)[:, 0]

    # ---- build left_mask for the winning (feat, k): order[:k+1] goes left
    k_sel = jnp.take_along_axis(best_k, best_feat[:, None], axis=-1)  # [nodes,1]
    if has_cat:
        order_sel = jnp.take_along_axis(
            order, best_feat[:, None, None], axis=1)[:, 0]  # [nodes, B]
        ranks = jnp.argsort(order_sel, axis=-1)             # bin -> position
        left_mask = ranks <= k_sel
    else:   # natural order: position == bin index
        left_mask = jnp.arange(b)[None, :] <= k_sel

    node_w = tw[..., 0, 0]
    if multiclass:
        node_cls = tcls[:, 0, 0, :]                       # [nodes, K]
        leaf_value = node_cls / jnp.maximum(node_w, EPS)[:, None]
    else:
        leaf_value = twy[..., 0, 0] / jnp.maximum(node_w, EPS)
    ok = jnp.isfinite(node_gain) & (node_gain > min_gain)
    feat = jnp.where(ok, best_feat, -1)
    return node_gain, feat.astype(jnp.int32), left_mask & ok[:, None], \
        leaf_value, node_w


def cap_splits_by_leaves(gain, feat, lmask, nodes_cnt, max_leaves: int):
    """Leaf-wise node budget (reference ``DTMaster.java:543-560``
    ``splitNodeForLeafWisedTree``: a split is refused once the tree's node
    count would exceed MaxLeaves; each split adds two nodes).  TPU-shaped
    as best-first-within-level: candidate splits rank by gain and consume
    the remaining budget in that order, the rest freeze to leaves — same
    budget arithmetic, static shapes, no host queue.

    Returns (feat, lmask, new nodes_cnt); ``nodes_cnt`` is a traced int32
    scalar starting at 1 (the root)."""
    cand = feat >= 0
    key = jnp.where(cand, -gain, jnp.inf)
    rank = jnp.argsort(jnp.argsort(key))
    # reference arithmetic: a split is allowed while nodeNum + 1 <=
    # maxLeaves BEFORE its two children land, so for even MaxLeaves the
    # final count may reach maxLeaves + 1 (one more split than a strict
    # <= maxLeaves cap); rank r's split sees nodes_cnt + 2r nodes
    budget = jnp.maximum((max_leaves - nodes_cnt + 1) // 2, 0)
    allow = cand & (rank < budget)
    return (jnp.where(allow, feat, -1), lmask & allow[:, None],
            nodes_cnt + 2 * allow.sum().astype(nodes_cnt.dtype))


# ------------------------------------------------------------------ grow
def _descend(bins, node_idx, feat, lmask):
    """One level of worker tree traversal: rows whose node split move to a
    child's level-local index; rows at leaves freeze at -1 (frozen rows
    select node 0's values through the clamp, masked by ``active``)."""
    node_feat, goes_left = _level_select(
        bins, jnp.maximum(node_idx, 0), feat, lmask)
    active = (node_idx >= 0) & (node_feat >= 0)
    return jnp.where(active, 2 * node_idx + jnp.where(goes_left, 0, 1), -1)


@partial(jax.jit, static_argnames=("n_bins", "depth", "impurity",
                                   "n_classes", "use_pallas", "max_leaves",
                                   "has_cat", "mesh", "stats_exact",
                                   "record_hists"))
def grow_tree_jit(bins, stats, cat, fa, n_bins: int, depth: int,
                  impurity: str, min_instances: float, min_gain: float,
                  n_classes: int = 0, use_pallas: bool = False,
                  max_leaves: int = 0, has_cat: bool = True, mesh=None,
                  stats_exact: bool = False, record_hists: bool = False,
                  tail_extra=None, prev_sf=None, prev_lm=None,
                  valid_upto=None):
    """Whole-tree level-wise growth as ONE jitted program — zero host syncs
    per level (reference ``DTMaster.java:543-600`` level mode; the round-1
    build synced feat/lmask/leaf to host every level).

    Returns (split_feat [total], left_mask [total, B], leaf_value [total],
    gain_fi [C]) device arrays; per-level arrays concatenate into the
    positional complete-binary-tree layout because level l starts at node
    2^l - 1.  ``gain_fi`` accumulates realized split gains per feature
    (gain-weighted FI, reference ``GainInfo`` aggregation).

    ``record_hists=True`` additionally returns (hist_left [depth,
    2^(depth-1), C, B, S], leaf_raw [S, 2^depth]): the per-level LEFT-child
    histograms (level 0 = the full root histogram) and the bottom level's
    raw stat sums, in exactly the accumulator layout
    :func:`build_path_histograms` emits — a coarse-to-fine tail grow on
    the resident prefix keeps its own histograms as the resident
    contribution to the exact totals instead of recomputing them.

    ``tail_extra`` ([depth, 2^(depth-1), C, B, S], optional — with
    ``prev_sf``/``prev_lm`` [total]/[total, B] and ``valid_upto`` traced
    int32) is STALE TAIL EVIDENCE for the split DECISIONS only: the
    previous coarse-to-fine pass's exact tail-only per-level left-child
    histograms (level 0 slot = the full tail root).  Level l's decision
    histogram becomes resident + tail_extra-derived WHEN the evidence is
    routing-compatible: l <= valid_upto (the previous pass confirmed its
    speculation through level l, so its accumulators are exactly routed
    there) AND this tree's structure above l bit-matches the previous
    tree's (checked level-by-level in-graph — GBT trees on smooth
    objectives repeat their upper structure, so the gate stays open deep
    and the speculated thresholds pin to near-full-data optima instead
    of the resident prefix's).  The evidence NEVER enters the recorded
    histograms or the subtraction chain — it only steers speculation;
    exactness is enforced downstream by the verify/repair pass.
    """
    n, c = bins.shape
    feats, lmasks, leaves = [], [], []
    gain_fi = jnp.zeros(c, jnp.float32)
    node_idx = jnp.zeros(n, jnp.int32)       # level-local position, -1 done
    leaf_glob = jnp.zeros(n, jnp.int32)      # global node id where row rests
    nodes_cnt = jnp.int32(1)                 # leaf-wise budget state
    half = max(1 << max(depth - 1, 0), 1)    # record slot width per level
    rec_left: list = []
    leaf_raw = None
    hist_prev = None
    feat_prev = None
    stale = tail_extra is not None
    prefix_ok = jnp.bool_(True)              # structure matches prev tree
    tail_full = None                         # prev level's full tail hist
    for level in range(depth + 1):
        n_nodes = 1 << level
        if level == depth:
            # the bottom level never splits — best_splits' gain/feat/lmask
            # would be discarded, so the full [K, C, B, S] histogram (the
            # deepest, most expensive kernel call of the tree) is pure
            # waste.  Leaf values need only per-node stat sums: one
            # [S, N] x [N, K] dot (HIGHEST precision keeps f32-exact
            # counts; frozen rows mask to no column).
            leaf_raw = _level_leaf_raw(stats, node_idx, n_nodes)
            leaves.append(leaf_values_from_raw(leaf_raw, n_classes))
            feats.append(jnp.full(n_nodes, -1, jnp.int32))
            lmasks.append(jnp.zeros((n_nodes, n_bins), bool))
            break
        if level == 0:
            hist = build_histograms(bins, node_idx, stats, n_nodes, n_bins,
                                    use_pallas, mesh, stats_exact)
            if record_hists:
                rec_left.append(_pad_nodes(hist, half))
            if stale:
                tail_full = tail_extra[0, :1]     # tail root, routing-free
                hist_decide = hist + tail_full
            else:
                hist_decide = hist
        else:
            # histogram SUBTRACTION (the LightGBM trick the reference's
            # level-wise DTMaster never had): build only the LEFT-child
            # histograms — half the one-hot node width, so half the MXU
            # work — and derive each right child as parent - left.  A
            # frozen (unsplit) parent contributes neither child: its left
            # rows map to no node (idx -1) and its right half is masked
            # to zero instead of inheriting the parent's histogram.
            hl = build_histograms(
                bins, _left_child_index(node_idx), stats, n_nodes // 2,
                n_bins, use_pallas, mesh, stats_exact)
            if record_hists:
                rec_left.append(_pad_nodes(hl, half))
            split_ok = feat_prev >= 0
            hr = jnp.where(split_ok[:, None, None, None],
                           hist_prev - hl, 0.0)
            hist = jnp.stack([hl, hr], axis=1) \
                .reshape(n_nodes, c, hl.shape[2], hl.shape[3])
            if stale:
                # derive the tail's full level hist the same way (the
                # evidence chain routes along the PREVIOUS tree, so its
                # subtraction uses prev_sf's split mask), then gate: the
                # prev pass must have confirmed through this level AND
                # this tree's prefix must still match the prev tree's
                t_hl = tail_extra[level][:n_nodes // 2]
                p_feat = jax.lax.dynamic_slice_in_dim(
                    prev_sf, n_nodes // 2 - 1, n_nodes // 2)
                t_hr = jnp.where((p_feat >= 0)[:, None, None, None],
                                 tail_full - t_hl, 0.0)
                tail_full = jnp.stack([t_hl, t_hr], axis=1) \
                    .reshape(n_nodes, c, hl.shape[2], hl.shape[3])
                gate = (jnp.int32(level) <= valid_upto) & prefix_ok
                hist_decide = jnp.where(gate, hist + tail_full, hist)
            else:
                hist_decide = hist
        gain, feat, lmask, leaf, node_w = best_splits(
            hist_decide, cat, fa, impurity, min_instances, min_gain,
            n_classes, has_cat)
        if max_leaves > 0:
            feat, lmask, nodes_cnt = cap_splits_by_leaves(
                gain, feat, lmask, nodes_cnt, max_leaves)
        if stale:
            p_feat = jax.lax.dynamic_slice_in_dim(prev_sf, n_nodes - 1,
                                                  n_nodes)
            p_lm = jax.lax.dynamic_slice_in_dim(prev_lm, n_nodes - 1,
                                                n_nodes, axis=0)
            prefix_ok = prefix_ok & jnp.all(feat == p_feat) & \
                jnp.all(lmask == p_lm)
        feats.append(feat)
        lmasks.append(lmask)
        leaves.append(leaf)
        gain_fi = gain_fi + jax.ops.segment_sum(
            jnp.where(feat >= 0, jnp.maximum(gain, 0.0), 0.0).astype(jnp.float32),
            jnp.maximum(feat, 0), num_segments=c)
        hist_prev, feat_prev = hist, feat
        node_idx = _descend(bins, node_idx, feat, lmask)
        # rows that just descended rest at their child's GLOBAL id; frozen
        # rows keep the node they stopped at — after the loop this is the
        # terminal node per row (predict = leaf_value[leaf_glob], no
        # re-walk; see traverse_nodes for the standalone path)
        leaf_glob = jnp.where(node_idx >= 0,
                              ((1 << (level + 1)) - 1) + node_idx,
                              leaf_glob)
    out = (jnp.concatenate(feats), jnp.concatenate(lmasks, axis=0),
           jnp.concatenate(leaves), gain_fi, leaf_glob)
    if record_hists:
        return out + (jnp.stack(rec_left), leaf_raw)
    return out


def _pad_nodes(hist, width: int):
    """Zero-pad a level histogram's node axis to ``width`` so every level
    shares one accumulator slot shape."""
    k = hist.shape[0]
    if k >= width:
        return hist
    return jnp.concatenate(
        [hist, jnp.zeros((width - k,) + hist.shape[1:], hist.dtype)])


@partial(jax.jit, static_argnames=("depth", "n_bins", "use_pallas", "mesh",
                                   "stats_exact"))
def build_path_histograms(bins, stats, split_feat, left_mask, depth: int,
                          n_bins: int, use_pallas: bool = False, mesh=None,
                          stats_exact: bool = False, hist_bins=None):
    """EVERY level's histograms along a FIXED tree structure in one pass
    over the rows — the coarse-to-fine disk-tail schedule's core op.

    The per-level tail re-stream exists because level l's node routing
    depends on level l-1's chosen splits.  Given a *speculated* structure
    (``split_feat``/``left_mask`` from the resident prefix), the routing
    of every level is known up front, so ONE pass over a window computes
    all of them: per level the LEFT-child histogram only (level 0 = the
    full root histogram; right children derive as parent - left at
    selection time, the same subtraction :func:`grow_tree_jit` uses) plus
    the bottom level's raw leaf stat sums.

    Returns (hist_left [depth, 2^(depth-1), C, B, S] — level l occupying
    the first ``max(2^(l-1), 1)`` node slots, rest zero — and leaf_raw
    [S, 2^depth]).  Layout matches ``grow_tree_jit(record_hists=True)``
    exactly so resident and tail contributions add cell-for-cell.

    ``hist_bins`` (optional [N, K]) narrows the HISTOGRAM build to a
    candidate feature subset while routing still walks the full ``bins``
    — the bounded-candidate scan of the coarse-to-fine tail.
    """
    assert depth >= 1
    n, c = bins.shape
    half = max(1 << (depth - 1), 1)
    node_idx = jnp.zeros(n, jnp.int32)
    idx_levels = [node_idx]                    # level 0: full root
    for level in range(1, depth + 1):
        base = (1 << (level - 1)) - 1
        feat = jax.lax.dynamic_slice_in_dim(split_feat, base,
                                            1 << (level - 1))
        lmask = jax.lax.dynamic_slice_in_dim(left_mask, base,
                                             1 << (level - 1), axis=0)
        node_idx = _descend(bins, node_idx, feat, lmask)
        if level < depth:
            idx_levels.append(_left_child_index(node_idx))
    idx_b = jnp.stack(idx_levels)              # [depth, N]
    stats_b = jnp.broadcast_to(stats[None], (depth,) + stats.shape)
    hb = bins if hist_bins is None else hist_bins
    hist_left = build_histograms_batch(hb, idx_b, stats_b, half, n_bins,
                                       use_pallas, mesh, stats_exact)
    leaf_raw = _level_leaf_raw(stats, node_idx, 1 << depth)
    return hist_left, leaf_raw


@partial(jax.jit, static_argnames=("n_bins", "depth", "impurity",
                                   "n_classes", "use_pallas", "max_leaves",
                                   "has_cat", "mesh", "stats_exact"))
def grow_forest_jit(bins, stats_b, cat, fa_b, n_bins: int, depth: int,
                    impurity: str, min_instances: float, min_gain: float,
                    n_classes: int = 0, use_pallas: bool = False,
                    max_leaves: int = 0, has_cat: bool = True, mesh=None,
                    stats_exact: bool = False):
    """TB independent same-structure trees grown level-wise as ONE jitted
    program — the tree-batched :func:`grow_tree_jit` (reference
    ``DTMaster.java:91``: the toDoQueue spans ALL RF trees of a round, one
    stats pass per level for the whole forest).

    stats_b: [TB, N, S] per-tree stat channels (RF bags differ per tree);
    fa_b: [TB, C] per-tree feature subsets; ``bins``/``cat`` are shared.
    Each level's TB histograms build in ONE kernel launch
    (:func:`build_histograms_batch` — the bins one-hot amortizes across
    the batch, and shallow levels' skinny [K, nblk] node operands stack
    into full MXU tiles).  Histogram subtraction, the leaf-sum bottom
    level and the leaf-wise budget all apply per tree exactly as in
    :func:`grow_tree_jit`; every per-tree result is bit-identical to a
    sequential grow (the batched==sequential parity guard pins it).

    Returns ([TB, total] split_feat, [TB, total, B] left_mask,
    [TB, total] (or [TB, total, K]) leaf_value, [TB, C] gain_fi,
    [TB, N] leaf_glob).
    """
    n, c = bins.shape
    tb = stats_b.shape[0]
    feats, lmasks, leaves = [], [], []
    gain_fi = jnp.zeros((tb, c), jnp.float32)
    node_idx = jnp.zeros((tb, n), jnp.int32)
    leaf_glob = jnp.zeros((tb, n), jnp.int32)
    nodes_cnt = jnp.ones(tb, jnp.int32)
    hist_prev = None
    feat_prev = None
    for level in range(depth + 1):
        n_nodes = 1 << level
        if level == depth:
            leaves.append(jax.vmap(
                lambda st, ni: _level_leaf_sums(st, ni, n_nodes,
                                                n_classes))(
                stats_b, node_idx))
            feats.append(jnp.full((tb, n_nodes), -1, jnp.int32))
            lmasks.append(jnp.zeros((tb, n_nodes, n_bins), bool))
            break
        if level == 0:
            hist = build_histograms_batch(bins, node_idx, stats_b, n_nodes,
                                          n_bins, use_pallas, mesh,
                                          stats_exact)
        else:
            hl = build_histograms_batch(
                bins, jax.vmap(_left_child_index)(node_idx), stats_b,
                n_nodes // 2, n_bins, use_pallas, mesh, stats_exact)
            split_ok = feat_prev >= 0                      # [TB, K/2]
            hr = jnp.where(split_ok[:, :, None, None, None],
                           hist_prev - hl, 0.0)
            hist = jnp.stack([hl, hr], axis=2) \
                .reshape(tb, n_nodes, c, hl.shape[3], hl.shape[4])
        gain, feat, lmask, leaf, _ = jax.vmap(
            lambda h, f: best_splits(h, cat, f, impurity, min_instances,
                                     min_gain, n_classes, has_cat))(
            hist, fa_b)
        if max_leaves > 0:
            feat, lmask, nodes_cnt = jax.vmap(
                lambda g, f, lm, nc: cap_splits_by_leaves(g, f, lm, nc,
                                                          max_leaves))(
                gain, feat, lmask, nodes_cnt)
        feats.append(feat)
        lmasks.append(lmask)
        leaves.append(leaf)
        gain_fi = gain_fi + jax.vmap(
            lambda g, f: jax.ops.segment_sum(
                jnp.where(f >= 0, jnp.maximum(g, 0.0),
                          0.0).astype(jnp.float32),
                jnp.maximum(f, 0), num_segments=c))(gain, feat)
        hist_prev, feat_prev = hist, feat
        node_idx = jax.vmap(
            lambda ni, f, lm: _descend(bins, ni, f, lm))(node_idx, feat,
                                                         lmask)
        leaf_glob = jnp.where(node_idx >= 0,
                              ((1 << (level + 1)) - 1) + node_idx,
                              leaf_glob)
    return (jnp.concatenate(feats, axis=1),
            jnp.concatenate(lmasks, axis=1),
            jnp.concatenate(leaves, axis=1), gain_fi, leaf_glob)


def _level_leaf_raw(stats, node_idx, n_nodes: int):
    """Per-node RAW stat sums [S, K] at one level (frozen rows contribute
    nothing) — the accumulable form of :func:`_level_leaf_sums`: streamed
    sweeps sum these across windows and divide once at the end, so the
    bottom level of an out-of-core tree costs a [S, N] x [N, K] dot per
    window instead of the full [K, C, B, S] histogram."""
    oh = jax.nn.one_hot(node_idx, n_nodes, dtype=jnp.float32)  # -1 -> 0s
    return jax.lax.dot_general(stats, oh, (((0,), (0,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST)


def leaf_values_from_raw(sums, n_classes: int = 0):
    """``[S, K]`` raw stat sums -> leaf values ([K] ``wy/w`` or [K, S]
    class distributions) — the ONE place the ratio lives (resident grow,
    streamed bottom sweeps and the coarse-to-fine tail must agree)."""
    if n_classes > 2:
        w = sums.sum(axis=0)                               # [K]
        return (sums / jnp.maximum(w, EPS)[None, :]).T     # [K, S]
    return sums[1] / jnp.maximum(sums[0], EPS)


def _level_leaf_sums(stats, node_idx, n_nodes: int, n_classes: int = 0):
    """Per-node leaf values from stat sums alone: [K] ``wy/w`` (binary /
    regression) or [K, n_classes] class distributions (multiclass)."""
    return leaf_values_from_raw(_level_leaf_raw(stats, node_idx, n_nodes),
                                n_classes)


def _left_child_index(node_idx):
    """Level-local LEFT-child selector for histogram subtraction: a row in
    left child ``2p`` maps to parent slot ``p``; right-child and frozen
    rows map to -1 (contribute to no one-hot node)."""
    return jnp.where((node_idx >= 0) & (node_idx % 2 == 0),
                     node_idx // 2, -1)


def grow_tree(bins, targets, weights, n_bins: int, depth: int,
              impurity: str = "variance", min_instances: float = 1.0,
              min_gain: float = 0.0, cat_mask: Optional[np.ndarray] = None,
              feat_active: Optional[np.ndarray] = None) -> TreeArrays:
    """Host-facing wrapper over :func:`grow_tree_jit`."""
    n, c = bins.shape
    bins = jnp.asarray(bins, jnp.int32)
    t = jnp.asarray(targets, jnp.float32)
    wt = jnp.asarray(weights, jnp.float32)
    stats = jnp.stack([wt, wt * t], axis=1)
    cat = jnp.zeros(c, bool) if cat_mask is None else jnp.asarray(cat_mask)
    fa = jnp.ones(c, bool) if feat_active is None else jnp.asarray(feat_active)
    split_feat, left_mask, leaf_value, _, _ = grow_tree_jit(
        bins, stats, cat, fa, n_bins, depth, impurity,
        float(min_instances), float(min_gain))
    return TreeArrays(split_feat=np.asarray(split_feat),
                      left_mask=np.asarray(left_mask),
                      leaf_value=np.asarray(leaf_value), depth=depth)


@partial(jax.jit, static_argnames=("level",))
def node_index_at_level(split_feat, left_mask, bins, level: int):
    """Level-local node index of every row in a PARTIAL tree (levels above
    ``level`` already decided); -1 where an ancestor froze.  The streaming
    trainers re-derive window row positions from the tree instead of keeping
    a per-row index resident (rows don't fit)."""
    n = bins.shape[0]
    node_idx = jnp.zeros(n, jnp.int32)
    for l in range(level):
        base = (1 << l) - 1
        feat = jax.lax.dynamic_slice_in_dim(split_feat, base, 1 << l)
        lmask = jax.lax.dynamic_slice_in_dim(left_mask, base, 1 << l, axis=0)
        node_idx = _descend(bins, node_idx, feat, lmask)
    return node_idx


# ---------------------------------------------------------------- predict
def traverse_nodes(split_feat, left_mask, bins, depth: int):
    """Terminal global node id per row after ``depth`` descents (shared by
    predict and the `encode` step's leaf indexing).

    The one-hot lowering works LEVEL-LOCALLY (selects against the 2^l
    nodes of level l, not all 2^(depth+1)-1 nodes) so the [N, K] one-hot
    width — and with it the :data:`ONEHOT_MAX_NODES` fast-path bound —
    grows with the widest level, keeping MXU selects through the
    reference's common depth range."""
    n = bins.shape[0]
    node = jnp.zeros(n, jnp.int32)           # global node ids, never -1
    for level in range(depth):
        k = 1 << level
        if _use_onehot(k):
            base = k - 1
            feat_l = jax.lax.dynamic_slice_in_dim(split_feat, base, k)
            lm_l = jax.lax.dynamic_slice_in_dim(left_mask, base, k, axis=0)
            loc = node - base                # frozen rows: loc < 0
            in_level = loc >= 0
            feat, goes_left = _level_select(
                bins, jnp.clip(loc, 0, k - 1), feat_l, lm_l)
            is_split = in_level & (feat >= 0)
        else:
            feat, goes_left = _level_select(bins, node, split_feat,
                                            left_mask)
            is_split = feat >= 0
        child = jnp.where(goes_left, 2 * node + 1, 2 * node + 2)
        node = jnp.where(is_split, child, node)
    return node


@partial(jax.jit, static_argnames=("depth",))
def predict_tree(split_feat, left_mask, leaf_value, bins, depth: int):
    """Batched traversal: one descent per level over all rows."""
    node = traverse_nodes(split_feat, left_mask, bins, depth)
    if _use_onehot(split_feat.shape[0]):
        oh = jax.nn.one_hot(node, split_feat.shape[0], dtype=jnp.float32)
        return _sel_exact(oh, leaf_value)    # [N] or [N, K] (multiclass)
    return leaf_value[node]


def stack_forest(trees) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Stack same-depth trees into [T, ...] arrays for one vmapped predict."""
    return (jnp.stack([jnp.asarray(t.split_feat) for t in trees]),
            jnp.stack([jnp.asarray(t.left_mask) for t in trees]),
            jnp.stack([jnp.asarray(t.leaf_value) for t in trees]))


@partial(jax.jit, static_argnames=("depth",))
def predict_forest_stacked(split_feats, left_masks, leaf_values, bins,
                           depth: int):
    """[T, N] predictions of a stacked forest in one compiled call — the
    per-tree Python loop (round-1 ``predict_tree`` per tree per model)
    becomes a single vmap."""
    return jax.vmap(predict_tree, in_axes=(0, 0, 0, None, None))(
        split_feats, left_masks, leaf_values, bins, depth)


def predict_forest(trees, bins, weights=None) -> np.ndarray:
    """Weighted-average forest prediction (RF mean vote / GBT partial sums
    are built by the caller).  Trees stack per depth group (continuous runs
    may append trees of a different depth).  Multiclass forests (2D
    ``leaf_value`` class distributions) average to [n, K]."""
    bins = jnp.asarray(bins)
    if not jnp.issubdtype(bins.dtype, jnp.integer):
        bins = bins.astype(jnp.int32)
    # integer bins keep their wire dtype (uint8 since PR 2): the gather
    # traversal consumes the narrow plane directly — the widen here cost
    # 4x the bytes of scoring's dominant operand
    k = trees[0].leaf_value.shape[1] if trees[0].leaf_value.ndim == 2 else 0
    shape = (len(trees), bins.shape[0], k) if k \
        else (len(trees), bins.shape[0])
    preds = np.empty(shape, np.float32)
    by_depth: dict = {}
    for i, t in enumerate(trees):
        by_depth.setdefault(t.depth, []).append(i)
    for depth, idxs in by_depth.items():
        sf, lm, lv = stack_forest([trees[i] for i in idxs])
        preds[idxs] = np.asarray(
            predict_forest_stacked(sf, lm, lv, bins, depth))
    if weights is None:
        return preds.mean(axis=0)
    w = np.asarray(weights).reshape((-1,) + (1,) * (preds.ndim - 1))
    return (preds * w).sum(axis=0)
