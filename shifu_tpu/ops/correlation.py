"""Pearson correlation across candidate columns, on device.

Replaces the reference's Correlation MR job (``core/correlation/``,
``CorrelationWritable.java:36-52`` running sums): each chunk contributes
``X^T X`` cross-products via one MXU matmul; missing values are imputed with
the column mean (pass-1 stats) so they contribute zero deviation — the dense,
TPU-friendly version of the reference's pairwise ``adjustCount`` bookkeeping.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _corr_kernel(x: jnp.ndarray, valid: jnp.ndarray, mean: jnp.ndarray):
    xc = jnp.where(valid, x - mean, 0.0)
    return xc.T @ xc, valid.astype(x.dtype).T @ valid.astype(x.dtype)


@dataclass
class CorrelationAccumulator:
    mean: np.ndarray                      # [C] per-column mean from pass 1
    xtx: Optional[np.ndarray] = None      # [C, C] sum of centered cross-products
    nn: Optional[np.ndarray] = None       # [C, C] pairwise valid counts

    def update(self, x: np.ndarray, valid: np.ndarray) -> None:
        a, b = _corr_kernel(jnp.asarray(x, jnp.float32), jnp.asarray(valid),
                            jnp.asarray(self.mean, jnp.float32))
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        self.xtx = a if self.xtx is None else self.xtx + a
        self.nn = b if self.nn is None else self.nn + b

    def finalize(self) -> np.ndarray:
        """[C, C] Pearson matrix; columns with ~zero variance give NaN."""
        if self.xtx is None:
            return np.zeros((len(self.mean), len(self.mean)))
        var = np.diag(self.xtx).copy()
        denom = np.sqrt(np.outer(var, var))
        with np.errstate(invalid="ignore", divide="ignore"):
            corr = np.where(denom > 1e-12, self.xtx / np.where(denom == 0, 1, denom),
                            np.nan)
        np.fill_diagonal(corr, 1.0)
        return corr
