"""Pearson correlation across ALL candidate columns, on device, with
pairwise-complete semantics.

Replaces the reference's Correlation MR job (``core/correlation/``,
``CorrelationWritable.java:36-52``): the reference keeps per-pair running
sums (sumX, sumY, sumXX, sumYY, sumXY, adjustCount) so each pair uses
exactly the rows where BOTH columns are valid.  Here those per-pair sums
are four MXU matmuls per chunk over the validity-masked matrix — the dense
TPU formulation of adjustCount bookkeeping (the round-2 version mean-imputed
missing values, which biases pairs with disjoint missingness).

Categorical columns participate via their bin pos-rate encoding
(``CorrelationMapper.java:309-318``), so the matrix covers every candidate,
not just numerics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def _pair_sums(x: jnp.ndarray, v: jnp.ndarray, offset: jnp.ndarray):
    """Per-pair running sums for one chunk: x [R, C] (invalid entries may
    hold anything), v [R, C] validity, offset [C] per-column shift.
    Returns (n, sx, sxy, sxx) each [C, C], where cell (i, j) sums over rows
    valid in BOTH i and j: n = count, sx = sum x_i, sxy = sum x_i x_j,
    sxx = sum x_i^2 — all over the SHIFTED values.  Pearson is per-column
    shift-invariant, and shifting by ~the column mean keeps the f32
    uncentered power sums from cancelling catastrophically (unix-timestamp
    scale columns would otherwise lose all variance signal)."""
    vf = v.astype(x.dtype)
    xv = jnp.where(v, x - offset, 0.0)
    x2v = xv * xv
    return (vf.T @ vf, xv.T @ vf, xv.T @ xv, x2v.T @ vf)


@dataclass
class CorrelationAccumulator:
    """Streaming pairwise-complete Pearson (sy/syy come free as sx^T/sxx^T).
    ``offset`` [C] shifts each column before the sums (pass-1 means keep
    f32 stable); None = no shift."""
    n_cols: int
    offset: Optional[np.ndarray] = None
    # data-axis row sharding (padded rows are invalid → contribute nothing
    # to the masked matmuls); the reference's CorrelationMapper fan-out
    mesh: Optional[object] = None
    n: Optional[np.ndarray] = None
    sx: Optional[np.ndarray] = None
    sxy: Optional[np.ndarray] = None
    sxx: Optional[np.ndarray] = None

    def update(self, x: np.ndarray, valid: np.ndarray) -> None:
        off = np.zeros(self.n_cols) if self.offset is None else self.offset
        if self.mesh is None or int(self.mesh.shape["data"]) <= 1:
            # jnp.asarray keeps device-resident chunks on device
            xd, vd = jnp.asarray(x, jnp.float32), jnp.asarray(valid)
        else:
            from ..parallel.mesh import shard_chunk_rows
            xd, vd, _ = shard_chunk_rows(
                self.mesh, np.asarray(x, np.float32), np.asarray(valid))
        out = _pair_sums(xd, vd, jnp.asarray(off, jnp.float32))
        n, sx, sxy, sxx = (np.asarray(a, np.float64) for a in out)
        if self.n is None:
            self.n, self.sx, self.sxy, self.sxx = n, sx, sxy, sxx
        else:
            self.n += n
            self.sx += sx
            self.sxy += sxy
            self.sxx += sxx

    def finalize(self) -> np.ndarray:
        """[C, C] Pearson over each pair's both-valid rows; degenerate pairs
        (no overlap / zero variance) give NaN."""
        if self.n is None:
            return np.full((self.n_cols, self.n_cols), np.nan)
        n, sx, sxy, sxx = self.n, self.sx, self.sxy, self.sxx
        sy, syy = sx.T, sxx.T
        with np.errstate(invalid="ignore", divide="ignore"):
            cov = n * sxy - sx * sy
            varx = n * sxx - sx * sx
            vary = n * syy - sy * sy
            denom = np.sqrt(np.where(varx > 0, varx, np.nan)
                            * np.where(vary > 0, vary, np.nan))
            corr = cov / denom
        np.fill_diagonal(corr, 1.0)
        return corr
