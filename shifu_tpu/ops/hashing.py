"""Device-side splitmix64 — the streaming row-hash, on chip.

The streamed trainers key every stateless decision (bags, splits) off
``data.streaming.row_uniform`` — host splitmix64 over the global row
index.  Replaying those draws on device (no uint64 there without x64
mode: 64-bit values ride as uint32 hi/lo pairs, products built from
16-bit limbs) lets a fully-resident streamed forest draw its per-tree
bags in-graph instead of hashing + transferring [N] floats per tree over
the host link.  Poisson counts compare the 53-bit uniform against
integer CDF thresholds, so device bags are BIT-IDENTICAL to the host's
(``tests/test_ops_hardening.py::test_device_hash_bags_match_host``).

The hashed-ID bucket map rides the same limbs: :func:`hash_bucket_host`
feeds the offline norm/trainer path while :func:`hash_bucket_device`
(via ``models.wdl.apply_hash_device``) folds the identical map into the
serving executable — raw-record ``POST /score`` requests hash their ID
columns in-graph inside the fused transform prelude, and the
host/device pair staying bit-identical is what keeps the raw serving
path's parity guarantee alive for hashed WDL models
(``tests/test_serve.py`` drives both paths over the same records).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

# np scalar, not jnp: a module-level jnp constant would be minted as a
# tracer (and leak) if this module's FIRST import happens inside a trace
# — the serving path's in-graph apply_hash_device can be that first
# importer in a fresh process
_MASK16 = np.uint32(0xFFFF)


def _add64(ahi, alo, bhi, blo):
    lo = alo + blo
    carry = (lo < alo).astype(jnp.uint32)
    return ahi + bhi + carry, lo


def _mul32x32(a, b):
    """(hi, lo) of the 64-bit product of two uint32 (16-bit limbs)."""
    a0, a1 = a & _MASK16, a >> 16
    b0, b1 = b & _MASK16, b >> 16
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = (p00 >> 16) + (p01 & _MASK16) + (p10 & _MASK16)
    lo = (p00 & _MASK16) | (mid << 16)
    hi = p11 + (p01 >> 16) + (p10 >> 16) + (mid >> 16)
    return hi, lo


def _mul64(ahi, alo, bhi, blo):
    """Low 64 bits of a 64x64 product."""
    hi, lo = _mul32x32(alo, blo)
    hi = hi + alo * bhi + ahi * blo          # wrapping uint32 products
    return hi, lo


def _xorshift_r(hi, lo, k: int):
    """(hi, lo) ^ ((hi, lo) >> k) for 0 < k < 64."""
    if k < 32:
        shi = hi >> k
        slo = (lo >> k) | (hi << (32 - k))
    else:
        shi = jnp.zeros_like(hi)
        slo = hi >> (k - 32)
    return hi ^ shi, lo ^ slo


def _const64(v: int):
    return jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF)


def _splitmix64_dev(hi, lo):
    hi, lo = _add64(hi, lo, *_const64(0x9E3779B97F4A7C15))
    hi, lo = _xorshift_r(hi, lo, 30)
    hi, lo = _mul64(hi, lo, *_const64(0xBF58476D1CE4E5B9))
    hi, lo = _xorshift_r(hi, lo, 27)
    hi, lo = _mul64(hi, lo, *_const64(0x94D049BB133111EB))
    return _xorshift_r(hi, lo, 31)


def _row_key(seed: int, stream: int) -> int:
    """Host scalar half of ``row_uniform``: splitmix64(seed * FNV + stream)
    (``data/streaming.py:40-46``)."""
    z = ((seed & 0xFFFFFFFF) * 0x100000001B3
         + (stream & 0xFFFFFFFF)) & 0xFFFFFFFFFFFFFFFF
    z = (z + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
    z ^= z >> 30
    z = (z * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z ^= z >> 27
    z = (z * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    z ^= z >> 31
    return z


def poisson_thresholds(lam: float, kmax: int = 16) -> np.ndarray:
    """[kmax] uint64 CDF thresholds over the 53-bit uniform lattice —
    ``count = sum_k [u53 >= t_k]`` reproduces ``_hash_poisson`` exactly
    (its float compare ``u >= cdf`` over u = u53 * 2^-53)."""
    p = np.exp(-lam)
    cdf = p
    term = p
    out = np.empty(kmax, np.uint64)
    for k in range(1, kmax + 1):
        # u >= cdf  <=>  u53 >= ceil(cdf * 2^53)  (u53 = u * 2^53 exact)
        out[k - 1] = np.uint64(min(np.ceil(cdf * (1 << 53)), 1 << 53))
        term = term * lam / k
        cdf = cdf + term
    return out


@partial(jax.jit, static_argnames=("seed", "stream", "lam", "kmax"))
def hash_poisson_device(idx_hi, idx_lo, seed: int, stream: int,
                        lam: float, kmax: int = 16):
    """[N] f32 Poisson(lam) bag counts from global row indices — the
    device replay of ``_hash_poisson(lam, row_uniform(seed, stream, idx))``,
    bit-identical to the host draw."""
    key = _row_key(seed, stream)
    khi, klo = jnp.uint32(key >> 32), jnp.uint32(key & 0xFFFFFFFF)
    zhi, zlo = _splitmix64_dev(idx_hi ^ khi, idx_lo ^ klo)
    # u53 = z >> 11: hi 21 bits + lo 32 bits
    uhi = zhi >> 11
    ulo = (zlo >> 11) | (zhi << 21)
    th = poisson_thresholds(lam, kmax)
    cnt = jnp.zeros(idx_lo.shape, jnp.float32)
    for t in th:
        thi = jnp.uint32(int(t) >> 32)
        tlo = jnp.uint32(int(t) & 0xFFFFFFFF)
        ge = (uhi > thi) | ((uhi == thi) & (ulo >= tlo))
        cnt = cnt + ge.astype(jnp.float32)
    return cnt


def row_key_u32(seed: int, stream: int) -> Tuple[np.uint32, np.uint32]:
    """(hi, lo) halves of the host row key — TRACED inputs for
    :func:`hash_poisson_traced`, so a per-tree stream does not recompile."""
    key = _row_key(seed, stream)
    return np.uint32(key >> 32), np.uint32(key & 0xFFFFFFFF)


def thresholds_u32(lam: float, kmax: int = 16):
    """(hi, lo) uint32 halves of :func:`poisson_thresholds`."""
    th = poisson_thresholds(lam, kmax)
    return ((th >> np.uint64(32)).astype(np.uint32),
            (th & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def hash_poisson_traced(idx_hi, idx_lo, khi, klo, thi, tlo):
    """Traced-key variant of :func:`hash_poisson_device` (key + CDF
    thresholds as device scalars/arrays — one executable serves every
    tree of a streamed forest)."""
    zhi, zlo = _splitmix64_dev(idx_hi ^ khi, idx_lo ^ klo)
    uhi = zhi >> 11
    ulo = (zlo >> 11) | (zhi << 21)
    ge = (uhi[:, None] > thi[None, :]) | \
        ((uhi[:, None] == thi[None, :]) & (ulo[:, None] >= tlo[None, :]))
    return ge.sum(axis=1).astype(jnp.float32)


def split_index_u32(idx: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host row-index array -> (hi, lo) uint32 halves for the device hash."""
    idx = np.asarray(idx, np.uint64)
    return ((idx >> np.uint64(32)).astype(np.uint32),
            (idx & np.uint64(0xFFFFFFFF)).astype(np.uint32))


# ---------------------------------------------------------- hash buckets
# The WDL hashed-ID path folds a high-cardinality categorical column into
# a fixed bucket space: bucket = high word of (splitmix64(id ^ col_key)
# >> 32) * buckets — Lemire's multiply-shift range reduction over the top
# 32 hash bits.  No 64-bit modulo anywhere, so the device replay (uint32
# limbs) is BIT-IDENTICAL to the host map by construction.

#: seed for per-column hash keys (distinct from the row-bagging streams
#: so a column never shares a key with a bag draw)
WDL_HASH_SEED = 0x5D1F00D


def column_hash_key(column_num: int, seed: int = WDL_HASH_SEED) -> int:
    """Stable 64-bit per-column key for the hashed-ID bucket map."""
    return _row_key(seed, column_num)


def hash_bucket_host(idx: np.ndarray, key: int, buckets: int) -> np.ndarray:
    """[N] int32 bucket ids for host-side (norm/trainer) hashed-ID columns."""
    z = np.maximum(np.asarray(idx, np.int64), 0).astype(np.uint64)
    z ^= np.uint64(key)
    z = (z + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    z ^= z >> np.uint64(30)
    z = (z * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    z ^= z >> np.uint64(27)
    z = (z * np.uint64(0x94D049BB133111EB)) & np.uint64(0xFFFFFFFFFFFFFFFF)
    z ^= z >> np.uint64(31)
    hi32 = z >> np.uint64(32)
    return ((hi32 * np.uint64(buckets)) >> np.uint64(32)).astype(np.int32)


def hash_bucket_device(idx, key: int, buckets: int):
    """Device replay of :func:`hash_bucket_host` (uint32 limbs, in-graph
    for the serving path) — bit-identical to the host map."""
    ilo = jnp.maximum(idx, 0).astype(jnp.uint32)
    ihi = jnp.zeros_like(ilo)
    khi, klo = jnp.uint32(key >> 32), jnp.uint32(key & 0xFFFFFFFF)
    zhi, zlo = _splitmix64_dev(ihi ^ khi, ilo ^ klo)
    bhi, _ = _mul32x32(zhi, jnp.uint32(buckets))
    return bhi.astype(jnp.int32)
