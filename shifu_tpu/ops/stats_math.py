"""Pure stats math: KS / IV / WOE / PSI / pos-rate from per-bin counts.

Formula parity with reference ``core/ColumnStatsCalculator.java`` (long[]
variant, the one used by ``UpdateBinningInfoReducer.java:345``):

- per-bin WOE = ln((n_i + eps) / (p_i + eps)) with p_i, n_i the bin's share of
  total positives / negatives,
- IV = sum (n_i - p_i) * woe_i,
- column WOE = ln((sumNeg + eps) / (sumPos + eps)),
- KS = 100 * max_i |cum_p - cum_n|.

All functions are numpy-vectorized over the bin axis and over columns, so the
whole ColumnConfig list is computed in one shot.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

EPS = 1e-10


class ColumnMetrics(NamedTuple):
    ks: np.ndarray          # [cols]
    iv: np.ndarray          # [cols]
    woe: np.ndarray         # [cols]
    bin_woe: np.ndarray     # [cols, bins]


def column_metrics(neg: np.ndarray, pos: np.ndarray) -> ColumnMetrics:
    """KS/IV/WOE for count (or weighted-count) bin arrays.

    Args:
      neg, pos: [cols, bins] arrays (missing bin included as the last entry,
        as the reference does).
    Columns with zero total pos or neg get NaN metrics (reference returns null).
    """
    neg = np.asarray(neg, dtype=np.float64)
    pos = np.asarray(pos, dtype=np.float64)
    sum_n = neg.sum(axis=-1, keepdims=True)
    sum_p = pos.sum(axis=-1, keepdims=True)
    ok = (sum_n[..., 0] > 0) & (sum_p[..., 0] > 0)
    with np.errstate(divide="ignore", invalid="ignore"):
        p = pos / np.where(sum_p == 0, 1, sum_p)
        n = neg / np.where(sum_n == 0, 1, sum_n)
        bin_woe = np.log((n + EPS) / (p + EPS))
        iv = ((n - p) * bin_woe).sum(axis=-1)
        woe = np.log((sum_n[..., 0] + EPS) / (sum_p[..., 0] + EPS))
        ks = 100.0 * np.abs(np.cumsum(p, axis=-1) - np.cumsum(n, axis=-1)).max(axis=-1)
    nanify = lambda a: np.where(ok, a, np.nan)
    return ColumnMetrics(ks=nanify(ks), iv=nanify(iv), woe=nanify(woe),
                         bin_woe=np.where(ok[..., None], bin_woe, np.nan))


def pos_rate(pos: np.ndarray, neg: np.ndarray) -> np.ndarray:
    """binPosRate — reference ``UpdateBinningInfoReducer.computePosRate``:
    pos/(pos+neg), NaN for empty bins."""
    pos = np.asarray(pos, dtype=np.float64)
    neg = np.asarray(neg, dtype=np.float64)
    tot = pos + neg
    with np.errstate(invalid="ignore", divide="ignore"):
        return np.where(tot > 0, pos / np.where(tot == 0, 1, tot), np.nan)


def psi(expected: np.ndarray, actual: np.ndarray) -> np.ndarray:
    """Population stability index between two per-bin count vectors
    (reference ``udf/PSICalculatorUDF``): sum((a%-e%)*ln(a%/e%))."""
    e = np.asarray(expected, dtype=np.float64)
    a = np.asarray(actual, dtype=np.float64)
    e = e / np.maximum(e.sum(axis=-1, keepdims=True), EPS)
    a = a / np.maximum(a.sum(axis=-1, keepdims=True), EPS)
    with np.errstate(divide="ignore", invalid="ignore"):
        term = (a - e) * np.log((a + EPS) / (e + EPS))
    return term.sum(axis=-1)


# ----------------------------------------------------------- dynamic rebin
def _iv_terms(neg: np.ndarray, pos: np.ndarray,
              sum_n: float, sum_p: float) -> np.ndarray:
    """Per-bin IV contribution given FIXED column totals.  Because merging
    adjacent bins never changes the totals, column IV decomposes as the sum
    of these terms — which is what makes the merge loop vectorizable."""
    if sum_n <= 0 or sum_p <= 0:
        return np.zeros_like(np.asarray(neg, np.float64))
    n = np.asarray(neg, np.float64) / sum_n
    p = np.asarray(pos, np.float64) / sum_p
    return (n - p) * np.log((n + EPS) / (p + EPS))


def merge_adjacent_by_iv(neg: np.ndarray, pos: np.ndarray,
                         target_bins: int, iv_keep: float = 0.95,
                         min_inst: int = 0) -> list:
    """IV-driven adjacent bin merge (reference ``DynamicBinning`` /
    ``AutoDynamicBinning``: merge bins while information value survives).

    neg/pos: per-VALUE-bin counts (missing bin excluded).  Greedily merges
    the adjacent pair whose merge preserves the most IV until ``target_bins``
    is reached; continues below that only while IV stays above
    ``iv_keep * original``.  Returns the list of merged index groups (each a
    list of original bin indices, in order).

    Each round evaluates ALL candidate merges in one vectorized pass: column
    totals are merge-invariant, so merging pair i changes the IV by
    ``t_merged(i) - t_i - t_{i+1}`` where ``t`` are per-bin IV terms —
    O(bins) per round instead of the naive O(bins^2).
    """
    neg = np.asarray(neg, np.float64).copy()
    pos = np.asarray(pos, np.float64).copy()
    groups = [[i] for i in range(len(neg))]
    sum_n, sum_p = float(neg.sum()), float(pos.sum())
    iv0 = float(_iv_terms(neg, pos, sum_n, sum_p).sum())
    while len(groups) > 2:
        t = _iv_terms(neg, pos, sum_n, sum_p)
        tm = _iv_terms(neg[:-1] + neg[1:], pos[:-1] + pos[1:], sum_n, sum_p)
        cand = float(t.sum()) - t[:-1] - t[1:] + tm  # IV after each merge
        i = int(np.argmax(cand))
        need_shrink = len(groups) > target_bins
        # reference -bic: bins under the minimum instance count must merge
        # regardless of IV (DynamicBinningUDF minimumBinInstCnt)
        tiny = (neg + pos) < min_inst if min_inst > 0 else None
        if tiny is not None and tiny.any() and not need_shrink:
            j = int(np.argmin(neg + pos))
            i = j if j < len(cand) and (j == 0 or cand[j] >= cand[j - 1]) \
                else max(j - 1, 0)
            need_shrink = True
        if not need_shrink and (iv0 <= 0 or cand[i] < iv_keep * iv0):
            break
        neg[i] += neg[i + 1]
        pos[i] += pos[i + 1]
        neg = np.delete(neg, i + 1)
        pos = np.delete(pos, i + 1)
        groups[i] = groups[i] + groups[i + 1]
        del groups[i + 1]
    return groups
