"""SE/ST sensitivity kernels — the reference's varselect MR job
(``core/varselect/VarSelectMapper.java:93-120``: re-score every record with
candidate column *i* frozen to its mean, accumulate the squared-error rise)
rebuilt as **streamed, mask-batched device programs**.

The seed implementation loaded the whole norm plane resident
(``Shards.load_all``) and dispatched ONE jitted forward plus ONE blocking
``float()`` host sync per candidate column — hundreds of sequential
full-dataset programs for a fraud-width schema, and a host footprint that
cannot exist at the 1TB north star.  Here the job is restructured the way
the stats/norm/train planes already were (PRs 2-3):

- the norm plane streams window-by-window through ``ShardStream`` /
  ``ResidentCache`` (prefetch + H2D double-buffering and the mmap spill
  fast path for free; windows under the device cache budget stay HBM-
  resident between the two passes);
- within each window a **batch of B column masks evaluates in one vmapped
  jitted launch**: semantically ``xf = where(mask_b, mean_x, x)`` →
  forward → per-mask weighted squared-error partial sums accumulated in
  HBM.  The first layer exploits the mask structure instead of
  materializing B frozen copies of the window: freezing block *i* only
  perturbs the first-layer pre-activation by a rank-``|block|`` update,
  so the kernel computes ``z = x @ W0 + b0`` ONCE per window and each
  mask adds ``dx[:, block] @ W0[block]`` — an O(D/k_max) FLOP and memory
  cut over the dense frozen forward (deeper layers run per mask as
  usual);
- host contact drops from ``O(candidates)`` blocking syncs to ONE packed
  ``[C+2]``-vector fetch at the end of the job (scores + base-error
  channel), counted by ``varsel.host_syncs``.

Two passes total: pass 1 accumulates the feature means and the unfrozen
base error (one program per window); pass 2 issues exactly
``ceil(C/B)`` mask-batch programs per window (the first of them also
emits the shared ``z``/``dx`` operands the rest reuse).  Weighting: every
partial sum is weighted by the supplied per-row weight — the pipeline
passes row VALIDITY (1 real / 0 padded), which reproduces the reference
loop's unweighted mean exactly on resident data.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..models import nn as nn_model


def mask_batch_size(params: Optional[dict] = None,
                    override: Optional[int] = None) -> int:
    """Mask-batch knob B: explicit override > varSelect param
    ``MaskBatch`` > property ``-Dshifu.varsel.maskBatch=N`` > default 32.
    B bounds HBM pressure (the vmapped launch materializes ~B frozen
    copies of the window) and sets the per-window program count
    ``ceil(C/B)``."""
    if override is not None:
        return max(1, int(override))
    p = params or {}
    if "MaskBatch" in p:
        return max(1, int(p["MaskBatch"]))
    from ..config import environment
    return max(1, environment.get_int("shifu.varsel.maskBatch", 32))


def mask_matrix(n_features: int,
                blocks: Sequence[Sequence[int]]) -> np.ndarray:
    """[C, D] bool mask matrix from per-candidate feature-index blocks.
    Onehot/woe feature blocks freeze as WHOLE blocks — every index of a
    candidate's block is set on its row (reference freezes the source
    column, which maps to all its generated features)."""
    masks = np.zeros((len(blocks), n_features), bool)
    for i, idx in enumerate(blocks):
        masks[i, list(idx)] = True
    return masks


def _per_row_sq_err(pred, y):
    # the reference job's plain squared error over the score vector
    # (output_dim 1 in the SE/ST path; summing the output axis keeps the
    # math identical there and well-defined for wider heads)
    return ((pred - y[:, None]) ** 2).sum(axis=-1)


def per_column_scores(spec, params, x, y,
                      masks: np.ndarray) -> Tuple[np.ndarray, float]:
    """The SEED per-column loop, kept verbatim as the parity oracle (and
    the ``-Dshifu.varsel.batched=false`` escape hatch): one jitted frozen
    forward + one blocking ``float()`` per candidate over the RESIDENT
    matrix.  Returns (per-candidate frozen MSE [C], base MSE)."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    base_mse = float(jnp.mean(_per_row_sq_err(
        nn_model.forward(params, spec, x), y)))
    mean_x = x.mean(axis=0)

    @jax.jit
    def frozen_mse(feat_mask):
        xf = jnp.where(feat_mask[None, :], mean_x[None, :], x)
        return jnp.mean(_per_row_sq_err(nn_model.forward(params, spec, xf),
                                        y))

    mse = np.array([float(frozen_mse(jnp.asarray(m))) for m in masks],
                   np.float64)
    return mse, base_mse


def streamed_sensitivity(stream, spec, params, masks: np.ndarray,
                         mesh=None, mask_batch: Optional[int] = None,
                         cache_budget: Optional[int] = None
                         ) -> Tuple[np.ndarray, float, int]:
    """Streamed, mask-batched SE/ST sensitivity job.

    ``stream`` is a ``ShardStream`` over the norm plane with keys
    ``("x", "y")``; ``masks`` is the [C, D] candidate mask matrix.  Rows
    shard over the mesh ``data`` axis like the scorer; per-mask partial
    sums accumulate in HBM and the ONLY host fetch is the packed
    ``[C_pad + 2]`` vector at the end (``varsel.host_syncs`` counts it).

    Returns (per-candidate frozen MSE [C] float64, base MSE, rows seen).
    Resident inputs produce scores matching :func:`per_column_scores`
    within f32 accumulation tolerance.
    """
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from ..data.streaming import (PreparedWindow, ResidentCache,
                                  pipeline_depth_for)
    from ..parallel import mesh as meshlib

    C, D = masks.shape
    assert C > 0, "streamed_sensitivity: no candidate masks"
    if mesh is None:
        mesh = meshlib.device_mesh()
    data_size = int(mesh.shape["data"])
    assert stream.window_rows % data_size == 0, \
        f"window_rows {stream.window_rows} must divide data axis {data_size}"

    B = min(mask_batch_size(override=mask_batch), C)
    n_batches = math.ceil(C / B)
    # block-index form of the masks, padded to the widest block: index D
    # points at an appended zero column of dx / zero row of W0, so padded
    # slots contribute nothing (and pad masks past C freeze nothing)
    k_max = max(int(m.sum()) for m in masks) or 1
    idx_pad = np.full((n_batches * B, k_max), D, np.int32)
    for i, m in enumerate(masks):
        nz = np.flatnonzero(m)
        idx_pad[i, :len(nz)] = nz
    sh_rep = NamedSharding(mesh, P())
    sh_r = NamedSharding(mesh, P("data"))
    sh_x = NamedSharding(mesh, P("data", None))
    idx_d = [jax.device_put(idx_pad[i * B:(i + 1) * B], sh_rep)
             for i in range(n_batches)]
    params_d = jax.device_put(params, sh_rep)

    # f64 cross-window accumulators when x64 is on (tests); f32 on
    # default-config TPU rigs
    acc_dt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32

    # cost-attributed varsel-plane entry points (obs/costs)
    @partial(obs.costed_jit, "varsel.base_window")
    def base_window(params, x, y, w, sum_x, stats):
        """Pass 1: feature sums (→ mean_x) + unfrozen base error."""
        per = _per_row_sq_err(nn_model.forward(params, spec, x), y)
        sum_x = sum_x + (x * w[:, None]).sum(axis=0).astype(sum_x.dtype)
        stats = stats + jnp.stack([(per * w).sum(),
                                   w.sum()]).astype(stats.dtype)
        return sum_x, stats

    acts = [nn_model.activation(a) for a in spec.activations]
    out_act = nn_model.activation(spec.output_activation)

    def _mask_scores(params, idx_b, z, dxp, y, w, acc_b):
        """B frozen forwards sharing the window's base first-layer
        pre-activation ``z``: each mask is a rank-``k_max`` update
        ``dx[:, block] @ W0[block]`` instead of a D-wide frozen copy."""
        w0p = jnp.concatenate([params[0]["w"],
                               jnp.zeros((1,) + params[0]["w"].shape[1:],
                                         params[0]["w"].dtype)])

        def one(idx):
            zf = z + dxp[:, idx] @ w0p[idx]
            if len(params) == 1:       # 0-hidden-layer net (LR/SVM head)
                pred = out_act(zf)
            else:
                h = acts[0 % max(1, len(acts))](zf)
                for i, layer in enumerate(params[1:-1], start=1):
                    h = acts[i % max(1, len(acts))](h @ layer["w"]
                                                    + layer["b"])
                pred = out_act(h @ params[-1]["w"] + params[-1]["b"])
            return (_per_row_sq_err(pred, y) * w).sum()
        return acc_b + jax.vmap(one)(idx_b).astype(acc_b.dtype)

    @partial(obs.costed_jit, "varsel.first_mask_window")
    def first_mask_window(params, idx_b, mean_x, x, y, w, acc_b):
        """The window's FIRST mask batch also emits the shared operands:
        base pre-activation z and the padded frozen-delta matrix dx —
        so a window still issues exactly ceil(C/B) programs."""
        z = x @ params[0]["w"] + params[0]["b"]
        dxp = jnp.concatenate(
            [mean_x[None, :] - x, jnp.zeros((x.shape[0], 1), x.dtype)],
            axis=1)
        return _mask_scores(params, idx_b, z, dxp, y, w, acc_b), z, dxp

    @partial(obs.costed_jit, "varsel.mask_window")
    def mask_window(params, idx_b, z, dxp, y, w, acc_b):
        return _mask_scores(params, idx_b, z, dxp, y, w, acc_b)

    def prepare(win):
        xb = jax.device_put(win.arrays["x"].astype(np.float32, copy=False),
                            sh_x)
        yb = jax.device_put(win.arrays["y"].astype(np.float32, copy=False),
                            sh_r)
        wv = np.zeros(win.rows, np.float32)
        wv[:win.n_valid] = 1.0          # validity weights: padded rows = 0
        wb = jax.device_put(wv, sh_r)
        return PreparedWindow(start=win.start, n_valid=win.n_valid,
                              rows=win.rows, index=win.index,
                              arrays={"x": xb, "y": yb, "w": wb})

    if cache_budget is None:
        from ..config import environment
        cache_budget = environment.get_int("shifu.train.deviceCacheBytes",
                                           1 << 30)
    cache = ResidentCache(stream, cache_budget, prepare,
                          pipeline_depth=pipeline_depth_for(mesh))

    win_c = obs.counter("varsel.windows")
    mb_c = obs.counter("varsel.mask_batches")

    sum_x = jnp.zeros(D, acc_dt)
    stats = jnp.zeros(2, acc_dt)
    n_windows = 0
    for it in cache.items():                       # pass 1
        sum_x, stats = base_window(params_d, it.arrays["x"],
                                   it.arrays["y"], it.arrays["w"],
                                   sum_x, stats)
        n_windows += 1
        win_c.inc()
    if n_windows == 0:
        raise RuntimeError("streamed sensitivity: empty shard stream")
    mean_x = (sum_x / jnp.maximum(stats[1], 1.0)).astype(jnp.float32)

    accs = [jnp.zeros(B, acc_dt) for _ in range(n_batches)]
    for it in cache.items():                       # pass 2
        accs[0], z, dxp = first_mask_window(       # ceil(C/B) programs
            params_d, idx_d[0], mean_x, it.arrays["x"],
            it.arrays["y"], it.arrays["w"], accs[0])
        mb_c.inc()
        for bi in range(1, n_batches):
            accs[bi] = mask_window(params_d, idx_d[bi], z, dxp,
                                   it.arrays["y"], it.arrays["w"],
                                   accs[bi])
            mb_c.inc()
        win_c.inc()

    # THE single end-of-job fetch: per-mask SSE + (base SSE, weight sum)
    packed = np.asarray(jnp.concatenate(accs + [stats]), np.float64)
    obs.counter("varsel.host_syncs").inc()
    wsum = max(packed[-1], 1e-12)
    mse = packed[:C] / wsum
    base_mse = float(packed[-2] / wsum)
    return mse, base_mse, int(round(packed[-1]))
