"""Streaming sketches for auto-type inference — the reference's
``core/autotype/`` pair (``AutoTypeDistinctCountMapper``: HyperLogLogPlus
distinct counts; ``CountAndFrequentItemsWritable``: bounded frequent-item
sets), vectorized over numpy hash lanes instead of per-value stream calls.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pandas as pd


class HyperLogLog:
    """Classic HLL over 64-bit hashes (reference uses HyperLogLogPlus(8);
    p=12 here: 4096 registers, ~1.6% standard error, 4KB)."""

    def __init__(self, p: int = 12):
        self.p = p
        self.m = 1 << p
        self.regs = np.zeros(self.m, np.uint8)

    def update(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        h = pd.util.hash_array(np.asarray(values, dtype=object),
                               categorize=False).astype(np.uint64)
        idx = (h >> np.uint64(64 - self.p)).astype(np.int64)
        rest = h << np.uint64(self.p)        # top 64-p bits shifted up
        # rank = leading zeros of `rest` + 1, capped at 64-p+1; a zero rest
        # means all remaining bits were 0
        nz = rest != 0
        lz = np.full(len(h), 64 - self.p, np.uint8)
        # float64 log2 is exact for the leading-bit position of a uint64
        with np.errstate(divide="ignore"):
            lz[nz] = (63 - np.floor(np.log2(rest[nz].astype(np.float64)))) \
                .astype(np.uint8)
        rank = np.minimum(lz + 1, 64 - self.p + 1).astype(np.uint8)
        np.maximum.at(self.regs, idx, rank)

    def estimate(self) -> int:
        m = float(self.m)
        alpha = 0.7213 / (1 + 1.079 / m)
        inv = np.power(2.0, -self.regs.astype(np.float64))
        e = alpha * m * m / inv.sum()
        zeros = int((self.regs == 0).sum())
        if e <= 2.5 * m and zeros:
            e = m * np.log(m / zeros)          # small-range correction
        return int(round(e))


class RangeSketch:
    """Streaming per-column value-range sketch → PROVISIONAL fine-histogram
    boundaries for the fused one-pass stats sweep.

    The two-pass stats plane needs pass 1 only to learn each column's
    [min, max] before the fine equal-width histogram of pass 2.  The fused
    sweep instead sketches the range as chunks stream by and, when its
    device chunk cache overflows, freezes an EXPANDED provisional range
    (margin headroom on both sides, the MunroPat-family "provisional
    boundaries, refine later" idea — reference
    ``core/binning/MunroPatBinning.java``).  Overflow chunks accumulate
    into the provisional grid; at finalize the provisional buckets re-bin
    onto the exact [min, max] grid ON DEVICE
    (``ops.binning._refine_prov_kernel``) — counts are conserved exactly,
    placement error is bounded by one provisional bucket width.
    """

    def __init__(self, n_cols: int, margin: float = 0.25):
        self.margin = margin
        self.mn = np.full(n_cols, np.inf)
        self.mx = np.full(n_cols, -np.inf)

    def update(self, mn: np.ndarray, mx: np.ndarray) -> None:
        np.minimum(self.mn, np.asarray(mn, np.float64), out=self.mn)
        np.maximum(self.mx, np.asarray(mx, np.float64), out=self.mx)

    def provisional_bounds(self):
        """(lo, hi) float64 arrays: the observed range expanded by
        ``margin`` on each side (late-arriving tails clip into the edge
        provisional buckets, bounded by the refinement error above).
        Degenerate columns take the same fallbacks as
        ``NumericAccumulator.finalize_range``."""
        lo, hi = self.mn.copy(), self.mx.copy()
        empty = ~np.isfinite(lo) | ~np.isfinite(hi)
        lo[empty], hi[empty] = 0.0, 1.0
        same = hi <= lo
        hi[same] = lo[same] + 1.0
        span = hi - lo
        return lo - self.margin * span, hi + self.margin * span


class FrequentItems:
    """Bounded frequent-item counter with Misra-Gries merging (reference
    ``CountAndFrequentItemsWritable`` role): batches merge vectorized via
    pandas; when more than ``cap`` items are live, every count drops by the
    (cap+1)-th largest and non-positive entries evict.  MG guarantee: any
    item whose true frequency exceeds n/cap survives, independent of chunk
    order (the naive keep-top-K prune was order-dependent)."""

    def __init__(self, k: int = 32, cap: int = 4096):
        self.k = k
        self.cap = cap
        self.counts: Dict[str, int] = {}

    def update(self, values: np.ndarray) -> None:
        if len(values) == 0:
            return
        vc = pd.Series(np.asarray(values, dtype=str)).value_counts()
        if self.counts:
            vc = vc.add(pd.Series(self.counts), fill_value=0)
        if len(vc) > self.cap:
            d = vc.nlargest(self.cap + 1).iloc[-1]
            vc = vc - d
            vc = vc[vc > 0]
            if len(vc) > self.cap:        # ties at the threshold
                vc = vc.nlargest(self.cap)
        self.counts = {str(key): int(v) for key, v in vc.items()}

    def top(self, k: int = None) -> List[str]:
        k = k or self.k
        return [v for v, _ in sorted(self.counts.items(),
                                     key=lambda kv: -kv[1])[:k]]
