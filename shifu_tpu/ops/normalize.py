"""Vectorized normalization — all norm types of the reference.

Covers every ``NormType`` of reference ``ModelNormalizeConf.java:34-46`` with
the semantics of reference ``core/Normalizer.java:124-287,444,619``:

- ZSCALE/ZSCORE (+OLD_*): numeric -> clip((v-mean)/std, ±cutoff); missing ->
  mean (z=0); categorical -> binPosRate (missing: POSRATE of the missing bin,
  or mean), then z-scored (OLD_* skips the z-step for categoricals).
- WOE / WEIGHT_WOE: per-bin (weighted) WOE lookup; missing -> last bin's woe.
- WOE_ZSCORE / WEIGHT_WOE_ZSCORE: woe then z-scored by the count-weighted
  woe mean/std (reference ``calculateWoeMeanAndStdDev``).
- HYBRID / WEIGHT_HYBRID: numeric zscore, categorical (weighted) woe.
- ONEHOT: bin one-hot incl. missing bin; ZSCALE_ONEHOT: numeric zscore +
  categorical one-hot.
- DISCRETE_ZSCORE: numeric discretized to bin left boundary (first bin: min)
  then z-scored; categorical -> posrate zscore.
- ASIS_WOE/ASIS_PR: raw numeric passthrough (missing -> mean); categorical ->
  bin woe / posrate.
- ZSCALE_INDEX / WOE_INDEX / WOE_ZSCALE_INDEX: categorical -> raw category
  index (missing -> num categories), numeric -> zscore / woe / zscored-woe.

Everything is table-lookup + affine math over columnar arrays: per column we
precompute a bin->value table, so normalization = bin-index gather (+ z-score
clip), which XLA fuses into the ingest pipeline on device; here the gather
runs in numpy at stream time since inputs arrive as host strings anyway.

Precision truncation mirrors ``NormalizeUDF.java:540-570``: FLOAT7 rounds to
7 decimals, FLOAT16 squeezes through half precision, FLOAT32/DOUBLE64 cast.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from ..config import ColumnConfig
from ..config.model_config import NormType, PrecisionType


class CategoryMissingNormType(enum.Enum):
    POSRATE = "POSRATE"
    MEAN = "MEAN"


def _nan_to(arr: Optional[List[Optional[float]]], fill: float) -> np.ndarray:
    if arr is None:
        return np.array([fill])
    a = np.array([fill if v is None else v for v in arr], dtype=np.float64)
    a[~np.isfinite(a)] = fill
    return a


def woe_mean_std(cc: ColumnConfig, weighted: bool) -> Tuple[float, float]:
    """Count-weighted mean/std of the per-bin woe values (incl. missing bin),
    reference ``Normalizer.calculateWoeMeanAndStdDev``."""
    bn = cc.columnBinning
    woes = _nan_to(bn.binWeightedWoe if weighted else bn.binCountWoe, 0.0)
    if weighted:
        counts = (_nan_to(bn.binWeightedPos, 0) + _nan_to(bn.binWeightedNeg, 0))
    else:
        counts = (_nan_to([float(x) for x in (bn.binCountPos or [0])], 0)
                  + _nan_to([float(x) for x in (bn.binCountNeg or [0])], 0))
    n = min(len(woes), len(counts))
    woes, counts = woes[:n], counts[:n]
    total = counts.sum()
    if total <= 0:
        return 0.0, 1.0
    mean = float((woes * counts).sum() / total)
    var = float(((woes - mean) ** 2 * counts).sum() / total)
    return mean, np.sqrt(var) if var > 1e-20 else 1.0


def z_score(v: np.ndarray, mean: float, std: float, cutoff: float) -> np.ndarray:
    """Reference ``Normalizer.computeZScore``: clip to mean±cutoff·std then
    standardize; zero when std ~ 0."""
    if std is None or std < 1e-5:
        return np.zeros_like(v)
    clipped = np.clip(v, mean - cutoff * std, mean + cutoff * std)
    return (clipped - mean) / std


@dataclass
class NormalizedColumn:
    """Per-column normalization plan: output width + vectorized transform."""
    cc: ColumnConfig
    norm_type: NormType
    cutoff: float
    cate_missing: CategoryMissingNormType = CategoryMissingNormType.POSRATE

    def output_names(self) -> List[str]:
        name = self.cc.columnName
        if self.norm_type in (NormType.ONEHOT, NormType.ZSCALE_ONEHOT):
            if self.norm_type == NormType.ONEHOT or self.cc.is_categorical():
                return [f"{name}_{i}" for i in range(self.cc.num_bins() + 1)]
        return [name]

    @property
    def width(self) -> int:
        return len(self.output_names())

    # ------------------------------------------------------------ tables
    def _posrate_table(self) -> np.ndarray:
        """bin -> posRate incl. missing bin; missing-bin fill per policy."""
        cc = self.cc
        mean = cc.mean()
        table = _nan_to(cc.bin_pos_rate, mean)
        if self.cate_missing == CategoryMissingNormType.MEAN and len(table):
            table[-1] = mean
        return table

    def _woe_table(self, weighted: bool) -> np.ndarray:
        bn = self.cc.columnBinning
        return _nan_to(bn.binWeightedWoe if weighted else bn.binCountWoe, 0.0)

    def bin_value_table(self, num_bins: int) -> np.ndarray:
        """``bin index -> normalized value`` as ONE f64 table, evaluated by
        the offline transform itself over every index a binner can emit
        (``0..num_bins+1``: real bins, the missing bin, and the clip
        sentinel).  Any bin-index-only norm family collapses to this
        gather, so the fused serving prelude (``serve.transform``) replays
        the offline values verbatim from a device constant — the public
        contract behind its bit-parity guarantee.  Value-carrying numeric
        families (ZSCALE/ZSCORE/HYBRID/ASIS) do NOT collapse; callers
        handle those with the clip/affine path instead."""
        dom = np.arange(num_bins + 2)
        if self.cc.is_categorical():
            return np.asarray(self._transform_categorical(dom), np.float64)
        return np.asarray(self._transform_numeric(
            np.zeros(len(dom)), np.ones(len(dom), bool), dom), np.float64)

    # --------------------------------------------------------- transform
    def transform(self, values: np.ndarray, valid: np.ndarray,
                  bin_idx: np.ndarray) -> np.ndarray:
        """values: numeric floats (NaN ok) or unused for categorical;
        bin_idx: precomputed bin indices (missing -> num_bins);
        returns [R, width] float64."""
        cc = self.cc
        t = self.norm_type
        cutoff = self.cutoff
        mean, std = cc.mean(), cc.std_dev()

        if t in (NormType.ONEHOT,) or (t == NormType.ZSCALE_ONEHOT and cc.is_categorical()):
            width = self.width
            out = np.zeros((len(bin_idx), width))
            idx = np.clip(bin_idx, 0, width - 1)
            out[np.arange(len(bin_idx)), idx] = 1.0
            return out

        if cc.is_categorical():
            return self._transform_categorical(bin_idx)[:, None]
        return self._transform_numeric(values, valid, bin_idx)[:, None]

    def _transform_numeric(self, values: np.ndarray, valid: np.ndarray,
                           bin_idx: np.ndarray) -> np.ndarray:
        cc, t, cutoff = self.cc, self.norm_type, self.cutoff
        mean, std = cc.mean(), cc.std_dev()
        v = np.where(valid, values, mean)  # missing -> mean (z = 0)

        if t in (NormType.WOE, NormType.WEIGHT_WOE, NormType.WOE_INDEX):
            table = self._woe_table(t == NormType.WEIGHT_WOE)
            return _safe_gather(table, bin_idx)
        if t in (NormType.WOE_ZSCORE, NormType.WOE_ZSCALE,
                 NormType.WEIGHT_WOE_ZSCORE, NormType.WEIGHT_WOE_ZSCALE,
                 NormType.WOE_ZSCALE_INDEX):
            weighted = t in (NormType.WEIGHT_WOE_ZSCORE, NormType.WEIGHT_WOE_ZSCALE)
            woe = _safe_gather(self._woe_table(weighted), bin_idx)
            wmean, wstd = woe_mean_std(cc, weighted)
            return z_score(woe, wmean, wstd, cutoff)
        if t in (NormType.DISCRETE_ZSCORE, NormType.DISCRETE_ZSCALE):
            bnds = _nan_to(cc.bin_boundary, mean)
            table = bnds.copy()
            if cc.columnStats.min is not None:
                table[0] = cc.columnStats.min  # first bin uses the min value
            disc = _safe_gather(np.append(table, mean), bin_idx)  # missing->mean
            return z_score(disc, mean, std, cutoff)
        if t in (NormType.ASIS_WOE, NormType.ASIS_PR):
            return v
        # ZSCALE/ZSCORE/OLD_*/HYBRID*/ZSCALE_ONEHOT numeric / *_INDEX numeric
        return z_score(v, mean, std, cutoff)

    def _transform_categorical(self, bin_idx: np.ndarray) -> np.ndarray:
        cc, t, cutoff = self.cc, self.norm_type, self.cutoff
        if t in (NormType.ZSCALE_INDEX, NormType.ZSCORE_INDEX, NormType.WOE_INDEX,
                 NormType.WOE_ZSCALE_INDEX):
            return bin_idx.astype(np.float64)  # missing already = num categories
        if t in (NormType.WOE, NormType.WEIGHT_WOE, NormType.HYBRID,
                 NormType.WEIGHT_HYBRID, NormType.ASIS_WOE):
            weighted = t in (NormType.WEIGHT_WOE, NormType.WEIGHT_HYBRID)
            return _safe_gather(self._woe_table(weighted), bin_idx)
        if t in (NormType.WOE_ZSCORE, NormType.WOE_ZSCALE,
                 NormType.WEIGHT_WOE_ZSCORE, NormType.WEIGHT_WOE_ZSCALE):
            weighted = t in (NormType.WEIGHT_WOE_ZSCORE, NormType.WEIGHT_WOE_ZSCALE)
            woe = _safe_gather(self._woe_table(weighted), bin_idx)
            wmean, wstd = woe_mean_std(cc, weighted)
            return z_score(woe, wmean, wstd, cutoff)
        if t == NormType.ASIS_PR:
            return _safe_gather(self._posrate_table(), bin_idx)
        # ZSCALE family: posrate then z-score (OLD_* returns raw posrate)
        pr = _safe_gather(self._posrate_table(), bin_idx)
        if t in (NormType.OLD_ZSCALE, NormType.OLD_ZSCORE):
            return pr
        return z_score(pr, self.cc.mean(), self.cc.std_dev(), cutoff)


def _safe_gather(table: np.ndarray, idx: np.ndarray) -> np.ndarray:
    if len(table) == 0:
        return np.zeros(len(idx))
    return table[np.clip(idx, 0, len(table) - 1)]


def apply_precision(x: np.ndarray, precision: PrecisionType) -> np.ndarray:
    """Output rounding family, reference ``NormalizeUDF.java:540-570``."""
    if precision == PrecisionType.FLOAT7:
        return np.round(x, 7)
    if precision == PrecisionType.FLOAT16:
        return x.astype(np.float16).astype(np.float64)
    if precision == PrecisionType.FLOAT32:
        return x.astype(np.float32).astype(np.float64)
    return x
