"""Quantized tree-traversal scoring: forests walked directly on uint8
bin planes, f32 only at the leaf-value accumulate.

Bins have been uint8 on the wire since PR 2 (the spill cache re-emits
the compact dtype) and stay uint8 in HBM for the trainers — yet every
SCORING traversal widened them to int32 at entry
(``IndependentTreeModel.compute``, ``ops.tree.predict_forest``), so the
serving plane's dominant operand cost 4x the bytes it carried.  This
module keeps the whole walk narrow:

- routing state is integer end-to-end: feature-index gather (uint8 bins,
  int32 node ids), bin-subset membership test (uint8 left-mask planes),
  child-index arithmetic — bit-identical to the f32/one-hot traversal in
  :mod:`shifu_tpu.ops.tree` by construction (every decision is an exact
  integer select; the one-hot form was itself exact);
- f32 appears exactly once, at the terminal leaf-value gather.

Two lowerings, dispatched like the histogram kernel
(:mod:`shifu_tpu.ops.hist_pallas`):

- a Pallas TPU kernel (``SHIFU_TREE_QUANT`` / property
  ``shifu.tree.quantKernel``): grid (row-blocks x trees), the bins block
  loaded into VMEM ONCE per row block and revisited across the whole
  forest — where the XLA lowering re-streams the [N, C] plane per
  (tree, level), the kernel pays the HBM read once.  Selects are one-hot
  matmuls over 0/1 operands (exact at any precision — the
  ``ops.tree._sel_exact`` argument), so the kernel lowers through the
  MXU without gathers.  Tests drive it in interpret mode on CPU.
- a jnp gather fallback (CPU / kernel off) that IS the narrow twin of
  ``ops.tree.traverse_nodes``'s gather branch — same routing, uint8
  operands.

The kernel is opaque to XLA's cost analysis, so an analytic model
registers under ``pallas.tree_traverse`` (the ``hist_kernel_cost``
pattern) and the serving plane records one model launch per scored
bucket — serving MFU rows stay honest.
"""

from __future__ import annotations

import os
from functools import lru_cache, partial
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

LANE = 128


# ------------------------------------------------------------------ knobs
def _quant_knob() -> str:
    """``SHIFU_TREE_QUANT`` env, falling back to the documented
    ``-Dshifu.tree.quantKernel`` property (the docs promised the
    property form long before it was wired — the knob-registry lint
    caught the gap)."""
    env = os.environ.get("SHIFU_TREE_QUANT")
    if env is not None:
        return env
    from ..config import environment
    return environment.get_property("shifu.tree.quantKernel", "auto")


@lru_cache(maxsize=None)
def quant_scoring() -> bool:
    """Use the quantized (uint8-narrow) scoring path at all.  Default ON —
    routing is bit-identical to the classic traversal on every backend;
    ``SHIFU_TREE_QUANT=0`` pins the old path (tests pin both)."""
    return _quant_knob() not in ("0", "off")


@lru_cache(maxsize=None)
def quant_kernel() -> bool:
    """Lower the traversal through the Pallas kernel (TPU only; the
    fallback serves CPU and kernel-off).  ``SHIFU_TREE_QUANT=force``
    pins the kernel on (interpret mode off-TPU — tests); ``=0/off``
    disables with the whole quant path."""
    env = _quant_knob()
    if env in ("0", "off"):
        return False
    if env == "force":
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:                                  # pragma: no cover
        return False


def bins_fit_uint8(n_bins: int) -> bool:
    """Whether a forest's bin ids ride uint8 (ids in [0, n_bins))."""
    return n_bins <= 256


def ensemble_bins_dtype(models: Sequence) -> np.dtype:
    """The narrowest dtype an ensemble's bins input can ride: uint8 when
    every bin-consuming model's id space fits a byte (tree forests with
    n_bins <= 256 — the PR 2 wire contract — and WDL categorical
    cardinalities <= 256), else int32.  Scoring batches then carry 1/4
    the bin bytes across H2D and HBM."""
    for m in models:
        name = type(m).__name__
        if name == "IndependentTreeModel":
            if m.spec.n_bins > 256:
                return np.dtype(np.int32)
        elif getattr(m, "input_kind", "norm") == "both":
            cards = getattr(m.spec, "cat_cardinalities", None) or []
            if cards and max(cards) > 256:
                return np.dtype(np.int32)
    return np.dtype(np.uint8)


# ------------------------------------------------------------ forest prep
def stack_forest_quant(trees) -> Tuple[jnp.ndarray, jnp.ndarray,
                                       jnp.ndarray]:
    """Same-depth trees stacked in the quantized layout: split_feat
    [T, K] int32, left-mask planes [T, K, B] uint8 (1 = bin goes left),
    leaf values [T, K] (or [T, K, S] multiclass) f32."""
    sf = jnp.stack([jnp.asarray(t.split_feat, jnp.int32) for t in trees])
    lm = jnp.stack([jnp.asarray(np.asarray(t.left_mask, np.uint8))
                    for t in trees])
    lv = jnp.stack([jnp.asarray(t.leaf_value, jnp.float32) for t in trees])
    return sf, lm, lv


# ------------------------------------------------------- fallback (jnp)
def traverse_quant(split_feat, left_u8, bins, depth: int):
    """Terminal global node id per row — the narrow gather walk.  bins
    [N, C] any integer dtype (uint8 stays uint8: the gather consumes it
    directly, no widen of the plane); split_feat [K] int32; left_u8
    [K, B] uint8.  Routing is the gather branch of
    ``ops.tree.traverse_nodes`` verbatim, so node ids — and therefore
    scores — are bit-identical to the classic path."""
    n = bins.shape[0]
    node = jnp.zeros(n, jnp.int32)
    for _ in range(depth):
        feat = split_feat[node]
        row_bin = jnp.take_along_axis(
            bins, jnp.maximum(feat, 0)[:, None], axis=1)[:, 0] \
            .astype(jnp.int32)
        goes_left = left_u8[node, row_bin] > 0
        child = jnp.where(goes_left, 2 * node + 1, 2 * node + 2)
        node = jnp.where(feat >= 0, child, node)
    return node


@partial(jax.jit, static_argnames=("depth",))
def _predict_quant_ref(split_feats, left_u8s, leaf_values, bins,
                       depth: int):
    """[T, N] (or [T, N, S]) fallback forest predict: vmapped narrow
    walks, one f32 leaf gather at the end."""
    def one(sf, lm, lv):
        return lv[traverse_quant(sf, lm, bins, depth)]
    return jax.vmap(one)(split_feats, left_u8s, leaf_values)


# --------------------------------------------------------- pallas kernel
def _traverse_kernel(bins_ref, sf_ref, lm_ref, lv_ref, out_ref, *,
                     depth: int, nblk: int, b_pad: int):
    """One (row block, tree) cell: walk ``depth`` levels with level-local
    one-hot selects (all 0/1 operands — exact), then the leaf-value dot.

    bins_ref [C_pad, nblk] int32 (features on sublanes, rows on lanes —
    the block is fetched from HBM once per row block and revisited
    across the tree sweep); sf_ref/lv_ref [1, K_pad] f32; lm_ref
    [1, K_pad, b_pad] f32 (0/1)."""
    binsf = bins_ref[...].astype(jnp.float32)            # [C_pad, nblk]
    c_pad = binsf.shape[0]
    node = jnp.zeros((1, nblk), jnp.int32)               # global node ids
    dims0 = (((0,), (0,)), ((), ()))                     # contract dim 0
    mm = (((1,), (0,)), ((), ()))                        # plain matmul
    for level in range(depth):
        k = 1 << level
        base = k - 1
        loc = node - base                                # level-local
        k_iota = jax.lax.broadcasted_iota(jnp.int32, (k, nblk), 0)
        oh = (k_iota == loc).astype(jnp.float32)         # [k, nblk]
        # feature id of each row's node: [1, k] x [k, nblk] one-term dot
        feat = jax.lax.dot_general(
            sf_ref[0:1, base:base + k], oh, mm,
            preferred_element_type=jnp.float32)          # [1, nblk]
        # row's bin at that feature: one-hot over the feature sublanes
        c_iota = jax.lax.broadcasted_iota(jnp.float32, (c_pad, nblk), 0)
        featoh = (c_iota == feat).astype(jnp.float32)
        rb = (featoh * binsf).sum(axis=0, keepdims=True)  # [1, nblk]
        # left-mask row select + bin membership, [B, nblk] oriented so
        # every reduction runs over sublanes (no transposes)
        lm_lvl = lm_ref[0, base:base + k, :]             # [k, b_pad]
        lrow = jax.lax.dot_general(
            lm_lvl, oh, dims0,
            preferred_element_type=jnp.float32)          # [b_pad, nblk]
        b_iota = jax.lax.broadcasted_iota(jnp.float32, (b_pad, nblk), 0)
        binoh = (b_iota == rb).astype(jnp.float32)
        goes_left = (lrow * binoh).sum(axis=0,
                                       keepdims=True) > 0.5  # [1, nblk]
        in_level = loc >= 0                              # frozen earlier?
        is_split = in_level & (feat >= 0)
        child = 2 * node + jnp.where(goes_left, 1, 2)
        node = jnp.where(is_split, child, node)
    k_total = sf_ref.shape[1]
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (k_total, nblk), 0)
    oh = (k_iota == node).astype(jnp.float32)
    out_ref[...] = jax.lax.dot_general(
        lv_ref[0:1, :], oh, mm,
        preferred_element_type=jnp.float32)              # [1, nblk]


def _pad_to(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


@partial(jax.jit, static_argnames=("depth", "interpret"))
def _predict_quant_pallas(split_feats, left_u8s, leaf_values, bins,
                          depth: int, interpret: bool = False):
    """Kernel launch wrapper: pads/transposes operands to tile shapes
    (bins widen to int32 per VMEM block, the ``hist_pallas`` convention —
    uint8 in HBM, int32 only block-local) and trims the output."""
    from jax.experimental import pallas as pl

    t, k = split_feats.shape
    n, c = bins.shape
    b = left_u8s.shape[2]
    nblk = LANE if n <= LANE else 4 * LANE
    n_pad = _pad_to(n, nblk)
    c_pad = _pad_to(c, 8)
    k_pad = _pad_to(k, 8)
    b_pad = _pad_to(b, 8)
    binst = jnp.zeros((c_pad, n_pad), jnp.int32) \
        .at[:c, :n].set(bins.astype(jnp.int32).T)
    # split ids pad with -1 (leaf): pad rows route nowhere
    sf = jnp.full((t, k_pad), -1.0, jnp.float32) \
        .at[:, :k].set(split_feats.astype(jnp.float32))
    lm = jnp.zeros((t, k_pad, b_pad), jnp.float32) \
        .at[:, :k, :b].set(left_u8s.astype(jnp.float32))
    lv = jnp.zeros((t, k_pad), jnp.float32).at[:, :k].set(leaf_values)
    grid = (n_pad // nblk, t)
    out = pl.pallas_call(
        partial(_traverse_kernel, depth=depth, nblk=nblk, b_pad=b_pad),
        grid=grid,
        in_specs=[
            pl.BlockSpec((c_pad, nblk), lambda r, ti: (0, r)),
            pl.BlockSpec((1, k_pad), lambda r, ti: (ti, 0)),
            pl.BlockSpec((1, k_pad, b_pad), lambda r, ti: (ti, 0, 0)),
            pl.BlockSpec((1, k_pad), lambda r, ti: (ti, 0)),
        ],
        out_specs=pl.BlockSpec((1, nblk), lambda r, ti: (ti, r)),
        out_shape=jax.ShapeDtypeStruct((t, n_pad), jnp.float32),
        interpret=interpret,
    )(binst, sf, lm, lv)
    return out[:, :n]


# ------------------------------------------------------------- dispatch
def _spans_devices(a) -> bool:
    """True when ``a`` is sharded across >1 device — a pallas_call is
    not partitionable, so such inputs must take the jnp fallback (which
    GSPMD partitions like any other traversal)."""
    try:
        sh = getattr(a, "sharding", None)
        return sh is not None and len(sh.device_set) > 1
    except Exception:                                  # pragma: no cover
        return False


def predict_forest_quant(split_feats, left_u8s, leaf_values, bins,
                         depth: int, use_kernel=None,
                         interpret: bool = False):
    """[T, N] forest predictions over the narrow plane — kernel on TPU
    (or forced/interpret), jnp fallback elsewhere.  Multiclass leaf
    distributions ([T, K, S]) and mesh-sharded bins always take the
    fallback (the kernel's leaf dot is scalar-leaf shaped, and a
    pallas_call cannot be partitioned)."""
    if use_kernel is None:
        use_kernel = quant_kernel() and not _spans_devices(bins)
    if use_kernel and leaf_values.ndim == 2:
        return _predict_quant_pallas(split_feats, left_u8s, leaf_values,
                                     bins, depth, interpret)
    return _predict_quant_ref(split_feats, left_u8s, leaf_values, bins,
                              depth)


# -------------------------------------------------- analytic cost model
def quant_traverse_cost(rows: int, n_feat: int, n_bins: int,
                        n_nodes: int, depth: int,
                        n_trees: int = 1) -> dict:
    """FLOPs / bytes of one traversal-kernel launch.

    Per (tree, level k-wide): the feature dot (2*k*N), the feature
    one-hot + bin select (~3*C*N), the mask dot (2*k*B*N) and the bin
    membership reduce (~3*B*N); plus the terminal leaf dot (2*K*N).
    Bytes: the uint8 bins plane read ONCE (the kernel's point — the XLA
    lowering reads it per tree), per-tree node arrays and masks once,
    [T, N] f32 out written once."""
    lv_flops = 0.0
    for level in range(depth):
        k = 1 << level
        lv_flops += 2.0 * k + 3.0 * n_feat + 2.0 * k * n_bins \
            + 3.0 * n_bins
    flops = float(rows) * n_trees * (lv_flops + 2.0 * n_nodes)
    read = 1.0 * rows * n_feat \
        + n_trees * (4.0 * n_nodes + 1.0 * n_nodes * n_bins
                     + 4.0 * n_nodes)
    write = 4.0 * n_trees * rows
    return {"flops": flops, "bytes_accessed": read + write}


def _register_cost_model() -> None:
    from ..obs import costs
    costs.register_cost_model("pallas.tree_traverse", quant_traverse_cost)


_register_cost_model()
