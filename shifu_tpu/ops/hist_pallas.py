"""Pallas TPU kernel for the decision-tree histogram build — the hot op.

The reference accumulates per-(node, feature, bin) stats with a
thread-parallel scalar loop (``DTWorker.java:763-884``, the
``impurity.featureUpdate`` hot loop at ``:844-854``).  The XLA port of that
idea (``jax.ops.segment_sum``) lowers to scatter-add, which the TPU
serializes — measured ~0.8 s per tree at 131k rows x 64 features on a v5e
chip, dwarfing every other part of tree growth.

TPU-first formulation: a histogram is a matmul against one-hot encodings,

    out[k*S+s, c*B+b] = sum_n  [node(n)==k] * stats(n,s) * [bins(n,c)==b]

so the MXU can do the accumulation — *if* the one-hot operands never
materialize in HBM (a [N, C*B] one-hot would be GBs).  This kernel builds
both one-hots on the fly in VMEM per (feature, row-block) grid cell and
feeds them straight to ``dot_general``:

    grid (C, R):   rows blocked over R, one feature per grid column
      oneh_T  [B_pad, nblk] = (bin_iota == bins_T[c, block])     (VPU)
      node1h  [K, nblk]     = (node_iota == node_T[block])       (VPU)
      per s:  out[c, s] += (node1h * stats_T[s]) @ oneh_T.T      (MXU)

Everything is static-shaped; rows past N pad with node=-1 (matches no
one-hot row, contributes zero).  S generalizes to per-class stat channels
for multiclass forests.  Measured ~50x over the scatter path at bench
shapes (131k x 64 x 64 bins, K=64).
"""

from __future__ import annotations

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128

# jax renamed TPUCompilerParams -> CompilerParams across 0.4/0.5; accept
# whichever this toolchain ships so the kernels lower on both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    getattr(pltpu, "TPUCompilerParams")


def _shard_map(fn, mesh, in_specs, out_specs):
    """``jax.shard_map`` with the replication check off, across the
    0.4/0.5 API split (top-level ``shard_map(check_vma=)`` vs
    ``jax.experimental.shard_map.shard_map(check_rep=)``) — the checker
    can't see through a pallas_call either way."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def _bf16_split(a):
    """bf16 (hi, lo) halves of an f32 operand — two native-rate MXU
    passes recover ~f32 accuracy (residual ~eps_bf16^2).  The split must
    NOT be written as a convert round-trip (a - f32(bf16(a))): XLA's
    allow-excess-precision simplification — explicitly enabled on this
    TPU toolchain — folds that to zero, silently degrading the kernel to
    plain bf16.  Masking the low mantissa bits via bitcast is opaque to
    the simplifier."""
    hi_f = jax.lax.bitcast_convert_type(
        jax.lax.bitcast_convert_type(a, jnp.uint32)
        & jnp.uint32(0xFFFF0000), jnp.float32)            # bf16-exact
    return hi_f.astype(jnp.bfloat16), (a - hi_f).astype(jnp.bfloat16)


def _hist_kernel(bins_ref, node_ref, stats_ref, out_ref, *, n_stats: int,
                 n_nodes: int, b_pad: int, nblk: int, cblk: int,
                 pair: bool = False, exact: bool = False):
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    nview = node_ref[0:1, :]
    k_iota = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, nblk), 0)
    node1h = (k_iota == nview).astype(jnp.float32)        # [K, nblk]
    # f32 accuracy at bf16 speed (see _bf16_split): stats channels feed
    # split gains, and the reference accumulates in double
    # (``DTWorker.java:850-852``) — plain bf16 rounding shifted chosen
    # thresholds measurably (2.5% cell error at bench shapes), the hi/lo
    # split does not.  ``exact=True`` (every stats value bf16-exact —
    # integer bag counts x 0/1 targets, the RF-without-weight-column
    # case) skips the split and the recovery dot entirely.
    #
    # Stat-channel PAIRS pack along the sublane axis ([2K, nblk] left
    # operands, K <= K_MAX = 64): one dot drives a full 128-row MXU tile
    # where per-channel dots drove two half-empty ones.
    a_hi, a_lo = [], []                  # per channel-GROUP operands
    groups = []                          # (s0, n_in_group)
    s = 0
    while s < n_stats:
        g = 2 if s + 1 < n_stats else 1
        a = jnp.concatenate(
            [node1h * stats_ref[s + j:s + j + 1, :] for j in range(g)],
            axis=0)                       # [g*K, nblk] f32
        if exact:
            a_hi.append(a.astype(jnp.bfloat16))
            a_lo.append(None)
        else:
            hi_b, lo_b = _bf16_split(a)
            a_hi.append(hi_b)
            a_lo.append(lo_b)
        groups.append((s, g))
        s += g
    dims = (((1,), (1,)), ((), ()))
    half = LANE // 2

    def accumulate(oneh, store):
        """One (or two) dots per channel group; ``store(gi, s, acc_s)``
        writes channel s's [K, LANE] slice."""
        for gi, (s0, g) in enumerate(groups):
            acc = jax.lax.dot_general(
                a_hi[gi], oneh, dims,
                preferred_element_type=jnp.float32)       # [g*K, LANE]
            if a_lo[gi] is not None:
                acc += jax.lax.dot_general(
                    a_lo[gi], oneh, dims,
                    preferred_element_type=jnp.float32)
            for j in range(g):
                store(s0 + j, acc[j * n_nodes:(j + 1) * n_nodes, :])

    if pair:
        # n_bins <= 64: pack TWO features per 128-lane tile (lanes 0-63 =
        # feature cf's bins, 64-127 = feature cf+1's) — halves the dots
        b_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, nblk), 0)
        lo_half = b_iota < half
        lane_val = jnp.where(lo_half, b_iota, b_iota - half)
        for cf in range(0, cblk, 2):
            bview_a = bins_ref[cf:cf + 1, :]              # [1, nblk]
            bview_b = bins_ref[cf + 1:cf + 2, :]
            oneh = (lane_val == jnp.where(lo_half, bview_a, bview_b)) \
                .astype(jnp.bfloat16)                     # [LANE, nblk]

            def store_pair(s, acc_s, cf=cf):
                out_ref[cf, s, :, :] += acc_s[:, :half]
                out_ref[cf + 1, s, :, :] += acc_s[:, half:]
            accumulate(oneh, store_pair)
        return
    for cf in range(cblk):
        bview = bins_ref[cf:cf + 1, :]                    # [1, nblk]
        for bt in range(b_pad // LANE):
            b_iota = jax.lax.broadcasted_iota(
                jnp.int32, (LANE, nblk), 0) + bt * LANE
            oneh = (b_iota == bview).astype(jnp.bfloat16)  # [LANE, nblk]

            def store_flat(s, acc_s, cf=cf, bt=bt):
                out_ref[cf, s, :, bt * LANE:(bt + 1) * LANE] += acc_s
            accumulate(oneh, store_flat)


def _hist_kernel_batch(bins_ref, node_ref, stats_ref, out_ref, *,
                       n_stats: int, n_trees: int, n_nodes: int, b_pad: int,
                       nblk: int, cblk: int, pair: bool = False,
                       exact: bool = False):
    """Multi-TREE histogram grid: TB independent trees' level histograms in
    ONE kernel launch.

    Same one-hot-matmul formulation as :func:`_hist_kernel`, with a
    tree-batch axis: each tree t has its own level-local ``node_ref[t]``
    row positions and its own ``stats_ref[t*S:(t+1)*S]`` channels (RF bags
    differ per tree), while the bins one-hot — the dominant VPU work at
    shallow levels — is built ONCE per (feature, row-block) grid cell and
    shared by every tree's dots.  The per-tree dot sequence (row blocks in
    grid order, channel pairs packed on the sublane axis, the bf16 hi/lo
    split) is IDENTICAL to the single-tree kernel's, so each tree's
    histogram is bit-identical to what ``_hist_kernel`` would produce —
    the batched==sequential parity guard pins this.

    Replaces TB sequential launches in the forest inner loop
    (``DTWorker.java:763-884`` runs the same per-tree loop thread-parallel;
    ``DTMaster.java:91`` grows all RF trees of a round simultaneously).
    """
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    k_iota = jax.lax.broadcasted_iota(jnp.int32, (n_nodes, nblk), 0)
    a_hi, a_lo = [], []                  # per (tree, channel-group) operands
    groups = []                          # (tree, s0, n_in_group)
    for t in range(n_trees):
        node1h = (k_iota == node_ref[t:t + 1, :]).astype(jnp.float32)
        s = 0
        while s < n_stats:
            g = 2 if s + 1 < n_stats else 1
            a = jnp.concatenate(
                [node1h * stats_ref[t * n_stats + s + j:
                                    t * n_stats + s + j + 1, :]
                 for j in range(g)], axis=0)          # [g*K, nblk] f32
            if exact:
                a_hi.append(a.astype(jnp.bfloat16))
                a_lo.append(None)
            else:
                hi_b, lo_b = _bf16_split(a)
                a_hi.append(hi_b)
                a_lo.append(lo_b)
            groups.append((t, s, g))
            s += g
    dims = (((1,), (1,)), ((), ()))
    half = LANE // 2

    def accumulate(oneh, store):
        """One (or two) dots per (tree, channel group); ``store(t, s,
        acc_s)`` writes tree t / channel s's [K, LANE] slice."""
        for gi, (t, s0, g) in enumerate(groups):
            acc = jax.lax.dot_general(
                a_hi[gi], oneh, dims,
                preferred_element_type=jnp.float32)       # [g*K, LANE]
            if a_lo[gi] is not None:
                acc += jax.lax.dot_general(
                    a_lo[gi], oneh, dims,
                    preferred_element_type=jnp.float32)
            for j in range(g):
                store(t, s0 + j, acc[j * n_nodes:(j + 1) * n_nodes, :])

    if pair:
        b_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, nblk), 0)
        lo_half = b_iota < half
        lane_val = jnp.where(lo_half, b_iota, b_iota - half)
        for cf in range(0, cblk, 2):
            bview_a = bins_ref[cf:cf + 1, :]              # [1, nblk]
            bview_b = bins_ref[cf + 1:cf + 2, :]
            oneh = (lane_val == jnp.where(lo_half, bview_a, bview_b)) \
                .astype(jnp.bfloat16)                     # [LANE, nblk]

            def store_pair(t, s, acc_s, cf=cf):
                out_ref[cf, t, s, :, :] += acc_s[:, :half]
                out_ref[cf + 1, t, s, :, :] += acc_s[:, half:]
            accumulate(oneh, store_pair)
        return
    for cf in range(cblk):
        bview = bins_ref[cf:cf + 1, :]                    # [1, nblk]
        for bt in range(b_pad // LANE):
            b_iota = jax.lax.broadcasted_iota(
                jnp.int32, (LANE, nblk), 0) + bt * LANE
            oneh = (b_iota == bview).astype(jnp.bfloat16)  # [LANE, nblk]

            def store_flat(t, s, acc_s, cf=cf, bt=bt):
                out_ref[cf, t, s, :, bt * LANE:(bt + 1) * LANE] += acc_s
            accumulate(oneh, store_flat)


K_MAX = 64   # per-call node cap: the [C_pad, S, K, B_pad] output must sit
             # under the ~16 MB VMEM scoped-allocation limit


# -------------------------------------------------- analytic cost model
# A pallas_call is an opaque custom call to XLA's cost analysis — the
# flops/bytes the obs cost plane would read off ``lowered.
# cost_analysis()`` come back zero.  This hand model of the one-hot MXU
# formulation registers with obs.costs under ``pallas.hist`` so the
# utilization report still attributes the kernel's work (the streamed
# trainers record one model launch per window when the kernel path is
# on).
def hist_kernel_cost(rows: int, n_feat: int, n_bins: int, n_nodes: int,
                     n_stats: int = 2, n_trees: int = 1) -> dict:
    """FLOPs / bytes of one histogram-kernel launch.

    Dominant term: per (feature, stat channel) the kernel feeds the MXU
    a [K, N] x [N, B] dot (node one-hot x bin one-hot) — 2*K*N*B MACs —
    plus the VPU one-hot constructions (~N*B + N*K compares).  Bytes:
    bins read once per launch (int32 in VMEM after the in-graph widen),
    stats per tree, and the [K, C, B, S] output written once.
    """
    dot = 2.0 * rows * n_nodes * n_bins * n_stats * n_feat * n_trees
    onehot = float(rows) * (n_bins + n_nodes) * n_feat * n_trees
    read = 4.0 * rows * n_feat + 4.0 * rows * n_stats * n_trees
    write = 4.0 * n_trees * n_nodes * n_feat * n_bins * n_stats
    return {"flops": dot + onehot, "bytes_accessed": read + write}


def _register_cost_model() -> None:
    from ..obs import costs
    costs.register_cost_model("pallas.hist", hist_kernel_cost)


_register_cost_model()


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "interpret",
                                   "exact"))
def build_histograms_pallas(bins, node_idx, stats, n_nodes: int,
                            n_bins: int, interpret: bool = False,
                            exact: bool = False):
    """Drop-in for :func:`shifu_tpu.ops.tree.build_histograms` on TPU.

    bins: [N, C] int32; node_idx: [N] int32 (-1 = inactive);
    stats: [N, S] float32.  Returns [n_nodes, C, n_bins, S] float32.
    ``exact=True`` asserts every stats value is exactly representable in
    bfloat16 (small-integer bag counts x 0/1 indicators): the f32-recovery
    dot is skipped (see ``_hist_kernel``).

    Deep levels decompose into K_MAX-node partitions: shifting
    ``node_idx`` by the partition base makes out-of-range rows match no
    one-hot row, so each call accumulates exactly its node range.
    """
    bins = bins.astype(jnp.int32)   # narrow-wire (uint8/uint16) bins widen
    if n_nodes > K_MAX:             # here; Mosaic sees the one int32 layout
        parts = []
        for k0 in range(0, n_nodes, K_MAX):
            parts.append(build_histograms_pallas(
                bins, node_idx - k0, stats, min(K_MAX, n_nodes - k0),
                n_bins, interpret, exact))
        return jnp.concatenate(parts, axis=0)
    n, c = bins.shape
    s = stats.shape[1]
    pair = n_bins <= LANE // 2       # two features share one 128-lane tile
    b_pad = LANE // 2 if pair else ((n_bins + LANE - 1) // LANE) * LANE
    cblk = 8                 # Mosaic wants >=8 sublanes per bins block
    c_pad = ((c + cblk - 1) // cblk) * cblk
    # row-block: large enough to keep the MXU busy, small enough that the
    # [K, nblk] + [B_pad, nblk] VMEM operands stay comfortably resident;
    # shallow levels (tiny K) take wider blocks — they are grid-step
    # bound, not VMEM bound (K is already <= K_MAX here)
    # wider row blocks when the one-hot node operand is small (histogram
    # subtraction keeps K <= 32 through depth 6): fewer grid steps, same
    # VMEM envelope (~10 MB at 16384)
    nblk = int(os.environ.get("SHIFU_HIST_NBLK", 0)) or \
        (16384 if n_nodes <= 16 else 8192 if n_nodes <= 32 else 2048)
    n_pad = ((n + nblk - 1) // nblk) * nblk

    bins_t = jnp.pad(bins, ((0, n_pad - n), (0, c_pad - c))).T  # [C_pad, N_pad]
    node_t = jnp.pad(node_idx, (0, n_pad - n),
                     constant_values=-1)[None, :]            # [1, N_pad]
    stats_t = jnp.pad(stats, ((0, n_pad - n), (0, 0))).T    # [S, N_pad]

    grid = (c_pad // cblk, n_pad // nblk)
    out = pl.pallas_call(
        partial(_hist_kernel, n_stats=s, n_nodes=n_nodes, b_pad=b_pad,
                nblk=nblk, cblk=cblk, pair=pair, exact=exact),
        grid=grid,
        in_specs=[
            pl.BlockSpec((cblk, nblk), lambda ci, r: (ci, r)),
            pl.BlockSpec((1, nblk), lambda ci, r: (0, r)),
            pl.BlockSpec((s, nblk), lambda ci, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((cblk, s, n_nodes, b_pad),
                               lambda ci, r: (ci, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, s, n_nodes, b_pad),
                                       jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bins_t, node_t, stats_t)
    # [C_pad, S, K, B_pad] -> [K, C, B, S]
    return out[:c, :, :, :n_bins].transpose(2, 0, 3, 1)


def _batch_vmem_bytes(tb: int, s: int, n_nodes: int, b_pad: int,
                      nblk: int, cblk: int, exact: bool) -> int:
    """Rough VMEM footprint of one batched grid cell: output block +
    per-(tree, group) dot operands (the hi/lo split doubles them) +
    double-buffered input blocks."""
    out = cblk * tb * s * n_nodes * b_pad * 4
    n_groups = (s + 1) // 2
    opnd = tb * n_groups * min(2, s) * n_nodes * nblk * 2
    if not exact:
        opnd *= 2
    inputs = 2 * nblk * (cblk * 4 + tb * 4 + tb * s * 4)
    return out + opnd + inputs


_BATCH_VMEM_BUDGET = 10 << 20     # leave headroom under the ~16 MB scope


@partial(jax.jit, static_argnames=("n_nodes", "n_bins", "interpret",
                                   "exact"))
def build_histograms_pallas_batch(bins, node_idx_b, stats_b, n_nodes: int,
                                  n_bins: int, interpret: bool = False,
                                  exact: bool = False):
    """Batched drop-in for :func:`build_histograms_pallas` over a leading
    TREE axis: B independent trees' level histograms in ONE launch.

    bins: [N, C] shared row matrix; node_idx_b: [TB, N] per-tree level-local
    positions (-1 = inactive); stats_b: [TB, N, S] per-tree stat channels.
    Returns [TB, n_nodes, C, n_bins, S] float32.

    Every per-tree parameter that shapes the accumulation order (nblk row
    blocking, K_MAX node partitioning, channel pairing, bf16 hi/lo split)
    matches the single-tree kernel exactly, so each tree's slice is
    BIT-identical to a sequential :func:`build_histograms_pallas` call —
    only the dispatch count changes (1 launch instead of TB, with the bins
    one-hot built once per grid cell instead of TB times).  Tree batches
    that would overflow the VMEM scope split transparently.
    """
    bins = bins.astype(jnp.int32)
    tb, n = node_idx_b.shape
    s = stats_b.shape[2]
    if n_nodes > K_MAX:             # deep levels: same node partitioning
        parts = []                  # as the single-tree path
        for k0 in range(0, n_nodes, K_MAX):
            parts.append(build_histograms_pallas_batch(
                bins, node_idx_b - k0, stats_b, min(K_MAX, n_nodes - k0),
                n_bins, interpret, exact))
        return jnp.concatenate(parts, axis=1)
    c = bins.shape[1]
    pair = n_bins <= LANE // 2
    b_pad = LANE // 2 if pair else ((n_bins + LANE - 1) // LANE) * LANE
    cblk = 8
    c_pad = ((c + cblk - 1) // cblk) * cblk
    # nblk MUST be the single-tree formula for the given node count — the
    # row-block accumulation order is what makes batched == sequential
    # bit-identical
    nblk = int(os.environ.get("SHIFU_HIST_NBLK", 0)) or \
        (16384 if n_nodes <= 16 else 8192 if n_nodes <= 32 else 2048)
    while tb > 1 and _batch_vmem_bytes(tb, s, n_nodes, b_pad, nblk, cblk,
                                       exact) > _BATCH_VMEM_BUDGET:
        # split the tree batch, not the row block: nblk is pinned by the
        # bit-identity contract above
        half_tb = tb // 2
        return jnp.concatenate([
            build_histograms_pallas_batch(
                bins, node_idx_b[:half_tb], stats_b[:half_tb], n_nodes,
                n_bins, interpret, exact),
            build_histograms_pallas_batch(
                bins, node_idx_b[half_tb:], stats_b[half_tb:], n_nodes,
                n_bins, interpret, exact)], axis=0)
    n_pad = ((n + nblk - 1) // nblk) * nblk

    bins_t = jnp.pad(bins, ((0, n_pad - n), (0, c_pad - c))).T  # [C_pad, N_pad]
    node_t = jnp.pad(node_idx_b, ((0, 0), (0, n_pad - n)),
                     constant_values=-1)                  # [TB, N_pad]
    stats_t = jnp.pad(stats_b, ((0, 0), (0, n_pad - n), (0, 0))) \
        .transpose(0, 2, 1).reshape(tb * s, n_pad)        # [TB*S, N_pad]

    grid = (c_pad // cblk, n_pad // nblk)
    out = pl.pallas_call(
        partial(_hist_kernel_batch, n_stats=s, n_trees=tb, n_nodes=n_nodes,
                b_pad=b_pad, nblk=nblk, cblk=cblk, pair=pair, exact=exact),
        grid=grid,
        in_specs=[
            pl.BlockSpec((cblk, nblk), lambda ci, r: (ci, r)),
            pl.BlockSpec((tb, nblk), lambda ci, r: (0, r)),
            pl.BlockSpec((tb * s, nblk), lambda ci, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((cblk, tb, s, n_nodes, b_pad),
                               lambda ci, r: (ci, 0, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, tb, s, n_nodes, b_pad),
                                       jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(bins_t, node_t, stats_t)
    # [C_pad, TB, S, K, B_pad] -> [TB, K, C, B, S]
    return out[:c, :, :, :, :n_bins].transpose(1, 3, 0, 4, 2)


def build_histograms_batch_sharded(bins, node_idx_b, stats_b, n_nodes: int,
                                   n_bins: int, mesh,
                                   interpret: bool = False,
                                   exact: bool = False):
    """Mesh lowering of the batched kernel (see
    :func:`build_histograms_sharded`): rows shard over ``data``, the tree
    axis replicates, one psum merges the per-device tree-batch grids."""
    from jax.sharding import PartitionSpec as P

    def local(b, ni, st):
        h = build_histograms_pallas_batch(b, ni, st, n_nodes, n_bins,
                                          interpret, exact)
        return jax.lax.psum(h, "data")

    return _shard_map(
        local, mesh,
        in_specs=(P("data", None), P(None, "data"), P(None, "data", None)),
        out_specs=P())(bins, node_idx_b, stats_b)


def build_histograms_sharded(bins, node_idx, stats, n_nodes: int,
                             n_bins: int, mesh, interpret: bool = False,
                             exact: bool = False):
    """Mesh lowering of the kernel: ``shard_map`` over the ``data`` axis.

    A ``pallas_call`` is opaque to the GSPMD partitioner, so under a
    multi-device mesh the kernel must be placed per-shard explicitly: each
    device builds the histogram of its local rows (the ``DTWorker`` side),
    then a ``psum`` over the data axis merges them on ICI (the
    ``DTMaster.java:274-533`` aggregation).  Inputs must already be sharded
    row-wise over ``data`` (the trainers' `_device_put_rows` layout); axes
    the specs don't mention (``ensemble``) stay replicated.

    ``check_vma=False``: the replication checker can't see through the
    kernel, but the output IS replicated — inputs are replicated over
    every non-data axis and the psum makes it data-invariant.
    """
    from jax.sharding import PartitionSpec as P

    def local(b, ni, st):
        h = build_histograms_pallas(b, ni, st, n_nodes, n_bins, interpret,
                                    exact)
        return jax.lax.psum(h, "data")

    return _shard_map(
        local, mesh,
        in_specs=(P("data", None), P("data"), P("data", None)),
        out_specs=P())(bins, node_idx, stats)


def target_platform(mesh=None) -> str:
    """The platform the histogram will actually run on: the mesh's devices
    when one is given (a CPU mesh on a TPU-backed host must NOT get the
    Mosaic lowering), the default backend otherwise."""
    if mesh is not None:
        return mesh.devices.flat[0].platform
    try:
        return jax.default_backend()
    except Exception:                                      # pragma: no cover
        return "cpu"


def pallas_available(mesh=None) -> bool:
    """Histogram kernel dispatch gate: runs on a real TPU and not disabled.
    ``SHIFU_HIST_PALLAS=force`` enables it on any platform (tests exercise
    the kernel + shard_map wiring in interpret mode on the CPU mesh)."""
    env = os.environ.get("SHIFU_HIST_PALLAS", "1")
    if env == "0":
        return False
    if env == "force":
        return True
    return target_platform(mesh) == "tpu"


# ---------------------------------------------------- wide-B stats kernel
def _stats_hist_kernel(idx_ref, stats_ref, out_ref, *, n_stats: int,
                      hi_n: int, nblk: int, cblk: int,
                      exact: tuple):
    """Fine-histogram build for the STATS plane (wide bucket axis).

    The tree kernel's one-hot trick is linear in the bucket count (one
    128-lane compare tile per 128 buckets), which is fine at B<=256 but
    hopeless at the stats plane's 4096 fine buckets.  Wide histograms
    factor instead: bucket id = hi*64 + lo, and

        out[c, s, hi, lo] = sum_n [hi(n)==hi] * stats(n,s) * [lo(n)==lo]

    is a ``dot_general`` per (column, stat-pair) — B-independent MXU work
    (the reference accumulates the same cells one row at a time in
    ``UpdateBinningInfoMapper.java:71``'s combiner).  Invalid cells
    arrive as idx -1: the arithmetic shift keeps hi == -1, which matches
    no one-hot row.

    Two MXU economies over the naive per-channel hi/lo-split loop
    (measured 5.5x at bench shapes together):

    * channel pairs pack along the sublane axis — rows 0-63 of the
      [128, nblk] left operand carry channel s's hi-one-hot, rows 64-127
      channel s+1's, so one dot feeds the whole 128-row MXU tile instead
      of two half-empty ones;
    * ``exact[s]`` marks channels whose values are bf16-exact (0/1
      indicators — the pos/neg count channels): the product
      one-hot * stats is then exactly representable and the f32-recovery
      lo dot (see :func:`_bf16_split`) is skipped entirely.  Weighted
      channels keep the split (weights are arbitrary f32 and feed
      KS/IV/WOE).
    """
    r = pl.program_id(1)

    @pl.when(r == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    lane_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE // 2, nblk), 0)
    pack_iota = jax.lax.broadcasted_iota(jnp.int32, (LANE, nblk), 0) % (LANE // 2)
    dims = (((1,), (1,)), ((), ()))
    for cf in range(cblk):
        col = idx_ref[cf:cf + 1, :]                       # [1, nblk] int32
        hi = col >> 6                                     # -1 stays -1
        lo = col & 63
        lo1h = (lane_iota == lo).astype(jnp.bfloat16)     # [64, nblk]
        s = 0
        while s < n_stats:
            if s + 1 < n_stats:
                # packed pair: [128, nblk] left operand, one (or two) dots
                hi2 = (pack_iota == jnp.broadcast_to(hi, (LANE, nblk))) \
                    .astype(jnp.float32)
                st = jnp.concatenate([
                    jnp.broadcast_to(stats_ref[s:s + 1, :],
                                     (LANE // 2, nblk)),
                    jnp.broadcast_to(stats_ref[s + 1:s + 2, :],
                                     (LANE // 2, nblk))], axis=0)
                a = hi2 * st                              # [128, nblk] f32
                if exact[s] and exact[s + 1]:
                    acc = jax.lax.dot_general(
                        a.astype(jnp.bfloat16), lo1h, dims,
                        preferred_element_type=jnp.float32)  # [128, 64]
                else:
                    hi_b, lo_b = _bf16_split(a)
                    acc = jax.lax.dot_general(
                        hi_b, lo1h, dims,
                        preferred_element_type=jnp.float32)
                    acc += jax.lax.dot_general(
                        lo_b, lo1h, dims,
                        preferred_element_type=jnp.float32)
                out_ref[cf, s, :, :] += acc[:hi_n, :]
                out_ref[cf, s + 1, :, :] += \
                    acc[LANE // 2:LANE // 2 + hi_n, :]
                s += 2
                continue
            hi1h = (lane_iota == hi).astype(jnp.float32)  # [64, nblk]
            a = hi1h * stats_ref[s:s + 1, :]              # [64, nblk] f32
            if exact[s]:
                acc = jax.lax.dot_general(
                    a.astype(jnp.bfloat16), lo1h, dims,
                    preferred_element_type=jnp.float32)   # [64, 64]
            else:
                hi_b, lo_b = _bf16_split(a)
                acc = jax.lax.dot_general(
                    hi_b, lo1h, dims,
                    preferred_element_type=jnp.float32)
                acc += jax.lax.dot_general(
                    lo_b, lo1h, dims,
                    preferred_element_type=jnp.float32)
            out_ref[cf, s, :, :] += acc[:hi_n, :]
            s += 1


@partial(jax.jit, static_argnames=("num_buckets", "interpret", "exact"))
def stats_histograms_pallas(idx, stats, num_buckets: int,
                            interpret: bool = False,
                            exact: tuple = None):
    """[C, num_buckets, S] fine-histogram from per-cell bucket ids.

    idx: [N, C] int32, -1 = invalid cell (missing value — contributes
    nowhere); stats: [N, S] float32 per-row channels (pos/neg indicators,
    weighted variants).  ``num_buckets`` must be a multiple of 64 and at
    most 4096 (the stats plane's fine-sketch width).  ``exact[s]`` marks
    channels whose values are exactly representable in bfloat16 (0/1
    indicators) — those skip the f32-recovery second dot.
    """
    assert num_buckets % 64 == 0 and num_buckets <= 4096, num_buckets
    if exact is None:
        exact = (False,) * stats.shape[1]
    n, c = idx.shape
    s = stats.shape[1]
    hi_n = num_buckets // 64
    cblk = 8
    c_pad = ((c + cblk - 1) // cblk) * cblk
    nblk = 2048
    n_pad = ((n + nblk - 1) // nblk) * nblk
    idx_t = jnp.pad(idx, ((0, n_pad - n), (0, c_pad - c)),
                    constant_values=-1).T                 # [C_pad, N_pad]
    stats_t = jnp.pad(stats, ((0, n_pad - n), (0, 0))).T  # [S, N_pad]
    grid = (c_pad // cblk, n_pad // nblk)
    out = pl.pallas_call(
        partial(_stats_hist_kernel, n_stats=s, hi_n=hi_n, nblk=nblk,
                cblk=cblk, exact=tuple(exact)),
        grid=grid,
        in_specs=[
            pl.BlockSpec((cblk, nblk), lambda ci, r: (ci, r)),
            pl.BlockSpec((s, nblk), lambda ci, r: (0, r)),
        ],
        out_specs=pl.BlockSpec((cblk, s, hi_n, 64),
                               lambda ci, r: (ci, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((c_pad, s, hi_n, 64), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(idx_t, stats_t)
    # [C_pad, S, HI, 64] -> [C, HI*64, S]
    return out[:c].reshape(c, s, hi_n * 64).transpose(0, 2, 1)


def stats_histograms_sharded(idx, stats, num_buckets: int, mesh,
                             interpret: bool = False, exact: tuple = None):
    """Mesh lowering of the stats fine-histogram: ``shard_map`` over the
    ``data`` axis (see :func:`build_histograms_sharded` — the pallas_call
    is opaque to GSPMD, so each device sketches its local rows and a
    ``psum`` merges on ICI; the reference's up-to-999 stats reducers,
    ``MapReducerStatsWorker.java:111-139``).  Rows must already be sharded
    over ``data`` and divide the axis (the accumulator pads)."""
    from jax.sharding import PartitionSpec as P

    def local(i, st):
        h = stats_histograms_pallas(i, st, num_buckets, interpret, exact)
        return jax.lax.psum(h, "data")

    return _shard_map(
        local, mesh, in_specs=(P("data", None), P("data", None)),
        out_specs=P())(idx, stats)
