"""Device-side streaming binning + per-column stats accumulation.

TPU-native replacement for the reference's stats data path (SURVEY.md §3.2):
the SPDT/MunroPat streaming-sketch binning (``core/binning/``) plus the
``UpdateBinningInfo`` MR second pass become two SPMD passes over columnar
chunks:

  pass 1 (moments): per-column count/min/max + centered moments M2..M4
          (Chan et al. pairwise combine, so f32 device sums stay accurate),
  pass 2 (sketch):  a fine equal-width histogram per column (pos/neg counts
          and weighted counts via one scatter-add ``segment_sum``).

Bin boundaries for every binning method (EqualPositive/Total/Negative/
Interval + weighted variants, ``ModelStatsConf.java:34-35``) are read off the
fine histogram's cumulative sums; final per-bin pos/neg counts are exact
segment-sums of fine buckets (boundaries always land on fine-bucket edges).
Categorical bins are exact dict aggregations.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import BinningMethod
from ..obs import costs as obs_costs

# merged-category group separator (reference uses \u0001 in CategoricalBinInfo)
CATEGORY_GROUP_SEP = "\x01"

NEG_INF = float("-inf")


# ----------------------------------------------------------------- kernels
# Stats-plane executables are cost-attributed (obs/costs) so the
# utilization report can say whether the fused sweep is compute- or
# bandwidth-bound; ``lazy=True`` because these wrap at module import,
# before the CLI's --telemetry flips the telemetry switch.
@obs_costs.costed_jit("stats.moments_kernel", lazy=True)
def _moments_kernel(x: jnp.ndarray, valid: jnp.ndarray):
    """Per-column count/sum/min/max + centered M2/M3/M4 for one chunk.

    x: [R, C] float32 with arbitrary values where invalid; valid: [R, C] bool.
    Centering by the chunk mean keeps f32 power sums small enough for TPU.
    """
    v = valid.astype(x.dtype)
    cnt = v.sum(axis=0)
    safe_cnt = jnp.maximum(cnt, 1.0)
    xv = jnp.where(valid, x, 0.0)
    s1 = xv.sum(axis=0)
    mean = s1 / safe_cnt
    d = jnp.where(valid, x - mean, 0.0)
    m2 = (d * d).sum(axis=0)
    m3 = (d * d * d).sum(axis=0)
    m4 = (d * d * d * d).sum(axis=0)
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
    mn = jnp.where(valid, x, big).min(axis=0)
    mx = jnp.where(valid, x, -big).max(axis=0)
    return cnt, mean, m2, m3, m4, mn, mx


def _stat_channels(target, weight, unit_weight: bool):
    """Per-row stat channels + their bf16-exactness flags: [pos, neg]
    (0/1 indicators, exact) or [pos, neg, w_pos, w_neg] — the ONE place
    that knows the channel order (histogram and missing-bin aggregation
    must never disagree on it)."""
    R = target.shape[0]
    is_pos = (target >= 0.5)[:, None]
    ones = jnp.ones((R, 1), jnp.float32)
    pos_i = jnp.where(is_pos, ones, 0.0)
    neg_i = jnp.where(is_pos, 0.0, ones)
    if unit_weight:
        return jnp.concatenate([pos_i, neg_i], axis=1), (True, True)
    w = weight[:, None]
    return jnp.concatenate(
        [pos_i, neg_i, jnp.where(is_pos, w, 0.0),
         jnp.where(is_pos, 0.0, w)], axis=1), (True, True, False, False)


@obs_costs.costed_jit("stats.histogram_kernel", lazy=True,
                      static_argnames=("num_buckets", "use_pallas",
                                       "unit_weight", "expand", "mesh"))
def _histogram_kernel(x: jnp.ndarray, valid: jnp.ndarray, target: jnp.ndarray,
                      weight: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                      num_buckets: int, use_pallas: bool = False,
                      unit_weight: bool = False, expand: bool = True,
                      mesh=None):
    """Fine-histogram for one chunk.

    Returns [C, num_buckets, 4]: (#pos, #neg, w_pos, w_neg) per fine bucket.
    Two lowerings, the tree-histogram story replayed for the ETL plane:
    ``use_pallas=True`` → the two-level one-hot MXU kernel
    (:func:`shifu_tpu.ops.hist_pallas.stats_histograms_pallas` — the TPU
    serializes scatter-adds, and at north-star widths the scatter path
    cannot keep up with object-storage IO); default → one flattened
    ``segment_sum``, the reference's per-(column,bin) reducer accumulation.

    ``unit_weight=True`` (no weight column configured — the common case)
    computes only the two 0/1 count channels and mirrors them into the
    weighted slots: half the accumulation work, and both channels are
    bf16-exact so the MXU path runs a single dot per column pair.
    ``expand=False`` skips the mirroring and returns the raw [C, B, 2] —
    for device-side accumulators whose drain pays link bandwidth per
    channel (the host expands after the fetch).
    """
    R, C = x.shape
    scale = num_buckets / jnp.maximum(hi - lo, 1e-30)
    idx = jnp.clip(((x - lo) * scale), 0, num_buckets - 1).astype(jnp.int32)
    vals, exact = _stat_channels(target, weight, unit_weight)
    if use_pallas:
        from .hist_pallas import (stats_histograms_pallas,
                                  stats_histograms_sharded, target_platform)
        cidx = jnp.where(valid, idx, -1)     # invalid cell -> matches no bin
        interp = target_platform(mesh) != "tpu"
        if mesh is not None and mesh.size > 1:
            h = stats_histograms_sharded(cidx, vals, num_buckets, mesh,
                                         interpret=interp, exact=exact)
        else:
            h = stats_histograms_pallas(cidx, vals, num_buckets,
                                        interpret=interp, exact=exact)
    else:
        S = vals.shape[1]
        flat = idx + jnp.arange(C, dtype=jnp.int32) * num_buckets
        flat = jnp.where(valid, flat, C * num_buckets)  # overflow slot
        data = jnp.broadcast_to(vals[:, None, :], (R, C, S)).reshape(R * C, S)
        seg = jax.ops.segment_sum(data, flat.reshape(-1),
                                  num_segments=C * num_buckets + 1)
        h = seg[:-1].reshape(C, num_buckets, S)
    if unit_weight and expand:               # w_pos = #pos, w_neg = #neg
        h = jnp.concatenate([h, h], axis=2)
    return h


# ------------------------------------------------------- moment combination
def _combine_moments(a: dict, b: Tuple[np.ndarray, ...]) -> dict:
    """Chan et al. pairwise combination of (count, mean, M2, M3, M4)."""
    cb, mb, M2b, M3b, M4b, mnb, mxb = [np.asarray(t, np.float64) for t in b]
    if not a:
        return {"count": cb, "mean": mb, "M2": M2b, "M3": M3b, "M4": M4b,
                "min": mnb, "max": mxb}
    ca, ma, M2a, M3a, M4a = a["count"], a["mean"], a["M2"], a["M3"], a["M4"]
    n = ca + cb
    safe_n = np.maximum(n, 1.0)
    delta = mb - ma
    mean = ma + delta * cb / safe_n
    M2 = M2a + M2b + delta ** 2 * ca * cb / safe_n
    M3 = (M3a + M3b + delta ** 3 * ca * cb * (ca - cb) / safe_n ** 2
          + 3 * delta * (ca * M2b - cb * M2a) / safe_n)
    M4 = (M4a + M4b
          + delta ** 4 * ca * cb * (ca ** 2 - ca * cb + cb ** 2) / safe_n ** 3
          + 6 * delta ** 2 * (ca ** 2 * M2b + cb ** 2 * M2a) / safe_n ** 2
          + 4 * delta * (ca * M3b - cb * M3a) / safe_n)
    return {"count": n, "mean": np.where(n > 0, mean, 0.0), "M2": M2, "M3": M3,
            "M4": M4, "min": np.minimum(a["min"], mnb),
            "max": np.maximum(a["max"], mxb)}


@obs_costs.costed_jit("stats.missing_agg", lazy=True,
                      static_argnames=("unit_weight", "expand"))
def _missing_agg_kernel(valid, target, weight, live=None,
                        unit_weight: bool = False, expand: bool = True):
    """[C, 4] (pos/neg/w_pos/w_neg) sums over INVALID cells — the
    missing-bin aggregation as one device matmul instead of four host
    passes over the [R, C] mask.  HIGHEST precision keeps f32-faithful
    accumulation (counts are exact integers below 2^24; the bounded
    drain in :class:`NumericAccumulator` keeps them there).

    ``live`` [R] bool marks real rows: mesh-sharded chunks pad rows to
    the data-axis extent, and a padded all-invalid row must NOT count as
    missing (every other kernel drops invalid cells on its own)."""
    inval = (~valid).astype(jnp.float32)               # [R, C]
    if live is not None:
        inval = inval * live.astype(jnp.float32)[:, None]
    vals, _ = _stat_channels(target, weight, unit_weight)
    magg = jax.lax.dot_general(inval, vals, (((0,), (0,)), ((), ())),
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)  # [C, S]
    if unit_weight and expand:
        magg = jnp.concatenate([magg, magg], axis=1)
    return magg


def _method_weight_col(hist, method_value: str, nch: int):
    """[C, K] per-fine-bucket weight measure for a binning method (the
    channel mix ``compute_boundaries`` reads off the histogram)."""
    pos, neg = hist[..., 0], hist[..., 1]
    wpos = hist[..., 2] if nch == 4 else pos
    wneg = hist[..., 3] if nch == 4 else neg
    return {
        "EqualPositive": pos,
        "EqualNegtive": neg,
        "WeightEqualTotal": wpos + wneg,
        "WeightEqualPositive": wpos,
        "WeightEqualNegative": wneg,
    }.get(method_value, pos + neg)


@obs_costs.costed_jit("stats.refine_prov", lazy=True,
                      static_argnames=("num_buckets",))
def _refine_prov_kernel(prov, plo, phi, lo, hi, num_buckets: int):
    """Re-bin a PROVISIONAL-grid fine histogram onto the exact final grid,
    on device (the fused one-pass sweep's refinement step — see
    :class:`shifu_tpu.ops.sketches.RangeSketch`).

    Each provisional bucket lands whole in the final bucket its center
    falls in: counts are conserved exactly; placement error is bounded by
    one provisional bucket width ((phi-plo)/K — with the sketch margin,
    ~1.5/K of the value range, far inside the fine-sketch resolution the
    boundaries are read at anyway)."""
    kk = jnp.arange(num_buckets, dtype=jnp.float32)
    centers = plo[:, None] + (phi - plo)[:, None] * \
        (kk[None, :] + 0.5) / num_buckets                     # [C, K]
    scale = num_buckets / jnp.maximum(hi - lo, 1e-30)
    idx = jnp.clip((centers - lo[:, None]) * scale[:, None],
                   0, num_buckets - 1).astype(jnp.int32)      # [C, K]
    return jax.vmap(
        lambda p, i: jax.ops.segment_sum(p, i,
                                         num_segments=num_buckets))(
        prov, idx)


@functools.partial(jax.jit, static_argnames=("method_value", "max_bins",
                                             "num_buckets", "nch",
                                             "interval"))
def _finalize_sketch_kernel(hist, magg, lo, hi, method_value: str,
                            max_bins: int, num_buckets: int, nch: int,
                            interval: bool = False):
    """The whole sketch→ColumnStats reduction ON DEVICE, one packed fetch.

    Replaces the host path (drain the [C, 4096, ch] fine histogram —
    8-16 MB over a ~35 MB/s link — then per-column numpy cumsums) with
    device math whose output is only [C, max_bins]-sized.  The
    fine-bucket→final-bin reduction needs no scatter: boundaries are
    nondecreasing, so each final bin is a contiguous fine-bucket range
    and per-bin sums are differences of the channel cumsum gathered at
    the range ends (the ``UpdateBinningInfoReducer.java:57`` aggregation,
    reformulated prefix-sum style).

    Returns (boundaries [C, max_bins] incl. leading -inf and possible
    duplicates — the host dedupes; agg [C, max_bins+1, nch] aligned to
    the UNdeduped boundaries, missing bin last; pct [C, 3]; distinct [C];
    totals [C] of the method measure — zero-total columns fall back to
    the reference's single-bin shape host-side).
    """
    C = hist.shape[0]
    weight_col = _method_weight_col(hist, method_value, nch)     # [C, K]
    edges = lo[:, None] + (hi - lo)[:, None] * \
        jnp.arange(num_buckets + 1, dtype=jnp.float32) / num_buckets
    cum = jnp.cumsum(weight_col, axis=1)                         # [C, K]
    total = cum[:, -1]                                           # [C]
    frac = jnp.arange(1, max_bins, dtype=jnp.float32) / max_bins
    if interval:                                 # EqualInterval: width, not
        bnd = lo[:, None] + (hi - lo)[:, None] * frac      # population
    else:
        targets = total[:, None] * frac                          # [C, B-1]
        pos = jax.vmap(lambda c, t: jnp.searchsorted(c, t, side="left"))(
            cum, targets)                                        # [C, B-1]
        bnd = jnp.take_along_axis(edges, pos + 1, axis=1)        # [C, B-1]
    bnd_full = jnp.concatenate(
        [jnp.full((C, 1), NEG_INF, jnp.float32), bnd], axis=1)   # [C, B]
    # fine bucket k belongs to final bin searchsorted(bnd, edge_k, right)-1;
    # the assignment is nondecreasing in k, so bin b covers fine buckets
    # [hi_idx[b-1], hi_idx[b]) where hi_idx[b] = #buckets assigned <= b
    bucket_bin = jnp.clip(
        jax.vmap(lambda b, e: jnp.searchsorted(b, e, side="right"))(
            bnd_full, edges[:, :-1]) - 1, 0, max_bins - 1)       # [C, K]
    bins_iota = jnp.arange(max_bins)
    # per-bin sums via a masked reduction rather than cumsum differences:
    # large-minus-large f32 prefixes put ~1e-5 x TOTAL error on every bin;
    # direct per-bin summation keeps the error proportional to the bin
    onehot = (bucket_bin[:, :, None] == bins_iota[None, None, :]) \
        .astype(hist.dtype)                                      # [C, K, B]
    agg_bins = jnp.einsum('ckb,cks->cbs', onehot, hist,
                          precision=jax.lax.Precision.HIGHEST)
    agg = jnp.concatenate([agg_bins, magg[:, None, :]], axis=1)  # [C,B+1,ch]
    # percentiles (count measure) to fine-bucket resolution; the count
    # cumsum is exact (integer sums below 2^24)
    cnt_cum = jnp.cumsum(hist[..., 0] + hist[..., 1], axis=1)    # [C, K]
    q = jnp.asarray([0.25, 0.5, 0.75], jnp.float32)
    qpos = jax.vmap(lambda c, t: jnp.searchsorted(c, t, side="left"))(
        cnt_cum, cnt_cum[:, -1:] * q[None, :])
    pct = jnp.take_along_axis(
        edges, jnp.minimum(qpos + 1, num_buckets), axis=1)       # [C, 3]
    distinct = (hist.sum(axis=2) > 0).sum(axis=1)                # [C]
    return jnp.concatenate([
        bnd_full.reshape(-1), agg.reshape(-1), pct.reshape(-1),
        distinct.astype(jnp.float32), total])


# ------------------------------------------------------------- accumulators
@dataclass
class NumericAccumulator:
    """Streaming accumulator over numeric columns (both passes).

    Device-side accumulation: per-chunk kernel outputs stay in HBM and
    drain to host float64 in ONE packed fetch per pass (or per ~8M-row
    super-chunk, which keeps f32 bucket counts integer-exact).  A host
    fetch over a remote-device link is a full round trip — measured
    ~98 ms on the dev tunnel — so the round-3 per-chunk ``np.asarray``
    serialized the whole stats plane behind the link latency."""
    n_cols: int
    num_buckets: int = 4096
    unit_weight: bool = False       # no weight column: w channels mirror counts
    # (ensemble, data) mesh: chunk rows shard over the data axis and the
    # per-chunk reductions psum on ICI — the reference's up-to-999 stats
    # reducers (``MapReducerStatsWorker.java:111-139``); None or a 1-device
    # mesh keeps the single-chip layout
    mesh: Optional[object] = None
    moments: dict = field(default_factory=dict)
    total_rows: int = 0
    missing: Optional[np.ndarray] = None
    hist: Optional[np.ndarray] = None          # [C, K, 4] float64
    missing_agg: Optional[np.ndarray] = None   # [C, 4] pos/neg/wpos/wneg of missing
    lo: Optional[np.ndarray] = None
    hi: Optional[np.ndarray] = None
    # exact mode (MunroPat): keep per-column (valid values, pos flag,
    # weight) so boundaries land on TRUE quantiles instead of sketch-bucket
    # edges (reference ``core/binning/MunroPatBinning.java:29`` materializes
    # the column sample the same way).  Memory is O(valid values) — the
    # exact path is for LOCAL-scale runs; the sketch remains the default.
    exact: bool = False
    _exact_cols: Optional[list] = None     # [C] lists of (vals, pos, w)
    _pend_moments: list = field(default_factory=list)  # [7, C] device chunks
    _pend_moment_rows: int = 0
    _hist_dev: Optional[object] = None     # [C, K, 4] f32 on device
    _magg_dev: Optional[object] = None     # [C, 4] f32 on device
    _pend_hist_rows: int = 0
    _lo_d: Optional[object] = None
    _hi_d: Optional[object] = None
    # fused one-pass sweep state (update_fused/finalize_fused): chunks
    # ship H2D ONCE and stay device-resident up to ``fused_budget`` bytes;
    # past it, chunks accumulate into a PROVISIONAL-range histogram that
    # refines onto the exact grid at finalize (ops/sketches.RangeSketch)
    fused_budget: int = 1 << 30
    _fused_chunks: list = field(default_factory=list)
    _fused_bytes: int = 0
    _prov_hist_dev: Optional[object] = None
    _prov_magg_dev: Optional[object] = None
    _prov_lo_d: Optional[object] = None
    _prov_hi_d: Optional[object] = None

    # f32 histogram counts are exact integers up to 2^24; drain to host
    # float64 well before that so TB-scale streams lose nothing
    DRAIN_ROWS = 8_000_000

    def __post_init__(self):
        # the fine-histogram bucket axis must stay MXU-tile-aligned: the
        # two-level one-hot stats kernel factors bucket ids as hi*64+lo
        # (64 sublanes x 64 lanes per dot tile) and caps at 4096 — a
        # misaligned count would silently fall off the kernel path onto
        # the serialized scatter lowering
        if self.num_buckets % 64 != 0 or not \
                (64 <= self.num_buckets <= 4096):
            raise ValueError(
                f"num_buckets={self.num_buckets} is not MXU-tile-aligned: "
                "the stats fine histogram requires a multiple of 64 in "
                "[64, 4096] (ops/hist_pallas.stats_histograms_pallas)")

    def _data_size(self) -> int:
        return int(self.mesh.shape["data"]) if self.mesh is not None else 1

    def _put_rows(self, *arrays):
        """Chunk rows onto the mesh (padded, data-axis sharded) — see
        :func:`shifu_tpu.parallel.mesh.shard_chunk_rows`.  Padded rows are
        all-invalid with weight/target 0."""
        from ..parallel.mesh import shard_chunk_rows
        return shard_chunk_rows(self.mesh, *arrays)

    # ---- pass 1
    def update_moments(self, x: np.ndarray, valid: np.ndarray) -> None:
        if self._data_size() <= 1:
            # jnp.asarray: a device-resident chunk stays put (np.asarray
            # would round-trip it through the host — catastrophic over a
            # remote-device link)
            xd, vd = jnp.asarray(x, jnp.float32), jnp.asarray(valid)
        else:
            xd, vd, _ = self._put_rows(np.asarray(x, np.float32),
                                       np.asarray(valid))
        out = _moments_kernel(xd, vd)
        self._pend_moments.append(jnp.stack(out))      # [7, C], stays on device
        self.total_rows += x.shape[0]
        self._pend_moment_rows += x.shape[0]
        if self._pend_moment_rows >= self.DRAIN_ROWS:  # bound the pending
            self._drain_moments()                      # list and its HBM

    def _drain_moments(self) -> None:
        if not self._pend_moments:
            return
        chunks = np.asarray(jnp.stack(self._pend_moments), np.float64)
        self._pend_moments.clear()
        self._pend_moment_rows = 0
        for m in chunks:                               # Chan combine in f64
            self.moments = _combine_moments(self.moments, tuple(m))
        # invalid cells among processed rows = rows - valid count
        self.missing = self.total_rows - self.moments["count"]

    def finalize_range(self) -> None:
        self._drain_moments()
        mn, mx = self.moments["min"].copy(), self.moments["max"].copy()
        empty = self.moments["count"] == 0
        mn[empty], mx[empty] = 0.0, 1.0
        same = mx <= mn
        mx[same] = mn[same] + 1.0
        self.lo, self.hi = mn, mx
        self._lo_d = jnp.asarray(self.lo, jnp.float32)
        self._hi_d = jnp.asarray(self.hi, jnp.float32)

    # ---- pass 2
    def update_histogram(self, x: np.ndarray, valid: np.ndarray,
                         target: np.ndarray, weight: np.ndarray) -> None:
        assert self.lo is not None, "call finalize_range() after pass 1"
        from .hist_pallas import pallas_available
        up = (pallas_available(self.mesh) and self.num_buckets % 64 == 0
              and self.num_buckets <= 4096)
        if self._data_size() <= 1:     # see update_moments on jnp.asarray
            xd = jnp.asarray(x, jnp.float32)
            vd = jnp.asarray(valid)
            td = jnp.asarray(target, jnp.float32)
            wd = jnp.asarray(weight, jnp.float32)
            live = None
        else:
            xd, vd, td, wd, live = self._put_rows(
                np.asarray(x, np.float32), np.asarray(valid),
                np.asarray(target, np.float32),
                np.asarray(weight, np.float32))
        h = _histogram_kernel(xd, vd, td, wd, self._lo_d, self._hi_d,
                              self.num_buckets, use_pallas=up,
                              unit_weight=self.unit_weight, expand=False,
                              mesh=self.mesh if self._data_size() > 1
                              else None)
        magg = _missing_agg_kernel(vd, td, wd, live,
                                   unit_weight=self.unit_weight,
                                   expand=False)
        self._hist_dev = h if self._hist_dev is None else self._hist_dev + h
        self._magg_dev = (magg if self._magg_dev is None
                          else self._magg_dev + magg)
        self._pend_hist_rows += x.shape[0]
        if self._pend_hist_rows >= self.DRAIN_ROWS:
            self._drain_hist()
        if self.exact:
            if self._exact_cols is None:
                self._exact_cols = [[] for _ in range(self.n_cols)]
            pos_r = np.asarray(target, np.float64) >= 0.5
            w64 = np.asarray(weight, np.float64)
            for c in range(self.n_cols):
                v = valid[:, c]
                self._exact_cols[c].append(
                    (np.asarray(x[v, c], np.float64), pos_r[v], w64[v]))

    # ---- fused one-pass sweep (moments + histogram in ONE disk pass)
    def _kernel_gate(self) -> bool:
        from .hist_pallas import pallas_available
        return bool(pallas_available(self.mesh))

    def update_fused(self, x: np.ndarray, valid: np.ndarray,
                     target: np.ndarray, weight: np.ndarray) -> None:
        """One-pass chunk update: moments accumulate as in pass 1 AND the
        chunk's device arrays are RETAINED (up to ``fused_budget`` bytes)
        so :meth:`finalize_fused` can build the exact-range fine histogram
        without re-reading or re-shipping the chunk — each shard window is
        read, parsed and put H2D ONCE (the two-pass plane paid all three
        twice).  Chunks past the budget accumulate immediately into a
        PROVISIONAL-range histogram (sketch-first boundaries,
        :class:`shifu_tpu.ops.sketches.RangeSketch`) refined on device at
        finalize.  Resident-path results are BIT-identical to the
        two-pass sweep (same kernels, same inputs, same order)."""
        assert not self.exact, \
            "fused sweep serves the sketch path; exact (MunroPat) " \
            "binning keeps the two-pass flow"
        if self._data_size() <= 1:
            xd, vd = jnp.asarray(x, jnp.float32), jnp.asarray(valid)
            td = jnp.asarray(target, jnp.float32)
            wd = jnp.asarray(weight, jnp.float32)
            live = None
        else:
            xd, vd, td, wd, live = self._put_rows(
                np.asarray(x, np.float32), np.asarray(valid),
                np.asarray(target, np.float32),
                np.asarray(weight, np.float32))
        self._pend_moments.append(jnp.stack(_moments_kernel(xd, vd)))
        self.total_rows += x.shape[0]
        self._pend_moment_rows += x.shape[0]
        if self._pend_moment_rows >= self.DRAIN_ROWS:
            self._drain_moments()
        nbytes = x.shape[0] * (5 * self.n_cols + 8)   # f32 x + bool v + t/w
        if self._fused_bytes + nbytes <= self.fused_budget:
            self._fused_chunks.append((xd, vd, td, wd, live, x.shape[0]))
            self._fused_bytes += nbytes
            return
        if self._prov_lo_d is None:
            self._freeze_provisional()     # ONE sync, at first overflow
        h = _histogram_kernel(xd, vd, td, wd, self._prov_lo_d,
                              self._prov_hi_d, self.num_buckets,
                              use_pallas=self._kernel_gate(),
                              unit_weight=self.unit_weight, expand=False,
                              mesh=self.mesh if self._data_size() > 1
                              else None)
        magg = _missing_agg_kernel(vd, td, wd, live,
                                   unit_weight=self.unit_weight,
                                   expand=False)
        self._prov_hist_dev = h if self._prov_hist_dev is None \
            else self._prov_hist_dev + h
        self._prov_magg_dev = magg if self._prov_magg_dev is None \
            else self._prov_magg_dev + magg

    def _freeze_provisional(self) -> None:
        """Freeze the provisional fine-histogram range from the running
        range sketch — drains pending moments (the single host sync the
        overflow path pays, once per job)."""
        from .sketches import RangeSketch
        self._drain_moments()
        rs = RangeSketch(self.n_cols)
        rs.update(self.moments["min"], self.moments["max"])
        plo, phi = rs.provisional_bounds()
        self._prov_lo_d = jnp.asarray(plo, jnp.float32)
        self._prov_hi_d = jnp.asarray(phi, jnp.float32)

    def finalize_fused(self) -> None:
        """Close the fused sweep: exact [lo, hi] from the drained moments,
        then the retained device chunks replay through the histogram
        kernel on the exact grid (zero disk reads, zero H2D) and the
        provisional overflow histogram re-bins onto the exact grid ON
        DEVICE.  Afterwards the accumulator is in the same state pass 2
        would have left — ``finalize_sketch`` / ``compute_boundaries``
        work unchanged."""
        self.finalize_range()
        up = self._kernel_gate()
        for xd, vd, td, wd, live, rows in self._fused_chunks:
            h = _histogram_kernel(xd, vd, td, wd, self._lo_d, self._hi_d,
                                  self.num_buckets, use_pallas=up,
                                  unit_weight=self.unit_weight,
                                  expand=False,
                                  mesh=self.mesh if self._data_size() > 1
                                  else None)
            magg = _missing_agg_kernel(vd, td, wd, live,
                                       unit_weight=self.unit_weight,
                                       expand=False)
            self._hist_dev = h if self._hist_dev is None \
                else self._hist_dev + h
            self._magg_dev = magg if self._magg_dev is None \
                else self._magg_dev + magg
            self._pend_hist_rows += rows
            if self._pend_hist_rows >= self.DRAIN_ROWS:
                self._drain_hist()
        self._fused_chunks.clear()
        self._fused_bytes = 0
        if self._prov_hist_dev is not None:
            refined = _refine_prov_kernel(
                self._prov_hist_dev, self._prov_lo_d, self._prov_hi_d,
                self._lo_d, self._hi_d, self.num_buckets)
            self._hist_dev = refined if self._hist_dev is None \
                else self._hist_dev + refined
            self._magg_dev = self._prov_magg_dev \
                if self._magg_dev is None \
                else self._magg_dev + self._prov_magg_dev
            self._prov_hist_dev = None
            self._prov_magg_dev = None

    # ---- mid-sweep checkpointing (stats-step crash resume)
    def spill_resident(self) -> None:
        """Migrate the device-resident fused chunks into the PROVISIONAL
        histogram (freezing provisional bounds on first use) so the
        fused-sweep state becomes a few host-serializable arrays instead
        of a dataset-sized chunk list.  Afterwards the budget is zeroed:
        every later chunk accumulates provisionally too, which keeps an
        uninterrupted checkpointing run and a crash-resumed one on the
        SAME numeric path (both refine the identical provisional grid at
        finalize)."""
        if self._prov_lo_d is None:
            self._freeze_provisional()
        up = self._kernel_gate()
        for xd, vd, td, wd, live, _rows in self._fused_chunks:
            h = _histogram_kernel(xd, vd, td, wd, self._prov_lo_d,
                                  self._prov_hi_d, self.num_buckets,
                                  use_pallas=up,
                                  unit_weight=self.unit_weight,
                                  expand=False,
                                  mesh=self.mesh if self._data_size() > 1
                                  else None)
            magg = _missing_agg_kernel(vd, td, wd, live,
                                       unit_weight=self.unit_weight,
                                       expand=False)
            self._prov_hist_dev = h if self._prov_hist_dev is None \
                else self._prov_hist_dev + h
            self._prov_magg_dev = magg if self._prov_magg_dev is None \
                else self._prov_magg_dev + magg
        self._fused_chunks.clear()
        self._fused_bytes = 0
        self.fused_budget = 0

    def checkpoint_state(self) -> Dict[str, np.ndarray]:
        """Host-serializable snapshot of the fused-sweep accumulation
        (moments + provisional histogram).  Restoring it and replaying
        the remaining chunks reproduces an uninterrupted checkpointing
        run exactly (f32 provisional counts round-trip bit-identically)."""
        assert not self.exact, "exact (MunroPat) stats do not checkpoint"
        self.spill_resident()
        self._drain_moments()
        out: Dict[str, np.ndarray] = {
            "total_rows": np.asarray(self.total_rows, np.int64)}
        for k, v in self.moments.items():
            out[f"m_{k}"] = np.asarray(v)
        out["prov_lo"] = np.asarray(self._prov_lo_d)
        out["prov_hi"] = np.asarray(self._prov_hi_d)
        if self._prov_hist_dev is not None:
            out["prov_hist"] = np.asarray(self._prov_hist_dev)
            out["prov_magg"] = np.asarray(self._prov_magg_dev)
        return out

    def restore_checkpoint(self, state: Dict[str, np.ndarray]) -> None:
        self.total_rows = int(state["total_rows"])
        self.moments = {k[2:]: np.asarray(state[k], np.float64)
                        for k in state if k.startswith("m_")}
        if "count" in self.moments:
            self.missing = self.total_rows - self.moments["count"]
        self._prov_lo_d = jnp.asarray(state["prov_lo"], jnp.float32)
        self._prov_hi_d = jnp.asarray(state["prov_hi"], jnp.float32)
        if "prov_hist" in state:
            self._prov_hist_dev = jnp.asarray(state["prov_hist"],
                                              jnp.float32)
            self._prov_magg_dev = jnp.asarray(state["prov_magg"],
                                              jnp.float32)
        self._fused_chunks.clear()
        self._fused_bytes = 0
        self.fused_budget = 0          # continue in provisional mode

    def _drain_hist(self) -> None:
        if self._hist_dev is None:
            return
        # ONE packed fetch for both accumulators (two would be two trips;
        # with no weight column only the 2 count channels cross the link —
        # the fetch is bandwidth-priced, ~35 MB/s on the dev tunnel)
        nch = 2 if self.unit_weight else 4
        packed = np.asarray(jnp.concatenate(
            [self._hist_dev.reshape(-1), self._magg_dev.reshape(-1)]),
            np.float64)
        self._hist_dev = None
        self._magg_dev = None
        self._pend_hist_rows = 0
        n_h = self.n_cols * self.num_buckets * nch
        h = packed[:n_h].reshape(self.n_cols, self.num_buckets, nch)
        magg = packed[n_h:].reshape(self.n_cols, nch)
        if self.unit_weight:                 # w_pos = #pos, w_neg = #neg
            h = np.concatenate([h, h], axis=2)
            magg = np.concatenate([magg, magg], axis=1)
        self.hist = h if self.hist is None else self.hist + h
        self.missing_agg = (magg if self.missing_agg is None
                            else self.missing_agg + magg)

    # ---- device-side finalize (the default stats path)
    def finalize_sketch(self, method: BinningMethod, max_bins: int):
        """Boundaries + per-bin stats + percentiles + distinct counts for
        EVERY column in one small packed fetch — the fine histogram never
        crosses the link (the drain path moves 8-16 MB at link bandwidth;
        this moves [C, max_bins]-sized results).

        Returns (boundaries: list of deduped [nb] arrays,
        aggs: list of [nb+1, 4] bin stats incl. trailing missing bin,
        pct: [C, 3] p25/median/p75, distinct: [C] ints) — element-exact
        with ``compute_boundaries`` + ``bin_counts`` + ``percentile`` +
        ``distinct_estimate`` (the parity test pins it)."""
        if self.hist is not None:
            # a mid-pass drain already moved counts to host float64 (>8M
            # rows); re-uploading as f32 would round counts past 2^24 —
            # stay on the exact host path for these TB-scale runs
            self._drain_hist()
            boundaries = self.compute_boundaries(method, max_bins)
            aggs = [self.bin_counts(c, boundaries[c])
                    for c in range(self.n_cols)]
            pct = np.stack([self.percentile(c, [0.25, 0.5, 0.75])
                            for c in range(self.n_cols)])
            distinct = np.array([self.distinct_estimate(c)
                                 for c in range(self.n_cols)])
            return boundaries, aggs, pct, distinct
        nch = 2 if self.unit_weight else 4
        hist_d = self._hist_dev
        magg_d = self._magg_dev
        assert hist_d is not None, "finalize_sketch needs pass-2 data"
        C, B = self.n_cols, max_bins
        interval = method == BinningMethod.EqualInterval
        packed = np.asarray(_finalize_sketch_kernel(
            hist_d, magg_d, self._lo_d, self._hi_d, method.value,
            B, self.num_buckets, nch, interval), np.float64)
        bnd_all, agg_all, pct, distinct, totals = np.split(
            packed, np.cumsum([C * B, C * (B + 1) * nch, C * 3, C]))
        bnd_all = bnd_all.reshape(C, B)
        agg_all = agg_all.reshape(C, B + 1, nch)
        pct = pct.reshape(C, 3)
        # all-missing columns have no percentiles (host path returns NaN,
        # serialized as null — not the empty-range fallback edge value)
        pct[np.asarray(self.moments["count"]) <= 0] = np.nan
        if nch == 2:                  # w_pos/w_neg mirror the counts
            agg_all = np.concatenate([agg_all, agg_all], axis=2)
        boundaries, aggs = [], []
        for c in range(C):
            if totals[c] <= 0 and not interval:
                # reference single-bin shape for a zero-measure column
                boundaries.append(np.array([NEG_INF]))
                agg = np.zeros((2, 4))
                agg[0] = agg_all[c, :B].sum(axis=0)
                agg[1] = agg_all[c, B]
                aggs.append(agg)
                continue
            bnds = bnd_all[c]
            keep = np.ones(B, bool)
            keep[1:] = np.diff(bnds) > 0              # _dedupe semantics
            # undeduped bin j collapses onto the last kept boundary <= j
            dd = np.cumsum(keep) - 1
            nb = int(keep.sum())
            agg = np.zeros((nb + 1, 4))
            np.add.at(agg, dd, agg_all[c, :B])
            agg[nb] = agg_all[c, B]
            boundaries.append(bnds[keep])
            aggs.append(agg)
        return boundaries, aggs, pct, distinct.astype(np.int64)

    # ---- boundary derivation
    def bucket_edges(self, col: int) -> np.ndarray:
        return np.linspace(self.lo[col], self.hi[col], self.num_buckets + 1)

    def compute_boundaries(self, method: BinningMethod, max_bins: int) -> List[np.ndarray]:
        """Per-column bin boundaries; element 0 is -inf like the reference's
        ``binBoundary`` (value v falls in bin i when b[i] <= v < b[i+1])."""
        self._drain_hist()
        assert self.hist is not None
        out = []
        for c in range(self.n_cols):
            h = self.hist[c]  # [K, 4]
            if method == BinningMethod.EqualInterval:
                inner = np.linspace(self.lo[c], self.hi[c], max_bins + 1)[:-1]
                bnds = np.concatenate([[NEG_INF], inner[1:]])
                out.append(_dedupe(bnds))
                continue
            # same channel mix as the device finalize (one mapping)
            weight_col = _method_weight_col(h[None], method.value, 4)[0]
            total = weight_col.sum()
            if total <= 0:
                out.append(np.array([NEG_INF]))
                continue
            cum = np.cumsum(weight_col)
            targets = total * np.arange(1, max_bins) / max_bins
            # first fine-bucket index where cum >= target -> boundary at its right edge
            pos = np.searchsorted(cum, targets, side="left")
            edges = self.bucket_edges(c)
            bnds = np.concatenate([[NEG_INF], edges[pos + 1]])
            out.append(_dedupe(bnds))
        return out

    def _exact_col(self, col: int):
        chunks = self._exact_cols[col]
        return (np.concatenate([c[0] for c in chunks]) if chunks
                else np.empty(0),
                np.concatenate([c[1] for c in chunks]) if chunks
                else np.empty(0, bool),
                np.concatenate([c[2] for c in chunks]) if chunks
                else np.empty(0))

    @staticmethod
    def _measure(method: BinningMethod):
        """Weight measure of one (pos, w) row set for a binning method —
        selected ONCE, not rebuilt per column."""
        return {
            BinningMethod.EqualTotal: lambda p, w: np.ones(len(p)),
            BinningMethod.EqualPositive: lambda p, w: p.astype(np.float64),
            BinningMethod.EqualNegtive: lambda p, w: (~p).astype(np.float64),
            BinningMethod.WeightEqualTotal: lambda p, w: w,
            BinningMethod.WeightEqualPositive: lambda p, w: w * p,
            BinningMethod.WeightEqualNegative: lambda p, w: w * ~p,
        }.get(method, lambda p, w: np.ones(len(p)))

    def compute_boundaries_exact(self, method: BinningMethod,
                                 max_bins: int) -> List[np.ndarray]:
        """Exact equal-frequency boundaries from the materialized values —
        the MunroPat path (reference ``MunroPatBinning.java:29`` exact
        quantiles): boundaries are TRUE data quantiles of the method's
        weight measure, not sketch-bucket edges.  Pair with
        :meth:`bin_counts_exact` — the sketch-based :meth:`bin_counts`
        assumes boundaries on bucket edges and would misassign rows tied
        at a mid-bucket boundary."""
        assert self._exact_cols is not None, \
            "exact boundaries need exact=True collection during pass 2"
        measure = self._measure(method)
        out = []
        for c in range(self.n_cols):
            vals, pos, ws = self._exact_col(c)
            if vals.size == 0:
                out.append(np.array([NEG_INF]))
                continue
            if method == BinningMethod.EqualInterval:
                inner = np.linspace(vals.min(), vals.max(), max_bins + 1)[:-1]
                out.append(_dedupe(np.concatenate([[NEG_INF], inner[1:]])))
                continue
            wrow = measure(pos, ws)
            order = np.argsort(vals, kind="stable")
            sv, sw = vals[order], wrow[order]
            cum = np.cumsum(sw)
            total = cum[-1]
            if total <= 0:
                out.append(np.array([NEG_INF]))
                continue
            targets = total * np.arange(1, max_bins) / max_bins
            pos_idx = np.searchsorted(cum, targets, side="left")
            pos_idx = np.minimum(pos_idx, len(sv) - 1)
            bnds = np.concatenate([[NEG_INF], sv[pos_idx]])
            out.append(_dedupe(bnds))
        return out

    def bin_counts_exact(self, col: int, boundaries: np.ndarray) -> np.ndarray:
        """Per-bin (pos, neg, wpos, wneg) from the EXACT materialized rows,
        with the same assignment rule scoring uses (``ColumnBinner
        .bin_numeric``: b[i] <= v < b[i+1]); trailing missing bin from the
        missing aggregation.  The sketch-based :meth:`bin_counts` is only
        exact when boundaries sit on fine-bucket edges — exact-quantile
        boundaries don't."""
        self._drain_hist()
        vals, pos, ws = self._exact_col(col)
        nb = len(boundaries)
        idx = np.clip(np.searchsorted(boundaries, vals, side="right") - 1,
                      0, nb - 1)
        agg = np.zeros((nb + 1, 4))
        np.add.at(agg, (idx, 0), pos.astype(np.float64))
        np.add.at(agg, (idx, 1), (~pos).astype(np.float64))
        np.add.at(agg, (idx, 2), ws * pos)
        np.add.at(agg, (idx, 3), ws * ~pos)
        if self.missing_agg is not None:
            agg[nb] = self.missing_agg[col]
        return agg

    def bin_counts(self, col: int, boundaries: np.ndarray) -> np.ndarray:
        """Exact per-bin (pos, neg, wpos, wneg) counts incl. trailing missing
        bin, derived by segment-summing fine buckets."""
        self._drain_hist()
        edges = self.bucket_edges(col)
        # fine bucket k covers [edges[k], edges[k+1]); assign to final bin
        bucket_bin = np.searchsorted(boundaries, edges[:-1], side="right") - 1
        bucket_bin = np.clip(bucket_bin, 0, len(boundaries) - 1)
        n_bins = len(boundaries)
        agg = np.zeros((n_bins + 1, 4))
        np.add.at(agg, bucket_bin, self.hist[col])
        if self.missing_agg is not None:
            agg[n_bins] = self.missing_agg[col]
        return agg

    def percentile(self, col: int, q: Sequence[float]) -> np.ndarray:
        """Approximate percentiles (to fine-bucket resolution) from the sketch."""
        self._drain_hist()
        h = self.hist[col][:, 0] + self.hist[col][:, 1]
        total = h.sum()
        if total <= 0:
            return np.full(len(q), np.nan)
        cum = np.cumsum(h)
        edges = self.bucket_edges(col)
        pos = np.searchsorted(cum, np.asarray(q) * total, side="left")
        return edges[np.minimum(pos + 1, self.num_buckets)]

    def distinct_estimate(self, col: int) -> int:
        """Lower-bound distinct estimate = occupied fine buckets (the
        reference uses HyperLogLog; this is the sketch-native analogue)."""
        self._drain_hist()
        return int((self.hist[col].sum(axis=1) > 0).sum())


def _dedupe(bnds: np.ndarray) -> np.ndarray:
    keep = np.ones(len(bnds), dtype=bool)
    keep[1:] = np.diff(bnds) > 0
    return bnds[keep]


@dataclass
class CategoricalAccumulator:
    """Exact per-category pos/neg/weight aggregation (dict-based, streamed)."""
    stats: Dict[str, Dict[str, np.ndarray]] = field(default_factory=dict)

    def update(self, col_name: str, values: np.ndarray, valid: np.ndarray,
               target: np.ndarray, weight: np.ndarray,
               stripped: bool = False) -> None:
        """``values`` may be pre-stripped (``stripped=True`` skips the
        string pass).  One factorize + four weighted bincounts per chunk —
        the per-chunk DataFrame/groupby this replaces was the host
        bottleneck on categorical-heavy (fraud-style) datasets
        (reference reducers are column-parallel,
        ``MapReducerStatsWorker.java:111-139``)."""
        import pandas as pd
        d = self.stats.setdefault(col_name, {})
        is_pos = target >= 0.5
        if not stripped:
            values = pd.Series(values, dtype=str).str.strip().to_numpy()
        codes, cats = pd.factorize(values)           # C hash table
        k = len(cats)
        # factorize codes NaN/None as -1; route them (and invalid rows) to
        # the missing slot rather than letting bincount see a negative
        idx = np.where(valid & (codes >= 0), codes, k)
        posf = is_pos.astype(np.float64)
        w = np.asarray(weight, np.float64)
        stacked = np.stack([
            np.bincount(idx, weights=posf, minlength=k + 1),
            np.bincount(idx, weights=1.0 - posf, minlength=k + 1),
            np.bincount(idx, weights=w * posf, minlength=k + 1),
            np.bincount(idx, weights=w * (1.0 - posf), minlength=k + 1)],
            axis=1)                                  # [k+1, 4]
        for i, cat in enumerate(cats):
            row = stacked[i]
            if not row.any():          # a missing-marker string: all rows
                continue               # of this category were invalid
            prev = d.get(cat)
            d[cat] = row if prev is None else prev + row
        m = stacked[k]
        if m.any():
            prev = d.get(_MISSING_KEY)
            d[_MISSING_KEY] = m if prev is None else prev + m

    def state_lists(self):
        """(meta, arrays) host snapshot for mid-sweep checkpoints: per
        column a category list (JSON side) + a [k, 4] count matrix."""
        meta, arrays = {}, {}
        for i, (col, d) in enumerate(self.stats.items()):
            cats = list(d.keys())
            meta[col] = {"i": i, "cats": cats}
            arrays[f"cat_{i}"] = (np.stack([d[c] for c in cats])
                                  if cats else np.zeros((0, 4)))
        return meta, arrays

    def load_state(self, meta, arrays) -> None:
        self.stats = {
            col: {c: np.asarray(arrays[f"cat_{m['i']}"][j], np.float64)
                  for j, c in enumerate(m["cats"])}
            for col, m in meta.items()}

    def finalize(self, col_name: str, max_cates: int = 0):
        """Return (categories, counts[cats+1, 4], n_distinct, n_missing) —
        last counts row = missing bin.  Categories ordered frequency desc; if
        ``max_cates``>0, overflow categories are folded into the missing bin
        (the reference caps via ``cateMaxNumBin``).  ``n_distinct`` /
        ``n_missing`` are the PRE-cap truths (the reference computes
        distinctCount from the raw value set, not the capped bin list)."""
        d = self.stats.get(col_name, {})
        items = [(k, v) for k, v in d.items() if k != _MISSING_KEY]
        n_distinct = len(items)
        items.sort(key=lambda kv: (-(kv[1][0] + kv[1][1]), kv[0]))
        missing = d.get(_MISSING_KEY, np.zeros(4))
        n_missing = int(missing[0] + missing[1])
        if max_cates and len(items) > max_cates:
            for _, v in items[max_cates:]:
                missing = missing + v
            items = items[:max_cates]
        cats = [k for k, _ in items]
        counts = np.stack([v for _, v in items] + [missing]) if items else \
            missing[None, :]
        return cats, counts, n_distinct, n_missing


_MISSING_KEY = "\x00__missing__"


# ----------------------------------------------------------------- binner
class ColumnBinner:
    """Maps raw column values -> bin indices given finalized binning.

    Numeric: searchsorted over binBoundary (boundary[0] = -inf); categorical:
    exact category index; missing/unseen -> ``num_bins`` (the trailing missing
    bin), matching reference ``BinUtils.getBinNum`` semantics.
    """

    def __init__(self, boundaries: Optional[np.ndarray] = None,
                 categories: Optional[List[str]] = None):
        assert (boundaries is None) != (categories is None)
        self.boundaries = None if boundaries is None else np.asarray(boundaries, np.float64)
        self.categories = categories
        if categories is None:
            self.cat_index = None
        else:
            # a bin label may be a merged group of raw categories joined by
            # CATEGORY_GROUP_SEP (dynamic rebin; reference CategoricalBinInfo)
            self.cat_index = {}
            for i, c in enumerate(categories):
                for member in c.split(CATEGORY_GROUP_SEP):
                    self.cat_index[member] = i

    @property
    def num_bins(self) -> int:
        if self.boundaries is not None:
            return len(self.boundaries)
        return len(self.categories)

    def bin_numeric(self, x: np.ndarray, valid: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self.boundaries, x, side="right") - 1
        idx = np.clip(idx, 0, self.num_bins - 1)
        return np.where(valid, idx, self.num_bins).astype(np.int32)

    def bin_categorical(self, values: np.ndarray) -> np.ndarray:
        import pandas as pd
        s = pd.Series(values, dtype=str).str.strip()
        idx = s.map(self.cat_index).fillna(self.num_bins).to_numpy(dtype=np.int64)
        return idx.astype(np.int32)
