"""Device-side streaming binning + per-column stats accumulation.

TPU-native replacement for the reference's stats data path (SURVEY.md §3.2):
the SPDT/MunroPat streaming-sketch binning (``core/binning/``) plus the
``UpdateBinningInfo`` MR second pass become two SPMD passes over columnar
chunks:

  pass 1 (moments): per-column count/min/max + centered moments M2..M4
          (Chan et al. pairwise combine, so f32 device sums stay accurate),
  pass 2 (sketch):  a fine equal-width histogram per column (pos/neg counts
          and weighted counts via one scatter-add ``segment_sum``).

Bin boundaries for every binning method (EqualPositive/Total/Negative/
Interval + weighted variants, ``ModelStatsConf.java:34-35``) are read off the
fine histogram's cumulative sums; final per-bin pos/neg counts are exact
segment-sums of fine buckets (boundaries always land on fine-bucket edges).
Categorical bins are exact dict aggregations.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config.model_config import BinningMethod

# merged-category group separator (reference uses \u0001 in CategoricalBinInfo)
CATEGORY_GROUP_SEP = "\x01"

NEG_INF = float("-inf")


# ----------------------------------------------------------------- kernels
@jax.jit
def _moments_kernel(x: jnp.ndarray, valid: jnp.ndarray):
    """Per-column count/sum/min/max + centered M2/M3/M4 for one chunk.

    x: [R, C] float32 with arbitrary values where invalid; valid: [R, C] bool.
    Centering by the chunk mean keeps f32 power sums small enough for TPU.
    """
    v = valid.astype(x.dtype)
    cnt = v.sum(axis=0)
    safe_cnt = jnp.maximum(cnt, 1.0)
    xv = jnp.where(valid, x, 0.0)
    s1 = xv.sum(axis=0)
    mean = s1 / safe_cnt
    d = jnp.where(valid, x - mean, 0.0)
    m2 = (d * d).sum(axis=0)
    m3 = (d * d * d).sum(axis=0)
    m4 = (d * d * d * d).sum(axis=0)
    big = jnp.asarray(jnp.finfo(x.dtype).max, x.dtype)
    mn = jnp.where(valid, x, big).min(axis=0)
    mx = jnp.where(valid, x, -big).max(axis=0)
    return cnt, mean, m2, m3, m4, mn, mx


@functools.partial(jax.jit, static_argnames=("num_buckets", "use_pallas"))
def _histogram_kernel(x: jnp.ndarray, valid: jnp.ndarray, target: jnp.ndarray,
                      weight: jnp.ndarray, lo: jnp.ndarray, hi: jnp.ndarray,
                      num_buckets: int, use_pallas: bool = False):
    """Fine-histogram for one chunk.

    Returns [C, num_buckets, 4]: (#pos, #neg, w_pos, w_neg) per fine bucket.
    Two lowerings, the tree-histogram story replayed for the ETL plane:
    ``use_pallas=True`` → the two-level one-hot MXU kernel
    (:func:`shifu_tpu.ops.hist_pallas.stats_histograms_pallas` — the TPU
    serializes scatter-adds, and at north-star widths the scatter path
    cannot keep up with object-storage IO); default → one flattened
    ``segment_sum``, the reference's per-(column,bin) reducer accumulation.
    """
    R, C = x.shape
    scale = num_buckets / jnp.maximum(hi - lo, 1e-30)
    idx = jnp.clip(((x - lo) * scale), 0, num_buckets - 1).astype(jnp.int32)
    is_pos = (target >= 0.5)[:, None]
    w = weight[:, None]
    ones = jnp.ones((R, 1), x.dtype)
    vals = jnp.concatenate([
        jnp.where(is_pos, ones, 0.0), jnp.where(is_pos, 0.0, ones),
        jnp.where(is_pos, w, 0.0), jnp.where(is_pos, 0.0, w)], axis=1)  # [R,4]
    if use_pallas:
        from .hist_pallas import stats_histograms_pallas, target_platform
        idx = jnp.where(valid, idx, -1)      # invalid cell -> matches no bin
        return stats_histograms_pallas(idx, vals, num_buckets,
                                       interpret=target_platform() != "tpu")
    flat = idx + jnp.arange(C, dtype=jnp.int32) * num_buckets
    flat = jnp.where(valid, flat, C * num_buckets)  # overflow slot for invalid
    data = jnp.broadcast_to(vals[:, None, :], (R, C, 4)).reshape(R * C, 4)
    seg = jax.ops.segment_sum(data, flat.reshape(-1),
                              num_segments=C * num_buckets + 1)
    return seg[:-1].reshape(C, num_buckets, 4)


# ------------------------------------------------------- moment combination
def _combine_moments(a: dict, b: Tuple[np.ndarray, ...]) -> dict:
    """Chan et al. pairwise combination of (count, mean, M2, M3, M4)."""
    cb, mb, M2b, M3b, M4b, mnb, mxb = [np.asarray(t, np.float64) for t in b]
    if not a:
        return {"count": cb, "mean": mb, "M2": M2b, "M3": M3b, "M4": M4b,
                "min": mnb, "max": mxb}
    ca, ma, M2a, M3a, M4a = a["count"], a["mean"], a["M2"], a["M3"], a["M4"]
    n = ca + cb
    safe_n = np.maximum(n, 1.0)
    delta = mb - ma
    mean = ma + delta * cb / safe_n
    M2 = M2a + M2b + delta ** 2 * ca * cb / safe_n
    M3 = (M3a + M3b + delta ** 3 * ca * cb * (ca - cb) / safe_n ** 2
          + 3 * delta * (ca * M2b - cb * M2a) / safe_n)
    M4 = (M4a + M4b
          + delta ** 4 * ca * cb * (ca ** 2 - ca * cb + cb ** 2) / safe_n ** 3
          + 6 * delta ** 2 * (ca ** 2 * M2b + cb ** 2 * M2a) / safe_n ** 2
          + 4 * delta * (ca * M3b - cb * M3a) / safe_n)
    return {"count": n, "mean": np.where(n > 0, mean, 0.0), "M2": M2, "M3": M3,
            "M4": M4, "min": np.minimum(a["min"], mnb),
            "max": np.maximum(a["max"], mxb)}


# ------------------------------------------------------------- accumulators
@dataclass
class NumericAccumulator:
    """Streaming accumulator over numeric columns (both passes)."""
    n_cols: int
    num_buckets: int = 4096
    moments: dict = field(default_factory=dict)
    total_rows: int = 0
    missing: Optional[np.ndarray] = None
    hist: Optional[np.ndarray] = None          # [C, K, 4] float64
    missing_agg: Optional[np.ndarray] = None   # [C, 4] pos/neg/wpos/wneg of missing
    lo: Optional[np.ndarray] = None
    hi: Optional[np.ndarray] = None
    # exact mode (MunroPat): keep per-column (valid values, pos flag,
    # weight) so boundaries land on TRUE quantiles instead of sketch-bucket
    # edges (reference ``core/binning/MunroPatBinning.java:29`` materializes
    # the column sample the same way).  Memory is O(valid values) — the
    # exact path is for LOCAL-scale runs; the sketch remains the default.
    exact: bool = False
    _exact_cols: Optional[list] = None     # [C] lists of (vals, pos, w)

    # ---- pass 1
    def update_moments(self, x: np.ndarray, valid: np.ndarray) -> None:
        out = _moments_kernel(jnp.asarray(x, jnp.float32), jnp.asarray(valid))
        self.moments = _combine_moments(self.moments, out)
        self.total_rows += x.shape[0]
        miss = (~valid).sum(axis=0).astype(np.float64)
        self.missing = miss if self.missing is None else self.missing + miss

    def finalize_range(self) -> None:
        mn, mx = self.moments["min"].copy(), self.moments["max"].copy()
        empty = self.moments["count"] == 0
        mn[empty], mx[empty] = 0.0, 1.0
        same = mx <= mn
        mx[same] = mn[same] + 1.0
        self.lo, self.hi = mn, mx

    # ---- pass 2
    def update_histogram(self, x: np.ndarray, valid: np.ndarray,
                         target: np.ndarray, weight: np.ndarray) -> None:
        assert self.lo is not None, "call finalize_range() after pass 1"
        from .hist_pallas import pallas_available
        up = (pallas_available() and self.num_buckets % 64 == 0
              and self.num_buckets <= 4096)
        h = _histogram_kernel(
            jnp.asarray(x, jnp.float32), jnp.asarray(valid),
            jnp.asarray(target, jnp.float32), jnp.asarray(weight, jnp.float32),
            jnp.asarray(self.lo, jnp.float32), jnp.asarray(self.hi, jnp.float32),
            self.num_buckets, use_pallas=up)
        h = np.asarray(h, np.float64)
        self.hist = h if self.hist is None else self.hist + h
        # missing-bin aggregation (invalid entries)
        is_pos = target >= 0.5
        inval = ~valid
        magg = np.stack([
            (inval & is_pos[:, None]).sum(0),
            (inval & ~is_pos[:, None]).sum(0),
            (inval * (weight * is_pos)[:, None]).sum(0),
            (inval * (weight * ~is_pos)[:, None]).sum(0)], axis=1).astype(np.float64)
        self.missing_agg = magg if self.missing_agg is None else self.missing_agg + magg
        if self.exact:
            if self._exact_cols is None:
                self._exact_cols = [[] for _ in range(self.n_cols)]
            pos_r = np.asarray(target, np.float64) >= 0.5
            w64 = np.asarray(weight, np.float64)
            for c in range(self.n_cols):
                v = valid[:, c]
                self._exact_cols[c].append(
                    (np.asarray(x[v, c], np.float64), pos_r[v], w64[v]))

    # ---- boundary derivation
    def bucket_edges(self, col: int) -> np.ndarray:
        return np.linspace(self.lo[col], self.hi[col], self.num_buckets + 1)

    def compute_boundaries(self, method: BinningMethod, max_bins: int) -> List[np.ndarray]:
        """Per-column bin boundaries; element 0 is -inf like the reference's
        ``binBoundary`` (value v falls in bin i when b[i] <= v < b[i+1])."""
        assert self.hist is not None
        out = []
        for c in range(self.n_cols):
            h = self.hist[c]  # [K, 4]
            if method == BinningMethod.EqualInterval:
                inner = np.linspace(self.lo[c], self.hi[c], max_bins + 1)[:-1]
                bnds = np.concatenate([[NEG_INF], inner[1:]])
                out.append(_dedupe(bnds))
                continue
            weight_col = {
                BinningMethod.EqualTotal: h[:, 0] + h[:, 1],
                BinningMethod.EqualPositive: h[:, 0],
                BinningMethod.EqualNegtive: h[:, 1],
                BinningMethod.WeightEqualTotal: h[:, 2] + h[:, 3],
                BinningMethod.WeightEqualPositive: h[:, 2],
                BinningMethod.WeightEqualNegative: h[:, 3],
                BinningMethod.WeightEqualInterval: h[:, 0] + h[:, 1],
            }.get(method, h[:, 0] + h[:, 1])
            total = weight_col.sum()
            if total <= 0:
                out.append(np.array([NEG_INF]))
                continue
            cum = np.cumsum(weight_col)
            targets = total * np.arange(1, max_bins) / max_bins
            # first fine-bucket index where cum >= target -> boundary at its right edge
            pos = np.searchsorted(cum, targets, side="left")
            edges = self.bucket_edges(c)
            bnds = np.concatenate([[NEG_INF], edges[pos + 1]])
            out.append(_dedupe(bnds))
        return out

    def _exact_col(self, col: int):
        chunks = self._exact_cols[col]
        return (np.concatenate([c[0] for c in chunks]) if chunks
                else np.empty(0),
                np.concatenate([c[1] for c in chunks]) if chunks
                else np.empty(0, bool),
                np.concatenate([c[2] for c in chunks]) if chunks
                else np.empty(0))

    @staticmethod
    def _measure(method: BinningMethod):
        """Weight measure of one (pos, w) row set for a binning method —
        selected ONCE, not rebuilt per column."""
        return {
            BinningMethod.EqualTotal: lambda p, w: np.ones(len(p)),
            BinningMethod.EqualPositive: lambda p, w: p.astype(np.float64),
            BinningMethod.EqualNegtive: lambda p, w: (~p).astype(np.float64),
            BinningMethod.WeightEqualTotal: lambda p, w: w,
            BinningMethod.WeightEqualPositive: lambda p, w: w * p,
            BinningMethod.WeightEqualNegative: lambda p, w: w * ~p,
        }.get(method, lambda p, w: np.ones(len(p)))

    def compute_boundaries_exact(self, method: BinningMethod,
                                 max_bins: int) -> List[np.ndarray]:
        """Exact equal-frequency boundaries from the materialized values —
        the MunroPat path (reference ``MunroPatBinning.java:29`` exact
        quantiles): boundaries are TRUE data quantiles of the method's
        weight measure, not sketch-bucket edges.  Pair with
        :meth:`bin_counts_exact` — the sketch-based :meth:`bin_counts`
        assumes boundaries on bucket edges and would misassign rows tied
        at a mid-bucket boundary."""
        assert self._exact_cols is not None, \
            "exact boundaries need exact=True collection during pass 2"
        measure = self._measure(method)
        out = []
        for c in range(self.n_cols):
            vals, pos, ws = self._exact_col(c)
            if vals.size == 0:
                out.append(np.array([NEG_INF]))
                continue
            if method == BinningMethod.EqualInterval:
                inner = np.linspace(vals.min(), vals.max(), max_bins + 1)[:-1]
                out.append(_dedupe(np.concatenate([[NEG_INF], inner[1:]])))
                continue
            wrow = measure(pos, ws)
            order = np.argsort(vals, kind="stable")
            sv, sw = vals[order], wrow[order]
            cum = np.cumsum(sw)
            total = cum[-1]
            if total <= 0:
                out.append(np.array([NEG_INF]))
                continue
            targets = total * np.arange(1, max_bins) / max_bins
            pos_idx = np.searchsorted(cum, targets, side="left")
            pos_idx = np.minimum(pos_idx, len(sv) - 1)
            bnds = np.concatenate([[NEG_INF], sv[pos_idx]])
            out.append(_dedupe(bnds))
        return out

    def bin_counts_exact(self, col: int, boundaries: np.ndarray) -> np.ndarray:
        """Per-bin (pos, neg, wpos, wneg) from the EXACT materialized rows,
        with the same assignment rule scoring uses (``ColumnBinner
        .bin_numeric``: b[i] <= v < b[i+1]); trailing missing bin from the
        missing aggregation.  The sketch-based :meth:`bin_counts` is only
        exact when boundaries sit on fine-bucket edges — exact-quantile
        boundaries don't."""
        vals, pos, ws = self._exact_col(col)
        nb = len(boundaries)
        idx = np.clip(np.searchsorted(boundaries, vals, side="right") - 1,
                      0, nb - 1)
        agg = np.zeros((nb + 1, 4))
        np.add.at(agg, (idx, 0), pos.astype(np.float64))
        np.add.at(agg, (idx, 1), (~pos).astype(np.float64))
        np.add.at(agg, (idx, 2), ws * pos)
        np.add.at(agg, (idx, 3), ws * ~pos)
        if self.missing_agg is not None:
            agg[nb] = self.missing_agg[col]
        return agg

    def bin_counts(self, col: int, boundaries: np.ndarray) -> np.ndarray:
        """Exact per-bin (pos, neg, wpos, wneg) counts incl. trailing missing
        bin, derived by segment-summing fine buckets."""
        edges = self.bucket_edges(col)
        # fine bucket k covers [edges[k], edges[k+1]); assign to final bin
        bucket_bin = np.searchsorted(boundaries, edges[:-1], side="right") - 1
        bucket_bin = np.clip(bucket_bin, 0, len(boundaries) - 1)
        n_bins = len(boundaries)
        agg = np.zeros((n_bins + 1, 4))
        np.add.at(agg, bucket_bin, self.hist[col])
        if self.missing_agg is not None:
            agg[n_bins] = self.missing_agg[col]
        return agg

    def percentile(self, col: int, q: Sequence[float]) -> np.ndarray:
        """Approximate percentiles (to fine-bucket resolution) from the sketch."""
        h = self.hist[col][:, 0] + self.hist[col][:, 1]
        total = h.sum()
        if total <= 0:
            return np.full(len(q), np.nan)
        cum = np.cumsum(h)
        edges = self.bucket_edges(col)
        pos = np.searchsorted(cum, np.asarray(q) * total, side="left")
        return edges[np.minimum(pos + 1, self.num_buckets)]

    def distinct_estimate(self, col: int) -> int:
        """Lower-bound distinct estimate = occupied fine buckets (the
        reference uses HyperLogLog; this is the sketch-native analogue)."""
        return int((self.hist[col].sum(axis=1) > 0).sum())


def _dedupe(bnds: np.ndarray) -> np.ndarray:
    keep = np.ones(len(bnds), dtype=bool)
    keep[1:] = np.diff(bnds) > 0
    return bnds[keep]


@dataclass
class CategoricalAccumulator:
    """Exact per-category pos/neg/weight aggregation (dict-based, streamed)."""
    stats: Dict[str, Dict[str, np.ndarray]] = field(default_factory=dict)

    def update(self, col_name: str, values: np.ndarray, valid: np.ndarray,
               target: np.ndarray, weight: np.ndarray,
               stripped: bool = False) -> None:
        """``values`` may be pre-stripped (``stripped=True`` skips the
        string pass).  One factorize + four weighted bincounts per chunk —
        the per-chunk DataFrame/groupby this replaces was the host
        bottleneck on categorical-heavy (fraud-style) datasets
        (reference reducers are column-parallel,
        ``MapReducerStatsWorker.java:111-139``)."""
        import pandas as pd
        d = self.stats.setdefault(col_name, {})
        is_pos = target >= 0.5
        if not stripped:
            values = pd.Series(values, dtype=str).str.strip().to_numpy()
        codes, cats = pd.factorize(values)           # C hash table
        k = len(cats)
        # factorize codes NaN/None as -1; route them (and invalid rows) to
        # the missing slot rather than letting bincount see a negative
        idx = np.where(valid & (codes >= 0), codes, k)
        posf = is_pos.astype(np.float64)
        w = np.asarray(weight, np.float64)
        stacked = np.stack([
            np.bincount(idx, weights=posf, minlength=k + 1),
            np.bincount(idx, weights=1.0 - posf, minlength=k + 1),
            np.bincount(idx, weights=w * posf, minlength=k + 1),
            np.bincount(idx, weights=w * (1.0 - posf), minlength=k + 1)],
            axis=1)                                  # [k+1, 4]
        for i, cat in enumerate(cats):
            row = stacked[i]
            if not row.any():          # a missing-marker string: all rows
                continue               # of this category were invalid
            prev = d.get(cat)
            d[cat] = row if prev is None else prev + row
        m = stacked[k]
        if m.any():
            prev = d.get(_MISSING_KEY)
            d[_MISSING_KEY] = m if prev is None else prev + m

    def finalize(self, col_name: str, max_cates: int = 0):
        """Return (categories, counts[cats+1, 4], n_distinct, n_missing) —
        last counts row = missing bin.  Categories ordered frequency desc; if
        ``max_cates``>0, overflow categories are folded into the missing bin
        (the reference caps via ``cateMaxNumBin``).  ``n_distinct`` /
        ``n_missing`` are the PRE-cap truths (the reference computes
        distinctCount from the raw value set, not the capped bin list)."""
        d = self.stats.get(col_name, {})
        items = [(k, v) for k, v in d.items() if k != _MISSING_KEY]
        n_distinct = len(items)
        items.sort(key=lambda kv: (-(kv[1][0] + kv[1][1]), kv[0]))
        missing = d.get(_MISSING_KEY, np.zeros(4))
        n_missing = int(missing[0] + missing[1])
        if max_cates and len(items) > max_cates:
            for _, v in items[max_cates:]:
                missing = missing + v
            items = items[:max_cates]
        cats = [k for k, _ in items]
        counts = np.stack([v for _, v in items] + [missing]) if items else \
            missing[None, :]
        return cats, counts, n_distinct, n_missing


_MISSING_KEY = "\x00__missing__"


# ----------------------------------------------------------------- binner
class ColumnBinner:
    """Maps raw column values -> bin indices given finalized binning.

    Numeric: searchsorted over binBoundary (boundary[0] = -inf); categorical:
    exact category index; missing/unseen -> ``num_bins`` (the trailing missing
    bin), matching reference ``BinUtils.getBinNum`` semantics.
    """

    def __init__(self, boundaries: Optional[np.ndarray] = None,
                 categories: Optional[List[str]] = None):
        assert (boundaries is None) != (categories is None)
        self.boundaries = None if boundaries is None else np.asarray(boundaries, np.float64)
        self.categories = categories
        if categories is None:
            self.cat_index = None
        else:
            # a bin label may be a merged group of raw categories joined by
            # CATEGORY_GROUP_SEP (dynamic rebin; reference CategoricalBinInfo)
            self.cat_index = {}
            for i, c in enumerate(categories):
                for member in c.split(CATEGORY_GROUP_SEP):
                    self.cat_index[member] = i

    @property
    def num_bins(self) -> int:
        if self.boundaries is not None:
            return len(self.boundaries)
        return len(self.categories)

    def bin_numeric(self, x: np.ndarray, valid: np.ndarray) -> np.ndarray:
        idx = np.searchsorted(self.boundaries, x, side="right") - 1
        idx = np.clip(idx, 0, self.num_bins - 1)
        return np.where(valid, idx, self.num_bins).astype(np.int32)

    def bin_categorical(self, values: np.ndarray) -> np.ndarray:
        import pandas as pd
        s = pd.Series(values, dtype=str).str.strip()
        idx = s.map(self.cat_index).fillna(self.num_bins).to_numpy(dtype=np.int64)
        return idx.astype(np.int32)
