"""Parse pool — the offline pipeline's one shared parse stage.

Every raw consumer (stats sweep, norm, correlation, PSI, eval scoring)
previously ran ``source.iter_chunks()`` + ``extractor.extract()`` inline
on one host thread — read, then parse, then compute, strictly serial,
once PER STEP.  :func:`iter_extracted` replaces that pattern with one
producer/consumer stage shared by all of them:

* **Pool** (``-Dshifu.ingest.parseWorkers``, default ``min(cores, 8)``;
  ``0`` = the inline seed path): one producer thread streams raw chunks
  in order (quarantine accounting and provenance byte-identical to the
  serial loop — it IS the serial loop), N workers run the vectorized
  parse concurrently (``pd.read_csv``'s C engine and the ``to_numeric``
  parses release the GIL, so read, parse and the caller's device compute
  overlap), and emission is strictly in chunk order behind a bounded
  queue — callers observe the exact serial sequence.
* **Raw cache** (:mod:`shifu_tpu.data.rawcache`): with a ``cache_root``,
  the first full-rate pass write-throughs the decoded columns; later
  passes stream memmap slices and never touch the string plane (no
  ``iter_chunks`` call at all — ``ingest.disk_passes`` stays flat).

Bit-parity contract: every extractor op is row-wise, so sample-then-
parse (the serial order) and parse-then-subset (the pooled/cached order)
produce identical arrays; pre-parse Bernoulli sampling uses one
deterministic per-chunk substream (``rng([977, chunk_idx])``, the
convention ``pipeline.stats`` established) in both orders.
"""

from __future__ import annotations

import os
import time
from typing import Iterator, Optional, Tuple

import numpy as np

from .extract import ChunkExtractor, ExtractedChunk
from .rawcache import (RawCacheWriter, open_raw_cache, raw_cache_budget_bytes,
                       raw_cache_enabled, source_signature)

RAW_SAMPLE_SEED = 977          # pre-parse sample substream (stats plane)

# default raw chunk geometry (rows per chunk) — one module-level source
# of truth so the cache's chunkRows pin and the reader's chunking can
# never disagree (tests shrink it to force multi-chunk/multi-shard runs)
CHUNK_ROWS = 262144


def parse_workers() -> int:
    """``shifu.ingest.parseWorkers``: <0 (default) = auto ``min(cores,
    8)``; 0 = inline serial seed path; N = that many parse threads."""
    from ..config import environment
    w = environment.get_int("shifu.ingest.parseWorkers", -1)
    if w < 0:
        w = min(os.cpu_count() or 1, 8)
    return w


def sample_raw_mask(raw_rows: int, rate: float, chunk_idx: int) -> np.ndarray:
    """The deterministic pre-parse Bernoulli mask over a chunk's raw
    rows — identical across passes and across the serial / pooled /
    cache-replay orders (seeded per chunk over the RAW row count)."""
    return np.random.default_rng(
        [RAW_SAMPLE_SEED, chunk_idx]).random(raw_rows) < rate


def _sample_chunk(chunk, rate: float, chunk_idx: int):
    """Serial order: subset the raw rows BEFORE parsing (skips the parse
    cost of dropped rows) — the reference samples in its stats mappers
    (``ModelStatsConf`` sampleRate, ``MapReducerStatsWorker``)."""
    if rate >= 1.0 or len(chunk.data) == 0:
        return chunk
    from .reader import RawChunk
    keep = sample_raw_mask(len(chunk.data), rate, chunk_idx)
    return RawChunk(chunk.columns, chunk.data[keep])


def subsample_extracted(ex: ExtractedChunk, rate: float,
                        chunk_idx: int) -> ExtractedChunk:
    """Cached/pooled order: replay the same pre-parse sample AFTER the
    full parse — ``mask[kept_idx]`` selects exactly the rows the
    sample-then-parse order would have kept, and row-wise parses commute
    with the subset, so the arrays match bit-for-bit."""
    if rate >= 1.0:
        return ex
    smask = sample_raw_mask(ex.raw_rows, rate, chunk_idx)
    sel = smask[ex.kept_idx] if ex.kept_idx is not None else \
        np.zeros(ex.n, dtype=bool)
    return ExtractedChunk(
        n=int(sel.sum()), target=ex.target[sel], weight=ex.weight[sel],
        numeric=ex.numeric[sel], numeric_valid=ex.numeric_valid[sel],
        numeric_cols=ex.numeric_cols,
        categorical={k: v[sel] for k, v in ex.categorical.items()},
        categorical_cols=ex.categorical_cols, raw=None,
        kept_idx=ex.kept_idx[sel] if ex.kept_idx is not None else None,
        raw_rows=ex.raw_rows)


def cache_dir_for(cache_root: str, source_sig,
                  extractor: ChunkExtractor) -> str:
    """One cache per (source files, row identity): the training source
    and each eval source key separate subdirectories, so a pass over one
    never clobbers the other's cache."""
    import hashlib
    import json
    key = hashlib.md5(json.dumps(
        [source_sig, extractor.row_identity()],
        sort_keys=True).encode()).hexdigest()[:16]
    return os.path.join(cache_root, key)


def iter_extracted(source, extractor: ChunkExtractor, *,
                   rate: float = 1.0, keep_raw: bool = False,
                   cache_root: Optional[str] = None, start_chunk: int = 0,
                   chunk_rows: Optional[int] = None
                   ) -> Iterator[Tuple[int, ExtractedChunk]]:
    """Yield ``(chunk_idx, ExtractedChunk)`` in strict chunk order.

    Drop-in for the ``enumerate(source.iter_chunks())`` + ``extract()``
    loops: same chunk indices, same arrays, same quarantine/threshold
    behavior.  ``start_chunk`` skips extraction of the resumed prefix
    (the raw rows still stream past, exactly like the serial resume
    loop's ``continue``).  ``keep_raw`` passes (PSI) parse through the
    pool but never touch the cache — raw strings are not cached.
    """
    from .. import obs
    if chunk_rows is None:
        chunk_rows = CHUNK_ROWS
    rd = None
    cdir = sig = None
    writable = False
    if cache_root and not keep_raw and raw_cache_enabled():
        sig = source_signature(source.files)
        cdir = cache_dir_for(cache_root, sig, extractor)
        rd, writable = open_raw_cache(cdir, sig, extractor, chunk_rows)
    if rd is not None:                 # serve: zero string-plane touch
        obs.counter("rawcache.hits").inc()
        for ci in range(start_chunk, rd.n_chunks):
            yield ci, subsample_extracted(rd.chunk(ci, extractor), rate, ci)
        return
    if cdir is not None:
        obs.counter("rawcache.misses").inc()
    writer = None
    if cdir is not None and writable and start_chunk == 0:
        writer = RawCacheWriter(cdir, extractor, sig, chunk_rows,
                                raw_cache_budget_bytes())
    workers = parse_workers()

    def work(ci, chunk):
        # cache-writing passes parse at FULL rate (the cache must cover
        # every row); the consumer view re-applies the sample from the
        # replay provenance.  Plain passes sample first — serial order.
        if writer is not None:
            return extractor.extract(chunk)
        return extractor.extract(_sample_chunk(chunk, rate, ci),
                                 keep_raw=keep_raw)

    def emit(ci, ex):
        if writer is not None:
            writer.append(ex)          # abandons itself on budget/IO
            return ci, subsample_extracted(ex, rate, ci)
        return ci, ex

    done = False
    try:
        if workers <= 0:
            for ci, chunk in enumerate(source.iter_chunks(chunk_rows)):
                if ci < start_chunk and writer is None:
                    continue
                yield emit(ci, work(ci, chunk))
        else:
            yield from _pooled(source, extractor, work, emit, writer,
                               start_chunk, chunk_rows, workers)
        if writer is not None:
            writer.finish()
        done = True
    finally:
        if not done and writer is not None:
            writer.abort()


def _pooled(source, extractor, work, emit, writer, start_chunk, chunk_rows,
            workers):
    """Producer thread streams chunks in order; a thread pool parses;
    emission is strictly FIFO behind a bounded future queue."""
    import queue
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from .. import obs
    pend: "queue.Queue" = queue.Queue(maxsize=max(2 * workers, 2))
    stop = threading.Event()
    exc: list = []

    def produce(pool):
        try:
            for ci, chunk in enumerate(source.iter_chunks(chunk_rows)):
                if ci < start_chunk and writer is None:
                    continue           # resumed prefix: stream past
                item = (ci, pool.submit(work, ci, chunk))
                while not stop.is_set():
                    try:
                        pend.put(item, timeout=0.05)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    item[1].cancel()
                    return
        except BaseException as e:     # incl. bad-threshold ShifuError
            exc.append(e)
        finally:
            while not stop.is_set():
                try:
                    pend.put(None, timeout=0.05)
                    break
                except queue.Full:
                    continue

    stall, t0 = 0.0, time.perf_counter()
    with ThreadPoolExecutor(max_workers=workers,
                            thread_name_prefix="parse") as pool:
        prod = threading.Thread(target=produce, args=(pool,), daemon=True,
                                name="parse-producer")
        prod.start()
        try:
            while True:
                item = pend.get()
                if item is None:
                    break
                ci, fut = item
                tw = time.perf_counter()
                ex = fut.result()
                stall += time.perf_counter() - tw
                yield emit(ci, ex)
        finally:
            stop.set()
            while True:                # unblock a put-blocked producer
                try:
                    item = pend.get_nowait()
                    if item is not None:
                        item[1].cancel()
                except queue.Empty:
                    break
            prod.join(timeout=10)
            wall = time.perf_counter() - t0
            # fraction of the consumer loop spent waiting on parse
            # futures: ~0 = parse fully hidden behind compute/IO, ~1 =
            # parse-bound (more workers or a raw cache would help)
            obs.gauge("ingest.parse_stall_frac").set(
                stall / max(wall, 1e-9))
    if exc:
        raise exc[0]
