"""Shard IO for the materialized norm/clean datasets."""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional

import numpy as np


@dataclass
class Shards:
    directory: str
    schema: dict
    files: List[str]

    @classmethod
    def open(cls, directory: str) -> "Shards":
        with open(os.path.join(directory, "schema.json")) as f:
            schema = json.load(f)
        files = sorted(os.path.join(directory, f) for f in os.listdir(directory)
                       if f.endswith(".npz"))
        return cls(directory, schema, files)

    def iter_shards(self, start: int = 0) -> Iterator[Dict[str, np.ndarray]]:
        for f in self.files[start:]:
            yield dict(np.load(f))

    def load_all(self) -> Dict[str, np.ndarray]:
        parts = list(self.iter_shards())
        if not parts:
            raise FileNotFoundError(f"no shards in {self.directory}")
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    @property
    def num_rows(self) -> int:
        return sum(len(np.load(f)["y"]) for f in self.files)
