"""Shard IO for the materialized norm/clean datasets."""

from __future__ import annotations

import json
import logging
import os
import zipfile
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

log = logging.getLogger(__name__)

# sidecar manifest of per-shard row counts (written on first scan; the
# norm step writes the counts straight into schema.json as "shardRows",
# so materialized datasets never scan at all)
ROWS_SIDECAR = ".shard_rows.json"


def bins_wire_dtype(n_bins: int) -> np.dtype:
    """The ONE compact storage/wire dtype policy for bin ids 0..n_bins-1:
    norm shards, the spill cache and the host→device transfer all use it
    (the reference stores worker rows as short[] bin ids,
    ``DTWorker.java:100`` — f32/int32 on the wire is pure waste)."""
    if n_bins <= 1 << 8:
        return np.dtype(np.uint8)
    if n_bins <= 1 << 16:
        return np.dtype(np.uint16)
    return np.dtype(np.int32)


def _npz_rows(path: str) -> int:
    """Row count of one npz shard WITHOUT decoding any array: read the
    npy header of one member through the zip directory.  Falls back to a
    full load on any format surprise."""
    try:
        from numpy.lib import format as npf
        with zipfile.ZipFile(path) as z:
            names = z.namelist()
            name = "y.npy" if "y.npy" in names else names[0]
            with z.open(name) as f:
                ver = npf.read_magic(f)
                if ver == (1, 0):
                    shape, _, _ = npf.read_array_header_1_0(f)
                else:
                    shape, _, _ = npf.read_array_header_2_0(f)
                return int(shape[0]) if shape else 0
    except Exception:
        return int(len(np.load(path)["y"]))


class _WireView:
    """SpillReader facade over the TAIL of a wire plane starting at shard
    ``base`` — memmaps/prefix-sums rebase so ShardStream's window and
    cursor bookkeeping are oblivious to where the view starts (the
    ``from_row`` refresh cursor, which slices npz file lists the same
    way)."""

    def __init__(self, rd, base_shard: int):
        self._rd = rd
        self._g0 = int(rd.cum[base_shard])
        self.rows = rd.rows - self._g0
        self.shard_rows = list(rd.shard_rows[base_shard:])
        self.cum = (np.asarray(rd.cum[base_shard:]) - self._g0).astype(
            np.int64)

    def memmap(self, key: str):
        return self._rd.memmap(key)[self._g0:]

    def global_of(self, shard: int, offset: int) -> Optional[int]:
        if not 0 <= shard < len(self.shard_rows):
            return None
        g = int(self.cum[shard]) + int(offset)
        return g if 0 <= g <= self.rows else None

    def src_of(self, g: int):
        si = int(np.searchsorted(self.cum, g, side="right") - 1)
        return si, int(g - self.cum[si])


@dataclass
class Shards:
    directory: str
    schema: dict
    files: List[str]
    _shard_rows: Optional[List[int]] = field(default=None, repr=False,
                                             compare=False)
    # wire mode (schema "wire"): shards live as flat spill raw files, no
    # npz at all; _wire_base is the from_row cursor in shard units
    _wire_base: int = field(default=0, repr=False, compare=False)
    _wire_rd: Optional[object] = field(default=None, repr=False,
                                       compare=False)

    @classmethod
    def open(cls, directory: str) -> "Shards":
        with open(os.path.join(directory, "schema.json")) as f:
            schema = json.load(f)
        files = sorted(os.path.join(directory, f) for f in os.listdir(directory)
                       if f.endswith(".npz"))
        return cls(directory, schema, files)

    @property
    def is_wire(self) -> bool:
        return bool(self.schema.get("wire"))

    def wire_reader(self, keys: Optional[Sequence[str]] = None):
        """A SpillReader(-like) over the wire plane, or None when this
        shard set is npz-backed.  ``keys`` names what the caller will
        read — any subset of the wire's keys is served from the same raw
        files.  A schema that claims wire over an invalid/torn spill is
        a coded error (there are no npz to fall back to): re-run norm."""
        if not self.is_wire:
            return None
        wire_keys = list(self.schema.get("wireKeys") or [])
        if keys is not None and not set(keys) <= set(wire_keys):
            raise ValueError(
                f"wire plane in {self.directory} carries {wire_keys}, "
                f"caller asked for {list(keys)}")
        if self._wire_rd is None:
            from .spill import open_spill, wire_dir
            d = wire_dir(self.directory, wire_keys)
            rd, _ = open_spill(d, wire_keys,
                               self.schema.get("wireSignature"))
            if rd is None:
                from ..config.errors import ErrorCode, ShifuError
                raise ShifuError(
                    ErrorCode.ERROR_INPUT_NOT_FOUND,
                    f"{self.directory}: schema says direct-to-wire but "
                    f"the wire spill under {d} is missing, torn or "
                    "stale — re-run `norm` (or set "
                    "-Dshifu.norm.wireOnly=false to materialize npz)")
            self._wire_rd = rd
        rd = self._wire_rd
        return _WireView(rd, self._wire_base) if self._wire_base else rd

    def _iter_wire(self, start: int) -> Iterator[Dict[str, np.ndarray]]:
        from .. import faults
        from ..ioutil import io_retry
        rd = self.wire_reader()
        keys = list(self.schema.get("wireKeys") or [])
        for i in range(start, len(rd.shard_rows)):
            def _load(i=i):
                faults.fire("shards", "shard", i, path=self.directory)
                s, e = int(rd.cum[i]), int(rd.cum[i + 1])
                return {k: np.asarray(rd.memmap(k)[s:e]) for k in keys}
            yield io_retry(_load, "wire shard read", self.directory)

    def iter_shards(self, start: int = 0,
                    strict: bool = False) -> Iterator[Dict[str, np.ndarray]]:
        """Decode shards in order.  Opens ride the transient-IO retry
        ladder; with ``shifu.data.badThreshold`` > 0 an undecodable shard
        is quarantined (skipped + counted, provenance logged) as long as
        the quarantined fraction stays under the threshold.  ``strict``
        disables quarantine — the streaming window planes index rows by
        shard position and cannot tolerate a silently missing shard.
        Wire-mode planes serve the same per-shard dicts as mmap slices
        (consumers cannot tell which backing they got)."""
        from .. import faults, obs
        from ..config import environment
        from ..ioutil import io_retry
        if self.is_wire:
            yield from self._iter_wire(start)
            return
        bad_threshold = 0.0 if strict else \
            environment.get_float("shifu.data.badThreshold", 0.0)
        quarantined = 0
        for i, f in enumerate(self.files[start:], start=start):
            def _load(f=f, i=i):
                faults.fire("shards", "shard", i, path=f)
                return dict(np.load(f))
            try:
                yield io_retry(_load, "shard decode", f)
            except (OSError, ValueError, zipfile.BadZipFile) as e:
                if bad_threshold <= 0:
                    raise
                quarantined += 1
                # quarantine is the rare branch by definition —
                # bounded by shifu.data.badThreshold
                obs.counter("data.quarantined_shards").inc()  # shifu-lint: disable=telemetry-guard
                log.warning("quarantined undecodable shard %s: %s", f, e)
                if quarantined / max(len(self.files), 1) > bad_threshold:
                    from ..config.errors import ErrorCode, ShifuError
                    raise ShifuError(
                        ErrorCode.ERROR_BAD_DATA_THRESHOLD,
                        f"{quarantined}/{len(self.files)} shards "
                        f"quarantined exceeds shifu.data.badThreshold="
                        f"{bad_threshold}; last: {f} ({e})") from e

    def load_all(self) -> Dict[str, np.ndarray]:
        parts = list(self.iter_shards())
        if not parts:
            raise FileNotFoundError(f"no shards in {self.directory}")
        return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}

    def _sidecar_sig(self) -> List[List]:
        return [[os.path.basename(f), os.path.getsize(f)]
                for f in self.files]

    @property
    def shard_rows(self) -> List[int]:
        """Per-shard row counts without decoding shards: schema
        ``shardRows`` (norm writes it), else the sidecar manifest, else a
        one-time npy-header scan persisted back to the sidecar."""
        if self._shard_rows is not None:
            return self._shard_rows
        sr = self.schema.get("shardRows")
        if isinstance(sr, list) and (len(sr) == len(self.files)
                                     or self.is_wire):
            self._shard_rows = [int(x) for x in sr]
            return self._shard_rows
        if self.is_wire:               # schema missing counts: manifest
            self._shard_rows = [int(x)
                                for x in self.wire_reader().shard_rows]
            return self._shard_rows
        side = os.path.join(self.directory, ROWS_SIDECAR)
        sig = self._sidecar_sig()
        try:
            with open(side) as f:
                d = json.load(f)
            if d.get("source") == sig and len(d.get("rows", [])) == \
                    len(self.files):
                self._shard_rows = [int(x) for x in d["rows"]]
                return self._shard_rows
        except (OSError, ValueError):
            pass
        rows = [_npz_rows(f) for f in self.files]
        try:                       # best effort: dir may be read-only
            tmp = side + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"source": sig, "rows": rows}, f)
            os.replace(tmp, side)
        except OSError:
            pass
        self._shard_rows = rows
        return rows

    @property
    def num_rows(self) -> int:
        return sum(self.shard_rows)

    @property
    def n_shards(self) -> int:
        """Shard count.  Wire planes have no npz files, so ``len(files)``
        is always 0 there — every consumer comparing or iterating shard
        counts must go through here."""
        return len(self.shard_rows) if self.is_wire else len(self.files)

    def from_row(self, row: int) -> "Shards":
        """A view of this shard set starting at the shard containing
        global row ``row`` — the refresh loop's data-window cursor
        (shard-aligned, rounded DOWN so no row is ever skipped).  A
        cursor at/past the end keeps the LAST shard: with no new data
        the freshest window is still the right thing to train on."""
        if row <= 0 or self.n_shards == 0:
            return self
        rows = self.shard_rows
        cum, k = 0, len(rows) - 1
        for i, r in enumerate(rows):
            if cum + r > row:
                k = i
                break
            cum += r
        kept = [int(x) for x in rows[k:]]
        schema = dict(self.schema)
        if "shardRows" in schema:
            schema["shardRows"] = list(kept)
        if "numRows" in schema:
            schema["numRows"] = int(sum(kept))
        view = Shards(self.directory, schema, list(self.files[k:]))
        view._shard_rows = kept
        view._wire_base = self._wire_base + k
        view._wire_rd = self._wire_rd
        return view

    def source_signature(self) -> List[List]:
        """[(name, size, mtime_ns)] identity of the shard set — the spill
        cache's staleness check (re-running norm rewrites files and
        invalidates any spill built over them).  Wire planes pin the
        schema's wire signature instead (re-running norm rewrites it)."""
        if self.is_wire:
            return [["wire", self.schema.get("wireSignature")]]
        out = []
        for f in self.files:
            st = os.stat(f)
            out.append([os.path.basename(f), st.st_size, st.st_mtime_ns])
        return out
