"""Columnar streaming reader — the data plane's ingest edge.

Replaces the reference's Pig/HDFS ETL input path (``ShifuPigStorage``,
``CombineInputFormat``): delimited text shards (optionally gzipped) are
streamed chunk-by-chunk into columnar numpy arrays, ready to be binned /
normalized on device.  Directories of part files, single files, and glob
patterns are all accepted, mirroring the reference's part-file scanning
(``fs/ShifuFileUtils.java``).
"""

from __future__ import annotations

import glob
import gzip
import io
import logging
import os
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence

import numpy as np
import pandas as pd

log = logging.getLogger(__name__)


# Hadoop-cluster filesystems stay gated (no libhdfs in this runtime);
# object stores and any other fsspec scheme stream directly
_GATED_SCHEMES = ("hdfs://", "viewfs://", "arrow_hdfs://")


def _is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def _fsspec_paths(path: str):
    """(fs, expanded paths) for a remote URL — the ONE place that gates
    Hadoop schemes and converts fsspec's failures into coded errors."""
    from ..config.errors import ErrorCode, ShifuError
    for scheme in _GATED_SCHEMES:
        if path.startswith(scheme):
            raise ShifuError(
                ErrorCode.ERROR_REMOTE_SOURCE,
                f"{path!r}: no native {scheme[:-3]} client in this "
                "runtime — point dataPath at the cluster's WebHDFS "
                "gateway instead (webhdfs://namenode:9870/path streams "
                "directly), stage the files locally (hdfs dfs -get), or "
                "serve them from object storage (gs://, s3://)")
    import fsspec
    try:
        fs, _, paths = fsspec.get_fs_token_paths(path)
    except ImportError as e:                   # backend package missing
        raise ShifuError(
            ErrorCode.ERROR_REMOTE_SOURCE,
            f"{path!r}: the fsspec backend for this scheme is not "
            f"installed ({e}) — stage the files locally (gsutil -m cp -r "
            "/ aws s3 sync) and set the path to the local copy") from e
    except ValueError as e:                    # unknown protocol / bad URL
        raise ShifuError(
            ErrorCode.ERROR_REMOTE_SOURCE,
            f"{path!r}: {e} — use a known scheme (gs://, s3://, file://) "
            "or stage the files locally") from e
    return fs, paths


def _resolve_remote(data_path: str) -> List[str]:
    """Expand a remote (fsspec) path / directory / glob into full URLs.

    The reference's ``RawSourceData.SourceType`` HDFS duality
    (``fs/ShifuFileUtils.java``) becomes fsspec here: ``gs://`` / ``s3://``
    (object storage — where the 1TB-scenario data actually lives) and
    ``memory://`` (tests) stream straight into the columnar reader;
    pandas/pyarrow consume the URLs natively.  Hadoop filesystems remain a
    coded error — no libhdfs client in this runtime.
    """
    from ..config.errors import ErrorCode, ShifuError
    fs, paths = _fsspec_paths(data_path)
    proto = fs.protocol if isinstance(fs.protocol, str) else fs.protocol[0]

    def url(p: str) -> str:
        if "://" in p:
            return p
        if proto == "memory":                  # ls yields "/bucket/file"
            return f"memory://{p.lstrip('/')}"
        return f"{proto}://{p}"                # s3/gs ls yields bucket/key

    out: List[str] = []
    for p in paths:
        if fs.isdir(p):
            # ONE detail listing per directory: a per-entry isfile() would
            # cost an object-store round-trip per part file
            entries = fs.ls(p, detail=True)
            out.extend(
                url(e["name"]) for e in sorted(entries,
                                               key=lambda e: e["name"])
                if e.get("type") == "file"
                and not os.path.basename(e["name"]).startswith((".", "_")))
        elif fs.isfile(p):
            out.append(url(p))
    if not out:
        raise ShifuError(ErrorCode.ERROR_INPUT_NOT_FOUND, data_path)
    return out


def resolve_data_files(data_path: str) -> List[str]:
    """Expand a file / directory / glob into an ordered list of data files.

    Skips hidden files (``.pig_header``, ``_SUCCESS``), like the reference's
    part-file scanners.  Remote fsspec schemes (``gs://``, ``s3://``,
    ``memory://``, ...) resolve through :func:`_resolve_remote`; Hadoop
    filesystems are a coded error (stage locally or use object storage).
    """
    from ..config.errors import ErrorCode, ShifuError
    if _is_remote(data_path):
        return _resolve_remote(data_path)
    if data_path.startswith("file://"):
        data_path = data_path[len("file://"):]
    if os.path.isdir(data_path):
        files = [f for f in sorted(
            os.path.join(data_path, f) for f in os.listdir(data_path)
            if not f.startswith(".") and not f.startswith("_"))
            if os.path.isfile(f)]
        if not files:
            raise ShifuError(ErrorCode.ERROR_INPUT_NOT_FOUND,
                             f"{data_path} holds no data files (markers "
                             "like _SUCCESS are skipped)")
        return files
    if os.path.isfile(data_path):
        return [data_path]
    files = sorted(glob.glob(data_path))
    if not files:
        raise ShifuError(ErrorCode.ERROR_INPUT_NOT_FOUND, data_path)
    return files


def _path_exists(path: str) -> bool:
    if _is_remote(path):
        fs, paths = _fsspec_paths(path)
        return bool(paths) and fs.exists(paths[0])
    if path.startswith("file://"):
        path = path[len("file://"):]
    return os.path.isfile(path)


def read_header(header_path: Optional[str], header_delimiter: str,
                data_files: Optional[Sequence[str]] = None,
                data_delimiter: str = "|") -> List[str]:
    """Read column names from a header file, or fall back to the first data
    line (named or synthesized), reference ``InitModelProcessor`` behavior."""
    if header_path and _path_exists(header_path):
        with _open_text(header_path) as f:
            line = f.readline().rstrip("\r\n")
        return [c.strip() for c in line.split(header_delimiter)]
    if not data_files:
        raise ValueError("neither header file nor data files to infer header from")
    with _open_text(data_files[0]) as f:
        line = f.readline().rstrip("\r\n")
    fields = line.split(data_delimiter)
    # Heuristic: if no field parses as a number, treat the first row as header.
    def _is_num(s: str) -> bool:
        try:
            float(s)
            return True
        except ValueError:
            return False
    if any(_is_num(x) for x in fields):
        return [f"column_{i}" for i in range(len(fields))]
    return [c.strip() for c in fields]


def _open_text(path: str):
    if _is_remote(path):
        import fsspec
        _fsspec_paths(path)            # gate + coded errors first
        return fsspec.open(path, "rt", compression="infer",
                           encoding="utf-8", errors="replace").open()
    if path.startswith("file://"):
        path = path[len("file://"):]
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"), encoding="utf-8", errors="replace")
    return open(path, encoding="utf-8", errors="replace")


@dataclass
class RawChunk:
    """A chunk of raw rows in columnar string form."""
    columns: List[str]
    data: pd.DataFrame  # all-string columns, "" for empty

    def __len__(self) -> int:
        return len(self.data)

    def col(self, name: str) -> np.ndarray:
        return self.data[name].to_numpy()


class DataSource:
    """Streaming columnar reader over one dataset (dataPath + delimiter)."""

    def __init__(self, data_path: str, data_delimiter: str = "|",
                 header: Optional[List[str]] = None,
                 header_path: Optional[str] = None,
                 header_delimiter: str = "|"):
        self.files = resolve_data_files(data_path)
        self.delimiter = data_delimiter or "|"
        self.parquet = all(_is_parquet(f) for f in self.files) \
            and bool(self.files)
        if header is None:
            if self.parquet:
                header = _parquet_schema_names(self.files[0])
            else:
                header = read_header(header_path,
                                     header_delimiter or self.delimiter,
                                     self.files, self.delimiter)
        self.header = header

    def iter_chunks(self, chunk_rows: int = 262144) -> Iterator[RawChunk]:
        """Yield RawChunks of up to ``chunk_rows`` rows across all files.

        Transient ``OSError``s on shard open ride the bounded-retry
        ladder (``ioutil.io_retry``).  With ``shifu.data.badThreshold``
        > 0, structurally-bad input (wrong column count, unreadable
        file) is QUARANTINED — counted, logged with provenance, dropped
        — instead of aborting the run; the run still fails with a coded
        error if the quarantined fraction exceeds the threshold
        (reference Shifu's bad-record tolerance)."""
        from .. import faults, obs
        from ..config import environment
        from ..config.errors import ErrorCode, ShifuError
        from ..ioutil import io_retry
        # each call is one full raw-plane traversal — the e2e "how many
        # times did the pipeline re-read its input" metric (cache-served
        # passes never get here)
        obs.counter("ingest.disk_passes").inc()
        bytes_c = obs.counter("ingest.bytes_read")
        if self.parquet:
            yield from self._iter_parquet(chunk_rows)
            return
        bad_threshold = environment.get_float("shifu.data.badThreshold", 0.0)
        q_rows = obs.counter("data.quarantined_rows")
        q_shards = obs.counter("data.quarantined_shards")
        quarantined_rows = yielded_rows = quarantined_files = 0
        provenance: List[str] = []

        def quarantine(what: str, rows: int = 0, files: int = 0) -> None:
            nonlocal quarantined_rows, quarantined_files
            quarantined_rows += rows
            quarantined_files += files
            q_rows.inc(rows)
            q_shards.inc(files)
            provenance.append(what)
            log.warning("bad input quarantined: %s (%d rows, %d files "
                        "quarantined so far)", what,
                        quarantined_rows, quarantined_files)

        for fi, path in enumerate(self.files):
            try:                  # raw ingest accounting (stats/norm plane)
                if not _is_remote(path):
                    bytes_c.inc(os.path.getsize(path))
            except OSError:
                pass

            def _open(path=path, fi=fi):
                faults.fire("reader", "file", fi, path=path)
                return pd.read_csv(
                    path, sep=self.delimiter, engine="c", header=None,
                    names=self.header, dtype=str, chunksize=chunk_rows,
                    keep_default_na=False, na_filter=False, quoting=3,
                    on_bad_lines="skip", compression="infer")
            try:
                reader = io_retry(_open, "shard open", path)
            except OSError as e:
                if bad_threshold > 0:
                    quarantine(f"{path}: unreadable ({e})", files=1)
                    continue
                raise
            first = True
            try:
                for df in reader:
                    if first:
                        first = False
                        # drop a literal header row if present in the file
                        row0 = df.iloc[0].tolist()
                        if row0 == list(self.header):
                            df = df.iloc[1:]
                            if df.empty:
                                continue
                    if len(df.columns) != len(self.header):
                        code = ErrorCode.ERROR_EXCEED_COL \
                            if len(df.columns) > len(self.header) \
                            else ErrorCode.ERROR_LESS_COL
                        msg = (f"{path}: {len(df.columns)} fields vs "
                               f"{len(self.header)} header cols")
                        if bad_threshold > 0:
                            quarantine(msg, rows=len(df))
                            continue
                        raise ShifuError(code, msg)
                    yielded_rows += len(df)
                    yield RawChunk(columns=self.header, data=df)
            except (OSError, pd.errors.ParserError) as e:
                if bad_threshold <= 0:
                    raise
                quarantine(f"{path}: read died mid-stream ({e})", files=1)

        if quarantined_rows or quarantined_files:
            frac_rows = quarantined_rows / max(
                yielded_rows + quarantined_rows, 1)
            frac_files = quarantined_files / max(len(self.files), 1)
            if max(frac_rows, frac_files) > bad_threshold:
                raise ShifuError(
                    ErrorCode.ERROR_BAD_DATA_THRESHOLD,
                    f"quarantined {quarantined_rows} row(s) / "
                    f"{quarantined_files} file(s) exceeds "
                    f"shifu.data.badThreshold={bad_threshold}; first "
                    f"offender: {provenance[0]}")

    def _iter_parquet(self, chunk_rows: int) -> Iterator[RawChunk]:
        """Columnar parquet ingest (reference ``NNParquetWorker`` /
        ``GuaguaParquetMapReduceClient`` role): record batches stream
        straight out of the column chunks; values render to the pipeline's
        string plane with nulls as '' (the missing marker)."""
        for path in self.files:
            pf = _open_parquet(path)
            for batch in pf.iter_batches(batch_size=chunk_rows,
                                         columns=list(self.header)):
                # cast to string IN ARROW: int64 renders '1' regardless of
                # nulls in the batch (to_pandas would upcast nullable ints
                # to float64 and stringify '1.0' in some chunks only)
                import pyarrow as pa
                import pyarrow.compute as pc
                cols = {}
                for name, col in zip(batch.schema.names, batch.columns):
                    sc = pc.cast(col, pa.string())
                    cols[name] = pc.fill_null(sc, "").to_pandas()
                df = pd.DataFrame(cols, columns=self.header)
                yield RawChunk(columns=self.header, data=df)

    def read_all(self) -> RawChunk:
        dfs = [c.data for c in self.iter_chunks()]
        if not dfs:
            return RawChunk(self.header, pd.DataFrame({c: [] for c in self.header}, dtype=str))
        return RawChunk(self.header, pd.concat(dfs, ignore_index=True))


# ------------------------------------------------------------------ parsing
def record_field_str(v) -> str:
    """A JSON field value as the string cell the offline CSV reader would
    have produced — the raw-record serving path (`serve.transform`) and the
    offline parity oracle (`pipeline.evaluate.score_records_offline`) both
    stringify through HERE, then parse through the same
    :func:`parse_numeric` / ``ColumnBinner`` code, so missing markers and
    number grammar agree bit-for-bit between the two pipelines."""
    if v is None:
        return ""
    if isinstance(v, bool):
        return str(v)
    return v if isinstance(v, str) else repr(v)


def parse_numeric(values: np.ndarray, missing_values: Sequence[str] = ()) -> tuple:
    """Vectorized string->float parse.

    Returns ``(floats, valid_mask)`` where invalid/missing entries are NaN and
    masked out.  This is the analogue of the reference's per-value
    try/parse-with-missing-list (``NormalizeUDF``/``CalculateStatsUDF``).
    """
    s = pd.Series(values, dtype=str).str.strip()
    floats = pd.to_numeric(s, errors="coerce").to_numpy(dtype=np.float64)
    valid = ~np.isnan(floats)
    if len(missing_values):
        missing_set = {m.strip().lower() for m in missing_values}
        is_missing = s.str.lower().isin(missing_set).to_numpy()
        valid &= ~is_missing
        floats = np.where(is_missing, np.nan, floats)
    return floats, valid


def tag_to_target(values: np.ndarray, pos_tags: Sequence[str],
                  neg_tags: Sequence[str]) -> np.ndarray:
    """Map tag strings -> {1.0 pos, 0.0 neg, NaN neither}.

    Rows with unknown tags are later filtered, matching the reference's
    invalid-tag filtering in its UDF layer.
    """
    s = pd.Series(values, dtype=str).str.strip()
    pos = set(str(t).strip() for t in pos_tags)
    neg = set(str(t).strip() for t in neg_tags)
    out = np.full(len(s), np.nan, dtype=np.float64)
    out[s.isin(pos).to_numpy()] = 1.0
    if neg:
        out[s.isin(neg).to_numpy()] = 0.0
    elif len(pos):  # multi-class handled elsewhere; binary w/o negTags: rest=0
        out[(~s.isin(pos)).to_numpy()] = 0.0
    return out


def tag_to_class(values: np.ndarray, tags: Sequence[str]) -> np.ndarray:
    """Map tag strings -> float class index (position in ``tags``), NaN for
    unknown tags (filtered like binary invalid tags).

    Multi-class tagging per the reference convention (``ModelConfig.java:
    429-447`` getTags): posTags lists every class, negTags empty; the class
    id is the tag's position.
    """
    s = pd.Series(values, dtype=str).str.strip()
    out = np.full(len(s), np.nan, dtype=np.float64)
    for k, t in enumerate(tags):
        out[(s == str(t).strip()).to_numpy()] = float(k)
    return out


def parse_weight(values: Optional[np.ndarray], n: int) -> np.ndarray:
    if values is None:
        return np.ones(n, dtype=np.float64)
    w, valid = parse_numeric(values)
    w = np.where(valid & (w > 0), w, 1.0)
    return w


def _is_parquet(path: str) -> bool:
    return path.endswith((".parquet", ".pq"))


def _open_parquet(path: str):
    """A ParquetFile over local or fsspec-remote storage."""
    import pyarrow.parquet as pq
    if _is_remote(path):
        import fsspec
        _fsspec_paths(path)            # gate + coded errors first
        return pq.ParquetFile(fsspec.open(path, "rb").open())
    return pq.ParquetFile(path)


def _parquet_schema_names(path: str) -> List[str]:
    return list(_open_parquet(path).schema_arrow.names)
