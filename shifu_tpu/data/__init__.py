from .reader import (  # noqa: F401
    DataSource, RawChunk, parse_numeric, parse_weight, read_header,
    resolve_data_files, tag_to_target,
)
from .purifier import DataPurifier, sample_mask  # noqa: F401
