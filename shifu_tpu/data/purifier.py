"""Row filtering + sampling — analogue of reference ``core/DataPurifier.java``
(JEXL expressions) and ``core/DataSampler.java``.

Filter expressions are evaluated vectorized via ``pandas.eval`` over the
chunk's columns (numeric where parseable, else string), so
``"bad_num > 2 and is_fraud == 'T'"`` style expressions work.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import pandas as pd


class DataPurifier:
    def __init__(self, filter_expression: Optional[str]):
        self.expr = (filter_expression or "").strip()

    def mask(self, df: pd.DataFrame) -> np.ndarray:
        """Boolean keep-mask for the chunk; invalid expressions keep all rows
        (the reference logs and ignores bad filters)."""
        n = len(df)
        if not self.expr:
            return np.ones(n, dtype=bool)
        env = {}
        for col in df.columns:
            vals = df[col]
            num = pd.to_numeric(vals, errors="coerce")
            env[col] = num if not num.isna().all() else vals
        try:
            res = pd.eval(self.expr, local_dict=env)
            arr = np.asarray(res, dtype=bool)
            if arr.shape != (n,):
                return np.ones(n, dtype=bool)
            return arr
        except Exception:
            return np.ones(n, dtype=bool)


def sample_mask(n: int, rate: float, seed: int, neg_only: bool = False,
                targets: Optional[np.ndarray] = None) -> np.ndarray:
    """Bernoulli sampling mask; with ``neg_only`` positives are always kept
    (reference stats/norm ``sampleNegOnly`` semantics)."""
    if rate >= 1.0:
        return np.ones(n, dtype=bool)
    rng = np.random.default_rng(seed)
    keep = rng.random(n) < rate
    if neg_only and targets is not None:
        keep |= targets == 1.0
    return keep
