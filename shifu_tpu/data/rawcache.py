"""Columnar raw cache — parse the string plane ONCE per pipeline run.

stats, norm, varselect re-runs, posttrain and eval all stream the same
raw delimited text and re-run the same string→typed parse
(``extract.ChunkExtractor``) serially per step — the re-read-everything
shape of the reference's Pig chain.  This module is the spill-cache idea
(:mod:`shifu_tpu.data.spill`) applied one plane earlier: the FIRST full
extraction writes the decoded columns into flat raw files next to a
``manifest.json`` commit point, and every later step streams ``np.memmap``
slices instead of touching the string plane at all.

Layout under ``<tmp>/RawCache/``::

    manifest.json      commit point (version, row identity, columns,
                       per-chunk row counts, categorical vocabularies,
                       source signature, bytes; ``aborted`` marker on a
                       permanent budget abort)
    target.raw         float64 [rows]
    weight.raw         float64 [rows]
    numeric.raw        float64 [rows, C_num]     (NaN = missing)
    numeric_valid.raw  bool    [rows, C_num]
    kept_idx.raw       int64   [rows]   positional raw-row index of each
                                        kept row within its chunk
    cat-<j>.raw        int32   [rows]   vocabulary codes, column j

Cached payload is the FULL (unsampled) extraction plus per-chunk
``raw_rows`` — every row-wise op in the extractor commutes with row
subsetting, so a consumer's pre-parse Bernoulli sample replays from
``kept_idx`` bit-identically (see ``parsepool.subsample_extracted``).
Categorical values store as vocabulary codes (the reader decodes back to
the exact string arrays the extractor produced — the raw plane is pure
strings by construction, ``reader.DataSource``).

Semantics mirror the spill cache: staleness pins the source-file
``(name, size, mtime_ns)`` signature plus the extractor's row identity;
writers append under a process-unique tmp suffix and commit raw renames
then the manifest (``faults rawcache:commit`` fires at that boundary), so
readers never observe a torn cache — a crash mid-commit leaves only tmp
files the next writer sweeps; ``shifu.ingest.rawCacheBudgetBytes``
overflow aborts once and leaves a permanent ``aborted`` marker.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .extract import ChunkExtractor, ExtractedChunk
from .spill import _tmp_suffix

log = logging.getLogger(__name__)

MANIFEST = "manifest.json"
RAWCACHE_FORMAT_VERSION = 1

_FIXED = (("target", np.dtype(np.float64), 0),
          ("weight", np.dtype(np.float64), 0),
          ("kept_idx", np.dtype(np.int64), 0))


def raw_cache_enabled() -> bool:
    from ..config import environment
    return environment.get_bool("shifu.ingest.rawCache", True)


def raw_cache_budget_bytes() -> int:
    from ..config import environment
    return environment.get_int("shifu.ingest.rawCacheBudgetBytes", 1 << 33)


def source_signature(files: Sequence[str]) -> List[List]:
    """[(name, size, mtime_ns)] identity of the raw input files — same
    convention as the spill cache / norm journal signatures."""
    out: List[List] = []
    for f in files:
        try:
            st = os.stat(f)
            out.append([os.path.basename(f), st.st_size, st.st_mtime_ns])
        except OSError:                        # remote URL: pin by name
            out.append([f, None, None])
    return out


def _sweep_tmp(directory: str) -> None:
    """Remove torn tmp segments a killed writer left behind (never
    half-read: absent manifest == absent cache)."""
    try:
        for f in os.listdir(directory):
            if ".tmp-" in f:
                try:
                    os.remove(os.path.join(directory, f))
                except OSError:
                    pass
    except OSError:
        pass


class RawCacheWriter:
    """Write-through raw cache built during one full-extraction pass.

    Unlike ``SpillWriter`` the dtypes are FIXED by the extraction contract
    (f64 numerics, i64 kept_idx, i32 codes) — no first-shard narrowing, no
    mid-stream outgrow abort; only the budget abort is shared."""

    def __init__(self, directory: str, extractor: ChunkExtractor,
                 source_sig, chunk_rows: int, budget_bytes: int):
        self.directory = directory
        self.sig = source_sig
        self.chunk_rows = int(chunk_rows)
        self.budget = int(budget_bytes)
        self.row_identity = extractor.row_identity()
        self.numeric_names = [c.columnName for c in extractor.numeric_cols]
        self.cat_names = [c.columnName for c in extractor.categorical_cols]
        self._suffix = _tmp_suffix()
        self._files: Dict[str, object] = {}
        self._vocab_maps: List[Dict[str, int]] = [
            {} for _ in self.cat_names]
        self._chunk_kept: List[int] = []
        self._chunk_raw: List[int] = []
        self._rows = 0
        self._bytes = 0
        self._dead = False
        os.makedirs(directory, exist_ok=True)
        _sweep_tmp(directory)

    def _keys(self) -> List[str]:
        return ([k for k, _, _ in _FIXED] + ["numeric", "numeric_valid"]
                + [f"cat-{j}" for j in range(len(self.cat_names))])

    def _raw_path(self, key: str) -> str:
        return os.path.join(self.directory, key + ".raw")

    def append(self, ex: ExtractedChunk) -> bool:
        """Append one chunk's full extraction.  Returns False once the
        cache is abandoned (budget / IO error) — the caller keeps
        parsing, unaffected."""
        if self._dead:
            return False
        try:
            if not self._files:
                for k in self._keys():
                    self._files[k] = open(self._raw_path(k) + self._suffix,
                                          "wb")
            import pandas as pd
            parts: Dict[str, np.ndarray] = {
                "target": np.ascontiguousarray(ex.target, np.float64),
                "weight": np.ascontiguousarray(ex.weight, np.float64),
                "kept_idx": np.ascontiguousarray(ex.kept_idx, np.int64),
                "numeric": np.ascontiguousarray(ex.numeric, np.float64),
                "numeric_valid": np.ascontiguousarray(
                    ex.numeric_valid, np.bool_)}
            for j, name in enumerate(self.cat_names):
                vmap = self._vocab_maps[j]
                s = pd.Series(ex.categorical[name], dtype=object)
                codes = s.map(vmap)
                na = codes.isna()
                if bool(na.any()):
                    for v in pd.unique(s[na]):
                        vmap[v] = len(vmap)
                    codes = s.map(vmap)
                parts[f"cat-{j}"] = np.ascontiguousarray(
                    codes.to_numpy(np.int64), np.int32)
            nb = sum(a.nbytes for a in parts.values())
            if self._bytes + nb > self.budget:
                self.abort(mark=f"budget {self.budget} bytes exceeded at "
                                f"row {self._rows}")
                return False
            for k, a in parts.items():
                a.tofile(self._files[k])
            self._rows += ex.n
            self._bytes += nb
            self._chunk_kept.append(int(ex.n))
            self._chunk_raw.append(int(ex.raw_rows))
            return True
        except OSError:
            self.abort()
            return False

    def finish(self) -> bool:
        """Commit: raw renames, then the manifest (the commit point)."""
        if self._dead:
            return False
        try:
            from .. import faults, obs
            from ..ioutil import io_retry
            for f in self._files.values():
                f.close()
            for k in self._files:
                os.replace(self._raw_path(k) + self._suffix,
                           self._raw_path(k))
            man = {"version": RAWCACHE_FORMAT_VERSION,
                   "rowIdentity": self.row_identity,
                   "numericCols": self.numeric_names,
                   "categoricalCols": self.cat_names,
                   "vocabs": [sorted(m, key=m.get)
                              for m in self._vocab_maps],
                   "rows": self._rows,
                   "chunkKept": self._chunk_kept,
                   "chunkRaw": self._chunk_raw,
                   "chunkRows": self.chunk_rows,
                   "bytes": self._bytes,
                   "source": self.sig}
            tmp = os.path.join(self.directory, MANIFEST + self._suffix)

            def write():
                faults.fire("rawcache", "commit", 0, path=tmp)
                with open(tmp, "w") as f:
                    json.dump(man, f)
                os.replace(tmp, os.path.join(self.directory, MANIFEST))
            io_retry(write, "raw cache manifest commit", self.directory)
            obs.counter("rawcache.bytes_written").inc(self._bytes)
            self._dead = True
            return True
        except OSError:
            self.abort()
            return False

    def abort(self, mark: Optional[str] = None) -> None:
        """Drop the half-written cache; ``mark`` records a permanent
        reason (budget) so later passes don't re-attempt."""
        if self._dead:
            return
        self._dead = True
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        for k in self._files:
            try:
                os.remove(self._raw_path(k) + self._suffix)
            except OSError:
                pass
        if mark:
            try:
                from ..ioutil import io_retry
                man = {"version": RAWCACHE_FORMAT_VERSION,
                       "rowIdentity": self.row_identity,
                       "aborted": mark,
                       "source": self.sig}
                tmp = os.path.join(self.directory, MANIFEST + self._suffix)

                def write():
                    with open(tmp, "w") as f:
                        json.dump(man, f)
                    os.replace(tmp, os.path.join(self.directory, MANIFEST))
                io_retry(write, "raw cache abort marker", self.directory)
            except OSError:
                pass


class RawCacheReader:
    """mmap view over a committed raw cache; serves ``ExtractedChunk``s
    for any extractor whose columns are a subset of the cached set."""

    def __init__(self, directory: str, man: dict):
        self.directory = directory
        self.man = man
        self.rows = int(man["rows"])
        self.chunk_kept = [int(x) for x in man["chunkKept"]]
        self.chunk_raw = [int(x) for x in man["chunkRaw"]]
        self.numeric_names = list(man["numericCols"])
        self.cat_names = list(man["categoricalCols"])
        self.vocab_arrays = [np.asarray(v, dtype=object)
                             for v in man["vocabs"]]
        self.cum = np.concatenate(
            [[0], np.cumsum(self.chunk_kept)]).astype(np.int64)
        self._mms: Dict[str, np.memmap] = {}

    @property
    def n_chunks(self) -> int:
        return len(self.chunk_kept)

    def _memmap(self, key: str, dtype: np.dtype, trailing: int) -> np.memmap:
        mm = self._mms.get(key)
        if mm is None:
            from ..ioutil import io_retry
            shape = (self.rows, trailing) if trailing else (self.rows,)
            path = os.path.join(self.directory, key + ".raw")
            mm = io_retry(
                lambda: np.memmap(path, dtype=dtype, mode="r", shape=shape),
                "raw cache mmap open", path)
            try:
                mm._mmap.madvise(mmap.MADV_SEQUENTIAL)
            except (AttributeError, ValueError, OSError):
                pass
            self._mms[key] = mm
        return mm

    def serves(self, extractor: ChunkExtractor) -> bool:
        """True when this cache can stand in for ``extractor``'s parse:
        row identity matches exactly and the requested columns are a
        subset of the cached set."""
        if self.man.get("rowIdentity") != extractor.row_identity():
            return False
        cached_num = set(self.numeric_names)
        cached_cat = set(self.cat_names)
        return (all(c.columnName in cached_num
                    for c in extractor.numeric_cols)
                and all(c.columnName in cached_cat
                        for c in extractor.categorical_cols))

    def chunk(self, ci: int, extractor: ChunkExtractor) -> ExtractedChunk:
        """Rebuild chunk ``ci``'s full extraction for ``extractor`` —
        values bit-identical to a fresh parse (parses are element-wise;
        codes decode to the exact cached strings)."""
        s, e = int(self.cum[ci]), int(self.cum[ci + 1])
        n = e - s
        C = len(self.numeric_names)
        target = np.asarray(self._memmap(
            "target", np.dtype(np.float64), 0)[s:e])
        weight = np.asarray(self._memmap(
            "weight", np.dtype(np.float64), 0)[s:e])
        kept_idx = np.asarray(self._memmap(
            "kept_idx", np.dtype(np.int64), 0)[s:e])
        if extractor.numeric_cols:
            cols = [self.numeric_names.index(c.columnName)
                    for c in extractor.numeric_cols]
            num_all = np.asarray(self._memmap(
                "numeric", np.dtype(np.float64), C)[s:e])
            val_all = np.asarray(self._memmap(
                "numeric_valid", np.dtype(np.bool_), C)[s:e])
            numeric = np.ascontiguousarray(num_all[:, cols])
            numeric_valid = np.ascontiguousarray(val_all[:, cols])
        else:
            numeric = np.zeros((n, 0))
            numeric_valid = np.zeros((n, 0), dtype=bool)
        categorical: Dict[str, np.ndarray] = {}
        for cc in extractor.categorical_cols:
            j = self.cat_names.index(cc.columnName)
            codes = np.asarray(self._memmap(
                f"cat-{j}", np.dtype(np.int32), 0)[s:e])
            categorical[cc.columnName] = self.vocab_arrays[j][codes] \
                if len(self.vocab_arrays[j]) else \
                np.empty(n, dtype=object)
        return ExtractedChunk(
            n=n, target=target, weight=weight, numeric=numeric,
            numeric_valid=numeric_valid,
            numeric_cols=extractor.numeric_cols, categorical=categorical,
            categorical_cols=extractor.categorical_cols, raw=None,
            kept_idx=kept_idx, raw_rows=int(self.chunk_raw[ci]))


def open_raw_cache(directory: str, source_sig,
                   extractor: ChunkExtractor,
                   chunk_rows: int) -> Tuple[Optional[RawCacheReader], bool]:
    """(reader, writable): ``reader`` is a committed cache that serves
    ``extractor``, or None; ``writable`` says whether a cold pass should
    (re)build one — False when a marker records a permanent abort for
    this exact source, or when a valid cache exists for this source that
    just doesn't cover the requested columns (rebuilding would thrash)."""
    path = os.path.join(directory, MANIFEST)

    def read():
        if not os.path.isfile(path):   # absence is final, not transient
            return None
        with open(path) as f:
            return json.load(f)
    try:
        from ..ioutil import io_retry
        man = io_retry(read, "raw cache manifest read", path)
        if man is None:
            return None, True
    except (OSError, ValueError):
        return None, True
    if man.get("version") != RAWCACHE_FORMAT_VERSION \
            or man.get("source") != source_sig:
        return None, True                      # stale source
    if man.get("aborted"):
        return None, False
    try:
        if int(man.get("chunkRows", 0)) != int(chunk_rows):
            return None, True
        rd = RawCacheReader(directory, man)
        rows, C = rd.rows, len(rd.numeric_names)
        sizes = [("target", 8), ("weight", 8), ("kept_idx", 8),
                 ("numeric", 8 * max(C, 0)), ("numeric_valid", max(C, 0))]
        sizes += [(f"cat-{j}", 4) for j in range(len(rd.cat_names))]
        for key, row_bytes in sizes:
            if rows and row_bytes and os.path.getsize(
                    os.path.join(directory, key + ".raw")) \
                    < rows * row_bytes:
                return None, True              # torn raw file
        if not rd.serves(extractor):
            # committed + fresh but the column set doesn't cover this
            # consumer: don't rebuild over a cache other steps still use
            return None, False
        return rd, False
    except (OSError, KeyError, ValueError, TypeError):
        return None, True
