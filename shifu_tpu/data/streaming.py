"""Out-of-core streaming data plane — the ``MemoryDiskFloatMLDataSet``
replacement (reference ``core/dtrain/dataset/MemoryDiskFloatMLDataSet.java:
54-99,315-361``: fill heap to a fraction, spill to disk, chain iterators).

TPU-native shape: the dataset never has to fit anywhere.  A ``ShardStream``
re-batches npz shards into fixed-size row windows (one compiled program shape)
while a background thread prefetches the next shard from disk, so the device
computes while the host reads.  Epoch = one pass over all windows.

Sampling masks cannot be materialized ``[bags, n_rows]`` when n_rows is
unbounded, so ``window_member_masks`` derives every row's bag/validation
assignment STATELESSLY from (seed, member, global row index) via a splitmix64
hash — any window of rows can be masked independently and reproducibly,
replacing the reference's load-time per-record assignment
(``AbstractNNWorker.java:668-716``).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, Optional, Sequence, Tuple

import numpy as np

from .. import obs
from .shards import Shards


def stream_prefetch_depth(override=None) -> int:
    """Prefetch/pipeline depth for shard streams: explicit override >
    env ``SHIFU_TPU_PREFETCH`` > property ``-Dshifu.stream.prefetch=N``
    > default 2.  Depth bounds both the shard read-ahead queue and the
    prepared-window (H2D double-buffer) queue."""
    if override is not None:
        try:
            return max(0, int(override))
        except (TypeError, ValueError):
            pass
    v = os.environ.get("SHIFU_TPU_PREFETCH")
    if v:
        try:
            return max(0, int(v))
        except ValueError:
            pass
    from ..config import environment
    return max(0, environment.get_int("shifu.stream.prefetch", 2))


def pipeline_depth_for(mesh) -> Optional[int]:
    """Pipelined window prep (background-thread masks + device_put) is
    single-device only: a second thread dispatching programs against a
    multi-device CPU mesh can interleave two collective programs, the
    known XLA:CPU in-process rendezvous deadlock.  None = the stream's
    prefetch depth.  Shared by every streamed plane (trees, varselect,
    genetic wrapper) — per-plane copies had already drifted once."""
    if mesh is not None and getattr(mesh, "size", 1) > 1:
        return 0
    return None


def should_stream(shards, schema: Optional[dict] = None) -> bool:
    """THE resident-vs-streamed decision every plane shares (train NN/WDL,
    varselect sensitivity, genetic wrapper): stream out-of-core when the
    f32 norm plane would not fit ``shifu.train.memoryBudgetBytes``;
    forced either way via ``-Dshifu.train.streaming=on|off``."""
    from ..config import environment
    mode = (environment.get_property("shifu.train.streaming", "auto")
            or "auto").lower()
    if mode in ("on", "true", "force"):
        return True
    if mode in ("off", "false"):
        return False
    schema = schema if schema is not None else getattr(shards, "schema", {})
    budget = environment.get_int("shifu.train.memoryBudgetBytes", 1 << 31)
    width = len(schema.get("outputNames") or []) or 1
    n_rows = schema.get("numRows") or shards.num_rows
    return n_rows * 4 * (width + 2) > budget

# ------------------------------------------------------------ hash uniforms
_U64 = np.uint64


def _splitmix64(z: np.ndarray) -> np.ndarray:
    z = (z + _U64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> _U64(27))) * _U64(0x94D049BB133111EB)
    return z ^ (z >> _U64(31))


def row_uniform(seed: int, stream: int, idx: np.ndarray) -> np.ndarray:
    """Deterministic uniforms in [0,1) keyed by (seed, stream, row index)."""
    with np.errstate(over="ignore"):
        key = _splitmix64(_U64(seed & 0xFFFFFFFF) * _U64(0x100000001B3)
                          + _U64(stream & 0xFFFFFFFF))
        z = _splitmix64(np.asarray(idx, _U64) ^ key)
    return (z >> _U64(11)).astype(np.float64) * (1.0 / (1 << 53))


def _hash_poisson(lam: float, u: np.ndarray, kmax: int = 16) -> np.ndarray:
    """Poisson(lam) counts via inverse CDF on hash uniforms (lam <= ~4)."""
    out = np.zeros(u.shape, np.float32)
    p = np.exp(-lam)
    cdf = np.full(u.shape, p)
    term = p
    for k in range(1, kmax + 1):
        out += (u >= cdf).astype(np.float32)
        term = term * lam / k
        cdf = cdf + term
    return out


def window_member_masks(idx: np.ndarray, bags: int, *, valid_rate: float,
                        kfold: int = -1, sample_rate: float = 1.0,
                        replacement: bool = False,
                        up_sample_weight: float = 1.0,
                        targets: Optional[np.ndarray] = None,
                        seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """(train_w, valid_w): [bags, len(idx)] row weights for a row window.

    Streaming analogue of ``train.sampling.member_masks``: same semantics
    (k-fold partition / shared validation split + Poisson-or-Bernoulli
    bagging / up-sampling) but every assignment is a pure function of the
    global row index, so windows mask independently.  Stratified validation
    degrades to plain Bernoulli(valid_rate) — exact per-class counts need a
    global pass, which streaming by definition doesn't have.
    """
    idx = np.asarray(idx)
    m = len(idx)
    if kfold and kfold > 1:
        fold = (row_uniform(seed, 101, idx) * kfold).astype(np.int64) % kfold
        valid_w = np.stack([(fold == i).astype(np.float32) for i in range(kfold)])
        train_w = 1.0 - valid_w
    else:
        vmask = row_uniform(seed, 11, idx) < valid_rate
        if bags == 1 and sample_rate >= 1.0 and not replacement:
            bag_w = np.ones((1, m), np.float32)
        else:
            bag_w = np.empty((bags, m), np.float32)
            for b in range(bags):
                u = row_uniform(seed, 1000 + b, idx)
                bag_w[b] = _hash_poisson(sample_rate, u) if replacement \
                    else (u < sample_rate).astype(np.float32)
        train_w = bag_w * (~vmask)[None, :]
        valid_w = np.broadcast_to(vmask.astype(np.float32),
                                  (bags, m)).copy()
    if up_sample_weight != 1.0 and targets is not None:
        train_w = train_w * np.where(targets > 0.5, up_sample_weight,
                                     1.0)[None, :]
    return train_w.astype(np.float32), valid_w.astype(np.float32)


# ----------------------------------------------------------------- windows
@dataclass
class Window:
    """A fixed-size row window.  Arrays are padded to ``rows``; padded rows
    have zero ``w`` (and must be ignored via weights by every consumer)."""
    start: int                       # global index of first (real) row
    n_valid: int                     # real rows (<= rows)
    arrays: Dict[str, np.ndarray]    # each [rows, ...]
    src: Optional[Tuple[int, int]] = None   # (shard idx, row offset) of row 0

    @property
    def rows(self) -> int:
        return len(next(iter(self.arrays.values())))

    @property
    def index(self) -> np.ndarray:
        """Global row indices (padded tail gets past-the-end ids)."""
        return np.arange(self.start, self.start + self.rows)


class ShardStream:
    """Windowed, prefetching iterator over npz shards — with an mmap
    spill-cache fast path for every sweep after the first.

    - ``window_rows`` fixes every emitted window's row count (jit-stable
      shapes; the last window is zero-padded).
    - the FIRST full pass reads npz on a daemon thread (a bounded queue
      ``prefetch`` deep overlaps disk IO with consumption) and spills the
      selected columns into flat raw files (:mod:`shifu_tpu.data.spill`);
      every later pass — including the ResidentCache's per-level tail
      re-streams — is pure ``np.memmap`` slicing: no zip decode, no
      reader thread, no copies until the bytes are consumed.
    - ``keys`` selects which arrays to materialize (e.g. ``("x","y","w")``
      for the NN path, ``("bins","y","w")`` for trees).  Integer columns
      re-emerge from the spill in the compact wire dtype (uint8 for
      <=256 bins) — values identical, 2-4x fewer bytes touched.
    """

    def __init__(self, shards: Shards, keys: Sequence[str],
                 window_rows: int, prefetch: Optional[int] = None,
                 spill: Optional[bool] = None,
                 remainder_multiple: int = 0):
        from .spill import spill_enabled
        assert window_rows > 0
        self.shards = shards
        self.keys = tuple(keys)
        self.window_rows = int(window_rows)
        # shape-stable remainder handling (> 0 enables): the LAST partial
        # window pads to the smallest W/2^k rung (k <= 3, rungs kept
        # multiples of ``remainder_multiple`` — the mesh data-axis size —
        # so sharding still divides) that covers its real rows, instead
        # of the full W.  At most 3 extra static shapes ever exist (one
        # per rung, and a given dataset only produces ONE tail shape), so
        # consumers pay at most one extra compile while ingest.rows_padded
        # drops by up to 8x on the tail.  0 keeps the old full-W pad.
        self.remainder_multiple = int(remainder_multiple)
        self.prefetch = stream_prefetch_depth(prefetch)
        self.spill = spill_enabled() if spill is None else bool(spill)
        self._spill_off = False         # sticky: aborted marker / IO error
        self._spill_rd = None           # validated SpillReader
        self.bytes_read = 0             # host-side total across sweeps
                                        # (always on — bench/guard tests
                                        # read it without telemetry)

    # ------------------------------------------------------ spill plumbing
    def _spill_dir(self) -> str:
        from .spill import spill_dir_for
        return spill_dir_for(self.shards.directory, self.keys)

    def _spill_reader(self):
        if self._spill_rd is not None:
            return self._spill_rd
        # direct-to-wire shard sets ARE a spill: serve them mmap-first,
        # regardless of the spill knob (there are no npz to stream and
        # nothing to write through — the wire is the dataset)
        wire = self.shards.wire_reader(self.keys) \
            if hasattr(self.shards, "wire_reader") else None
        if wire is not None:
            self._spill_rd = wire
            return wire
        if not self.spill or self._spill_off:
            return None
        from .spill import open_spill
        try:
            rd, writable = open_spill(self._spill_dir(), self.keys,
                                      self.shards.source_signature())
        except OSError:
            self._spill_off = True
            return None
        if rd is not None:
            self._spill_rd = rd
        elif not writable:
            self._spill_off = True      # permanent abort marker on disk
        return rd

    def _spill_writer(self):
        """A writer for the cold pass, or None (disabled / already built /
        permanently aborted)."""
        if not self.spill or self._spill_off or self._spill_rd is not None:
            return None
        from .spill import SpillWriter, spill_budget_bytes
        try:
            return SpillWriter(self._spill_dir(), self.keys,
                               self.shards.source_signature(),
                               spill_budget_bytes())
        except OSError:
            self._spill_off = True
            return None

    # background shard reader (cold npz path); the spill write-through
    # happens HERE, off the consumer's critical path
    def _reader(self, q: "queue.Queue", stop: threading.Event,
                start_shard: int, shard_offset: int, writer=None) -> None:
        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False
        try:
            for si, part in enumerate(
                    self.shards.iter_shards(start_shard, strict=True)):
                item = {k: part[k] for k in self.keys}
                if writer is not None and not writer.append(item):
                    writer = None             # abandoned; keep streaming
                if si == 0 and shard_offset:
                    item = {k: v[shard_offset:] for k, v in item.items()}
                if not put((start_shard + si, shard_offset if si == 0 else 0,
                            item)):
                    if writer is not None:
                        writer.abort()        # consumer abandoned mid-epoch
                    return
            if writer is not None:
                writer.finish()
            put(None)
        except BaseException as e:  # surface IO errors on the consumer side
            if writer is not None:
                writer.abort()
            put(e)

    def windows(self, start_shard: int = 0, shard_offset: int = 0,
                start_row: int = 0) -> Iterator[Window]:
        """Window the shard sequence.  The three offsets resume mid-dataset
        (the ResidentCache tail: skip fully-cached shard files entirely,
        slice into the first partial one, keep global row ids aligned).
        A committed spill serves the whole call by mmap slicing."""
        rd = self._spill_reader()
        if rd is not None:
            g0 = rd.global_of(start_shard, shard_offset)
            if g0 is not None:
                obs.counter("ingest.spill_hits").inc()
                yield from self._windows_mmap(rd, g0, start_row)
                return
        obs.counter("ingest.spill_misses").inc()
        yield from self._windows_npz(start_shard, shard_offset, start_row)

    def _tail_rows(self, buffered: int) -> int:
        """Padded row count for the final partial window: the smallest
        remainder-ladder rung covering ``buffered`` (see __init__), or
        the full window when the ladder is off / nothing smaller fits."""
        w = self.window_rows
        m = self.remainder_multiple
        if m <= 0 or buffered >= w:
            return w
        rung, r = w, w // 2
        for _ in range(3):
            if r < max(m, buffered) or r % m:
                break
            rung, r = r, r // 2
        return rung

    def _windows_mmap(self, rd, g0: int, start_row: int) -> Iterator[Window]:
        """Serve windows as raw-file slices — the hot path for every sweep
        after the first (src/start bookkeeping identical to the npz path,
        so ResidentCache tail resumes are oblivious to which path ran)."""
        W = self.window_rows
        if rd.rows <= g0:
            return
        mms = {k: rd.memmap(k) for k in self.keys}
        bytes_c = obs.counter("ingest.bytes_read")
        win_c = obs.counter("ingest.windows_emitted")
        rows_c = obs.counter("ingest.rows_emitted")
        pad_c = obs.counter("ingest.rows_padded")
        start, g = start_row, g0
        while g < rd.rows:
            e = min(g + W, rd.rows)
            arrays = {k: np.asarray(mms[k][g:e]) for k in self.keys}
            nv = e - g
            if nv < W:
                rows = self._tail_rows(nv)
                arrays = {k: _pad_rows(a, rows) for k, a in arrays.items()}
                pad_c.inc(rows - nv)
            nb = sum(a.nbytes for a in arrays.values())
            bytes_c.inc(nb)
            self.bytes_read += nb
            win_c.inc()
            rows_c.inc(nv)
            yield Window(start=start, n_valid=nv, arrays=arrays,
                         src=rd.src_of(g))
            start += W
            g += W

    def _windows_npz(self, start_shard: int = 0, shard_offset: int = 0,
                     start_row: int = 0) -> Iterator[Window]:
        writer = self._spill_writer() \
            if (start_shard == 0 and shard_offset == 0) else None
        q: "queue.Queue" = queue.Queue(maxsize=max(1, self.prefetch))
        stop = threading.Event()
        t = threading.Thread(target=self._reader,
                             args=(q, stop, start_shard, shard_offset,
                                   writer),
                             daemon=True)
        t.start()
        try:
            buf: Dict[str, list] = {k: [] for k in self.keys}
            # (shard idx, offset of first unconsumed row, rows left) per
            # buffered source chunk — gives each window its (shard, offset)
            sources: list = []
            buffered = 0
            start = start_row
            W = self.window_rows
            bytes_c = obs.counter("ingest.bytes_read")
            win_c = obs.counter("ingest.windows_emitted")
            rows_c = obs.counter("ingest.rows_emitted")

            def consume(rows: int) -> Tuple[int, int]:
                """Pop ``rows`` rows off the source list; return the (shard,
                offset) of the first popped row."""
                src = (sources[0][0], sources[0][1])
                left = rows
                while left > 0 and sources:
                    si, off, n = sources[0]
                    take = min(left, n)
                    left -= take
                    if take == n:
                        sources.pop(0)
                    else:
                        sources[0] = (si, off + take, n - take)
                return src

            while True:
                item = q.get()
                if isinstance(item, BaseException):
                    raise item
                if item is None:
                    break
                si, off, part = item
                n = len(next(iter(part.values())))
                if n == 0:
                    continue
                for k in self.keys:
                    buf[k].append(part[k])
                sources.append((si, off, n))
                buffered += n
                while buffered >= W:
                    arrays, buf, buffered = _take(buf, W, self.keys)
                    nb = sum(a.nbytes for a in arrays.values())
                    bytes_c.inc(nb)
                    self.bytes_read += nb
                    win_c.inc()
                    rows_c.inc(W)
                    yield Window(start=start, n_valid=W, arrays=arrays,
                                 src=consume(W))
                    start += W
            if buffered:
                arrays, buf, _ = _take(buf, buffered, self.keys)
                rows = self._tail_rows(buffered)
                arrays = {k: _pad_rows(a, rows) for k, a in arrays.items()}
                # padding waste surface for the utilization report: rows
                # the device computes over that carry zero weight
                obs.counter("ingest.rows_padded").inc(rows - buffered)
                nb = sum(a.nbytes for a in arrays.values())
                bytes_c.inc(nb)
                self.bytes_read += nb
                win_c.inc()
                rows_c.inc(buffered)
                yield Window(start=start, n_valid=buffered,
                             arrays=arrays, src=consume(buffered))
        finally:
            # unblock + retire the reader even when the generator is
            # abandoned mid-iteration (jit error, early stop, interrupt);
            # JOIN it so no daemon thread survives into interpreter
            # shutdown (a live thread racing stdio finalization is a
            # "Fatal Python error: _enter_buffered_busy" waiting to happen)
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    def prepared(self, prepare: Callable[["Window"], "PreparedWindow"],
                 start_shard: int = 0, shard_offset: int = 0,
                 start_row: int = 0,
                 depth: Optional[int] = None) -> Iterator["PreparedWindow"]:
        """Pipelined window prep + H2D double-buffering: window assembly
        AND the trainer's ``prepare`` hook (hash masks, host stacking,
        ``jax.device_put``) run on a background thread, ``depth`` windows
        ahead of the consumer — the put for window N+1 is issued while
        window N's executable runs, so the fixed per-put protocol cost
        and host prep overlap device compute instead of serializing with
        it (the TF-sys / sync-SGD input-pipelining prescription).

        ``depth=None`` uses the stream's prefetch depth; ``depth<=0``
        runs inline (multi-device CPU meshes must stay inline: a second
        thread dispatching collective programs can interleave two mesh
        programs, the known XLA:CPU rendezvous deadlock).  Time the
        consumer spends blocked on the queue lands in the
        ``ingest.h2d_wait_seconds`` counter — the ingest stall the
        telemetry report surfaces."""
        depth = self.prefetch if depth is None else int(depth)

        def _prep(win: "Window") -> "PreparedWindow":
            item = prepare(win)
            if getattr(item, "src", None) is None:
                try:
                    item.src = win.src    # tail bookkeeping (ResidentCache)
                except AttributeError:
                    pass
            return item

        if depth <= 0:
            # inline: every second of window fetch + prep IS consumer
            # stall — record it so the report's stall line still reads
            # true on rigs that must prep inline (multi-device CPU mesh)
            wait_c = obs.counter("ingest.h2d_wait_seconds")
            it = self.windows(start_shard, shard_offset, start_row)
            while True:
                t0 = time.perf_counter()
                with obs.span("ingest.window_prep"):
                    win = next(it, None)
                    if win is None:
                        return
                    item = _prep(win)
                wait_c.inc(time.perf_counter() - t0)
                yield item
            return

        q: "queue.Queue" = queue.Queue(maxsize=depth)
        stop = threading.Event()

        def put(item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def worker() -> None:
            # each window's assembly+prep runs under an ingest.window_prep
            # span — recorded off the main thread, so the timeline export
            # (obs/timeline) lands them on their own track opposite the
            # consumer's device-compute spans, making the PR 2/6 overlap
            # (or the lack of it) visually auditable
            try:
                for win in self.windows(start_shard, shard_offset,
                                        start_row):
                    with obs.span("ingest.window_prep", window=win.start,
                                  rows=win.n_valid):
                        item = _prep(win)
                    if not put(item):
                        return
                put(None)
            except BaseException as e:
                put(e)

        t = threading.Thread(target=worker, daemon=True,
                             name="shifu-ingest")
        t.start()
        wait_s = 0.0
        try:
            while True:
                t0 = time.perf_counter()
                with obs.span("ingest.h2d_wait"):
                    item = q.get()
                wait_s += time.perf_counter() - t0
                if isinstance(item, BaseException):
                    raise item
                if item is None:
                    break
                yield item
        finally:
            obs.counter("ingest.h2d_wait_seconds").inc(wait_s)
            stop.set()
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=5.0)

    @property
    def num_rows(self) -> int:
        return self.shards.num_rows


def _take(buf: Dict[str, list], rows: int, keys: Sequence[str]):
    """Split ``rows`` rows off the buffer front (no copy when aligned)."""
    arrays = {}
    rest: Dict[str, list] = {}
    for k in keys:
        cat = buf[k][0] if len(buf[k]) == 1 else np.concatenate(buf[k])
        arrays[k] = cat[:rows]
        rest[k] = [cat[rows:]] if len(cat) > rows else []
    remaining = sum(len(a) for a in rest[keys[0]])
    return arrays, rest, remaining


def _pad_rows(a: np.ndarray, rows: int) -> np.ndarray:
    if len(a) >= rows:
        return a
    pad = np.zeros((rows - len(a),) + a.shape[1:], a.dtype)
    return np.concatenate([a, pad])


@dataclass
class PreparedWindow:
    """A window after the trainer's ``prepare`` hook — arrays may live on
    device (sharded over a mesh) or host.  ``src`` is filled in by
    ``ShardStream.prepared`` / ``ResidentCache`` from the source window
    (tail resume bookkeeping); hooks need not set it."""
    start: int
    n_valid: int
    rows: int
    index: np.ndarray
    arrays: Dict[str, object]
    resident: bool = False
    src: Optional[Tuple[int, int]] = None

    @property
    def nbytes(self) -> int:
        return int(sum(getattr(a, "nbytes", 0)
                       for a in self.arrays.values()))


class ResidentCache:
    """Two-tier window residency — the ``MemoryDiskFloatMLDataSet.java:54-99``
    memoryFraction design, TPU-shaped: prepared (typically device-resident,
    mesh-sharded) windows fill a byte budget; only the tail past the budget
    re-streams from disk on every subsequent sweep, resuming at the recorded
    (shard, offset) so fully-cached shard files are never re-read.

    With the dataset under budget, a GBT tree's (depth+2) level sweeps cost
    ZERO disk passes after the single warm pass — the round-2 design's
    (depth+2) full re-reads collapse to ~1/forest.  ``disk_passes`` counts
    actual stream traversals for tests/telemetry.

    Window prep runs through ``ShardStream.prepared`` (assembly + masks +
    ``device_put`` pipelined ``pipeline_depth`` windows ahead on a
    background thread); resident windows keep their device buffers — and
    any per-row state the trainer attaches (GBT scores ``f``, RF oob
    votes) — alive across every subsequent sweep.  ``pipeline_depth=0``
    forces inline prep (required on multi-device CPU meshes, see
    ``ShardStream.prepared``)."""

    def __init__(self, stream: "ShardStream", budget_bytes: int,
                 prepare: Callable[[Window], PreparedWindow],
                 pipeline_depth: Optional[int] = None):
        self.stream = stream
        self.budget = int(budget_bytes)
        self.prepare = prepare
        self.pipeline_depth = pipeline_depth
        self.cached: list = []
        self.tail: Optional[Tuple[int, int, int]] = None  # shard, offset, row
        self.disk_passes = 0
        self.tail_sweeps = 0
        self._warm = False

    def _prepared(self, start_shard: int = 0, shard_offset: int = 0,
                  start_row: int = 0) -> Iterator[PreparedWindow]:
        return self.stream.prepared(self.prepare, start_shard, shard_offset,
                                    start_row, depth=self.pipeline_depth)

    def items(self) -> Iterator[PreparedWindow]:
        if not self._warm:
            used = 0
            caching = True
            self.disk_passes += 1
            obs.counter("ingest.disk_passes").inc()
            for item in self._prepared():
                if caching and used + item.nbytes <= self.budget:
                    item.resident = True
                    self.cached.append(item)
                    used += item.nbytes
                elif caching:
                    caching = False
                    self.tail = (item.src[0], item.src[1], item.start) \
                        if item.src else (0, 0, 0)
                yield item
            self._warm = True
        else:
            yield from self.cached
            if self.tail is not None:
                yield from self.tail_items()

    def tail_items(self) -> Iterator[PreparedWindow]:
        """Re-stream ONLY the tail (windows past the resident budget) —
        one disk pass over the spill/npz remainder, prep pipelined like
        the warm pass.  The super-batched tree trainers sweep the
        resident set as a coalesced device block and drive the disk tail
        through this; ``train.tail_sweeps`` counts the tail re-streams
        the schedule actually paid (the disk-passes guard tests and the
        ``analysis --telemetry`` tail stall line read it)."""
        if not self._warm:
            raise RuntimeError("tail_items() before the warm pass — "
                               "iterate items() once first")
        if self.tail is None:
            return
        self.disk_passes += 1
        self.tail_sweeps += 1
        obs.counter("ingest.disk_passes").inc()
        obs.counter("train.tail_sweeps").inc()
        sh, off, row = self.tail
        yield from self._prepared(start_shard=sh, shard_offset=off,
                                  start_row=row)

    @property
    def resident_rows(self) -> int:
        return sum(it.n_valid for it in self.cached)

    @property
    def warmed(self) -> bool:
        """True once the first full sweep has classified every window as
        resident or tail — ``tail`` is only meaningful after this."""
        return self._warm


def auto_window_rows(row_bytes: int, budget_bytes: int,
                     multiple: int = 8, lo: int = 1024,
                     hi: int = 1 << 22, n_rows: Optional[int] = None) -> int:
    """Window size from a device-memory budget (the reference's
    ``guagua.data.memoryFraction`` analogue, ``AbstractNNWorker.java:
    479-496``): as many rows as fit, clamped and rounded to ``multiple``.

    ``n_rows`` (when the schema knows it) caps the window at the dataset —
    windows pad to their full static shape, so without the cap a small
    dataset under a big budget computes over millions of padded rows per
    sweep (measured 2800x waste: 1500 rows in a 4.19M-row window)."""
    rows = int(budget_bytes // max(row_bytes, 1))
    if n_rows:
        hi = min(hi, n_rows + (-n_rows) % multiple)
        lo = min(lo, hi)
    rows = max(lo, min(rows, hi))
    return max(multiple, rows - rows % multiple)


def stream_window_rows(row_bytes: int, data_size: int, shards) -> int:
    """THE window-geometry recipe for every streamed trainer (NN / WDL /
    trees): the ``shifu.train.windowRows`` override or the budget-derived
    auto size, capped at the dataset (see :func:`auto_window_rows`) and
    rounded up to the mesh data axis.  One implementation — per-trainer
    copies drifted (different rounding directions, a missing dataset cap
    that cost a 2800x padded-row waste)."""
    from ..config import environment
    budget = environment.get_int("shifu.train.memoryBudgetBytes", 1 << 31)
    n_rows = (shards.schema.get("numRows") if hasattr(shards, "schema")
              else None) or getattr(shards, "num_rows", None)
    wr = environment.get_int("shifu.train.windowRows", 0) or \
        auto_window_rows(row_bytes, budget, multiple=data_size,
                         n_rows=n_rows)
    wr += (-wr) % data_size
    return max(data_size, wr)


MaskFn = Callable[[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]


def mask_fn_from_settings(bags: int, *, valid_rate: float, kfold: int = -1,
                          sample_rate: float = 1.0, replacement: bool = False,
                          up_sample_weight: float = 1.0,
                          seed: int = 0) -> MaskFn:
    """Bind sampling settings into a ``(index, targets) -> (train_w,
    valid_w)`` window mask function for the streamed trainers."""
    def fn(idx: np.ndarray, targets: np.ndarray):
        return window_member_masks(
            idx, bags, valid_rate=valid_rate, kfold=kfold,
            sample_rate=sample_rate, replacement=replacement,
            up_sample_weight=up_sample_weight, targets=targets, seed=seed)
    return fn
