"""Chunk extraction: raw string chunk -> typed arrays per the ColumnConfig list.

Shared by stats / normalize / eval: applies the row filter, parses the target
tag, weight column, numeric candidate columns into one [R, C] matrix and
leaves categorical columns as string arrays.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from ..config import ColumnConfig, ModelConfig
from .purifier import DataPurifier
from .reader import RawChunk, parse_numeric, parse_weight, tag_to_target


@dataclass
class ExtractedChunk:
    n: int
    target: np.ndarray                 # [R] 1/0
    weight: np.ndarray                 # [R]
    numeric: np.ndarray                # [R, C_num] float64 (NaN = missing)
    numeric_valid: np.ndarray          # [R, C_num] bool
    numeric_cols: List[ColumnConfig]
    categorical: Dict[str, np.ndarray]  # name -> [R] str values
    categorical_cols: List[ColumnConfig]
    raw: Optional[RawChunk] = None
    # replay provenance (raw cache): positional raw-row index of each kept
    # row and the chunk's pre-filter row count — every row-wise op in this
    # extractor commutes with row subsetting, so a cached full extraction
    # plus (raw_rows, kept_idx) replays any pre-parse Bernoulli sample
    # bit-identically (sample_mask[kept_idx] selects the same rows the
    # sample-then-extract order would have kept)
    kept_idx: Optional[np.ndarray] = None   # [R] int64
    raw_rows: int = 0


class ChunkExtractor:
    def __init__(self, model_config: ModelConfig, column_configs: List[ColumnConfig],
                 columns: Optional[List[ColumnConfig]] = None,
                 for_eval_set: Optional[int] = None):
        self.mc = model_config
        ds = model_config.dataSet if for_eval_set is None else \
            model_config.evals[for_eval_set].dataSet
        self.ds = ds
        self.purifier = DataPurifier(ds.filterExpressions)
        # eval sets may rename the target / use different tags or missing
        # markers than the training source (reference EvalConfig dataSet)
        self.missing_values = ds.missingOrInvalidValues \
            or model_config.dataSet.missingOrInvalidValues
        if columns is None:
            columns = [c for c in column_configs if c.is_candidate()]
        self.numeric_cols = [c for c in columns if not c.is_categorical()]
        self.categorical_cols = [c for c in columns if c.is_categorical()]
        self.target_name = ds.targetColumnName \
            or model_config.dataSet.targetColumnName
        self.pos_tags = ds.posTags or model_config.dataSet.posTags
        self.neg_tags = ds.negTags or model_config.dataSet.negTags
        # multi-class: posTags lists every class, negTags empty — y becomes
        # the class index instead of a 0/1 target
        self.multiclass = len(self.pos_tags) > 1 and not self.neg_tags
        self.weight_name = ds.weightColumnName

    def row_identity(self) -> dict:
        """Everything that decides WHICH rows survive extraction and how
        the shared target/weight columns parse — the raw cache's row-plane
        staleness key.  Column-independent on purpose: a cache written by
        one extractor serves any other whose row identity matches exactly
        and whose numeric/categorical columns are a SUBSET of the cached
        set (per-column parses are row-wise and independent)."""
        return {"filters": self.ds.filterExpressions,
                "missing": sorted(m for m in (self.missing_values or [])),
                "target": self.target_name,
                "posTags": [str(t) for t in self.pos_tags],
                "negTags": [str(t) for t in self.neg_tags],
                "multiclass": bool(self.multiclass),
                "weight": self.weight_name}

    def extract(self, chunk: RawChunk, keep_raw: bool = False) -> ExtractedChunk:
        df = chunk.data
        raw_rows = len(df)
        keep = self.purifier.mask(df)
        if self.target_name and self.target_name in df.columns:
            raw_tags = df[self.target_name].to_numpy()
            if self.multiclass:
                from .reader import tag_to_class
                y = tag_to_class(raw_tags, self.pos_tags)
            else:
                y = tag_to_target(raw_tags, self.pos_tags, self.neg_tags)
            keep &= ~np.isnan(y)  # drop rows with unknown tags
        else:
            y = np.zeros(len(df))
        kept_idx = np.flatnonzero(np.asarray(keep, dtype=bool))
        df = df[keep]
        y = y[keep]
        n = len(df)
        w = parse_weight(
            df[self.weight_name].to_numpy() if self.weight_name and
            self.weight_name in df.columns else None, n)
        if self.numeric_cols:
            mats, valids = [], []
            for cc in self.numeric_cols:
                f, v = parse_numeric(df[cc.columnName].to_numpy(), self.missing_values)
                mats.append(f)
                valids.append(v)
            numeric = np.stack(mats, axis=1)
            numeric_valid = np.stack(valids, axis=1)
        else:
            numeric = np.zeros((n, 0))
            numeric_valid = np.zeros((n, 0), dtype=bool)
        categorical = {cc.columnName: df[cc.columnName].to_numpy()
                       for cc in self.categorical_cols}
        return ExtractedChunk(
            n=n, target=y, weight=w, numeric=numeric, numeric_valid=numeric_valid,
            numeric_cols=self.numeric_cols, categorical=categorical,
            categorical_cols=self.categorical_cols,
            raw=RawChunk(chunk.columns, df) if keep_raw else None,
            kept_idx=kept_idx, raw_rows=raw_rows)
