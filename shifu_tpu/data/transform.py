"""DatasetTransformer: raw chunks -> (normalized float matrix, binned int
matrix, target, weight), the dual data plane trees vs NN/LR need (reference
keeps the same cleaned-vs-normalized duality,
``TrainModelProcessor.java:1366-1372``).

Used by `norm` (materializes shards), `train` (streams), and `eval`
(normalizes eval sets on the fly, like ``EvalNormUDF``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from ..config import (ColumnConfig, ModelConfig, selected_columns)
from ..config.model_config import NormType
from ..ops.binning import ColumnBinner
from ..ops.normalize import (CategoryMissingNormType, NormalizedColumn,
                             apply_precision)
from .extract import ChunkExtractor, ExtractedChunk
from .reader import RawChunk


def model_input_columns(model_config: ModelConfig,
                        column_configs: List[ColumnConfig]) -> List[ColumnConfig]:
    """Columns that feed the model: finalSelect if any, else all candidates
    with stats (norm can run before varselect)."""
    sel = selected_columns(column_configs)
    if sel:
        return sel
    return [c for c in column_configs
            if c.is_candidate() and c.num_bins() > 0]


@dataclass
class TransformedChunk:
    n: int
    x: np.ndarray          # [R, D] float32 normalized
    bins: np.ndarray       # [R, C] int32 bin indices (missing = num_bins)
    target: np.ndarray     # [R] float32
    weight: np.ndarray     # [R] float32


class DatasetTransformer:
    def __init__(self, model_config: ModelConfig, column_configs: List[ColumnConfig],
                 columns: Optional[List[ColumnConfig]] = None,
                 for_eval_set: Optional[int] = None):
        self.mc = model_config
        self.columns = columns if columns is not None else \
            model_input_columns(model_config, column_configs)
        if not self.columns:
            raise ValueError("no input columns with binning stats — run `stats` first")
        self.extractor = ChunkExtractor(model_config, column_configs,
                                        columns=self.columns,
                                        for_eval_set=for_eval_set)
        norm_type = model_config.normalize.normType
        cutoff = model_config.normalize.stdDevCutOff
        self.norm_cols = [NormalizedColumn(cc, norm_type, cutoff)
                          for cc in self.columns]
        self.binners = {}
        for cc in self.columns:
            if cc.is_categorical():
                self.binners[cc.columnNum] = ColumnBinner(categories=cc.bin_category or [])
            elif cc.bin_boundary:
                self.binners[cc.columnNum] = ColumnBinner(
                    boundaries=np.asarray(cc.bin_boundary))
            else:
                self.binners[cc.columnNum] = None
        self.output_names = [n for nc in self.norm_cols for n in nc.output_names()]

    @property
    def width(self) -> int:
        return len(self.output_names)

    def transform(self, chunk) -> TransformedChunk:
        """Raw chunk OR already-extracted chunk (the parse pool / raw
        cache hand out :class:`ExtractedChunk` directly) -> transformed."""
        if isinstance(chunk, ExtractedChunk):
            return self.transform_extracted(chunk)
        ex = self.extractor.extract(chunk)
        return self.transform_extracted(ex)

    def transform_extracted(self, ex: ExtractedChunk) -> TransformedChunk:
        num_index = {c.columnNum: i for i, c in enumerate(ex.numeric_cols)}
        outs, bin_cols = [], []
        for nc in self.norm_cols:
            cc = nc.cc
            binner = self.binners[cc.columnNum]
            if cc.is_categorical():
                vals = ex.categorical[cc.columnName]
                bidx = binner.bin_categorical(vals) if binner else \
                    np.zeros(ex.n, dtype=np.int32)
                out = nc.transform(np.zeros(ex.n), np.zeros(ex.n, dtype=bool), bidx)
            else:
                j = num_index[cc.columnNum]
                v, valid = ex.numeric[:, j], ex.numeric_valid[:, j]
                bidx = binner.bin_numeric(v, valid) if binner else \
                    np.where(valid, 0, 1).astype(np.int32)
                out = nc.transform(v, valid, bidx)
            outs.append(out)
            bin_cols.append(bidx)
        x = np.concatenate(outs, axis=1) if outs else np.zeros((ex.n, 0))
        x = apply_precision(x, self.mc.normalize.precisionType)
        return TransformedChunk(
            n=ex.n, x=x.astype(np.float32),
            bins=np.stack(bin_cols, axis=1).astype(np.int32) if bin_cols else
            np.zeros((ex.n, 0), np.int32),
            target=ex.target.astype(np.float32),
            weight=ex.weight.astype(np.float32))
