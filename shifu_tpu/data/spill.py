"""Binned spill cache — mmap-backed re-read path for shard streams.

The out-of-core tree/stats planes sweep the same materialized shards many
times per forest ((depth+2) level sweeps x trees for the GBT disk tail).
The npz container makes every one of those sweeps a zip decode on a single
thread.  The reference system never paid that: ``MemoryDiskFloatMLDataSet``
(``core/dtrain/dataset/MemoryDiskFloatMLDataSet.java:315-361``) fills a
heap tier once and spills the remainder to a FLAT row file it re-reads
directly on every subsequent iterator chain.

This module is that spill tier, columnar: on the first full pass over a
shard stream the selected columns land in flat raw files (one per key)
next to a sidecar ``manifest.json`` (row counts per shard, dtypes,
trailing shapes, source signature).  Every later sweep is ``np.memmap``
slicing — zero zip/npz decode, zero host copies until the bytes are
actually consumed (typically by ``jax.device_put``).

Layout under ``<shards dir>/.spill_cache/spill-<keys>/``::

    manifest.json      commit point; see MANIFEST_* fields below
    <key>.raw          rows-major flat array, dtype/shape from manifest

Integer columns (bin ids) are narrowed to the smallest unsigned dtype the
data fits (uint8 for <=256 bins) — the same compact wire format the
trainers ship to the device, so a spill window's bins transfer without a
single host-side cast or copy.

Knobs (``config.environment`` properties / ``SHIFU_*`` env):

- ``shifu.stream.spill``            on/off (default on)
- ``shifu.stream.spillBudgetBytes`` cap on raw-file bytes (default 8 GiB;
  a stream larger than the budget streams npz as before — the manifest
  records the abort so later epochs don't retry the write)
- ``shifu.stream.spillDir``         base directory override (default: the
  shard directory itself)

Staleness: the manifest pins ``(basename, size, mtime_ns)`` of every
source npz; any mismatch invalidates the spill and the next pass rebuilds
it.  Writers commit via tmp-file + ``os.replace`` with the manifest last,
so readers never observe a torn cache.
"""

from __future__ import annotations

import json
import mmap
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MANIFEST = "manifest.json"
SPILL_FORMAT_VERSION = 1

_tmp_lock = threading.Lock()
_tmp_seq = 0


def _tmp_suffix() -> str:
    """Process-unique temp suffix (two concurrent streams in one pid must
    not append to each other's raw files)."""
    global _tmp_seq
    with _tmp_lock:
        _tmp_seq += 1
        return f".tmp-{os.getpid()}-{_tmp_seq}"


def spill_enabled() -> bool:
    from ..config import environment
    return environment.get_bool("shifu.stream.spill", True)


def spill_budget_bytes() -> int:
    from ..config import environment
    return environment.get_int("shifu.stream.spillBudgetBytes", 1 << 33)


def spill_base_dir(shards_dir: str) -> str:
    from ..config import environment
    base = environment.get_property("shifu.stream.spillDir") or shards_dir
    return os.path.join(base, ".spill_cache")


def spill_dir_for(shards_dir: str, keys: Sequence[str]) -> str:
    return os.path.join(spill_base_dir(shards_dir),
                        "spill-" + "-".join(keys))


def _narrow_int_dtype(a: np.ndarray) -> np.dtype:
    """Storage dtype for one column: integers narrow to the smallest
    unsigned type the observed values fit (the compact wire format);
    floats store as-is."""
    if a.dtype.kind in "iu" and a.size:
        lo, hi = int(a.min()), int(a.max())
        if lo >= 0:
            if hi < 1 << 8:
                return np.dtype(np.uint8)
            if hi < 1 << 16:
                return np.dtype(np.uint16)
    return a.dtype


class SpillWriter:
    """Write-through spill built during one cold pass over the shards.

    ``append`` per shard in order; ``finish`` commits (raw renames, then
    the manifest — the commit point); ``abort`` discards, optionally
    leaving an ``aborted`` marker so later passes skip the write (budget
    overflow would just recur)."""

    def __init__(self, directory: str, keys: Sequence[str], source_sig,
                 budget_bytes: int):
        self.directory = directory
        self.keys = tuple(keys)
        self.sig = source_sig
        self.budget = int(budget_bytes)
        self._suffix = _tmp_suffix()
        self._files: Dict[str, object] = {}
        self._dtypes: Dict[str, np.dtype] = {}
        self._shapes: Dict[str, Tuple[int, ...]] = {}
        self._row_bytes = 0
        self._rows = 0
        self._bytes = 0
        self._shard_rows: List[int] = []
        self._dead = False
        os.makedirs(directory, exist_ok=True)

    def _raw_path(self, key: str) -> str:
        return os.path.join(self.directory, key + ".raw")

    def append(self, part: Dict[str, np.ndarray]) -> bool:
        """Append one shard's selected columns.  Returns False once the
        spill is abandoned (budget / dtype overflow / IO error) — the
        caller keeps streaming npz, unaffected."""
        if self._dead:
            return False
        try:
            from .. import faults
            faults.fire("spill", "append", len(self._shard_rows))
            n = int(len(next(iter(part.values()))))
            if not self._files:
                for k in self.keys:
                    a = np.asarray(part[k])
                    self._dtypes[k] = _narrow_int_dtype(a)
                    self._shapes[k] = tuple(a.shape[1:])
                self._row_bytes = sum(
                    int(np.prod(self._shapes[k], dtype=np.int64))
                    * self._dtypes[k].itemsize for k in self.keys)
                for k in self.keys:
                    self._files[k] = open(self._raw_path(k) + self._suffix,
                                          "wb")
            if self._bytes + n * self._row_bytes > self.budget:
                self.abort(mark=f"budget {self.budget} bytes exceeded at "
                                f"row {self._rows}")
                return False
            for k in self.keys:
                a = np.ascontiguousarray(np.asarray(part[k]))
                dt = self._dtypes[k]
                if a.dtype != dt:
                    if a.size and dt.kind == "u" and (
                            int(a.min()) < 0
                            or int(a.max()) >= 1 << (8 * dt.itemsize)):
                        # a later shard outgrew the first shard's narrow
                        # dtype — cannot widen a half-written file
                        self.abort(mark=f"column {k!r} outgrew "
                                        f"{dt.name} mid-stream")
                        return False
                    a = a.astype(dt)
                a.tofile(self._files[k])
            self._rows += n
            self._bytes += n * self._row_bytes
            self._shard_rows.append(n)
            return True
        except OSError:
            self.abort()
            return False

    def finish(self) -> bool:
        """Commit the completed spill (the pass reached the dataset end)."""
        if self._dead:
            return False
        try:
            for f in self._files.values():
                f.close()
            for k in self._files:
                os.replace(self._raw_path(k) + self._suffix,
                           self._raw_path(k))
            man = {"version": SPILL_FORMAT_VERSION,
                   "keys": list(self.keys),
                   "dtypes": {k: self._dtypes[k].str for k in self._files},
                   "shapes": {k: list(self._shapes[k]) for k in self._files},
                   "rows": self._rows,
                   "shard_rows": self._shard_rows,
                   "bytes": self._bytes,
                   "source": self.sig}
            self._write_manifest(man)
            self._dead = True
            return True
        except OSError:
            self.abort()
            return False

    def abort(self, mark: Optional[str] = None) -> None:
        """Drop the half-written spill.  ``mark`` records a permanent
        reason (budget/dtype) so later passes don't re-attempt; an
        unmarked abort (consumer abandoned the stream) leaves nothing and
        the next full pass retries."""
        if self._dead:
            return
        self._dead = True
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        for k in self._files:
            try:
                os.remove(self._raw_path(k) + self._suffix)
            except OSError:
                pass
        if mark:
            try:
                self._write_manifest({"version": SPILL_FORMAT_VERSION,
                                      "keys": list(self.keys),
                                      "aborted": mark,
                                      "source": self.sig})
            except OSError:
                pass

    def _write_manifest(self, man: dict) -> None:
        from .. import faults
        from ..ioutil import io_retry
        tmp = os.path.join(self.directory, MANIFEST + self._suffix)

        def write():
            faults.fire("spill", "manifest", 0, path=tmp)
            with open(tmp, "w") as f:
                json.dump(man, f)
            os.replace(tmp, os.path.join(self.directory, MANIFEST))
        io_retry(write, "spill manifest commit", self.directory)


def wire_dir(shards_dir: str, keys: Sequence[str]) -> str:
    """The direct-to-wire norm output directory.  Deliberately NOT
    routed through ``spill_base_dir``'s ``shifu.stream.spillDir``
    override: the wire plane IS the materialized dataset (norm's
    output), not a cache placement choice — it lives with its schema."""
    return os.path.join(shards_dir, ".spill_cache",
                        "spill-" + "-".join(keys))


class WireWriter:
    """Per-shard durable spill writer — norm's direct-to-wire output.

    ``SpillWriter`` commits once at ``finish``; this writer re-commits
    the manifest after EVERY shard append, so the committed wire prefix
    always matches the norm journal's committed-shard prefix and a crash
    never loses a committed shard (a torn append leaves raw-file tail
    bytes past the manifest's row count — harmless, and :meth:`resume`
    truncates them).  Dtypes/shapes are fixed up front (norm knows the
    bins wire dtype before the first row), so none of ``SpillWriter``'s
    first-shard narrowing or mid-stream outgrow aborts apply.  Write
    failures raise — the wire plane is the dataset, not an optimization
    a caller can shrug off."""

    def __init__(self, directory: str, keys: Sequence[str],
                 dtypes: Dict[str, np.dtype], trailing: Dict[str, tuple],
                 source_sig):
        self.directory = directory
        self.keys = tuple(keys)
        self._dtypes = {k: np.dtype(dtypes[k]) for k in self.keys}
        self._shapes = {k: tuple(trailing.get(k, ())) for k in self.keys}
        self.sig = source_sig
        self._suffix = _tmp_suffix()
        self._files: Dict[str, object] = {}
        self._shard_rows: List[int] = []
        self._rows = 0
        os.makedirs(directory, exist_ok=True)

    def _raw_path(self, key: str) -> str:
        return os.path.join(self.directory, key + ".raw")

    def _row_bytes(self, key: str) -> int:
        return int(np.prod(self._shapes[key] or (1,), dtype=np.int64)) \
            * self._dtypes[key].itemsize

    @property
    def n_shards(self) -> int:
        return len(self._shard_rows)

    @classmethod
    def resume(cls, directory: str, keys: Sequence[str],
               dtypes: Dict[str, np.dtype], trailing: Dict[str, tuple],
               source_sig, n_shards: int) -> Optional["WireWriter"]:
        """Adopt the committed prefix of an interrupted wire plane: the
        manifest must cover >= ``n_shards`` shards of this exact source/
        layout; raw files truncate to exactly those rows (dropping any
        tail bytes a mid-append crash left) and the returned writer is
        positioned after them.  None = unusable, rebuild from scratch."""
        try:
            with open(os.path.join(directory, MANIFEST)) as f:
                man = json.load(f)
        except (OSError, ValueError):
            return None
        if (man.get("version") != SPILL_FORMAT_VERSION or man.get("aborted")
                or list(man.get("keys") or []) != list(keys)
                or man.get("source") != source_sig
                or len(man.get("shard_rows") or []) < n_shards):
            return None
        w = cls(directory, keys, dtypes, trailing, source_sig)
        try:
            for k in keys:
                if np.dtype(man["dtypes"][k]) != w._dtypes[k] or \
                        tuple(man["shapes"][k]) != w._shapes[k]:
                    return None
        except KeyError:
            return None
        w._shard_rows = [int(x) for x in man["shard_rows"][:n_shards]]
        w._rows = sum(w._shard_rows)
        try:
            for k in keys:
                need = w._rows * w._row_bytes(k)
                path = w._raw_path(k)
                if os.path.getsize(path) < need:
                    w.close()
                    return None
                with open(path, "r+b") as f:
                    f.truncate(need)
                w._files[k] = open(path, "ab")
            w._commit_manifest()       # re-pin to the adopted prefix
        except OSError:
            w.close()
            return None
        return w

    def append(self, part: Dict[str, np.ndarray]) -> None:
        """Append one shard's columns and durably commit the manifest."""
        if not self._files:
            for k in self.keys:
                self._files[k] = open(self._raw_path(k), "wb")
        n = int(len(next(iter(part.values()))))
        for k in self.keys:
            a = np.asarray(part[k])
            if a.shape[1:] != self._shapes[k]:
                raise ValueError(f"wire column {k!r}: shard shape "
                                 f"{a.shape[1:]} != {self._shapes[k]}")
            if a.dtype != self._dtypes[k]:
                a = a.astype(self._dtypes[k])
            np.ascontiguousarray(a).tofile(self._files[k])
        self._rows += n
        self._shard_rows.append(n)
        self._commit_manifest()

    def truncate_to(self, n_shards: int) -> None:
        """Drop every shard past ``n_shards`` (a resumed shard's replay
        diverged from the journal — it and everything after re-run)."""
        self._shard_rows = self._shard_rows[:n_shards]
        self._rows = sum(self._shard_rows)
        for k in self.keys:
            f = self._files.get(k)
            if f is not None:
                f.close()
            with open(self._raw_path(k), "r+b") as g:
                g.truncate(self._rows * self._row_bytes(k))
            self._files[k] = open(self._raw_path(k), "ab")
        self._commit_manifest()

    def finish(self) -> None:
        """Close out; zero-shard planes still land an (empty) manifest so
        readers see a committed-but-empty wire plane, not a torn one."""
        if not self._shard_rows:
            self._commit_manifest()
        self.close()

    def close(self) -> None:
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files = {}

    def _commit_manifest(self) -> None:
        from ..ioutil import io_retry
        man = {"version": SPILL_FORMAT_VERSION,
               "keys": list(self.keys),
               "dtypes": {k: self._dtypes[k].str for k in self.keys},
               "shapes": {k: list(self._shapes[k]) for k in self.keys},
               "rows": self._rows,
               "shard_rows": list(self._shard_rows),
               "bytes": sum(self._rows * self._row_bytes(k)
                            for k in self.keys),
               "source": self.sig}
        tmp = os.path.join(self.directory, MANIFEST + self._suffix)

        def write():
            for f in self._files.values():
                f.flush()
            with open(tmp, "w") as f:
                json.dump(man, f)
            os.replace(tmp, os.path.join(self.directory, MANIFEST))
        io_retry(write, "wire manifest commit", self.directory)


class SpillReader:
    """mmap view over a committed spill."""

    def __init__(self, directory: str, man: dict):
        self.directory = directory
        self.man = man
        self.keys = tuple(man["keys"])
        self.rows = int(man["rows"])
        self.shard_rows = [int(x) for x in man["shard_rows"]]
        # prefix sums: cum[i] = global row of shard i's first row
        self.cum = np.concatenate(
            [[0], np.cumsum(self.shard_rows)]).astype(np.int64)
        self._mms: Dict[str, np.memmap] = {}

    def memmap(self, key: str) -> np.memmap:
        mm = self._mms.get(key)
        if mm is None:
            from ..ioutil import io_retry
            dt = np.dtype(self.man["dtypes"][key])
            shape = (self.rows,) + tuple(self.man["shapes"][key])
            path = os.path.join(self.directory, key + ".raw")
            mm = io_retry(
                lambda: np.memmap(path, dtype=dt, mode="r", shape=shape),
                "spill mmap open", path)
            # the super-batched tail re-streams walk each raw file front
            # to back, many times per forest — tell the VM to read ahead
            # aggressively and not to keep pages hot behind the cursor
            # (without this the first tail sweep after a cold page cache
            # faults 4 KiB at a time)
            try:
                mm._mmap.madvise(mmap.MADV_SEQUENTIAL)
            except (AttributeError, ValueError, OSError):
                pass                       # platform without madvise
            self._mms[key] = mm
        return mm

    def global_of(self, shard: int, offset: int) -> Optional[int]:
        """Global row index of (shard, row offset); None when the request
        falls outside what the manifest covers."""
        if not 0 <= shard < len(self.shard_rows):
            return None
        g = int(self.cum[shard]) + int(offset)
        return g if 0 <= g <= self.rows else None

    def src_of(self, g: int) -> Tuple[int, int]:
        """(shard idx, row offset) of global row ``g`` — the inverse of
        :meth:`global_of`, matching the npz stream's per-window ``src``
        bookkeeping exactly (zero-row shards are skipped the same way)."""
        si = int(np.searchsorted(self.cum, g, side="right") - 1)
        return si, int(g - self.cum[si])


def open_spill(directory: str, keys: Sequence[str],
               source_sig) -> Tuple[Optional[SpillReader], bool]:
    """(reader, writable): ``reader`` is a valid committed spill or None;
    ``writable`` says whether a cold pass should (re)build one — False
    when a marker records a permanent abort for this exact source."""
    path = os.path.join(directory, MANIFEST)

    def read():
        if not os.path.isfile(path):   # absence is final, not transient
            return None
        with open(path) as f:
            return json.load(f)
    try:
        from ..ioutil import io_retry
        man = io_retry(read, "spill manifest read", path)
        if man is None:
            return None, True
    except (OSError, ValueError):
        return None, True
    if man.get("version") != SPILL_FORMAT_VERSION \
            or list(man.get("keys") or []) != list(keys) \
            or man.get("source") != source_sig:
        return None, True                      # stale / other keyset
    if man.get("aborted"):
        return None, False
    try:
        rows = int(man["rows"])
        for k in keys:
            dt = np.dtype(man["dtypes"][k])
            need = rows * int(np.prod(man["shapes"][k] or [1],
                                      dtype=np.int64)) * dt.itemsize
            if rows and os.path.getsize(
                    os.path.join(directory, k + ".raw")) < need:
                return None, True              # torn raw file
        return SpillReader(directory, man), False
    except (OSError, KeyError, ValueError, TypeError):
        return None, True
