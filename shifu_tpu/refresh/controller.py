"""RefreshController — the drift-gated continual train→gate→promote loop.

The reference re-runs its whole Hadoop pipeline when a fraud model goes
stale; production systems run a continuous loop feeding the serving
fleet.  This controller closes that loop over pieces that already exist
in-tree:

- **trigger** — a PSI threshold breach from the streaming
  :class:`~shifu_tpu.obs.drift.DriftMonitor` (fed live via
  :meth:`observe`, or read from the ``telemetry/drift.json`` artifact a
  norm/eval re-run emits), or a wall-clock schedule
  (``-Dshifu.refresh.intervalS``).  A cooldown guard
  (``-Dshifu.refresh.cooldownS``) keeps a sustained breach from
  thrashing the fleet with back-to-back retrains.  A THIRD trigger
  source is live model quality (PR 16): the attached server's
  :class:`~shifu_tpu.obs.quality.QualityMonitor` (or the
  ``telemetry/quality.json`` artifact it emits) reporting live-AUC
  degradation vs the posttrain snapshot or a score-distribution PSI
  breach — the model itself went stale, even if the inputs look fine;
- **warm retrain** — :func:`shifu_tpu.refresh.retrain.warm_retrain`:
  NN/WDL resume (params, opt state, RNG, early-stop state) from the
  PR-4 trainer checkpoints, GBT appends trees on boosted residuals of
  the restored score sidecar — onto the data-window cursor's NEW rows
  only, never a cold full re-run;
- **gate** — the candidate reaches the fleet ONLY on AUC non-regression
  over a fresh holdout (:mod:`shifu_tpu.eval.gate`,
  ``-Dshifu.refresh.minAucDelta``); a rejected candidate is archived
  with its eval report and the incumbent stays live;
- **probation** — the promotion is watched through the PR-10 SLO plane
  for ``-Dshifu.refresh.probationS``: a firing error-budget burn alert
  or a parity-canary mismatch rolls the registry back to the previous
  generation automatically (``ModelRegistry.rollback``, the same
  journal-first path as the swap).

Every decision (trigger / skip / train / promote / reject / rollback /
complete) commits to the refresh journal under ``<modelset>/refresh/``
(:mod:`shifu_tpu.refresh.journal`), so a killed controller resumes its
loop mid-cycle exactly like the PR-4 step journals: re-entering at the
gate after a post-retrain death, adopting an already-committed swap, or
re-watching a half-served probation window.

Fault sites: ``refresh:trigger`` (before the trigger record commits),
``refresh:promote`` (after the gate passes, before the registry swap),
``refresh:rollback`` (before the rollback re-flip).

The clock and sleep are injectable; the decision matrix runs in tests
with zero real waiting.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from .. import faults, obs
from ..ioutil import atomic_savez, atomic_write_json
from .journal import IDLE, PROBATION, TRAINED, TRIGGERED, RefreshJournal

log = logging.getLogger(__name__)

# heartbeat/monitor surface: the three externally meaningful states
STAGE_STATE = {IDLE: "idle", TRIGGERED: "training", TRAINED: "training",
               PROBATION: "probation"}
_STATE_CODE = {"idle": 0, "training": 1, "probation": 2}

CANARY_BASENAME = "canary.npz"

DEFAULT_COOLDOWN_S = 300.0
DEFAULT_PROBATION_S = 60.0
DEFAULT_CANARY_ROWS = 64


@dataclass
class RefreshConfig:
    """Resolved refresh knobs (see the module docstring for semantics)."""
    psi_threshold: float
    interval_s: float = 0.0          # 0 = no schedule trigger
    cooldown_s: float = DEFAULT_COOLDOWN_S
    min_auc_delta: float = 0.0
    probation_s: float = DEFAULT_PROBATION_S
    units: int = 0                   # extra epochs/trees (0 = derived)
    canary_rows: int = DEFAULT_CANARY_ROWS
    holdout_rows: int = 4096

    @classmethod
    def from_env(cls, **overrides) -> "RefreshConfig":
        """Knob resolution: ``shifu.refresh.psiThreshold`` defaults to
        the drift monitor's own ``shifu.drift.psiThreshold``."""
        from ..config import environment
        from ..obs.drift import psi_threshold as drift_threshold
        psi = environment.get_property("shifu.refresh.psiThreshold")
        try:
            psi_thr = float(psi) if psi is not None else drift_threshold()
        except (TypeError, ValueError):
            psi_thr = drift_threshold()
        cfg = cls(
            psi_threshold=psi_thr,
            interval_s=environment.get_float("shifu.refresh.intervalS",
                                             0.0),
            cooldown_s=environment.get_float("shifu.refresh.cooldownS",
                                             DEFAULT_COOLDOWN_S),
            min_auc_delta=environment.get_float(
                "shifu.refresh.minAucDelta", 0.0),
            probation_s=environment.get_float("shifu.refresh.probationS",
                                              DEFAULT_PROBATION_S),
            units=environment.get_int("shifu.refresh.units", 0),
            canary_rows=environment.get_int("shifu.refresh.canaryRows",
                                            DEFAULT_CANARY_ROWS),
        )
        for k, v in overrides.items():
            if v is not None:
                setattr(cfg, k, v)
        return cfg


def drift_columns_for(model_set_dir: str) -> Optional[List]:
    """The model-input ColumnConfig list in clean-plane column order —
    what a :class:`DriftMonitor` over served/normed bin windows needs.
    None when the plane or ColumnConfig is not materialized yet."""
    from ..config import load_column_configs
    cc_path = os.path.join(model_set_dir, "ColumnConfig.json")
    schema_path = os.path.join(model_set_dir, "tmp", "CleanedData",
                               "schema.json")
    try:
        with open(schema_path) as f:
            nums = json.load(f).get("columnNums") or []
        by_num = {c.columnNum: c for c in load_column_configs(cc_path)}
        cols = [by_num[n] for n in nums if n in by_num]
        return cols if len(cols) == len(nums) else None
    except (OSError, ValueError, KeyError):
        return None


class RefreshController:
    """One controller per model set; see module docs.

    Serving attachment: pass a live in-process ``server``
    (:class:`~shifu_tpu.serve.server.ServeServer` — promotions go
    through its traffic-refined ladder and probation reads its SLO
    tracker), or a bare ``registry`` + ``key`` (the CLI/daemon mode:
    promotions commit the serving journal, a serving fleet re-resolves
    it, and probation reads the fleet's SERVE heartbeats).

    Hooks (``retrain_fn(controller, gen)``, ``gate_fn(controller,
    candidate)``, ``drift_fn()``, ``quality_fn()``,
    ``slo_alerts_fn()``) default to the real pipeline wiring and are
    injectable for tests/benches."""

    def __init__(self, model_set_dir: str, server=None, registry=None,
                 key: Optional[str] = None,
                 config: Optional[RefreshConfig] = None,
                 clock: Callable[[], float] = time.time,
                 sleep: Callable[[float], None] = time.sleep,
                 retrain_fn=None, gate_fn=None,
                 drift_fn: Optional[Callable[[], Optional[dict]]] = None,
                 quality_fn: Optional[Callable[[], Optional[dict]]] = None,
                 slo_alerts_fn: Optional[Callable[[], List[dict]]] = None,
                 drift_columns: Optional[Sequence] = None,
                 warm: bool = True):
        self.dir = os.path.abspath(model_set_dir)
        self.server = server
        self.registry = server.registry if server is not None else registry
        if self.registry is None:
            raise ValueError("RefreshController needs a server= or "
                             "registry= to promote into")
        self.key = key or (server.key if server is not None
                           else os.path.basename(self.dir))
        self.config = config or RefreshConfig.from_env()
        self.journal = RefreshJournal(self.dir)
        self.clock = clock
        self.sleep = sleep
        self.warm = warm
        self.retrain_fn = retrain_fn or _default_retrain
        self.gate_fn = gate_fn or _default_gate
        self.drift_fn = drift_fn
        self.quality_fn = quality_fn
        self.slo_alerts_fn = slo_alerts_fn
        self._drift_columns = list(drift_columns) if drift_columns \
            else None
        self._drift = self._fresh_drift()
        self._candidate = None           # models dir or in-memory list
        self._canary: Optional[Dict[str, Any]] = None
        self._heartbeat = None
        self._started_ts = self.clock()
        self._set_gauges()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RefreshController":
        """Attach the live surfaces (heartbeat when telemetry is on) —
        idempotent; the tick loop works without it."""
        if self._heartbeat is None:
            self._heartbeat = obs.start_heartbeat(
                obs.health_dir_for(self.dir), step="REFRESH",
                proc=f"refresh-{self.key}", extras_fn=self._beat_extras)
        return self

    def stop(self, exit_code: Optional[int] = 0) -> None:
        if self._heartbeat is not None:
            self._heartbeat.stop(exit_code=exit_code)
            self._heartbeat = None

    def _beat_extras(self) -> dict:
        last = self.journal.doc.get("last_decision") or {}
        return {"refresh": {
            "state": STAGE_STATE.get(self.journal.stage, "idle"),
            "last_decision": last.get("kind"),
            "generation": self.registry.generation(self.key),
            "generations_held": len(
                self.registry.generation_history(self.key)),
            "cycle": self.journal.cycle,
            "last_outcome": self.journal.doc.get("last_outcome"),
        }}

    def _set_gauges(self) -> None:
        state = STAGE_STATE.get(self.journal.stage, "idle")
        obs.gauge("refresh.state").set(_STATE_CODE.get(state, 0))
        obs.gauge("refresh.generation").set(
            self.registry.generation(self.key))
        obs.gauge("refresh.cycle").set(self.journal.cycle)

    # ----------------------------------------------------------- drift feed
    def _fresh_drift(self):
        from ..obs.drift import DriftMonitor
        if not self._drift_columns:
            return None
        mon = DriftMonitor(self._drift_columns,
                           threshold=self.config.psi_threshold)
        return mon if mon._have.any() else None

    def observe(self, bins: np.ndarray,
                weights: Optional[np.ndarray] = None) -> None:
        """Fold one binned window of live traffic into the internal
        drift monitor (requires ``drift_columns``); every 8th window
        also refreshes the ``telemetry/drift.json`` artifact."""
        if self._drift is None:
            raise ValueError("no drift monitor attached — pass "
                             "drift_columns= (or feed drift_fn=)")
        self._drift.update(bins, weights)
        if self._drift.windows % 8 == 0:
            self._drift.emit(path=os.path.join(self.dir, "telemetry",
                                               "drift.json"))

    def _drift_summary(self):
        """(summary, from_artifact) — injectable fn > live monitor >
        the drift.json artifact a norm/eval re-run emitted."""
        if self.drift_fn is not None:
            return self.drift_fn(), False
        if self._drift is not None and self._drift.rows:
            return self._drift.summary(), False
        path = os.path.join(self.dir, "telemetry", "drift.json")
        try:
            with open(path) as f:
                doc = json.load(f)
            return (doc if isinstance(doc, dict) else None), True
        except (OSError, ValueError):
            return None, True

    def _quality_summary(self):
        """(summary, from_artifact) — injectable fn > the attached
        server's live quality monitor > the quality.json artifact a
        serve process emitted."""
        if self.quality_fn is not None:
            return self.quality_fn(), False
        if self.server is not None \
                and getattr(self.server, "quality", None) is not None:
            return self.server.quality.summary(), False
        path = os.path.join(self.dir, "telemetry", "quality.json")
        try:
            with open(path) as f:
                doc = json.load(f)
            return (doc if isinstance(doc, dict) else None), True
        except (OSError, ValueError):
            return None, True

    # -------------------------------------------------------------- trigger
    def _check_trigger(self, now: float) -> Optional[Dict[str, Any]]:
        summ, from_artifact = self._drift_summary()
        anchor = self.journal.doc.get("last_cycle_end_ts")
        if summ and summ.get("psi_max") is not None \
                and float(summ["psi_max"]) >= self.config.psi_threshold:
            ts = summ.get("ts")
            # an artifact breach older than the last cycle already
            # caused (or was rejected by) that cycle — not a new signal
            if not (from_artifact and anchor is not None
                    and ts is not None and float(ts) <= float(anchor)):
                return {"source": "psi",
                        "psi_max": round(float(summ["psi_max"]), 6),
                        "rows": int(summ.get("rows") or 0),
                        "flagged": list(summ.get("flagged") or [])[:8]}
        q, q_from_artifact = self._quality_summary()
        if q and q.get("degraded"):
            ts = q.get("ts")
            # same staleness anchor as the drift artifact: a degraded
            # table older than the last cycle is that cycle's cause,
            # not a new signal
            if not (q_from_artifact and anchor is not None
                    and ts is not None and float(ts) <= float(anchor)):
                return {"source": "quality",
                        "reasons": list(q.get("reasons") or []),
                        "live_auc": q.get("live_auc"),
                        "baseline_auc": q.get("baseline_auc"),
                        "score_psi": q.get("score_psi"),
                        "joined": int(q.get("joined") or 0)}
        if self.config.interval_s > 0:
            base = anchor if anchor is not None else self._started_ts
            if now - float(base) >= self.config.interval_s:
                return {"source": "schedule",
                        "interval_s": self.config.interval_s}
        return None

    def _in_cooldown(self, now: float) -> bool:
        last_end = self.journal.doc.get("last_cycle_end_ts")
        return last_end is not None and \
            now - float(last_end) < self.config.cooldown_s

    # ------------------------------------------------------------- the loop
    def tick(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One controller iteration: advance the cycle state machine as
        far as it can go without waiting (a fresh trigger runs all the
        way into probation; probation completes on a later tick).
        Returns the last decision record committed this tick, or None
        when nothing changed."""
        now = self.clock() if now is None else now
        decided: Optional[Dict[str, Any]] = None
        stage = self.journal.stage
        if stage == IDLE:
            trig = self._check_trigger(now)
            if trig is None:
                return None
            if self._in_cooldown(now):
                decided = self._skip_once(trig, now)
                self._set_gauges()
                return decided
            faults.fire("refresh", "trigger", self.key)
            self.journal.begin_cycle(
                trig, now,
                incumbent_gen=self.registry.generation(self.key))
            decided = self.journal.record("trigger", now, **trig)
            obs.counter("refresh.triggers").inc()
            stage = TRIGGERED
        if stage == TRIGGERED:
            decided = self._retrain(now)
            stage = TRAINED
        if stage == TRAINED:
            decided = self._gate_and_promote(self.clock())
            stage = self.journal.stage
        if stage == PROBATION:
            rec = self._probation(self.clock() if now is None else now)
            decided = rec or decided
        self._set_gauges()
        return decided

    def _skip_once(self, trig: Dict[str, Any],
                   now: float) -> Optional[Dict[str, Any]]:
        """Cooldown suppression: a sustained breach records ONE skip per
        cooldown window, not one per tick."""
        last_skip = self.journal.doc.get("last_skip_ts")
        last_end = float(self.journal.doc.get("last_cycle_end_ts") or 0.0)
        if last_skip is not None and float(last_skip) >= last_end:
            return None
        self.journal.doc["last_skip_ts"] = round(now, 3)
        rec = self.journal.record(
            "skip", now, reason="cooldown",
            cooldown_s=self.config.cooldown_s, trigger=trig)
        obs.counter("refresh.skips").inc()
        return rec

    # -------------------------------------------------------------- retrain
    def _retrain(self, now: float) -> Dict[str, Any]:
        gen = self.registry.next_generation(self.key)
        with obs.span("refresh.retrain", kind="phase"):
            info = dict(self.retrain_fn(self, gen) or {})
        self._candidate = info.pop("models", None) \
            or info.get("models_dir")
        if self._candidate is None:
            raise RuntimeError("retrain_fn returned no candidate "
                               "(models= or models_dir=)")
        obs.counter("refresh.retrains").inc()
        done = self.clock()
        rec = self.journal.record(
            "train", done, gen=gen,
            duration_s=round(done - now, 3),
            **{k: v for k, v in info.items()
               if isinstance(v, (str, int, float, bool, type(None)))})
        self.journal.set_stage(
            TRAINED, candidate=info.get("models_dir"), candidate_gen=gen)
        return rec

    def _load_candidate(self) -> bool:
        """Resume path: re-resolve the candidate from the journal after
        a controller death (only dir-backed candidates survive)."""
        cand = self.journal.doc.get("candidate")
        if cand and os.path.isdir(cand):
            self._candidate = cand
            return True
        return False

    # ------------------------------------------------------- gate / promote
    def _gate_and_promote(self, now: float) -> Dict[str, Any]:
        gen = int(self.journal.doc.get("candidate_gen") or 0)
        incumbent = int(self.journal.doc.get("incumbent_gen") or 0)
        if self.registry.generation(self.key) > incumbent:
            # the swap committed before a previous controller died —
            # adopt the promotion instead of re-promoting
            rec = self.journal.record("promote", now,
                                      gen=self.registry.generation(
                                          self.key),
                                      resumed=True)
            self._enter_probation(now)
            return rec
        if self._candidate is None and not self._load_candidate():
            # in-memory candidate lost with the previous controller —
            # fall back one stage and retrain
            log.warning("refresh resume: candidate gone, re-entering "
                        "retrain")
            self.journal.set_stage(TRIGGERED)
            return self._retrain(now)
        gate = self.gate_fn(self, self._candidate)
        if not gate.passed:
            archived = self._archive_reject(gate, gen)
            obs.counter("refresh.rejections").inc()
            rec = self.journal.record("reject", self.clock(), gen=gen,
                                      gate=gate.report(),
                                      archived=archived)
            self._finish_cycle("rejected")
            return rec
        faults.fire("refresh", "promote", self.key)
        if self.server is not None:
            self.server.swap(self._candidate)
        else:
            self.registry.swap(self.key, self._candidate, warm=self.warm)
        promoted = self.registry.generation(self.key)
        obs.counter("refresh.promotions").inc()
        rec = self.journal.record("promote", self.clock(), gen=promoted,
                                  gate=gate.report())
        self._enter_probation(self.clock())
        return rec

    def _archive_reject(self, gate, gen: int) -> Optional[str]:
        """A rejected candidate is archived beside its eval report — the
        incumbent stays live and the evidence stays on disk."""
        adir = self.journal.archive_dir(gen)
        os.makedirs(adir, exist_ok=True)
        if isinstance(self._candidate, str) \
                and os.path.isdir(self._candidate):
            dst = os.path.join(adir, "models")
            if not os.path.isdir(dst):
                os.rename(self._candidate, dst)
        atomic_write_json(os.path.join(adir, "eval_report.json"),
                          {"gate": gate.report(), "gen": gen,
                           "cycle": self.journal.cycle})
        self._candidate = None
        return adir

    def _enter_probation(self, now: float) -> None:
        self._capture_canary()
        self.journal.set_stage(
            PROBATION,
            promoted_gen=self.registry.generation(self.key),
            probation_until=round(now + self.config.probation_s, 3))

    # ------------------------------------------------------------ probation
    def _capture_canary(self) -> None:
        """Pin a canary batch + the freshly promoted generation's scores
        for it (bit-parity is re-checked through probation; persisted so
        a restarted controller keeps checking)."""
        xb = self._canary_rows()
        if xb is None:
            self._canary = None
            return
        x, bins = xb
        scorer = self.registry.get(self.key)
        try:
            expected = np.asarray(scorer.score_batch(
                x, bins if scorer.needs_bins else None))
        except Exception:
            log.warning("canary capture failed — probation runs on SLO "
                        "signals only", exc_info=True)
            self._canary = None
            return
        self._canary = {"x": x, "bins": bins, "expected": expected,
                        "gen": self.registry.generation(self.key)}
        payload = {"x": x, "expected": expected,
                   "gen": np.asarray(self._canary["gen"], np.int64)}
        if bins is not None:
            payload["bins"] = bins
        atomic_savez(os.path.join(self.journal.root, CANARY_BASENAME),
                     **payload)

    def _canary_rows(self):
        """Canary input: the head of the newest holdout window, sliced
        to the live scorer's serving signature (``n_features`` /
        ``n_bins_cols`` are a prefix of the materialized planes — the
        same contract serve requests follow).  None when no plane is
        materialized (in-memory test rigs) or it can't cover the
        signature."""
        try:
            from ..eval.gate import load_holdout
            h = load_holdout(self.dir, max_rows=self.config.canary_rows)
        except (OSError, ValueError):
            return None
        scorer = self.registry.get(self.key)
        nf = int(getattr(scorer, "n_features", h.x.shape[1]))
        nb = int(getattr(scorer, "n_bins_cols", 0))
        if h.x.shape[1] < nf or (nb and (h.bins is None
                                         or h.bins.shape[1] < nb)):
            return None
        x = np.ascontiguousarray(h.x[:, :nf], np.float32)
        bins = None
        if nb and h.bins is not None:
            bins = np.ascontiguousarray(h.bins[:, :nb])
        return x, bins

    def _restore_canary(self) -> None:
        path = os.path.join(self.journal.root, CANARY_BASENAME)
        try:
            d = np.load(path)
            self._canary = {"x": np.asarray(d["x"]),
                            "bins": np.asarray(d["bins"])
                            if "bins" in d else None,
                            "expected": np.asarray(d["expected"]),
                            "gen": int(d["gen"])}
        except (OSError, ValueError, KeyError):
            self._canary = None

    def _slo_alerts(self) -> List[dict]:
        if self.slo_alerts_fn is not None:
            return list(self.slo_alerts_fn() or [])
        if self.server is not None:
            return list(self.server.slo.alerts())
        # daemon mode: the serving fleet's heartbeats carry the compact
        # SLO summary — a firing alert on any SERVE proc is the signal
        from ..obs.health import health_dir_for, read_health
        out = []
        for rec in read_health(health_dir_for(self.dir)):
            slo = rec.get("slo") or {}
            if rec.get("step") == "SERVE" and slo.get("alerting"):
                out.append({"severity": "page",
                            "budget": ",".join(slo.get("alerts") or [])
                            or "burn", "proc": rec.get("proc")})
        return out

    def _probation_breach(self) -> Optional[str]:
        alerts = self._slo_alerts()
        if alerts:
            a = alerts[0]
            return f"slo-burn:{a.get('severity', '?')}:" \
                   f"{a.get('budget', '?')}"
        if self._canary is None:
            self._restore_canary()
        can = self._canary
        if can is not None \
                and can["gen"] == self.registry.generation(self.key):
            scorer = self.registry.get(self.key)
            try:
                got = np.asarray(scorer.score_batch(
                    can["x"], can["bins"] if scorer.needs_bins else None))
            except Exception:
                log.warning("canary rescore failed", exc_info=True)
                return "canary-error"
            if got.tobytes() != can["expected"].tobytes():
                return "canary-parity"
        return None

    def _probation(self, now: float) -> Optional[Dict[str, Any]]:
        promoted = int(self.journal.doc.get("promoted_gen") or 0)
        reason = self._probation_breach()
        if reason is not None:
            faults.fire("refresh", "rollback", self.key)
            self.registry.rollback(self.key, warm=self.warm)
            obs.counter("refresh.rollbacks").inc()
            rec = self.journal.record(
                "rollback", self.clock(), reason=reason,
                from_gen=promoted,
                gen=self.registry.generation(self.key))
            self._finish_cycle("rolled_back")
            return rec
        until = float(self.journal.doc.get("probation_until") or 0.0)
        if now >= until:
            rec = self.journal.record("complete", now, gen=promoted)
            self._finish_cycle("promoted")
            return rec
        return None

    def _finish_cycle(self, outcome: str) -> None:
        self.journal.end_cycle(outcome, self.clock())
        self._candidate = None
        self._canary = None
        # the next cycle drifts against a FRESH live window — a breach
        # the cycle just answered must re-accumulate to re-trigger
        self._drift = self._fresh_drift()
        # same for live quality: the just-answered degradation must not
        # re-trigger off the old generation's windows
        if self.server is not None \
                and getattr(self.server, "quality", None) is not None:
            self.server.quality.reset_windows()

    # ------------------------------------------------------------ run modes
    def run_once(self, poll_s: float = 0.5,
                 timeout_s: float = 3600.0) -> str:
        """Drive at most one full cycle to completion (the ``shifu-tpu
        refresh`` one-shot): returns ``no-trigger`` when nothing fired,
        else the cycle outcome (promoted / rejected / rolled_back)."""
        rec = self.tick()
        if self.journal.stage == IDLE:
            # nothing fired (or only a cooldown skip): report THAT, not
            # a previous cycle's outcome
            if rec is None:
                return "no-trigger"
            if rec.get("kind") == "skip":
                return "skipped"
            return str(self.journal.doc.get("last_outcome"))
        deadline = self.clock() + timeout_s
        while self.journal.stage != IDLE:
            if self.clock() >= deadline:
                return "timeout"
            self.sleep(poll_s)
            self.tick()
        return str(self.journal.doc.get("last_outcome"))

    def run(self, poll_s: float = 2.0, max_ticks: Optional[int] = None,
            stop: Optional[Callable[[], bool]] = None) -> None:
        """The ``--daemon`` loop: tick forever (``max_ticks`` / ``stop``
        bound it for tests), logging decisions as they commit."""
        self.start()
        ticks = 0
        try:
            while True:
                try:
                    rec = self.tick()
                except faults.InjectedFault:
                    raise
                except Exception:
                    log.exception("refresh tick failed — retrying after "
                                  "poll interval")
                    rec = None
                if rec is not None:
                    log.info("refresh decision: %s (cycle %d)",
                             rec.get("kind"), rec.get("cycle", -1))
                ticks += 1
                if max_ticks is not None and ticks >= max_ticks:
                    return
                if stop is not None and stop():
                    return
                self.sleep(poll_s)
        finally:
            self.stop()


# ------------------------------------------------------ default hooks
def _default_retrain(controller: RefreshController, gen: int) -> dict:
    from .retrain import warm_retrain
    return warm_retrain(controller.dir, gen, journal=controller.journal,
                        units=controller.config.units)


def _default_gate(controller: RefreshController, candidate):
    from ..eval.gate import auc_gate, load_holdout
    from ..eval.scorer import Scorer
    holdout = load_holdout(controller.dir,
                           max_rows=controller.config.holdout_rows)
    old = controller.registry.get(controller.key).models
    new = Scorer.from_dir(candidate).models \
        if isinstance(candidate, str) else list(candidate)
    return auc_gate(old, new, holdout,
                    min_delta=controller.config.min_auc_delta)
