"""Warm-start retraining for the refresh loop — never a cold re-run.

One refresh retrain = one real ``train`` step, warmed three ways:

- **trainer state** — ``params["resume"]=True`` restores the PR-4
  checkpoints: NN/WDL get (params, opt state, RNG, early-stop window)
  back from ``tmp/checkpoints/ckpt-<epoch>.npz``; GBT/RF restore the
  mid-forest checkpoint and its byte-exact per-row score sidecar
  (``forest_ckpt.npz.scores.npz``) and APPEND trees on the boosted
  residuals — the reference's full-Hadoop-re-run cost collapses to
  "grow a little more model on the new rows";
- **data-window cursor** — the refresh journal tracks how many rows of
  the materialized plane earlier trainings consumed;
  ``params["window_cursor"]`` hands the trainers a shard-aligned view
  starting there, so a warm retrain streams the NEW windows only (with
  no new rows it falls back to the freshest shard — the most recent
  distribution is still the right thing to fit);
- **unit budget** — ``params["refresh_extra"]`` asks for N MORE
  epochs/trees past the restored state (``-Dshifu.refresh.units``;
  0 derives the configured ``numTrainEpochs`` / ``TreeNum`` — the same
  budget as a fresh run, warm-started).

The trained models are copied into an immutable candidate dir under
``<modelset>/refresh/candidates/gen-<N>/`` — the registry promotes (or
the archive keeps) THAT dir; ``<modelset>/models`` stays the training
workspace.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)


def _tree_alg(alg_name: str) -> bool:
    return alg_name in ("GBT", "RF", "DT")


def _warm_evidence(paths, alg_name: str) -> int:
    """Restorable trainer state BEFORE the retrain runs: the checkpoint
    epoch (NN/LR/WDL/SVM) or the forest checkpoint's tree count — 0
    means the retrain will cold-start (no checkpoint to resume)."""
    if _tree_alg(alg_name):
        meta = os.path.join(paths.checkpoint_dir,
                            "forest_ckpt.npz.meta.json")
        try:
            with open(meta) as f:
                return int(json.load(f).get("trees_done") or 0)
        except (OSError, ValueError):
            return 0
    from ..train import checkpoint as ckpt
    return int(ckpt.latest_epoch(paths.checkpoint_dir) or 0)


def derived_units(mc) -> int:
    """The default warm budget: the configured fresh-run budget, spent
    from a warm start on the new window."""
    if _tree_alg(mc.train.algorithm.name):
        return int((mc.train.params or {}).get("TreeNum", 100))
    return int(mc.train.numTrainEpochs)


def warm_retrain(model_set_dir: str, gen: int, journal=None,
                 units: int = 0,
                 extra_params: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Run one warm retrain and stage the result as candidate ``gen``.
    Returns the decision-record payload (``models_dir``, ``warm``,
    ``resumed_from``, ``units``, cursor accounting)."""
    from ..config import ModelConfig, PathFinder
    from ..data.shards import Shards
    from ..pipeline.train import TrainProcessor
    from .journal import RefreshJournal

    journal = journal or RefreshJournal(model_set_dir)
    mc = ModelConfig.load(os.path.join(model_set_dir,
                                       "ModelConfig.json"))
    alg = mc.train.algorithm.name
    paths = PathFinder(mc, model_set_dir)
    resumed_from = _warm_evidence(paths, alg)
    units = int(units) if units else derived_units(mc)

    plane_dir = paths.clean_dir if _tree_alg(alg) else paths.norm_dir
    total = Shards.open(plane_dir).num_rows
    cursor = min(journal.data_cursor, total)

    t0 = time.perf_counter()
    rc = TrainProcessor(model_set_dir, params={
        "resume": True,
        "window_cursor": cursor,
        "refresh_extra": units,
        **(extra_params or {})}).run()
    if rc != 0:
        raise RuntimeError(f"warm retrain failed: train step rc={rc}")

    cand = journal.candidate_dir(gen)
    os.makedirs(cand, exist_ok=True)
    copied = 0
    for f in sorted(os.listdir(paths.models_dir)):
        if f.startswith("model"):
            shutil.copy2(os.path.join(paths.models_dir, f),
                         os.path.join(cand, f))
            copied += 1
    if not copied:
        raise RuntimeError(f"warm retrain produced no model files in "
                           f"{paths.models_dir}")
    journal.set_cursor(total)
    return {
        "models_dir": cand,
        "algorithm": alg,
        "warm": resumed_from > 0,
        "resumed_from": resumed_from,
        "units": units,
        "cursor_rows": cursor,
        "new_rows": max(total - cursor, 0),
        "train_s": round(time.perf_counter() - t0, 3),
    }
