"""Refresh-cycle journal — the controller's crash-consistency spine.

The continual-refresh loop is a long-running state machine (idle →
triggered → trained → probation → idle) whose every transition matters
operationally: WHY did the fleet retrain at 03:12, WHICH candidate was
rejected, WHAT rolled back.  This module commits that state the same way
the PR-4 step journals commit pipeline work:

- ``<modelset>/refresh/state.json`` — the live cycle state, atomically
  rewritten (:mod:`shifu_tpu.ioutil`) at every transition.  A killed
  controller re-reads it on restart and resumes its loop mid-cycle: a
  death after retraining re-enters at the gate, a death after the
  registry swap adopts the promotion and enters probation — never a
  duplicate retrain, never a forgotten candidate.
- ``<modelset>/refresh/decisions/`` — one immutable record per decision
  (``trigger`` / ``skip`` / ``train`` / ``promote`` / ``reject`` /
  ``rollback`` / ``complete``), written once via the atomic tmp+rename
  discipline.  The decision stream IS the audit log the monitor line and
  post-mortems read.

Timestamps come from the caller (the controller's injectable clock), so
tests drive the whole lifecycle with a fake clock and zero sleeps.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional

from ..ioutil import atomic_write_json, sweep_orphan_tmp

log = logging.getLogger(__name__)

JOURNAL_VERSION = 1

REFRESH_DIRNAME = "refresh"
STATE_BASENAME = "state.json"
DECISIONS_DIRNAME = "decisions"
CANDIDATES_DIRNAME = "candidates"
ARCHIVE_DIRNAME = "archive"

# cycle stages (the resume points)
IDLE = "idle"
TRIGGERED = "triggered"          # trigger committed, retrain owed
TRAINED = "trained"              # candidate built, gate + promote owed
PROBATION = "probation"          # promoted, watching the SLO window

STAGES = (IDLE, TRIGGERED, TRAINED, PROBATION)

DECISION_KINDS = ("trigger", "skip", "train", "promote", "reject",
                  "rollback", "complete")


def refresh_dir_for(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), REFRESH_DIRNAME)


class RefreshJournal:
    """Cycle state + append-only decision records for ONE model set."""

    def __init__(self, model_set_dir: str):
        self.root = refresh_dir_for(model_set_dir)
        self.state_path = os.path.join(self.root, STATE_BASENAME)
        self.decisions_dir = os.path.join(self.root, DECISIONS_DIRNAME)
        self.doc: Dict[str, Any] = self._load()

    # --------------------------------------------------------------- state
    def _load(self) -> Dict[str, Any]:
        try:
            with open(self.state_path) as f:
                doc = json.load(f)
            if doc.get("version") == JOURNAL_VERSION \
                    and doc.get("stage") in STAGES:
                return doc
            log.warning("refresh journal %s has unknown version/stage — "
                        "starting a fresh state", self.state_path)
        except (OSError, ValueError):
            pass
        return {"version": JOURNAL_VERSION, "stage": IDLE, "cycle": 0,
                "seq": 0, "last_decision": None,
                "last_cycle_end_ts": None, "data_cursor": 0}

    def _flush(self) -> None:
        os.makedirs(self.root, exist_ok=True)
        atomic_write_json(self.state_path, self.doc)

    @property
    def stage(self) -> str:
        return self.doc.get("stage") or IDLE

    @property
    def cycle(self) -> int:
        return int(self.doc.get("cycle") or 0)

    def candidate_dir(self, gen: int) -> str:
        return os.path.join(self.root, CANDIDATES_DIRNAME, f"gen-{gen}")

    def archive_dir(self, gen: int) -> str:
        return os.path.join(self.root, ARCHIVE_DIRNAME, f"gen-{gen}")

    # ------------------------------------------------------------ decisions
    def record(self, kind: str, ts: float, **fields) -> Dict[str, Any]:
        """Commit one immutable decision record + fold it into the live
        state.  ``ts`` is the controller's clock (injectable)."""
        if kind not in DECISION_KINDS:
            raise ValueError(f"unknown refresh decision kind {kind!r}")
        seq = int(self.doc.get("seq") or 0)
        rec = {"kind": kind, "seq": seq, "cycle": self.cycle,
               "ts": round(float(ts), 3), **fields}
        os.makedirs(self.decisions_dir, exist_ok=True)
        sweep_orphan_tmp(self.decisions_dir)
        atomic_write_json(
            os.path.join(self.decisions_dir, f"d{seq:06d}-{kind}.json"),
            rec)
        self.doc["seq"] = seq + 1
        self.doc["last_decision"] = {"kind": kind, "seq": seq,
                                     "cycle": self.cycle,
                                     "ts": rec["ts"]}
        self._flush()
        return rec

    def decisions(self) -> List[Dict[str, Any]]:
        """All parseable decision records, in commit order."""
        out: List[Dict[str, Any]] = []
        try:
            names = sorted(os.listdir(self.decisions_dir))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.decisions_dir, name)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                log.warning("skipping unparseable refresh decision %s",
                            name)
                continue
            if isinstance(rec, dict):
                out.append(rec)
        return out

    # ------------------------------------------------------------ lifecycle
    def begin_cycle(self, trigger: Dict[str, Any], ts: float,
                    incumbent_gen: int) -> None:
        self.doc["cycle"] = self.cycle + 1
        self.doc["stage"] = TRIGGERED
        self.doc["trigger"] = dict(trigger)
        self.doc["cycle_started_ts"] = round(float(ts), 3)
        self.doc["incumbent_gen"] = int(incumbent_gen)
        for k in ("candidate", "candidate_gen", "gate", "promoted_gen",
                  "probation_until"):
            self.doc.pop(k, None)
        self._flush()

    def set_stage(self, stage: str, **fields) -> None:
        if stage not in STAGES:
            raise ValueError(f"unknown refresh stage {stage!r}")
        self.doc["stage"] = stage
        self.doc.update(fields)
        self._flush()

    def end_cycle(self, outcome: str, ts: float) -> None:
        """Close the cycle (promoted / rejected / rolled_back) — the
        cooldown window anchors on this timestamp."""
        self.doc["stage"] = IDLE
        self.doc["last_outcome"] = outcome
        self.doc["last_cycle_end_ts"] = round(float(ts), 3)
        self._flush()

    def set_cursor(self, rows: int) -> None:
        """Advance the data-window cursor: rows of the materialized plane
        already consumed by training (warm retrains start here)."""
        self.doc["data_cursor"] = int(rows)
        self._flush()

    @property
    def data_cursor(self) -> int:
        return int(self.doc.get("data_cursor") or 0)
