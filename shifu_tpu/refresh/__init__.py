"""Continual refresh subsystem — the drift-gated train→gate→promote
loop that turns the one-shot pipeline into a long-running service.

- :mod:`controller` — the :class:`RefreshController` state machine
  (trigger on PSI breach / schedule, warm retrain, AUC-gated hot-swap,
  SLO-observed probation with automatic rollback);
- :mod:`journal` — crash-consistent cycle state + the immutable
  decision-record stream under ``<modelset>/refresh/``;
- :mod:`retrain` — warm-start retraining over the data-window cursor
  (checkpoint resume, never a cold full re-run).
"""

from .controller import (RefreshConfig, RefreshController,  # noqa: F401
                         drift_columns_for)
from .journal import (IDLE, PROBATION, TRAINED,  # noqa: F401
                      TRIGGERED, RefreshJournal, refresh_dir_for)
from .retrain import warm_retrain  # noqa: F401

__all__ = [
    "RefreshConfig", "RefreshController", "drift_columns_for",
    "RefreshJournal", "refresh_dir_for", "warm_retrain",
    "IDLE", "TRIGGERED", "TRAINED", "PROBATION",
]
