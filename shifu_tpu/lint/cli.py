"""``shifu-tpu lint`` front-end: text + ``--json``, baseline workflow.

Exit codes: 0 clean (or everything grandfathered), 2 new findings or a
stale baseline, 1 usage trouble (unknown rule, unreadable baseline).
Output is byte-deterministic for a given tree — the CI guard diffs two
runs."""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .baseline import apply_baseline, load_baseline, write_baseline
from .engine import Finding, LintEngine
from .rules import ALL_RULES, make_rules

__all__ = ["add_lint_args", "run_lint", "run_lint_cli", "main",
           "default_target", "default_baseline_path", "repo_root"]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE_NAME = "lint-baseline.json"


def default_target() -> str:
    """The installed ``shifu_tpu`` package tree."""
    return _PKG_DIR


def repo_root() -> str:
    return os.path.dirname(_PKG_DIR)


def default_baseline_path() -> str:
    return os.path.join(repo_root(), BASELINE_NAME)


def run_lint(paths: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             full_tree: Optional[bool] = None,
             ) -> Tuple[List[Finding], LintEngine]:
    """Programmatic entry: lint ``paths`` (default: the whole package)
    and return the sorted findings.  ``full_tree`` enables cross-file
    checks (README knob table, dead knob declarations); by default it is
    on exactly when the scan covers the whole package."""
    paths = list(paths) if paths else [default_target()]
    if full_tree is None:
        tgt = os.path.realpath(default_target())
        full_tree = any(os.path.realpath(p) == tgt for p in paths)
    engine = LintEngine(make_rules(rules), root=root or repo_root(),
                        full_tree=full_tree)
    return engine.run(paths), engine


def add_lint_args(sp: argparse.ArgumentParser) -> None:
    sp.add_argument("lint_paths", nargs="*", metavar="PATH",
                    help="files/dirs to lint (default: the shifu_tpu "
                    "package)")
    sp.add_argument("--json", dest="lint_json", action="store_true",
                    help="machine-readable output (one JSON doc)")
    sp.add_argument("--rules", dest="lint_rules", default=None,
                    metavar="R1,R2", help="run only these rules")
    sp.add_argument("--list-rules", dest="lint_list", action="store_true",
                    help="print the rule catalogue and exit")
    sp.add_argument("--baseline", dest="lint_baseline", default=None,
                    metavar="FILE",
                    help="grandfather file (default: lint-baseline.json "
                    "at the repo root when present)")
    sp.add_argument("--no-baseline", dest="lint_no_baseline",
                    action="store_true",
                    help="ignore any baseline: report every finding")
    sp.add_argument("--update-baseline", dest="lint_update",
                    action="store_true",
                    help="rewrite the baseline from the current findings "
                    "(review the diff — a growing baseline is the smell)")


def _render_text(new: List[Finding], old: List[Finding],
                 stale: List[Tuple[str, str, str]],
                 engine: LintEngine, elapsed_s: float) -> str:
    out: List[str] = []
    for f in new:
        out.append(f.render())
    if old:
        out.append(f"({len(old)} grandfathered finding(s) absorbed by "
                   "the baseline)")
    for rule, path, message in stale:
        out.append(f"stale baseline entry: {rule}: {path}: {message}")
    verdict = "clean" if not (new or stale) else \
        f"{len(new)} new finding(s)" + \
        (f", {len(stale)} stale baseline entr(ies)" if stale else "")
    out.append(f"shifu-tpu lint: {engine.files_scanned} file(s), "
               f"{verdict}  [{elapsed_s:.2f}s]")
    return "\n".join(out)


def run_lint_cli(args: argparse.Namespace) -> int:
    if getattr(args, "lint_list", False):
        for cls in ALL_RULES:
            print(f"{cls.name}")
            print(f"    {cls.doc}")
        return 0
    rules = None
    if getattr(args, "lint_rules", None):
        rules = [r.strip() for r in args.lint_rules.split(",") if r.strip()]
    t0 = time.perf_counter()
    try:
        findings, engine = run_lint(getattr(args, "lint_paths", None),
                                    rules=rules)
    except ValueError as e:
        print(f"shifu-tpu lint: {e}", file=sys.stderr)
        return 1

    bl_path = getattr(args, "lint_baseline", None) or \
        default_baseline_path()
    explicit = getattr(args, "lint_baseline", None) is not None
    baseline: Dict = {}
    if not getattr(args, "lint_no_baseline", False) \
            and not getattr(args, "lint_update", False):
        try:
            baseline = load_baseline(bl_path)
        except (ValueError, OSError, json.JSONDecodeError) as e:
            if explicit or os.path.exists(bl_path):
                print(f"shifu-tpu lint: bad baseline: {e}",
                      file=sys.stderr)
                return 1

    if getattr(args, "lint_update", False):
        write_baseline(bl_path, findings)
        print(f"baseline -> {bl_path}  ({len(findings)} finding(s) "
              "grandfathered)")
        return 0

    new, old, stale = apply_baseline(findings, baseline)
    elapsed = time.perf_counter() - t0
    if getattr(args, "lint_json", False):
        doc = {
            "files_scanned": engine.files_scanned,
            "new": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in old],
            "stale_baseline": [{"rule": r, "path": p, "message": m}
                               for r, p, m in stale],
            "elapsed_s": round(elapsed, 3),
        }
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(_render_text(new, old, stale, engine, elapsed))
    return 2 if (new or stale) else 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="shifu-tpu lint")
    add_lint_args(p)
    return run_lint_cli(p.parse_args(list(argv) if argv is not None
                                     else None))


if __name__ == "__main__":
    raise SystemExit(main())
