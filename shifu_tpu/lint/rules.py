"""The rule catalogue — this codebase's implicit contracts, as checks.

Each rule encodes a convention earlier PRs established but nothing
enforced (see the module docstrings it references):

- ``host-sync-hot-path``   — no ``.item()`` / ``np.asarray`` / traced
  ``float()``/``int()`` inside jitted / ``costed_jit`` functions, and no
  per-window forced fetches inside streamed window loops (the sync-free
  growth contract of PR 3; ``train.host_syncs`` exists to count the few
  sanctioned ones);
- ``recompile-hazard``     — named hot-path executables in ``train/``,
  ``serve/`` and ``pipeline/`` route through ``obs.costed_jit`` so the
  recompile sentinel sees them (PR 8), and executable names are never
  interpolated f-strings (per-name dedup would count every distinct
  name once and the sentinel goes blind);
- ``knob-registry``        — every ``-Dshifu.*`` / ``SHIFU_*`` literal
  read or mentioned anywhere resolves against ``config/knobs.py``;
- ``atomic-write``         — artifact writes are tmp+``os.replace``
  atomic via ``ioutil`` (PR 4), never a raw ``open(path, "w")``;
- ``telemetry-guard``      — instrument *factory* lookups stay out of
  hot loops (hoist the handle; the zero-cost-when-disabled contract of
  PR 1/7 is only zero-cost when the name lookup isn't per-iteration);
- ``metric-manifest`` / ``span-manifest`` / ``fault-site`` — the
  grep-based manifest lints that lived in ``tests/test_obs_plane.py``,
  now first-class AST rules (names resolve against ``obs/manifest.py``
  and ``faults.SITES``).
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .engine import (FileContext, LintEngine, Rule, call_name,
                     fstring_head, qualname, str_const)

__all__ = ["ALL_RULES", "make_rules"]

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_light(rel: str, alias: str):
    """Import a dependency-free module by file path, dodging package
    ``__init__`` chains (``shifu_tpu.obs`` pulls jax; the linter must
    stay import-light so a full-tree run clears the <5 s guard cold)."""
    name = f"_shifu_lint_{alias}"
    if name in sys.modules:
        return sys.modules[name]
    path = os.path.join(_PKG_DIR, rel)
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _knobs():
    return _load_light(os.path.join("config", "knobs.py"), "knobs")


def _obs_manifest():
    return _load_light(os.path.join("obs", "manifest.py"), "obs_manifest")


def _fault_sites() -> Dict[Tuple[str, str], str]:
    return _load_light("faults.py", "faults").SITES


# --------------------------------------------------------------- helpers
_JIT_NAMES = ("jax.jit", "jit")


def _is_jit_decorator(dec: ast.AST) -> bool:
    if qualname(dec) in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fq = call_name(dec)
        if fq in _JIT_NAMES or fq.endswith("costed_jit"):
            return True
        if fq in ("partial", "functools.partial") and dec.args:
            aq = qualname(dec.args[0])
            if aq in _JIT_NAMES or aq.endswith("costed_jit"):
                return True
    return False


def _static_argnames(fn: ast.AST) -> Set[str]:
    """Names bound statically by the jit decorator — ``float()``/
    ``int()`` over these is host math, not a device sync."""
    out: Set[str] = set()
    for dec in getattr(fn, "decorator_list", ()):
        if not isinstance(dec, ast.Call):
            continue
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for el in ast.walk(kw.value):
                    s = str_const(el)
                    if s:
                        out.add(s)
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             (*a.posonlyargs, *a.args, *a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _enclosing(parents: Sequence[ast.AST], *types) -> Optional[ast.AST]:
    for node in reversed(parents):
        if isinstance(node, types):
            return node
    return None


def _enclosing_jit_fn(parents: Sequence[ast.AST]) -> Optional[ast.AST]:
    for node in reversed(parents):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_decorator(d) for d in node.decorator_list):
                return node
    return None


_WINDOW_ITERS = (".prepared(", ".windows(", ".tail_items(")


def _enclosing_window_loop(parents: Sequence[ast.AST],
                           ctx: FileContext) -> Optional[ast.For]:
    """Nearest enclosing ``for`` whose iterable is a streamed window
    source (``stream.prepared(...)`` / ``.windows(...)`` /
    ``cache.tail_items(...)``) — the per-window hot loop."""
    for node in reversed(parents):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        if isinstance(node, ast.For):
            it = ctx.src(node.iter)
            if any(w in it for w in _WINDOW_ITERS):
                return node
    return None


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


# ---------------------------------------------------------------- rule 1
class HostSyncRule(Rule):
    name = "host-sync-hot-path"
    doc = ("no .item()/.tolist()/np.asarray()/jax.device_get() and no "
           "float()/int() over traced parameters inside jitted/"
           "costed_jit functions; no forced per-window fetches inside "
           "streamed window loops")
    interests = (ast.Call,)

    _NP_SYNCS = ("np.asarray", "np.array", "np.asanyarray",
                 "numpy.asarray", "numpy.array", "jax.device_get")

    def visit(self, node: ast.Call, parents, ctx) -> None:
        func = node.func
        is_item = (isinstance(func, ast.Attribute)
                   and func.attr in ("item", "tolist") and not node.args)
        fq = call_name(node)
        jit_fn = _enclosing_jit_fn(parents)
        if jit_fn is not None:
            if is_item:
                self.report(ctx, node,
                            f".{func.attr}() inside the jitted function "
                            f"'{jit_fn.name}' forces a device->host sync "
                            "(or breaks tracing) — return the value and "
                            "fetch outside the executable")
                return
            if fq in self._NP_SYNCS:
                self.report(ctx, node,
                            f"{fq}() inside the jitted function "
                            f"'{jit_fn.name}' materializes a traced value "
                            "on host — use jnp inside the trace")
                return
            if fq in ("float", "int") and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                traced = (_param_names(jit_fn) - _static_argnames(jit_fn))
                if _names_in(node.args[0]) & traced:
                    self.report(ctx, node,
                                f"{fq}() over a traced parameter of "
                                f"'{jit_fn.name}' forces a host sync — "
                                "keep it in-graph or mark the argument "
                                "static")
                return
        if not (is_item or fq == "jax.device_get"):
            return
        loop = _enclosing_window_loop(parents, ctx)
        if loop is not None:
            what = f".{func.attr}()" if is_item else f"{fq}()"
            self.report(ctx, node,
                        f"{what} inside a streamed window loop syncs the "
                        "device every window — accumulate on device and "
                        "fetch once after the sweep (train.host_syncs "
                        "counts the sanctioned packed fetches)")


# ---------------------------------------------------------------- rule 2
class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    doc = ("hot-path layers (train/, serve/, pipeline/) route named "
           "executables through obs.costed_jit so the recompile "
           "sentinel sees them; executable names are never interpolated "
           "f-strings (per-name dedup would go blind)")
    interests = (ast.Call, ast.FunctionDef, ast.AsyncFunctionDef)

    _HOT_LAYERS = ("train", "serve", "pipeline")

    def _hot(self, ctx: FileContext) -> bool:
        parts = ctx.rel_path.split("/")
        return any(p in self._HOT_LAYERS for p in parts[:-1])

    def visit(self, node, parents, ctx) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if not self._hot(ctx):
                return
            for dec in node.decorator_list:
                if self._is_bare_jit(dec):
                    self.report(
                        ctx, dec,
                        f"bare jax.jit decorating '{node.name}' in a "
                        "hot-path layer — route through obs.costed_jit("
                        "name, ...) so the recompile sentinel and the "
                        "cost plane see this executable",
                        line=dec.lineno)
            return
        fq = call_name(node)
        if fq.endswith("costed_jit") or fq.endswith("record_executable"):
            if node.args and isinstance(node.args[0], ast.JoinedStr) \
                    and any(isinstance(v, ast.FormattedValue)
                            for v in node.args[0].values):
                self.report(ctx, node,
                            f"f-string executable name passed to {fq} — "
                            "every distinct interpolation mints a new "
                            "name, so the sentinel's per-name recompile "
                            "dedup never fires; use a fixed name (or a "
                            "bounded, shape-keyed family registered "
                            "per-bucket like serve does)")
            return
        if fq in _JIT_NAMES and self._hot(ctx):
            self.report(ctx, node,
                        "bare jax.jit() call in a hot-path layer — wrap "
                        "with obs.costed_jit(name, fn, ...) so the "
                        "recompile sentinel and cost attribution see "
                        "the executable")

    @staticmethod
    def _is_bare_jit(dec: ast.AST) -> bool:
        if qualname(dec) in _JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):
            fq = call_name(dec)
            if fq in _JIT_NAMES:
                return True
            if fq in ("partial", "functools.partial") and dec.args \
                    and qualname(dec.args[0]) in _JIT_NAMES:
                return True
        return False


# ---------------------------------------------------------------- rule 3
_PROP_READS = ("get_property", "get_int", "get_float", "get_bool",
               "set_property")
_KNOB_MENTION_RE = re.compile(
    # the lookbehinds keep reference Java packages (ml.shifu.shifu.*)
    # and prefixed env names out of the mention scan
    r"-D(shifu\.[A-Za-z0-9_.]+)"
    r"|(?<![\w.])(SHIFU_[A-Z0-9][A-Z0-9_]*)"
    r"|(?<![\w.])(shifu\.[A-Za-z][A-Za-z0-9_.]*)")


class KnobRegistryRule(Rule):
    name = "knob-registry"
    doc = ("every -Dshifu.* / SHIFU_* literal read or mentioned in "
           "shifu_tpu/ must be declared in config/knobs.py (and every "
           "declared knob must appear in the README table and be "
           "referenced somewhere)")
    interests = (ast.Call, ast.Subscript, ast.Constant)

    _SKIP_FILES = ("config/knobs.py",)

    def __init__(self) -> None:
        super().__init__()
        self.knobs = _knobs()
        self.referenced: Set[str] = set()   # normalized declared names hit

    def _skip(self, ctx: FileContext) -> bool:
        return any(ctx.rel_path.endswith(s) for s in self._SKIP_FILES)

    def _note(self, token: str) -> None:
        k = self.knobs
        if token in k.KNOBS:
            self.referenced.add(token)
        else:
            tl = token.lower()
            for n in k.KNOBS:
                if n.lower() == tl or n.lower().startswith(tl):
                    self.referenced.add(n)

    def _check_read(self, token: str, node, ctx,
                    where: str) -> None:
        if not (token.startswith("shifu.") or token.startswith("SHIFU_")):
            return
        if self.knobs.is_declared(token):
            self._note(token)
            return
        self.report(ctx, node,
                    f"knob {token!r} read via {where} is not declared in "
                    "config/knobs.py — add a Knob(name, kind, type, "
                    "default, doc) entry (and the README table row)")

    def visit(self, node, parents, ctx) -> None:
        if self._skip(ctx):
            return
        if isinstance(node, ast.Call):
            fq = call_name(node)
            leaf = fq.rsplit(".", 1)[-1]
            if leaf in _PROP_READS and node.args:
                s = str_const(node.args[0])
                if s is not None:
                    self._check_read(s, node, ctx, f"{leaf}()")
                return
            if fq in ("os.getenv", "os.environ.get",
                      "environ.get") and node.args:
                s = str_const(node.args[0])
                if s is not None:
                    self._check_read(s, node, ctx, fq)
                return
            return
        if isinstance(node, ast.Subscript):
            if qualname(node.value) in ("os.environ", "environ"):
                s = str_const(node.slice)
                if s is not None:
                    self._check_read(s, node, ctx, "os.environ[]")
            return
        # mentions in docstrings / help text / messages (f-string parts
        # arrive here too — JoinedStr children are Constant nodes)
        text = str_const(node)
        if text is None:
            return
        if self._in_read_call(node, parents):
            return                       # already judged by the read branch
        for m in _KNOB_MENTION_RE.finditer(text):
            token = (m.group(1) or m.group(2) or m.group(3)).rstrip(".")
            if token in ("shifu", "SHIFU"):
                continue
            if self.knobs.is_declared(token) \
                    or self.knobs.is_declared_prefix(token):
                self._note(token)
                continue
            self.report(ctx, node,
                        f"mention of undeclared knob {token!r} — "
                        "declare it in config/knobs.py or fix the "
                        "doc (dead knobs rot)")

    @staticmethod
    def _in_read_call(node: ast.AST, parents) -> bool:
        """Is this literal the key argument of a read call / env
        subscript the read branch already checked?"""
        if not parents:
            return False
        parent = parents[-1]
        if isinstance(parent, ast.Call):
            fq = call_name(parent)
            leaf = fq.rsplit(".", 1)[-1]
            if (leaf in _PROP_READS
                    or fq in ("os.getenv", "os.environ.get",
                              "environ.get")) \
                    and parent.args and parent.args[0] is node:
                return True
        if isinstance(parent, ast.Subscript) \
                and qualname(parent.value) in ("os.environ", "environ"):
            return True
        return False

    def finish(self, engine: LintEngine) -> None:
        knobs_rel = "shifu_tpu/config/knobs.py"
        readme = os.path.join(engine.root, "README.md")
        readme_text = ""
        if os.path.isfile(readme):
            with open(readme, encoding="utf-8") as f:
                readme_text = f.read()
        for name, knob in sorted(self.knobs.KNOBS.items()):
            if readme_text and name not in readme_text:
                self.report_project(
                    knobs_rel,
                    f"declared knob {name!r} missing from the README "
                    "knob table — regenerate with "
                    "knobs.knob_table_markdown()")
            if name not in self.referenced:
                self.report_project(
                    knobs_rel,
                    f"declared knob {name!r} is never read or mentioned "
                    "in shifu_tpu/ — remove the dead declaration (or "
                    "wire the knob)")


# ---------------------------------------------------------------- rule 4
class AtomicWriteRule(Rule):
    name = "atomic-write"
    doc = ("artifact writes are atomic (ioutil tmp+os.replace) — a raw "
           "open(path, 'w')/np.save*(path) can leave a torn, committed-"
           "looking file for a resumed run to trust; json.dump/.write "
           "targets are caught at their open() site")
    interests = (ast.Call,)

    _NP_WRITERS = ("np.save", "np.savez", "np.savez_compressed",
                   "numpy.save", "numpy.savez", "numpy.savez_compressed")

    def _exempt_scope(self, parents, ctx) -> bool:
        """tmp-file discipline is the atomic pattern itself: a write
        whose enclosing function — or enclosing class, for write-
        through protocols like the spill cache (open .part in append(),
        os.replace in finish()) — calls os.replace() is exempt."""
        scope = _enclosing(parents, ast.FunctionDef, ast.AsyncFunctionDef)
        if scope is not None and self._calls_replace(scope):
            return True
        cls = _enclosing(parents, ast.ClassDef)
        if cls is not None and self._calls_replace(cls):
            return True
        if scope is None and cls is None and parents:
            return self._calls_replace(parents[0])
        return False

    @staticmethod
    def _calls_replace(scope: ast.AST) -> bool:
        for n in ast.walk(scope):
            if isinstance(n, ast.Call) and call_name(n) == "os.replace":
                return True
        return False

    @staticmethod
    def _buf_names(parents) -> Set[str]:
        scope = _enclosing(parents, ast.FunctionDef, ast.AsyncFunctionDef)
        if scope is None:
            return set()
        out: Set[str] = set()
        for n in ast.walk(scope):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                if call_name(n.value).rsplit(".", 1)[-1] == "BytesIO":
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    def visit(self, node: ast.Call, parents, ctx) -> None:
        if ctx.rel_path.endswith("ioutil.py"):
            return
        fq = call_name(node)
        if fq == "open" and node.args:
            mode = None
            if len(node.args) >= 2:
                mode = str_const(node.args[1])
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = str_const(kw.value)
            if not mode or not any(c in mode for c in "wax"):
                return                  # read modes (incl. r+b) pass
            path_src = ctx.src(node.args[0])
            if "tmp" in path_src.lower():
                return
            if self._exempt_scope(parents, ctx):
                return
            self.report(ctx, node,
                        f"raw open({path_src or '...'}, {mode!r}) — a "
                        "crash mid-write leaves a torn file; use "
                        "ioutil.atomic_write_text/json/bytes (or write "
                        "a .tmp and os.replace)")
            return
        if fq in self._NP_WRITERS and node.args:
            target = node.args[0]
            tsrc = ctx.src(target)
            if "tmp" in tsrc.lower() or "buf" in tsrc.lower():
                return
            if isinstance(target, ast.Name) \
                    and target.id in self._buf_names(parents):
                return
            if self._exempt_scope(parents, ctx):
                return
            self.report(ctx, node,
                        f"{fq}({tsrc or '...'}) writes the final path "
                        "directly — np.save* mid-crash leaves a torn "
                        "zip; use ioutil.atomic_savez (or a BytesIO + "
                        "atomic_write_bytes)")


# ---------------------------------------------------------------- rule 5
class TelemetryGuardRule(Rule):
    name = "telemetry-guard"
    doc = ("obs.counter/gauge/histogram factory lookups stay out of "
           "loops — hoist the instrument handle before the loop, or "
           "guard the block with obs.enabled() / a hoisted obs_on "
           "bool; the name lookup takes the registry lock per "
           "iteration even when telemetry is off (bench.py is exempt: "
           "its publishing loops run once per measured plane with "
           "telemetry force-enabled)")
    interests = (ast.Call,)

    _FACTORIES = ("counter", "gauge", "histogram")
    _BASES = ("obs", "registry", "_registry")
    _GUARDS = ("enabled(", "obs_on", "telemetry_on")

    def visit(self, node: ast.Call, parents, ctx) -> None:
        if ctx.rel_path.endswith("bench.py"):
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in self._FACTORIES
                and qualname(func.value) in self._BASES):
            return
        in_loop = False
        for p in reversed(parents):
            if isinstance(p, (ast.FunctionDef, ast.AsyncFunctionDef)):
                break
            if isinstance(p, (ast.For, ast.While)):
                in_loop = True
                break
        if not in_loop:
            return
        for p in reversed(parents):
            if isinstance(p, ast.If) \
                    and any(g in ctx.src(p.test) for g in self._GUARDS):
                return
        name = str_const(node.args[0]) if node.args else None
        self.report(ctx, node,
                    f"instrument factory {qualname(func.value)}."
                    f"{func.attr}({name!r}) inside a loop — hoist the "
                    "handle out of the loop or guard with obs.enabled() "
                    "(the per-iteration name lookup defeats the "
                    "zero-cost-when-disabled contract)")


# ------------------------------------------------------------ rules 6-8
class MetricManifestRule(Rule):
    name = "metric-manifest"
    doc = ("every obs.counter/gauge/histogram name literal resolves "
           "against obs/manifest.py with the declared instrument type; "
           "f-string families must start with a declared prefix (a "
           "typo'd name silently mints a NEW metric)")
    interests = (ast.Call,)

    _FACTORIES = ("counter", "gauge", "histogram")
    _BASES = ("obs", "registry", "_registry")

    def __init__(self) -> None:
        super().__init__()
        self.manifest = _obs_manifest()

    def visit(self, node: ast.Call, parents, ctx) -> None:
        if ctx.rel_path.endswith("obs/manifest.py"):
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in self._FACTORIES
                and qualname(func.value) in self._BASES):
            return
        if not node.args:
            return
        kind = func.attr
        arg = node.args[0]
        head = fstring_head(arg)
        if head is not None and isinstance(arg, ast.JoinedStr) \
                and any(isinstance(v, ast.FormattedValue)
                        for v in arg.values):
            if not any(head.startswith(p)
                       for p in self.manifest.PREFIXES):
                self.report(ctx, node,
                            f"f-string {kind} name {head + '...'!r} has "
                            "no declared prefix in obs.manifest.PREFIXES")
            return
        name = str_const(arg) if head is None else head
        if name is None:
            return
        if not self.manifest.is_declared(name):
            self.report(ctx, node,
                        f"{kind} {name!r} not declared in "
                        "obs.manifest.MANIFEST — a typo here would "
                        "silently mint a new metric")
        elif name in self.manifest.MANIFEST \
                and self.manifest.MANIFEST[name][0] != kind:
            self.report(ctx, node,
                        f"{name!r} used as {kind} but declared "
                        f"{self.manifest.MANIFEST[name][0]} in "
                        "obs.manifest.MANIFEST")


class SpanManifestRule(Rule):
    name = "span-manifest"
    doc = ("every obs.span()/record_span() name literal resolves "
           "against obs.manifest.SPANS (the timeline tracks / report "
           "sections join on these; a typo'd span silently vanishes "
           "from every report)")
    interests = (ast.Call,)

    _BASES = ("obs", "tracer")

    def visit(self, node: ast.Call, parents, ctx) -> None:
        if ctx.rel_path.endswith("obs/manifest.py"):
            return
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in ("span", "record_span")
                and qualname(func.value) in self._BASES):
            return
        if not node.args:
            return
        manifest = _obs_manifest()
        arg = node.args[0]
        if isinstance(arg, ast.JoinedStr):
            head = fstring_head(arg) or ""
            if not any(head.startswith(p)
                       for p in manifest.SPAN_PREFIXES):
                self.report(ctx, node,
                            f"f-string span name {head + '...'!r} has no "
                            "declared prefix in "
                            "obs.manifest.SPAN_PREFIXES")
            return
        name = str_const(arg)
        if name is None:
            return                      # step-root spans named by variable
        if not manifest.is_declared_span(name):
            self.report(ctx, node,
                        f"span {name!r} not declared in "
                        "obs.manifest.SPANS")


class FaultSiteRule(Rule):
    name = "fault-site"
    doc = ("every faults.fire(site, point, ...) literal pair resolves "
           "against faults.SITES — an undeclared site can't be armed "
           "from the documented spec grammar and would silently never "
           "fire")
    interests = (ast.Call,)

    def visit(self, node: ast.Call, parents, ctx) -> None:
        fq = call_name(node)
        if not (fq == "fire" or fq.endswith(".fire")):
            return
        if fq not in ("fire", "faults.fire") and \
                not fq.endswith("faults.fire"):
            return
        if len(node.args) < 2:
            return
        site, point = str_const(node.args[0]), str_const(node.args[1])
        if site is None or point is None:
            return
        if (site, point) not in self._sites():
            self.report(ctx, node,
                        f"fault site ({site!r}, {point!r}) not declared "
                        "in faults.SITES — declare the boundary (and "
                        "its spec-grammar line) so it can be armed")

    @staticmethod
    def _sites() -> Dict[Tuple[str, str], str]:
        return _fault_sites()


ALL_RULES = (HostSyncRule, RecompileHazardRule, KnobRegistryRule,
             AtomicWriteRule, TelemetryGuardRule, MetricManifestRule,
             SpanManifestRule, FaultSiteRule)


def make_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    """Instantiate the catalogue (or the named subset, lint-CLI
    ``--rules``)."""
    if names is None:
        return [cls() for cls in ALL_RULES]
    by_name = {cls.name: cls for cls in ALL_RULES}
    unknown = [n for n in names if n not in by_name]
    if unknown:
        known = ", ".join(sorted(by_name))
        raise ValueError(f"unknown rule(s) {unknown} — known: {known}")
    return [by_name[n]() for n in names]
