"""Lint engine: one parse, one walk, deterministic findings.

Every rule subscribes to the ``ast`` node types it cares about
(``interests``); the engine parses each file once and drives a single
depth-first walk, dispatching each node to the interested rules with the
ancestor stack (so rules can ask "am I inside a jitted function / a
loop / an ``if obs.enabled()`` guard" without walking themselves).

Findings are value objects ordered ``(path, line, col, rule, message)``
— two runs over the same tree are byte-identical, which the CI guard
test pins.  The *fingerprint* used by the baseline intentionally drops
the line number: grandfathered debt should not churn every time an
unrelated edit moves a line.

Suppressions::

    bad()          # shifu-lint: disable=rule-a,rule-b -- justification
    # shifu-lint: disable=rule-a        (comment-only line: next line)
    # shifu-lint: disable-file=rule-a   (whole file, any line)
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple, Type

__all__ = ["Finding", "FileContext", "Rule", "LintEngine",
           "iter_python_files"]

_SUPPRESS_RE = re.compile(
    r"#\s*shifu-lint:\s*disable(?P<file>-file)?\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source location."""

    path: str            # repo-relative, forward slashes
    line: int
    col: int
    rule: str
    message: str

    @property
    def fingerprint(self) -> Tuple[str, str, str]:
        """Baseline identity — line-independent so grandfathered debt
        doesn't churn when unrelated edits move lines."""
        return (self.rule, self.path, self.message)

    def sort_key(self) -> Tuple[str, int, int, str, str]:
        return (self.path, self.line, self.col, self.rule, self.message)

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"path": self.path, "line": self.line, "col": self.col,
                "rule": self.rule, "message": self.message}


class FileContext:
    """Per-file state shared by every rule during the walk."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.AST] = None
        # line -> rules disabled on that line; rules disabled file-wide
        self.line_disables: Dict[int, Set[str]] = {}
        self.file_disables: Set[str] = set()
        self._scan_suppressions()

    def _scan_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            names = {r.strip() for r in m.group("rules").split(",")}
            if m.group("file"):
                self.file_disables |= names
                continue
            self.line_disables.setdefault(i, set()).update(names)
            # a comment-only suppression covers the NEXT code line
            if line.strip().startswith("#"):
                self.line_disables.setdefault(i + 1, set()).update(names)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_disables:
            return True
        return rule in self.line_disables.get(line, set())

    def src(self, node: ast.AST) -> str:
        """Source segment of a node ('' when unavailable)."""
        try:
            return ast.get_source_segment(self.source, node) or ""
        except Exception:
            return ""


def qualname(node: ast.AST) -> str:
    """Dotted name of a Name/Attribute chain: ``obs.counter`` /
    ``np.asarray`` / ``jax.jit`` ('' for anything dynamic)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def call_name(node: ast.Call) -> str:
    return qualname(node.func)


def str_const(node: ast.AST) -> Optional[str]:
    """The value of a plain string literal node, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fstring_head(node: ast.AST) -> Optional[str]:
    """For an f-string, the constant prefix before the first ``{}``
    field (None for non-JoinedStr).  A fully-constant JoinedStr returns
    the whole string."""
    if not isinstance(node, ast.JoinedStr):
        return None
    head: List[str] = []
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            head.append(part.value)
        else:
            break
    return "".join(head)


class Rule:
    """Base class: subscribe to node types, report findings.

    ``interests`` names the ``ast`` node classes the engine should
    dispatch to :meth:`visit`; ``finish`` runs once after the walk (only
    on full-tree scans) for cross-file checks.
    """

    name: str = ""
    doc: str = ""
    interests: Tuple[Type[ast.AST], ...] = ()

    def __init__(self) -> None:
        self.findings: List[Finding] = []

    # -- hooks -----------------------------------------------------------
    def begin_file(self, ctx: FileContext) -> None:
        pass

    def visit(self, node: ast.AST, parents: Sequence[ast.AST],
              ctx: FileContext) -> None:
        pass

    def end_file(self, ctx: FileContext) -> None:
        pass

    def finish(self, engine: "LintEngine") -> None:
        pass

    # -- reporting -------------------------------------------------------
    def report(self, ctx: FileContext, node: Optional[ast.AST],
               message: str, *, line: Optional[int] = None) -> None:
        ln = line if line is not None else getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0) if node is not None else 0
        if ctx.suppressed(self.name, ln):
            return
        self.findings.append(Finding(ctx.rel_path, ln, col, self.name,
                                     message))

    def report_project(self, rel_path: str, message: str,
                       line: int = 1) -> None:
        """A finding not anchored to a walked node (cross-file checks)."""
        self.findings.append(Finding(rel_path, line, 0, self.name, message))


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/dirs into a sorted, deterministic .py file list."""
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, files in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
        elif p.endswith(".py"):
            out.append(p)
    seen: Set[str] = set()
    for p in sorted(out):
        rp = os.path.realpath(p)
        if rp not in seen:
            seen.add(rp)
            yield p


class LintEngine:
    """Parse each file once, walk once, dispatch to all rules."""

    def __init__(self, rules: Sequence[Rule], root: str,
                 full_tree: bool = False):
        self.rules = list(rules)
        self.root = os.path.abspath(root)
        self.full_tree = full_tree
        self.parse_errors: List[Finding] = []
        self.files_scanned = 0
        self._dispatch: Dict[Type[ast.AST], List[Rule]] = {}
        for r in self.rules:
            for t in r.interests:
                self._dispatch.setdefault(t, []).append(r)

    def rel(self, path: str) -> str:
        ap = os.path.abspath(path)
        rp = os.path.relpath(ap, self.root)
        if rp.startswith(".."):          # outside the root: keep absolute
            return ap.replace(os.sep, "/")
        return rp.replace(os.sep, "/")

    # -- driving ---------------------------------------------------------
    def run(self, paths: Iterable[str]) -> List[Finding]:
        for path in iter_python_files(paths):
            self._run_file(path)
        if self.full_tree:
            for r in self.rules:
                r.finish(self)
        found = list(self.parse_errors)
        for r in self.rules:
            found.extend(r.findings)
        return sorted(found, key=Finding.sort_key)

    def _run_file(self, path: str) -> None:
        rel = self.rel(path)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            self.parse_errors.append(
                Finding(rel, 1, 0, "parse-error", f"unreadable: {e}"))
            return
        ctx = FileContext(path, rel, source)
        try:
            ctx.tree = ast.parse(source, filename=path)
        except SyntaxError as e:
            self.parse_errors.append(
                Finding(rel, e.lineno or 1, e.offset or 0, "parse-error",
                        f"syntax error: {e.msg}"))
            return
        self.files_scanned += 1
        for r in self.rules:
            r.begin_file(ctx)
        stack: List[ast.AST] = [ctx.tree]
        self._walk(ctx.tree, stack, ctx)
        for r in self.rules:
            r.end_file(ctx)

    def _walk(self, node: ast.AST, stack: List[ast.AST],
              ctx: FileContext) -> None:
        for child in ast.iter_child_nodes(node):
            rules = self._dispatch.get(type(child))
            if rules:
                for r in rules:
                    r.visit(child, stack, ctx)
            stack.append(child)
            self._walk(child, stack, ctx)
            stack.pop()
