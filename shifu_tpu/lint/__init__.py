"""``shifu-tpu lint`` — AST-based convention checker for this codebase.

Eleven PRs in, correctness rests on conventions no compiler enforces:
named hot executables route through ``obs.costed_jit`` so the recompile
sentinel sees them, artifact writes are atomic via ``ioutil``, telemetry
is zero-cost when disabled, metric/span/fault-site names resolve against
their manifests, and every ``-Dshifu.*`` / ``SHIFU_*`` knob is declared
in ``config/knobs.py``.  This package turns those implicit contracts
into machine-checked rules:

- :mod:`engine`   — per-file ``ast`` parse, ONE tree walk shared by all
  rules (rules subscribe to node types), deterministic finding order,
  ``# shifu-lint: disable=RULE`` inline suppressions;
- :mod:`rules`    — the rule catalogue (see ``ALL_RULES``);
- :mod:`baseline` — checked-in grandfather file: new debt fails CI while
  old debt stays tracked (``lint-baseline.json`` at the repo root);
- :mod:`cli`      — ``shifu-tpu lint`` (text + ``--json``; exit 0 clean,
  2 findings, 1 usage/parse trouble).

Suppressing a finding::

    x = forced.item()   # shifu-lint: disable=host-sync-hot-path -- why

A comment line immediately above the flagged line works too.  Whole-file
opt-outs use ``# shifu-lint: disable-file=RULE`` anywhere in the file.
"""

from __future__ import annotations

from .baseline import apply_baseline, load_baseline, write_baseline
from .engine import Finding, LintEngine, Rule, iter_python_files
from .rules import ALL_RULES, make_rules
from .cli import main, run_lint

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintEngine",
    "Rule",
    "apply_baseline",
    "iter_python_files",
    "load_baseline",
    "main",
    "make_rules",
    "run_lint",
    "write_baseline",
]
