"""Baseline file: grandfathered findings tracked, new debt fails CI.

The checked-in ``lint-baseline.json`` (repo root) records the findings
that existed when a rule landed, keyed by the line-independent
fingerprint ``(rule, path, message)`` with a count (the same message can
legitimately occur N times in one file).  ``shifu-tpu lint`` subtracts
the baseline from the current run: up to ``count`` matching findings
are absorbed per fingerprint, everything else is NEW and exits 2.

The workflow mirrors every grandfathering linter: ``--update-baseline``
rewrites the file from the current findings (review the diff — a
GROWING baseline is the smell the rule exists to catch), and fixing old
debt shrinks it; a stale entry whose finding no longer exists is
reported so the file can't rot."""

from __future__ import annotations

import json
from typing import Dict, List, Tuple

from .engine import Finding
from .. import ioutil

__all__ = ["load_baseline", "write_baseline", "apply_baseline",
           "BASELINE_VERSION"]

BASELINE_VERSION = 1

_Key = Tuple[str, str, str]


def load_baseline(path: str) -> Dict[_Key, int]:
    """fingerprint -> grandfathered count.  Missing file = empty."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        return {}
    if doc.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {doc.get('version')!r} "
            f"(this build reads {BASELINE_VERSION})")
    out: Dict[_Key, int] = {}
    for rec in doc.get("findings", []):
        key = (rec["rule"], rec["path"], rec["message"])
        out[key] = out.get(key, 0) + int(rec.get("count", 1))
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    counts: Dict[_Key, int] = {}
    for f in findings:
        counts[f.fingerprint] = counts.get(f.fingerprint, 0) + 1
    recs = [{"rule": k[0], "path": k[1], "message": k[2], "count": n}
            for k, n in sorted(counts.items())]
    ioutil.atomic_write_json(path, {"version": BASELINE_VERSION,
                                    "findings": recs})


def apply_baseline(findings: List[Finding],
                   baseline: Dict[_Key, int]
                   ) -> Tuple[List[Finding], List[Finding], List[_Key]]:
    """Split into (new, grandfathered) and name stale baseline entries.

    Deterministic: findings arrive sorted; the FIRST ``count`` matches
    of each fingerprint are absorbed."""
    budget = dict(baseline)
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        k = f.fingerprint
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            old.append(f)
        else:
            new.append(f)
    stale = sorted(k for k, n in budget.items() if n > 0)
    return new, old, stale
