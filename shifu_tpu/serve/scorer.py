"""AOT device-resident ensemble scorer — one executable per batch bucket.

The eval plane's :class:`~shifu_tpu.eval.scorer.Scorer` dispatches
per-model on every call (stacked NN groups on device, tree/WDL/SVM
columns through host ``np.asarray`` round trips).  For serving that
dispatch is pure per-request overhead, so :class:`AOTScorer` builds ONE
fused traceable function over the whole ensemble — every model's scores
as device sub-expressions of a single graph, no host hop between the
models of a bag — and ``lower()→compile()``s it ONCE per batch bucket at
startup, with donated input buffers.  A request batch then costs: pad to
the smallest covering bucket, one compiled launch, trim.

Every bucket executable registers with the cost-attribution plane
(:func:`shifu_tpu.obs.costs.record_executable`) under its own name
(``serve.score.<tag>.b<bucket>``), so the shape-churn sentinel
(``xla.recompiles``) police the central hazard of this design: a warmed
server must NEVER compile again, whatever request sizes arrive.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..eval.scorer import SCORE_SCALE, Scorer
from ..obs import costs

log = logging.getLogger(__name__)

# geometric bucket ladder default: one executable per rung; request
# batches pad to the smallest covering rung (``-Dshifu.serve.buckets``)
DEFAULT_BUCKETS = (1, 8, 64, 512)


def bucket_ladder() -> Tuple[int, ...]:
    """The configured bucket ladder, ascending and deduplicated
    (property ``shifu.serve.buckets`` = comma-separated sizes)."""
    from ..config import environment
    spec = environment.get_property("shifu.serve.buckets")
    if not spec:
        return DEFAULT_BUCKETS
    try:
        sizes = sorted({int(s) for s in spec.split(",") if s.strip()})
        if not sizes or any(s <= 0 for s in sizes):
            raise ValueError(spec)
        return tuple(sizes)
    except ValueError:
        log.warning("ignoring unparseable shifu.serve.buckets=%r", spec)
        return DEFAULT_BUCKETS


def covering_bucket(buckets: Sequence[int], n: int) -> int:
    """Smallest rung >= n (the largest rung when n exceeds the ladder —
    the caller chunks oversize batches)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


def refine_ladder(buckets: Sequence[int], size_counts: dict,
                  max_extra: int = 2, min_share: float = 0.2,
                  occupancy_target: float = 0.8,
                  multiple: int = 8) -> Tuple[int, ...]:
    """Occupancy-driven rung refinement: given the observed distribution
    of real batch row-counts (``size_counts``: rows -> batches), propose
    intermediate rungs under rungs that systematically pad.

    A rung qualifies when it carries at least ``min_share`` of observed
    batches AND the p95 of its real batch sizes — rounded up to
    ``multiple`` — lands below ``occupancy_target`` of the rung: most of
    its traffic then pads to the tighter rung instead.  At most
    ``max_extra`` rungs are added per refinement (bounded compile
    budget) and existing rungs are NEVER removed, so every in-flight
    ``covering_bucket`` decision stays valid and already-compiled
    executables keep serving — the zero-recompile contract is untouched
    because a new rung compiles (a NEW executable name, first
    signature) before any batch pads to it."""
    buckets = tuple(sorted(set(int(b) for b in buckets)))
    total = sum(size_counts.values())
    if not total:
        return buckets
    per_rung: dict = {b: [] for b in buckets}
    for n, cnt in size_counts.items():
        per_rung[covering_bucket(buckets, int(n))].append((int(n), cnt))
    proposals = []
    for b, sizes in per_rung.items():
        if b == buckets[0]:
            continue                    # nothing tighter to offer
        carried = sum(c for _, c in sizes)
        if carried / total < min_share:
            continue
        cum, p95 = 0, b
        for n, c in sorted(sizes):
            cum += c
            if cum >= 0.95 * carried:
                p95 = n
                break
        rung = min(b, ((p95 + multiple - 1) // multiple) * multiple)
        if 0 < rung < occupancy_target * b and rung not in buckets:
            proposals.append((carried, rung))
    extra = sorted(r for _, r in
                   sorted(proposals, reverse=True)[:max_extra])
    return tuple(sorted(set(buckets) | set(extra)))


def infer_dims(models: Sequence) -> Tuple[int, int]:
    """(n_features, n_bin_cols) the ensemble's inputs must provide,
    derived from the saved specs — what startup warming compiles
    against.  ``n_bin_cols`` is 0 when no model consumes bins."""
    n_feat = 0
    n_bins_cols = 0
    for m in models:
        kind = getattr(m, "input_kind", "norm")
        name = type(m).__name__
        if name == "IndependentNNModel":
            n_feat = max(n_feat, int(m.spec.input_dim))
        elif name == "IndependentSVMModel":
            n_feat = max(n_feat, int(m.sv_x.shape[1]))
        elif name == "IndependentTreeModel":
            feats = max((int(np.max(t.split_feat)) for t in m.trees),
                        default=-1)
            n_bins_cols = max(n_bins_cols, feats + 1)
        elif kind == "both":                       # WDL: index lists
            nf = (getattr(m.spec, "extra", None) or {}).get(
                "num_feat_idx") or []
            cf = (getattr(m.spec, "extra", None) or {}).get(
                "cat_col_idx") or []
            if nf:
                n_feat = max(n_feat, max(nf) + 1)
            if cf:
                n_bins_cols = max(n_bins_cols, max(cf) + 1)
    return n_feat, n_bins_cols


def _tree_column(m) -> Callable:
    """Device-traceable score column for a saved forest — the jnp twin of
    ``IndependentTreeModel.compute`` (same f32 link math, no host hop).

    The traversal is the QUANTIZED one by default (``ops.tree_quant``):
    bins walk in their uint8 wire dtype with f32 only at the leaf
    accumulate — bit-identical scores, 1/4 the bytes on serving's
    dominant operand, and the Pallas kernel on TPU loads each row block
    once for the whole forest instead of once per (tree, level)."""
    import jax
    import jax.numpy as jnp

    from ..ops import tree_quant as tq
    from ..ops.tree import predict_forest_stacked, stack_forest

    depth = m.trees[0].depth
    spec = m.spec
    quant = tq.quant_scoring() and tq.bins_fit_uint8(spec.n_bins)
    if quant:
        qarrays = tq.stack_forest_quant(m.trees)
    else:
        stacked = stack_forest(m.trees)

    def col(x, bins):
        if quant:
            b = bins if bins.dtype == jnp.uint8 else bins.astype(jnp.uint8)
            preds = tq.predict_forest_quant(*qarrays, b, depth)
        else:
            preds = predict_forest_stacked(*stacked, bins, depth)
        if spec.algorithm == "GBT":
            f = spec.init_score + spec.learning_rate * preds.sum(axis=0)
            if spec.loss == "log":
                return 1.0 / (1.0 + jnp.exp(-f))
            return jnp.clip(f, 0.0, 1.0)
        out = preds.mean(axis=0)        # RF mean vote
        return out[:, 0] if out.ndim > 1 else out
    return col


def _wdl_column(m) -> Callable:
    """Device-traceable WDL column: the index slicing of
    ``compute_full`` moved inside the trace.

    The serve copy of the categorical plane is picked ONCE at build time
    (``shifu.wdl.serveCopy`` — see :func:`train.wdl_shard.
    build_serve_forward`): tables too big for one device score through a
    row-sharded gather inside this same traced graph (replicated
    activations, one psum per lookup plane — never an all-gather of a
    table), a hot-rows copy squashes the cold tail, and small tables keep
    the classic replicated forward.  All modes trace to fixed shapes, so
    the per-bucket AOT contract (zero recompiles) is untouched.  Hashed-ID
    columns fold in-graph (``apply_hash_device``) — bit-identical to the
    trainer's host hashing."""
    import jax.numpy as jnp

    from ..models.wdl import apply_hash_device, forward
    from ..train.wdl_shard import build_serve_forward

    nf = tuple((m.spec.extra or {}).get("num_feat_idx") or ())
    cf = tuple((m.spec.extra or {}).get("cat_col_idx") or ())
    spec, params = m.spec, m.params
    mode, sharded_fwd = build_serve_forward(spec, params)
    if mode != "full":
        log.info("WDL serve column: %s table copy", mode)

    def col(x, bins):
        x_num = x[:, np.asarray(nf, np.int32)] if nf \
            else jnp.zeros((x.shape[0], 0), jnp.float32)
        x_cat = bins[:, np.asarray(cf, np.int32)].astype(jnp.int32) if cf \
            else jnp.zeros((x.shape[0], 0), jnp.int32)
        x_cat = apply_hash_device(spec, x_cat)
        if sharded_fwd is not None:
            return sharded_fwd(x_num, x_cat)[:, 0]
        return forward(params, spec, x_num, x_cat)[:, 0]
    return col


def build_ensemble_fn(scorer: Scorer) -> Tuple[Callable, bool]:
    """One pure traceable ``fn(x[, bins]) -> raw [n, M]`` over the whole
    ensemble (scores already scaled), plus whether it consumes bins.

    Same dispatch rules as :meth:`Scorer.score_device` — same-shape NN
    models ride the stacked-group vmap, everything else contributes its
    own device sub-expression — but as ONE graph XLA fuses end to end.
    """
    from ..models.nn import forward as nn_forward

    models = scorer.models
    groups = scorer._stacked_nn_groups()
    grouped = {i for idxs, _, _ in groups for i in idxs}
    needs_bins = any(getattr(m, "input_kind", "norm") in ("bins", "both")
                     for m in models)

    cols: List[Optional[Callable]] = [None] * len(models)
    for i, m in enumerate(models):
        if i in grouped:
            continue
        kind = getattr(m, "input_kind", "norm")
        if kind == "bins":
            cols[i] = _tree_column(m)
        elif kind == "both":
            cols[i] = _wdl_column(m)
        elif type(m).__name__ == "IndependentNNModel":
            cols[i] = (lambda sp, ps: lambda x, bins:
                       nn_forward(ps, sp, x)[:, 0])(m.spec, m.params)
        elif type(m).__name__ == "IndependentSVMModel":
            cols[i] = (lambda mm: lambda x, bins:
                       mm._decision(x)[:, 0])(m)
        else:
            raise TypeError(f"cannot build a device column for "
                            f"{type(m).__name__}")

    scale = scorer.scale

    def fn(x, bins=None):
        import jax.numpy as jnp
        out = [None] * len(models)
        for idxs, stacked, fwd in groups:
            g = fwd(stacked, x)                      # [M, n, out]
            for pos, i in enumerate(idxs):
                out[i] = g[pos][:, 0]
        for i, col in enumerate(cols):
            if col is not None:
                out[i] = col(x, bins)
        return jnp.stack(out, axis=1) * scale
    return fn, needs_bins


def serve_recompile_count(prefix: str = "serve.score") -> int:
    """Distinct-signature recompiles observed across all serve
    executables — the telemetry-independent read of the shape-churn
    sentinel (``record_executable`` feeds the cost registry whether or
    not telemetry is on).  A warmed server must report 0."""
    by_name: dict = {}
    for e in costs.get_cost_registry().entries():
        if e.name.startswith(prefix):
            by_name.setdefault(e.name, set()).add(e.signature)
    return sum(len(sigs) - 1 for sigs in by_name.values())


class AOTScorer:
    """The modelset's ensemble, pinned in HBM, behind per-bucket AOT
    executables (see module docs).

    ``warm()`` compiles every rung of the ladder up front;
    :meth:`score_batch` then pads to the covering rung, launches the
    compiled executable (donated input buffers — the pad copy is the
    only host-side byte movement), and trims.  Thread-safe: the batcher
    worker launches while a hot-swap builds the NEXT scorer instance
    elsewhere; one instance's executables are immutable after warm.
    """

    def __init__(self, models: Sequence, scale: float = SCORE_SCALE,
                 buckets: Optional[Sequence[int]] = None,
                 name: str = "serve.score", transform=None):
        import jax

        from ..ops import tree_quant as tq
        self.scorer = Scorer(models, scale)
        self.buckets = tuple(sorted(set(buckets or bucket_ladder())))
        self.name = name
        self.n_features, self.n_bins_cols = infer_dims(models)
        # requests carry bins in the narrowest dtype the ensemble admits
        # (uint8 wire contract) — quant off pins the old int32 signature
        self.bins_dtype = tq.ensemble_bins_dtype(models) \
            if tq.quant_scoring() else np.dtype(np.int32)
        # analytic kernel launches for the cost plane: the Pallas
        # traversal is opaque to XLA's cost analysis, so each scored
        # bucket records one model launch per quant-kernel forest
        # (serving MFU rows stay honest — the hist_kernel_cost pattern)
        self._quant_kernel_shapes = []
        if tq.quant_scoring() and tq.quant_kernel():
            for m in models:
                if type(m).__name__ == "IndependentTreeModel" \
                        and tq.bins_fit_uint8(m.spec.n_bins):
                    from ..ops.tree import n_tree_nodes
                    self._quant_kernel_shapes.append(dict(
                        n_feat=self.n_bins_cols,
                        n_bins=m.spec.n_bins,
                        n_nodes=n_tree_nodes(m.trees[0].depth),
                        depth=m.trees[0].depth,
                        n_trees=len(m.trees)))
        fn, self.needs_bins = build_ensemble_fn(self.scorer)
        # donated input buffers: the padded batch is dead the moment the
        # launch reads it, so XLA may overwrite it in place (CPU's PJRT
        # cannot donate — gating avoids a warning per compile there)
        donate = () if jax.default_backend() == "cpu" \
            else ((0, 1) if self.needs_bins else (0,))
        # AOT template only — never launched directly; every bucket's
        # executable registers with record_executable in _ensure_compiled
        self._jitted = jax.jit(fn, donate_argnums=donate)  # shifu-lint: disable=recompile-hazard
        self._compiled: dict = {}
        self._compiled_raw: dict = {}
        # raw-record family: the norm transform fused as a jnp prelude of
        # the SAME ensemble graph — one executable per rung, wire format
        # [n, 3C] (serve/transform.py), bins minted in-graph in the
        # narrow wire dtype so tree_quant stays uint8
        self.transform = transform
        self.accepts_raw = transform is not None
        self._jitted_raw = None
        if transform is not None:
            if transform.width < self.n_features:
                raise ValueError(
                    f"transform emits {transform.width} features but the "
                    f"ensemble consumes {self.n_features} — the ColumnConfig "
                    "snapshot does not match the models")
            if transform.n_columns < self.n_bins_cols:
                raise ValueError(
                    f"transform emits {transform.n_columns} bin columns but "
                    f"the ensemble consumes {self.n_bins_cols}")
            nfeat, nbc = self.n_features, self.n_bins_cols
            bdt, needs_bins = self.bins_dtype, self.needs_bins

            def raw_fn(packed):
                xx, bb = transform.apply_device(packed)
                xx = xx[:, :nfeat]
                if not needs_bins:
                    return fn(xx)
                return fn(xx, bb[:, :nbc].astype(bdt))
            donate_raw = () if jax.default_backend() == "cpu" else (0,)
            # AOT template only — per-bucket executables register below
            self._jitted_raw = jax.jit(  # shifu-lint: disable=recompile-hazard
                raw_fn, donate_argnums=donate_raw)
        self._lock = threading.Lock()
        self._pin_params()

    @property
    def models(self) -> List:
        return self.scorer.models

    def _pin_params(self) -> None:
        """Force every param/forest leaf onto the device ONCE — scoring
        must never pay a lazy host->HBM transfer mid-request."""
        import jax
        for idxs, stacked, _ in self.scorer._stacked_nn_groups():
            jax.block_until_ready(stacked)
        for m in self.models:
            for leaf in jax.tree_util.tree_leaves(
                    getattr(m, "params", None)):
                jax.block_until_ready(jax.device_put(leaf))

    # ------------------------------------------------------------ compile
    def _avals(self, bucket: int):
        import jax
        x = jax.ShapeDtypeStruct((bucket, self.n_features), np.float32)
        if not self.needs_bins:
            return (x,)
        return (x, jax.ShapeDtypeStruct((bucket, self.n_bins_cols),
                                        self.bins_dtype))

    def _avals_raw(self, bucket: int):
        import jax
        return (jax.ShapeDtypeStruct((bucket, self.transform.wire_width),
                                     self.transform.wire_dtype),)

    def _ensure_compiled(self, bucket: int, raw: bool = False):
        cache = self._compiled_raw if raw else self._compiled
        ent = cache.get(bucket)
        if ent is not None:
            return ent
        with self._lock:
            ent = cache.get(bucket)
            if ent is not None:
                return ent
            import jax
            jitted = self._jitted_raw if raw else self._jitted
            avals = self._avals_raw(bucket) if raw else self._avals(bucket)
            lowered = jitted.lower(*avals)
            exe = lowered.compile()
            try:
                sig = ",".join(a.str_short() for a in
                               jax.tree_util.tree_leaves(lowered.in_avals))
            except Exception:
                sig = f"b{bucket}"
            # per-bucket name: each rung has exactly ONE legal signature,
            # so ANY second signature under it is real shape churn and
            # trips the xla.recompiles sentinel
            # bounded shape-keyed family: ONE name per ladder rung by
            # design, so the per-name dedup stays meaningful
            suffix = ".raw" if raw else ""
            costs.record_executable(f"{self.name}{suffix}.b{bucket}",  # shifu-lint: disable=recompile-hazard
                                    lowered, exe, signature=sig)
            ent = cache[bucket] = (exe, sig)
        return ent

    def warm(self, launch: bool = True) -> None:
        """Compile every rung; ``launch=True`` additionally runs each
        executable once so first-request latency pays no dispatch-path
        lazy init either."""
        for b in self.buckets:
            self._warm_one(b, launch)

    def _warm_one(self, bucket: int, launch: bool = True) -> None:
        exe, _ = self._ensure_compiled(bucket)
        if launch:
            args = [np.zeros((bucket, self.n_features), np.float32)]
            if self.needs_bins:
                args.append(np.zeros((bucket, self.n_bins_cols),
                                     self.bins_dtype))
            import jax
            jax.block_until_ready(exe(*args))
        if not self.accepts_raw:
            return
        rexe, _ = self._ensure_compiled(bucket, raw=True)
        if launch:
            import jax
            # a zero wire row decodes as all-missing — a legal record
            jax.block_until_ready(rexe(np.zeros(
                (bucket, self.transform.wire_width),
                self.transform.wire_dtype)))

    def extend_buckets(self, new_buckets: Sequence[int]) -> int:
        """Grow the ladder with occupancy-refined rungs (see
        :func:`refine_ladder`).  Every new rung compiles AND launches
        once BEFORE it is published, so the first real batch that pads
        to it pays a warm dispatch — compiling ahead of use is what
        keeps the zero-recompile contract intact.  Existing rungs are
        never removed.  Returns the number of rungs added."""
        add = [int(b) for b in sorted(set(new_buckets))
               if int(b) > 0 and int(b) not in self.buckets]
        for b in add:
            self._warm_one(b)
        if add:
            with self._lock:
                self.buckets = tuple(sorted(set(self.buckets) | set(add)))
            from .. import obs
            obs.counter("serve.bucket_rungs_added").inc(len(add))
            log.info("%s: ladder refined to %s", self.name, self.buckets)
        return len(add)

    # the batcher's request tracer may pass ``timings=`` (duck-checked —
    # test doubles wrapping this class need not support it)
    supports_timings = True

    # ------------------------------------------------------------- score
    def score_batch(self, x: np.ndarray,
                    bins: Optional[np.ndarray] = None,
                    timings: Optional[dict] = None) -> np.ndarray:
        """raw scaled scores [n, M] for a request batch; pads to the
        covering bucket, chunks batches beyond the top rung.  Returns a
        host array (the serving response crosses the link by
        definition — ONE fetch per launch).

        ``timings`` (sampled request tracing only) accumulates the
        launch decomposition in place: ``pad_s`` the host pad copy,
        ``device_s`` the executable call (device compute on the
        synchronous CPU/TPU-AOT dispatch path), ``launch_s`` argument
        prep + the host fetch around it."""
        import time as _time
        n = len(x)
        top = self.buckets[-1]
        if n > top:
            return np.concatenate(
                [self.score_batch(x[s:s + top],
                                  None if bins is None else bins[s:s + top],
                                  timings=timings)
                 for s in range(0, n, top)], axis=0)
        t0 = _time.perf_counter() if timings is not None else 0.0
        bucket = covering_bucket(self.buckets, n)
        pad = bucket - n
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad, x.shape[1]), x.dtype)], axis=0)
            if bins is not None:
                bins = np.concatenate(
                    [bins, np.zeros((pad, bins.shape[1]), bins.dtype)],
                    axis=0)
        if timings is not None:
            t1 = _time.perf_counter()
            timings["pad_s"] = timings.get("pad_s", 0.0) + (t1 - t0)
        exe, sig = self._ensure_compiled(bucket)
        args = [np.ascontiguousarray(x, np.float32)]
        if self.needs_bins:
            if bins is None:
                raise ValueError("ensemble contains bin-consuming models "
                                 "— requests must carry bins")
            args.append(np.ascontiguousarray(bins, self.bins_dtype))
        costs.get_cost_registry().launch(f"{self.name}.b{bucket}", sig)
        for kw in self._quant_kernel_shapes:
            costs.record_model_launch("pallas.tree_traverse",
                                      rows=bucket, **kw)
        if timings is None:
            return np.asarray(exe(*args))[:n]
        t2 = _time.perf_counter()
        out = exe(*args)
        t3 = _time.perf_counter()
        raw = np.asarray(out)
        t4 = _time.perf_counter()
        timings["device_s"] = timings.get("device_s", 0.0) + (t3 - t2)
        timings["launch_s"] = timings.get("launch_s", 0.0) \
            + (t2 - t1) + (t4 - t3)
        return raw[:n]

    def score_batch_raw(self, packed: np.ndarray,
                        timings: Optional[dict] = None) -> np.ndarray:
        """raw scaled scores [n, M] for PACKED raw-record rows (the
        ``serve/transform.py`` wire format): the fused executable norms
        in-graph and scores in one launch.  Same pad/chunk/trim contract
        as :meth:`score_batch`; pad rows are all-missing and cost
        nothing beyond the rung."""
        import time as _time
        if not self.accepts_raw:
            raise ValueError("this scorer was built without a norm "
                             "transform — raw records need the "
                             "ColumnConfig snapshot")
        n = len(packed)
        top = self.buckets[-1]
        if n > top:
            return np.concatenate(
                [self.score_batch_raw(packed[s:s + top], timings=timings)
                 for s in range(0, n, top)], axis=0)
        t0 = _time.perf_counter() if timings is not None else 0.0
        bucket = covering_bucket(self.buckets, n)
        pad = bucket - n
        if pad:
            packed = np.concatenate(
                [packed, np.zeros((pad, packed.shape[1]), packed.dtype)],
                axis=0)
        if timings is not None:
            t1 = _time.perf_counter()
            timings["pad_s"] = timings.get("pad_s", 0.0) + (t1 - t0)
        exe, sig = self._ensure_compiled(bucket, raw=True)
        arg = np.ascontiguousarray(packed, self.transform.wire_dtype)
        costs.get_cost_registry().launch(f"{self.name}.raw.b{bucket}", sig)
        for kw in self._quant_kernel_shapes:
            costs.record_model_launch("pallas.tree_traverse",
                                      rows=bucket, **kw)
        if timings is None:
            return np.asarray(exe(arg))[:n]
        t2 = _time.perf_counter()
        out = exe(arg)
        t3 = _time.perf_counter()
        raw = np.asarray(out)
        t4 = _time.perf_counter()
        timings["device_s"] = timings.get("device_s", 0.0) + (t3 - t2)
        timings["launch_s"] = timings.get("launch_s", 0.0) \
            + (t2 - t1) + (t4 - t3)
        return raw[:n]

    def score_mean(self, x: np.ndarray,
                   bins: Optional[np.ndarray] = None) -> np.ndarray:
        """Per-row ensemble mean — the serving response column."""
        return self.score_batch(x, bins).mean(axis=1)
