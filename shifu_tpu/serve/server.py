"""ServeServer — registry + batcher + heartbeats behind one object.

``shifu-tpu serve`` loads the modelset's trained ensemble
(``<dir>/models``), warms every bucket executable, starts the
micro-batcher worker and the per-process heartbeat
(:mod:`shifu_tpu.obs.health`, step ``SERVE`` — the same
``shifu-tpu monitor`` surface every pipeline step reports to), then
serves scoring requests:

- in-process: :meth:`ServeServer.score` (closed-loop) /
  :meth:`ServeServer.submit` (async ticket) — what the bench drives;
- over HTTP (stdlib, zero new deps): ``POST /score`` with
  ``{"rows": [[...]], "bins": [[...]]}`` -> ``{"scores": [...]}``, or
  RAW records ``{"records": [{field: value, ...}]}`` when the modelset
  dir carries its ColumnConfig snapshot (the norm transform runs fused
  inside the scorer executable — :mod:`shifu_tpu.serve.transform`; a
  malformed record fails alone with a coded error, its ``scores`` slot
  null), ``GET /healthz`` -> live state (``accepts_raw`` next to
  ``needs_bins``) + bucket/batch/queue accounting + the compact SLO
  summary, ``GET /slo`` -> the full SLO/burn-rate payload,
  ``GET /quality`` -> the live model-quality table, ``POST /outcome``
  -> delayed-label records joined onto logged predictions,
  ``POST /swap`` -> promotion phases (``prepare``/``commit``/``abort``
  or a one-shot full swap) the fleet router drives for a coordinated,
  no-mixed-window hot-swap;
- request tracing: an ``X-Shifu-Trace`` request header propagates the
  caller's trace id onto the batch pipeline (forcing sampling for that
  request); otherwise requests are head-sampled at
  ``-Dshifu.serve.traceSampleRate`` and ids are minted here;
- hot-swap: :meth:`ServeServer.swap` re-points the live model between
  batches without dropping queued requests (``serve:swap`` fault site).

The server owns an :class:`shifu_tpu.obs.SLOTracker` (fed per-row
latencies by the batcher) and, when a model-set dir is given, its SERVE
heartbeats carry ``queue_depth`` / ``queue_buildup`` / the compact SLO
summary each beat (``shifu-tpu monitor`` renders and flags them); the
metrics exporter mirrors the same numbers into ``metrics.prom``, and a
``stop()`` flushes any sampled request spans to the telemetry trace.

Model-quality plane (``-Dshifu.scorelog.sampleRate`` > 0, default 0 =
off): the server wires a sampled :class:`shifu_tpu.obs.ScoreLog` onto
the batcher (crash-safe segments under ``telemetry/scorelog/``), an
:class:`shifu_tpu.obs.OutcomeJoiner` (``POST /outcome`` +
``telemetry/outcomes/`` drop directory, swept each heartbeat), and a
:class:`shifu_tpu.obs.QualityMonitor` seeded from eval's
``telemetry/posttrain.json`` snapshot — per-generation live AUC /
calibration / score-PSI, surfaced via ``GET /quality``, a ``quality``
heartbeat extra, and the atomic ``telemetry/quality.json`` artifact the
refresh controller and ``analysis --telemetry`` read.

Knobs: ``-Dshifu.serve.buckets`` (bucket ladder),
``-Dshifu.serve.bucketRefineEvery`` (batches between occupancy-driven
ladder refinements, 0 = off),
``-Dshifu.serve.maxDelayMs`` (deadline flush, default 2 ms),
``-Dshifu.serve.traceSampleRate`` (head sampling, default 0),
``-Dshifu.serve.sloP99Ms`` / ``-Dshifu.serve.sloAvailability``
(objectives; default 2x the deadline and 0.999),
``-Dshifu.scorelog.sampleRate`` / ``segmentBytes`` / ``budgetBytes``
and ``-Dshifu.quality.*`` (the quality plane).
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional, Sequence

import numpy as np

from .. import faults, obs
from .batcher import MicroBatcher, Ticket
from .overload import (DeadlineExceededError, OverloadedError,
                       configured_brownout_enabled)
from .registry import ModelRegistry
from .scorer import bucket_ladder

log = logging.getLogger(__name__)

DEFAULT_MAX_DELAY_MS = 2.0

# queue depth at/over this many top buckets flags "buildup" in
# heartbeats — work queued beyond what the next few flushes can absorb
QUEUE_BUILDUP_BUCKETS = 4

# brownout policy: the flush deadline shrinks to this fraction of its
# configured value while degraded (smaller batches, lower queue wait —
# throughput for latency, the right trade under overload)
BROWNOUT_DELAY_FACTOR = 0.25


def max_delay_s(override_ms: Optional[float] = None) -> float:
    """Deadline-flush bound: explicit override > property
    ``shifu.serve.maxDelayMs`` > 2 ms."""
    if override_ms is not None:
        return max(0.0, float(override_ms)) / 1000.0
    from ..config import environment
    return max(0.0, environment.get_float("shifu.serve.maxDelayMs",
                                          DEFAULT_MAX_DELAY_MS)) / 1000.0


def _load_transform(model_set_dir: str):
    """The modelset's :class:`FusedTransform` when its config snapshot
    (ModelConfig.json + ColumnConfig.json) is on disk — pre-binned-only
    sets serve fine without one, they just refuse raw records."""
    if not all(os.path.isfile(os.path.join(model_set_dir, f))
               for f in ("ModelConfig.json", "ColumnConfig.json")):
        return None
    from .transform import FusedTransform
    try:
        return FusedTransform.from_dir(model_set_dir)
    except (OSError, ValueError, KeyError) as e:
        log.warning("raw-record path disabled (%s)", e)
        return None


class ServeServer:
    """One serving process for one (or more) modelsets."""

    def __init__(self, model_set_dir: Optional[str] = None,
                 models: Optional[Sequence] = None,
                 key: Optional[str] = None,
                 buckets: Optional[Sequence[int]] = None,
                 max_delay_ms: Optional[float] = None,
                 trace_sample_rate: Optional[float] = None,
                 slo_p99_ms: Optional[float] = None,
                 slo_availability: Optional[float] = None,
                 scorelog_sample_rate: Optional[float] = None,
                 transform=None, replica: Optional[str] = None):
        self.model_set_dir = model_set_dir
        self.key = key or (os.path.basename(os.path.abspath(model_set_dir))
                           if model_set_dir else "default")
        self.replica = replica
        state_dir = (os.path.join(model_set_dir, "serving")
                     if model_set_dir else None)
        self.registry = ModelRegistry(state_dir=state_dir)
        src = models if models is not None \
            else os.path.join(model_set_dir, "models")
        if transform is None and model_set_dir:
            transform = _load_transform(model_set_dir)
        self.transform = transform
        self.registry.load(self.key, src,
                           buckets=tuple(buckets or bucket_ladder()),
                           transform=transform)
        delay_s = max_delay_s(max_delay_ms)
        p99_obj, avail_obj = obs.slo_objectives(delay_s * 1000.0)
        self.slo = obs.SLOTracker(
            p99_ms=slo_p99_ms if slo_p99_ms is not None else p99_obj,
            availability=slo_availability
            if slo_availability is not None else avail_obj)
        self.batcher = MicroBatcher(self.registry.provider(self.key),
                                    max_delay_s=delay_s,
                                    trace_sample_rate=trace_sample_rate,
                                    slo=self.slo)
        # brownout governor (overload tentpole): evaluated each beat —
        # or directly via check_brownout() — against burn-rate alerts
        # and queue buildup; None when -Dshifu.serve.brownout=false
        self.brownout = obs.BrownoutGovernor() \
            if configured_brownout_enabled() else None
        self._normal_settings: Optional[dict] = None
        self._heartbeat = None
        self._exporter = None
        self._started = False
        # model-quality plane: only exists at sampleRate > 0 (zero-cost
        # contract — the batcher tap stays one is-not-None check)
        self.scorelog = None
        self.outcomes = None
        self.quality = None
        self._join_count = 0
        from ..obs.scorelog import scorelog_sample_rate as _rate_knob
        rate = _rate_knob(scorelog_sample_rate)
        if model_set_dir and rate > 0.0:
            from ..obs.outcomes import OutcomeJoiner, outcomes_drop_dir
            from ..obs.quality import (quality_artifact_path,
                                       start_quality_monitor)
            from ..obs.scorelog import ScoreLog, scorelog_dir
            self.quality = start_quality_monitor(model_set_dir,
                                                 sample_rate=rate)
            self.outcomes = OutcomeJoiner(on_join=self._on_join)
            self.scorelog = ScoreLog(
                scorelog_dir(model_set_dir), sample_rate=rate,
                gen_fn=lambda: self.registry.generation(self.key),
                on_log=self._on_scored)
            self.batcher.scorelog = self.scorelog
            self._quality_path = quality_artifact_path(model_set_dir)
            self._drop_dir = outcomes_drop_dir(model_set_dir)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "ServeServer":
        if self._started:
            return self
        self.batcher.start()
        if self.model_set_dir:
            proc = f"serve-{self.key}" + \
                (f"-{self.replica}" if self.replica else "")
            self._heartbeat = obs.start_heartbeat(
                obs.health_dir_for(self.model_set_dir), step="SERVE",
                proc=proc, extras_fn=self._beat_extras)
            self._exporter = obs.start_exporter(
                os.path.join(self.model_set_dir, "telemetry"),
                step="SERVE")
        self._started = True
        return self

    def stop(self, exit_code: Optional[int] = 0) -> None:
        if not self._started:
            return
        self.batcher.stop()
        if self.scorelog is not None:
            self.scorelog.close()       # commit the partial tail segment
        if self.quality is not None:
            self.quality.emit(path=self._quality_path)
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        if self._heartbeat is not None:
            self._heartbeat.stop(exit_code=exit_code)
            self._heartbeat = None
        if self.model_set_dir and obs.enabled():
            # sampled request/batch spans land in the same trace the
            # pipeline steps flush to (analysis --telemetry renders it)
            from ..obs.report import trace_path
            obs.flush(trace_path(self.model_set_dir), step="SERVE")
        self._started = False

    # -------------------------------------------------- brownout mode
    @property
    def mode(self) -> str:
        """``normal`` or ``brownout`` (the ``serve.mode`` gauge /
        heartbeat extra / ``<< BROWNOUT`` monitor flag)."""
        return self.brownout.mode if self.brownout is not None \
            else "normal"

    def check_brownout(self, now: Optional[float] = None) -> str:
        """One governor evaluation (rides each heartbeat; tests call it
        directly): *stressed* = a firing burn-rate alert OR queue
        buildup.  Applies/reverts the degradation policy on a mode
        flip and returns the current mode."""
        if self.brownout is None:
            return "normal"
        qd = self.batcher.queue_depth
        top = self.registry.get(self.key).buckets[-1]
        stressed = bool(self.slo.alerts(now=now)) \
            or qd >= QUEUE_BUILDUP_BUCKETS * top
        if self.brownout.check(stressed):
            if self.brownout.mode == "brownout":
                self._enter_brownout()
            else:
                self._exit_brownout()
        obs.gauge("serve.mode").set(
            1.0 if self.brownout.mode == "brownout" else 0.0)
        return self.brownout.mode

    def _enter_brownout(self) -> None:
        """Shed everything optional: shrink the flush deadline (smaller
        batches, bounded queue wait), stop trace and score-log sampling,
        freeze ladder refinement.  Settings are saved for the exit."""
        b = self.batcher
        self._normal_settings = {
            "max_delay_s": b.max_delay_s,
            "trace_sample_rate": b.trace_sample_rate,
            "refine_every": b.refine_every,
            "scorelog": b.scorelog,
        }
        b.max_delay_s = b.max_delay_s * BROWNOUT_DELAY_FACTOR
        b.trace_sample_rate = 0.0
        b.refine_every = 0
        b.scorelog = None
        obs.counter("serve.brownouts").inc()
        log.warning("serve %s: BROWNOUT engaged (deadline %.2f ms, "
                    "sampling/refinement suspended)", self.key,
                    b.max_delay_s * 1000.0)

    def _exit_brownout(self) -> None:
        saved, self._normal_settings = self._normal_settings, None
        if saved is None:
            return
        b = self.batcher
        b.max_delay_s = saved["max_delay_s"]
        b.trace_sample_rate = saved["trace_sample_rate"]
        b.refine_every = saved["refine_every"]
        b.scorelog = saved["scorelog"]
        log.warning("serve %s: brownout lifted, normal service restored",
                    self.key)

    def _beat_extras(self) -> dict:
        """Per-beat heartbeat payload: queue depth + serving mode + the
        compact SLO summary (the monitor's buildup / burn-rate /
        brownout flags), mirrored into the registry gauges the exporter
        scrapes."""
        qd = self.batcher.queue_depth
        top = self.registry.get(self.key).buckets[-1]
        self.slo.emit_gauges()
        obs.gauge("serve.queue_depth").set(qd)
        extras = {"queue_depth": int(qd),
                  "queue_buildup": bool(qd >= QUEUE_BUILDUP_BUCKETS * top),
                  "mode": self.check_brownout(),
                  "slo": self.slo.compact()}
        if self.quality is not None:
            if self.outcomes is not None:
                self.outcomes.ingest_drop_dir(self._drop_dir)
            extras["quality"] = self.quality.compact()
            self.quality.emit(path=self._quality_path)
        return extras

    # ------------------------------------------------------------- scoring
    def submit(self, rows: np.ndarray,
               bins: Optional[np.ndarray] = None,
               trace_id: Optional[str] = None,
               req_id: Optional[str] = None,
               deadline_ms: Optional[float] = None) -> Ticket:
        return self.batcher.submit_burst(np.asarray(rows, np.float32),
                                         bins, trace_id=trace_id,
                                         req_id=req_id,
                                         deadline_ms=deadline_ms)

    def score(self, rows: np.ndarray, bins: Optional[np.ndarray] = None,
              timeout: float = 30.0,
              trace_id: Optional[str] = None,
              req_id: Optional[str] = None,
              deadline_ms: Optional[float] = None) -> np.ndarray:
        """Closed-loop scoring (mean ensemble score per row, scaled)."""
        if not self._started:                  # in-process, no worker
            t = self.batcher.submit_burst(np.asarray(rows, np.float32),
                                          bins, trace_id=trace_id,
                                          req_id=req_id,
                                          deadline_ms=deadline_ms)
            self.batcher.drain()
            return t.wait(timeout)
        t = self.batcher.submit_burst(np.asarray(rows, np.float32), bins,
                                      trace_id=trace_id, req_id=req_id,
                                      deadline_ms=deadline_ms)
        return t.wait(timeout)

    def score_raw(self, records: Sequence, timeout: float = 30.0,
                  trace_id: Optional[str] = None,
                  req_id: Optional[str] = None,
                  deadline_ms: Optional[float] = None) -> dict:
        """Raw-record scoring: parse + categorical binning on host, the
        whole norm transform in-graph (fused into the scorer
        executable).  PER-RECORD rejection: a malformed record (non-
        object, non-scalar field) gets a coded error and a null
        ``scores`` slot while its neighbours still score — the
        ``-Dshifu.data.badThreshold`` philosophy applied to serving."""
        scorer = self.registry.get(self.key)
        if not getattr(scorer, "accepts_raw", False):
            raise ValueError(
                "this modelset serves pre-binned rows only — raw "
                "records need the ModelConfig/ColumnConfig snapshot "
                "next to models/")
        obs.counter("serve.raw_requests").inc()
        packed, kept, errors = scorer.transform.parse_records(records)
        if errors:
            obs.counter("serve.raw_rejects").inc(len(errors))
        scores: list = [None] * len(records)
        if len(packed):
            obs.counter("serve.raw_rows").inc(int(len(packed)))
            t = self.batcher.submit_burst(packed, raw=True,
                                          trace_id=trace_id,
                                          req_id=req_id,
                                          deadline_ms=deadline_ms)
            if not self._started:              # in-process, no worker
                self.batcher.drain()
            got = t.wait(timeout)
            for i, s in zip(kept, got):
                scores[int(i)] = float(s)
        return {"scores": scores, "errors": errors,
                "generation": self.registry.generation(self.key)}

    def swap_phase(self, doc: dict) -> dict:
        """The ``POST /swap`` body: ``{"phase": ..., "dir": ...}``.

        ``prepare`` BUILDs + warms the candidate from ``dir`` and holds
        it pending (live model untouched); ``commit`` journals + flips
        it; ``abort`` discards it; ``swap`` (the default) does
        prepare+commit in one call.  The fleet router drives
        prepare-everywhere THEN commit-everywhere so no request ever
        sees a mixed-model fleet."""
        phase = str(doc.get("phase") or "swap")
        if phase in ("prepare", "swap"):
            mdir = doc.get("dir") or doc.get("models_dir")
            if not mdir:
                raise ValueError(
                    'swap phase %r needs a models dir ({"dir": ...})'
                    % phase)
            if phase == "swap":
                self.swap(str(mdir))
            else:
                gen = self.registry.prepare(
                    self.key, str(mdir), buckets=self._refined_ladder())
                return {"kind": "swap", "phase": phase,
                        "prepared_generation": gen,
                        "generation": self.registry.generation(self.key)}
        elif phase == "commit":
            self.registry.commit(self.key)
        elif phase == "abort":
            self.registry.abort(self.key)
        else:
            raise ValueError(f"unknown swap phase {phase!r}")
        return {"kind": "swap", "phase": phase,
                "generation": self.registry.generation(self.key)}

    def _refined_ladder(self) -> tuple:
        """The live ladder refined against observed batch sizes — the
        candidate compiles/warms on it during BUILD."""
        from .scorer import refine_ladder
        scorer = self.registry.get(self.key)
        with self.batcher._cond:
            counts = dict(self.batcher.size_counts)
        return refine_ladder(scorer.buckets, counts)

    def swap(self, models_or_dir) -> None:
        """Promote a retrained model without dropping requests.  The
        candidate's ladder is the live ladder REFINED against the
        observed batch-size distribution (:func:`refine_ladder`), so a
        swap is also the natural point where padding waste learned
        during this generation's traffic is squeezed out — every rung
        (inherited and refined) compiles and warms during the swap's
        BUILD phase, before the flip."""
        self.registry.swap(self.key, models_or_dir,
                           buckets=self._refined_ladder())

    def status(self) -> dict:
        scorer = self.registry.get(self.key)
        return {
            "state": "serving" if self._started else "loaded",
            "key": self.key,
            "generation": self.registry.generation(self.key),
            "models": len(scorer.models),
            "buckets": list(scorer.buckets),
            "needs_bins": scorer.needs_bins,
            "accepts_raw": bool(getattr(scorer, "accepts_raw", False)),
            "replica": self.replica,
            "n_features": scorer.n_features,
            "max_delay_ms": self.batcher.max_delay_s * 1000.0,
            "trace_sample_rate": self.batcher.trace_sample_rate,
            "queue_depth": int(self.batcher.queue_depth),
            "mode": self.mode,
            "slo": self.slo.compact(),
            "stats": dict(self.batcher.stats),
            "bucket_counts": {str(k): v for k, v in
                              sorted(self.batcher.bucket_counts.items())},
        }

    def slo_doc(self) -> dict:
        """The ``GET /slo`` payload: objectives, short/long-horizon
        quantiles/availability, burn rates and firing alerts."""
        return {"kind": "slo", "key": self.key,
                "queue_depth": int(self.batcher.queue_depth),
                **self.slo.summary()}

    # ------------------------------------------------------ quality plane
    def _on_scored(self, req: str, scores, gen: int, ts: float) -> None:
        """Score-log hook (every SAMPLED record): feed the PSI
        histogram and register the prediction for the delayed join."""
        if self.quality is not None:
            self.quality.observe_scores(gen, scores)
        if self.outcomes is not None:
            self.outcomes.record_prediction(req, scores, gen, ts=ts)

    def _on_join(self, gen: int, scores, labels) -> None:
        """Outcome-join hook: fold the joined rows into the live
        AUC/calibration windows; re-emit the artifact periodically so
        the controller/monitor read fresh numbers between beats."""
        if self.quality is None:
            return
        self.quality.update(gen, scores, labels)
        self._join_count += 1
        if self._join_count % 8 == 0:
            self.quality.emit(path=self._quality_path)

    def add_outcomes(self, doc) -> dict:
        """The ``POST /outcome`` body: one ``{"req", "labels"}`` record
        or a ``{"outcomes": [...]}`` batch.  Returns join accounting
        (``joined_rows`` counts rows joined by THIS call)."""
        if self.outcomes is None:
            return {"kind": "outcome", "enabled": False,
                    "joined_rows": 0}
        recs = doc.get("outcomes") \
            if isinstance(doc, dict) and "outcomes" in doc else [doc]
        joined = 0
        for rec in recs:
            got = self.outcomes.add_outcome(
                str(rec["req"]), rec.get("labels", rec.get("label")))
            if got is not None:
                joined += int(len(got[1]))
        return {"kind": "outcome", "enabled": True,
                "joined_rows": joined,
                "pending": self.outcomes.pending,
                "late": self.outcomes.stats["late"]}

    def quality_doc(self) -> dict:
        """The ``GET /quality`` payload: the live quality summary (drop
        directory swept first, so a batch label feed lands before the
        read)."""
        if self.quality is None:
            return {"kind": "quality", "key": self.key, "enabled": False}
        if self.outcomes is not None:
            self.outcomes.ingest_drop_dir(self._drop_dir)
        return {"key": self.key, "enabled": True,
                **self.quality.summary()}


# ------------------------------------------------------------------ HTTP
def _make_handler(server: ServeServer):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive: every reply carries Content-Length, so
        # the router's per-replica connection pool can reuse sockets
        # across health polls and scoring requests
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, doc: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):                      # noqa: N802 (stdlib API)
            if self.path in ("/healthz", "/health", "/status"):
                self._reply(200, server.status())
            elif self.path == "/slo":
                self._reply(200, server.slo_doc())
            elif self.path == "/quality":
                self._reply(200, server.quality_doc())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):                     # noqa: N802
            if self.path not in ("/score", "/outcome", "/swap"):
                self._reply(404, {"error": f"unknown path {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/outcome":
                    self._reply(200, server.add_outcomes(doc))
                    return
                if self.path == "/swap":
                    self._reply(200, server.swap_phase(doc))
                    return
                # a kill here models a replica dying mid-request — the
                # router requeues the un-launched ticket on a peer
                faults.fire("serve", "replica",
                            server.replica or server.key)
                # propagate the caller's trace id (forces sampling)
                trace_id = self.headers.get("X-Shifu-Trace")
                # the outcome-join key: caller-supplied, or minted here
                # when the score log is live (sampling decides whether
                # the id actually becomes joinable)
                req_id = self.headers.get("X-Shifu-Request")
                if req_id is None and server.scorelog is not None:
                    req_id = os.urandom(8).hex()
                # the propagated request budget (router -> worker):
                # remaining milliseconds; absent = the property default
                deadline_ms = None
                hdr = self.headers.get("X-Shifu-Deadline-Ms")
                if hdr is not None:
                    deadline_ms = float(hdr)
                if "records" in doc:           # raw-record path
                    recs = doc["records"]
                    if not isinstance(recs, list):
                        self._reply(400, {"error": "records must be a "
                                          "list of objects"})
                        return
                    got = server.score_raw(recs, trace_id=trace_id,
                                           req_id=req_id,
                                           deadline_ms=deadline_ms)
                    if got["errors"] and not any(
                            s is not None for s in got["scores"]):
                        self._reply(400, {**got, "error":
                                          "no parseable records"})
                        return
                    out = {**got, "scores":
                           [None if s is None else round(float(s), 6)
                            for s in got["scores"]]}
                else:
                    rows = np.asarray(doc["rows"], np.float32)
                    bins = doc.get("bins")
                    if bins is not None:
                        bins = np.asarray(bins, np.int32)
                    scores = server.score(rows, bins, trace_id=trace_id,
                                          req_id=req_id,
                                          deadline_ms=deadline_ms)
                    out = {"scores": [round(float(s), 6)
                                      for s in scores],
                           "generation":
                               server.registry.generation(server.key)}
                if trace_id:
                    out["trace"] = trace_id
                if req_id:
                    out["req"] = req_id
                self._reply(200, out)
            except OverloadedError as e:       # coded admission shed
                self._reply(429, {"error": e.code,
                                  "retry_after_ms":
                                      round(e.retry_after_s * 1000.0, 3)},
                            headers={"Retry-After":
                                     str(max(1, round(e.retry_after_s)))})
            except DeadlineExceededError as e:  # coded deadline shed
                self._reply(504, {"error": e.code, "detail": str(e)})
            except Exception as e:             # noqa: BLE001 — HTTP edge
                self._reply(400, {"error": str(e)})

        def log_message(self, fmt, *args):     # stdlib prints to stderr
            log.debug("http: " + fmt, *args)

    return Handler


def run_serve(model_set_dir: str, port: int = 8188,
              selfcheck: int = 0, max_delay_ms: Optional[float] = None,
              buckets: Optional[Sequence[int]] = None,
              replica: Optional[str] = None,
              announce: Optional[str] = None) -> int:
    """The ``shifu-tpu serve`` entry.  ``selfcheck=N`` scores N synthetic
    rows in-process and exits (CI-friendly, no port); otherwise binds the
    stdlib HTTP front-end on ``port`` until interrupted.  A fleet worker
    runs with ``replica`` (its fleet name, stamped on heartbeats) and
    ``announce`` (a JSON file written after the bind with the actual
    port + pid — ``port=0`` binds ephemeral, the router reads the file
    to learn where)."""
    server = ServeServer(model_set_dir, max_delay_ms=max_delay_ms,
                         buckets=buckets, replica=replica)
    server.start()
    try:
        scorer = server.registry.get(server.key)
        if selfcheck:
            rng = np.random.default_rng(0)
            rows = rng.normal(size=(selfcheck,
                                    scorer.n_features)).astype(np.float32)
            bins = None
            if scorer.needs_bins:
                bins = np.zeros((selfcheck, scorer.n_bins_cols), np.int32)
            scores = server.score(rows, bins)
            print(json.dumps({"selfcheck_rows": int(selfcheck),
                              "scores_head": [round(float(s), 4)
                                              for s in scores[:5]],
                              **server.status()}))
            return 0
        from http.server import ThreadingHTTPServer
        httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                    _make_handler(server))
        bound = httpd.server_address[1]
        if announce:
            from ..ioutil import atomic_write_json
            atomic_write_json(announce, {"port": int(bound),
                                         "pid": os.getpid(),
                                         "name": replica or server.key})
        who = f"{server.key}/{replica}" if replica else server.key
        print(f"shifu-tpu serve: {who} on http://127.0.0.1:{bound} "
              f"(buckets {list(scorer.buckets)}, "
              f"deadline {server.batcher.max_delay_s * 1000:.1f} ms)")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return 0
    finally:
        server.stop()
