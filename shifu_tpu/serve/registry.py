"""Live-model registry: modelset-keyed scorers with atomic hot-swap.

A serving process holds one :class:`AOTScorer` per modelset.  Promoting
a retrained model must never drop requests, so a swap is journal-style:

1. BUILD — load the candidate's models and compile/warm every bucket
   executable, entirely off-line (the live scorer keeps serving);
2. JOURNAL — commit ``serving.json`` via :mod:`shifu_tpu.ioutil`'s
   atomic write (a restart re-resolves to whatever was last promoted —
   a crash mid-commit leaves the previous journal intact, and a crash
   between the commit and the flip re-promotes the candidate on
   restart: the journal is write-ahead);
3. FLIP — one reference assignment under the lock.  In-flight batches
   finish on the old scorer (the batcher reads the provider per flush);
   the next batch scores on the new one.  A journal failure (disk full,
   perms) raises BEFORE the flip, so the previous model stays live.

Fault site: ``serve:swap=<key>`` fires after BUILD and before
JOURNAL+FLIP — a crash or injected error there must leave the previous
model live and serving bit-identical scores.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

from .. import faults, obs
from ..eval.scorer import SCORE_SCALE, Scorer
from ..ioutil import atomic_write_json
from .scorer import AOTScorer

log = logging.getLogger(__name__)

SERVING_JOURNAL = "serving.json"


class ModelRegistry:
    """See module docs.  ``state_dir=None`` keeps the journal in-memory
    only (tests, embedded use)."""

    def __init__(self, state_dir: Optional[str] = None):
        self.state_dir = state_dir
        self._lock = threading.Lock()
        self._live: Dict[str, AOTScorer] = {}
        self._gen: Dict[str, int] = {}
        self._dirs: Dict[str, str] = {}

    # ------------------------------------------------------------ lookup
    def get(self, key: str) -> AOTScorer:
        with self._lock:
            try:
                return self._live[key]
            except KeyError:
                raise KeyError(f"no live model under {key!r} — load() or "
                               "swap() one first") from None

    def provider(self, key: str):
        """A per-flush scorer resolver for :class:`MicroBatcher`."""
        return lambda: self.get(key)

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._live)

    def generation(self, key: str) -> int:
        with self._lock:
            return self._gen.get(key, 0)

    # ------------------------------------------------------- load / swap
    def _build(self, key: str, models_or_dir, scale: float,
               buckets: Optional[Sequence[int]], gen: int,
               warm: bool) -> AOTScorer:
        if isinstance(models_or_dir, str):
            models = Scorer.from_dir(models_or_dir).models
        else:
            models = list(models_or_dir)
        scorer = AOTScorer(models, scale=scale, buckets=buckets,
                           name=f"serve.score.{key}.g{gen}")
        if warm:
            scorer.warm()
        return scorer

    def load(self, key: str, models_or_dir, scale: float = SCORE_SCALE,
             buckets: Optional[Sequence[int]] = None,
             warm: bool = True) -> AOTScorer:
        """First load of a modelset (no previous model to protect);
        accepts a models dir or an in-memory model sequence."""
        scorer = self._build(key, models_or_dir, scale, buckets, 0, warm)
        new_dir = models_or_dir if isinstance(models_or_dir, str) else None
        self._journal(pending={key: (new_dir, 0)})
        with self._lock:
            self._live[key] = scorer
            self._gen[key] = 0
            if new_dir is not None:
                self._dirs[key] = new_dir
        return scorer

    def swap(self, key: str, models_or_dir, scale: float = SCORE_SCALE,
             buckets: Optional[Sequence[int]] = None,
             warm: bool = True) -> AOTScorer:
        """Atomic hot-swap (see module docs).  Raises if the build or
        journal fails — the previous model stays live in that case."""
        with self._lock:
            if key not in self._live:
                raise KeyError(f"swap({key!r}) before load() — nothing "
                               "is live to replace")
            gen = self._gen[key] + 1
        # BUILD off-line: the expensive part happens while the old
        # scorer keeps serving
        scorer = self._build(key, models_or_dir, scale, buckets, gen, warm)
        # a crash from here to the flip must leave the OLD model live
        faults.fire("serve", "swap", key)
        new_dir = models_or_dir if isinstance(models_or_dir, str) else None
        # JOURNAL before FLIP (module docs): a journal failure raises
        # while the old model is still live; once committed, the flip is
        # one infallible reference assignment
        self._journal(pending={key: (new_dir, gen)})
        with self._lock:
            self._live[key] = scorer
            self._gen[key] = gen
            if new_dir is not None:
                self._dirs[key] = new_dir
        obs.counter("serve.swaps").inc()
        log.info("promoted %s generation %d", key, gen)
        return scorer

    # ------------------------------------------------------------ journal
    def _journal(self, pending: Optional[Dict[str, tuple]] = None) -> None:
        """Commit the serving journal.  ``pending`` maps key ->
        ``(models_dir|None, generation)`` for a promotion that is being
        journalled BEFORE its flip (write-ahead)."""
        if not self.state_dir:
            return
        with self._lock:
            keys = set(self._live)
            dirs = dict(self._dirs)
            gens = dict(self._gen)
        for k, (mdir, gen) in (pending or {}).items():
            keys.add(k)
            gens[k] = gen
            if mdir is not None:
                dirs[k] = mdir
        doc = {k: {"models_dir": dirs.get(k),
                   "generation": gens.get(k, 0),
                   "promoted_ts": round(time.time(), 3)}
               for k in sorted(keys)}
        os.makedirs(self.state_dir, exist_ok=True)
        atomic_write_json(os.path.join(self.state_dir, SERVING_JOURNAL),
                          doc)
