"""Live-model registry: modelset-keyed scorers with atomic hot-swap.

A serving process holds one :class:`AOTScorer` per modelset.  Promoting
a retrained model must never drop requests, so a swap is journal-style:

1. BUILD — load the candidate's models and compile/warm every bucket
   executable, entirely off-line (the live scorer keeps serving);
2. JOURNAL — commit ``serving.json`` via :mod:`shifu_tpu.ioutil`'s
   atomic write (a restart re-resolves to whatever was last promoted —
   a crash mid-commit leaves the previous journal intact, and a crash
   between the commit and the flip re-promotes the candidate on
   restart: the journal is write-ahead);
3. FLIP — one reference assignment under the lock.  In-flight batches
   finish on the old scorer (the batcher reads the provider per flush);
   the next batch scores on the new one.  A journal failure (disk full,
   perms) raises BEFORE the flip, so the previous model stays live.

The registry keeps a bounded GENERATION HISTORY per key (previous
serving docs + their scorers/model dirs, ``-Dshifu.serve.generations``
deep): :meth:`rollback` re-flips to the prior generation through the
SAME journal-first path — the continual-refresh controller's escape
hatch when a promotion burns its probation window.  Generation numbers
are monotonic (a post-rollback promotion never reuses a number), and
``serving.json`` records the history so a restarted process can resolve
*and* roll back.

Fault site: ``serve:swap=<key>`` fires after BUILD and before
JOURNAL+FLIP — on the swap AND rollback paths — a crash or injected
error there must leave the currently-live model serving bit-identical
scores.

For a FLEET-coordinated swap the two phases are exposed separately:
:meth:`prepare` runs BUILD and holds the warmed candidate pending
(the live model keeps serving, nothing is journalled), then
:meth:`commit` runs JOURNAL+FLIP, or :meth:`abort` discards the
candidate.  A router prepares every replica before committing any, so
no request ever sees a mixed-model fleet; :meth:`swap` is simply
prepare+commit in one call.

A registry may carry a :class:`~shifu_tpu.serve.transform.FusedTransform`
per key (``load(..., transform=...)``): it is threaded into every
scorer the registry builds — swap, rollback rebuild, restore — so the
raw-record path survives promotion.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

from .. import faults, obs
from ..eval.scorer import SCORE_SCALE, Scorer
from ..ioutil import atomic_write_json
from .scorer import AOTScorer

log = logging.getLogger(__name__)

SERVING_JOURNAL = "serving.json"

DEFAULT_GENERATIONS = 3


def history_limit(override: Optional[int] = None) -> int:
    """Bounded generation history depth: ``shifu.serve.generations``
    previous generations kept rollback-able (default 3)."""
    if override is not None:
        return max(0, int(override))
    from ..config import environment
    return max(0, environment.get_int("shifu.serve.generations",
                                      DEFAULT_GENERATIONS))


class _Generation(NamedTuple):
    gen: int
    scorer: Optional[AOTScorer]     # None = rebuildable from models_dir
    models_dir: Optional[str]
    promoted_ts: float


class _Pending(NamedTuple):
    """A prepared-but-uncommitted swap candidate (see :meth:`prepare`)."""
    gen: int
    scorer: AOTScorer
    models_dir: Optional[str]
    buckets: Optional[tuple]
    transform: Optional[object]


class ModelRegistry:
    """See module docs.  ``state_dir=None`` keeps the journal in-memory
    only (tests, embedded use)."""

    def __init__(self, state_dir: Optional[str] = None):
        self.state_dir = state_dir
        self._lock = threading.Lock()
        self._live: Dict[str, AOTScorer] = {}
        self._gen: Dict[str, int] = {}
        self._dirs: Dict[str, str] = {}
        self._hist: Dict[str, List[_Generation]] = {}
        self._peak: Dict[str, int] = {}      # highest gen ever (monotonic)
        self._buckets: Dict[str, Optional[tuple]] = {}   # last ladder used
        self._transforms: Dict[str, Optional[object]] = {}  # FusedTransform
        self._pending: Dict[str, _Pending] = {}   # prepared, uncommitted

    # ------------------------------------------------------------ lookup
    def get(self, key: str) -> AOTScorer:
        with self._lock:
            try:
                return self._live[key]
            except KeyError:
                raise KeyError(f"no live model under {key!r} — load() or "
                               "swap() one first") from None

    def provider(self, key: str):
        """A per-flush scorer resolver for :class:`MicroBatcher`."""
        return lambda: self.get(key)

    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._live)

    def generation(self, key: str) -> int:
        with self._lock:
            return self._gen.get(key, 0)

    def next_generation(self, key: str) -> int:
        """The number the NEXT promotion will take — monotonic past the
        peak, so a rolled-back generation's number is never reused."""
        with self._lock:
            return self._peak.get(key, 0) + 1

    def generation_history(self, key: str) -> List[Dict]:
        """Previous generations (oldest first) still rollback-able."""
        with self._lock:
            return [{"generation": g.gen, "models_dir": g.models_dir,
                     "promoted_ts": g.promoted_ts}
                    for g in self._hist.get(key, [])]

    # ------------------------------------------------------- load / swap
    def _build(self, key: str, models_or_dir, scale: float,
               buckets: Optional[Sequence[int]], gen: int,
               warm: bool, transform=None) -> AOTScorer:
        if isinstance(models_or_dir, str):
            models = Scorer.from_dir(models_or_dir).models
        else:
            models = list(models_or_dir)
        scorer = AOTScorer(models, scale=scale, buckets=buckets,
                           name=f"serve.score.{key}.g{gen}",
                           transform=transform)
        if warm:
            scorer.warm()
        return scorer

    def load(self, key: str, models_or_dir, scale: float = SCORE_SCALE,
             buckets: Optional[Sequence[int]] = None,
             warm: bool = True, transform=None) -> AOTScorer:
        """First load of a modelset (no previous model to protect);
        accepts a models dir or an in-memory model sequence.  A
        ``transform`` (:class:`FusedTransform`) enables the raw-record
        executable family and is carried into every later rebuild."""
        scorer = self._build(key, models_or_dir, scale, buckets, 0, warm,
                             transform)
        new_dir = models_or_dir if isinstance(models_or_dir, str) else None
        self._journal(pending={key: (new_dir, 0)})
        with self._lock:
            self._live[key] = scorer
            self._gen[key] = 0
            self._peak[key] = max(self._peak.get(key, 0), 0)
            self._hist.setdefault(key, [])
            self._buckets[key] = tuple(buckets) if buckets else None
            self._transforms[key] = transform
            if new_dir is not None:
                self._dirs[key] = new_dir
        return scorer

    def restore(self, key: str, default_models_dir: str,
                scale: float = SCORE_SCALE,
                buckets: Optional[Sequence[int]] = None,
                warm: bool = True, transform=None) -> AOTScorer:
        """Resolve the serving journal and load whatever was last
        promoted under ``key`` (falling back to ``default_models_dir``
        for a never-promoted set), restoring the recorded generation
        number and the rollback history (scorers rebuild lazily from
        their model dirs) — the restart path of a serving/refresh
        process."""
        doc = self._read_journal().get(key) or {}
        mdir = doc.get("models_dir") or default_models_dir
        gen = int(doc.get("generation") or 0)
        hist = [h for h in (doc.get("history") or [])
                if h.get("models_dir")]
        scorer = self._build(key, mdir, scale, buckets, gen, warm,
                             transform)
        with self._lock:
            self._live[key] = scorer
            self._gen[key] = gen
            self._dirs[key] = mdir
            self._transforms[key] = transform
            self._hist[key] = [
                _Generation(int(h["generation"]), None, h["models_dir"],
                            float(h.get("promoted_ts") or 0.0))
                for h in hist]
            self._peak[key] = max([gen] + [int(h["generation"])
                                           for h in hist])
            self._buckets[key] = tuple(buckets) if buckets else None
        # re-commit the resolved doc: a never-promoted set gets its
        # first journal here, and a pruned history is recorded
        self._journal()
        log.info("restored %s at generation %d (%d prior generation(s) "
                 "rollback-able)", key, gen, len(hist))
        return scorer

    def prepare(self, key: str, models_or_dir, scale: float = SCORE_SCALE,
                buckets: Optional[Sequence[int]] = None,
                warm: bool = True, transform=None) -> int:
        """Phase 1 of a swap: BUILD the candidate (load, compile and
        warm every bucket executable) and hold it PENDING — the live
        model keeps serving and nothing is journalled, so a fleet
        router can prepare EVERY replica before committing any.
        Returns the generation the candidate will take on
        :meth:`commit`; :meth:`abort` discards it.  The number is not
        reserved until commit, so an aborted or failed prepare lets the
        next promotion take the same number."""
        with self._lock:
            if key not in self._live:
                raise KeyError(f"prepare({key!r}) before load() — "
                               "nothing is live to replace")
            gen = self._peak.get(key, self._gen[key]) + 1
            if transform is None:
                transform = self._transforms.get(key)
        # BUILD off-line: the expensive part happens while the old
        # scorer keeps serving
        scorer = self._build(key, models_or_dir, scale, buckets, gen,
                             warm, transform)
        new_dir = models_or_dir if isinstance(models_or_dir, str) else None
        with self._lock:
            self._pending[key] = _Pending(
                gen, scorer, new_dir,
                tuple(buckets) if buckets else None, transform)
        return gen

    def commit(self, key: str) -> AOTScorer:
        """Phase 2 of a swap: JOURNAL then FLIP the PENDING candidate
        (module docs — a failure before the flip leaves the previous
        model live and the candidate discarded)."""
        with self._lock:
            if key not in self._pending:
                raise KeyError(f"commit({key!r}) without a prepare()")
            pend = self._pending.pop(key)
            # interleaved promotions may have moved the peak since
            # prepare: never reuse a taken number
            gen = max(pend.gen, self._peak.get(key, 0) + 1)
            prev = _Generation(self._gen[key], self._live[key],
                               self._dirs.get(key), round(time.time(), 3))
        # a crash from here to the flip must leave the OLD model live
        faults.fire("serve", "swap", key)
        # JOURNAL before FLIP (module docs): a journal failure raises
        # while the old model is still live; once committed, the flip is
        # one infallible reference assignment.  The journal records the
        # post-flip history (incumbent demoted into it, bounded).
        limit = history_limit()
        with self._lock:
            hist_after = (self._hist.get(key, []) + [prev])[-limit:] \
                if limit else []
        self._journal(pending={key: (pend.models_dir, gen)},
                      history={key: hist_after})
        with self._lock:
            self._hist[key] = hist_after
            self._live[key] = pend.scorer
            self._gen[key] = gen
            self._peak[key] = max(self._peak.get(key, 0), gen)
            self._buckets[key] = pend.buckets
            self._transforms[key] = pend.transform
            if pend.models_dir is not None:
                self._dirs[key] = pend.models_dir
        obs.counter("serve.swaps").inc()
        log.info("promoted %s generation %d", key, gen)
        return pend.scorer

    def abort(self, key: str) -> bool:
        """Discard a PENDING candidate (canary losers, a fleet-mate's
        failed prepare).  The live model never moved; returns whether
        anything was pending."""
        with self._lock:
            return self._pending.pop(key, None) is not None

    def pending_generation(self, key: str) -> Optional[int]:
        """The generation a PENDING candidate will take, or None."""
        with self._lock:
            pend = self._pending.get(key)
            return None if pend is None else pend.gen

    def swap(self, key: str, models_or_dir, scale: float = SCORE_SCALE,
             buckets: Optional[Sequence[int]] = None,
             warm: bool = True, transform=None) -> AOTScorer:
        """Atomic hot-swap (see module docs): :meth:`prepare` +
        :meth:`commit` in one call.  Raises if the build or journal
        fails — the previous model stays live in that case."""
        self.prepare(key, models_or_dir, scale, buckets, warm, transform)
        return self.commit(key)

    def rollback(self, key: str, warm: bool = True) -> AOTScorer:
        """Re-flip to the previous generation through the same
        journal-first path as :meth:`swap`: journal commits the
        post-rollback doc first, then one reference assignment.  The
        prior generation's scorer is reused when still held (bit-
        identical scores by construction) or rebuilt from its recorded
        model dir.  Raises with the CURRENT model still live when there
        is no history (or the journal fails)."""
        with self._lock:
            if key not in self._live:
                raise KeyError(f"rollback({key!r}) before load()")
            hist = list(self._hist.get(key) or [])
            if not hist:
                raise LookupError(
                    f"rollback({key!r}): no previous generation held — "
                    "the history window (shifu.serve.generations) is "
                    "empty")
            prev = hist[-1]
            cur_gen = self._gen[key]
        scorer = prev.scorer
        if scorer is None:
            # restored-process history entry: rebuild from the dir the
            # journal recorded (off-line, like a swap's BUILD phase) on
            # the key's own bucket ladder — same launch shapes, same
            # bits
            scorer = self._build(key, prev.models_dir, SCORE_SCALE,
                                 self._buckets.get(key), prev.gen, warm,
                                 self._transforms.get(key))
        # same crash-safety contract as swap: a death here leaves the
        # CURRENT model live and the journal un-flipped
        faults.fire("serve", "swap", key)
        self._journal(pending={key: (prev.models_dir, prev.gen)},
                      history={key: hist[:-1]})
        with self._lock:
            self._hist[key] = hist[:-1]
            self._live[key] = scorer
            self._gen[key] = prev.gen
            if prev.models_dir is not None:
                self._dirs[key] = prev.models_dir
        obs.counter("serve.rollbacks").inc()
        log.info("rolled back %s generation %d -> %d", key, cur_gen,
                 prev.gen)
        return scorer

    # ------------------------------------------------------------ journal
    def _read_journal(self) -> Dict[str, dict]:
        if not self.state_dir:
            return {}
        try:
            with open(os.path.join(self.state_dir, SERVING_JOURNAL)) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def _journal(self, pending: Optional[Dict[str, tuple]] = None,
                 history: Optional[Dict[str, List[_Generation]]] = None
                 ) -> None:
        """Commit the serving journal.  ``pending`` maps key ->
        ``(models_dir|None, generation)`` for a promotion/rollback that
        is being journalled BEFORE its flip (write-ahead); ``history``
        carries the post-flip generation history for those keys."""
        if not self.state_dir:
            return
        with self._lock:
            keys = set(self._live)
            dirs = dict(self._dirs)
            gens = dict(self._gen)
            hists = {k: list(v) for k, v in self._hist.items()}
        for k, (mdir, gen) in (pending or {}).items():
            keys.add(k)
            gens[k] = gen
            if mdir is not None:
                dirs[k] = mdir
        for k, h in (history or {}).items():
            hists[k] = list(h)
        doc = {k: {"models_dir": dirs.get(k),
                   "generation": gens.get(k, 0),
                   "promoted_ts": round(time.time(), 3),
                   "history": [{"generation": g.gen,
                                "models_dir": g.models_dir,
                                "promoted_ts": g.promoted_ts}
                               for g in hists.get(k, [])]}
               for k in sorted(keys)}
        os.makedirs(self.state_dir, exist_ok=True)
        atomic_write_json(os.path.join(self.state_dir, SERVING_JOURNAL),
                          doc)
