"""Online serving plane — sustained-traffic scoring with latency SLOs.

The reference's production surface scores one record at a time
(``IndependentNNModel`` / ``IndependentTreeModel`` behind a thread pool,
~1.5k rows/s/worker measured — BASELINE.md); this plane applies the
large-fused-graph argument to inference: concurrent single-record
requests coalesce into a handful of PRE-COMPILED padded-bucket device
launches, so the per-request cost is one queue append, not one tracing +
dispatch round trip.

Modules:

- :mod:`scorer`  — :class:`AOTScorer`: the modelset's ensemble pinned in
  HBM once, ``lower()→compile()`` one executable per batch bucket with
  donated input buffers (no per-request tracing; the recompile sentinel
  from :mod:`shifu_tpu.obs.costs` polices shape churn);
- :mod:`batcher` — :class:`MicroBatcher`: request queue + deadline
  batcher that coalesces requests into the smallest covering bucket of a
  geometric ladder (``-Dshifu.serve.buckets``), padding the remainder and
  flushing on ``-Dshifu.serve.maxDelayMs`` so p99 is bounded at low load
  and throughput wins at high load;
- :mod:`registry` — :class:`ModelRegistry`: live models keyed by
  modelset with atomic hot-swap (build + warm the new scorer fully, then
  journal-style promote) so a retrain replaces the live model without
  dropping requests;
- :mod:`server`  — :class:`ServeServer` + the ``shifu-tpu serve`` CLI
  entry: heartbeats from :mod:`shifu_tpu.obs.health` (carrying queue
  depth + the live SLO summary), optional stdlib HTTP front-end
  (``POST /score``, ``GET /healthz``, ``GET /slo``, ``POST /swap``);
- :mod:`transform` — :class:`FusedTransform`: the offline norm pipeline
  (binning, WoE/zscore maps, missing handling) compiled as a jnp
  prelude INSIDE the scorer executable, so ``POST /score`` accepts raw
  ``{field: value}`` records bit-identical to the offline norm+eval
  path;
- :mod:`router`  — :class:`ServeRouter` + ``shifu-tpu serve
  --replicas N``: N worker processes behind a health-/SLO-aware
  balancing front with requeue-on-replica-death and coordinated
  no-mixed-window fleet hot-swap (``-Dshifu.serve.canaryFrac`` commits
  an explicit canary slice instead).

Observability: per-request tracing (head-sampled at
``-Dshifu.serve.traceSampleRate``, or forced by an ``X-Shifu-Trace``
header) decomposes each sampled request into queue-wait / deadline-wait
/ pad / launch / device spans with batch fan-in links (see
:mod:`batcher`), and every completion feeds the live SLO plane
(:mod:`shifu_tpu.obs.slo`: sliding-window quantiles, burn-rate alerts
against ``-Dshifu.serve.sloP99Ms`` / ``-Dshifu.serve.sloAvailability``).

Bench: ``bench.py --plane serve`` (sustained QPS, p50/p99 at several
offered loads, bucket occupancy / padding waste, zero-recompile guard,
1%-sampled traced pass + latency-decomposition extras).
"""

from .batcher import MicroBatcher, Ticket                     # noqa: F401
from .registry import ModelRegistry                           # noqa: F401
from .router import ServeRouter, run_fleet                    # noqa: F401
from .scorer import (AOTScorer, bucket_ladder,                # noqa: F401
                     covering_bucket, infer_dims,
                     serve_recompile_count)
from .server import ServeServer, max_delay_s                  # noqa: F401
from .transform import FusedTransform                         # noqa: F401

__all__ = [
    "AOTScorer", "bucket_ladder", "covering_bucket", "infer_dims",
    "serve_recompile_count", "MicroBatcher", "Ticket", "ModelRegistry",
    "ServeServer", "max_delay_s", "FusedTransform", "ServeRouter",
    "run_fleet",
]
