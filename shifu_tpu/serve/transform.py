"""Fused raw-record transform — the norm pipeline as a jnp prelude.

The offline path norms on host (``data/transform.DatasetTransformer`` →
``ops/normalize.NormalizedColumn``) and ships pre-binned matrices to the
serving plane, so no production caller can actually POST a raw record.
This module compiles the SAME transform into the scorer's fused
executable: per-column device constant tables are built once from the
ColumnConfig snapshot, and the per-request work collapses to
string→float parsing on host plus searchsorted/gather/affine math
in-graph (the large-fused-graph argument: one XLA program instead of a
Python pass per request).

Bit-parity contract: for every norm type the device prelude reproduces
``DatasetTransformer.transform`` EXACTLY —

- every bin-index-only norm family (WoE, posrate/zscale categoricals,
  DISCRETE, INDEX) is collapsed to ONE fused f64 table evaluated on host
  by the offline code itself (``NormalizedColumn`` over the full bin-index
  domain), so the device op is a plain gather of offline-produced values;
- value-carrying families (ZSCALE/ZSCORE/HYBRID numerics, ASIS) run the
  identical clip/affine in-graph with host-precomputed f64 bounds;
- numeric binning is ``searchsorted(boundaries, v, side="right") - 1``
  with the same clip and missing→num_bins fill as ``ColumnBinner``;
- categorical string→index runs on host via the SAME ``ColumnBinner``
  (strings cannot enter the graph), riding the packed wire format.

Under x64 (the test/CI configuration) the prelude computes in float64
and the output is bit-identical to the offline f64→f32 pipeline; on
accelerators without x64 it computes in f32.

Tables are held as NUMPY arrays and minted into the graph at trace time
— a module-level jnp constant would leak as a tracer if the first import
happens inside a trace (see ``ops/hashing._MASK16``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config.model_config import NormType, PrecisionType

#: coded per-record rejection reasons (the ``-Dshifu.data.badThreshold``
#: philosophy: one malformed record fails ITS OWN ticket, never the batch)
ERR_BAD_RECORD = "bad_record"
ERR_BAD_FIELD = "bad_field"

_TABLE_TYPES = (
    NormType.WOE, NormType.WEIGHT_WOE, NormType.WOE_INDEX,
    NormType.WOE_ZSCORE, NormType.WOE_ZSCALE,
    NormType.WEIGHT_WOE_ZSCORE, NormType.WEIGHT_WOE_ZSCALE,
    NormType.WOE_ZSCALE_INDEX,
    NormType.DISCRETE_ZSCORE, NormType.DISCRETE_ZSCALE,
)


@dataclass
class _ColumnPlan:
    """One input column's host+device recipe."""
    name: str
    categorical: bool
    mode: str                      # onehot | table | asis | zscore
    width: int
    num_bins: int                  # binner bins; invalid/missing -> num_bins
    binner: Any = None             # ColumnBinner (host side)
    boundaries: Optional[np.ndarray] = None   # numeric split points (f64)
    table: Optional[np.ndarray] = None        # fused bin->value map (f64)
    mean: float = 0.0
    std: float = 1.0
    lo: float = 0.0                # z-score clip bounds (host f64 math,
    hi: float = 0.0                # identical rounding to the offline pass)
    zero: bool = False             # std ~ 0: the offline path emits zeros


class FusedTransform:
    """ColumnConfig snapshot -> packed wire format -> in-graph (x, bins).

    Wire format: one ``[n, 3*C]`` float array per request —
    ``vals | valid | bin-idx`` column triples — so the micro-batcher's
    split/pad/concat machinery handles raw tickets unchanged and a
    zero row (the pad filler) decodes as all-missing.
    """

    def __init__(self, model_config, column_configs,
                 columns: Optional[Sequence] = None):
        from ..data.transform import model_input_columns
        from ..ops.binning import ColumnBinner
        from ..ops.normalize import NormalizedColumn

        import jax
        self.mc = model_config
        self.norm_type = model_config.normalize.normType
        self.cutoff = model_config.normalize.stdDevCutOff
        self.precision = model_config.normalize.precisionType
        self.missing_values = list(
            model_config.dataSet.missingOrInvalidValues or [])
        self._x64 = bool(jax.config.jax_enable_x64)
        cols = list(columns) if columns is not None else \
            model_input_columns(model_config, column_configs)
        if not cols:
            raise ValueError("no input columns with binning stats — the "
                             "raw path needs the stats+norm snapshot")
        self.plan: List[_ColumnPlan] = [self._plan_column(
            cc, NormalizedColumn(cc, self.norm_type, self.cutoff),
            ColumnBinner) for cc in cols]
        self.width = sum(p.width for p in self.plan)
        # onehot columns emit >1 output column; the vectorized device
        # path assumes width 1 everywhere, so their presence routes
        # apply_device through the per-column fallback
        self._has_onehot = any(p.mode == "onehot" for p in self.plan)
        self._build_groups()

    # ------------------------------------------------------------- build
    def _plan_column(self, cc, nc, ColumnBinner) -> _ColumnPlan:
        cat = cc.is_categorical()
        t = self.norm_type
        if cat:
            binner = ColumnBinner(categories=cc.bin_category or [])
            boundaries = None
        else:
            binner = ColumnBinner(boundaries=np.asarray(cc.bin_boundary)) \
                if cc.bin_boundary else None
            boundaries = None if binner is None else binner.boundaries
        nb = binner.num_bins if binner is not None else 1
        onehot = t == NormType.ONEHOT or \
            (t == NormType.ZSCALE_ONEHOT and cat)
        p = _ColumnPlan(name=cc.columnName, categorical=cat, mode="zscore",
                        width=nc.width, num_bins=nb, binner=binner,
                        boundaries=boundaries)
        if onehot:
            p.mode = "onehot"
        elif cat or t in _TABLE_TYPES:
            # the offline transform itself, evaluated over every index the
            # binner can emit — the device gather replays it verbatim
            p.mode = "table"
            p.table = nc.bin_value_table(nb)
        elif t in (NormType.ASIS_WOE, NormType.ASIS_PR):
            p.mode = "asis"
            p.mean = float(cc.mean())
        else:
            # ZSCALE/ZSCORE/OLD_*/HYBRID*/ZSCALE_ONEHOT-numeric/*_INDEX-numeric
            mean, std = float(cc.mean()), cc.std_dev()
            p.mean = mean
            if std is None or std < 1e-5:
                p.zero = True
            else:
                p.std = float(std)
                p.lo = mean - self.cutoff * float(std)
                p.hi = mean + self.cutoff * float(std)
        return p

    @classmethod
    def from_dir(cls, model_set_dir: str) -> "FusedTransform":
        """Build from a model-set directory's config snapshot (the same
        files `norm`/`eval` read)."""
        from ..config import ModelConfig, load_column_configs
        mc = ModelConfig.load(os.path.join(model_set_dir,
                                           "ModelConfig.json"))
        ccs = load_column_configs(os.path.join(model_set_dir,
                                               "ColumnConfig.json"))
        return cls(mc, ccs)

    # -------------------------------------------------------------- wire
    @property
    def n_columns(self) -> int:
        return len(self.plan)

    @property
    def wire_width(self) -> int:
        return 3 * len(self.plan)

    @property
    def wire_dtype(self) -> np.dtype:
        return np.dtype(np.float64 if self._x64 else np.float32)

    def parse_records(self, records: Sequence[Any]
                      ) -> Tuple[np.ndarray, np.ndarray, List[Dict]]:
        """JSON records -> (packed [m, 3C], kept row indices, errors).

        A malformed record (non-object, or a non-scalar field value) is
        rejected ALONE with a coded error; parseable records around it
        still score.  Unparseable numeric STRINGS are not malformed —
        they are the offline pipeline's missing/invalid values and norm
        to the missing semantics, bit-identically.
        """
        from ..data.reader import parse_numeric
        errors: List[Dict] = []
        kept: List[int] = []
        for i, rec in enumerate(records):
            if not isinstance(rec, dict):
                errors.append({"index": i, "code": ERR_BAD_RECORD,
                               "error": "record must be an object of "
                                        "{field: value}"})
                continue
            bad = next((k for k, v in rec.items() if v is not None and
                        not isinstance(v, (str, int, float, bool))), None)
            if bad is not None:
                errors.append({"index": i, "code": ERR_BAD_FIELD,
                               "error": f"field {bad!r} must be a scalar "
                                        "value"})
                continue
            kept.append(i)
        c = len(self.plan)
        packed = np.zeros((len(kept), 3 * c), self.wire_dtype)
        if kept:
            for j, p in enumerate(self.plan):
                vals = np.array([_field_str(records[i].get(p.name))
                                 for i in kept], dtype=object)
                if p.categorical:
                    packed[:, 2 * c + j] = p.binner.bin_categorical(vals)
                    packed[:, c + j] = 1.0
                else:
                    f, valid = parse_numeric(vals, self.missing_values)
                    packed[:, j] = np.where(valid, f, 0.0)
                    packed[:, c + j] = valid
        return packed, np.asarray(kept, np.int64), errors

    def _build_groups(self) -> None:
        """Host-side column groups for the vectorized device path: the
        per-column graph loop emits O(C) tiny ops XLA CPU fuses poorly
        (measured 0.4-0.8x the pre-binned rate); grouping same-mode
        columns collapses the transform to a handful of batched ops —
        one vmapped searchsorted over padded boundaries, one padded
        table gather, one broadcast z-score — with bit-identical
        values (same elementwise IEEE ops, value-preserving column
        permutation at the end).  All constants stay NUMPY here (the
        tracer-leak rule, see the module docstring)."""
        z_idx: List[int] = []    # zscore/zero columns (width 1)
        t_idx: List[int] = []    # non-empty fused tables
        t0_idx: List[int] = []   # empty tables -> zeros
        a_idx: List[int] = []    # asis passthrough
        bc_idx: List[int] = []   # bins: categorical (wire passthrough)
        bn_idx: List[int] = []   # bins: numeric with boundaries
        bu_idx: List[int] = []   # bins: numeric without a binner
        for j, p in enumerate(self.plan):
            (bc_idx if p.categorical else
             bn_idx if p.boundaries is not None else bu_idx).append(j)
            if p.mode == "onehot":
                continue
            if p.mode == "table":
                (t_idx if len(p.table) else t0_idx).append(j)
            elif p.mode == "asis":
                a_idx.append(j)
            else:
                z_idx.append(j)
        pl = self.plan
        self._z_idx = np.asarray(z_idx, np.int32)
        self._z_mean = np.asarray([pl[j].mean for j in z_idx], np.float64)
        self._z_std = np.asarray([pl[j].std for j in z_idx], np.float64)
        self._z_lo = np.asarray([pl[j].lo for j in z_idx], np.float64)
        self._z_hi = np.asarray([pl[j].hi for j in z_idx], np.float64)
        self._z_zero = np.asarray([pl[j].zero for j in z_idx], bool)
        self._t_idx = np.asarray(t_idx, np.int32)
        self._t_len = np.asarray([len(pl[j].table) for j in t_idx],
                                 np.int32)
        tmax = int(self._t_len.max()) if t_idx else 0
        self._t_tab = np.zeros((len(t_idx), tmax), np.float64)
        for k, j in enumerate(t_idx):
            self._t_tab[k, :len(pl[j].table)] = pl[j].table
        self._t0_idx = np.asarray(t0_idx, np.int32)
        self._a_idx = np.asarray(a_idx, np.int32)
        self._a_mean = np.asarray([pl[j].mean for j in a_idx], np.float64)
        self._bc_idx = np.asarray(bc_idx, np.int32)
        self._bn_idx = np.asarray(bn_idx, np.int32)
        self._bn_nb = np.asarray([pl[j].num_bins for j in bn_idx],
                                 np.int32)
        bmax = max((len(pl[j].boundaries) for j in bn_idx), default=0)
        # +inf pad: finite values always insert before the pad, so the
        # padded searchsorted returns the unpadded column's index
        self._bn_bounds = np.full((len(bn_idx), bmax), np.inf, np.float64)
        for k, j in enumerate(bn_idx):
            self._bn_bounds[k, :len(pl[j].boundaries)] = pl[j].boundaries
        self._bu_idx = np.asarray(bu_idx, np.int32)
        if not self._has_onehot:
            self._x_inv = np.argsort(
                np.concatenate([self._z_idx, self._t_idx, self._t0_idx,
                                self._a_idx]))
        self._bin_inv = np.argsort(
            np.concatenate([self._bc_idx, self._bn_idx, self._bu_idx]))

    # ------------------------------------------------------------ device
    def apply_device(self, packed):
        """TRACED: packed wire rows -> (x [n, width] f32, bins [n, C]
        int32) — the whole norm transform as graph ops, fused by XLA
        into the scorer executable that consumes it.  Same-mode columns
        run as single batched ops (see :meth:`_build_groups`); onehot
        plans take the per-column fallback."""
        import jax
        import jax.numpy as jnp
        if self._has_onehot:
            return self._apply_device_cols(packed)
        cd = jnp.float64 if self._x64 else jnp.float32
        c = len(self.plan)
        n = packed.shape[0]
        vals = packed[:, :c].astype(cd)
        valid = packed[:, c:2 * c] != 0
        cats = packed[:, 2 * c:3 * c].astype(jnp.int32)

        bin_blocks = []
        if len(self._bc_idx):
            bin_blocks.append(cats[:, self._bc_idx])
        if len(self._bn_idx):
            v, ok = vals[:, self._bn_idx], valid[:, self._bn_idx]
            bounds = jnp.asarray(self._bn_bounds, cd)
            idx = jax.vmap(
                lambda b, col: jnp.searchsorted(b, col, side="right"),
                in_axes=(0, 1), out_axes=1)(bounds, v) - 1
            nb = jnp.asarray(self._bn_nb)
            idx = jnp.clip(idx, 0, nb[None, :] - 1)
            bin_blocks.append(
                jnp.where(ok, idx, nb[None, :]).astype(jnp.int32))
        if len(self._bu_idx):
            bin_blocks.append(
                jnp.where(valid[:, self._bu_idx], 0, 1).astype(jnp.int32))
        binm = bin_blocks[0] if len(bin_blocks) == 1 else \
            jnp.concatenate(bin_blocks, axis=1)
        bins = binm[:, self._bin_inv]

        x_blocks = []
        if len(self._z_idx):
            v, ok = vals[:, self._z_idx], valid[:, self._z_idx]
            mean = jnp.asarray(self._z_mean, cd)
            filled = jnp.where(ok, v, mean[None, :])
            lo = jnp.asarray(self._z_lo, cd)[None, :]
            hi = jnp.asarray(self._z_hi, cd)[None, :]
            std = jnp.asarray(self._z_std, cd)[None, :]
            z = (jnp.clip(filled, lo, hi) - mean[None, :]) / std
            x_blocks.append(jnp.where(self._z_zero[None, :], 0.0, z))
        if len(self._t_idx):
            idx = jnp.clip(bins[:, self._t_idx], 0,
                           jnp.asarray(self._t_len)[None, :] - 1)
            tab = jnp.asarray(self._t_tab, cd)
            x_blocks.append(tab[jnp.arange(len(self._t_idx))[None, :],
                                idx])
        if len(self._t0_idx):
            x_blocks.append(jnp.zeros((n, len(self._t0_idx)), cd))
        if len(self._a_idx):
            v, ok = vals[:, self._a_idx], valid[:, self._a_idx]
            mean = jnp.asarray(self._a_mean, cd)
            x_blocks.append(jnp.where(ok, v, mean[None, :]))
        xm = x_blocks[0] if len(x_blocks) == 1 else \
            jnp.concatenate(x_blocks, axis=1)
        x = self._apply_precision(xm[:, self._x_inv], cd)
        return x.astype(jnp.float32), bins

    def _apply_device_cols(self, packed):
        """Per-column fallback (onehot plans: output widths vary)."""
        import jax
        import jax.numpy as jnp
        cd = jnp.float64 if self._x64 else jnp.float32
        c = len(self.plan)
        n = packed.shape[0]
        vals = packed[:, :c].astype(cd)
        valid = packed[:, c:2 * c] != 0
        cats = packed[:, 2 * c:3 * c].astype(jnp.int32)
        outs, bin_cols = [], []
        for j, p in enumerate(self.plan):
            v, ok = vals[:, j], valid[:, j]
            if p.categorical:
                bidx = cats[:, j]
            elif p.boundaries is not None:
                idx = jnp.searchsorted(jnp.asarray(p.boundaries, cd), v,
                                       side="right") - 1
                idx = jnp.clip(idx, 0, p.num_bins - 1)
                bidx = jnp.where(ok, idx, p.num_bins).astype(jnp.int32)
            else:
                bidx = jnp.where(ok, 0, 1).astype(jnp.int32)
            bin_cols.append(bidx)
            if p.mode == "onehot":
                idx = jnp.clip(bidx, 0, p.width - 1)
                outs.append(jax.nn.one_hot(idx, p.width, dtype=cd))
            elif p.mode == "table":
                if len(p.table) == 0:
                    outs.append(jnp.zeros((n, 1), cd))
                else:
                    tab = jnp.asarray(p.table, cd)
                    outs.append(tab[jnp.clip(bidx, 0, len(p.table) - 1)]
                                [:, None])
            elif p.mode == "asis":
                outs.append(jnp.where(ok, v, p.mean)[:, None])
            elif p.zero:
                outs.append(jnp.zeros((n, 1), cd))
            else:            # zscore: clip to host-precomputed bounds
                filled = jnp.where(ok, v, p.mean)
                z = (jnp.clip(filled, p.lo, p.hi) - p.mean) / p.std
                outs.append(z[:, None])
        x = jnp.concatenate(outs, axis=1)
        x = self._apply_precision(x, cd)
        return x.astype(jnp.float32), jnp.stack(bin_cols, axis=1)

    def _apply_precision(self, x, cd):
        """In-graph twin of ``ops.normalize.apply_precision``."""
        import jax.numpy as jnp
        if self.precision == PrecisionType.FLOAT7:
            return jnp.round(x, 7)
        if self.precision == PrecisionType.FLOAT16:
            return x.astype(jnp.float16).astype(cd)
        if self.precision == PrecisionType.FLOAT32:
            return x.astype(jnp.float32).astype(cd)
        return x


def _field_str(v) -> str:
    """A JSON field value as the string the offline CSV reader would have
    seen — the shared rule lives in :func:`data.reader.record_field_str`
    so the offline parity oracle stringifies identically."""
    from ..data.reader import record_field_str
    return record_field_str(v)
