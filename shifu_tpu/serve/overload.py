"""Overload-protection primitives shared by the serving plane.

The serving path degrades PREDICTABLY under stress instead of
congestion-collapsing (the reference system's 97%-quorum / iteration-
timeout philosophy applied to serving):

- **Coded fast-fail errors** — :class:`OverloadedError` (HTTP 429 with a
  drain-rate-derived ``Retry-After``) for submits rejected at the
  admission cap, :class:`DeadlineExceededError` (HTTP 504) for tickets
  whose deadline passed before their rows launched.  A shed request is
  ALWAYS answered with one of these, never silently dropped.
- **Admission cap** — ``-Dshifu.serve.maxQueueRows`` bounds the
  micro-batcher queue (0 = auto: :data:`AUTO_QUEUE_BUCKETS` x the top
  bucket rung — enough runway for a burst, small enough that queue wait
  cannot blow the deadline by itself).
- **Request deadlines** — ``-Dshifu.serve.requestDeadlineMs`` is the
  default per-request budget; the ``X-Shifu-Deadline-Ms`` header
  overrides per request and propagates router -> worker -> batcher.
- :class:`RetryBudget` — a token bucket capping router requeues at
  ``-Dshifu.serve.retryBudgetFrac`` of recent successes, so a dying
  fleet sheds retries instead of amplifying its own overload.
- :class:`CircuitBreaker` — per-replica consecutive-failure breaker
  (``-Dshifu.serve.breakerFailures``): open after N consecutive
  transport/5xx failures, half-open single probe after a cooldown,
  closed again on the first success.

Everything here is plain state-machine code with injectable time — the
serve tests drive every transition with a fake clock and zero sleeps.
"""

from __future__ import annotations

import threading
from typing import Optional

#: auto admission cap: this many top-bucket flushes of queue runway
AUTO_QUEUE_BUCKETS = 128

DEFAULT_RETRY_BUDGET_FRAC = 0.1
DEFAULT_BREAKER_FAILURES = 3
#: breaker cooldown before the half-open probe (seconds)
DEFAULT_BREAKER_COOLDOWN_S = 2.0
#: retry tokens a fresh budget starts with — the full cap, so a replica
#: death right after startup can be absorbed by healthy peers; sustained
#: failure still drains it and sheds (successes refill only ``frac`` each)
RETRY_BUDGET_INITIAL = 10.0
#: retry tokens never accumulate past this many
RETRY_BUDGET_CAP = 10.0


class OverloadedError(RuntimeError):
    """Coded admission rejection: the queue is at ``maxQueueRows`` (or a
    retry budget is exhausted).  Maps to HTTP 429 with a ``Retry-After``
    derived from the batcher's current drain rate."""

    code = "overloaded"

    def __init__(self, msg: str, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.retry_after_s = max(0.001, float(retry_after_s))


class DeadlineExceededError(RuntimeError):
    """Coded deadline shed: the request's deadline passed before its
    rows launched (or the client abandoned the ticket), so ``pump()``
    dropped it BEFORE pad/launch.  Maps to HTTP 504."""

    code = "deadline_exceeded"


# ------------------------------------------------------------- knob readers
def configured_max_queue_rows() -> int:
    """Admission cap (rows): property ``shifu.serve.maxQueueRows``;
    0 (the default) = auto, ``AUTO_QUEUE_BUCKETS`` x the top rung."""
    from ..config import environment
    return max(0, environment.get_int("shifu.serve.maxQueueRows", 0))


def configured_deadline_s() -> float:
    """Default per-request deadline (seconds): property
    ``shifu.serve.requestDeadlineMs``; 0 (the default) = no deadline."""
    from ..config import environment
    return max(0.0, environment.get_float(
        "shifu.serve.requestDeadlineMs", 0.0)) / 1000.0


def configured_retry_budget_frac() -> float:
    """Router retry allowance per recent success: property
    ``shifu.serve.retryBudgetFrac`` (default 0.1; 0 = no retries)."""
    from ..config import environment
    return max(0.0, environment.get_float("shifu.serve.retryBudgetFrac",
                                          DEFAULT_RETRY_BUDGET_FRAC))


def configured_hedge_s() -> float:
    """Hedged-dispatch floor/fallback delay (seconds): property
    ``shifu.serve.hedgeMs``; 0 (the default) = hedging off.  When the
    router's latency tracker has data, the ACTUAL delay is its observed
    p99 (never below this floor) — the knob both arms hedging and keeps
    a cold tracker from hedging instantly."""
    from ..config import environment
    return max(0.0, environment.get_float("shifu.serve.hedgeMs",
                                          0.0)) / 1000.0


def configured_breaker_failures() -> int:
    """Consecutive transport/5xx failures that open a replica's breaker:
    property ``shifu.serve.breakerFailures`` (default 3; 0 = off)."""
    from ..config import environment
    return max(0, environment.get_int("shifu.serve.breakerFailures",
                                      DEFAULT_BREAKER_FAILURES))


def configured_brownout_enabled() -> bool:
    """Brownout degradation switch: property ``shifu.serve.brownout``
    (default true)."""
    from ..config import environment
    return environment.get_bool("shifu.serve.brownout", True)


# ------------------------------------------------------------- retry budget
class RetryBudget:
    """Token bucket bounding retries to a fraction of recent successes.

    Each success deposits ``frac`` of a token (capped); each retry
    spends one whole token.  Under total backend failure the budget
    drains after ``RETRY_BUDGET_INITIAL`` + accrued retries and further
    requests fast-fail as :class:`OverloadedError` instead of hammering
    dead replicas — retry *amplification* is the collapse mechanism this
    caps."""

    def __init__(self, frac: Optional[float] = None,
                 initial: float = RETRY_BUDGET_INITIAL,
                 cap: float = RETRY_BUDGET_CAP):
        self.frac = configured_retry_budget_frac() if frac is None \
            else max(0.0, float(frac))
        self.cap = float(cap)
        self._tokens = min(float(initial), self.cap) if self.frac > 0 \
            else 0.0
        self._lock = threading.Lock()

    @property
    def tokens(self) -> float:
        return self._tokens

    def on_success(self) -> None:
        with self._lock:
            self._tokens = min(self.cap, self._tokens + self.frac)

    def try_retry(self) -> bool:
        """Spend one token; False = budget exhausted, shed the retry."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


# ----------------------------------------------------------- circuit breaker
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-replica consecutive-failure breaker (see module docs).

    ``allow(now)`` gates dispatch: CLOSED always allows; OPEN refuses
    until ``cooldown_s`` has passed, then flips HALF_OPEN and allows
    exactly ONE probe; the probe's outcome closes (success) or re-opens
    (failure) the breaker.  ``threshold`` 0 disables the breaker (always
    allows, never opens)."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S):
        self.threshold = configured_breaker_failures() \
            if threshold is None else max(0, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self.state = CLOSED
        self.failures = 0
        self.opens = 0                    # lifetime open transitions
        self._open_until = 0.0
        self._probing = False
        self._lock = threading.Lock()

    def allow(self, now: float) -> bool:
        if self.threshold <= 0:
            return True
        with self._lock:
            if self.state == CLOSED:
                return True
            if self.state == OPEN:
                if now < self._open_until:
                    return False
                self.state = HALF_OPEN
                self._probing = True
                return True               # the single half-open probe
            # HALF_OPEN: one probe outstanding at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        if self.threshold <= 0:
            return
        with self._lock:
            self.state = CLOSED
            self.failures = 0
            self._probing = False

    def record_failure(self, now: float) -> bool:
        """One transport/5xx failure; True when this one OPENED the
        breaker (the ``serve.fleet_breaker_opens`` edge)."""
        if self.threshold <= 0:
            return False
        with self._lock:
            if self.state == HALF_OPEN:   # failed probe: straight back
                self.state = OPEN
                self.opens += 1
                self._open_until = now + self.cooldown_s
                self._probing = False
                return True
            self.failures += 1
            if self.state == CLOSED and self.failures >= self.threshold:
                self.state = OPEN
                self.opens += 1
                self._open_until = now + self.cooldown_s
                return True
            return False
