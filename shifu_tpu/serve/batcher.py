"""Micro-batching front-end: queue, deadline flush, padded-bucket launch.

Requests (single rows or bursts of rows) append to a queue; a worker
drains it into the smallest covering bucket of the ladder, pads the
remainder (counted — padding waste is a first-class bench metric), and
launches the AOT executable.  Flush fires when a full top bucket is
queued (throughput wins at high load) or when the oldest queued request
has waited ``max_delay_s`` (p99 stays bounded at low load).

Wall-clock is injectable (``clock=``) and the drain path is callable
in-process (:meth:`pump`), so unit tests drive deadline semantics with
a fake clock and zero sleeps; only the real server starts the worker
thread (:meth:`start`).

Fault site: ``serve:request=<batch#>`` fires before batch ``<batch#>``'s
device launch — an ``ioerror`` there fails exactly that batch's tickets
(the error propagates to the waiting callers) and must leave the scorer
and registry fully serviceable for the next request.
``serve:admit=<shed#>`` fires while the <shed#>-th submit is being
rejected at the admission cap — the die-during-shed drill.

Overload protection (:mod:`shifu_tpu.serve.overload`): admission is
BOUNDED — ``-Dshifu.serve.maxQueueRows`` (0 = auto, 128x the top rung)
caps queued rows, and a submit that would exceed it fast-fails with a
coded :class:`OverloadedError` carrying a ``Retry-After`` derived from
the drain-rate EWMA the launch path maintains.  Requests carry a
DEADLINE (``deadline_ms=`` / ``-Dshifu.serve.requestDeadlineMs``,
measured from the ideal arrival stamp); :meth:`pump` sheds tickets
whose deadline already passed — and tickets the client abandoned via a
:meth:`Ticket.wait` timeout — BEFORE pad/launch, so dead work never
reaches the device and the shed caller gets a coded
:class:`DeadlineExceededError`, never a silently-dropped result.

Per-request tracing (head-sampled, ``-Dshifu.serve.traceSampleRate``,
default 0 = off): a sampled request carries a trace id from submit
through batch assembly into the device launch and decomposes into
queue-wait (submit -> taken off the queue; ``deadline_wait_s`` marks the
part attributable to the deadline coalescing window), pad (burst
concatenate + the scorer's pad copy), launch (argument prep + host
fetch) and device (the executable call) — segments that sum, within
scheduler noise, to the request's end-to-end latency.  Each sampled
batch emits a ``serve.batch`` span linking its member requests' trace
ids (fan-in causality); both land on the ``shifu-serve`` timeline track
via :func:`shifu_tpu.obs.record_span`.  With sampling off the hot path
pays ONE float compare per submit and nothing per batch, matching the
PR 1/8 zero-cost convention; an explicit ``trace_id`` (the
``X-Shifu-Trace`` header) forces sampling for that request.

Score logging (the quality plane's feed): when the server wires a
:class:`shifu_tpu.obs.scorelog.ScoreLog` onto ``self.scorelog``, every
completed launch offers its per-request mean scores to the log's own
head sampler, keyed by the request id carried on the ticket
(``req_id=``, the ``X-Shifu-Request`` header).  ``scorelog`` defaults to
``None`` — one ``is not None`` check per launch, nothing per submit.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import faults, obs
from .overload import (AUTO_QUEUE_BUCKETS, DeadlineExceededError,
                       OverloadedError, configured_deadline_s,
                       configured_max_queue_rows)
from .scorer import AOTScorer, covering_bucket, refine_ladder

log = logging.getLogger(__name__)


def configured_trace_sample_rate() -> float:
    """Head-sampling probability for per-request tracing: property
    ``shifu.serve.traceSampleRate`` in [0, 1], default 0 (off)."""
    from ..config import environment
    rate = environment.get_float("shifu.serve.traceSampleRate", 0.0)
    return min(max(rate, 0.0), 1.0)


def configured_refine_every() -> int:
    """Batches between occupancy-driven ladder refinements (property
    ``shifu.serve.bucketRefineEvery``; 0 disables).  Default 512: often
    enough to adapt to a load shift within seconds at serving rates,
    rare enough that the (background, ahead-of-use) compiles are
    noise."""
    from ..config import environment
    return max(0, environment.get_int("shifu.serve.bucketRefineEvery",
                                      512))


def _mint_trace_id() -> str:
    return os.urandom(8).hex()


class _ReqTrace:
    """Per-sampled-request trace state carried on the ticket: the trace
    id, submit timestamps, and the latency decomposition accumulated as
    the request's rows move through one or more batches."""

    __slots__ = ("trace_id", "ts", "t0", "taken", "queue_wait_s",
                 "deadline_wait_s", "pad_s", "launch_s", "device_s",
                 "batches", "flushes")

    def __init__(self, trace_id: str):
        self.trace_id = trace_id
        self.ts = time.time()                 # wall clock (span ts)
        self.t0 = time.perf_counter()         # duration basis
        self.taken = False
        self.queue_wait_s = 0.0
        self.deadline_wait_s = 0.0
        self.pad_s = 0.0
        self.launch_s = 0.0
        self.device_s = 0.0
        self.batches = 0
        self.flushes: List[str] = []


class Ticket:
    """Completion handle for one submitted burst of rows.  A burst may
    span several device launches; the event fires when every row has a
    score (or its batch errored).  One event per BURST, not per row —
    the per-request cost at high load is an array append."""

    __slots__ = ("n", "stamps", "scores", "done_ts", "_pending", "_event",
                 "error", "_lock", "trace", "req", "deadline", "cancelled")

    def __init__(self, n: int, stamps: np.ndarray,
                 trace: Optional[_ReqTrace] = None,
                 req: Optional[str] = None,
                 deadline: Optional[float] = None):
        self.n = n
        self.stamps = stamps                  # arrival time per row
        self.scores = np.empty(n, np.float32)
        self.done_ts = np.empty(n, np.float64)
        self._pending = n
        self._event = threading.Event()
        self._lock = threading.Lock()
        self.error: Optional[BaseException] = None
        self.trace = trace                    # sampled requests only
        self.req = req                        # score-log join id
        self.deadline = deadline              # absolute batcher-clock time
        self.cancelled = False                # client abandoned the wait

    def _complete(self, sl: slice, scores: Optional[np.ndarray],
                  now: float, error: Optional[BaseException]) -> None:
        if error is None:
            self.scores[sl] = scores
        else:
            self.error = error
        self.done_ts[sl] = now
        with self._lock:
            self._pending -= sl.stop - sl.start
            done = self._pending <= 0
        if done:
            self._event.set()

    def cancel(self) -> None:
        """Mark the ticket abandoned: ``pump()`` sheds its still-queued
        rows through the expired-ticket path instead of scoring work
        whose result nobody will read (counted as ``serve.cancelled``)."""
        self.cancelled = True

    def wait(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until every row is scored; raises the batch error if
        the request died with its batch.  A timeout CANCELS the ticket —
        the client is gone, so its queued rows shed instead of being
        scored into the void."""
        if not self._event.wait(timeout):
            self.cancel()
            raise TimeoutError("scoring request timed out")
        if self.error is not None:
            raise self.error
        return self.scores

    def done(self) -> bool:
        return self._event.is_set()

    def latencies(self) -> np.ndarray:
        """Per-row completion latency (seconds) — open-loop clients
        stamp ideal arrival times, so these are coordination-free."""
        return self.done_ts - self.stamps


class MicroBatcher:
    """See module docs.  ``scorer_provider`` is read once per flush, so
    a registry hot-swap takes effect at the next batch boundary without
    dropping queued requests."""

    def __init__(self, scorer_provider: Callable[[], AOTScorer],
                 max_delay_s: float = 0.002,
                 clock: Callable[[], float] = time.monotonic,
                 trace_sample_rate: Optional[float] = None,
                 slo=None):
        self._provider = scorer_provider
        self.max_delay_s = float(max_delay_s)
        self.clock = clock
        # head-sampled request tracing (property default) + optional SLO
        # tracker (obs/slo) fed per-row latencies at each completion
        self.trace_sample_rate = trace_sample_rate \
            if trace_sample_rate is not None \
            else configured_trace_sample_rate()
        self.slo = slo
        self._trace_rng = random.Random(0x51F0)
        self._cond = threading.Condition()
        # queue of (ticket, rows, bins, row_offset, raw): row_offset = how
        # many of this burst's rows earlier flushes already consumed; raw
        # marks packed raw-record bursts (serve/transform.py wire format)
        # — a launch never mixes raw and pre-binned rows, the two ride
        # different executables
        self._queue: deque = deque()
        self._queued_rows = 0
        self._batches = 0
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        # overload protection: bounded admission (0 = auto at submit
        # time, AUTO_QUEUE_BUCKETS x the top rung) + default deadline
        # (0 = none) + the drain-rate EWMA behind Retry-After
        self.max_queue_rows = configured_max_queue_rows()
        self.default_deadline_s = configured_deadline_s()
        self._drain_rate = 0.0            # rows/s EWMA across launches
        self._last_launch_t: Optional[float] = None
        # telemetry-independent accounting (the bench reads this; the
        # same numbers mirror into obs counters when telemetry is on)
        self.stats: Dict[str, float] = {
            "requests": 0, "rows": 0, "batches": 0, "rows_padded": 0,
            "flush_full": 0, "flush_deadline": 0, "errors": 0,
            "shed_overload": 0, "shed_expired": 0, "cancelled": 0}
        self.bucket_counts: Dict[int, int] = {}
        # real batch row-counts (rows -> batches): the occupancy-driven
        # ladder refinement's evidence (refine_ladder); keys are bounded
        # by the top rung
        self.size_counts: Dict[int, int] = {}
        self.refine_every = configured_refine_every()
        self._refining = False
        # sampled score logging (obs/scorelog), wired by the server when
        # -Dshifu.scorelog.sampleRate > 0; None keeps the launch path to
        # one is-not-None check
        self.scorelog = None

    # ------------------------------------------------------------ submit
    def submit(self, row: np.ndarray, bins: Optional[np.ndarray] = None,
               stamp: Optional[float] = None,
               trace_id: Optional[str] = None,
               req_id: Optional[str] = None,
               deadline_ms: Optional[float] = None) -> Ticket:
        """One single-record scoring request."""
        return self.submit_burst(
            np.asarray(row, np.float32)[None, :],
            None if bins is None else np.asarray(bins)[None, :],
            stamps=None if stamp is None else np.asarray([stamp]),
            trace_id=trace_id, req_id=req_id, deadline_ms=deadline_ms)

    def submit_burst(self, rows: np.ndarray,
                     bins: Optional[np.ndarray] = None,
                     stamps: Optional[np.ndarray] = None,
                     trace_id: Optional[str] = None,
                     req_id: Optional[str] = None,
                     raw: bool = False,
                     deadline_ms: Optional[float] = None) -> Ticket:
        """A burst of concurrent single-record requests (an open-loop
        load generator's arrivals for one tick) — one queue append, one
        shared ticket.  ``stamps`` lets the generator record IDEAL
        arrival times so latency percentiles are free of coordinated
        omission.  ``trace_id`` (a propagated ``X-Shifu-Trace`` header)
        forces request tracing for this burst; otherwise the burst is
        head-sampled at ``trace_sample_rate`` (minting an id).
        ``req_id`` (the ``X-Shifu-Request`` header) is the score log's
        delayed-outcome join key for this burst.  ``raw=True`` marks
        ``rows`` as PACKED raw-record wire rows (``serve/transform.py``)
        — they flush through the fused transform+score executable and
        never share a launch with pre-binned rows.  ``deadline_ms``
        (the ``X-Shifu-Deadline-Ms`` header; default the
        ``requestDeadlineMs`` property, 0 = none) is the request's
        budget measured from its ideal arrival stamp — an expired
        ticket sheds in :meth:`pump` with a coded error.

        Raises :class:`OverloadedError` (coded 429 + Retry-After) when
        the queue is at the admission cap — a burst larger than the cap
        is still admitted into an EMPTY queue, so oversized requests
        stay serviceable."""
        n = len(rows)
        if stamps is None:
            stamps = np.full(n, self.clock())
        st = np.asarray(stamps, np.float64)
        dl_s = (self.default_deadline_s if deadline_ms is None
                else max(0.0, float(deadline_ms)) / 1000.0)
        deadline = float(st.min()) + dl_s if dl_s > 0.0 else None
        trace = None
        if trace_id is not None or (
                self.trace_sample_rate > 0.0 and obs.enabled()
                and self._trace_rng.random() < self.trace_sample_rate):
            trace = _ReqTrace(trace_id or _mint_trace_id())
            obs.counter("serve.trace_sampled").inc()
        t = Ticket(n, st, trace=trace, req=req_id, deadline=deadline)
        shed_no = None
        with self._cond:
            if self._stop:
                raise RuntimeError("batcher is stopped")
            cap = self.max_queue_rows \
                or AUTO_QUEUE_BUCKETS * self._top_bucket()
            if self._queued_rows and self._queued_rows + n > cap:
                self.stats["shed_overload"] += 1
                shed_no = int(self.stats["shed_overload"])
                retry_after = self._retry_after_s()
            else:
                self._queue.append((t, rows, bins, 0, raw))
                self._queued_rows += n
                # one accepted request per submit call; row volume is
                # the separate "rows" / serve.rows_scored accounting
                self.stats["requests"] += 1
                self._cond.notify_all()
        if shed_no is not None:
            obs.counter("serve.shed_overload").inc()
            if self.slo is not None:
                self.slo.record_shed()
            # the die-during-shed drill: an ioerror here surfaces
            # INSTEAD of the coded rejection and must leave the queue
            # depth and SLO shed accounting exactly as recorded above
            faults.fire("serve", "admit", shed_no)
            raise OverloadedError(
                f"queue at admission cap ({cap} rows); retry in "
                f"{retry_after:.3f}s", retry_after_s=retry_after)
        obs.counter("serve.requests").inc()
        return t

    def _retry_after_s(self) -> float:
        """Time for the drain-rate EWMA to absorb the current queue —
        the 429 Retry-After hint.  Caller holds the lock."""
        if self._drain_rate > 0.0:
            est = self._queued_rows / self._drain_rate
        else:
            est = max(self.max_delay_s * 2.0, 0.01)
        return min(max(est, 0.001), 30.0)

    def score_sync(self, rows: np.ndarray,
                   bins: Optional[np.ndarray] = None,
                   timeout: Optional[float] = 30.0) -> np.ndarray:
        """Closed-loop convenience: submit + wait."""
        return self.submit_burst(np.asarray(rows, np.float32),
                                 bins).wait(timeout)

    @property
    def queue_depth(self) -> int:
        """Rows currently queued (sampled into SERVE heartbeats /
        ``/healthz`` so the monitor can flag buildup before the deadline
        blows)."""
        return self._queued_rows

    # ------------------------------------------------------------- drain
    def _top_bucket(self) -> int:
        return self._provider().buckets[-1]

    def _oldest_stamp(self) -> Optional[float]:
        return float(self._queue[0][0].stamps[self._queue[0][3]]) \
            if self._queue else None

    def _take(self, max_rows: int, now: Optional[float] = None
              ) -> Tuple[List[Tuple[Ticket, np.ndarray,
                                    Optional[np.ndarray], int, bool]],
                         List[Tuple[Ticket, int, int]]]:
        """Pop up to ``max_rows`` rows off the queue head (splitting a
        burst when it straddles the boundary).  Stops at a raw/pre-binned
        kind boundary — one launch, one executable family.  Expired or
        client-cancelled tickets met on the way are SHED, not taken —
        returned as ``(ticket, offset, remaining_rows)`` so the caller
        can complete them with a coded error OUTSIDE the lock, before
        any pad/launch work is spent on them.  Caller holds the lock."""
        out, shed, taken = [], [], 0
        kind: Optional[bool] = None
        while self._queue and taken < max_rows:
            t, rows, bins, off, raw = self._queue[0]
            if t.cancelled or (now is not None and t.deadline is not None
                               and t.deadline <= now):
                self._queue.popleft()
                remaining = len(rows) - off
                self._queued_rows -= remaining
                shed.append((t, off, remaining))
                key = "cancelled" if t.cancelled else "shed_expired"
                self.stats[key] += 1
                continue
            if kind is None:
                kind = raw
            elif raw != kind:
                break
            self._queue.popleft()
            room = max_rows - taken
            avail = len(rows) - off
            take = min(room, avail)
            out.append((t, rows[off:off + take],
                        None if bins is None else bins[off:off + take],
                        off, raw))
            taken += take
            if take < avail:
                self._queue.appendleft((t, rows, bins, off + take, raw))
        self._queued_rows -= taken
        return out, shed

    def pump(self, now: Optional[float] = None, force: bool = False) -> int:
        """In-process drain: flush ONE batch if a flush condition holds
        (full top bucket queued, or the oldest request's deadline has
        passed, or ``force``).  Returns rows flushed (0 = no flush due).
        This is the testable core the worker thread loops around."""
        now = self.clock() if now is None else now
        with self._cond:
            if not self._queue:
                return 0
            full = self._queued_rows >= self._top_bucket()
            deadline_hit = now - self._oldest_stamp() >= self.max_delay_s
            if not (full or deadline_hit or force):
                return 0
            parts, shed = self._take(self._top_bucket(), now=now)
            if parts:
                self.stats["flush_full" if full else "flush_deadline"] += 1
            obs.gauge("serve.queue_depth").set(self._queued_rows)
        if shed:
            # coded fast-fail BEFORE pad/launch: the device never sees
            # expired/abandoned work, the client never sees silence
            n_cancelled = sum(1 for t, _, _ in shed if t.cancelled)
            if n_cancelled:
                obs.counter("serve.cancelled").inc(n_cancelled)
            if len(shed) > n_cancelled:
                obs.counter("serve.shed_expired").inc(
                    len(shed) - n_cancelled)
            if self.slo is not None:
                self.slo.record_shed(len(shed))
            err = DeadlineExceededError(
                "request deadline passed before its rows launched")
            for t, off, remaining in shed:
                t._complete(slice(off, off + remaining), None, now, err)
        if not parts:
            return 0
        if full:
            obs.counter("serve.flush_full").inc()
        else:
            obs.counter("serve.flush_deadline").inc()
        return self._launch(parts, reason="full" if full
                            else ("deadline" if deadline_hit else "forced"))

    def drain(self, timeout: float = 30.0) -> None:
        """Flush everything queued right now (shutdown / tests)."""
        deadline = time.monotonic() + timeout
        while True:
            with self._cond:
                if not self._queue:
                    return
            if self.pump(force=True) == 0 and time.monotonic() > deadline:
                raise TimeoutError("batcher drain timed out")

    # ------------------------------------------------------------ launch
    def _launch(self, parts, reason: str = "forced") -> int:
        n = sum(len(rows) for _, rows, _, _, _ in parts)
        if n == 0:
            return 0
        raw_kind = parts[0][4]
        with self._cond:
            batch_index = self._batches
            self._batches += 1
        # sampled members (the common case is NONE: no perf counters, no
        # timing dict, no record emission — the batch path is unchanged)
        traced = [t for t, _, _, _, _ in parts if t.trace is not None]
        t_take = time.perf_counter() if traced else 0.0
        tm: Optional[Dict[str, float]] = \
            {"pad_s": 0.0, "launch_s": 0.0, "device_s": 0.0} if traced \
            else None
        err: Optional[BaseException] = None
        mean = None
        bucket = n
        scorer = None
        # assembly stays INSIDE the try: mismatched row widths across
        # bursts, a missing bins array, or a provider failure must fail
        # this batch's tickets, not escape into the worker loop
        try:
            scorer = self._provider()
            bucket = covering_bucket(scorer.buckets, n)
            t_asm = time.perf_counter() if traced else 0.0
            rows = np.concatenate([r for _, r, _, _, _ in parts], axis=0) \
                if len(parts) > 1 else parts[0][1]
            bins = None
            if not raw_kind and scorer.needs_bins:
                bins = np.concatenate([b for _, _, b, _, _ in parts],
                                      axis=0) \
                    if len(parts) > 1 else parts[0][2]
            if tm is not None:
                tm["pad_s"] += time.perf_counter() - t_asm
            faults.fire("serve", "request", batch_index)
            if raw_kind:
                if not getattr(scorer, "accepts_raw", False):
                    raise ValueError("raw-record request but the live "
                                     "scorer has no fused transform")
                if tm is not None and getattr(scorer, "supports_timings",
                                              False):
                    raw = scorer.score_batch_raw(rows, timings=tm)
                else:
                    raw = scorer.score_batch_raw(rows)
            elif tm is not None and getattr(scorer, "supports_timings",
                                            False):
                raw = scorer.score_batch(rows, bins, timings=tm)
            else:
                raw = scorer.score_batch(rows, bins)
            mean = raw.mean(axis=1).astype(np.float32)
        except BaseException as e:          # noqa: BLE001 — tickets carry it
            err = e
        now = self.clock()
        now_pc = time.perf_counter() if traced else 0.0
        # SLO record BEFORE ticket completion: a caller unblocked by
        # _complete may read /slo immediately, and must see this batch's
        # latencies (guarded so a tracker fault can never hang tickets)
        if self.slo is not None:
            try:
                if err is not None:
                    self.slo.record_errors(n)
                else:
                    self.slo.observe_batch(np.concatenate(
                        [now - t.stamps[so:so + len(r)]
                         for t, r, _, so, _ in parts]))
            except Exception:               # noqa: BLE001
                log.exception("SLO record failed for batch")
        off = 0
        for t, r, _, src_off, _ in parts:
            sl_dst = slice(src_off, src_off + len(r))
            t._complete(sl_dst,
                        None if err is not None
                        else mean[off:off + len(r)], now, err)
            off += len(r)
        pad = bucket - n
        with self._cond:
            # drain-rate EWMA (rows/s across launch completions): the
            # admission path's Retry-After estimate
            if self._last_launch_t is not None:
                dt = now - self._last_launch_t
                if dt > 0:
                    inst = n / dt
                    self._drain_rate = inst if self._drain_rate == 0.0 \
                        else 0.7 * self._drain_rate + 0.3 * inst
            self._last_launch_t = now
            self.stats["batches"] += 1
            self.stats["rows"] += n
            self.stats["rows_padded"] += pad
            self.bucket_counts[bucket] = \
                self.bucket_counts.get(bucket, 0) + 1
            self.size_counts[n] = self.size_counts.get(n, 0) + 1
            batches_now = self.stats["batches"]
            if err is not None:
                self.stats["errors"] += 1
        obs.counter("serve.batches").inc()
        obs.counter("serve.rows_scored").inc(n)
        obs.counter("serve.rows_padded").inc(pad)
        # histogram, not gauge: a gauge only ever showed the LAST batch's
        # occupancy — the report now carries the p50/p99 of the whole
        # distribution (metrics.prom quantile lines, PR 10)
        obs.histogram("serve.bucket_occupancy").observe(n / bucket)
        if err is None and self.refine_every \
                and batches_now % self.refine_every == 0:
            self._maybe_refine(scorer)
        if self.scorelog is not None and err is None:
            lo = 0
            for t, r, b, _, _ in parts:
                self.scorelog.log(t.req, mean[lo:lo + len(r)], bins=b)
                lo += len(r)
        if traced:
            self._emit_trace_spans(parts, traced, batch_index, bucket, n,
                                   pad, reason, err, t_take, tm, now_pc)
        if err is not None:
            obs.counter("serve.request_errors").inc()
            if not isinstance(err, (faults.InjectedFault, ValueError,
                                    RuntimeError)):
                raise err
            return n
        oldest = min(float(t.stamps[so]) for t, _, _, so, _ in parts)
        obs.histogram("serve.batch_latency_ms").observe(
            (now - oldest) * 1000.0)
        return n

    def _maybe_refine(self, scorer) -> None:
        """Occupancy-driven ladder refinement (every ``refine_every``
        batches): propose tighter rungs from the observed batch-size
        distribution and grow the scorer's ladder on a BACKGROUND
        thread — each new rung compiles and warms before it is
        published, so the serving loop never waits on a compile and the
        zero-recompile contract holds.  Test doubles without
        ``extend_buckets`` are skipped."""
        if scorer is None or self._refining \
                or not hasattr(scorer, "extend_buckets"):
            return
        with self._cond:
            counts = dict(self.size_counts)
        refined = refine_ladder(scorer.buckets, counts)
        if tuple(refined) == tuple(sorted(scorer.buckets)):
            return
        self._refining = True

        def grow() -> None:
            try:
                scorer.extend_buckets(refined)
            except Exception:           # noqa: BLE001 — advisory path
                log.exception("bucket-ladder refinement failed; ladder "
                              "unchanged")
            finally:
                self._refining = False

        threading.Thread(target=grow, daemon=True,
                         name="shifu-serve-ladder").start()

    def _emit_trace_spans(self, parts, traced, batch_index: int,
                          bucket: int, n: int, pad: int, reason: str,
                          err: Optional[BaseException], t_take: float,
                          tm: Dict[str, float], now_pc: float) -> None:
        """Fold this batch's measured decomposition into its sampled
        members and emit the ``serve.batch`` span plus a
        ``serve.request`` span for every member that just COMPLETED
        (split bursts emit once, after their final batch)."""
        for t in traced:
            tr = t.trace
            if not tr.taken:
                tr.taken = True
                tr.queue_wait_s = max(t_take - tr.t0, 0.0)
                if reason == "deadline":
                    tr.deadline_wait_s = min(tr.queue_wait_s,
                                             self.max_delay_s)
            # every member rides the whole batch's pad/launch/device wall
            tr.pad_s += tm["pad_s"]
            tr.launch_s += tm["launch_s"]
            tr.device_s += tm["device_s"]
            tr.batches += 1
            tr.flushes.append(reason)
        batch_wall = now_pc - t_take
        obs.record_span(
            "serve.batch", ts=time.time() - batch_wall, dur_s=batch_wall,
            tid="shifu-serve",
            attrs={"batch": batch_index, "bucket": bucket, "rows": n,
                   "pad": pad, "flush": reason,
                   "links": [t.trace.trace_id for t in traced],
                   "pad_s": round(tm["pad_s"], 6),
                   "launch_s": round(tm["launch_s"], 6),
                   "device_s": round(tm["device_s"], 6),
                   **({"error": type(err).__name__} if err else {})})
        for t in traced:
            if not t.done():
                continue                     # more launches still due
            tr = t.trace
            obs.record_span(
                "serve.request", ts=tr.ts, dur_s=now_pc - tr.t0,
                tid="shifu-serve",
                attrs={"trace": tr.trace_id, "rows": t.n,
                       "batch": batch_index, "batches": tr.batches,
                       "flush": ",".join(tr.flushes),
                       "queue_wait_s": round(tr.queue_wait_s, 6),
                       "deadline_wait_s": round(tr.deadline_wait_s, 6),
                       "pad_s": round(tr.pad_s, 6),
                       "launch_s": round(tr.launch_s, 6),
                       "device_s": round(tr.device_s, 6),
                       "e2e_s": round(now_pc - tr.t0, 6),
                       **({"error": type(err).__name__} if err else {})})

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "MicroBatcher":
        self._stop = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shifu-serve-batcher")
        self._thread.start()
        return self

    def _run(self) -> None:
        while True:
            try:
                with self._cond:
                    while not self._queue and not self._stop:
                        self._cond.wait()
                    if self._stop and not self._queue:
                        return
                    # coalesce: wait for the top bucket to fill, but never
                    # past the oldest request's deadline
                    while (self._queued_rows < self._top_bucket()
                           and not self._stop):
                        remaining = (self._oldest_stamp() + self.max_delay_s
                                     - self.clock())
                        if remaining <= 0:
                            break
                        self._cond.wait(timeout=remaining)
                self.pump(force=True)
            except Exception:               # noqa: BLE001 — worker survives
                # the failed batch's tickets already carry the error (see
                # serve:request contract); the server must stay serviceable
                log.exception("serve batch failed; batcher continues")
                time.sleep(0.05)            # no hot loop on repeated failure

    def stop(self, drain: bool = True) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        if drain:
            self.drain()
