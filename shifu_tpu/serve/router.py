"""ServeRouter — N serve workers behind one health-/SLO-aware front.

One serving process is one accelerator's ceiling; the fleet goes
horizontal.  ``shifu-tpu serve --replicas N`` spawns N ordinary serve
workers (each its own process, registry, batcher and journal — the
worker code is untouched) on ephemeral ports and puts this thin HTTP
router in front:

- **Balancing**: requests go to the live replica with the fewest
  in-flight requests.  The router polls every replica's ``GET /healthz``
  (``-Dshifu.serve.fleetPollMs``) and DRAINS — stops dispatching to,
  keeps polling — any replica whose SLO tracker is alerting (the
  ``<< SLO BURN`` flag ``shifu-tpu monitor`` renders) or whose last
  successful poll is older than ``-Dshifu.serve.fleetStaleS``; a drained
  replica that recovers is returned to rotation.
- **Requeue on replica death**: scoring is stateless/idempotent, so a
  request whose connection dies mid-flight (the worker was SIGKILLed —
  the ``serve:replica`` fault site's drill) is requeued on a peer; every
  accepted request completes as long as one replica survives.
- **Overload resilience** (:mod:`shifu_tpu.serve.overload`): requeues
  spend a token-bucket RETRY BUDGET (``-Dshifu.serve.retryBudgetFrac``
  of recent successes) — an exhausted budget sheds the request with a
  coded 429 instead of amplifying a dying fleet's load; each replica
  carries a CIRCUIT BREAKER (``-Dshifu.serve.breakerFailures``
  consecutive transport/5xx failures open it, a half-open probe after a
  cooldown closes it) so dispatch stops hammering a sick backend before
  the health poll notices; with ``-Dshifu.serve.hedgeMs`` > 0 a request
  still unanswered after the router-observed p99 delay is HEDGED onto a
  second replica — first response wins, the loser is ignored (scoring
  is idempotent).  A caller deadline (``deadline_ms`` /
  ``X-Shifu-Deadline-Ms``) rides every dispatch to the worker so its
  batcher can shed expired work before pad/launch.
- **Connection reuse**: a small per-replica connection pool backs
  ``_http`` (health polls AND scoring); a transport error on a pooled
  connection recycles it and retries once on a fresh one, so a stale
  keep-alive socket never surfaces as a replica failure.
- **Coordinated hot-swap** (``POST /swap`` on the router): phase one
  PREPAREs the candidate on every replica (each builds + warms off-line,
  old model keeps serving), phase two pauses dispatch, waits for
  in-flight requests to finish, COMMITs every replica through its
  ModelRegistry journal, and resumes — no request is ever scored by a
  mixed-model fleet.  With ``-Dshifu.serve.canaryFrac`` > 0 only
  ``ceil(frac*N)`` replicas commit (the rest abort their candidates):
  an EXPLICIT canary slice — that fraction of balanced traffic scores
  on the candidate until a follow-up swap commits or rolls back.
- **Uniformity**: the router refuses to start a fleet whose replicas
  disagree on ``accepts_raw`` / ``needs_bins`` — a caller's request
  shape cannot depend on which replica it lands on.

Fleet SLO: each worker heartbeats its own SLO summary into the shared
health plane (proc ``serve-<key>-<replica>``), so
``shifu-tpu monitor --aggregate`` renders the merged per-replica
burn-rate view with no router involvement.
"""

from __future__ import annotations

import http.client
import json
import logging
import math
import os
import queue
import subprocess
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .. import obs
from .overload import (CircuitBreaker, OverloadedError, RetryBudget,
                       configured_hedge_s)

log = logging.getLogger(__name__)

DEFAULT_POLL_MS = 500.0
DEFAULT_STALE_S = 10.0
DEFAULT_CANARY_FRAC = 0.0

#: idle keep-alive connections pooled per replica
CONN_POOL_SIZE = 4

#: replica lifecycle: starting -> up <-> draining -> dead
STARTING, UP, DRAINING, DEAD = "starting", "up", "draining", "dead"


def fleet_poll_s(override_ms: Optional[float] = None) -> float:
    """Health-poll cadence: ``shifu.serve.fleetPollMs`` (default 500)."""
    if override_ms is None:
        from ..config import environment
        override_ms = environment.get_float("shifu.serve.fleetPollMs",
                                            DEFAULT_POLL_MS)
    return max(0.01, float(override_ms)) / 1000.0


def fleet_stale_s(override: Optional[float] = None) -> float:
    """Stale-heartbeat cutoff: a replica unreachable for longer is
    declared dead (``shifu.serve.fleetStaleS``, default 10)."""
    if override is not None:
        return max(0.1, float(override))
    from ..config import environment
    return max(0.1, environment.get_float("shifu.serve.fleetStaleS",
                                          DEFAULT_STALE_S))


def canary_frac(override: Optional[float] = None) -> float:
    """Coordinated-swap canary slice: commit only ``ceil(frac*N)``
    replicas (``shifu.serve.canaryFrac``, default 0 = commit all)."""
    if override is not None:
        return min(1.0, max(0.0, float(override)))
    from ..config import environment
    return min(1.0, max(0.0, environment.get_float(
        "shifu.serve.canaryFrac", DEFAULT_CANARY_FRAC)))


class Replica:
    """One backend worker as the router sees it."""

    def __init__(self, name: str, port: int, host: str = "127.0.0.1",
                 proc: Optional[subprocess.Popen] = None):
        self.name = name
        self.host = host
        self.port = int(port)
        self.proc = proc
        self.state = STARTING
        self.inflight = 0
        self.last_ok = 0.0
        self.accepts_raw: Optional[bool] = None
        self.needs_bins: Optional[bool] = None
        self.generation: Optional[int] = None
        self.requests = 0
        # per-replica circuit breaker (consecutive transport/5xx ->
        # open -> half-open probe) — replaces bury-on-first-error
        self.breaker = CircuitBreaker()
        # small keep-alive connection pool (health polls + scoring)
        self._conns: deque = deque()
        self._conn_lock = threading.Lock()

    def take_conn(self, timeout: float):
        """(connection, was_pooled): a pooled keep-alive connection when
        one is idle, else a fresh one."""
        with self._conn_lock:
            conn = self._conns.popleft() if self._conns else None
        if conn is not None:
            conn.timeout = timeout
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            return conn, True
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=timeout), False

    def put_conn(self, conn) -> None:
        with self._conn_lock:
            if len(self._conns) < CONN_POOL_SIZE:
                self._conns.append(conn)
                return
        conn.close()

    def drop_conns(self) -> None:
        """Close every pooled connection (replica died / shutdown)."""
        with self._conn_lock:
            conns, self._conns = list(self._conns), deque()
        for c in conns:
            c.close()

    def doc(self) -> dict:
        return {"name": self.name, "port": self.port, "state": self.state,
                "inflight": int(self.inflight),
                "requests": int(self.requests),
                "generation": self.generation,
                "breaker": self.breaker.state,
                "accepts_raw": self.accepts_raw,
                "needs_bins": self.needs_bins}


class ServeRouter:
    """See module docs.  In-process testable: ``add_backend`` +
    ``poll_once`` + ``score``/``coordinated_swap`` need no poll thread
    or subprocesses — any HTTP endpoint speaking the worker protocol
    (``/healthz``, ``/score``, ``/swap``) is a backend."""

    def __init__(self, poll_ms: Optional[float] = None,
                 stale_s: Optional[float] = None,
                 clock=time.monotonic):
        self.replicas: Dict[str, Replica] = {}
        self.clock = clock
        self.poll_s = fleet_poll_s(poll_ms)
        self.stale_s = fleet_stale_s(stale_s)
        # overload resilience: the fleet-wide retry budget, the hedge
        # floor (0 = off), and the router-side latency tracker whose
        # observed p99 sets the actual hedge delay
        self.retry_budget = RetryBudget()
        self._hedge_s = configured_hedge_s()
        self.latency = obs.SLOTracker(
            p99_ms=max(self._hedge_s * 1000.0, 1000.0), clock=clock)
        self._lock = threading.Lock()
        self._gate = threading.Event()      # cleared = dispatch paused
        self._gate.set()
        self._idle = threading.Condition(self._lock)  # inflight -> 0
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None
        self._swap_lock = threading.Lock()  # one coordinated swap at a time

    # -------------------------------------------------------------- fleet
    def add_backend(self, name: str, port: int, host: str = "127.0.0.1",
                    proc: Optional[subprocess.Popen] = None) -> Replica:
        r = Replica(name, port, host=host, proc=proc)
        with self._lock:
            self.replicas[name] = r
        return r

    def _http(self, r: Replica, method: str, path: str,
              doc: Optional[dict] = None, timeout: float = 30.0,
              headers: Optional[dict] = None) -> dict:
        """One HTTP exchange with a worker over its pooled keep-alive
        connection (a transport error on a POOLED connection recycles
        it and retries once fresh — a stale socket is not a replica
        failure).  Raises ``OSError`` for transport failures (the
        requeue trigger); a worker-side 5xx raises ``RuntimeError``
        (the request REACHED the worker, so it is not blindly
        requeued) — except 504, the worker's coded deadline shed, which
        passes through like 429 for the caller to see."""
        body = None if doc is None else json.dumps(doc).encode()
        hdrs = {"Content-Type": "application/json"} if body else {}
        hdrs.update(headers or {})
        conn, pooled = r.take_conn(timeout)
        resp = data = None
        for attempt in (0, 1):
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
                break
            except OSError:
                conn.close()
                if pooled and attempt == 0:
                    conn, pooled = http.client.HTTPConnection(
                        r.host, r.port, timeout=timeout), False
                    continue
                raise
        payload = json.loads(data or b"{}")
        if resp.will_close:
            conn.close()
        else:
            r.put_conn(conn)
        if resp.status >= 500 and resp.status != 504:
            raise RuntimeError(f"{r.name}{path} -> {resp.status}: "
                               f"{payload.get('error')}")
        payload["_status"] = resp.status
        return payload

    def poll_once(self) -> dict:
        """One health sweep: refresh every replica's state from its
        ``/healthz`` (drain on SLO burn, bury on stale/exited), update
        the fleet gauge, and return the merged fleet doc."""
        now = self.clock()
        for r in list(self.replicas.values()):
            if r.state == DEAD:
                continue
            try:
                hz = self._http(r, "GET", "/healthz", timeout=5.0)
                r.last_ok = now
                r.accepts_raw = bool(hz.get("accepts_raw"))
                r.needs_bins = bool(hz.get("needs_bins"))
                r.generation = hz.get("generation")
                burning = bool((hz.get("slo") or {}).get("alerting"))
                if burning and r.state != DRAINING:
                    log.warning("draining %s: SLO burn", r.name)
                    if obs.enabled():
                        obs.counter("serve.fleet_drains").inc()
                    r.state = DRAINING
                elif not burning:
                    r.state = UP
            except (OSError, ValueError, RuntimeError) as e:
                exited = r.proc is not None and r.proc.poll() is not None
                stale = r.last_ok and now - r.last_ok > self.stale_s
                never = not r.last_ok and r.state != STARTING
                if exited or stale or never:
                    if r.state != DEAD:
                        log.warning("replica %s dead (%s)", r.name, e)
                        if obs.enabled():
                            obs.counter("serve.fleet_drains").inc()
                    r.state = DEAD
                    r.drop_conns()
                elif r.state == UP:
                    log.warning("draining %s: unreachable (%s)", r.name, e)
                    if obs.enabled():
                        obs.counter("serve.fleet_drains").inc()
                    r.state = DRAINING
        up = sum(1 for r in self.replicas.values() if r.state == UP)
        obs.gauge("serve.fleet_replicas_up").set(up)
        return self.fleet_doc()

    def ensure_uniform(self) -> None:
        """Refuse a mixed fleet: every live replica must agree on
        ``accepts_raw`` and ``needs_bins`` — a request's shape cannot
        depend on which replica the balancer picks."""
        live = [r for r in self.replicas.values()
                if r.state in (UP, DRAINING) and r.accepts_raw is not None]
        for field in ("accepts_raw", "needs_bins"):
            vals = {bool(getattr(r, field)) for r in live}
            if len(vals) > 1:
                detail = ", ".join(f"{r.name}={getattr(r, field)}"
                                   for r in live)
                raise ValueError(
                    f"mixed fleet: replicas disagree on {field} "
                    f"({detail}) — refusing to serve")

    def fleet_doc(self) -> dict:
        reps = [r.doc() for r in self.replicas.values()]
        gens = {r["generation"] for r in reps
                if r["state"] in (UP, DRAINING)}
        return {"kind": "fleet",
                "replicas": reps,
                "up": sum(1 for r in reps if r["state"] == UP),
                "generations": sorted(g for g in gens if g is not None),
                "accepts_raw": all(r["accepts_raw"] for r in reps
                                   if r["state"] == UP) if reps else False}

    # ----------------------------------------------------------- dispatch
    def _pick(self, exclude: Optional[Replica] = None
              ) -> Optional[Replica]:
        """Least-inflight live replica whose circuit breaker allows
        dispatch (an open breaker hides the replica; a half-open one
        admits exactly the probe request).  ``exclude`` keeps a hedged
        second dispatch off the primary's replica."""
        now = self.clock()
        with self._lock:
            up = [r for r in self.replicas.values()
                  if r.state == UP and r is not exclude]
            up.sort(key=lambda x: (x.inflight, x.requests))
            for r in up:
                if r.breaker.allow(now):
                    r.inflight += 1
                    r.requests += 1
                    return r
            return None

    def _done(self, r: Replica) -> None:
        with self._idle:
            r.inflight = max(0, r.inflight - 1)
            if not self._total_inflight():
                self._idle.notify_all()

    def _total_inflight(self) -> int:
        return sum(r.inflight for r in self.replicas.values())

    def _dispatch(self, r: Replica, doc: dict, timeout: float,
                  headers: Optional[dict] = None) -> dict:
        """One replica dispatch with inflight + breaker bookkeeping.
        Transport errors and 5xx feed the breaker; the replica stays in
        rotation unless its process exited (the breaker — not instant
        burial — decides when to stop dispatching to a flaky one)."""
        t0 = self.clock()
        try:
            out = self._http(r, "POST", "/score", doc, timeout=timeout,
                             headers=headers)
            r.breaker.record_success()
            if out.get("_status", 200) < 400:
                self.latency.observe_batch([self.clock() - t0])
            out["replica"] = r.name
            return out
        except (OSError, RuntimeError) as e:
            if r.breaker.record_failure(self.clock()):
                log.warning("breaker OPEN for %s (%s)", r.name, e)
                if obs.enabled():
                    obs.counter("serve.fleet_breaker_opens").inc()
            if isinstance(e, OSError) and r.proc is not None \
                    and r.proc.poll() is not None:
                r.state = DEAD
                r.drop_conns()
            raise
        finally:
            self._done(r)

    def _hedge_delay_s(self) -> float:
        """The hedged-dispatch trigger delay: the router-observed p99
        when the latency tracker has data, never below the ``hedgeMs``
        floor; 0 = hedging off."""
        if self._hedge_s <= 0.0:
            return 0.0
        p99 = self.latency.quantile_ms(0.99)
        return self._hedge_s if p99 is None \
            else max(self._hedge_s, p99 / 1000.0)

    def _dispatch_hedged(self, r: Replica, doc: dict, timeout: float,
                         headers: Optional[dict] = None) -> dict:
        """Dispatch with tail-shaving: when the primary has not
        answered within the p99-derived hedge delay, fire the SAME
        request at a second replica — first response wins, the loser's
        answer is dropped (scoring is idempotent).  A first ERROR does
        not win: while another dispatch is still in flight, its answer
        gets the remaining budget."""
        delay = self._hedge_delay_s()
        if delay <= 0.0 or timeout <= delay:
            return self._dispatch(r, doc, timeout, headers)
        results: queue.Queue = queue.Queue()

        def run(rep: Replica) -> None:
            try:
                results.put(("ok", self._dispatch(rep, doc, timeout,
                                                  headers)))
            except BaseException as e:      # noqa: BLE001 — relayed
                results.put(("err", e))

        threading.Thread(target=run, args=(r,), daemon=True,
                         name="fleet-dispatch").start()
        launched = 1
        try:
            kind, val = results.get(timeout=delay)
        except queue.Empty:
            r2 = self._pick(exclude=r)
            if r2 is not None:
                launched = 2
                if obs.enabled():
                    obs.counter("serve.fleet_hedges").inc()
                threading.Thread(target=run, args=(r2,), daemon=True,
                                 name="fleet-hedge").start()
            kind, val = results.get(timeout=max(0.05, timeout))
        if kind == "err" and launched == 2:
            try:
                kind, val = results.get(timeout=max(0.05, timeout))
            except queue.Empty:
                pass                        # fall through to the error
        if kind == "err":
            raise val
        return val

    def score(self, doc: dict, timeout: float = 30.0,
              deadline_ms: Optional[float] = None) -> dict:
        """Route one ``POST /score`` body to the best live replica.
        A transport failure (replica died before replying) REQUEUES the
        request on a peer — scoring is idempotent, so the retry is safe
        — but each requeue spends the retry budget: exhausted, the
        request sheds with a coded 429 instead of amplifying overload.
        ``deadline_ms`` (the ``X-Shifu-Deadline-Ms`` header) bounds the
        whole attempt and propagates to the worker, shrinking, on every
        dispatch."""
        if deadline_ms is not None:
            timeout = min(timeout, max(0.001, float(deadline_ms) / 1000.0))
        deadline = self.clock() + timeout
        attempts = 0
        while True:
            # the swap gate: cleared while a coordinated commit runs
            self._gate.wait(timeout=max(0.0, deadline - self.clock()))
            if not self._gate.is_set():
                raise RuntimeError("timed out while a coordinated swap "
                                   "held the dispatch gate")
            r = self._pick()
            if r is None:
                with self._lock:
                    live = [x for x in self.replicas.values()
                            if x.state == UP]
                if live:
                    # replicas are live but every breaker refuses the
                    # dispatch: shed coded instead of spinning on the
                    # poller until the cooldown elapses
                    raise OverloadedError(
                        f"all {len(live)} live replica breaker(s) open",
                        retry_after_s=self.poll_s)
                if self.clock() >= deadline:
                    raise RuntimeError("no live replicas")
                self.poll_once()
                if not any(x.state in (UP, STARTING, DRAINING)
                           for x in self.replicas.values()):
                    raise RuntimeError("no live replicas")
                time.sleep(min(0.05, self.poll_s))
                continue
            left = max(0.1, deadline - self.clock())
            headers = None
            if deadline_ms is not None:
                headers = {"X-Shifu-Deadline-Ms":
                           f"{max(1.0, left * 1000.0):.1f}"}
            try:
                out = self._dispatch_hedged(r, doc, left, headers)
                if out.get("_status", 200) < 400:
                    self.retry_budget.on_success()
                return out
            except OSError as e:
                # transport death: the worker never answered — requeue
                attempts += 1
                if obs.enabled():
                    obs.counter("serve.fleet_requeues").inc()
                log.warning("requeue after %s failed (%s), attempt %d",
                            r.name, e, attempts)
                if self.clock() >= deadline:
                    raise RuntimeError(
                        f"request failed on {attempts} replica(s): {e}"
                        ) from e
                if not self.retry_budget.try_retry():
                    if obs.enabled():
                        obs.counter("serve.fleet_retry_denied").inc()
                    raise OverloadedError(
                        f"retry budget exhausted after {attempts} "
                        f"transport failure(s): {e}",
                        retry_after_s=self.poll_s) from e

    # --------------------------------------------------- coordinated swap
    def coordinated_swap(self, models_dir: str,
                         canary: Optional[float] = None,
                         timeout: float = 300.0) -> dict:
        """Fleet-wide hot-swap with NO mixed-model scoring window:

        1. PREPARE on every live replica (each builds + warms the
           candidate off-line; serving continues on the old model);
           any failure aborts every already-prepared replica and the
           old fleet keeps serving.  A DRAINING replica that no longer
           answers is buried (DEAD) and skipped instead — it serves
           nothing, so skipping it cannot create a mixed window —
           but a reachable DRAINING replica still swaps, so it rejoins
           on the NEW model when its SLO burn clears.
        2. PAUSE dispatch, wait for in-flight requests to finish.
        3. COMMIT every replica (``canaryFrac`` > 0: only the canary
           slice commits, the rest abort — an explicit mixed window).
        4. RESUME dispatch.
        """
        frac = canary_frac(canary)
        with self._swap_lock:
            self.poll_once()
            live = [r for r in self.replicas.values()
                    if r.state in (UP, DRAINING)]
            if not live:
                raise RuntimeError("coordinated swap with no live replicas")
            prepared: List[Replica] = []
            for r in live:
                try:
                    got = self._http(r, "POST", "/swap",
                                     {"phase": "prepare",
                                      "dir": models_dir}, timeout=timeout)
                    if got["_status"] != 200:
                        raise RuntimeError(
                            f"prepare on {r.name}: {got.get('error')}")
                    prepared.append(r)
                except (OSError, RuntimeError) as e:
                    if isinstance(e, OSError) and r.state == DRAINING:
                        # already out of dispatch and now unreachable:
                        # bury it and keep the fleet swap going
                        log.warning("swap skips %s: draining replica "
                                    "unreachable (%s)", r.name, e)
                        r.state = DEAD
                        continue
                    for p in prepared:
                        try:
                            self._http(p, "POST", "/swap",
                                       {"phase": "abort"}, timeout=30.0)
                        except (OSError, RuntimeError):
                            pass        # dead replica: nothing to abort
                    raise RuntimeError(
                        f"coordinated swap aborted: prepare failed on "
                        f"{r.name}: {e}") from e
            if not prepared:
                raise RuntimeError("coordinated swap: no replica "
                                   "survived the prepare phase")
            n_commit = len(prepared) if frac <= 0.0 \
                else min(len(prepared), max(1, math.ceil(frac
                                                         * len(prepared))))
            commit = prepared[:n_commit]
            abort = prepared[n_commit:]
            self._gate.clear()          # pause dispatch
            try:
                with self._idle:
                    deadline = self.clock() + timeout
                    while self._total_inflight():
                        left = deadline - self.clock()
                        if left <= 0:
                            raise RuntimeError(
                                "coordinated swap: in-flight requests "
                                "did not drain")
                        self._idle.wait(timeout=min(0.1, left))
                errors = {}
                for r in commit:
                    try:
                        self._http(r, "POST", "/swap",
                                   {"phase": "commit"}, timeout=timeout)
                    except (OSError, RuntimeError) as e:
                        # a replica dying mid-commit is buried, not a
                        # mixed window: it serves nothing until repolled
                        errors[r.name] = str(e)
                        r.state = DEAD
                for r in abort:
                    try:
                        self._http(r, "POST", "/swap", {"phase": "abort"},
                                   timeout=30.0)
                    except (OSError, RuntimeError) as e:
                        errors[r.name] = str(e)
                        r.state = DEAD
            finally:
                self._gate.set()        # resume dispatch
            obs.counter("serve.fleet_swaps").inc()
            self.poll_once()
            doc = {"kind": "fleet-swap",
                   "committed": [r.name for r in commit
                                 if r.name not in errors],
                   "canary": [r.name for r in commit] if abort else [],
                   "aborted": [r.name for r in abort],
                   **self.fleet_doc()}
            if errors:
                doc["errors"] = errors
            return doc

    # ---------------------------------------------------------- lifecycle
    def start_polling(self) -> None:
        if self._poll_thread is not None:
            return

        def loop():
            while not self._stop.wait(self.poll_s):
                try:
                    self.poll_once()
                except Exception:       # noqa: BLE001 — keep polling
                    log.exception("fleet poll failed")

        self._poll_thread = threading.Thread(target=loop, daemon=True,
                                             name="fleet-poll")
        self._poll_thread.start()

    def stop(self, kill_workers: bool = True) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=2.0)
            self._poll_thread = None
        for r in self.replicas.values():
            r.drop_conns()
        if kill_workers:
            for r in self.replicas.values():
                if r.proc is not None and r.proc.poll() is None:
                    r.proc.terminate()
            for r in self.replicas.values():
                if r.proc is not None:
                    try:
                        r.proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        r.proc.kill()


# ------------------------------------------------------------------ HTTP
def _make_router_handler(router: ServeRouter):
    from http.server import BaseHTTPRequestHandler

    class Handler(BaseHTTPRequestHandler):
        # HTTP/1.1 keep-alive: replies always carry Content-Length, so
        # clients (and the fleet's own pooled connections) can reuse
        # the socket across requests
        protocol_version = "HTTP/1.1"

        def _reply(self, code: int, doc: dict,
                   headers: Optional[dict] = None) -> None:
            body = json.dumps(doc).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):                      # noqa: N802 (stdlib API)
            if self.path in ("/healthz", "/health", "/status"):
                self._reply(200, router.fleet_doc())
            else:
                self._reply(404, {"error": f"unknown path {self.path}"})

        def do_POST(self):                     # noqa: N802
            try:
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/score":
                    hdr = self.headers.get("X-Shifu-Deadline-Ms")
                    out = router.score(
                        doc, deadline_ms=None if hdr is None
                        else float(hdr))
                    self._reply(out.pop("_status", 200), out)
                elif self.path == "/swap":
                    mdir = doc.get("dir") or doc.get("models_dir")
                    if not mdir:
                        raise ValueError('swap needs {"dir": ...}')
                    self._reply(200, router.coordinated_swap(
                        str(mdir), canary=doc.get("canary_frac")))
                else:
                    self._reply(404, {"error": f"unknown {self.path}"})
            except OverloadedError as e:       # coded fast-fail: the
                # retry budget shed this request, do not mask it as 500
                self._reply(429, {"error": e.code,
                                  "retry_after_ms":
                                      round(e.retry_after_s * 1000.0, 3)},
                            headers={"Retry-After":
                                     str(max(1, round(e.retry_after_s)))})
            except Exception as e:             # noqa: BLE001 — HTTP edge
                self._reply(500, {"error": str(e)})

        def log_message(self, fmt, *args):
            log.debug("router: " + fmt, *args)

    return Handler


def spawn_worker(model_set_dir: str, name: str, announce: str,
                 max_delay_ms: Optional[float] = None,
                 extra_env: Optional[dict] = None) -> subprocess.Popen:
    """One fleet worker: an ordinary ``shifu-tpu serve`` process on an
    ephemeral port that writes ``announce`` (port/pid JSON) once bound.
    ``-D`` properties set in THIS process are forwarded on the worker's
    command line so fleet knobs behave like single-process knobs."""
    from ..config import environment
    cmd = [sys.executable, "-m", "shifu_tpu.cli"]
    cmd += [f"-D{k}={v}" for k, v in
            sorted(environment.all_properties().items())]
    cmd += ["--dir", model_set_dir, "serve", "--port", "0",
            "--replica", name, "--announce", announce]
    if max_delay_ms is not None:
        cmd += ["--max-delay-ms", str(max_delay_ms)]
    env = dict(os.environ)
    env.update(extra_env or {})
    return subprocess.Popen(cmd, env=env)


def wait_for_announce(path: str, proc: subprocess.Popen,
                      timeout: float = 300.0) -> dict:
    """Block until the worker writes its announce file (compile+warm
    happens before the bind, so this can take a while on first start)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"fleet worker exited rc={proc.returncode} before "
                "announcing its port")
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    doc = json.load(f)
                if doc.get("port"):
                    return doc
            except (OSError, ValueError):
                pass                    # torn read: announce mid-write
        time.sleep(0.05)
    raise RuntimeError(f"fleet worker did not announce within {timeout}s")


def run_fleet(model_set_dir: str, replicas: int = 2, port: int = 8188,
              max_delay_ms: Optional[float] = None) -> int:
    """The ``shifu-tpu serve --replicas N`` entry: spawn N workers,
    wait for their announces, refuse a mixed fleet, then serve the
    routing front on ``port`` until interrupted."""
    fleet_dir = os.path.join(model_set_dir, "serving", "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    router = ServeRouter()
    try:
        procs = []
        for i in range(int(replicas)):
            name = f"r{i}"
            announce = os.path.join(fleet_dir, f"{name}.json")
            if os.path.exists(announce):
                os.unlink(announce)
            procs.append((name, announce,
                          spawn_worker(model_set_dir, name, announce,
                                       max_delay_ms=max_delay_ms)))
        for name, announce, proc in procs:
            doc = wait_for_announce(announce, proc)
            router.add_backend(name, doc["port"], proc=proc)
        router.poll_once()
        router.ensure_uniform()
        router.start_polling()
        from http.server import ThreadingHTTPServer
        httpd = ThreadingHTTPServer(("127.0.0.1", port),
                                    _make_router_handler(router))
        bound = httpd.server_address[1]
        fd = router.fleet_doc()
        print(f"shifu-tpu serve fleet: {len(procs)} replica(s) on "
              f"http://127.0.0.1:{bound} (up={fd['up']}, "
              f"accepts_raw={fd['accepts_raw']})")
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return 0
    finally:
        router.stop()
