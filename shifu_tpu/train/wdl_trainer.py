"""WDL trainer — reference ``WDLWorker``/``WDLMaster``/``WDLOutput``
(``core/dtrain/wdl/``, 5.7k LoC): the BSP gradient loop as jitted steps over
the dual data planes (normalized numerics + categorical bin indices).

Round-3 rebuild: WDL now runs the SAME shape as the NN trainer —
- bagging members stack on the ``ensemble`` mesh axis (one vmapped program,
  reference per-member YARN jobs ``WDLWorker.java:679-712``),
- rows shard over the ``data`` axis; gradient aggregation is XLA's psum,
- out-of-core mode streams both planes as zipped ShardStream windows with
  stateless hash sampling masks (the round-2 ``load_all`` + host minibatch
  loop is gone).
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..data.shards import Shards
from ..models import wdl as wdl_model
from ..parallel import mesh as meshlib
from .early_stop import WindowEarlyStop
from .nn_trainer import (TrainSettings, _ckpt_state, _ckpt_template,
                         _resume_epoch_target,
                         _restore_tracking, _stack, _to_host)
from .optimizers import (cast_tree, make_optimizer, mixed_apply,
                         mixed_init, resolve_precision)
from .sampling import member_masks

log = logging.getLogger(__name__)


@dataclass
class WDLResult:
    params: List[Any]
    train_errors: np.ndarray
    valid_errors: np.ndarray
    epochs_run: int
    history: List[Tuple[float, float]]


def plane_indices(schema: dict, column_configs) -> Tuple[List[int], List[int],
                                                         List[int], List[int]]:
    """Column index lists for the dual planes, derived from schema +
    ColumnConfig ONLY (no data read): numeric feature columns in the norm
    plane, categorical bin columns in the clean plane."""
    col_nums = schema["columnNums"]
    names = schema["outputNames"]
    by_num = {c.columnNum: c for c in column_configs}
    # map output features back to source columns by name prefix
    name_to_num = {by_num[cn].columnName: cn for cn in col_nums if cn in by_num}
    blocks: Dict[int, List[int]] = {}
    for i, n in enumerate(names):
        base = n
        if base not in name_to_num and "_" in base:
            stem, suf = base.rsplit("_", 1)
            if stem in name_to_num and suf.isdigit():
                base = stem
        cn = name_to_num.get(base)
        if cn is not None:
            blocks.setdefault(cn, []).append(i)

    num_feat_idx: List[int] = []
    num_col_nums: List[int] = []
    cat_col_idx: List[int] = []
    cat_col_nums: List[int] = []
    for j, cn in enumerate(col_nums):
        cc = by_num.get(cn)
        if cc is None:
            continue
        if cc.is_categorical():
            cat_col_idx.append(j)
            cat_col_nums.append(cn)
        else:
            num_feat_idx.extend(blocks.get(cn, []))
            num_col_nums.append(cn)
    return num_feat_idx, cat_col_idx, num_col_nums, cat_col_nums


def split_planes(x: np.ndarray, bins: np.ndarray, schema: dict,
                 column_configs) -> Tuple[np.ndarray, np.ndarray, List[int],
                                          List[int], List[int], List[int]]:
    """Split the materialized planes into (numeric features, categorical bin
    indices) by column type: numerics keep their normalized block, each
    categorical column contributes its bin index (embedding id)."""
    num_feat_idx, cat_col_idx, num_col_nums, cat_col_nums = \
        plane_indices(schema, column_configs)
    x_num = x[:, num_feat_idx] if num_feat_idx else np.zeros((len(x), 0),
                                                             np.float32)
    x_cat = bins[:, cat_col_idx] if cat_col_idx else np.zeros((len(x), 0),
                                                              np.int32)
    return x_num, x_cat, num_feat_idx, cat_col_idx, num_col_nums, cat_col_nums


# ------------------------------------------------------------ in-RAM mesh
def _pad_rows(arrays: List[np.ndarray], multiple: int,
              w_axis1: List[np.ndarray]) -> Tuple[List[np.ndarray],
                                                  List[np.ndarray]]:
    n = arrays[0].shape[0]
    extra = meshlib.pad_rows(n, multiple)
    if not extra:
        return arrays, w_axis1
    out = []
    for a in arrays:
        pad = np.zeros((extra,) + a.shape[1:], a.dtype)
        out.append(np.concatenate([a, pad]))
    out_w = [np.concatenate([w, np.zeros((w.shape[0], extra), w.dtype)],
                            axis=1) for w in w_axis1]
    return out, out_w


def train_wdl_ensemble(x_num, x_cat, y, w, spec: wdl_model.WDLModelSpec,
                       settings: TrainSettings, bags: int = 1,
                       valid_rate: float = 0.2,
                       sample_rate: float = 1.0, replacement: bool = False,
                       stratified: bool = False, up_sample_weight: float = 1.0,
                       mesh=None, progress=None,
                       shard: Optional[bool] = None) -> WDLResult:
    """B bagging members vmapped over the (ensemble, data) mesh — the NN
    trainer's SPMD shape with WDL's dual input planes.

    ``shard`` overrides ``shifu.wdl.shardTables``: True row-shards every
    embedding/wide table (and its optimizer moments) over the ``data``
    axis (see train/wdl_shard), False keeps them replicated, None lets
    the knob's auto gate decide from the table footprint."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from . import wdl_shard

    n = len(y)
    # hashed-ID columns fold into bucket space ONCE, on the raw bins
    # (spec.extra carries the plan; forward consumes bucket ids)
    x_cat = wdl_model.apply_hash_host(spec, np.asarray(x_cat, np.int32))
    train_w, valid_w = member_masks(
        n, bags, valid_rate=valid_rate, sample_rate=sample_rate,
        replacement=replacement, stratified=stratified,
        up_sample_weight=up_sample_weight, targets=y, seed=settings.seed)
    train_w = train_w * np.asarray(w, np.float32)[None, :]
    valid_w = valid_w * np.asarray(w, np.float32)[None, :]

    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=bags)
    data_size = mesh.shape["data"]
    bs = settings.batch_size
    if bs:
        bs = max(bs - bs % data_size, data_size)
    # one-time host shuffle so contiguous minibatches mix classes even when
    # the source shards are sorted/grouped (the per-epoch gather a full
    # permutation would need doesn't pay on the mesh; batch ORDER is
    # re-randomized per epoch below)
    perm = np.random.default_rng(settings.seed).permutation(n)
    # pad ONCE to the batch multiple (bs is a data_size multiple) so the
    # minibatch loop never drops the tail; padded rows carry zero weight
    (xn, xc, yv), (train_w, valid_w) = _pad_rows(
        [np.asarray(x_num, np.float32)[perm],
         np.asarray(x_cat, np.int32)[perm],
         np.asarray(y, np.float32)[perm]], bs or data_size,
        [train_w[:, perm], valid_w[:, perm]])

    key = jax.random.PRNGKey(settings.seed)
    keys = jax.random.split(key, bags)
    init_list = [wdl_model.init_params(k, spec) for k in keys]
    opt = make_optimizer(settings.optimizer, settings.learning_rate,
                         **settings.opt_kwargs)
    # precision ladder (shifu.train.precision) — same contract as the NN
    # trainer: bf16/mixed params train narrow, mixed keeps the f32
    # master in the optimizer state
    precision = resolve_precision(settings.precision)
    if precision != "f32":
        init_list = [cast_tree(p, jnp.bfloat16) for p in init_list]
    use_shard = wdl_shard.shard_enabled(spec, mesh, bags, precision,
                                        override=shard)
    plane = None
    if use_shard:
        # row-shard every embed/wide_cat table over the data axis BEFORE
        # opt.init so the optimizer moments inherit the padded shard shape
        # — no device ever materializes a full table (train/wdl_shard)
        plane = wdl_shard.WDLShardPlane(mesh, spec, bags)
        init_list = [plane.pad_params(m) for m in init_list]
    stacked = _stack(init_list)
    if precision == "mixed":
        opt_state = _stack([mixed_init(opt, p) for p in init_list])
    else:
        opt_state = _stack([opt.init(p) for p in init_list])

    sh_ens = NamedSharding(mesh, P("ensemble"))
    if use_shard:
        stacked, opt_state = plane.put(stacked, opt_state)
    else:
        stacked = jax.device_put(stacked, sh_ens)
        opt_state = jax.device_put(opt_state, sh_ens)
    xnd = jax.device_put(xn, NamedSharding(mesh, P("data", None)))
    xcd = jax.device_put(xc, NamedSharding(mesh, P("data", None)))
    yd = jax.device_put(yv, NamedSharding(mesh, P("data")))
    twd = jax.device_put(train_w, NamedSharding(mesh, P("ensemble", "data")))
    vwd = jax.device_put(valid_w, NamedSharding(mesh, P("ensemble", "data")))
    l2 = settings.l2

    fns = wdl_shard.build_inram_fns(plane, stacked, opt_state, opt,
                                    precision, l2) if use_shard else None
    if use_shard:
        extra = spec.extra or {}
        wdl_shard.record_shard_gauges(
            plane, precision, int(extra.get("hash_buckets", 0) or 0),
            len(extra.get("hashed_cols") or []))

    from functools import partial

    def member_update(params, ostate, xnb, xcb, yb, mw):
        # normalizer OUTSIDE the grad, L2 added analytically after — the
        # exact gradient the sharded plane computes, so the replicated and
        # sharded paths agree bitwise at any device count that keeps the
        # row reduction order (see train/wdl_shard module docstring)
        inv = 1.0 / jnp.maximum(mw.sum(), 1e-9)

        def data_loss(p):
            pr = wdl_model.forward(p, spec, xnb, xcb)
            per = wdl_model.per_row_bce(pr, yb[:, None])
            return (per * mw).sum() * inv

        loss, grads = jax.value_and_grad(data_loss)(params)
        if l2:
            grads = jax.tree_util.tree_map(
                jnp.add, grads, wdl_model.l2_grads(params, l2))
        if precision == "mixed":
            params, ostate = mixed_apply(opt, grads, ostate)
            return params, ostate, loss
        delta, ostate = opt.update(grads, ostate, params)
        # apply in the PARAM dtype (adam's f32 step counter would widen
        # a bf16 ladder's delta; no-op for f32 params)
        params = jax.tree_util.tree_map(
            lambda p, d: p + d.astype(p.dtype), params, delta)
        return params, ostate, loss

    # cost-attributed wdl-plane entry points (obs/costs): the utilization
    # report joins these against the TRAIN span wall-clock.  Data planes
    # travel as ARGUMENTS, never closures: a closed-over array becomes an
    # XLA constant the compiler may fold into differently-fused (last-ulp
    # different) programs — args keep both trainer paths on one lowering
    @partial(obs.costed_jit, "wdl.step")
    def step(stacked, opt_state, xnb, xcb, yb, tw):
        return jax.vmap(member_update, in_axes=(0, 0, None, None, None, 0))(
            stacked, opt_state, xnb, xcb, yb, tw)

    @partial(obs.costed_jit, "wdl.eval_errors")
    def eval_errors(stacked, tw, vw, xnd, xcd, yd):
        def one(params, mw):
            p = wdl_model.forward(params, spec, xnd, xcd)
            per = wdl_model.per_row_bce(p, yd[:, None])
            return (per * mw).sum() / jnp.maximum(mw.sum(), 1e-9)
        return jax.vmap(one)(stacked, tw), jax.vmap(one)(stacked, vw)

    n_padded = xnd.shape[0]        # already a bs (or data_size) multiple

    # batching happens INSIDE jit: dynamic_slice of the sharded arrays
    # compiles into the SPMD program — an EAGER lax.slice on sharded inputs
    # does ad-hoc device-to-device copies on the host backend, which the
    # XLA:CPU runtime intermittently aborts on (observed SIGABRT)
    def step_batch(stacked, opt_state, start, bs: int, xnd, xcd, yd, twd):
        xnb = jax.lax.dynamic_slice_in_dim(xnd, start, bs, axis=0)
        xcb = jax.lax.dynamic_slice_in_dim(xcd, start, bs, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(yd, start, bs, axis=0)
        twb = jax.lax.dynamic_slice_in_dim(twd, start, bs, axis=1)
        return jax.vmap(member_update, in_axes=(0, 0, None, None, None, 0))(
            stacked, opt_state, xnb, xcb, yb, twb)

    @partial(obs.costed_jit, "wdl.epoch_steps", static_argnames=("blen",))
    def epoch_steps(stacked, opt_state, starts, blen: int, xnd, xcd, yd,
                    twd):
        """One epoch's minibatch sweep as ONE executable (lax.scan over the
        permuted batch starts) — see nn_trainer.epoch_steps."""
        def body(carry, start):
            st, os_ = carry
            st, os_, _ = step_batch(st, os_, start, blen, xnd, xcd, yd, twd)
            return (st, os_), None
        (st, os_), _ = jax.lax.scan(body, (stacked, opt_state), starts)
        return st, os_

    if use_shard and bs and bs < n_padded:
        # the sharded epoch scan indexes PRE-BATCHED [nb, bs, ...] planes:
        # a dynamic row-slice of a data-sharded array is not device-local
        # inside shard_map, while batch-major layout keeps every minibatch
        # evenly split over the data axis
        nb = n_padded // bs
        xn3 = jax.device_put(xn.reshape(nb, bs, xn.shape[1]),
                             NamedSharding(mesh, P(None, "data", None)))
        xc3 = jax.device_put(xc.reshape(nb, bs, xc.shape[1]),
                             NamedSharding(mesh, P(None, "data", None)))
        y3 = jax.device_put(yv.reshape(nb, bs),
                            NamedSharding(mesh, P(None, "data")))
        tw3 = jax.device_put(train_w.reshape(bags, nb, bs),
                             NamedSharding(mesh, P("ensemble", None,
                                                   "data")))

    stops = [WindowEarlyStop(settings.early_stop_window) for _ in range(bags)]
    best_valid = np.full(bags, np.inf)
    best_train = np.full(bags, np.inf)
    best_params: List[Any] = [None] * bags
    history: List[Tuple[float, float]] = []
    epochs_run = 0
    tr = va = np.zeros(bags)
    order_rng = np.random.default_rng([settings.seed, 1])
    obs_on = obs.enabled()
    start_epoch = 0
    epochs_target = settings.epochs
    if settings.resume and settings.checkpoint_dir:
        from . import checkpoint as ckpt
        restored = ckpt.restore_state(
            settings.checkpoint_dir,
            _ckpt_template(stacked, opt_state, key, bags),
            expect_precision=precision)
        if restored is not None:
            start_epoch, state = restored
            if use_shard:
                # checkpoints persist the PADDED shard shapes; re-placing
                # through the plane restores the row-sharded layout so
                # resume is bit-exact against an uninterrupted run
                stacked, opt_state = plane.put(state[0], state[1])
            else:
                stacked = jax.device_put(state[0], sh_ens)
                opt_state = jax.device_put(state[1], sh_ens)
            _restore_tracking(state, best_valid, best_train, best_params,
                              stops)
            # replay the batch-order RNG stream up to the resume point so
            # the remaining epochs see the same permutations
            for _ in range(start_epoch):
                if bs and bs < n_padded:
                    order_rng.permutation(
                        np.arange(0, n_padded - bs + 1, bs).astype(np.int32))
            epochs_target = _resume_epoch_target(settings, start_epoch,
                                                 stops)
            log.info("resumed WDL trainer state at epoch %d (target %d)",
                     start_epoch, epochs_target)
            if settings.early_stop_window > 0 and \
                    all(s.since_best >= s.window_size for s in stops):
                start_epoch = epochs_target     # already early-stopped
    for epoch in range(start_epoch, epochs_target):
        ep_t0 = time.perf_counter()
        if bs and bs < n_padded:
            # rows were shuffled once; re-randomize the BATCH ORDER each
            # epoch (cheap host-side; no gather, no recompile)
            starts = order_rng.permutation(
                np.arange(0, n_padded - bs + 1, bs).astype(np.int32))
            if use_shard:
                stacked, opt_state = fns["epoch_steps"](
                    stacked, opt_state, xn3, xc3, y3, tw3,
                    jnp.asarray(starts // bs, jnp.int32))
            else:
                stacked, opt_state = epoch_steps(stacked, opt_state,
                                                 jnp.asarray(starts), bs,
                                                 xnd, xcd, yd, twd)
        elif use_shard:
            stacked, opt_state, _ = fns["step"](stacked, opt_state, xnd,
                                                xcd, yd, twd)
        else:
            stacked, opt_state, _ = step(stacked, opt_state, xnd, xcd, yd,
                                         twd)
        if use_shard:
            tr, va = fns["eval_errors"](stacked, twd, vwd, xnd, xcd, yd)
        else:
            tr, va = eval_errors(stacked, twd, vwd, xnd, xcd, yd)
        tr, va = np.asarray(jnp.stack([tr, va]))       # one fetch
        history.append((float(tr.mean()), float(va.mean())))
        epochs_run = epoch + 1
        if obs_on:
            if use_shard:
                wdl_shard.record_epoch_launches(
                    plane, n_padded,
                    (n_padded // bs) if bs and bs < n_padded else 1,
                    precision)
            dt = time.perf_counter() - ep_t0
            obs.counter("train.epochs").inc()
            obs.histogram("train.epoch_s").observe(dt)
            obs.gauge("train.valid_err").set(float(va.mean()))
            obs.event("epoch", trainer="wdl", epoch=epoch,
                      train_err=round(float(tr.mean()), 6),
                      valid_err=round(float(va.mean()), 6), rows=n,
                      rows_per_sec=round(n / max(dt, 1e-9), 1))
        improved = np.flatnonzero(va < best_valid)
        if improved.size:
            host = _to_host(stacked)
            for i in improved:
                best_valid[i], best_train[i] = va[i], tr[i]
                best_params[i] = jax.tree_util.tree_map(
                    lambda a: a[i].copy(), host)
        if progress:
            progress(epoch, float(tr.mean()), float(va.mean()))
        stop_now = False
        if settings.early_stop_window > 0:
            flags = [s.should_stop(float(v)) for s, v in zip(stops, va)]
            stop_now = all(flags)
        if settings.checkpoint_dir and settings.checkpoint_every and \
                ((epoch + 1) % settings.checkpoint_every == 0 or stop_now):
            from . import checkpoint as ckpt
            ckpt.save_state(settings.checkpoint_dir, epoch + 1,
                            _ckpt_state(stacked, opt_state, key,
                                        best_valid, best_train,
                                        best_params, stops),
                            precision=precision)
        if stop_now:
            obs.event("early_stop", trainer="wdl", epoch=epoch,
                      window=settings.early_stop_window)
            log.info("WDL early stop at epoch %d", epoch)
            break
    final = _to_host(stacked)
    for i in range(bags):
        if best_params[i] is None:
            best_params[i] = jax.tree_util.tree_map(lambda a: a[i], final)
            best_valid[i], best_train[i] = float(va[i]), float(tr[i])
    if use_shard:
        # tracking/checkpoints keep the PADDED shard shapes; the models
        # that leave the trainer are always true-cardinality
        best_params = [plane.unpad_params(m) for m in best_params]
    return WDLResult(params=best_params, train_errors=best_train,
                     valid_errors=best_valid, epochs_run=epochs_run,
                     history=history)


# ------------------------------------------------------------- streaming
class ZippedPlanes:
    """Zip the norm (x) and clean (bins) shard streams into joint windows —
    both planes were materialized by the norm step with identical row
    partitioning, asserted per window."""

    def __init__(self, norm_shards: Shards, clean_shards: Shards,
                 window_rows: int, remainder_multiple: int = 0):
        from ..data.streaming import ShardStream
        # both planes share one remainder ladder, so the zipped tail
        # windows agree on their (possibly sub-W) padded shape
        self.norm = ShardStream(norm_shards, ("x", "y", "w"), window_rows,
                                remainder_multiple=remainder_multiple)
        self.clean = ShardStream(clean_shards, ("bins",), window_rows,
                                 remainder_multiple=remainder_multiple)
        self.window_rows = window_rows

    @property
    def num_rows(self) -> int:
        return self.norm.num_rows

    def windows(self):
        for nw, cw in zip(self.norm.windows(), self.clean.windows()):
            assert nw.start == cw.start and nw.rows == cw.rows, \
                "norm/clean shard planes disagree on row layout"
            nw.arrays["bins"] = cw.arrays["bins"]
            yield nw


def train_wdl_streamed(planes: ZippedPlanes, spec: wdl_model.WDLModelSpec,
                       settings: TrainSettings, bags: int, mask_fn,
                       num_feat_idx, cat_col_idx,
                       mesh=None, progress=None,
                       elastic=None,
                       shard: Optional[bool] = None) -> WDLResult:
    """Out-of-core WDL: full-batch gradient accumulation over zipped windows
    (one synchronized update per epoch — the reference's BSP iteration,
    ``WDLMaster`` aggregation), members vmapped on the ensemble axis,
    windows mesh-sharded over the data axis.

    ``elastic`` (:class:`parallel.elastic.ElasticContext`) swaps the
    cross-process combine for the quorum-gated step protocol exactly as
    in the streamed NN trainer: per-epoch grad/stat sums post as one
    contribution, the update applies the committed quorum aggregate,
    and an already-closed epoch replays from the journal (rejoin
    catch-up) without streaming."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from . import wdl_shard

    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=bags)
    sh_ens = NamedSharding(mesh, P("ensemble"))
    sh_row = NamedSharding(mesh, P("data", None))
    sh_y = NamedSharding(mesh, P("data"))
    sh_w = NamedSharding(mesh, P("ensemble", "data"))

    key = jax.random.PRNGKey(settings.seed)
    keys = jax.random.split(key, bags)
    init_list = [wdl_model.init_params(k, spec) for k in keys]
    opt = make_optimizer(settings.optimizer, settings.learning_rate,
                         **settings.opt_kwargs)
    precision = resolve_precision(settings.precision)
    if precision != "f32":
        init_list = [cast_tree(p, jnp.bfloat16) for p in init_list]
    use_shard = wdl_shard.shard_enabled(spec, mesh, bags, precision,
                                        override=shard)
    plane = None
    if use_shard:
        plane = wdl_shard.WDLShardPlane(mesh, spec, bags)
        init_list = [plane.pad_params(m) for m in init_list]
    stacked = _stack(init_list)
    if precision == "mixed":
        opt_state = _stack([mixed_init(opt, p) for p in init_list])
    else:
        opt_state = _stack([opt.init(p) for p in init_list])
    if use_shard:
        stacked, opt_state = plane.put(stacked, opt_state)
        extra = spec.extra or {}
        wdl_shard.record_shard_gauges(
            plane, precision, int(extra.get("hash_buckets", 0) or 0),
            len(extra.get("hashed_cols") or []))
    else:
        stacked = jax.device_put(stacked, sh_ens)
        opt_state = jax.device_put(opt_state, sh_ens)
    l2 = settings.l2
    sfns = wdl_shard.build_streamed_fns(plane, stacked, opt_state, opt,
                                        precision, l2) if use_shard else None

    def _loss_sum(params, xnb, xcb, yb, mw):
        p = wdl_model.forward(params, spec, xnb, xcb)
        return (wdl_model.per_row_bce(p, yb[:, None]) * mw).sum()

    def _eval_sums(params, xnb, xcb, yb, mw, vw):
        p = wdl_model.forward(params, spec, xnb, xcb)
        per = wdl_model.per_row_bce(p, yb[:, None])
        return jnp.stack([(per * mw).sum(), mw.sum(),
                          (per * vw).sum(), vw.sum()])

    @partial(obs.costed_jit, "wdl.grad_eval_window")
    def grad_eval_window(stacked, grad_acc, stats_acc, xnb, xcb, yb, tw, vw):
        def one(params, mw, vwm):
            _, grads = jax.value_and_grad(_loss_sum)(params, xnb, xcb, yb, mw)
            return grads, _eval_sums(params, xnb, xcb, yb, mw, vwm)
        grads, stats = jax.vmap(one)(stacked, tw, vw)
        grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, grads)
        return grad_acc, stats_acc + stats

    @partial(obs.costed_jit, "wdl.eval_window")
    def eval_window(stacked, stats_acc, xnb, xcb, yb, tw, vw):
        stats = jax.vmap(_eval_sums, in_axes=(0, None, None, None, 0, 0))(
            stacked, xnb, xcb, yb, tw, vw)
        return stats_acc + stats

    @partial(obs.costed_jit, "wdl.apply_update")
    def apply_update(stacked, opt_state, grad_acc, train_wsum):
        def one(params, ostate, grads, wsum):
            inv = 1.0 / jnp.maximum(wsum, 1e-9)
            g = jax.tree_util.tree_map(lambda a: a * inv, grads)
            if l2:
                # the SAME L2 term the in-RAM weighted_loss applies: deep
                # weights + embeddings only, never bias/wide
                g = jax.tree_util.tree_map(
                    jnp.add, g, wdl_model.l2_grads(params, l2))
            if precision == "mixed":
                return mixed_apply(opt, g, ostate)
            delta, ostate = opt.update(g, ostate, params)
            params = jax.tree_util.tree_map(
                lambda p, d: p + d.astype(p.dtype), params, delta)
            return params, ostate
        return jax.vmap(one)(stacked, opt_state, grad_acc, train_wsum)

    # mixed accumulates cross-window gradient sums in f32 (jnp.add's
    # bf16+f32 promotion keeps the accumulator wide per window)
    zero_grads = jax.device_put(
        jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape,
                                jnp.float32 if precision == "mixed"
                                else a.dtype), stacked),
        plane.param_shardings() if use_shard else sh_ens)
    if elastic is not None:
        from ..parallel.elastic import grad_codec
        _ravel_grads, _unravel_grads = grad_codec(zero_grads)

    def put_window(win):
        x = win.arrays["x"].astype(np.float32)
        bins = win.arrays["bins"].astype(np.int32)
        xnb = jax.device_put(
            x[:, num_feat_idx] if num_feat_idx
            else np.zeros((len(x), 0), np.float32), sh_row)
        xcb = jax.device_put(
            wdl_model.apply_hash_host(spec, bins[:, cat_col_idx])
            if cat_col_idx
            else np.zeros((len(x), 0), np.int32), sh_row)
        yb = jax.device_put(win.arrays["y"].astype(np.float32), sh_y)
        tm, vm = mask_fn(win.index, win.arrays["y"])
        wcol = win.arrays["w"].astype(np.float32)
        if win.n_valid < win.rows:
            wcol = wcol.copy()
            wcol[win.n_valid:] = 0.0
        tw = jax.device_put(tm * wcol[None, :], sh_w)
        vw = jax.device_put(vm * wcol[None, :], sh_w)
        return xnb, xcb, yb, tw, vw

    stops = [WindowEarlyStop(settings.early_stop_window) for _ in range(bags)]
    best_valid = np.full(bags, np.inf)
    best_train = np.full(bags, np.inf)
    best_params: List[Any] = [None] * bags
    history: List[Tuple[float, float]] = []

    def bookkeep(epoch_done: int, stats: np.ndarray, params_snapshot) -> bool:
        """Record errors for ``epoch_done`` measured on ``params_snapshot``;
        True when every member's early-stop window fired."""
        tr = stats[:, 0] / np.maximum(stats[:, 1], 1e-9)
        va = stats[:, 2] / np.maximum(stats[:, 3], 1e-9)
        history.append((float(tr.mean()), float(va.mean())))
        improved = np.flatnonzero(va < best_valid)
        if improved.size:
            host = _to_host(params_snapshot)
            for i in improved:
                best_valid[i], best_train[i] = va[i], tr[i]
                best_params[i] = jax.tree_util.tree_map(
                    lambda a: a[i].copy(), host)
        if progress:
            progress(epoch_done, float(tr.mean()), float(va.mean()))
        obs.counter("train.epochs").inc()
        obs.event("epoch", trainer="wdl_streamed", epoch=epoch_done,
                  train_err=round(float(tr.mean()), 6),
                  valid_err=round(float(va.mean()), 6),
                  rows=planes.num_rows)
        if settings.early_stop_window > 0:
            return all(s.should_stop(float(v)) for s, v in zip(stops, va))
        return False

    epochs_run = 0
    stopped = False
    start_epoch = 0
    epochs_target = settings.epochs
    if settings.resume and settings.checkpoint_dir:
        from . import checkpoint as ckpt
        restored = ckpt.restore_state(
            settings.checkpoint_dir,
            _ckpt_template(stacked, opt_state, key, bags),
            expect_precision=precision)
        if restored is not None:
            start_epoch, state = restored
            if use_shard:
                stacked, opt_state = plane.put(state[0], state[1])
            else:
                stacked = jax.device_put(state[0], sh_ens)
                opt_state = jax.device_put(state[1], sh_ens)
            _restore_tracking(state, best_valid, best_train, best_params,
                              stops)
            epochs_target = _resume_epoch_target(settings, start_epoch,
                                                 stops)
            log.info("resumed streamed WDL trainer state at epoch %d "
                     "(target %d)", start_epoch, epochs_target)
            epochs_run = start_epoch
            if settings.early_stop_window > 0 and \
                    all(s.since_best >= s.window_size for s in stops):
                start_epoch = epochs_target     # already early-stopped
                stopped = True
    for epoch in range(start_epoch, epochs_target):
        params_entering = stacked
        grad_flat = None
        replayed = elastic.closed_step(epoch) if elastic is not None \
            else None
        if replayed is not None:
            # rejoin catch-up: the job already closed this epoch — apply
            # the committed aggregate without streaming (see nn_trainer)
            stats = np.asarray(replayed.payload["stats"])
            grad_flat = replayed.payload["grads"]
        else:
            stats_acc = jnp.zeros((bags, 4))
            grad_acc = zero_grads
            n_win = 0
            for win in planes.windows():
                xnb, xcb, yb, tw, vw = put_window(win)
                grad_acc, stats_acc = (sfns["grad_eval_window"]
                                       if use_shard else grad_eval_window)(
                    stacked, grad_acc, stats_acc, xnb, xcb, yb, tw, vw)
                n_win += 1
            if n_win == 0:
                raise RuntimeError("streamed WDL: empty shard stream")
            if elastic is not None:
                res = elastic.step(epoch, {
                    "grads": _ravel_grads(grad_acc),
                    "stats": np.asarray(stats_acc)})
                stats = np.asarray(res.payload["stats"])
                grad_flat = res.payload["grads"]
            else:
                stats = np.asarray(stats_acc)
        # stats were measured on params_entering: they close the ledger of
        # the params BEFORE this epoch's update
        stopped = bookkeep(epoch, stats, params_entering)
        grads_in = grad_acc if grad_flat is None \
            else _unravel_grads(grad_flat)
        if use_shard and grad_flat is not None:
            # the elastic codec round-trips grads through a flat host
            # vector — restore the row-shard layout before the update
            grads_in = jax.device_put(grads_in, plane.param_shardings())
        stacked, opt_state = (sfns["apply_update"]
                              if use_shard else apply_update)(
            stacked, opt_state, grads_in, jnp.asarray(stats[:, 1]))
        epochs_run = epoch + 1
        if settings.checkpoint_dir and settings.checkpoint_every and \
                ((epoch + 1) % settings.checkpoint_every == 0 or stopped):
            from . import checkpoint as ckpt
            ckpt.save_state(settings.checkpoint_dir, epoch + 1,
                            _ckpt_state(stacked, opt_state, key,
                                        best_valid, best_train,
                                        best_params, stops),
                            precision=precision)
        if stopped:
            obs.event("early_stop", trainer="wdl_streamed", epoch=epoch,
                      window=settings.early_stop_window)
            log.info("WDL early stop at epoch %d (streamed)", epoch)
            break
    if not stopped:
        # final eval-only sweep so the LAST update's params compete for best
        # (otherwise the last epoch's work is always discarded); elastic
        # closes it as one more quorum step (id ``epochs_run``, past
        # every epoch id) so best-model selection agrees job-wide
        final_close = elastic.closed_step(epochs_run) \
            if elastic is not None else None
        if final_close is None:
            stats_acc = jnp.zeros((bags, 4))
            for win in planes.windows():
                xnb, xcb, yb, tw, vw = put_window(win)
                stats_acc = (sfns["eval_window"]
                             if use_shard else eval_window)(
                    stacked, stats_acc, xnb, xcb, yb, tw, vw)
            if elastic is not None:
                final_close = elastic.step(
                    epochs_run, {"stats": np.asarray(stats_acc)})
        bookkeep(epochs_run,
                 np.asarray(final_close.payload["stats"])
                 if final_close is not None else np.asarray(stats_acc),
                 stacked)
    final = _to_host(stacked)
    for i in range(bags):
        if best_params[i] is None:
            best_params[i] = jax.tree_util.tree_map(lambda a: a[i], final)
    if use_shard:
        best_params = [plane.unpad_params(m) for m in best_params]
    return WDLResult(params=best_params, train_errors=best_train,
                     valid_errors=best_valid, epochs_run=epochs_run,
                     history=history)


# -------------------------------------------------------- pipeline driver
def _wdl_settings(mc, p: Dict[str, Any]) -> TrainSettings:
    return TrainSettings(
        optimizer=str(p.get("Optimizer", "ADAM")),
        learning_rate=float(p.get("LearningRate", 0.002)),
        l2=float(p.get("RegularizedConstant", p.get("L2Const", 1e-5))),
        epochs=int(mc.train.numTrainEpochs),
        batch_size=int(p.get("MiniBatchs", 128)),
        early_stop_window=int(p.get("WindowSize", 10))
        if mc.train.earlyStopEnable else 0,
        seed=int(p.get("Seed", 0)),
        precision=str(p.get("TrainPrecision", "") or ""))


def run_wdl_training(proc) -> int:
    mc = proc.model_config
    trials = proc._trials(dict(mc.train.params or {}))
    if len(trials) > 1:
        return _run_wdl_grid(proc, trials)
    # trials[0] == params when no grid axes; a 1-trial gridConfigFile or
    # single-element list axis must still apply its expanded values
    mc.train.params = trials[0]
    norm = proc._open_shards(proc.paths.norm_dir) \
        if hasattr(proc, "_open_shards") \
        else Shards.open(proc.paths.norm_dir)
    clean = proc._open_shards(proc.paths.clean_dir) \
        if hasattr(proc, "_open_shards") \
        else Shards.open(proc.paths.clean_dir)
    schema = norm.schema
    p = mc.train.params or {}
    bags = max(1, mc.train.baggingNum)
    settings = _wdl_settings(mc, p)
    # trainer-state fail-over checkpoints + `train -resume` — the same
    # epoch hooks the NN family has (grid trials stay checkpoint-free)
    settings.checkpoint_dir = proc.paths.checkpoint_dir
    settings.checkpoint_every = int(p.get("CheckpointInterval", 25))
    settings.resume = bool(proc.params.get("resume"))
    # refresh warm-start: N MORE epochs past the restored state
    settings.resume_extra = int(proc.params.get("refresh_extra") or 0)

    by_num = {c.columnNum: c for c in proc.column_configs}
    streaming = proc._use_streaming(norm, schema) \
        if hasattr(proc, "_use_streaming") else False

    with open(proc.paths.progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
        def progress(epoch, tr, va):
            pf.write(f"Epoch #{epoch + 1} Train Error: {tr:.6f} "
                     f"Validation Error: {va:.6f}\n")
            pf.flush()

        if streaming:
            from ..data.streaming import (mask_fn_from_settings,
                                          stream_window_rows)
            mesh = meshlib.device_mesh(n_ensemble=bags)
            data_size = mesh.shape["data"]
            d = len(schema.get("outputNames") or [])
            window_rows = stream_window_rows(6 * (d + 2), data_size,
                                             norm)
            # WDL streams full-batch: the remainder ladder shrinks the
            # tail window instead of padding it to full W (sub-rungs stay
            # data_size multiples, so sharding divides; at most one extra
            # compiled shape per run)
            planes = ZippedPlanes(norm, clean, window_rows,
                                  remainder_multiple=data_size)
            # plane split derives from schema + ColumnConfig alone — no
            # window read needed
            num_feat_idx, cat_col_idx, num_nums, cat_nums = \
                plane_indices(schema, proc.column_configs)
            spec = _make_spec(len(num_feat_idx), by_num, cat_nums, num_nums,
                              num_feat_idx, cat_col_idx, p)
            log.info("train WDL STREAMED: %d rows, window %d, %d members, "
                     "mesh %s", planes.num_rows, window_rows, bags,
                     dict(mesh.shape))
            if mc.train.stratifiedSample:
                log.warning("streaming: stratified validation degrades to "
                            "Bernoulli split (needs a global pass)")
            mask_fn = mask_fn_from_settings(
                bags, valid_rate=mc.train.validSetRate,
                sample_rate=mc.train.baggingSampleRate,
                replacement=mc.train.baggingWithReplacement,
                up_sample_weight=mc.train.upSampleWeight,
                seed=settings.seed)
            # elastic multi-controller combine (same opt-in as the NN
            # streamed path; WDL streams full-batch, so no gate needed)
            from ..parallel.elastic import elastic_context_for
            ectx = elastic_context_for(proc.dir, step_name="TRAIN")
            if ectx is not None:
                ectx.start()
            try:
                res = train_wdl_streamed(planes, spec, settings, bags,
                                         mask_fn, num_feat_idx,
                                         cat_col_idx, mesh=mesh,
                                         progress=progress, elastic=ectx)
            except BaseException:
                if ectx is not None:
                    ectx.stop(exit_code=1)
                raise
            if ectx is not None:
                ectx.stop(exit_code=0)
        else:
            ndata = norm.load_all()
            cdata = clean.load_all()
            x, y, w = ndata["x"], ndata["y"], ndata["w"]
            bins = cdata["bins"].astype(np.int32)
            x_num, x_cat, num_feat_idx, cat_col_idx, num_nums, cat_nums = \
                split_planes(x, bins, schema, proc.column_configs)
            spec = _make_spec(x_num.shape[1], by_num, cat_nums, num_nums,
                              num_feat_idx, cat_col_idx, p)
            log.info("train WDL: %d rows, %d numeric + %d categorical cols "
                     "(embed %d), %d members", len(y), x_num.shape[1],
                     len(spec.cat_cardinalities), spec.embed_dim, bags)
            res = train_wdl_ensemble(
                x_num, x_cat, y, w, spec, settings, bags=bags,
                valid_rate=mc.train.validSetRate,
                sample_rate=mc.train.baggingSampleRate,
                replacement=mc.train.baggingWithReplacement,
                stratified=mc.train.stratifiedSample,
                up_sample_weight=mc.train.upSampleWeight,
                progress=progress)

    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    for i, params in enumerate(res.params):
        wdl_model.save_model(proc.paths.model_path(i, "wdl"), spec, params)
    log.info("train WDL done: %d model(s), valid errors %s (%d epochs)",
             len(res.params), np.round(res.valid_errors, 6).tolist(),
             res.epochs_run)
    return 0


def _run_wdl_grid(proc, trials) -> int:
    """WDL grid search: trials MAY differ structurally (embed dim /
    hidden shape change the program), so they run sequentially — the
    reference's job-queue shape (``gs/GridSearch.java:62`` is
    algorithm-agnostic).  Scalar-only grids could stack as vmapped
    members the way the NN path does, but the WDL trainer has no
    per-member hyper plumbing yet.
    The ranked report lands in tmp/grid_search.json and the best trial's
    model saves as model0 (the NN grid contract)."""
    mc = proc.model_config
    norm = Shards.open(proc.paths.norm_dir)
    clean = Shards.open(proc.paths.clean_dir)
    schema = norm.schema
    by_num = {c.columnNum: c for c in proc.column_configs}
    if hasattr(proc, "_use_streaming") and \
            proc._use_streaming(norm, schema):
        log.warning("WDL grid trials train in-RAM (structural trials "
                    "can't stream-share); reduce trials or raise the "
                    "memory budget if this OOMs")
    ndata = norm.load_all()
    cdata = clean.load_all()
    x, y, w = ndata["x"], ndata["y"], ndata["w"]
    bins = cdata["bins"].astype(np.int32)
    x_num, x_cat, num_feat_idx, cat_col_idx, num_nums, cat_nums = \
        split_planes(x, bins, schema, proc.column_configs)
    results = []
    with open(proc.paths.progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
        for ti, p in enumerate(trials):
            spec = _make_spec(x_num.shape[1], by_num, cat_nums, num_nums,
                              num_feat_idx, cat_col_idx, p)
            settings = _wdl_settings(mc, p)

            def progress(epoch, tr, va, ti=ti):
                pf.write(f"Trial [{ti}] Epoch #{epoch + 1} Train Error: "
                         f"{tr:.6f} Validation Error: {va:.6f}\n")
                pf.flush()

            res = train_wdl_ensemble(
                x_num, x_cat, y, w, spec, settings, bags=1,
                valid_rate=mc.train.validSetRate,
                sample_rate=mc.train.baggingSampleRate,
                replacement=mc.train.baggingWithReplacement,
                stratified=mc.train.stratifiedSample,
                up_sample_weight=mc.train.upSampleWeight,
                progress=progress)
            results.append((float(res.valid_errors[0]), spec,
                            res.params[0], p))
            log.info("WDL grid trial %d/%d: valid err %.6f", ti + 1,
                     len(trials), res.valid_errors[0])
    from ..train.grid_search import rank_and_report
    order = rank_and_report(proc.paths.tmp_dir,
                            [r[0] for r in results],
                            [r[3] for r in results])
    best = order[0]
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    wdl_model.save_model(proc.paths.model_path(0, "wdl"),
                         results[best][1], results[best][2])
    log.info("WDL grid search: best trial #%d valid error %.6f params %s",
             best, results[best][0], results[best][3])
    return 0


def _make_spec(numeric_dim: int, by_num, cat_nums, num_nums,
               num_feat_idx, cat_col_idx, p: Dict[str, Any]):
    from ..config import environment
    from ..ops.hashing import column_hash_key
    cards = [by_num[cn].num_bins() + 1 for cn in cat_nums]
    extra: Dict[str, Any] = {"num_feat_idx": num_feat_idx,
                             "cat_col_idx": cat_col_idx}
    # hashed-ID path (shifu.wdl.hashBuckets / params.HashBuckets): any
    # categorical column WIDER than the bucket space maps its raw ids
    # through splitmix64 into [0, buckets) and its table shrinks to the
    # bucket count; narrower columns keep exact ids.  The plan lives in
    # spec.extra so train, checkpoint, and serve all hash identically.
    buckets = int(p.get("HashBuckets", 0) or 0) or \
        environment.get_int("shifu.wdl.hashBuckets", 0)
    if buckets > 0:
        hashed = [i for i, c in enumerate(cards) if c > buckets]
        if hashed:
            extra.update(
                hash_buckets=int(buckets), hashed_cols=hashed,
                hash_keys=[column_hash_key(cat_nums[i]) for i in hashed])
            cards = [buckets if i in hashed else c
                     for i, c in enumerate(cards)]
    return wdl_model.WDLModelSpec(
        numeric_dim=numeric_dim, cat_cardinalities=cards,
        embed_dim=int(p.get("EmbedColumnNum", p.get("EmbedDim", 8))),
        hidden_nodes=[int(v) for v in p.get("NumHiddenNodes", [64, 32])],
        activations=[str(a).lower()
                     for a in p.get("ActivationFunc", ["relu", "relu"])],
        wide_enable=bool(p.get("WideEnable", True)),
        deep_enable=bool(p.get("DeepEnable", True)),
        column_nums=num_nums, cat_column_nums=cat_nums,
        extra=extra)
