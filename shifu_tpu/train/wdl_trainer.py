"""WDL trainer — reference ``WDLWorker``/``WDLMaster``/``WDLOutput``
(``core/dtrain/wdl/``): the BSP gradient loop as jitted minibatch steps over
the dual data planes (normalized numerics + categorical bin indices).
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..config.model_config import Algorithm
from ..data.shards import Shards
from ..models import wdl as wdl_model
from .early_stop import WindowEarlyStop
from .optimizers import make_optimizer
from .sampling import validation_split

log = logging.getLogger(__name__)


def split_planes(x: np.ndarray, bins: np.ndarray, schema: dict,
                 column_configs) -> Tuple[np.ndarray, np.ndarray, List[int],
                                          List[int], List[int], List[int]]:
    """Split the materialized planes into (numeric features, categorical bin
    indices) by column type: numerics keep their normalized block, each
    categorical column contributes its bin index (embedding id)."""
    col_nums = schema["columnNums"]
    names = schema["outputNames"]
    by_num = {c.columnNum: c for c in column_configs}
    # map output features back to source columns by name prefix
    name_to_num = {by_num[cn].columnName: cn for cn in col_nums if cn in by_num}
    blocks: Dict[int, List[int]] = {}
    for i, n in enumerate(names):
        base = n
        if base not in name_to_num and "_" in base:
            stem, suf = base.rsplit("_", 1)
            if stem in name_to_num and suf.isdigit():
                base = stem
        cn = name_to_num.get(base)
        if cn is not None:
            blocks.setdefault(cn, []).append(i)

    num_feat_idx: List[int] = []
    num_col_nums: List[int] = []
    cat_col_idx: List[int] = []
    cat_col_nums: List[int] = []
    for j, cn in enumerate(col_nums):
        cc = by_num.get(cn)
        if cc is None:
            continue
        if cc.is_categorical():
            cat_col_idx.append(j)
            cat_col_nums.append(cn)
        else:
            num_feat_idx.extend(blocks.get(cn, []))
            num_col_nums.append(cn)
    x_num = x[:, num_feat_idx] if num_feat_idx else np.zeros((len(x), 0),
                                                             np.float32)
    x_cat = bins[:, cat_col_idx] if cat_col_idx else np.zeros((len(x), 0),
                                                              np.int32)
    return x_num, x_cat, num_feat_idx, cat_col_idx, num_col_nums, cat_col_nums


def run_wdl_training(proc) -> int:
    mc = proc.model_config
    norm = Shards.open(proc.paths.norm_dir)
    clean = Shards.open(proc.paths.clean_dir)
    ndata = norm.load_all()
    cdata = clean.load_all()
    x, y, w = ndata["x"], ndata["y"], ndata["w"]
    bins = cdata["bins"].astype(np.int32)
    schema = norm.schema
    x_num, x_cat, num_feat_idx, cat_col_idx, num_nums, cat_nums = \
        split_planes(x, bins, schema, proc.column_configs)

    by_num = {c.columnNum: c for c in proc.column_configs}
    cards = [by_num[cn].num_bins() + 1 for cn in cat_nums]
    p = mc.train.params or {}
    spec = wdl_model.WDLModelSpec(
        numeric_dim=x_num.shape[1], cat_cardinalities=cards,
        embed_dim=int(p.get("EmbedColumnNum", p.get("EmbedDim", 8))),
        hidden_nodes=[int(v) for v in p.get("NumHiddenNodes", [64, 32])],
        activations=[str(a).lower()
                     for a in p.get("ActivationFunc", ["relu", "relu"])],
        wide_enable=bool(p.get("WideEnable", True)),
        deep_enable=bool(p.get("DeepEnable", True)),
        column_nums=num_nums, cat_column_nums=cat_nums,
        extra={"num_feat_idx": num_feat_idx, "cat_col_idx": cat_col_idx})
    n = len(y)
    log.info("train WDL: %d rows, %d numeric + %d categorical cols "
             "(embed %d)", n, x_num.shape[1], len(cards), spec.embed_dim)

    settings = {
        "lr": float(p.get("LearningRate", 0.002)),
        "l2": float(p.get("RegularizedConstant", p.get("L2Const", 1e-5))),
        "epochs": int(mc.train.numTrainEpochs),
        "batch": int(p.get("MiniBatchs", 128)),
        "optimizer": str(p.get("Optimizer", "ADAM")),
        "window": int(p.get("WindowSize", 10)) if mc.train.earlyStopEnable else 0,
    }
    res = train_wdl(x_num, x_cat, y, w, spec, settings,
                    valid_rate=mc.train.validSetRate,
                    seed=int(p.get("Seed", 0)),
                    progress_path=proc.paths.progress_path)

    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    wdl_model.save_model(proc.paths.model_path(0, "wdl"), spec, res["params"])
    log.info("train WDL done: valid error %.6f (%d epochs)",
             res["valid_error"], res["epochs_run"])
    return 0


def train_wdl(x_num, x_cat, y, w, spec: wdl_model.WDLModelSpec,
              settings: dict, valid_rate: float = 0.2, seed: int = 0,
              progress_path: Optional[str] = None) -> dict:
    n = len(y)
    vmask = validation_split(n, valid_rate, seed)
    tw = np.asarray(w, np.float32) * ~vmask
    vw = np.asarray(w, np.float32) * vmask

    xn = jnp.asarray(x_num, jnp.float32)
    xc = jnp.asarray(x_cat, jnp.int32)
    yj = jnp.asarray(y, jnp.float32)[:, None]
    twj = jnp.asarray(tw)
    vwj = jnp.asarray(vw)

    key = jax.random.PRNGKey(seed)
    params = wdl_model.init_params(key, spec)
    opt = make_optimizer(settings["optimizer"], settings["lr"])
    opt_state = opt.init(params)
    l2 = settings["l2"]

    @jax.jit
    def step(params, opt_state, xn_b, xc_b, y_b, w_b):
        loss, grads = jax.value_and_grad(wdl_model.weighted_loss)(
            params, spec, xn_b, xc_b, y_b, w_b, l2)
        delta, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda a, d: a + d, params, delta)
        return params, opt_state, loss

    @jax.jit
    def errors(params):
        p = wdl_model.forward(params, spec, xn, xc)
        per = -(yj * jnp.log(jnp.clip(p, 1e-7, 1.0))
                + (1 - yj) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0)))[:, 0]
        tr = (per * twj).sum() / jnp.maximum(twj.sum(), 1e-9)
        va = (per * vwj).sum() / jnp.maximum(vwj.sum(), 1e-9)
        return tr, va

    bs = max(8, settings["batch"])
    stop = WindowEarlyStop(settings["window"]) if settings["window"] else None
    best_va, best_params = np.inf, params
    pf = open(progress_path, "w") if progress_path else None
    epochs_run = 0
    history = []
    rng = np.random.default_rng(seed)
    try:
        for epoch in range(settings["epochs"]):
            perm = rng.permutation(n)
            for s in range(0, n - bs + 1, bs):
                idx = jnp.asarray(perm[s:s + bs])
                params, opt_state, _ = step(params, opt_state, xn[idx],
                                            xc[idx], yj[idx], twj[idx])
            tr, va = errors(params)
            tr, va = float(tr), float(va)
            history.append((tr, va))
            epochs_run = epoch + 1
            if pf:
                pf.write(f"Epoch #{epoch + 1} Train Error: {tr:.6f} "
                         f"Validation Error: {va:.6f}\n")
                pf.flush()
            if va < best_va:
                best_va = va
                best_params = jax.tree_util.tree_map(np.asarray, params)
            if stop and stop.should_stop(va):
                log.info("WDL early stop at epoch %d", epoch)
                break
    finally:
        if pf:
            pf.close()
    return {"params": best_params, "valid_error": best_va,
            "epochs_run": epochs_run, "history": history}
