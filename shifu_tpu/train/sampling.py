"""Sampling semantics — bagging / k-fold / stratified / validation split as
RNG-keyed masks (reference ``AbstractNNWorker.java:668-716,737-757``).

The reference assigns each streamed record to bags/folds at load time on each
worker; here the whole dataset's assignments materialize as arrays in one
vectorized shot, so every ensemble member's per-row weight lives in a
``[bags, rows]`` matrix the vmapped trainer consumes directly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def validation_split(n: int, valid_rate: float, seed: int = 0,
                     stratified: bool = False,
                     targets: Optional[np.ndarray] = None) -> np.ndarray:
    """Boolean mask, True = validation row.  Stratified keeps the pos/neg
    ratio in both splits (reference stratified sampling path)."""
    rng = np.random.default_rng(seed)
    if not stratified or targets is None:
        return rng.random(n) < valid_rate
    mask = np.zeros(n, dtype=bool)
    for cls in np.unique(targets):
        idx = np.flatnonzero(targets == cls)
        k = int(round(len(idx) * valid_rate))
        mask[rng.choice(idx, size=k, replace=False)] = True
    return mask


def kfold_assignment(n: int, k: int, seed: int = 0) -> np.ndarray:
    """Fold id per row (reference k-fold crossValidation: fold i is member
    i's validation shard)."""
    rng = np.random.default_rng(seed)
    return rng.permutation(np.arange(n) % k)


def bagging_weights(n: int, bags: int, sample_rate: float = 1.0,
                    replacement: bool = False, seed: int = 0,
                    up_sample_weight: float = 1.0,
                    targets: Optional[np.ndarray] = None) -> np.ndarray:
    """[bags, n] per-row sample weights.

    with replacement → Poisson(rate) counts (the classic bootstrap
    approximation the reference's per-record re-draw converges to) — even for
    baggingNum=1, matching the reference's per-job sampling; without
    replacement → Bernoulli(rate) 0/1 mask, except a single bag at full rate
    sees every row.  ``upSampleWeight`` multiplies positive rows (reference
    up-sampling)."""
    rng = np.random.default_rng(seed)
    if bags == 1 and sample_rate >= 1.0 and not replacement:
        w = np.ones((1, n), np.float32)
    elif replacement:
        w = rng.poisson(sample_rate, size=(bags, n)).astype(np.float32)
    else:
        w = (rng.random((bags, n)) < sample_rate).astype(np.float32)
    if up_sample_weight != 1.0 and targets is not None:
        w = w * np.where(targets > 0.5, up_sample_weight, 1.0)[None, :].astype(np.float32)
    return w


def member_masks(n: int, bags: int, *, valid_rate: float, kfold: int = -1,
                 sample_rate: float = 1.0, replacement: bool = False,
                 stratified: bool = False, up_sample_weight: float = 1.0,
                 targets: Optional[np.ndarray] = None,
                 seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """(train_w, valid_w): [bags, n] float32 row-weight matrices for every
    ensemble member.  k-fold mode makes ``bags == kfold`` members whose
    validation shards partition the data; otherwise one shared validation
    split + per-bag bagging weights."""
    if kfold and kfold > 1:
        fold = kfold_assignment(n, kfold, seed)
        valid_w = np.stack([(fold == i).astype(np.float32) for i in range(kfold)])
        train_w = 1.0 - valid_w
        if up_sample_weight != 1.0 and targets is not None:
            train_w = train_w * np.where(targets > 0.5, up_sample_weight, 1.0)[None, :]
        return train_w.astype(np.float32), valid_w
    vmask = validation_split(n, valid_rate, seed, stratified, targets)
    bag_w = bagging_weights(n, bags, sample_rate, replacement, seed + 1,
                            up_sample_weight, targets)
    train_w = bag_w * (~vmask)[None, :]
    valid_w = np.broadcast_to(vmask.astype(np.float32), (bags, n)).copy()
    return train_w.astype(np.float32), valid_w.astype(np.float32)
