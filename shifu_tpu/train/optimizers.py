"""Weight-update rules — reference ``core/dtrain/Weight.java`` re-done as pure
JAX pytree transforms.

The reference exposes two families (``Weight.java:39,48-56``):

- propagation algorithms: ``B`` backprop+momentum, ``Q`` quickprop,
  ``R`` resilient RPROP, ``M`` manhattan;
- update rules: ``ADAM | MOMENTUM | RMSPROP | ADAGRAD | NESTEROV``
  (``nn/update/*.java``).

Each rule here is an ``(init, update)`` pair over arbitrary param pytrees,
jit-safe (state is a pytree of arrays, no Python branching on values).
``update`` returns a delta to ADD to params.  L1/L2 regularization
(``Weight.java:201-213``) is applied in the loss, not here.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    update: Callable[[Any, Any, Any], Tuple[Any, Any]]  # (grads, state, params)


def _tmap(f, *trees):
    return jax.tree_util.tree_map(f, *trees)


def _zeros_like(params):
    return _tmap(jnp.zeros_like, params)


# ------------------------------------------------------------ update rules
def sgd(learning_rate: float) -> Optimizer:
    def init(params):
        return ()

    def update(grads, state, params):
        return _tmap(lambda g: -learning_rate * g, grads), state

    return Optimizer(init, update)


def momentum(learning_rate: float, beta: float = 0.9,
             nesterov_mode: bool = False) -> Optimizer:
    """MOMENTUM / NESTEROV update rules (``nn/update/MomentumUpdate.java``,
    ``NesterovUpdate.java``)."""
    def init(params):
        return {"v": _zeros_like(params)}

    def update(grads, state, params):
        v = _tmap(lambda v_, g: beta * v_ - learning_rate * g, state["v"], grads)
        if nesterov_mode:
            delta = _tmap(lambda v_, g: beta * v_ - learning_rate * g, v, grads)
        else:
            delta = v
        return delta, {"v": v}

    return Optimizer(init, update)


def adagrad(learning_rate: float, eps: float = 1e-8) -> Optimizer:
    def init(params):
        return {"g2": _zeros_like(params)}

    def update(grads, state, params):
        g2 = _tmap(lambda a, g: a + g * g, state["g2"], grads)
        delta = _tmap(lambda g, a: -learning_rate * g / (jnp.sqrt(a) + eps),
                      grads, g2)
        return delta, {"g2": g2}

    return Optimizer(init, update)


def rmsprop(learning_rate: float, decay: float = 0.9, eps: float = 1e-8) -> Optimizer:
    def init(params):
        return {"g2": _zeros_like(params)}

    def update(grads, state, params):
        g2 = _tmap(lambda a, g: decay * a + (1 - decay) * g * g,
                   state["g2"], grads)
        delta = _tmap(lambda g, a: -learning_rate * g / (jnp.sqrt(a) + eps),
                      grads, g2)
        return delta, {"g2": g2}

    return Optimizer(init, update)


def adam(learning_rate: float, beta1: float = 0.9, beta2: float = 0.999,
         eps: float = 1e-8) -> Optimizer:
    def init(params):
        return {"m": _zeros_like(params), "v": _zeros_like(params),
                "t": jnp.zeros((), jnp.float32)}

    def update(grads, state, params):
        t = state["t"] + 1.0
        m = _tmap(lambda m_, g: beta1 * m_ + (1 - beta1) * g, state["m"], grads)
        v = _tmap(lambda v_, g: beta2 * v_ + (1 - beta2) * g * g, state["v"], grads)
        mh = _tmap(lambda m_: m_ / (1 - beta1 ** t), m)
        vh = _tmap(lambda v_: v_ / (1 - beta2 ** t), v)
        delta = _tmap(lambda m_, v_: -learning_rate * m_ / (jnp.sqrt(v_) + eps),
                      mh, vh)
        return delta, {"m": m, "v": v, "t": t}

    return Optimizer(init, update)


# ----------------------------------------------- propagation algos (B/Q/R/M)
def backprop(learning_rate: float, momentum_term: float = 0.5) -> Optimizer:
    """``B``: plain backprop + momentum (``Weight.java`` B branch)."""
    return momentum(learning_rate, beta=momentum_term)


def manhattan(learning_rate: float) -> Optimizer:
    """``M``: fixed step in the gradient's sign direction."""
    def init(params):
        return ()

    def update(grads, state, params):
        return _tmap(lambda g: -learning_rate * jnp.sign(g), grads), state

    return Optimizer(init, update)


def rprop(init_step: float = 0.1, eta_plus: float = 1.2, eta_minus: float = 0.5,
          max_step: float = 50.0, min_step: float = 1e-6) -> Optimizer:
    """``R``: resilient propagation — per-weight adaptive step from gradient
    sign agreement; the reference NN default (``Weight.java`` R branch,
    Encog ResilientPropagation constants)."""
    def init(params):
        return {"step": _tmap(lambda p: jnp.full_like(p, init_step), params),
                "prev_g": _zeros_like(params)}

    def update(grads, state, params):
        def one(g, pg, st):
            agree = g * pg
            new_st = jnp.where(agree > 0, jnp.minimum(st * eta_plus, max_step),
                               jnp.where(agree < 0,
                                         jnp.maximum(st * eta_minus, min_step), st))
            # on sign flip: no move this step, zero the remembered gradient
            delta = jnp.where(agree < 0, 0.0, -jnp.sign(g) * new_st)
            carry_g = jnp.where(agree < 0, 0.0, g)
            return delta, new_st, carry_g

        flat_g, treedef = jax.tree_util.tree_flatten(grads)
        flat_pg = treedef.flatten_up_to(state["prev_g"])
        flat_st = treedef.flatten_up_to(state["step"])
        outs = [one(g, pg, st) for g, pg, st in zip(flat_g, flat_pg, flat_st)]
        delta = treedef.unflatten([o[0] for o in outs])
        step = treedef.unflatten([o[1] for o in outs])
        prev = treedef.unflatten([o[2] for o in outs])
        return delta, {"step": step, "prev_g": prev}

    return Optimizer(init, update)


def quickprop(learning_rate: float, mu: float = 1.75,
              eps: float = 1e-10) -> Optimizer:
    """``Q``: quickprop — quadratic step from consecutive gradients
    (``Weight.java`` Q branch), clamped by the maximum-growth factor ``mu``."""
    def init(params):
        return {"prev_g": _zeros_like(params), "prev_d": _zeros_like(params)}

    def update(grads, state, params):
        def one(g, pg, pd):
            quick = g / (pg - g + jnp.where(pg == g, eps, 0.0)) * pd
            quick = jnp.clip(quick, -mu * jnp.abs(pd) - eps, mu * jnp.abs(pd) + eps)
            grad_step = -learning_rate * g
            first = pd == 0.0
            d = jnp.where(first, grad_step, quick + grad_step)
            return d

        delta = _tmap(one, grads, state["prev_g"], state["prev_d"])
        return delta, {"prev_g": grads, "prev_d": delta}

    return Optimizer(init, update)


# ------------------------------------------------------ precision ladder
# the -Dshifu.train.precision knob (ISSUE 11 / ROADMAP #5): "f32" keeps
# today's math untouched; "bf16" trains entirely in bfloat16 (params,
# activations, optimizer state — halves HBM and feeds the MXU native
# rate, lossy); "mixed" is the production ladder: an f32 MASTER copy of
# the params lives in the optimizer state, forward/backward run on the
# bf16 cast (activations narrow), gradients cast back to f32 and the
# update rule applied to the master — one bf16 rounding per step instead
# of compounding rounding in the weights themselves.
PRECISIONS = ("f32", "bf16", "mixed")


def resolve_precision(setting: str = "") -> str:
    """The effective training precision: an explicit trainer setting
    wins, else the ``shifu.train.precision`` property, default ``f32``.
    Unknown values fail loudly — a typo'd precision silently training
    f32 would invalidate every bench row claiming otherwise."""
    if not setting:
        from ..config import environment
        setting = environment.get_property("shifu.train.precision", "f32")
    key = str(setting).lower()
    if key not in PRECISIONS:
        raise ValueError(f"unknown shifu.train.precision {setting!r}; "
                         f"one of {PRECISIONS}")
    return key


def compute_dtype(precision: str):
    """Param/activation dtype of the forward/backward pass."""
    return jnp.float32 if precision == "f32" else jnp.bfloat16


def cast_tree(tree, dtype):
    """Cast every floating leaf of a pytree; integer/bool leaves (opt
    step counters, masks) pass through untouched."""
    return _tmap(lambda l: l.astype(dtype)
                 if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                 else l, tree)


def mixed_init(opt: Optimizer, params_bf16):
    """Mixed-precision optimizer state: the f32 master (exactly equal to
    the bf16 params at init — the cast up is value-preserving) plus the
    wrapped rule's own state built over the master."""
    master = cast_tree(params_bf16, jnp.float32)
    return {"master": master, "inner": opt.init(master)}


def mixed_apply(opt: Optimizer, grads, state, scale=1.0, freeze=None):
    """One mixed-precision update: bf16 grads widen to f32, the inner
    rule steps the f32 master (``freeze`` optionally zeroes fixed-layer
    deltas, ``scale`` is the trainer's lr decay/per-member factor), and
    the new bf16 training params are ONE rounding of the new master.
    Returns ``(params_bf16, state)``."""
    g32 = cast_tree(grads, jnp.float32)
    delta, inner = opt.update(g32, state["inner"], state["master"])
    if freeze is not None:
        delta = freeze(delta)
    master = _tmap(lambda m, d: m + d * scale, state["master"], delta)
    return cast_tree(master, jnp.bfloat16), \
        {"master": master, "inner": inner}


# ----------------------------------------------------------------- factory
_RULES = {
    "ADAM": lambda lr, kw: adam(lr, **kw),
    "MOMENTUM": lambda lr, kw: momentum(lr, **kw),
    "NESTEROV": lambda lr, kw: momentum(lr, nesterov_mode=True, **kw),
    "RMSPROP": lambda lr, kw: rmsprop(lr, **kw),
    "ADAGRAD": lambda lr, kw: adagrad(lr, **kw),
    "SGD": lambda lr, kw: sgd(lr),
    # propagation letters (reference train#params "Propagation")
    "B": lambda lr, kw: backprop(lr, **kw),
    "M": lambda lr, kw: manhattan(lr),
    "R": lambda lr, kw: rprop(**kw),
    "Q": lambda lr, kw: quickprop(lr, **kw),
}


def make_optimizer(name: str, learning_rate: float = 0.1, **kwargs) -> Optimizer:
    key = (name or "R").upper()
    if key not in _RULES:
        raise ValueError(f"unknown optimizer/propagation {name!r}; "
                         f"one of {sorted(_RULES)}")
    return _RULES[key](learning_rate, kwargs)
