"""Early-stop strategies (reference ``core/dtrain/earlystop/``).

``WindowEarlyStop``: stop when validation error hasn't improved for
``windowSize`` epochs (``earlystop/WindowEarlyStop.java:23``).
``ConvergeAndValidToleranceEarlyStop``: stop when |train - valid| error and
train error both fall under the convergence threshold.
These run host-side between jitted epochs — matching the reference's
master-side check (``NNMaster.java:310-316``) — so the jitted step stays
branch-free.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List


@dataclass
class WindowEarlyStop:
    window_size: int = 10
    best: float = math.inf
    since_best: int = 0

    def should_stop(self, valid_error: float) -> bool:
        if valid_error < self.best - 1e-12:
            self.best = valid_error
            self.since_best = 0
        else:
            self.since_best += 1
        return self.since_best >= self.window_size


@dataclass
class ConvergeAndValidToleranceEarlyStop:
    threshold: float = 0.0
    tolerance: float = 0.01

    def should_stop(self, train_error: float, valid_error: float) -> bool:
        if self.threshold <= 0:
            return False
        return (abs(train_error - valid_error) < self.tolerance
                and train_error < self.threshold)


@dataclass
class GBTEarlyStopDecider:
    """Moving-average + trend halt for boosted trees (reference
    ``dt/DTEarlyStopDecider.java``): stop when the smoothed validation error
    has been rising for ``patience`` consecutive trees."""
    window: int = 5
    patience: int = 3
    history: List[float] = field(default_factory=list)
    rising: int = 0

    def add(self, valid_error: float) -> bool:
        self.history.append(valid_error)
        if len(self.history) < 2 * self.window:
            return False
        cur = sum(self.history[-self.window:]) / self.window
        prev = sum(self.history[-2 * self.window:-self.window]) / self.window
        self.rising = self.rising + 1 if cur > prev else 0
        return self.rising >= self.patience
