"""Sharded WDL categorical plane — mesh-partitioned embedding/wide tables.

The replicated WDL trainer keeps every embedding and wide table whole on
every device, which caps the model at one chip's memory and makes each
step pay a full-table gradient allreduce plus a full-table optimizer
sweep per device.  This module partitions each ``embed``/``wide_cat``
table (and its optimizer moments) ROW-wise over the mesh ``data`` axis
and rewrites the lookup and the update around that layout:

- **sparse row gather**: the minibatch's int bin indices all-gather over
  the axis (4 bytes/row/column — the only replicated traffic), each
  device resolves the gathered ids against its own row shard (masked
  local take), and ONE tiled ``psum_scatter`` returns every device the
  embedding rows of its own data block.  Each (row, column) pair has
  exactly one nonzero contributor, so the scatter reconstructs the
  replicated gather bit for bit (``x + 0 == x``);
- **sharded weight update**: autodiff transposes the psum_scatter to an
  all_gather of the local cotangents, so each shard's gradient lands
  complete on its owner with NO cross-device table traffic, and the
  optimizer steps only the local rows — the full-table allreduce and
  the ``(D-1)/D`` redundant Adam work of the replicated path are gone
  (this is the throughput lever, per "Automatic Cross-Replica Sharding
  of Weight Update in Data-Parallel Training");
- **dense leaves stay replicated**: their per-device partial grads psum
  AFTER ``jax.grad`` — never inside it, because with replication
  tracking off (``check_rep/check_vma=False``) a ``psum`` inside the
  differentiated region transposes to another psum and inflates every
  cotangent by the axis size.  The loss normalizer is parameter-free,
  so it is computed outside the grad for the same reason (exact);
- **row padding**: each table pads with zero rows to a ``data``-axis
  multiple.  Lookups clip to the TRUE cardinality, so padded rows are
  never gathered, their grads stay zero, and every update rule leaves
  them zero; host snapshots unpad so saved models keep exact shapes.

Serving (``shifu.wdl.serveCopy``) closes the loop without a full-table
allgather anywhere: multi-device backends score through the same masked
lookup + psum inside the AOT executable (bitwise-equal scores, zero
recompiles — the batch is replicated, only table rows move); single
device picks the replicated copy or an opt-in lossy hot-rows copy built
at swap time (first K rows exact + one mean-of-tail fallback row, which
the classic forward's clip resolves with no code change).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import environment
from ..models import wdl as wdl_model
from .optimizers import mixed_apply

log = logging.getLogger(__name__)

_AXIS = "data"


def _shard_map(fn, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions (same shim as ops/hist_pallas)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


# ------------------------------------------------------------------ knobs
def shard_mode() -> str:
    """``shifu.wdl.shardTables``: on | off | auto (size-gated)."""
    raw = str(environment.get_property("shifu.wdl.shardTables", "auto")
              or "auto").lower()
    if raw in ("on", "true", "1"):
        return "on"
    if raw in ("off", "false", "0"):
        return "off"
    if raw != "auto":
        log.warning("unknown shifu.wdl.shardTables %r; using auto", raw)
    return "auto"


def shard_min_bytes() -> int:
    """``shifu.wdl.shardMinBytes``: auto-shard threshold on the
    replicated per-device footprint of tables + moments."""
    return environment.get_int("shifu.wdl.shardMinBytes", 64 << 20)


def serve_copy_mode() -> str:
    """``shifu.wdl.serveCopy``: auto | full | sharded | hot."""
    raw = str(environment.get_property("shifu.wdl.serveCopy", "auto")
              or "auto").lower()
    if raw in ("auto", "full", "sharded", "hot"):
        return raw
    log.warning("unknown shifu.wdl.serveCopy %r; using auto", raw)
    return "auto"


def serve_hot_rows() -> int:
    """``shifu.wdl.serveHotRows``: exact head rows of the lossy
    single-device serving copy."""
    return environment.get_int("shifu.wdl.serveHotRows", 1 << 16)


def table_param_bytes(spec, bags: int = 1, precision: str = "f32") -> int:
    """Replicated per-device bytes of all categorical tables + their two
    Adam moments, stacked over ``bags`` — what the auto gate weighs
    (mixed also carries an f32 master+moments; this stays a f32-ladder
    estimate on purpose: a conservative lower bound)."""
    per = 4 if precision == "f32" else 2
    elems = 0
    for c in spec.cat_cardinalities:
        if spec.deep_enable:
            elems += int(c) * spec.embed_dim
        if spec.wide_enable:
            elems += int(c)
    return 3 * elems * per * bags


def shard_enabled(spec, mesh, bags: int = 1, precision: str = "f32",
                  override: Optional[bool] = None) -> bool:
    """Whether this run shards the WDL categorical plane: an explicit
    trainer arg wins, else ``shifu.wdl.shardTables`` (auto = multi-device
    mesh AND tables past ``shifu.wdl.shardMinBytes``)."""
    if not spec.cat_cardinalities:
        return False
    if override is not None:
        return bool(override)
    mode = shard_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    if int(mesh.shape[_AXIS]) <= 1:
        return False
    return table_param_bytes(spec, bags, precision) >= shard_min_bytes()


# ------------------------------------------------------------------ plane
class WDLShardPlane:
    """Row-sharding layout of one spec over one mesh: per-table shard
    sizes, padded cardinalities, PartitionSpec/NamedSharding trees for the
    stacked params and any optimizer state, pad/unpad helpers."""

    def __init__(self, mesh, spec, bags: int):
        self.mesh = mesh
        self.spec = spec
        self.bags = bags
        self.d = int(mesh.shape[_AXIS])
        self.cards = [int(c) for c in spec.cat_cardinalities]
        self.vs = [-(-c // self.d) for c in self.cards]   # rows per shard
        self.vp = [v * self.d for v in self.vs]           # padded rows

    # -- shape plumbing
    def pad_params(self, tree: Dict) -> Dict:
        """Zero-pad one member's table leaves [V, ...] to [Vp, ...] BEFORE
        optimizer init, so moments are born shard-aligned too."""
        def pad(a, vp):
            extra = vp - a.shape[0]
            if not extra:
                return a
            return jnp.pad(a, [(0, extra)] + [(0, 0)] * (a.ndim - 1))
        out = dict(tree)
        if "embed" in out:
            out["embed"] = [pad(t, vp)
                            for t, vp in zip(out["embed"], self.vp)]
        if "wide_cat" in out:
            out["wide_cat"] = [pad(t, vp)
                               for t, vp in zip(out["wide_cat"], self.vp)]
        return out

    def unpad_params(self, tree: Dict) -> Dict:
        """Slice one member's host tree back to the true cardinalities —
        saved ``.wdl`` models keep the replicated path's exact shapes
        (a padded table would change ``clip(idx, 0, V-1)`` semantics for
        out-of-range ids)."""
        out = dict(tree)
        if "embed" in out:
            out["embed"] = [t[:c] for t, c in zip(out["embed"], self.cards)]
        if "wide_cat" in out:
            out["wide_cat"] = [t[:c]
                               for t, c in zip(out["wide_cat"], self.cards)]
        return out

    def param_specs(self) -> Dict:
        """PartitionSpec tree over the STACKED [B, ...] param tree: table
        rows split on ``data``, everything else only on ``ensemble``."""
        from jax.sharding import PartitionSpec as P
        spec = self.spec
        out: Dict[str, Any] = {"bias": P("ensemble")}
        if spec.deep_enable:
            out["embed"] = [P("ensemble", _AXIS, None) for _ in self.cards]
            out["deep"] = [{"w": P("ensemble"), "b": P("ensemble")}
                           for _ in range(len(spec.hidden_nodes) + 1)]
        if spec.wide_enable:
            out["wide_cat"] = [P("ensemble", _AXIS) for _ in self.cards]
            out["wide_num"] = P("ensemble")
        return out

    def state_specs(self, opt_state, stacked) -> Any:
        """Spec tree for any optimizer state by STRUCTURE matching: every
        params-shaped subtree (adam m/v, momentum v, the mixed master)
        inherits the param specs, scalar-stacked leaves (adam's step
        counter) stay ensemble-only — no per-optimizer plumbing."""
        from jax.sharding import PartitionSpec as P
        pspecs = self.param_specs()
        ptree = jax.tree_util.tree_structure(stacked)

        def is_params(node):
            return jax.tree_util.tree_structure(node) == ptree

        return jax.tree_util.tree_map(
            lambda node: pspecs if is_params(node) else P("ensemble"),
            opt_state, is_leaf=is_params)

    def _shardings(self, spec_tree):
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), spec_tree,
            is_leaf=lambda x: isinstance(x, PartitionSpec))

    def param_shardings(self):
        return self._shardings(self.param_specs())

    def state_shardings(self, opt_state, stacked):
        return self._shardings(self.state_specs(opt_state, stacked))

    def put(self, stacked, opt_state):
        """Place padded stacked params + optimizer state shard-aligned."""
        return (jax.device_put(stacked, self.param_shardings()),
                jax.device_put(opt_state,
                               self.state_shardings(opt_state, stacked)))

    def table_bytes_per_device(self, precision: str = "f32") -> int:
        return table_param_bytes(self.spec, self.bags, precision) // self.d


# ---------------------------------------------------------- local compute
def _gather_rows(tabs: List, gcat, cards: List[int], vs: List[int], me):
    """[N, C, ...] masked local lookups of the all-gathered global bin
    indices: rows owned by this shard keep their values, foreign rows are
    zero — exactly one nonzero contributor per (row, column) across the
    axis, so a psum/psum_scatter reconstructs the replicated gather
    bitwise.  Clips use the TRUE cardinality: padded rows never load."""
    outs = []
    for i, t in enumerate(tabs):
        gi = jnp.clip(gcat[:, i], 0, cards[i] - 1)
        rel = gi - me * vs[i]
        ok = (rel >= 0) & (rel < vs[i])
        rows = t[jnp.clip(rel, 0, vs[i] - 1)]
        mask = ok[:, None] if rows.ndim == 2 else ok
        outs.append(jnp.where(mask, rows, jnp.zeros_like(rows)))
    return jnp.stack(outs, axis=1)


def _local_forward_logits(lp, spec, cards, vs, x_num, gcat):
    """forward_logits against row-sharded tables, from INSIDE shard_map:
    ``x_num`` is this device's row block, ``gcat`` the all-gathered
    [N, C] indices.  Touched rows move through one tiled psum_scatter per
    side; the dense half is the replicated gather lowering's own code
    (``forward_logits_gathered``), so the arithmetic matches bit for
    bit."""
    me = jax.lax.axis_index(_AXIS)
    emb = None
    wide_rows = None
    if spec.deep_enable:
        emb = jax.lax.psum_scatter(
            _gather_rows(lp["embed"], gcat, cards, vs, me), _AXIS,
            scatter_dimension=0, tiled=True)
    if spec.wide_enable:
        wide_rows = jax.lax.psum_scatter(
            _gather_rows(lp["wide_cat"], gcat, cards, vs, me), _AXIS,
            scatter_dimension=0, tiled=True)
    return wdl_model.forward_logits_gathered(lp, spec, x_num, emb,
                                             wide_rows)


def _psum_dense(grads: Dict, axis: str = _AXIS) -> Dict:
    """Sum the REPLICATED leaves' per-device partial grads.  Table shards
    skip this: the psum_scatter transpose already delivered every row's
    complete gradient to its owner."""
    out = dict(grads)
    for k, v in grads.items():
        if k in ("embed", "wide_cat"):
            continue
        out[k] = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, axis), v)
    return out


def _member_data_loss(lp, spec, cards, vs, x_num, gcat, yb, mw, inv_den):
    """This device's share of one member's weighted BCE (NO psum — see
    the module docstring; the caller psums the value for reporting and
    the dense grads after ``jax.grad``).  ``inv_den`` is the global
    ``1/max(sum w, 1e-9)``, parameter-free, computed outside the grad."""
    logit = _local_forward_logits(lp, spec, cards, vs, x_num, gcat)
    p = jax.nn.sigmoid(logit)
    per = wdl_model.per_row_bce(p, yb[:, None])
    return (per * mw).sum() * inv_den


def _member_loss_sum(lp, spec, cards, vs, x_num, gcat, yb, mw):
    """Streamed-path local weighted-SUM loss (normalization happens in
    apply_update, as in the replicated ``_loss_sum``)."""
    logit = _local_forward_logits(lp, spec, cards, vs, x_num, gcat)
    p = jax.nn.sigmoid(logit)
    return (wdl_model.per_row_bce(p, yb[:, None]) * mw).sum()


def _member_eval_sums(lp, spec, cards, vs, x_num, gcat, yb, mw, vw):
    """[4] global (train num, train wsum, valid num, valid wsum) — one
    forward for both masks, one psum on the stacked sums."""
    logit = _local_forward_logits(lp, spec, cards, vs, x_num, gcat)
    p = jax.nn.sigmoid(logit)
    per = wdl_model.per_row_bce(p, yb[:, None])
    s = jnp.stack([(per * mw).sum(), mw.sum(),
                   (per * vw).sum(), vw.sum()])
    return jax.lax.psum(s, _AXIS)


def _make_member_update(spec, cards, vs, opt, precision: str, l2: float):
    def member_update(lp, lo, x_num, gcat, yb, mw, inv_den):
        loss, grads = jax.value_and_grad(_member_data_loss)(
            lp, spec, cards, vs, x_num, gcat, yb, mw, inv_den)
        grads = _psum_dense(grads)
        if l2:
            # the in-RAM weighted_loss's L2 term, applied analytically
            # AFTER the dense psum (in-loss L2 would be psummed D times);
            # the factor-2 reassociation is exact, so this stays bitwise
            grads = jax.tree_util.tree_map(
                jnp.add, grads, wdl_model.l2_grads(lp, l2))
        if precision == "mixed":
            lp, lo = mixed_apply(opt, grads, lo)
            return lp, lo, loss
        delta, lo = opt.update(grads, lo, lp)
        lp = jax.tree_util.tree_map(
            lambda p, d: p + d.astype(p.dtype), lp, delta)
        return lp, lo, loss
    return member_update


# ----------------------------------------------------- trainer executables
def build_inram_fns(plane: WDLShardPlane, stacked, opt_state, opt,
                    precision: str, l2: float) -> Dict[str, Any]:
    """The in-RAM trainer's sharded executables: ``step`` (full batch),
    ``epoch_steps`` (lax.scan over pre-batched [n_batches, bs_local]
    blocks by permuted batch id) and ``eval_errors``.  Same call shapes
    as the replicated ones apart from eval taking the data planes as
    explicit args (shard_map cannot close over sharded arrays)."""
    from jax.sharding import PartitionSpec as P
    mesh, spec = plane.mesh, plane.spec
    cards, vs = plane.cards, plane.vs
    member_update = _make_member_update(spec, cards, vs, opt, precision, l2)
    pspecs = plane.param_specs()
    ospecs = plane.state_specs(opt_state, stacked)

    def step_local(st, os_, xn, xc, yb, tw):
        gcat = jax.lax.all_gather(xc, _AXIS, axis=0, tiled=True)
        den = jax.lax.psum(tw.sum(axis=1), _AXIS)
        inv = 1.0 / jnp.maximum(den, 1e-9)
        st, os_, losses = jax.vmap(
            member_update, in_axes=(0, 0, None, None, None, 0, 0))(
            st, os_, xn, gcat, yb, tw, inv)
        # the DATA loss only — same semantics as the replicated
        # member_update, which applies L2 analytically after the grad
        losses = jax.lax.psum(losses, _AXIS)
        return st, os_, losses

    step = obs.costed_jit("wdl.shard_step", _shard_map(
        step_local, mesh,
        in_specs=(pspecs, ospecs, P(_AXIS, None), P(_AXIS, None),
                  P(_AXIS), P("ensemble", _AXIS)),
        out_specs=(pspecs, ospecs, P("ensemble"))))

    def epoch_local(st, os_, xn3, xc3, y3, tw3, border):
        def body(carry, bi):
            st, os_ = carry
            xnb, xcb, yb, twb = xn3[bi], xc3[bi], y3[bi], tw3[:, bi]
            gcat = jax.lax.all_gather(xcb, _AXIS, axis=0, tiled=True)
            den = jax.lax.psum(twb.sum(axis=1), _AXIS)
            inv = 1.0 / jnp.maximum(den, 1e-9)
            st, os_, _ = jax.vmap(
                member_update, in_axes=(0, 0, None, None, None, 0, 0))(
                st, os_, xnb, gcat, yb, twb, inv)
            return (st, os_), None
        (st, os_), _ = jax.lax.scan(body, (st, os_), border)
        return st, os_

    epoch_steps = obs.costed_jit("wdl.shard_epoch_steps", _shard_map(
        epoch_local, mesh,
        in_specs=(pspecs, ospecs, P(None, _AXIS, None),
                  P(None, _AXIS, None), P(None, _AXIS),
                  P("ensemble", None, _AXIS), P(None)),
        out_specs=(pspecs, ospecs)))

    def eval_local(st, tw, vw, xn, xc, yv):
        gcat = jax.lax.all_gather(xc, _AXIS, axis=0, tiled=True)

        def one(lp, mw):
            logit = _local_forward_logits(lp, spec, cards, vs, xn, gcat)
            p = jax.nn.sigmoid(logit)
            per = wdl_model.per_row_bce(p, yv[:, None])
            num = jax.lax.psum((per * mw).sum(), _AXIS)
            den = jax.lax.psum(mw.sum(), _AXIS)
            return num / jnp.maximum(den, 1e-9)
        return jax.vmap(one)(st, tw), jax.vmap(one)(st, vw)

    eval_errors = obs.costed_jit("wdl.shard_eval", _shard_map(
        eval_local, mesh,
        in_specs=(pspecs, P("ensemble", _AXIS), P("ensemble", _AXIS),
                  P(_AXIS, None), P(_AXIS, None), P(_AXIS)),
        out_specs=(P("ensemble"), P("ensemble"))))

    return {"step": step, "epoch_steps": epoch_steps,
            "eval_errors": eval_errors}


def build_streamed_fns(plane: WDLShardPlane, stacked, opt_state, opt,
                       precision: str, l2: float) -> Dict[str, Any]:
    """The streamed trainer's sharded executables: per-window grad+stat
    accumulation, eval-only window sweep, and the end-of-epoch sharded
    apply (normalize, L2, optimizer step — all on local rows only)."""
    from jax.sharding import PartitionSpec as P
    mesh, spec = plane.mesh, plane.spec
    cards, vs = plane.cards, plane.vs
    pspecs = plane.param_specs()
    ospecs = plane.state_specs(opt_state, stacked)

    def gew_local(st, gacc, sacc, xn, xc, yb, tw, vw):
        gcat = jax.lax.all_gather(xc, _AXIS, axis=0, tiled=True)

        def one(lp, mw, vwm):
            grads = jax.grad(_member_loss_sum)(
                lp, spec, cards, vs, xn, gcat, yb, mw)
            grads = _psum_dense(grads)
            return grads, _member_eval_sums(lp, spec, cards, vs, xn, gcat,
                                            yb, mw, vwm)
        grads, stats = jax.vmap(one)(st, tw, vw)
        gacc = jax.tree_util.tree_map(jnp.add, gacc, grads)
        return gacc, sacc + stats

    grad_eval_window = obs.costed_jit(
        "wdl.shard_grad_eval_window", _shard_map(
            gew_local, mesh,
            in_specs=(pspecs, pspecs, P("ensemble", None), P(_AXIS, None),
                      P(_AXIS, None), P(_AXIS), P("ensemble", _AXIS),
                      P("ensemble", _AXIS)),
            out_specs=(pspecs, P("ensemble", None))))

    def ew_local(st, sacc, xn, xc, yb, tw, vw):
        gcat = jax.lax.all_gather(xc, _AXIS, axis=0, tiled=True)
        stats = jax.vmap(lambda lp, mw, vwm: _member_eval_sums(
            lp, spec, cards, vs, xn, gcat, yb, mw, vwm))(st, tw, vw)
        return sacc + stats

    eval_window = obs.costed_jit("wdl.shard_eval_window", _shard_map(
        ew_local, mesh,
        in_specs=(pspecs, P("ensemble", None), P(_AXIS, None),
                  P(_AXIS, None), P(_AXIS), P("ensemble", _AXIS),
                  P("ensemble", _AXIS)),
        out_specs=P("ensemble", None)))

    def au_local(st, os_, gacc, wsum):
        def one(lp, lo, g, ws):
            inv = 1.0 / jnp.maximum(ws, 1e-9)
            g = jax.tree_util.tree_map(lambda a: a * inv, g)
            if l2:
                g = jax.tree_util.tree_map(
                    jnp.add, g, wdl_model.l2_grads(lp, l2))
            if precision == "mixed":
                return mixed_apply(opt, g, lo)
            delta, lo = opt.update(g, lo, lp)
            lp = jax.tree_util.tree_map(
                lambda p, d: p + d.astype(p.dtype), lp, delta)
            return lp, lo
        return jax.vmap(one)(st, os_, gacc, wsum)

    apply_update = obs.costed_jit("wdl.shard_apply_update", _shard_map(
        au_local, mesh,
        in_specs=(pspecs, ospecs, pspecs, P("ensemble")),
        out_specs=(pspecs, ospecs)))

    return {"grad_eval_window": grad_eval_window,
            "eval_window": eval_window, "apply_update": apply_update}


# -------------------------------------------------------------- telemetry
def _register_cost_models() -> None:
    """Analytic roofline entries for the shard_map executables XLA's cost
    analysis cannot attribute (same contract as ``pallas.tree_traverse``):
    per-call totals across members and devices."""
    def sparse_gather(rows=0, cols=0, embed=0, members=1, devices=1,
                      bytes_per=4):
        touched = float(rows) * cols * (embed + 1) * members
        # index all_gather (4B ints) + table reads + psum-scatter traffic
        return {"flops": 2.0 * touched,
                "bytes_accessed": float(rows) * cols * 4 * devices
                + 2.0 * touched * bytes_per}

    def shard_update(table_elems=0, members=1, steps=1, bytes_per=4):
        # adam-shaped bound: ~10 flops/elem, p+m+v read and written once
        elems = float(table_elems) * members * steps
        return {"flops": 10.0 * elems,
                "bytes_accessed": 6.0 * elems * bytes_per}

    obs.register_cost_model("wdl.sparse_gather", sparse_gather)
    obs.register_cost_model("wdl.shard_update", shard_update)


_register_cost_models()


def record_shard_gauges(plane: WDLShardPlane, precision: str,
                        hash_buckets: int = 0, hashed_cols: int = 0) -> None:
    """One-shot setup gauges for the sharded run (no-op when telemetry
    is off — gauge handles are no-op singletons then)."""
    if not obs.enabled():
        return
    obs.gauge("wdl.shard_devices").set(float(plane.d))
    obs.gauge("wdl.shard_table_bytes").set(
        float(plane.table_bytes_per_device(precision)))
    obs.gauge("wdl.hash_buckets").set(float(hash_buckets))
    obs.gauge("wdl.hashed_cols").set(float(hashed_cols))


def record_epoch_launches(plane: WDLShardPlane, rows: int, steps: int,
                          precision: str = "f32") -> None:
    """Attribute one epoch's sparse gathers + sharded updates to the
    analytic cost models (keys are constant per run: one registry entry,
    ``steps`` launches folded into the shape signature)."""
    spec = plane.spec
    bytes_per = 4 if precision == "f32" else 2
    obs.record_model_launch(
        "wdl.sparse_gather", rows=int(rows),
        cols=len(plane.cards),
        embed=spec.embed_dim if spec.deep_enable else 0,
        members=plane.bags, devices=plane.d, bytes_per=bytes_per)
    elems = sum(vp * (spec.embed_dim if spec.deep_enable else 0) + vp
                for vp in plane.vp)
    obs.record_model_launch(
        "wdl.shard_update", table_elems=int(elems), members=plane.bags,
        steps=int(steps), bytes_per=bytes_per)


# ---------------------------------------------------------------- serving
def resolve_serve_mode(spec, params) -> str:
    """Effective serving-copy mode for one loaded WDL model: the knob
    wins; ``auto`` picks the sharded gather on multi-device backends with
    tables past the shard threshold, else the replicated copy."""
    mode = serve_copy_mode()
    if not spec.cat_cardinalities:
        return "full"
    if mode != "auto":
        return mode
    if jax.device_count() > 1 and \
            table_param_bytes(spec) >= shard_min_bytes():
        return "sharded"
    return "full"


def _hot_params(spec, params, k: int):
    """Lossy single-device serving copy: first ``k`` rows exact + ONE
    mean-of-tail fallback row per table.  The classic forward's
    ``clip(idx, 0, V-1)`` then maps every cold id to the fallback row —
    no forward change needed."""
    def squash(t):
        if t.shape[0] <= k + 1:
            return t
        return jnp.concatenate([t[:k], t[k:].mean(axis=0, keepdims=True)])
    out = dict(params)
    if spec.deep_enable:
        out["embed"] = [squash(t) for t in params["embed"]]
    if spec.wide_enable:
        out["wide_cat"] = [squash(t) for t in params["wide_cat"]]
    return out


def build_serve_forward(spec, params):
    """Serving-copy forward for one WDL model, built at scorer-construction
    (= hot-swap) time.  Returns ``(mode, fn)`` where ``fn(x_num, x_cat)
    -> [N, 1] probabilities`` is traceable inside the scorer's AOT jit,
    or ``(mode, None)`` to keep the classic replicated forward.

    ``sharded`` scores against row-sharded table copies with the SAME
    masked-lookup + psum the trainer uses — the batch stays replicated,
    only touched rows move, scores are bitwise the replicated forward's
    (single nonzero psum contribution per row/column), and the lookup
    traces into the padded-bucket executables so the zero-recompile
    contract holds."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mode = resolve_serve_mode(spec, params)
    if mode == "full":
        return mode, None
    if mode == "hot":
        hot = _hot_params(spec, params, max(1, serve_hot_rows()))

        def fwd_hot(x_num, x_cat):
            return wdl_model.forward(hot, spec, x_num, x_cat)
        return mode, fwd_hot

    devs = np.asarray(jax.devices())
    mesh = Mesh(devs, (_AXIS,))
    d = len(devs)
    cards = [int(c) for c in spec.cat_cardinalities]
    vs = [-(-c // d) for c in cards]

    def pad_put(t, vp, spec2):
        extra = vp - t.shape[0]
        if extra:
            t = jnp.pad(jnp.asarray(t),
                        [(0, extra)] + [(0, 0)] * (t.ndim - 1))
        return jax.device_put(t, NamedSharding(mesh, spec2))

    dense = {k: v for k, v in params.items()
             if k not in ("embed", "wide_cat")}
    embed_s = wide_s = None
    if spec.deep_enable:
        embed_s = [pad_put(t, v * d, P(_AXIS, None))
                   for t, v in zip(params["embed"], vs)]
    if spec.wide_enable:
        wide_s = [pad_put(t, v * d, P(_AXIS))
                  for t, v in zip(params["wide_cat"], vs)]

    def lookup_local(tabs, xc):
        me = jax.lax.axis_index(_AXIS)
        return jax.lax.psum(_gather_rows(tabs, xc, cards, vs, me), _AXIS)

    n_tab = len(cards)
    emb_fn = _shard_map(lookup_local, mesh,
                        in_specs=([P(_AXIS, None)] * n_tab, P(None, None)),
                        out_specs=P(None, None, None))
    wide_fn = _shard_map(lookup_local, mesh,
                         in_specs=([P(_AXIS)] * n_tab, P(None, None)),
                         out_specs=P(None, None))

    def fwd_sharded(x_num, x_cat):
        emb = emb_fn(embed_s, x_cat) if embed_s is not None else None
        wr = wide_fn(wide_s, x_cat) if wide_s is not None else None
        logit = wdl_model.forward_logits_gathered(dense, spec, x_num,
                                                  emb, wr)
        return jax.nn.sigmoid(logit)

    if obs.enabled():
        obs.gauge("wdl.serve_shard_devices").set(float(d))
    return mode, fwd_sharded
