"""Distributed NN/LR ensemble trainer — the Guagua BSP loop + bagging job
fan-out as ONE jitted SPMD program.

Reference mapping:
- Guagua iteration (workers sum gradients over their shard → master applies
  ``Weight`` update → broadcast): one full-batch jitted step over a row-
  sharded dataset; XLA's psum over the ``data`` mesh axis IS the master
  accumulate (``NNMaster.java:207-319``, ``AbstractNNWorker.java:521-588``).
- N bagging / k-fold / grid-like jobs (``TrainModelProcessor.java:684-945``):
  ensemble members stacked on a leading axis, trained by ``vmap`` and sharded
  over the ``ensemble`` mesh axis — every "job" advances each step.
- Full-batch per epoch matches the reference exactly (each Guagua iteration
  consumes every row once; RPROP — their default — requires it).  An optional
  mini-batch mode serves ADAM-style rules.
- Early stop windows, LR decay, per-epoch progress lines, and tmp-model
  checkpoints mirror ``NNMaster``/``NNOutput`` behavior host-side.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..models import nn as nn_model
from ..parallel import mesh as meshlib
from .early_stop import WindowEarlyStop
from .optimizers import make_optimizer

log = logging.getLogger(__name__)


@dataclass
class TrainSettings:
    optimizer: str = "R"               # reference default Propagation=R (RPROP)
    learning_rate: float = 0.1
    learning_decay: float = 0.0        # per-epoch multiplicative decay
    l2: float = 0.0
    l1: float = 0.0
    dropout_rate: float = 0.0
    epochs: int = 100
    batch_size: int = 0                # 0 = full batch (reference semantics)
    early_stop_window: int = 0         # 0 = disabled
    weight_initializer: str = "xavier"
    seed: int = 0
    tmp_model_every: int = 0           # epochs between tmp-model checkpoints
    checkpoint_dir: str = ""           # "" disables trainer-state checkpoints
    checkpoint_every: int = 25
    resume: bool = False               # restore latest trainer state
    opt_kwargs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class EnsembleResult:
    params: List[Any]                  # per-member best params (unstacked, host)
    train_errors: np.ndarray           # [bags] at best epoch
    valid_errors: np.ndarray           # [bags]
    epochs_run: int
    history: List[Tuple[float, float]]  # per-epoch (mean train, mean valid)


ProgressFn = Callable[[int, float, float], None]


def _stack(trees: List[Any]):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _unstack(tree, n: int) -> List[Any]:
    host = jax.tree_util.tree_map(np.asarray, tree)
    return [jax.tree_util.tree_map(lambda a: a[i], host) for i in range(n)]


def train_ensemble(x: np.ndarray, y: np.ndarray,
                   train_w: np.ndarray, valid_w: np.ndarray,
                   spec: nn_model.NNModelSpec,
                   settings: TrainSettings,
                   init_params_list: Optional[List[Any]] = None,
                   progress: Optional[ProgressFn] = None,
                   checkpoint: Optional[Callable[[int, List[Any]], None]] = None,
                   mesh=None) -> EnsembleResult:
    """Train ``B`` members; ``train_w``/``valid_w`` are ``[B, N]`` per-row
    weight matrices (bagging/fold masks × data weights)."""
    bags = train_w.shape[0]
    n = x.shape[0]
    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=bags)
    data_size = mesh.shape["data"]
    x, y, train_w, valid_w = _pad_all(x, y, train_w, valid_w, data_size)

    key = jax.random.PRNGKey(settings.seed)
    if init_params_list is None:
        keys = jax.random.split(key, bags)
        init_params_list = [nn_model.init_params(k, spec, settings.weight_initializer)
                            for k in keys]
    opt = make_optimizer(settings.optimizer, settings.learning_rate,
                         **settings.opt_kwargs)
    stacked = _stack(init_params_list)
    opt_state = _stack([opt.init(p) for p in init_params_list])

    from jax.sharding import NamedSharding, PartitionSpec as P
    sh_ens = NamedSharding(mesh, P("ensemble"))
    stacked = jax.device_put(stacked, sh_ens)
    opt_state = jax.device_put(opt_state, sh_ens)
    xd = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    twd = jax.device_put(train_w, NamedSharding(mesh, P("ensemble", "data")))
    vwd = jax.device_put(valid_w, NamedSharding(mesh, P("ensemble", "data")))

    dropout = settings.dropout_rate

    def member_update(params, opt_state, xb, yb, mw, rng, lr_scale):
        loss, grads = jax.value_and_grad(nn_model.weighted_loss)(
            params, spec, xb, yb[:, None], mw,
            l2=settings.l2, l1=settings.l1,
            dropout_rate=dropout, rng=rng if dropout > 0 else None)
        delta, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, d: p + d * lr_scale,
                                        params, delta)
        return params, opt_state, loss

    @jax.jit
    def step(stacked, opt_state, xb, yb, tw, rngs, lr_scale):
        return jax.vmap(member_update, in_axes=(0, 0, None, None, 0, 0, None))(
            stacked, opt_state, xb, yb, tw, rngs, lr_scale)

    @jax.jit
    def eval_errors(stacked, tw, vw):
        def one(params, mw):
            pred = nn_model.forward(params, spec, xd)
            lfn = nn_model.LOSSES.get(spec.loss, nn_model.LOSSES["squared"])
            per_row = lfn(pred, yd[:, None]).sum(axis=-1)
            return (per_row * mw).sum() / jnp.maximum(mw.sum(), 1e-9)
        return jax.vmap(one)(stacked, tw), jax.vmap(one)(stacked, vw)

    bs = settings.batch_size
    if bs:
        bs = max(bs - bs % data_size, data_size)
        # pad rows to a batch multiple so the tail is never dropped;
        # padded rows carry zero weight
        x, y, train_w, valid_w = _pad_all(
            np.asarray(xd), np.asarray(yd), np.asarray(twd), np.asarray(vwd), bs)
        xd = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        yd = jax.device_put(y, NamedSharding(mesh, P("data")))
        twd = jax.device_put(train_w, NamedSharding(mesh, P("ensemble", "data")))
        vwd = jax.device_put(valid_w, NamedSharding(mesh, P("ensemble", "data")))

    stops = [WindowEarlyStop(settings.early_stop_window) for _ in range(bags)]
    best_valid = np.full(bags, np.inf)
    best_train = np.full(bags, np.inf)
    best_params: List[Any] = [None] * bags
    history: List[Tuple[float, float]] = []
    lr_scale = 1.0
    epochs_run = 0
    tr = va = np.zeros(bags)

    start_epoch = 0
    if settings.resume and settings.checkpoint_dir:
        from . import checkpoint as ckpt
        restored = ckpt.restore_state(settings.checkpoint_dir,
                                      (stacked, opt_state, key))
        if restored is not None:
            start_epoch, (st_h, os_h, key_h) = restored
            stacked = jax.device_put(st_h, sh_ens)
            opt_state = jax.device_put(os_h, sh_ens)
            key = jnp.asarray(key_h)
            lr_scale = (1.0 - settings.learning_decay) ** start_epoch \
                if settings.learning_decay > 0 else 1.0
            log.info("resumed trainer state at epoch %d", start_epoch)

    n_padded = xd.shape[0]
    for epoch in range(start_epoch, settings.epochs):
        key, sub = jax.random.split(key)
        rngs = jax.random.split(sub, bags)
        if bs and bs < n_padded:
            for bi, start in enumerate(range(0, n_padded - bs + 1, bs)):
                xb = jax.lax.slice_in_dim(xd, start, start + bs, axis=0)
                yb = jax.lax.slice_in_dim(yd, start, start + bs, axis=0)
                twb = jax.lax.slice_in_dim(twd, start, start + bs, axis=1)
                rngs_b = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    rngs, bi) if dropout > 0 else rngs
                stacked, opt_state, _ = step(stacked, opt_state, xb, yb, twb,
                                             rngs_b, lr_scale)
        else:
            stacked, opt_state, _ = step(stacked, opt_state, xd, yd, twd,
                                         rngs, lr_scale)
        tr, va = eval_errors(stacked, twd, vwd)
        tr, va = np.asarray(tr), np.asarray(va)
        history.append((float(tr.mean()), float(va.mean())))
        epochs_run = epoch + 1

        improved = np.flatnonzero(va < best_valid)
        if improved.size:
            host = jax.tree_util.tree_map(np.asarray, stacked)
            for i in improved:
                best_valid[i], best_train[i] = va[i], tr[i]
                best_params[i] = jax.tree_util.tree_map(lambda a: a[i].copy(), host)
        if progress:
            progress(epoch, float(tr.mean()), float(va.mean()))
        if checkpoint and settings.tmp_model_every and \
                (epoch + 1) % settings.tmp_model_every == 0:
            checkpoint(epoch, _unstack(stacked, bags))
        if settings.checkpoint_dir and settings.checkpoint_every and \
                (epoch + 1) % settings.checkpoint_every == 0:
            from . import checkpoint as ckpt
            ckpt.save_state(settings.checkpoint_dir, epoch + 1,
                            (jax.tree_util.tree_map(np.asarray, stacked),
                             jax.tree_util.tree_map(np.asarray, opt_state),
                             np.asarray(key)))
        if settings.learning_decay > 0:
            lr_scale *= (1.0 - settings.learning_decay)
        if settings.early_stop_window > 0:
            # evaluate every member's window (no short-circuit: the stop
            # counters must advance uniformly) then stop when all agree
            flags = [s.should_stop(float(v)) for s, v in zip(stops, va)]
            if all(flags):
                log.info("early stop at epoch %d (window %d)", epoch,
                         settings.early_stop_window)
                break

    final = jax.tree_util.tree_map(np.asarray, stacked)
    for i in range(bags):
        if best_params[i] is None:
            best_params[i] = jax.tree_util.tree_map(lambda a: a[i], final)
            best_valid[i], best_train[i] = float(va[i]), float(tr[i])
    return EnsembleResult(params=best_params, train_errors=best_train,
                          valid_errors=best_valid, epochs_run=epochs_run,
                          history=history)


def _pad_all(x, y, train_w, valid_w, multiple):
    extra = meshlib.pad_rows(x.shape[0], multiple)
    if extra:
        x = np.concatenate([x, np.zeros((extra, x.shape[1]), x.dtype)])
        y = np.concatenate([y, np.zeros(extra, y.dtype)])
        zpad = np.zeros((train_w.shape[0], extra), train_w.dtype)
        train_w = np.concatenate([train_w, zpad], axis=1)
        valid_w = np.concatenate([valid_w, zpad], axis=1)
    return x, y, train_w, valid_w
