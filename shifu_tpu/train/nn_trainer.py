"""Distributed NN/LR ensemble trainer — the Guagua BSP loop + bagging job
fan-out as ONE jitted SPMD program.

Reference mapping:
- Guagua iteration (workers sum gradients over their shard → master applies
  ``Weight`` update → broadcast): one full-batch jitted step over a row-
  sharded dataset; XLA's psum over the ``data`` mesh axis IS the master
  accumulate (``NNMaster.java:207-319``, ``AbstractNNWorker.java:521-588``).
- N bagging / k-fold / grid-like jobs (``TrainModelProcessor.java:684-945``):
  ensemble members stacked on a leading axis, trained by ``vmap`` and sharded
  over the ``ensemble`` mesh axis — every "job" advances each step.
- Full-batch per epoch matches the reference exactly (each Guagua iteration
  consumes every row once; RPROP — their default — requires it).  An optional
  mini-batch mode serves ADAM-style rules.
- Early stop windows, LR decay, per-epoch progress lines, and tmp-model
  checkpoints mirror ``NNMaster``/``NNOutput`` behavior host-side.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from functools import lru_cache, partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..models import nn as nn_model
from ..parallel import mesh as meshlib
from .early_stop import WindowEarlyStop
from .optimizers import (cast_tree, make_optimizer, mixed_apply,
                         mixed_init, resolve_precision)

log = logging.getLogger(__name__)


@dataclass
class TrainSettings:
    optimizer: str = "R"               # reference default Propagation=R (RPROP)
    learning_rate: float = 0.1
    learning_decay: float = 0.0        # per-epoch multiplicative decay
    l2: float = 0.0
    l1: float = 0.0
    dropout_rate: float = 0.0
    epochs: int = 100
    batch_size: int = 0                # 0 = full batch (reference semantics)
    early_stop_window: int = 0         # 0 = disabled
    weight_initializer: str = "xavier"
    seed: int = 0
    tmp_model_every: int = 0           # epochs between tmp-model checkpoints
    checkpoint_dir: str = ""           # "" disables trainer-state checkpoints
    checkpoint_every: int = 25
    resume: bool = False               # restore latest trainer state
    resume_extra: int = 0              # refresh warm-start: train N MORE
                                       # epochs past the restored state
                                       # (0 = plain resume, keep budget)
    fixed_layers: Tuple[int, ...] = () # 1-based layer ids frozen during
    fixed_bias: bool = False           # continuous training (NNMaster
    matmul_precision: str = ""         # FIXED_LAYERS); ""=backend default,
    precision: str = ""                # bfloat16=MXU.  precision: f32|
    opt_kwargs: Dict[str, Any] = field(default_factory=dict)  # bf16|mixed
                                       # ("" = shifu.train.precision)


def _resume_epoch_target(settings: "TrainSettings", start_epoch: int,
                         stops) -> int:
    """Epoch budget after a checkpoint restore.  A refresh warm-start
    (``resume_extra`` > 0) trains that many MORE epochs past the
    restored state — and re-opens the early-stop patience, because a
    stopper that tripped on the OLD distribution must not veto learning
    the new data window (best-model tracking still carries over).  A
    plain crash resume (``resume_extra`` == 0) keeps the original
    budget and stop state untouched."""
    if settings.resume_extra <= 0:
        return settings.epochs
    for s in stops:
        s.since_best = 0
    return start_epoch + settings.resume_extra


@dataclass
class EnsembleResult:
    params: List[Any]                  # per-member best params (unstacked, host)
    train_errors: np.ndarray           # [bags] at best epoch
    valid_errors: np.ndarray           # [bags]
    epochs_run: int
    history: List[Tuple[float, float]]  # per-epoch (mean train, mean valid)


ProgressFn = Callable[[int, float, float], None]


def _stack(trees: List[Any]):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


def _gather_np(a) -> np.ndarray:
    """Host copy of a (possibly multi-host) array.  Under multiple
    controllers ``np.asarray`` can only read fully-addressable arrays;
    ``process_allgather`` assembles the global value over the DCN (the
    reference's master-side model collect, ``NNMaster.java:240-286``)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(a, tiled=True))
    return np.asarray(a)


def _pack_leaves_impl(leaves, mesh=None):
    """Flatten a tuple of 4-byte-dtype arrays into ONE f32 vector (bitcast,
    not convert — int leaves round-trip exactly).

    Each flat leaf is constrained to REPLICATED before the concatenate:
    this toolchain's partitioner mis-lowers a concatenate of
    ensemble-sharded flat vectors whose lengths don't divide the mesh —
    the output arrives as UNREDUCED partial sums (every value scaled by
    the data-axis size).  The explicit constraint forces the resharding
    BEFORE the concatenate, where it is a plain allgather."""
    if mesh is not None:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        rep = NamedSharding(mesh, P())
        return jnp.concatenate([
            jax.lax.with_sharding_constraint(
                jax.lax.bitcast_convert_type(l, jnp.float32).reshape(-1),
                rep)
            for l in leaves])
    return jnp.concatenate([
        jax.lax.bitcast_convert_type(l, jnp.float32).reshape(-1)
        for l in leaves])


@lru_cache(maxsize=None)
def _pack_leaves_meshed(mesh):
    """Single-controller packer pinned to ``mesh`` (see the partial-sum
    trap in :func:`_pack_leaves_impl`)."""
    # tiny packed-fetch glue (see _pack_leaves_impl): ~zero FLOPs,
    # shapes keyed by the lru_cache — sanctioned bare jit
    return jax.jit(partial(_pack_leaves_impl, mesh=mesh))  # shifu-lint: disable=recompile-hazard


_pack_leaves = jax.jit(_pack_leaves_impl)  # shifu-lint: disable=recompile-hazard


@lru_cache(maxsize=None)
def _pack_leaves_replicated(mesh):
    """Multi-controller :func:`_pack_leaves`: the REPLICATED out-sharding
    makes XLA fuse every leaf's cross-host allgather into the one packing
    program, after which each process reads its own addressable copy."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    return jax.jit(partial(_pack_leaves_impl, mesh=mesh),  # shifu-lint: disable=recompile-hazard
                   out_shardings=NamedSharding(mesh, P()))


def _to_host(tree):
    """Host copy of a whole pytree in ONE device fetch.  A per-leaf
    ``np.asarray`` walk costs one transfer per leaf — on a remote-device
    link at ~0.1-0.25 s per transfer, a WDL param tree (per-column
    embedding tables, ~70 leaves) made every epoch's best-params copy
    slower than the epoch's compute.  Leaves pack (bitcast) into one f32
    vector on device and split back on the host; multi-controller runs
    pack through :func:`_pack_leaves_replicated` (one program whose
    output every process holds) instead of the old per-leaf allgather
    walk."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves or any(l.dtype.itemsize != 4 for l in leaves):
        return jax.tree_util.tree_map(_gather_np, tree)
    shardings = [getattr(l, "sharding", None) for l in leaves]
    meshed = (all(hasattr(sh, "mesh") for sh in shardings)
              and len({sh.mesh for sh in shardings}) == 1)
    if jax.process_count() > 1:
        if not meshed:
            # heterogeneous/mesh-less leaves cannot ride one pinned
            # program — keep the conservative per-leaf gather for them
            return jax.tree_util.tree_map(_gather_np, tree)
        flat = np.asarray(
            _pack_leaves_replicated(shardings[0].mesh)(tuple(leaves)))
    elif meshed and shardings[0].mesh.size > 1:
        # mesh-sharded leaves take the constrained packer (see the
        # partial-sum trap in _pack_leaves_impl)
        flat = np.asarray(_pack_leaves_meshed(shardings[0].mesh)(
            tuple(leaves)))
    else:
        flat = np.asarray(_pack_leaves(tuple(leaves)))
    out, off = [], 0
    for l in leaves:
        size = int(np.prod(l.shape)) if l.shape else 1
        part = flat[off:off + size]
        off += size
        out.append(part.view(l.dtype).reshape(l.shape))
    return jax.tree_util.tree_unflatten(treedef, out)


def _unstack(tree, n: int) -> List[Any]:
    host = _to_host(tree)
    return [jax.tree_util.tree_map(lambda a: a[i], host) for i in range(n)]


# -------------------------------------------- trainer-state checkpointing
# The checkpoint must carry MORE than (params, opt_state, key): the final
# model is each member's BEST-epoch params, and early stop is a stateful
# window — dropping either made a resumed run pick a different model than
# the uninterrupted one whenever the global best predated the crash.
def _ckpt_template(stacked, opt_state, key, bags: int):
    zf = np.zeros(bags, np.float64)
    zi = np.zeros(bags, np.int64)
    return (stacked, opt_state, np.asarray(key), zf, zf.copy(), stacked,
            zf.copy(), zi)


def _ckpt_state(stacked, opt_state, key, best_valid, best_train,
                best_params, stops):
    host = _to_host(stacked)
    bp = [p if p is not None
          else jax.tree_util.tree_map(lambda a, i=i: a[i], host)
          for i, p in enumerate(best_params)]
    best_stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs), *bp)
    return (host, _to_host(opt_state), np.asarray(key),
            np.asarray(best_valid, np.float64),
            np.asarray(best_train, np.float64), best_stacked,
            np.asarray([s.best for s in stops], np.float64),
            np.asarray([s.since_best for s in stops], np.int64))


def _restore_tracking(state, best_valid, best_train, best_params,
                      stops) -> None:
    _, _, _, bv, bt, best_stacked, es_b, es_s = state
    best_valid[:] = bv
    best_train[:] = bt
    for i in range(len(best_params)):
        if np.isfinite(bv[i]):
            best_params[i] = jax.tree_util.tree_map(
                lambda a, i=i: a[i].copy(), best_stacked)
    for s, b, n in zip(stops, es_b, es_s):
        s.best = float(b)
        s.since_best = int(n)


def train_ensemble(x: np.ndarray, y: np.ndarray,
                   train_w: np.ndarray, valid_w: np.ndarray,
                   spec: nn_model.NNModelSpec,
                   settings: TrainSettings,
                   init_params_list: Optional[List[Any]] = None,
                   progress: Optional[ProgressFn] = None,
                   checkpoint: Optional[Callable[[int, List[Any]],
                                                 None]] = None,
                   mesh=None,
                   y_members: Optional[np.ndarray] = None,
                   member_hypers: Optional[Dict[str, np.ndarray]] = None
                   ) -> EnsembleResult:
    """See :func:`_train_ensemble_impl`; wraps it in the configured matmul
    precision (bfloat16 inputs with f32 accumulation feed the MXU at full
    rate — the training math stays f32 elsewhere)."""
    if settings.matmul_precision:
        with jax.default_matmul_precision(settings.matmul_precision):
            return _train_ensemble_impl(
                x, y, train_w, valid_w, spec, settings, init_params_list,
                progress, checkpoint, mesh, y_members, member_hypers)
    return _train_ensemble_impl(
        x, y, train_w, valid_w, spec, settings, init_params_list,
        progress, checkpoint, mesh, y_members, member_hypers)


def _train_ensemble_impl(x: np.ndarray, y: np.ndarray,
                   train_w: np.ndarray, valid_w: np.ndarray,
                   spec: nn_model.NNModelSpec,
                   settings: TrainSettings,
                   init_params_list: Optional[List[Any]] = None,
                   progress: Optional[ProgressFn] = None,
                   checkpoint: Optional[Callable[[int, List[Any]], None]] = None,
                   mesh=None,
                   y_members: Optional[np.ndarray] = None,
                   member_hypers: Optional[Dict[str, np.ndarray]] = None
                   ) -> EnsembleResult:
    """Train ``B`` members; ``train_w``/``valid_w`` are ``[B, N]`` per-row
    weight matrices (bagging/fold masks × data weights).

    ``y_members`` ([B, N]) gives each member its OWN target — the one-vs-all
    fan-out (reference ``TrainModelProcessor.java:684-714`` runs one bagging
    job per class; here classes are members on the ensemble axis, trained
    simultaneously as one vmapped program).

    ``member_hypers`` gives each member its OWN scalar hypers ([B] arrays
    under keys ``lr_scale``/``l2``/``l1``/``dropout``) — how same-shape
    grid-search trials train as ONE compiled run instead of the reference's
    queue of jobs (``gs/GridSearch.java:62``)."""
    bags = train_w.shape[0]
    n = x.shape[0]
    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=bags)
    data_size = mesh.shape["data"]
    if y_members is not None:
        # fold the per-member targets through the same row padding as the
        # weights, then restore the shared-y variable for the common path
        x, y, train_w, valid_w, y_members = _pad_all(
            x, y, train_w, valid_w, data_size, y_members)
    else:
        x, y, train_w, valid_w = _pad_all(x, y, train_w, valid_w, data_size)

    key = jax.random.PRNGKey(settings.seed)
    if init_params_list is None:
        keys = jax.random.split(key, bags)
        init_params_list = [nn_model.init_params(k, spec, settings.weight_initializer)
                            for k in keys]
    opt = make_optimizer(settings.optimizer, settings.learning_rate,
                         **settings.opt_kwargs)
    # ---- precision ladder (shifu.train.precision): bf16/mixed cast the
    # training params narrow; mixed keeps the f32 master in the opt state
    precision = resolve_precision(settings.precision)
    if precision != "f32":
        init_params_list = [cast_tree(p, jnp.bfloat16)
                            for p in init_params_list]
    stacked = _stack(init_params_list)
    if precision == "mixed":
        opt_state = _stack([mixed_init(opt, p) for p in init_params_list])
    else:
        opt_state = _stack([opt.init(p) for p in init_params_list])

    from jax.sharding import NamedSharding, PartitionSpec as P
    sh_ens = NamedSharding(mesh, P("ensemble"))
    stacked = jax.device_put(stacked, sh_ens)
    opt_state = jax.device_put(opt_state, sh_ens)
    xd = jax.device_put(x, NamedSharding(mesh, P("data", None)))
    yd = jax.device_put(y, NamedSharding(mesh, P("data")))
    twd = jax.device_put(train_w, NamedSharding(mesh, P("ensemble", "data")))
    vwd = jax.device_put(valid_w, NamedSharding(mesh, P("ensemble", "data")))
    ymd = None if y_members is None else jax.device_put(
        y_members, NamedSharding(mesh, P("ensemble", "data")))

    # per-member hyper rows [B, 4]: lr_scale, l2, l1, dropout — uniform from
    # settings unless stacked grid trials supplied their own
    if member_hypers is None:
        hyp = np.tile(np.asarray(
            [[1.0, settings.l2, settings.l1, settings.dropout_rate]],
            np.float32), (bags, 1))
    else:
        hyp = np.stack([
            np.asarray(member_hypers.get("lr_scale", np.ones(bags)),
                       np.float32),
            np.asarray(member_hypers.get("l2", np.full(bags, settings.l2)),
                       np.float32),
            np.asarray(member_hypers.get("l1", np.full(bags, settings.l1)),
                       np.float32),
            np.asarray(member_hypers.get(
                "dropout", np.full(bags, settings.dropout_rate)),
                np.float32)], axis=1)
    dropout = float(hyp[:, 3].max())       # static gate: any member drops?
    uniform = member_hypers is None
    hd = jax.device_put(hyp, sh_ens)

    fixed = set(settings.fixed_layers)

    def _freeze(delta):
        """Zero deltas of fixed layers (reference FIXED_LAYERS /
        FIXED_BIAS: frozen weights during continuous training; 1-based
        layer ids)."""
        if not fixed:
            return delta
        return [dl if (li + 1) not in fixed else
                {"w": jnp.zeros_like(dl["w"]),
                 "b": jnp.zeros_like(dl["b"]) if settings.fixed_bias
                 else dl["b"]}
                for li, dl in enumerate(delta)]

    def member_update(params, opt_state, xb, yb, mw, rng, h, lr_scale):
        loss, grads = jax.value_and_grad(nn_model.weighted_loss)(
            params, spec, xb, yb[:, None], mw,
            l2=settings.l2 if uniform else h[1],
            l1=settings.l1 if uniform else h[2],
            dropout_rate=settings.dropout_rate if uniform else h[3],
            rng=rng if dropout > 0 else None)
        if precision == "mixed":
            # bf16 grads widen once; the rule steps the f32 master and
            # the bf16 training copy is one rounding of it
            params, opt_state = mixed_apply(opt, grads, opt_state,
                                            scale=lr_scale * h[0],
                                            freeze=_freeze)
            return params, opt_state, loss
        delta, opt_state = opt.update(grads, opt_state, params)
        # apply in the PARAM dtype: the f32-strong lr_scale tracer would
        # otherwise silently widen a bf16 ladder back to f32 (no-op for
        # f32 params)
        params = jax.tree_util.tree_map(
            lambda p, d: p + (d * (lr_scale * h[0])).astype(p.dtype),
            params, _freeze(delta))
        return params, opt_state, loss

    y_axis = None if ymd is None else 0    # per-member targets vmap over B

    # cost-attributed entry points: the full-batch step, the scanned
    # epoch sweep and the eval pass are THE nn-plane executables the
    # utilization report joins against the TRAIN span (obs/costs)
    @partial(obs.costed_jit, "nn.step")
    def step(stacked, opt_state, xb, yb, tw, rngs, lr_scale):
        return jax.vmap(member_update,
                        in_axes=(0, 0, None, y_axis, 0, 0, 0, None))(
            stacked, opt_state, xb, yb, tw, rngs, hd, lr_scale)

    @partial(obs.costed_jit, "nn.eval_errors")
    def eval_errors(stacked, tw, vw, xe, ys):
        # data arrays enter as ARGUMENTS: closing over a multi-host-sharded
        # array is an error under multiple controllers
        def one(params, mw, ym):
            pred = nn_model.forward(params, spec, xe)
            per_row = nn_model.per_row_loss(pred, ym[:, None], spec)
            return (per_row * mw).sum() / jnp.maximum(mw.sum(), 1e-9)
        ev = jax.vmap(one, in_axes=(0, 0, y_axis))
        return ev(stacked, tw, ys), ev(stacked, vw, ys)

    bs = settings.batch_size
    if bs:
        bs = max(bs - bs % data_size, data_size)
        # pad rows to a batch multiple so the tail is never dropped;
        # padded rows carry zero weight (_gather_np: a plain np.asarray
        # cannot read cross-host-sharded arrays under multiple controllers)
        if ymd is None:
            x, y, train_w, valid_w = _pad_all(
                _gather_np(xd), _gather_np(yd), _gather_np(twd),
                _gather_np(vwd), bs)
        else:
            x, y, train_w, valid_w, y_members = _pad_all(
                _gather_np(xd), _gather_np(yd), _gather_np(twd),
                _gather_np(vwd), bs, _gather_np(ymd))
            ymd = jax.device_put(y_members,
                                 NamedSharding(mesh, P("ensemble", "data")))
        xd = jax.device_put(x, NamedSharding(mesh, P("data", None)))
        yd = jax.device_put(y, NamedSharding(mesh, P("data")))
        twd = jax.device_put(train_w, NamedSharding(mesh, P("ensemble", "data")))
        vwd = jax.device_put(valid_w, NamedSharding(mesh, P("ensemble", "data")))

    stops = [WindowEarlyStop(settings.early_stop_window) for _ in range(bags)]
    best_valid = np.full(bags, np.inf)
    best_train = np.full(bags, np.inf)
    best_params: List[Any] = [None] * bags
    history: List[Tuple[float, float]] = []
    lr_scale = 1.0
    epochs_run = 0
    tr = va = np.zeros(bags)

    start_epoch = 0
    epochs_target = settings.epochs
    if settings.resume and settings.checkpoint_dir:
        from . import checkpoint as ckpt
        restored = ckpt.restore_state(
            settings.checkpoint_dir,
            _ckpt_template(stacked, opt_state, key, bags),
            expect_precision=precision)
        if restored is not None:
            start_epoch, state = restored
            stacked = jax.device_put(state[0], sh_ens)
            opt_state = jax.device_put(state[1], sh_ens)
            key = jnp.asarray(state[2])
            _restore_tracking(state, best_valid, best_train, best_params,
                              stops)
            lr_scale = (1.0 - settings.learning_decay) ** start_epoch \
                if settings.learning_decay > 0 else 1.0
            epochs_target = _resume_epoch_target(settings, start_epoch,
                                                 stops)
            log.info("resumed trainer state at epoch %d (target %d)",
                     start_epoch, epochs_target)
            if settings.early_stop_window > 0 and \
                    all(s.since_best >= s.window_size for s in stops):
                # the interrupted run had already early-stopped — don't
                # grow past its stop point
                start_epoch = epochs_target

    n_padded = xd.shape[0]

    # batch slicing happens INSIDE jit (dynamic_slice of sharded arrays
    # compiles into the SPMD program); an EAGER lax.slice on sharded inputs
    # does ad-hoc device-to-device copies the XLA:CPU runtime has been seen
    # to SIGABRT on
    def step_batch(stacked, opt_state, start, rngs, lr_scale, blen: int,
                   xe, ye, twe):
        xb = jax.lax.dynamic_slice_in_dim(xe, start, blen, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(ye, start, blen, axis=0) \
            if ymd is None else \
            jax.lax.dynamic_slice_in_dim(ye, start, blen, axis=1)
        twb = jax.lax.dynamic_slice_in_dim(twe, start, blen, axis=1)
        return jax.vmap(member_update,
                        in_axes=(0, 0, None, y_axis, 0, 0, 0, None))(
            stacked, opt_state, xb, yb, twb, rngs, hd, lr_scale)

    @partial(obs.costed_jit, "nn.epoch_steps",
             static_argnames=("blen", "n_b"))
    def epoch_steps(stacked, opt_state, rngs, lr_scale, xe, ye, twe,
                    blen: int, n_b: int):
        """A whole epoch's minibatch sweep as ONE executable (lax.scan over
        batches) — the per-batch dispatch loop costs one program execution
        per batch, which dominates wall-clock on a remote-device link."""
        def body(carry, bi):
            st, os_ = carry
            rngs_b = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                rngs, bi) if dropout > 0 else rngs
            st, os_, _ = step_batch(st, os_, bi * blen, rngs_b, lr_scale,
                                    blen, xe, ye, twe)
            return (st, os_), None
        (st, os_), _ = jax.lax.scan(body, (stacked, opt_state),
                                    jnp.arange(n_b, dtype=jnp.int32))
        return st, os_

    obs_on = obs.enabled()
    for epoch in range(start_epoch, epochs_target):
        ep_t0 = time.perf_counter()
        key, sub = jax.random.split(key)
        rngs = jax.random.split(sub, bags)
        if bs and bs < n_padded:
            stacked, opt_state = epoch_steps(
                stacked, opt_state, rngs, lr_scale, xd,
                yd if ymd is None else ymd, twd, bs,
                (n_padded - bs) // bs + 1)
        else:
            stacked, opt_state, _ = step(stacked, opt_state, xd,
                                         yd if ymd is None else ymd, twd,
                                         rngs, lr_scale)
        tr, va = eval_errors(stacked, twd, vwd, xd,
                             yd if ymd is None else ymd)
        tr, va = _gather_np(jnp.stack([tr, va]))       # one fetch
        history.append((float(tr.mean()), float(va.mean())))
        epochs_run = epoch + 1
        if obs_on:
            # host-side per-epoch metrics: the _gather_np fetch above IS
            # the value-forcing sync, so the wall-clock covers real work
            dt = time.perf_counter() - ep_t0
            obs.counter("train.epochs").inc()
            obs.histogram("train.epoch_s").observe(dt)
            obs.gauge("train.valid_err").set(float(va.mean()))
            obs.event("epoch", trainer="nn", epoch=epoch,
                      train_err=round(float(tr.mean()), 6),
                      valid_err=round(float(va.mean()), 6), rows=n,
                      rows_per_sec=round(n / max(dt, 1e-9), 1))

        improved = np.flatnonzero(va < best_valid)
        if improved.size:
            host = _to_host(stacked)
            for i in improved:
                best_valid[i], best_train[i] = va[i], tr[i]
                best_params[i] = jax.tree_util.tree_map(lambda a: a[i].copy(), host)
        if progress:
            progress(epoch, float(tr.mean()), float(va.mean()))
        if checkpoint and settings.tmp_model_every and \
                (epoch + 1) % settings.tmp_model_every == 0:
            checkpoint(epoch, _unstack(stacked, bags))
        if settings.learning_decay > 0:
            lr_scale *= (1.0 - settings.learning_decay)
        stop_now = False
        if settings.early_stop_window > 0:
            # evaluate every member's window (no short-circuit: the stop
            # counters must advance uniformly) then stop when all agree
            flags = [s.should_stop(float(v)) for s, v in zip(stops, va)]
            stop_now = all(flags)
        if settings.checkpoint_dir and settings.checkpoint_every and \
                ((epoch + 1) % settings.checkpoint_every == 0 or stop_now):
            # saved AFTER the early-stop windows advanced (and forced on
            # the stop epoch): a resumed run replays the exact stop state
            from . import checkpoint as ckpt
            ckpt.save_state(settings.checkpoint_dir, epoch + 1,
                            _ckpt_state(stacked, opt_state, key,
                                        best_valid, best_train,
                                        best_params, stops),
                            precision=precision)
        if stop_now:
            obs.event("early_stop", trainer="nn", epoch=epoch,
                      window=settings.early_stop_window)
            log.info("early stop at epoch %d (window %d)", epoch,
                     settings.early_stop_window)
            break

    final = _to_host(stacked)
    for i in range(bags):
        if best_params[i] is None:
            best_params[i] = jax.tree_util.tree_map(lambda a: a[i], final)
            best_valid[i], best_train[i] = float(va[i]), float(tr[i])
    return EnsembleResult(params=best_params, train_errors=best_train,
                          valid_errors=best_valid, epochs_run=epochs_run,
                          history=history)


def _pad_all(x, y, train_w, valid_w, multiple, y_members=None):
    extra = meshlib.pad_rows(x.shape[0], multiple)
    if extra:
        x = np.concatenate([x, np.zeros((extra, x.shape[1]), x.dtype)])
        y = np.concatenate([y, np.zeros(extra, y.dtype)])
        zpad = np.zeros((train_w.shape[0], extra), train_w.dtype)
        train_w = np.concatenate([train_w, zpad], axis=1)
        valid_w = np.concatenate([valid_w, zpad], axis=1)
        if y_members is not None:
            y_members = np.concatenate(
                [y_members, np.zeros((y_members.shape[0], extra),
                                     y_members.dtype)], axis=1)
    if y_members is not None:
        return x, y, train_w, valid_w, y_members
    return x, y, train_w, valid_w


# ------------------------------------------------------------- streaming
def train_ensemble_streamed(stream, spec: nn_model.NNModelSpec,
                            settings: TrainSettings, bags: int, mask_fn,
                            init_params_list: Optional[List[Any]] = None,
                            progress: Optional[ProgressFn] = None,
                            checkpoint: Optional[Callable[[int, List[Any]],
                                                          None]] = None,
                            mesh=None,
                            member_classes: Optional[List[int]] = None,
                            elastic=None) -> EnsembleResult:
    """See :func:`_train_ensemble_streamed_impl`; precision wrapper as in
    :func:`train_ensemble`."""
    if settings.matmul_precision:
        with jax.default_matmul_precision(settings.matmul_precision):
            return _train_ensemble_streamed_impl(
                stream, spec, settings, bags, mask_fn, init_params_list,
                progress, checkpoint, mesh, member_classes, elastic)
    return _train_ensemble_streamed_impl(
        stream, spec, settings, bags, mask_fn, init_params_list,
        progress, checkpoint, mesh, member_classes, elastic)


def _train_ensemble_streamed_impl(stream, spec: nn_model.NNModelSpec,
                            settings: TrainSettings, bags: int, mask_fn,
                            init_params_list: Optional[List[Any]] = None,
                            progress: Optional[ProgressFn] = None,
                            checkpoint: Optional[Callable[[int, List[Any]], None]] = None,
                            mesh=None,
                            member_classes: Optional[List[int]] = None,
                            elastic=None) -> EnsembleResult:
    """Out-of-core ensemble training: one pass over ``stream.windows()`` per
    epoch, dataset never resident anywhere (the
    ``MemoryDiskFloatMLDataSet.java`` role, done the streaming-SPMD way).

    Full-batch semantics (RPROP & friends) hold exactly: per-window
    UNNORMALIZED gradient sums accumulate on device across windows; the
    optimizer applies once per epoch on ``sum(grads)/sum(weights)`` plus the
    regularizer — bit-for-bit the math of :func:`train_ensemble` up to fp
    reassociation.  With ``settings.batch_size > 0`` each window instead
    yields minibatch updates (ADAM-style), like the reference's in-epoch
    iteration.

    ``mask_fn(global_row_index, targets) -> (train_w, valid_w)`` supplies
    each window's ``[bags, rows]`` sampling masks (see
    ``data.streaming.window_member_masks``); they are multiplied by the data
    weight column inside.

    Reported errors for epoch e are measured during pass e+1 (same params,
    one pass later) so each epoch streams the data once, not twice; a final
    eval-only pass closes the ledger.  Early stop therefore lags one epoch.

    ``elastic`` (a :class:`parallel.elastic.ElasticContext`) switches the
    CROSS-PROCESS combine from the in-mesh psum to the quorum-gated step
    protocol: each controller streams its OWN shard set on its LOCAL
    mesh, per-epoch unnormalized grad sums + eval stat sums post as one
    contribution, and the epoch's update applies the committed quorum
    aggregate (summed in sorted-controller order — every survivor steps
    the same bits).  An epoch whose close record already exists is
    REPLAYED from the journal without streaming (rejoin catch-up).
    Elastic transport is f32; full-batch mode only.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    if elastic is not None and settings.batch_size != 0:
        raise ValueError("elastic multi-controller training requires the "
                         "full-batch streamed mode (batch_size=0): the "
                         "quorum step protocol closes once per epoch")
    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=bags)
    data_size = mesh.shape["data"]
    assert stream.window_rows % data_size == 0, \
        f"window_rows {stream.window_rows} must divide data axis {data_size}"

    key = jax.random.PRNGKey(settings.seed)
    if init_params_list is None:
        keys = jax.random.split(key, bags)
        init_params_list = [nn_model.init_params(k, spec,
                                                 settings.weight_initializer)
                            for k in keys]
    opt = make_optimizer(settings.optimizer, settings.learning_rate,
                         **settings.opt_kwargs)
    precision = resolve_precision(settings.precision)
    if precision != "f32":
        init_params_list = [cast_tree(p, jnp.bfloat16)
                            for p in init_params_list]
    stacked = _stack(init_params_list)
    if precision == "mixed":
        opt_state = _stack([mixed_init(opt, p) for p in init_params_list])
    else:
        opt_state = _stack([opt.init(p) for p in init_params_list])
    sh_ens = NamedSharding(mesh, P("ensemble"))
    sh_x = NamedSharding(mesh, P("data", None))
    sh_y = NamedSharding(mesh, P("data"))
    sh_w = NamedSharding(mesh, P("ensemble", "data"))
    stacked = jax.device_put(stacked, sh_ens)
    opt_state = jax.device_put(opt_state, sh_ens)

    dropout = settings.dropout_rate
    l1, l2 = settings.l1, settings.l2

    def _loss_sum(params, xb, yb, mw, rng):
        pred = nn_model.forward(params, spec, xb,
                                dropout_rate=dropout,
                                rng=rng if dropout > 0 else None)
        return (nn_model.per_row_loss(pred, yb[:, None], spec) * mw).sum()

    def _eval_sums(params, xb, yb, mw, vw):
        pred = nn_model.forward(params, spec, xb)
        per_row = nn_model.per_row_loss(pred, yb[:, None], spec)
        return jnp.stack([(per_row * mw).sum(), mw.sum(),
                          (per_row * vw).sum(), vw.sum()])

    # OVA fan-out (``member_classes``): member m binarizes the shared
    # class-id window against its OWN class on device — the streamed
    # analogue of the in-RAM path's y_members (reference per-class jobs,
    # ``TrainModelProcessor.java:684-714``)
    cls_arr = None if member_classes is None else \
        jnp.asarray(member_classes, jnp.float32)

    # streamed nn-plane entry points, cost-attributed (obs/costs): the
    # per-window grad/eval programs are where streamed NN wall-clock goes
    @partial(obs.costed_jit, "nn.grad_eval_window")
    def grad_eval_window(stacked, grad_acc, stats_acc, xb, yb, tw, vw, rngs):
        def one(params, mw, vwm, rng, ci):
            ym = yb if cls_arr is None else (yb == ci).astype(yb.dtype)
            _, grads = jax.value_and_grad(_loss_sum)(params, xb, ym, mw, rng)
            return grads, _eval_sums(params, xb, ym, mw, vwm)
        cis = jnp.zeros(tw.shape[0]) if cls_arr is None else cls_arr
        grads, stats = jax.vmap(one)(stacked, tw, vw, rngs, cis)
        grad_acc = jax.tree_util.tree_map(jnp.add, grad_acc, grads)
        return grad_acc, stats_acc + stats

    @partial(obs.costed_jit, "nn.eval_window")
    def eval_window(stacked, stats_acc, xb, yb, tw, vw):
        def one(params, mw, vwm, ci):
            ym = yb if cls_arr is None else (yb == ci).astype(yb.dtype)
            return _eval_sums(params, xb, ym, mw, vwm)
        cis = jnp.zeros(tw.shape[0]) if cls_arr is None else cls_arr
        stats = jax.vmap(one)(stacked, tw, vw, cis)
        return stats_acc + stats

    @partial(obs.costed_jit, "nn.apply_update")
    def apply_update(stacked, opt_state, grad_acc, train_wsum, lr_scale):
        def one(params, ostate, grads, wsum):
            inv = 1.0 / jnp.maximum(wsum, 1e-9)
            g = [{"w": gl["w"] * inv + 2.0 * l2 * pl["w"]
                       + l1 * jnp.sign(pl["w"]),
                  "b": gl["b"] * inv}
                 for gl, pl in zip(grads, params)]
            if precision == "mixed":
                # accumulated-f32 grads step the f32 master; the bf16
                # training copy is one rounding of the new master
                return mixed_apply(opt, g, ostate, scale=lr_scale)
            delta, ostate = opt.update(g, ostate, params)
            params = jax.tree_util.tree_map(
                lambda p, d: p + (d * lr_scale).astype(p.dtype),
                params, delta)
            return params, ostate
        return jax.vmap(one)(stacked, opt_state, grad_acc, train_wsum)

    @partial(obs.costed_jit, "nn.minibatch_window",
             static_argnames=("blen",))
    def minibatch_window(stacked, opt_state, xw, yw, tww, rngs, lr_scale,
                         start, blen: int):
        # slice INSIDE jit: dynamic_slice of the sharded window compiles
        # into the SPMD program (an eager lax.slice would trigger ad-hoc
        # device copies the XLA:CPU runtime can SIGABRT on)
        xb = jax.lax.dynamic_slice_in_dim(xw, start, blen, axis=0)
        yb = jax.lax.dynamic_slice_in_dim(yw, start, blen, axis=0)
        tw = jax.lax.dynamic_slice_in_dim(tww, start, blen, axis=1)

        def one(params, ostate, mw, rng, ci):
            ym = yb if cls_arr is None else (yb == ci).astype(yb.dtype)
            def norm_loss(p):
                return _loss_sum(p, xb, ym, mw, rng) / jnp.maximum(mw.sum(), 1e-9) \
                    + l2 * sum((layer["w"] ** 2).sum() for layer in p) \
                    + l1 * sum(jnp.abs(layer["w"]).sum() for layer in p)
            grads = jax.grad(norm_loss)(params)
            if precision == "mixed":
                return mixed_apply(opt, grads, ostate, scale=lr_scale)
            delta, ostate = opt.update(grads, ostate, params)
            params = jax.tree_util.tree_map(
                lambda p, d: p + (d * lr_scale).astype(p.dtype),
                params, delta)
            return params, ostate
        cis = jnp.zeros(tw.shape[0]) if cls_arr is None else cls_arr
        return jax.vmap(one, in_axes=(0, 0, 0, 0, 0))(stacked, opt_state,
                                                      tw, rngs, cis)

    # mixed accumulates the cross-window gradient sums in f32 (bf16
    # accumulation over many windows loses low-order mass); jnp.add's
    # bf16+f32 promotion keeps the accumulator f32 per window
    zero_grads = jax.device_put(
        jax.tree_util.tree_map(
            lambda a: jnp.zeros(a.shape,
                                jnp.float32 if precision == "mixed"
                                else a.dtype), stacked), sh_ens)

    if elastic is not None:
        from ..parallel.elastic import grad_codec
        _ravel_grads, _unravel_grads = grad_codec(zero_grads)

    full_batch = settings.batch_size == 0
    W = stream.window_rows
    if not full_batch:
        # sub-slice each window into ~batch_size minibatches (same update
        # granularity as the in-RAM loop); slice edges land on data_size
        # multiples so every slice shards cleanly — at most 2 distinct slice
        # shapes, so at most 2 compiles
        bs = max(settings.batch_size - settings.batch_size % data_size,
                 data_size)
        n_slices = max(1, W // bs)
        edges = [min(W, ((i * W // n_slices) // data_size) * data_size)
                 for i in range(n_slices)] + [W]
        slices = [(s, e) for s, e in zip(edges[:-1], edges[1:]) if e > s]
    stops = [WindowEarlyStop(settings.early_stop_window) for _ in range(bags)]
    best_valid = np.full(bags, np.inf)
    best_train = np.full(bags, np.inf)
    best_params: List[Any] = [None] * bags
    history: List[Tuple[float, float]] = []
    lr_scale = 1.0
    start_epoch = 0
    epochs_target = settings.epochs
    if settings.resume and settings.checkpoint_dir:
        from . import checkpoint as ckpt
        restored = ckpt.restore_state(
            settings.checkpoint_dir,
            _ckpt_template(stacked, opt_state, key, bags),
            expect_precision=precision)
        if restored is not None:
            start_epoch, state = restored
            stacked = jax.device_put(state[0], sh_ens)
            opt_state = jax.device_put(state[1], sh_ens)
            key = jnp.asarray(state[2])
            _restore_tracking(state, best_valid, best_train, best_params,
                              stops)
            lr_scale = (1.0 - settings.learning_decay) ** start_epoch \
                if settings.learning_decay > 0 else 1.0
            epochs_target = _resume_epoch_target(settings, start_epoch,
                                                 stops)
            log.info("resumed streamed trainer state at epoch %d "
                     "(target %d)", start_epoch, epochs_target)
            if settings.early_stop_window > 0 and \
                    all(s.since_best >= s.window_size for s in stops):
                start_epoch = epochs_target     # already early-stopped

    def put_window(win):
        xb = jax.device_put(win.arrays["x"].astype(np.float32), sh_x)
        yb = jax.device_put(win.arrays["y"].astype(np.float32), sh_y)
        tm, vm = mask_fn(win.index, win.arrays["y"])
        wcol = win.arrays["w"].astype(np.float32)
        if win.n_valid < win.rows:                 # zero out padded tail
            wcol = wcol.copy()
            wcol[win.n_valid:] = 0.0
        tw = jax.device_put(tm * wcol[None, :], sh_w)
        vw = jax.device_put(vm * wcol[None, :], sh_w)
        return xb, yb, tw, vw

    def bookkeep(epoch_done: int, stats: np.ndarray, params_snapshot) -> bool:
        """Record errors for ``epoch_done`` measured on ``params_snapshot``
        (device).  Returns True when every member's early-stop window fired."""
        tr = stats[:, 0] / np.maximum(stats[:, 1], 1e-9)
        va = stats[:, 2] / np.maximum(stats[:, 3], 1e-9)
        history.append((float(tr.mean()), float(va.mean())))
        improved = np.flatnonzero(va < best_valid)
        if improved.size:
            host = _to_host(params_snapshot)
            for i in improved:
                best_valid[i], best_train[i] = va[i], tr[i]
                best_params[i] = jax.tree_util.tree_map(
                    lambda a: a[i].copy(), host)
        if progress:
            progress(epoch_done, float(tr.mean()), float(va.mean()))
        obs.counter("train.epochs").inc()
        obs.event("epoch", trainer="nn_streamed", epoch=epoch_done,
                  train_err=round(float(tr.mean()), 6),
                  valid_err=round(float(va.mean()), 6),
                  rows=stream.num_rows)
        if settings.early_stop_window > 0:
            flags = [s.should_stop(float(v)) for s, v in zip(stops, va)]
            return all(flags)
        return False

    epochs_run = start_epoch
    stopped = False
    for epoch in range(start_epoch, epochs_target):
        key, sub = jax.random.split(key)
        rngs = jax.random.split(sub, bags)
        grad_flat = None
        params_entering = stacked   # params the epoch's stats are measured on
        replayed = elastic.closed_step(epoch) if elastic is not None \
            else None
        if replayed is not None:
            # rejoin catch-up: this epoch already closed across the job —
            # apply the committed aggregate (bit-identical to what the
            # survivors stepped) without streaming a single window
            stats = np.asarray(replayed.payload["stats"])
            grad_flat = replayed.payload["grads"]
        else:
            stats_acc = jnp.zeros((bags, 4))
            grad_acc = zero_grads
            n_win = 0
            for win in stream.windows():
                xb, yb, tw, vw = put_window(win)
                rngs_w = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    rngs, n_win) if dropout > 0 else rngs
                if full_batch:
                    grad_acc, stats_acc = grad_eval_window(
                        stacked, grad_acc, stats_acc, xb, yb, tw, vw,
                        rngs_w)
                else:
                    stats_acc = eval_window(stacked, stats_acc, xb, yb,
                                            tw, vw)
                    for si, (s, e) in enumerate(slices):
                        rngs_s = jax.vmap(jax.random.fold_in,
                                          in_axes=(0, None))(
                            rngs_w, si) if dropout > 0 else rngs_w
                        stacked, opt_state = minibatch_window(
                            stacked, opt_state, xb, yb, tw, rngs_s,
                            lr_scale, jnp.int32(s), e - s)
                n_win += 1
            if n_win == 0:
                raise RuntimeError("streamed training: empty shard stream")
            if elastic is not None:
                # quorum-gated epoch close: local grad/stat sums post to
                # the control plane; everyone applies the SAME aggregate
                res = elastic.step(epoch, {
                    "grads": _ravel_grads(grad_acc),
                    "stats": np.asarray(stats_acc)})
                stats = np.asarray(res.payload["stats"])
                grad_flat = res.payload["grads"]
            else:
                stats = np.asarray(stats_acc)
        # stats were measured on the params entering this epoch => they close
        # the ledger of the PREVIOUS epoch (snapshot the matching params, not
        # the post-minibatch-update ones).  ``epoch > 0`` (not
        # ``> start_epoch``): a RESUMED epoch's stats close the ledger of
        # the last pre-crash epoch, which the checkpoint deliberately did
        # not record — skipping it would desync best-params tracking from
        # an uninterrupted run
        if epoch > 0:
            stopped = bookkeep(epoch - 1, stats, params_entering)
        if full_batch:
            stacked, opt_state = apply_update(
                stacked, opt_state,
                grad_acc if grad_flat is None else _unravel_grads(
                    grad_flat),
                jnp.asarray(stats[:, 1]), lr_scale)
        epochs_run = epoch + 1
        if checkpoint and settings.tmp_model_every and \
                (epoch + 1) % settings.tmp_model_every == 0:
            checkpoint(epoch, _unstack(stacked, bags))
        if settings.checkpoint_dir and settings.checkpoint_every and \
                ((epoch + 1) % settings.checkpoint_every == 0 or stopped):
            from . import checkpoint as ckpt
            ckpt.save_state(settings.checkpoint_dir, epoch + 1,
                            _ckpt_state(stacked, opt_state, key,
                                        best_valid, best_train,
                                        best_params, stops),
                            precision=precision)
        if settings.learning_decay > 0:
            lr_scale *= (1.0 - settings.learning_decay)
        if stopped:
            obs.event("early_stop", trainer="nn_streamed", epoch=epoch,
                      window=settings.early_stop_window)
            log.info("early stop at epoch %d (window %d, streamed)",
                     epoch, settings.early_stop_window)
            break

    # final eval-only pass: errors of the last params.  Elastic runs it
    # as one more quorum step (id ``epochs_run`` — past every epoch id,
    # and identical on all controllers since early stop reads the same
    # aggregated history) so best-model selection agrees job-wide; a
    # rejoiner that finds it already closed adopts the committed stats.
    final_close = elastic.closed_step(epochs_run) if elastic is not None \
        else None
    if final_close is None:
        stats_acc = jnp.zeros((bags, 4))
        for win in stream.windows():
            xb, yb, tw, vw = put_window(win)
            stats_acc = eval_window(stacked, stats_acc, xb, yb, tw, vw)
        if elastic is not None:
            final_close = elastic.step(
                epochs_run, {"stats": np.asarray(stats_acc)})
    final_stats = np.asarray(final_close.payload["stats"]) \
        if final_close is not None else np.asarray(stats_acc)
    bookkeep(epochs_run - 1, final_stats, stacked)

    final = _to_host(stacked)
    for i in range(bags):
        if best_params[i] is None:
            best_params[i] = jax.tree_util.tree_map(lambda a: a[i], final)
    return EnsembleResult(params=best_params, train_errors=best_train,
                          valid_errors=best_valid, epochs_run=epochs_run,
                          history=history)
