"""Grid search / hyper-param fan-out (reference ``core/dtrain/gs/GridSearch.java:62``).

List-valued entries in ``train#params`` expand cartesian-product style into
flattened trial param dicts; a ``gridConfigFile`` contributes extra axes.  In
the reference each combo becomes its own Guagua YARN job; here each trial is
one ensemble-trainer run (a future optimization could vmap same-shape trials
together, but per-trial settings feed the optimizer closure today).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List


def is_grid_search(params: Dict[str, Any]) -> bool:
    return any(isinstance(v, list) and _is_axis(k, v) for k, v in params.items())


def _is_axis(key: str, v: list) -> bool:
    """A list value is a grid axis unless the key naturally takes a list
    (hidden node counts / activations), where only list-of-list is an axis."""
    if key in ("NumHiddenNodes", "ActivationFunc", "FixedLayers",
               "NumEmbedColumnIds"):
        return bool(v) and isinstance(v[0], list)
    return True


def expand(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flatten list-valued params into trial dicts (order = reference's
    row-major cartesian iteration)."""
    axes, fixed = [], {}
    for k, v in params.items():
        if isinstance(v, list) and _is_axis(k, v):
            axes.append((k, v))
        else:
            fixed[k] = v
    if not axes:
        return [dict(params)]
    trials = []
    for combo in itertools.product(*(v for _, v in axes)):
        t = dict(fixed)
        t.update({k: c for (k, _), c in zip(axes, combo)})
        trials.append(t)
    return trials
