"""Grid search / hyper-param fan-out (reference ``core/dtrain/gs/GridSearch.java:62``).

List-valued entries in ``train#params`` expand cartesian-product style into
flattened trial param dicts; alternatively ``train.gridConfigFile`` lists one
EXPLICIT trial per line (``key:value;key:value``, :func:`load_grid_config`).
In the reference each combo becomes its own Guagua YARN job; here same-shape
trials stack as members of ONE vmapped ensemble run
(:func:`stackable_groups` + per-member hyper arrays in ``train_ensemble``).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List


def is_grid_search(params: Dict[str, Any]) -> bool:
    return any(isinstance(v, list) and _is_axis(k, v) for k, v in params.items())


def _is_axis(key: str, v: list) -> bool:
    """A list value is a grid axis unless the key naturally takes a list
    (hidden node counts / activations), where only list-of-list is an axis."""
    if key in ("NumHiddenNodes", "ActivationFunc", "FixedLayers",
               "NumEmbedColumnIds"):
        return bool(v) and isinstance(v[0], list)
    return True


def expand(params: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flatten list-valued params into trial dicts (order = reference's
    row-major cartesian iteration)."""
    axes, fixed = [], {}
    for k, v in params.items():
        if isinstance(v, list) and _is_axis(k, v):
            axes.append((k, v))
        else:
            fixed[k] = v
    if not axes:
        return [dict(params)]
    trials = []
    for combo in itertools.product(*(v for _, v in axes)):
        t = dict(fixed)
        t.update({k: c for (k, _), c in zip(axes, combo)})
        trials.append(t)
    return trials


# hypers a vmapped ensemble can vary per member (scalar multipliers in the
# update rule / loss); everything else changes program structure
STACKABLE_KEYS = ("LearningRate", "RegularizedConstant", "L2Const",
                  "L1Const", "DropoutRate")

# optimizers whose update delta is LINEAR in learning_rate — only for these
# can a LearningRate axis stack as a per-member delta multiplier.  RPROP
# ('R', the default) ignores lr entirely and quickprop is nonlinear in it.
LR_LINEAR_OPTS = frozenset({"ADAM", "SGD", "MOMENTUM", "NESTEROV",
                            "RMSPROP", "ADAGRAD", "B", "M"})


def _trial_stackable(trial: Dict[str, Any]) -> frozenset:
    opt = str(trial.get("Propagation", trial.get("Optimizer", "R"))).upper()
    if opt in LR_LINEAR_OPTS:
        return frozenset(STACKABLE_KEYS)
    return frozenset(k for k in STACKABLE_KEYS if k != "LearningRate")


def stackable_groups(trials: List[Dict[str, Any]]) -> List[List[int]]:
    """Group trial indices whose params differ ONLY in stackable scalar
    hypers — each group trains as ONE vmapped ensemble run (scalar hypers
    become per-member arrays), instead of the reference's queue of 5
    concurrent YARN jobs (``TrainModelProcessor.java:768-781``)."""
    import json
    groups: Dict[str, List[int]] = {}
    for i, t in enumerate(trials):
        stackable = _trial_stackable(t)
        key = json.dumps({k: v for k, v in sorted(t.items())
                          if k not in stackable}, default=str)
        groups.setdefault(key, []).append(i)
    return list(groups.values())


# tree-trainer hypers that are traced scalars in the forest executables —
# trials differing only in these vmap as members of ONE bagged run (an
# extra leading axis on weights/keys/feature-subsets); everything else
# (TreeNum/MaxDepth/Impurity/Loss/...) changes program structure
TREE_STACKABLE_KEYS = ("LearningRate", "MinInstancesPerNode", "MinInfoGain",
                       "Seed")


def tree_stackable_groups(trials: List[Dict[str, Any]]) -> List[List[int]]:
    """Group tree-trial indices whose params differ only in traced scalar
    hypers (see :data:`TREE_STACKABLE_KEYS`) — each group trains as one
    vmapped multi-forest run (reference queues one Guagua job per combo,
    ``TrainModelProcessor.java:768-781``)."""
    import json
    groups: Dict[str, List[int]] = {}
    for i, t in enumerate(trials):
        key = json.dumps({k: v for k, v in sorted(t.items())
                          if k not in TREE_STACKABLE_KEYS}, default=str)
        groups.setdefault(key, []).append(i)
    return list(groups.values())


def rank_and_report(tmp_dir: str, valid_errors: List[float],
                    trial_params: List[Dict[str, Any]]) -> List[int]:
    """THE grid-report contract (one place): rank trials by validation
    error, write the ordered ``[{trial, validError, params}]`` list to
    ``tmp_dir/grid_search.json``, return the ranked trial indices (best
    first).  Consumers: NN/tree/WDL grid drivers + their tests."""
    import json
    import os
    order = sorted(range(len(valid_errors)), key=lambda i: valid_errors[i])
    report = [{"trial": i, "validError": float(valid_errors[i]),
               "params": trial_params[i]} for i in order]
    os.makedirs(tmp_dir, exist_ok=True)
    with open(os.path.join(tmp_dir, "grid_search.json"), "w") as f:
        json.dump(report, f, indent=2, default=str)
    return order


def load_grid_config(path: str) -> List[Dict[str, Any]]:
    """Explicit trial list from ``train.gridConfigFile`` — one trial per
    line, ``key:value;key:value`` (reference ``GridSearch.java:119-153``);
    values parse as JSON when possible (lists/numbers), else strings."""
    import json
    trials: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            t: Dict[str, Any] = {}
            for ele in line.split(";"):
                if not ele.strip():
                    continue
                key, sep, val = ele.partition(":")
                if not sep:
                    raise ValueError(
                        f"{path}:{lineno}: expected <name>:<value> "
                        f"elements joined by ';', got {ele!r}")
                val = val.strip()
                try:
                    t[key.strip()] = json.loads(val)
                except json.JSONDecodeError:
                    t[key.strip()] = val
            if t:
                trials.append(t)
    return trials
