"""Genetic wrapper variable selection — the reference's ``core/dvarsel/``
stack (``VarSelMaster``/``VarSelWorker``, ``wrapper/CandidateGenerator``
inherit/crossover/mutation, ``wrapper/ValidationConductor`` per-candidate NN
fitness, ``CandidatePopulation``/``SeedCredit``, ~1.6k LoC) rebuilt
TPU-first.

The reference evaluates each candidate seed by training a small NN on its
column subset in a Guagua iteration; here the WHOLE population trains
simultaneously as ONE vmapped program — a candidate's subset is a binary
mask on the first-layer weights (``x @ (w * mask)`` ≡ masking the inputs),
so every member shares a single compiled graph and the population fans out
on the vmap/ensemble axis instead of worker threads.

Two data modes share one search loop (:func:`_genetic_search`):
resident (:func:`genetic_varselect`, the matrix in HBM) and streamed
(:func:`genetic_varselect_streamed`, fitness epochs as minibatch scans
over prepared ``ShardStream`` windows — the out-of-core treatment the
train/stats/sensitivity planes already get; the norm plane is never
host-resident).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..models import nn as nn_model
from .optimizers import make_optimizer

log = logging.getLogger(__name__)


@dataclass
class WrapperSettings:
    """Reference CandidateGenerator params (``CandidateGenerator.java:42-90``
    POPULATION_LIVE_SIZE / POPULATION_MULTIPLY_CNT / HYBRID_PERCENT /
    MUTATION_PERCENT / EXPECT_VARIABLE_CNT)."""
    n_select: int = 10            # columns per candidate seed
    population: int = 16          # live seeds per generation
    generations: int = 5          # multiply count
    hybrid_percent: float = 0.5   # crossover share of the next generation
    mutation_percent: float = 0.2 # mutation share (rest inherits)
    epochs: int = 40              # fitness-model epochs
    learning_rate: float = 0.05
    hidden: int = 8
    valid_rate: float = 0.25
    seed: int = 0

    @classmethod
    def from_params(cls, params: Dict, n_select: int,
                    valid_rate: float) -> "WrapperSettings":
        p = params or {}
        return cls(
            # reference knob for the seed size wins over the filterNum
            # default (CandidateGenerator EXPECT_VARIABLE_CNT)
            n_select=int(p.get("EXPECT_VARIABLE_CNT", n_select)),
            population=int(p.get("POPULATION_LIVE_SIZE", 16)),
            generations=int(p.get("POPULATION_MULTIPLY_CNT", 5)),
            hybrid_percent=float(p.get("HYBRID_PERCENT", 50)) / 100.0,
            mutation_percent=float(p.get("MUTATION_PERCENT", 20)) / 100.0,
            epochs=int(p.get("WrapperEpochs", 40)),
            learning_rate=float(p.get("WrapperLearningRate", 0.05)),
            hidden=int(p.get("WrapperHiddenNodes", 8)),
            valid_rate=valid_rate,
            seed=int(p.get("Seed", 0)))


def make_population_evaluator(x: np.ndarray, y: np.ndarray,
                              tw: np.ndarray, vw: np.ndarray,
                              settings: WrapperSettings):
    """Build ONE jitted population evaluator (masks are a traced argument,
    so every generation reuses the same compiled program — the per-call
    retrace a closure over masks would cause compiles 5x for nothing).

    Returns ``evaluate(feat_masks [P, D] bool) -> val-loss [P]``: P masked
    NNs trained as ONE vmapped full-batch run (the reference's
    ``ValidationConductor.voteVariables`` per-seed training loop, all seeds
    at once).  Identical init across members so fitness ranks subsets, not
    initializations.
    """
    n, d = x.shape
    spec = nn_model.NNModelSpec(input_dim=d,
                                hidden_nodes=[settings.hidden],
                                activations=["tanh"], loss="log")
    p0 = nn_model.init_params(jax.random.PRNGKey(settings.seed), spec)
    opt = make_optimizer("ADAM", settings.learning_rate)
    os0 = opt.init(p0)

    xj = jnp.asarray(x, jnp.float32)
    yj = jnp.asarray(y, jnp.float32)[:, None]
    twj = jnp.asarray(tw, jnp.float32)
    vwj = jnp.asarray(vw, jnp.float32)

    def masked_params(params, m):
        # first-layer weight mask: x @ (w * m[:, None]) == (x * m) @ w
        return [{"w": params[0]["w"] * m[:, None], "b": params[0]["b"]}] \
            + params[1:]

    def member_loss(params, m):
        return nn_model.weighted_loss(masked_params(params, m), spec,
                                      xj, yj, twj)

    @obs.costed_jit("varsel.genetic.train")
    def train(masks):
        P = masks.shape[0]
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (P,) + a.shape), p0)
        opt_state = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a, (P,) + a.shape), os0)

        def epoch(_, carry):
            st, os_ = carry

            def one(params, ostate, m):
                _, grads = jax.value_and_grad(member_loss)(params, m)
                delta, ostate = opt.update(grads, ostate, params)
                params = jax.tree_util.tree_map(lambda p, dl: p + dl,
                                                params, delta)
                return params, ostate
            return jax.vmap(one)(st, os_, masks)
        stacked, opt_state = jax.lax.fori_loop(0, settings.epochs, epoch,
                                               (stacked, opt_state))

        def fitness(params, m):
            pred = nn_model.forward(masked_params(params, m), spec, xj)
            per = nn_model.per_row_loss(pred, yj, spec)
            return (per * vwj).sum() / jnp.maximum(vwj.sum(), 1e-9)
        return jax.vmap(fitness)(stacked, masks)

    def evaluate(feat_masks: np.ndarray) -> np.ndarray:
        return np.asarray(train(jnp.asarray(feat_masks, jnp.float32)))
    return evaluate


def evaluate_population(x, y, tw, vw, feat_masks,
                        settings: WrapperSettings) -> np.ndarray:
    """One-shot convenience wrapper over :func:`make_population_evaluator`."""
    return make_population_evaluator(x, y, tw, vw, settings)(feat_masks)


def make_streamed_population_evaluator(stream, settings: WrapperSettings,
                                       mesh=None,
                                       cache_budget: Optional[int] = None):
    """Out-of-core counterpart of :func:`make_population_evaluator`: the
    whole population still trains as ONE vmapped program, but fitness
    epochs are **minibatch scans over prepared windows** — the norm plane
    streams through ``ShardStream.prepared`` (prefetch/H2D pipelining +
    the mmap spill fast path) with windows under the device cache budget
    staying HBM-resident across every epoch and generation, so the
    dataset never materializes on host.  Members shard over the mesh
    ``ensemble`` axis, rows over ``data``.

    Train/validation split derives statelessly from the global row index
    (``row_uniform``, same stream/seed convention as the streamed
    trainers) — the resident evaluator's load-time ``rng.random`` split
    needs the whole plane in one array, which streaming by definition
    does not have.

    Returns ``(evaluate, d)``: ``evaluate(feat_masks [P, D]) -> val-loss
    [P]`` with ONE ``[P, 2]`` device fetch per generation (counted by
    ``varsel.host_syncs``)."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as Spec

    from .. import obs
    from ..data.streaming import (PreparedWindow, ResidentCache,
                                  pipeline_depth_for, row_uniform)
    from ..parallel import mesh as meshlib

    names = stream.shards.schema.get("outputNames") or []
    if not names:
        raise ValueError("streamed dvarsel needs schema outputNames "
                         "(run `norm` to materialize the plane)")
    d = len(names)
    P = settings.population
    if mesh is None:
        mesh = meshlib.device_mesh(n_ensemble=P)
    data_size = int(mesh.shape["data"])
    assert stream.window_rows % data_size == 0, \
        f"window_rows {stream.window_rows} must divide data axis {data_size}"

    spec = nn_model.NNModelSpec(input_dim=d,
                                hidden_nodes=[settings.hidden],
                                activations=["tanh"], loss="log")
    p0 = nn_model.init_params(jax.random.PRNGKey(settings.seed), spec)
    opt = make_optimizer("ADAM", settings.learning_rate)
    os0 = opt.init(p0)

    sh_ens = NamedSharding(mesh, Spec("ensemble"))
    sh_x = NamedSharding(mesh, Spec("data", None))
    sh_r = NamedSharding(mesh, Spec("data"))
    stacked0 = jax.device_put(jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (P,) + a.shape), p0), sh_ens)
    opt0 = jax.device_put(jax.tree_util.tree_map(
        lambda a: jnp.broadcast_to(a, (P,) + a.shape), os0), sh_ens)

    def masked_params(params, m):
        return [{"w": params[0]["w"] * m[:, None], "b": params[0]["b"]}] \
            + params[1:]

    @obs.costed_jit("varsel.genetic.window_update")
    def window_update(stacked, opt_state, masks, xb, yb, tw):
        """One minibatch (= window) ADAM step for every member at once."""
        def one(params, ostate, m):
            def loss(p):
                pred = nn_model.forward(masked_params(p, m), spec, xb)
                per = nn_model.per_row_loss(pred, yb[:, None], spec)
                return (per * tw).sum() / jnp.maximum(tw.sum(), 1e-9)
            grads = jax.grad(loss)(params)
            delta, ostate = opt.update(grads, ostate, params)
            params = jax.tree_util.tree_map(lambda p_, dl: p_ + dl,
                                            params, delta)
            return params, ostate
        return jax.vmap(one)(stacked, opt_state, masks)

    @obs.costed_jit("varsel.genetic.window_fitness")
    def window_fitness(stacked, masks, acc, xb, yb, vw):
        def one(params, m):
            pred = nn_model.forward(masked_params(params, m), spec, xb)
            per = nn_model.per_row_loss(pred, yb[:, None], spec)
            return jnp.stack([(per * vw).sum(), vw.sum()])
        return acc + jax.vmap(one)(stacked, masks)

    def prepare(win):
        xb = jax.device_put(win.arrays["x"].astype(np.float32, copy=False),
                            sh_x)
        yb = jax.device_put(win.arrays["y"].astype(np.float32, copy=False),
                            sh_r)
        vmask = row_uniform(settings.seed, 11, win.index) \
            < settings.valid_rate
        wcol = np.asarray(win.arrays["w"], np.float32).copy()
        wcol[win.n_valid:] = 0.0
        tw = jax.device_put((wcol * ~vmask).astype(np.float32), sh_r)
        vw = jax.device_put((wcol * vmask).astype(np.float32), sh_r)
        return PreparedWindow(start=win.start, n_valid=win.n_valid,
                              rows=win.rows, index=win.index,
                              arrays={"x": xb, "y": yb, "tw": tw,
                                      "vw": vw})

    if cache_budget is None:
        from ..config import environment
        cache_budget = environment.get_int("shifu.train.deviceCacheBytes",
                                           1 << 30)
    cache = ResidentCache(stream, cache_budget, prepare,
                          pipeline_depth=pipeline_depth_for(mesh))

    def evaluate(feat_masks: np.ndarray) -> np.ndarray:
        masks = jax.device_put(
            np.asarray(feat_masks, np.float32),
            NamedSharding(mesh, Spec("ensemble", None)))
        stacked, opt_state = stacked0, opt0
        win_c = obs.counter("varsel.windows")
        for _ in range(settings.epochs):
            for it in cache.items():
                stacked, opt_state = window_update(
                    stacked, opt_state, masks, it.arrays["x"],
                    it.arrays["y"], it.arrays["tw"])
                win_c.inc()
        acc = jnp.zeros((feat_masks.shape[0], 2))
        for it in cache.items():
            acc = window_fitness(stacked, masks, acc, it.arrays["x"],
                                 it.arrays["y"], it.arrays["vw"])
        a = np.asarray(acc)        # the generation's ONE device fetch
        obs.counter("varsel.host_syncs").inc()
        return a[:, 0] / np.maximum(a[:, 1], 1e-9)

    return evaluate, d


def genetic_varselect(x: np.ndarray, y: np.ndarray, w: np.ndarray,
                      blocks: Dict[int, List[int]],
                      settings: WrapperSettings
                      ) -> Tuple[Dict[int, float], List[dict]]:
    """Evolve column subsets over a RESIDENT matrix; returns (per-column
    credit scores, history).  See :func:`_genetic_search` for the loop;
    :func:`genetic_varselect_streamed` is the out-of-core twin."""
    rng = np.random.default_rng(settings.seed)
    vmask = rng.random(len(y)) < settings.valid_rate
    tw = np.asarray(w, np.float32) * ~vmask
    vw = np.asarray(w, np.float32) * vmask
    evaluate = make_population_evaluator(x, y, tw, vw, settings)
    return _genetic_search(evaluate, blocks, settings, x.shape[1], rng)


def genetic_varselect_streamed(stream, blocks: Dict[int, List[int]],
                               settings: WrapperSettings, mesh=None,
                               cache_budget: Optional[int] = None
                               ) -> Tuple[Dict[int, float], List[dict]]:
    """Out-of-core genetic wrapper: same search
    (``CandidateGenerator``/``SeedCredit`` semantics, shared loop), with
    fitness evaluated by minibatch scans over prepared norm-plane windows
    instead of a resident matrix."""
    evaluate, d = make_streamed_population_evaluator(stream, settings,
                                                     mesh, cache_budget)
    return _genetic_search(evaluate, blocks, settings, d,
                           np.random.default_rng(settings.seed))


def _genetic_search(evaluate, blocks: Dict[int, List[int]],
                    settings: WrapperSettings, d: int,
                    rng: np.random.Generator
                    ) -> Tuple[Dict[int, float], List[dict]]:
    """The generation loop both data modes share.

    Seeds are column-id sets of size ``n_select``; each generation ranks
    them by masked-NN validation loss (``evaluate(feat_masks [P, d])``),
    then builds the next from inherit + crossover + mutation
    (``CandidateGenerator.java``); per-column credit accumulates
    rank-weighted wins (``SeedCredit.java``)."""
    col_ids = sorted(blocks.keys())
    C = len(col_ids)
    k = min(settings.n_select, C)
    P = settings.population

    def feat_mask(seed_cols: np.ndarray) -> np.ndarray:
        m = np.zeros(d, bool)
        for ci in seed_cols:
            m[blocks[col_ids[ci]]] = True
        return m

    if k >= C:
        log.warning("dvarsel: seed size %d >= %d candidate columns — every "
                    "seed holds ALL columns, the search is degenerate; set "
                    "EXPECT_VARIABLE_CNT (or filterNum) below the candidate "
                    "count", k, C)
    pop = np.stack([rng.choice(C, size=k, replace=False) for _ in range(P)])
    credit = np.zeros(C)
    history: List[dict] = []
    best_seed, best_fit = None, np.inf
    for gen in range(settings.generations):
        fmasks = np.stack([feat_mask(s) for s in pop])
        fits = evaluate(fmasks)
        order = np.argsort(fits)
        # SeedCredit: rank-weighted column wins
        for rank, pi in enumerate(order):
            for ci in pop[pi]:
                credit[ci] += (P - rank)
        if fits[order[0]] < best_fit:
            best_fit = float(fits[order[0]])
            best_seed = pop[order[0]].copy()
        history.append({"generation": gen,
                        "best": float(fits[order[0]]),
                        "mean": float(fits.mean())})
        log.info("dvarsel gen %d: best %.6f mean %.6f", gen,
                 fits[order[0]], fits.mean())
        if gen == settings.generations - 1:
            break
        # ---- next generation (CandidateGenerator proportions)
        n_cross = int(P * settings.hybrid_percent)
        n_mut = int(P * settings.mutation_percent)
        n_inherit = P - n_cross - n_mut
        nxt = [pop[pi].copy() for pi in order[:max(1, n_inherit)]]
        parents = pop[order[:max(2, P // 2)]]
        while len(nxt) < max(1, n_inherit) + n_cross:
            pa, pb = parents[rng.integers(len(parents), size=2)]
            union = np.union1d(pa, pb)
            nxt.append(rng.choice(union, size=min(k, len(union)),
                                  replace=False))
        while len(nxt) < P:
            base = pop[order[rng.integers(max(1, P // 2))]].copy()
            flip = rng.integers(len(base))
            choices = np.setdiff1d(np.arange(C), base)
            if len(choices):
                base[flip] = rng.choice(choices)
            nxt.append(base)
        pop = np.stack([np.sort(np.asarray(s)) for s in nxt])

    scores = {col_ids[ci]: float(credit[ci]) for ci in range(C)}
    # the winning seed's columns get a decisive bonus so exactly those rank
    # first when filterNum == n_select
    if best_seed is not None:
        for ci in best_seed:
            scores[col_ids[ci]] += credit.max() * C
    return scores, history
