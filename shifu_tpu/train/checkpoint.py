"""Trainer-state checkpointing — the fail-over story (SURVEY.md §5).

The reference leans on Guagua restarting failed masters/workers from the
last iteration state (``NNMaster.java:517-528``, DT ``doCheckPoint`` to HDFS
``DTMaster.java:637``).  A synchronous mesh has no partial restart, so the
equivalent is periodic full-state checkpoints (params + optimizer state +
epoch + PRNG key) and resume-from-latest.

Format: one npz per checkpoint with leaves in tree-flatten order; restore
maps them back onto a freshly built template pytree, so arbitrary optimizer
state trees round-trip without pickling.
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
from typing import Any, Optional, Tuple

import numpy as np

import jax

log = logging.getLogger(__name__)

_NAME = re.compile(r"ckpt-(\d+)\.npz$")


def save_state(directory: str, epoch: int, state: Any, keep: int = 3) -> str:
    """state: arbitrary pytree of arrays (params, opt_state, rng key...)."""
    os.makedirs(directory, exist_ok=True)
    # sweep orphaned tmp files a previous crash left mid-rename — they
    # are never valid checkpoints and would otherwise accumulate forever
    for f in os.listdir(directory):
        if f.endswith(".npz.tmp"):
            try:
                os.remove(os.path.join(directory, f))
            except OSError:
                pass
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {f"leaf{i}": np.asarray(l) for i, l in enumerate(leaves)}
    arrays["__meta__"] = np.frombuffer(json.dumps(
        {"epoch": epoch, "n_leaves": len(leaves)}).encode(), np.uint8)
    path = os.path.join(directory, f"ckpt-{epoch}.npz")
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint
    _prune(directory, keep)
    return path


def latest_epoch(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    epochs = [int(m.group(1)) for f in os.listdir(directory)
              if (m := _NAME.search(f))]
    return max(epochs) if epochs else None


def restore_state(directory: str, template: Any) -> Optional[Tuple[int, Any]]:
    """Load the latest checkpoint onto ``template``'s structure.  Returns
    (epoch, state) or None; shape mismatch (config changed) -> None."""
    epoch = latest_epoch(directory)
    if epoch is None:
        return None
    data = np.load(os.path.join(directory, f"ckpt-{epoch}.npz"))
    meta = json.loads(bytes(data["__meta__"]).decode())
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if meta["n_leaves"] != len(leaves):
        log.warning("checkpoint %d has %d leaves, template %d — ignoring",
                    epoch, meta["n_leaves"], len(leaves))
        return None
    new_leaves = []
    for i, tmpl in enumerate(leaves):
        a = data[f"leaf{i}"]
        if tuple(a.shape) != tuple(np.shape(tmpl)):
            log.warning("checkpoint leaf %d shape %s != template %s — "
                        "ignoring checkpoint", i, a.shape, np.shape(tmpl))
            return None
        tmpl_dt = np.dtype(getattr(tmpl, "dtype", None)
                           or np.asarray(tmpl).dtype)
        if a.dtype != tmpl_dt:
            # shape-only acceptance silently CAST the restored leaves
            # (e.g. an f32 checkpoint onto an int opt-state slot) — a
            # config change this subtle must fall back to fresh init
            log.warning("checkpoint leaf %d dtype %s != template %s — "
                        "ignoring checkpoint", i, a.dtype, tmpl_dt)
            return None
        new_leaves.append(a)
    return meta["epoch"], jax.tree_util.tree_unflatten(treedef, new_leaves)


def _prune(directory: str, keep: int) -> None:
    files = sorted(((int(m.group(1)), f) for f in os.listdir(directory)
                    if (m := _NAME.search(f))))
    for _, f in files[:-keep]:
        os.remove(os.path.join(directory, f))
