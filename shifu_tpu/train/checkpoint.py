"""Trainer-state checkpointing — the fail-over story (SURVEY.md §5).

The reference leans on Guagua restarting failed masters/workers from the
last iteration state (``NNMaster.java:517-528``, DT ``doCheckPoint`` to HDFS
``DTMaster.java:637``).  A synchronous mesh has no partial restart, so the
equivalent is periodic full-state checkpoints (params + optimizer state +
epoch + PRNG key) and resume-from-latest.

Format: one npz per checkpoint with leaves in tree-flatten order; restore
maps them back onto a freshly built template pytree, so arbitrary optimizer
state trees round-trip without pickling.
"""

from __future__ import annotations

import io
import json
import logging
import os
import re
from typing import Any, Optional, Tuple

import numpy as np

import jax

log = logging.getLogger(__name__)

_NAME = re.compile(r"ckpt-(\d+)\.npz$")


def save_state(directory: str, epoch: int, state: Any, keep: int = 3,
               precision: Optional[str] = None) -> str:
    """state: arbitrary pytree of arrays (params, opt_state, rng key...).

    ``precision`` tags the checkpoint with the training-precision mode
    it was written under (``shifu.train.precision``); restore refuses a
    mismatched tag with a coded error instead of silently casting.
    Leaves in dtypes npz cannot round-trip natively (bfloat16 — numpy
    reloads the ml_dtypes descriptor as a V2 void) are stored as their
    uint16 bit pattern and viewed back on restore; the per-leaf dtype
    names ride in the meta record so restore is bit-exact."""
    os.makedirs(directory, exist_ok=True)
    # sweep orphaned tmp files a previous crash left mid-rename — they
    # are never valid checkpoints and would otherwise accumulate forever
    for f in os.listdir(directory):
        if f.endswith(".npz.tmp"):
            try:
                os.remove(os.path.join(directory, f))
            except OSError:
                pass
    leaves, treedef = jax.tree_util.tree_flatten(state)
    arrays = {}
    dtypes = []
    for i, l in enumerate(leaves):
        a = np.asarray(l)
        dtypes.append(str(a.dtype))
        if a.dtype.kind not in "biufc":
            # ml_dtypes leaf (bfloat16): same-width integer bit pattern
            a = a.view(np.uint16 if a.dtype.itemsize == 2 else np.uint8)
        arrays[f"leaf{i}"] = a
    meta = {"epoch": epoch, "n_leaves": len(leaves), "dtypes": dtypes}
    if precision is not None:
        meta["precision"] = precision
    arrays["__meta__"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    path = os.path.join(directory, f"ckpt-{epoch}.npz")
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)  # atomic: a crash never leaves a torn checkpoint
    _prune(directory, keep)
    return path


def latest_epoch(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    epochs = [int(m.group(1)) for f in os.listdir(directory)
              if (m := _NAME.search(f))]
    return max(epochs) if epochs else None


def restore_state(directory: str, template: Any,
                  expect_precision: Optional[str] = None
                  ) -> Optional[Tuple[int, Any]]:
    """Load the latest checkpoint onto ``template``'s structure.  Returns
    (epoch, state) or None; shape mismatch (config changed) -> None.

    ``expect_precision`` enforces the precision-mode handshake: a
    checkpoint tagged (or implicitly) under a DIFFERENT
    ``shifu.train.precision`` raises
    :class:`~shifu_tpu.config.errors.ShifuError`
    (``ERROR_CHECKPOINT_PRECISION_MISMATCH``) — resuming an f32
    checkpoint under ``mixed`` (or vice versa) must fail loudly, never
    silently cast the master copy.  Untagged (pre-round-12) checkpoints
    count as ``f32``."""
    epoch = latest_epoch(directory)
    if epoch is None:
        return None
    data = np.load(os.path.join(directory, f"ckpt-{epoch}.npz"))
    meta = json.loads(bytes(data["__meta__"]).decode())
    if expect_precision is not None:
        found = meta.get("precision") or "f32"
        if found != expect_precision:
            from ..config.errors import ErrorCode, ShifuError
            raise ShifuError(
                ErrorCode.ERROR_CHECKPOINT_PRECISION_MISMATCH,
                f"checkpoint {directory}/ckpt-{epoch}.npz was written "
                f"under precision={found!r} but this run trains under "
                f"precision={expect_precision!r} — restart from scratch "
                "or set shifu.train.precision back")
    leaves, treedef = jax.tree_util.tree_flatten(template)
    if meta["n_leaves"] != len(leaves):
        log.warning("checkpoint %d has %d leaves, template %d — ignoring",
                    epoch, meta["n_leaves"], len(leaves))
        return None
    saved_dtypes = meta.get("dtypes")
    new_leaves = []
    for i, tmpl in enumerate(leaves):
        a = data[f"leaf{i}"]
        if tuple(a.shape) != tuple(np.shape(tmpl)):
            log.warning("checkpoint leaf %d shape %s != template %s — "
                        "ignoring checkpoint", i, a.shape, np.shape(tmpl))
            return None
        tmpl_dt = np.dtype(getattr(tmpl, "dtype", None)
                           or np.asarray(tmpl).dtype)
        # the dtype the leaf was SAVED as (pre-round-12 checkpoints have
        # no dtypes record; the on-disk dtype is then authoritative)
        saved_dt = saved_dtypes[i] if saved_dtypes else str(a.dtype)
        if saved_dt != str(tmpl_dt):
            # shape-only acceptance silently CAST the restored leaves
            # (e.g. an f32 checkpoint onto an int opt-state slot) — a
            # config change this subtle must fall back to fresh init
            log.warning("checkpoint leaf %d dtype %s != template %s — "
                        "ignoring checkpoint", i, saved_dt, tmpl_dt)
            return None
        if a.dtype != tmpl_dt:
            # narrow ml_dtypes leaf stored as its integer bit pattern
            a = a.view(tmpl_dt)
        new_leaves.append(a)
    return meta["epoch"], jax.tree_util.tree_unflatten(treedef, new_leaves)


def _prune(directory: str, keep: int) -> None:
    files = sorted(((int(m.group(1)), f) for f in os.listdir(directory)
                    if (m := _NAME.search(f))))
    for _, f in files[:-keep]:
        os.remove(os.path.join(directory, f))
