"""Training layer: trainers, optimizers, sampling, early stop, grid search."""
