"""Kernel C-SVC trainer — the reference's local libsvm SVM, TPU-shaped.

The reference trains ``SVMType.SupportVectorClassification`` through
Encog/libsvm SMO in its LOCAL (Akka) mode only (``core/alg/
SVMTrainer.java:80-145``; Kernel/Gamma/Const params).  SMO is a scalar
working-set loop — the opposite of MXU-shaped — so the TPU formulation
solves the same soft-margin dual as a box-constrained QP with
diagonally-scaled projected gradient ascent, where every iteration is one
[n, n] kernel matvec:

    max_a  1.a - 1/2 a^T Q a,   0 <= a_i <= C,   Q = (y y^T) o (K + 1)

The ``K + 1`` augmentation folds the bias into the RKHS (regularized-bias
trick), dropping libsvm's equality constraint; the decision function is
``f(x) = sum_i a_i y_i (K(x_i, x) + 1)``.  Documented deviation: the
optimizer and bias treatment differ from libsvm SMO — margins agree to
optimization tolerance, support sets can differ on ties.

Like the reference, this is a LOCAL-scale trainer: the kernel matrix is
materialized ([n, n] f32), so n is capped; cluster-scale nonlinear
surfaces are what NN/GBT are for.
"""

from __future__ import annotations

import logging
import time
from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..models.svm import SVMModelSpec, kernel_matrix

log = logging.getLogger(__name__)

# kernel matrix rows cap: [n, n] f32 must sit comfortably in HBM next to
# the solver state (16384^2 x 4B = 1 GiB)
MAX_KERNEL_ROWS = 16384


@obs.costed_jit("svm.solve_dual", lazy=True,
                static_argnames=("spec_key", "iters"))
def _solve_dual(x, y_pm, train_mask, c_box, gamma, coef0,
                spec_key: Tuple, iters: int):
    """Projected gradient ascent on the augmented dual.  ``c_box`` is the
    per-row box bound (0 for validation rows — they simply cannot become
    support vectors, which IS the train/valid split)."""
    kind, degree = spec_key
    spec = SVMModelSpec(input_dim=x.shape[1], kernel=kind,
                        gamma=gamma, coef0=coef0, degree=degree)
    k = kernel_matrix(spec, x, x) + 1.0          # bias fold
    q = (y_pm[:, None] * y_pm[None, :]) * k
    # Gershgorin step: 1/sum_j |Q_ij| guarantees the simultaneous
    # projected update contracts (a plain 1/Q_ii Jacobi step oscillates —
    # kernel rows are strongly correlated)
    eta = 1.0 / jnp.maximum(jnp.abs(q).sum(axis=1), 1e-8)

    def body(alpha, _):
        g = 1.0 - q @ alpha
        alpha = jnp.clip(alpha + eta * g, 0.0, c_box)
        return alpha, ()

    alpha0 = jnp.zeros_like(y_pm)
    alpha, _ = jax.lax.scan(body, alpha0, None, length=iters)
    f = k @ (alpha * y_pm)                       # decision on all rows
    margins = y_pm * f
    hinge = jnp.maximum(0.0, 1.0 - margins)
    tr_w = train_mask
    va_w = 1.0 - train_mask
    tr_err = (hinge * tr_w).sum() / jnp.maximum(tr_w.sum(), 1e-9)
    va_err = (hinge * va_w).sum() / jnp.maximum(va_w.sum(), 1e-9)
    return alpha, f, tr_err, va_err


def train_kernel_svm(x: np.ndarray, y01: np.ndarray, train_mask: np.ndarray,
                     spec: SVMModelSpec, c_penalty: float = 1.0,
                     iters: int = 2000):
    """(sv_x, alpha_y, train_hinge, valid_hinge, n_sv): solve the dual on
    the training rows, keep rows with nonzero duals as support vectors."""
    n = x.shape[0]
    if n > MAX_KERNEL_ROWS:
        from ..config.errors import ErrorCode, ShifuError
        raise ShifuError(
            ErrorCode.ERROR_MODELCONFIG_NOT_VALIDATION,
            f"kernel SVM materializes an [n, n] kernel matrix; {n} rows "
            f"exceed the {MAX_KERNEL_ROWS}-row local-scale cap (the "
            "reference's libsvm SVM is local-only too) — sample the data "
            "or use NN/GBT for cluster-scale nonlinear training")
    y_pm = jnp.asarray(2.0 * np.asarray(y01, np.float32) - 1.0)
    tm = jnp.asarray(np.asarray(train_mask, np.float32))
    c_box = tm * float(c_penalty)
    t0 = time.perf_counter()
    alpha, f, tr, va = _solve_dual(
        jnp.asarray(x, jnp.float32), y_pm, tm, c_box,
        float(spec.gamma), float(spec.coef0),
        (spec.kernel, spec.degree), iters)
    alpha = np.asarray(alpha)            # value-forcing fetch = the sync
    solve_s = time.perf_counter() - t0
    keep = alpha > 1e-6
    sv_x = np.asarray(x, np.float32)[keep]
    alpha_y = (alpha * np.asarray(y_pm))[keep].astype(np.float32)
    obs.counter("train.epochs").inc(iters)   # dual iterations ≈ epochs
    obs.event("svm_solve", trainer="svm", kernel=spec.kernel,
              n_sv=int(keep.sum()), rows=n, iters=iters,
              train_err=round(float(tr), 6), valid_err=round(float(va), 6),
              dur_s=round(solve_s, 3))
    log.info("kernel SVM (%s): %d SVs of %d train rows, "
             "train hinge %.6f valid hinge %.6f", spec.kernel,
             int(keep.sum()), int(np.asarray(tm).sum()), float(tr),
             float(va))
    return sv_x, alpha_y, float(tr), float(va), int(keep.sum())
