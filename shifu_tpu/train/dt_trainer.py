"""GBT / RF distributed trainers — reference ``DTMaster``/``DTWorker``
(``core/dtrain/dt/``, 8.5k LoC) as device-side histogram + scan loops.

- GBT (``DTWorker.java:582-686`` residual update, ``DTMaster.java:392-435``
  tree switching): sequential trees; per-tree gradients (squared: y − f,
  log: y − sigmoid(f)) refit by a variance-impurity tree; shrinkage
  ``learning_rate``; moving-average early stop
  (``dt/DTEarlyStopDecider.java``).
- RF (``DTWorker`` Poisson bagging + oob-as-validation): independent trees
  over Poisson row weights, entropy/gini impurity, per-tree feature
  subsetting (featureSubsetStrategy ALL/HALF/SQRT/LOG2/ONETHIRD/TWOTHIRDS).
- Feature importance from split gains (reference FI output for tree models).

The row shard lives once in HBM as int bins; every tree/level reuses it —
the reference's short[] bin-index worker memory (``DTWorker.java:100``).
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from ..config.model_config import Algorithm
from ..data.shards import Shards
from ..models import tree as tree_model
from ..ops.tree import TreeArrays, grow_tree, predict_tree
from .early_stop import GBTEarlyStopDecider
from .sampling import validation_split

log = logging.getLogger(__name__)


@dataclass
class DTSettings:
    n_trees: int = 100
    depth: int = 7
    impurity: str = "variance"
    loss: str = "squared"
    learning_rate: float = 0.05          # GBT shrinkage
    min_instances: float = 1.0
    min_gain: float = 0.0
    feature_subset: str = "ALL"
    valid_rate: float = 0.2
    bagging_rate: float = 1.0            # RF Poisson rate
    poisson_bagging: bool = True         # False: plain single tree (DT)
    early_stop: bool = False
    seed: int = 0


def settings_from_params(params: Dict[str, Any], train_conf,
                         alg: Algorithm) -> DTSettings:
    """Reference train#params tree keys (``DTMaster.java:91`` init region):
    TreeNum / MaxDepth / Impurity / Loss / LearningRate /
    FeatureSubsetStrategy / MinInstancesPerNode / MinInfoGain."""
    p = params or {}
    default_impurity = "variance" if alg == Algorithm.GBT else "entropy"
    return DTSettings(
        n_trees=int(p.get("TreeNum", 10 if alg != Algorithm.DT else 1)),
        depth=int(p.get("MaxDepth", 7)),
        impurity=str(p.get("Impurity", default_impurity)).lower(),
        loss=str(p.get("Loss", "squared")).lower(),
        learning_rate=float(p.get("LearningRate", 0.05)),
        min_instances=float(p.get("MinInstancesPerNode", 1)),
        min_gain=float(p.get("MinInfoGain", 0.0)),
        feature_subset=str(p.get("FeatureSubsetStrategy", "ALL")).upper(),
        valid_rate=float(train_conf.validSetRate),
        bagging_rate=float(train_conf.baggingSampleRate),
        poisson_bagging=alg != Algorithm.DT,  # plain DT = one tree, full data
        early_stop=bool(train_conf.earlyStopEnable),
        seed=int(p.get("Seed", 0)))


def subset_count(strategy: str, c: int) -> int:
    s = strategy.upper()
    if s == "ALL":
        return c
    if s == "HALF":
        return max(1, c // 2)
    if s == "SQRT":
        return max(1, int(np.sqrt(c)))
    if s == "LOG2":
        return max(1, int(np.log2(max(c, 2))))
    if s == "ONETHIRD":
        return max(1, c // 3)
    if s == "TWOTHIRDS":
        return max(1, 2 * c // 3)
    return c


@dataclass
class ForestResult:
    trees: List[TreeArrays]
    spec_kwargs: Dict[str, Any]
    train_error: float
    valid_error: float
    feature_importance: np.ndarray       # [C] summed split gains
    trees_built: int = 0
    history: List[Tuple[float, float]] = field(default_factory=list)


def _feature_gains(trees: List[TreeArrays], c: int) -> np.ndarray:
    """FI = number-weighted presence of features in splits (gain values are
    folded in during growth via leaf statistics; split counts are the
    reference's simple FI mode)."""
    fi = np.zeros(c)
    for t in trees:
        for f in t.split_feat:
            if f >= 0:
                fi[f] += 1.0
    return fi


def train_gbt(bins, y, w, n_bins: int, cat_mask, settings: DTSettings,
              progress=None, init_trees: Optional[List[TreeArrays]] = None,
              init_score: Optional[float] = None) -> ForestResult:
    n, c = bins.shape
    vmask = validation_split(n, settings.valid_rate, settings.seed)
    tmask = ~vmask
    bins_d = jnp.asarray(bins, jnp.int32)
    wt = np.asarray(w, np.float64) * tmask
    y64 = np.asarray(y, np.float64)

    if init_score is None:  # continuous runs reuse the saved forest's prior
        prior = float((y64 * wt).sum() / max(wt.sum(), 1e-9))
        if settings.loss == "log":
            prior = np.clip(prior, 1e-6, 1 - 1e-6)
            init_score = float(np.log(prior / (1 - prior)))
        else:
            init_score = prior
    f = np.full(n, init_score, np.float64)
    trees: List[TreeArrays] = list(init_trees or [])
    for t in trees:  # continuous training: replay existing trees
        f += settings.learning_rate * np.asarray(
            predict_tree(jnp.asarray(t.split_feat), jnp.asarray(t.left_mask),
                         jnp.asarray(t.leaf_value), bins_d, t.depth))

    stopper = GBTEarlyStopDecider()
    history: List[Tuple[float, float]] = []
    rng = np.random.default_rng(settings.seed)
    for ti in range(settings.n_trees):
        if settings.loss == "log":
            grad = y64 - 1.0 / (1.0 + np.exp(-f))
        elif settings.loss == "absolute":
            grad = np.sign(y64 - f)
        else:
            grad = y64 - f
        k = subset_count(settings.feature_subset, c)
        fa = np.zeros(c, bool)
        fa[rng.choice(c, size=k, replace=False)] = True
        tree = grow_tree(bins_d, grad, wt, n_bins, settings.depth,
                         impurity="variance",
                         min_instances=settings.min_instances,
                         min_gain=settings.min_gain, cat_mask=cat_mask,
                         feat_active=fa)
        trees.append(tree)
        pred = np.asarray(predict_tree(
            jnp.asarray(tree.split_feat), jnp.asarray(tree.left_mask),
            jnp.asarray(tree.leaf_value), bins_d, tree.depth))
        f = f + settings.learning_rate * pred
        tr_err, va_err = _gbt_errors(f, y64, w, tmask, vmask, settings.loss)
        history.append((tr_err, va_err))
        if progress:
            progress(ti, tr_err, va_err)
        if settings.early_stop and stopper.add(va_err):
            log.info("GBT early stop after %d trees", ti + 1)
            break
    return ForestResult(
        trees=trees,
        spec_kwargs={"algorithm": "GBT", "loss": settings.loss,
                     "learning_rate": settings.learning_rate,
                     "init_score": init_score},
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=_feature_gains(trees, c),
        trees_built=len(trees), history=history)


def _gbt_errors(f, y, w, tmask, vmask, loss: str) -> Tuple[float, float]:
    if loss == "log":
        p = 1.0 / (1.0 + np.exp(-f))
        per = -(y * np.log(np.clip(p, 1e-9, 1)) +
                (1 - y) * np.log(np.clip(1 - p, 1e-9, 1)))
    else:
        per = (y - f) ** 2
    w = np.asarray(w, np.float64)
    tw, vw = w * tmask, w * vmask
    tr = float((per * tw).sum() / max(tw.sum(), 1e-9))
    va = float((per * vw).sum() / max(vw.sum(), 1e-9)) if vmask.any() else tr
    return tr, va


def train_rf(bins, y, w, n_bins: int, cat_mask, settings: DTSettings,
             progress=None) -> ForestResult:
    """Independent Poisson-bagged trees; out-of-bag rows score validation
    (reference RF oob-as-validation, ``DTWorker.java:582-616``)."""
    n, c = bins.shape
    bins_d = jnp.asarray(bins, jnp.int32)
    y64 = np.asarray(y, np.float64)
    w64 = np.asarray(w, np.float64)
    rng = np.random.default_rng(settings.seed)
    trees: List[TreeArrays] = []
    oob_sum = np.zeros(n)
    oob_cnt = np.zeros(n)
    history: List[Tuple[float, float]] = []
    for ti in range(settings.n_trees):
        bag = rng.poisson(settings.bagging_rate, n).astype(np.float64) \
            if settings.poisson_bagging else np.ones(n)
        k = subset_count(settings.feature_subset, c)
        fa = np.zeros(c, bool)
        fa[rng.choice(c, size=k, replace=False)] = True
        tree = grow_tree(bins_d, y64, w64 * bag, n_bins, settings.depth,
                         impurity=settings.impurity,
                         min_instances=settings.min_instances,
                         min_gain=settings.min_gain, cat_mask=cat_mask,
                         feat_active=fa)
        trees.append(tree)
        pred = np.asarray(predict_tree(
            jnp.asarray(tree.split_feat), jnp.asarray(tree.left_mask),
            jnp.asarray(tree.leaf_value), bins_d, tree.depth))
        oob = bag == 0
        oob_sum[oob] += pred[oob]
        oob_cnt[oob] += 1
        seen = oob_cnt > 0
        if seen.any():
            oob_pred = oob_sum[seen] / oob_cnt[seen]
            per = (y64[seen] - oob_pred) ** 2
            va = float((per * w64[seen]).sum() / max(w64[seen].sum(), 1e-9))
        else:
            va = float("nan")
        tr = float((((y64 - pred) ** 2) * w64).sum() / max(w64.sum(), 1e-9))
        history.append((tr, va))
        if progress:
            progress(ti, tr, va)
    return ForestResult(
        trees=trees, spec_kwargs={"algorithm": "RF"},
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=_feature_gains(trees, c),
        trees_built=len(trees), history=history)


# -------------------------------------------------------- pipeline driver
def run_tree_training(proc) -> int:
    """Entry called by TrainProcessor for GBT/RF/DT."""
    mc = proc.model_config
    alg = mc.train.algorithm
    shards = Shards.open(proc.paths.clean_dir)
    data = shards.load_all()
    bins, y, w = data["bins"].astype(np.int32), data["y"], data["w"]
    col_nums = shards.schema.get("columnNums", [])
    by_num = {c.columnNum: c for c in proc.column_configs}
    cat_mask = np.array([by_num[cn].is_categorical() if cn in by_num else False
                         for cn in col_nums])
    # bin-space width from ColumnConfig (num value bins + the missing bin) —
    # NOT from observed data, which may lack rare bins under sampling and
    # would make eval-time indices overflow the left_mask
    n_bins = max((by_num[cn].num_bins() + 1 for cn in col_nums if cn in by_num),
                 default=2)
    settings = settings_from_params(mc.train.params, mc.train, alg)
    log.info("train %s: %d rows x %d features, %d bins, %d trees depth %d",
             alg.name, *bins.shape, n_bins, settings.n_trees, settings.depth)

    progress_path = proc.paths.progress_path
    with open(progress_path, "w") as pf:
        def progress(ti, tr, va):
            line = (f"Tree #{ti + 1} Train Error: {tr:.6f} "
                    f"Validation Error: {va:.6f}")
            pf.write(line + "\n")
            pf.flush()
            if (ti + 1) % 5 == 0 or ti == 0:
                log.info(line)

        init_trees, init_score = _continuous_trees(proc, alg, settings)
        if alg == Algorithm.GBT:
            res = train_gbt(bins, y, w, n_bins, cat_mask, settings, progress,
                            init_trees=init_trees, init_score=init_score)
        else:
            res = train_rf(bins, y, w, n_bins, cat_mask, settings, progress)
            res.spec_kwargs["algorithm"] = "RF" if alg != Algorithm.DT else "DT"

    spec = tree_model.TreeModelSpec(
        n_trees=len(res.trees), depth=settings.depth, n_bins=n_bins,
        column_nums=list(col_nums),
        feature_names=shards.schema.get("columnNames"),
        **res.spec_kwargs)
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    path = proc.paths.model_path(0, alg.name.lower())
    tree_model.save_model(path, spec, res.trees)

    fi_named = sorted(
        ((shards.schema.get("columnNames", [str(cn) for cn in col_nums])[j],
          float(v)) for j, v in enumerate(res.feature_importance)),
        key=lambda kv: -kv[1])
    log.info("train %s done: %d trees, train err %.6f valid err %.6f; "
             "top features %s", alg.name, res.trees_built, res.train_error,
             res.valid_error, [n for n, _ in fi_named[:5]])
    return 0


def _continuous_trees(proc, alg, settings: DTSettings
                      ) -> Tuple[Optional[List[TreeArrays]], Optional[float]]:
    """GBT continuous training appends trees to the existing forest —
    guarded like reference ``checkContinuousTraining``: the saved forest's
    shrinkage/loss must match or resuming would mis-score the old trees."""
    if not proc.model_config.train.isContinuous or alg != Algorithm.GBT:
        return None, None
    path = proc.paths.model_path(0, alg.name.lower())
    if not os.path.isfile(path):
        return None, None
    spec, trees = tree_model.load_model(path)
    if spec.loss != settings.loss or \
            abs(spec.learning_rate - settings.learning_rate) > 1e-12:
        log.warning("continuous GBT: saved forest used loss=%s lr=%s but "
                    "params now say loss=%s lr=%s — training fresh",
                    spec.loss, spec.learning_rate, settings.loss,
                    settings.learning_rate)
        return None, None
    log.info("continuous GBT: resuming from %d existing trees", len(trees))
    return trees, spec.init_score
