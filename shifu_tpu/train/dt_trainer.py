"""GBT / RF distributed trainers — reference ``DTMaster``/``DTWorker``
(``core/dtrain/dt/``, 8.5k LoC) as device-side histogram + scan loops.

- GBT (``DTWorker.java:582-686`` residual update, ``DTMaster.java:392-435``
  tree switching): sequential trees; per-tree gradients (squared: y − f,
  log: y − sigmoid(f)) refit by a variance/Friedman tree; shrinkage
  ``learning_rate``; moving-average early stop
  (``dt/DTEarlyStopDecider.java``).
- RF (``DTWorker`` Poisson bagging + oob-as-validation): independent trees
  over Poisson row weights, entropy/gini impurity, per-tree feature
  subsetting (featureSubsetStrategy ALL/HALF/SQRT/LOG2/ONETHIRD/TWOTHIRDS).
- Whole-tree growth is ONE jitted program per round (``ops.tree.
  grow_tree_jit``); residuals/oob accumulators stay device-resident across
  trees — one host sync per tree (errors + the tiny tree arrays), not per
  level (the reference syncs worker↔master stats every level).
- On a mesh, rows shard over the ``data`` axis and XLA's psum aggregates the
  [nodes, C, B, S] histograms — the ``DTWorker``→``DTMaster`` merge
  (``DTMaster.java:274-533``) on ICI.
- Streaming mode (dataset > memory budget): per-level histogram accumulation
  over ``ShardStream`` windows; per-row residual/oob state lives in compact
  host caches (rows × 8B, ~100× smaller than the binned matrix).
- Mid-forest checkpointing every N trees + ``train -resume`` (reference
  ``DTMaster.doCheckPoint``, ``:637``); per-tree stateless RNG keys make a
  resumed run bit-identical to an uninterrupted one.
- Feature importance accumulates realized split GAINS (reference GainInfo
  aggregation), not split counts.
"""

from __future__ import annotations

import json
import logging
import os
from dataclasses import dataclass, field, replace
from functools import lru_cache, partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import faults, ioutil, obs
from ..config.model_config import Algorithm
from ..data.shards import Shards
from ..models import tree as tree_model
from ..ops.tree import (TreeArrays, _left_child_index, _level_leaf_raw,
                        best_splits, build_histograms,
                        build_histograms_batch, build_path_histograms,
                        cap_splits_by_leaves, grow_forest_jit,
                        grow_tree_jit, leaf_values_from_raw, n_tree_nodes,
                        node_index_at_level, predict_tree)
from .early_stop import GBTEarlyStopDecider
from .sampling import validation_split

log = logging.getLogger(__name__)


@dataclass
class DTSettings:
    n_trees: int = 100
    depth: int = 7
    impurity: str = "variance"
    loss: str = "squared"
    learning_rate: float = 0.05          # GBT shrinkage
    min_instances: float = 1.0
    min_gain: float = 0.0
    feature_subset: str = "ALL"
    valid_rate: float = 0.2
    bagging_rate: float = 1.0            # RF Poisson rate
    poisson_bagging: bool = True         # False: plain single tree (DT)
    early_stop: bool = False
    seed: int = 0
    checkpoint_dir: str = ""             # "" disables mid-forest checkpoints
    checkpoint_every: int = 25           # trees between checkpoints
    resume: bool = False
    n_classes: int = 0                   # >2: RF multiclass NATIVE mode
    max_leaves: int = 0                  # >0: leaf-wise node budget
    stats_exact: bool = False            # weights promised small-integer
                                         # (no weight column): RF hist
                                         # kernel skips f32-recovery dots
    tree_batch: int = 0                  # RF same-round trees grown per
                                         # batched device program; 0 = auto
                                         # (RF_TREE_BATCH)
    early_stop_check: int = 8            # trees between early-stop
                                         # decisions (device-accumulated
                                         # errors fetch in bulk)
    tail_tree_batch: int = 0             # RF disk-tail super-batch: trees
                                         # fed by one tail re-stream; 0 =
                                         # auto (budget-derived, see
                                         # _tail_super_batch)


def settings_from_params(params: Dict[str, Any], train_conf,
                         alg: Algorithm) -> DTSettings:
    """Reference train#params tree keys (``DTMaster.java:91`` init region):
    TreeNum / MaxDepth / Impurity / Loss / LearningRate /
    FeatureSubsetStrategy / MinInstancesPerNode / MinInfoGain."""
    p = params or {}
    default_impurity = "variance" if alg == Algorithm.GBT else "entropy"
    return DTSettings(
        n_trees=int(p.get("TreeNum", 10 if alg != Algorithm.DT else 1)),
        depth=int(p.get("MaxDepth", 7)),
        impurity=str(p.get("Impurity", default_impurity)).lower(),
        loss=str(p.get("Loss", "squared")).lower(),
        learning_rate=float(p.get("LearningRate", 0.05)),
        min_instances=float(p.get("MinInstancesPerNode", 1)),
        min_gain=float(p.get("MinInfoGain", 0.0)),
        feature_subset=str(p.get("FeatureSubsetStrategy", "ALL")).upper(),
        max_leaves=max(0, int(p.get("MaxLeaves", -1))),
        valid_rate=float(train_conf.validSetRate),
        bagging_rate=float(train_conf.baggingSampleRate),
        poisson_bagging=alg != Algorithm.DT,  # plain DT = one tree, full data
        early_stop=bool(train_conf.earlyStopEnable),
        seed=int(p.get("Seed", 0)),
        checkpoint_every=int(p.get("CheckpointInterval", 25)),
        tree_batch=int(p.get("TreeBatch", 0)),
        early_stop_check=max(1, int(p.get("EarlyStopCheckInterval", 8))),
        tail_tree_batch=int(p.get("TailTreeBatch", 0)))


def subset_count(strategy: str, c: int) -> int:
    s = strategy.upper()
    if s == "ALL":
        return c
    if s == "HALF":
        return max(1, c // 2)
    if s == "SQRT":
        return max(1, int(np.sqrt(c)))
    if s == "LOG2":
        return max(1, int(np.log2(max(c, 2))))
    if s == "ONETHIRD":
        return max(1, c // 3)
    if s == "TWOTHIRDS":
        return max(1, 2 * c // 3)
    return c


def _tree_rng(seed: int, tree_idx: int) -> np.random.Generator:
    """Stateless per-tree RNG: resume from tree k reproduces the exact
    feature subsets / bags an uninterrupted run would draw."""
    return np.random.default_rng([seed, tree_idx])


def _feat_subset(settings: DTSettings, c: int, tree_idx: int) -> np.ndarray:
    k = subset_count(settings.feature_subset, c)
    fa = np.zeros(c, bool)
    fa[_tree_rng(settings.seed, tree_idx).choice(c, size=k, replace=False)] = True
    return fa


@dataclass
class ForestResult:
    trees: List[TreeArrays]
    spec_kwargs: Dict[str, Any]
    train_error: float
    valid_error: float
    feature_importance: np.ndarray       # [C] summed split gains
    trees_built: int = 0
    history: List[Tuple[float, float]] = field(default_factory=list)
    disk_passes: int = 0                 # streamed mode: cold stream sweeps taken
    tail_sweeps: int = 0                 # streamed mode: disk-tail re-streams
                                         # (the super-batch schedule's guard
                                         # metric; bench extras read it)
    bytes_read: int = 0                  # streamed mode: bytes this train
                                         # run pulled off disk (host-side
                                         # stream accounting, telemetry-
                                         # independent)


# ---------------------------------------------------------------- jitted rounds
def _loss_grad(y, f, loss: str):
    if loss == "log":
        return y - jax.nn.sigmoid(f)
    if loss == "absolute":
        return jnp.sign(y - f)
    return y - f


def _per_row_loss(y, f, loss: str):
    if loss == "log":
        p = jnp.clip(jax.nn.sigmoid(f), 1e-9, 1 - 1e-9)
        return -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    if loss == "absolute":
        return jnp.abs(y - f)
    return (y - f) ** 2


def _gbt_round_impl(bins, y, tw, vw, f, fa, cat, lr, min_instances,
                    min_gain, n_bins: int, depth: int, impurity: str,
                    loss: str, use_pallas: bool = False,
                    max_leaves: int = 0, has_cat: bool = True, mesh=None):
    """One GBT tree end-to-end on device: residual grad → grow → predict →
    score update → train/valid error sums.  Only the tree arrays and two
    scalars cross to the host."""
    grad = _loss_grad(y, f, loss)
    stats = jnp.stack([tw, tw * grad], axis=1).astype(jnp.float32)
    sf, lm, lv, gfi, leaf_glob = grow_tree_jit(
        bins, stats, cat, fa, n_bins, depth, impurity, min_instances,
        min_gain, use_pallas=use_pallas, max_leaves=max_leaves,
        has_cat=has_cat, mesh=mesh)
    pred = jnp.take(lv, leaf_glob, axis=0)   # growth already walked the
    f2 = f + lr * pred                       # rows to their leaves
    per = _per_row_loss(y, f2, loss)
    tr = (per * tw).sum() / jnp.maximum(tw.sum(), 1e-9)
    va = (per * vw).sum() / jnp.maximum(vw.sum(), 1e-9)
    return sf, lm, lv, gfi, f2, tr, va



def _gbt_forest_impl(bins, y, tw, vw, f, fa_all, cat, lr, min_instances,
                     min_gain, n_bins: int, depth: int, impurity: str,
                     loss: str, n_trees: int, use_pallas: bool = False,
                     max_leaves: int = 0, has_cat: bool = True, mesh=None):
    """A whole chunk of the GBT forest as ONE executable (``lax.scan`` over
    trees).  The per-tree loop costs one program execution per tree; over a
    remote-device link each execution carries latency that dwarfs the
    sub-ms tree compute (measured ~0.8 s/exec cold vs ~0.3 ms compute), so
    the forest scans on device and crosses to the host once.  This is the
    natural end point of the reference's master/worker iteration collapse
    (``DTMaster.java:274-533`` per-iteration sync → zero syncs)."""
    del n_trees    # shape comes from fa_all; static arg keys the cache

    def body(f, fa):
        sf, lm, lv, gfi, f2, tr, va = _gbt_round_impl(
            bins, y, tw, vw, f, fa, cat, lr, min_instances, min_gain,
            n_bins, depth, impurity, loss, use_pallas, max_leaves,
            has_cat, mesh)
        return f2, _pack_tree_impl(sf, lm, lv, gfi, tr, va)

    f_out, packed = jax.lax.scan(body, f, fa_all)
    return f_out, packed


# cost-attributed (obs/costs, lazy: wrapped at import, telemetry flips
# later): the resident whole-forest executable — the gbt plane's main
# cost entry for the utilization report
_gbt_forest = obs.costed_jit("gbt.forest", _gbt_forest_impl, lazy=True,
                             static_argnames=(
    "n_bins", "depth", "impurity", "loss", "n_trees", "use_pallas",
    "max_leaves", "has_cat", "mesh"))


@lru_cache(maxsize=None)
def _gbt_forest_multi(n_bins: int, depth: int, impurity: str, loss: str,
                      n_trees: int, use_pallas: bool, max_leaves: int,
                      has_cat: bool, mesh=None):
    """vmapped :func:`_gbt_forest_impl` over a leading member axis —
    bagging members / same-structure grid trials train as ONE executable
    (reference queues one Guagua job per bag/combo,
    ``TrainModelProcessor.java:768-945``).  Members vary in weights,
    scores, feature subsets and the traced scalar hypers (lr /
    min_instances / min_gain); ``bins``/``y``/``cat`` broadcast."""
    def one(bins, y, tw, vw, f, fa_all, cat, lr, mi, mg):
        return _gbt_forest_impl(bins, y, tw, vw, f, fa_all, cat, lr, mi,
                                mg, n_bins, depth, impurity, loss, n_trees,
                                use_pallas, max_leaves, has_cat, mesh)
    return obs.costed_jit(
        "gbt.forest_bagged",
        jax.vmap(one, in_axes=(None, None, 0, 0, 0, 0, None, 0, 0,
                               0)))


def _stats_bf16_exact(w) -> bool:
    """True when every weight is a small non-negative integer, so RF stat
    channels (Poisson bag counts x weights x 0/1 targets) are exactly
    representable in bfloat16 and the histogram kernel may skip its
    f32-recovery dots (``ops/hist_pallas._hist_kernel``, ~1.6x).  Bag
    counts cap at 16, so w <= 16 keeps products <= 256 (bf16-exact)."""
    w = np.asarray(w)
    return bool(w.size and (w >= 0).all() and (w <= 16).all()
                and (np.mod(w, 1) == 0).all())


def _rf_round_impl(bins, y, w, key, bag_rate, oob_sum, oob_cnt, fa, cat,
                   min_instances, min_gain, n_bins: int, depth: int,
                   impurity: str, loss: str, poisson: bool,
                   n_classes: int = 0, use_pallas: bool = False,
                   max_leaves: int = 0, has_cat: bool = True, mesh=None,
                   stats_exact: bool = False):
    """One RF tree on device: Poisson bag → grow → oob accumulate →
    loss-consistent oob validation error (reference oob-as-validation,
    ``DTWorker.java:582-616``; round 1 hardcoded squared error).

    Multiclass NATIVE (``n_classes > 2``): per-class stat channels, leaf
    class distributions, misclassification-rate errors (reference
    ``dt/Impurity.java:368,553`` multiclass Entropy/Gini)."""
    n = bins.shape[0]
    bag = jax.random.poisson(key, bag_rate, (n,)).astype(jnp.float32) \
        if poisson else jnp.ones(n, jnp.float32)
    return _rf_round_from_bag(bins, y, w, bag, oob_sum, oob_cnt, fa, cat,
                              min_instances, min_gain, n_bins, depth,
                              impurity, loss, n_classes, use_pallas,
                              max_leaves, has_cat, mesh, stats_exact)


def _rf_stats_from_bag(y, w, bag, n_classes: int):
    """Per-row stat channels of one RF tree's bag — the ONE place the
    channel layout lives (per-tree, batched and streamed paths must never
    drift)."""
    bw = w * bag
    if n_classes > 2:
        return bw[:, None] * jax.nn.one_hot(y.astype(jnp.int32), n_classes,
                                            dtype=jnp.float32)
    return jnp.stack([bw, bw * y], axis=1).astype(jnp.float32)


def _rf_oob_update(pred, y, w, bag, oob_sum, oob_cnt, loss: str,
                   n_classes: int):
    """Out-of-bag vote accumulation + loss-consistent errors for ONE grown
    tree (reference oob-as-validation, ``DTWorker.java:582-616``) —
    shared by the per-tree round and the tree-batched round so their
    error streams stay bit-identical.  Returns (oob_sum, oob_cnt, tr, va).
    """
    oob = (bag == 0) & (w > 0)
    if n_classes > 2:
        yi = y.astype(jnp.int32)
        oob_sum = oob_sum + jnp.where(oob[:, None], pred, 0.0)
        oob_cnt = oob_cnt + oob.astype(oob_cnt.dtype)
        seen = oob_cnt > 0
        per_v = (jnp.argmax(oob_sum, axis=-1) != yi).astype(jnp.float32)
        per_t = (jnp.argmax(pred, axis=-1) != yi).astype(jnp.float32)
        wv = w * seen
        va = (per_v * wv).sum() / jnp.maximum(wv.sum(), 1e-9)
        tr = (per_t * w).sum() / jnp.maximum(w.sum(), 1e-9)
        return oob_sum, oob_cnt, tr, va
    oob_sum = oob_sum + jnp.where(oob, pred, 0.0)
    oob_cnt = oob_cnt + oob.astype(oob_cnt.dtype)
    seen = oob_cnt > 0
    oob_pred = oob_sum / jnp.maximum(oob_cnt, 1.0)
    # RF votes average probabilities; log loss needs them clipped, not logit
    if loss == "log":
        p = jnp.clip(oob_pred, 1e-9, 1 - 1e-9)
        per_v = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
    else:
        per_v = _per_row_loss(y, oob_pred, loss)
    wv = w * seen
    va = (per_v * wv).sum() / jnp.maximum(wv.sum(), 1e-9)
    per_t = _per_row_loss(y, pred, loss) if loss != "log" else \
        -(y * jnp.log(jnp.clip(pred, 1e-9, 1 - 1e-9))
          + (1 - y) * jnp.log(jnp.clip(1 - pred, 1e-9, 1 - 1e-9)))
    tr = (per_t * w).sum() / jnp.maximum(w.sum(), 1e-9)
    return oob_sum, oob_cnt, tr, va


def _rf_round_from_bag(bins, y, w, bag, oob_sum, oob_cnt, fa, cat,
                       min_instances, min_gain, n_bins: int, depth: int,
                       impurity: str, loss: str, n_classes: int = 0,
                       use_pallas: bool = False, max_leaves: int = 0,
                       has_cat: bool = True, mesh=None,
                       stats_exact: bool = False):
    """RF round body given a PRECOMPUTED bag — shared by the resident
    path (Poisson drawn in-graph above) and the streamed mega path
    (hash bags replayed on device, ``ops/hashing.py``)."""
    stats = _rf_stats_from_bag(y, w, bag, n_classes)
    sf, lm, lv, gfi, leaf_glob = grow_tree_jit(
        bins, stats, cat, fa, n_bins, depth, impurity, min_instances,
        min_gain, n_classes, use_pallas, max_leaves, has_cat, mesh,
        stats_exact)
    pred = jnp.take(lv, leaf_glob, axis=0)         # [n, K] mc, [n] binary
    oob_sum, oob_cnt, tr, va = _rf_oob_update(
        pred, y, w, bag, oob_sum, oob_cnt, loss, n_classes)
    return sf, lm, lv, gfi, oob_sum, oob_cnt, tr, va



def _mask_nbytes(total: int, n_bins: int) -> int:
    return (total * n_bins + 7) // 8


def _pack_mask_bits(lm):
    """left_mask bits packed 8-per-byte-value (f32-exact 0..255) for the
    host fetch — the mask is ~96%% of a packed tree's floats, so bit
    packing shrinks every tree transfer ~8x on the wire.  MSB-first to
    match ``np.unpackbits`` in :func:`_unpack_mask_bits`."""
    flat = lm.reshape(-1).astype(jnp.float32)
    pad = (-flat.shape[0]) % 8
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
    w = jnp.asarray([128, 64, 32, 16, 8, 4, 2, 1], jnp.float32)
    return flat.reshape(-1, 8) @ w


def _unpack_mask_bits(vals: np.ndarray, total: int, n_bins: int):
    bits = np.unpackbits(np.asarray(np.rint(vals), np.uint8))
    return bits[:total * n_bins].reshape(total, n_bins) > 0


def _pack_tree_impl(sf, lm, lv, gfi, tr, va):
    """Flatten one round's outputs into a single f32 vector so the host
    fetches the whole tree in ONE transfer.  The tunnel to the chip costs
    ~100-250 ms per transfer regardless of size (measured on this rig);
    unbatched per-array fetches dominated round-2 GBT wall-clock ~15:1
    over compute."""
    return jnp.concatenate([
        sf.astype(jnp.float32), _pack_mask_bits(lm),
        lv.reshape(-1).astype(jnp.float32), gfi.astype(jnp.float32),
        jnp.stack([tr, va]).astype(jnp.float32)])


_pack_tree = jax.jit(_pack_tree_impl)  # shifu-lint: disable=recompile-hazard

# RF same-round trees grown per batched device program in the RESIDENT
# path (``grow_forest_jit``): each level's TB histograms build in ONE
# kernel launch with the bins one-hot shared across the batch.  8 matches
# the tail-sweep batch and the progress burst size.
RF_TREE_BATCH = 8


def _effective_tree_batch(settings: DTSettings) -> int:
    """The RF resident tree-batch width: ``TreeBatch`` train param /
    ``SHIFU_TREE_BATCH`` env; 0 = auto (:data:`RF_TREE_BATCH`)."""
    env = os.environ.get("SHIFU_TREE_BATCH")
    if env:
        return max(1, int(env))
    return settings.tree_batch if settings.tree_batch > 0 \
        else RF_TREE_BATCH


def _rf_forest_impl(bins, y, w, base_key, tree_ids, bag_rate, oob_sum,
                    oob_cnt, fa_all, cat, min_instances, min_gain,
                    n_bins: int, depth: int, impurity: str, loss: str,
                    poisson: bool, n_classes: int, n_trees: int,
                    use_pallas: bool = False, max_leaves: int = 0,
                    has_cat: bool = True, mesh=None,
                    stats_exact: bool = False, tree_batch: int = 1):
    """A chunk of the RF forest as ONE executable (see :func:`_gbt_forest`).
    Per-tree keys fold the tree id into the base key on device — identical
    draws to the per-tree path, so resumed and scanned runs agree.

    ``tree_batch > 1``: RF trees are mutually independent, so the scan
    grows TB same-round trees per step through :func:`grow_forest_jit` —
    each level's TB histograms build in ONE kernel launch (the reference's
    ``DTMaster`` grows all RF trees of a round simultaneously,
    ``DTMaster.java:91`` toDoQueue).  Bags/keys/oob votes replay the exact
    per-tree stream (bags are per-tree key folds; oob votes chain through
    the batch in tree order), so results are bit-identical to
    ``tree_batch=1``; a chunk remainder past the last full batch runs the
    per-tree scan."""
    del n_trees
    n = bins.shape[0]

    def one_tree(carry, fa, ti):
        oob_sum, oob_cnt = carry
        key = jax.random.fold_in(base_key, ti)
        sf, lm, lv, gfi, oob_sum2, oob_cnt2, tr, va = _rf_round_impl(
            bins, y, w, key, bag_rate, oob_sum, oob_cnt, fa, cat,
            min_instances, min_gain, n_bins, depth, impurity, loss,
            poisson, n_classes, use_pallas, max_leaves, has_cat, mesh,
            stats_exact)
        return (oob_sum2, oob_cnt2), _pack_tree_impl(sf, lm, lv, gfi, tr, va)

    def body(carry, inp):
        fa, ti = inp
        return one_tree(carry, fa, ti)

    def body_batched(carry, inp):
        oob_sum, oob_cnt = carry
        fa_b, ti_b = inp                       # [TB, C], [TB]
        keys = jax.vmap(lambda t: jax.random.fold_in(base_key, t))(ti_b)
        if poisson:
            bags = jax.vmap(lambda k: jax.random.poisson(
                k, bag_rate, (n,)).astype(jnp.float32))(keys)
        else:
            bags = jnp.ones((tree_batch, n), jnp.float32)
        stats_b = jax.vmap(
            lambda bag: _rf_stats_from_bag(y, w, bag, n_classes))(bags)
        sf_b, lm_b, lv_b, gfi_b, lg_b = grow_forest_jit(
            bins, stats_b, cat, fa_b, n_bins, depth, impurity,
            min_instances, min_gain, n_classes, use_pallas, max_leaves,
            has_cat, mesh, stats_exact)
        packed = []
        for j in range(tree_batch):            # oob votes chain in order
            pred = jnp.take(lv_b[j], lg_b[j], axis=0)
            oob_sum, oob_cnt, tr, va = _rf_oob_update(
                pred, y, w, bags[j], oob_sum, oob_cnt, loss, n_classes)
            packed.append(_pack_tree_impl(sf_b[j], lm_b[j], lv_b[j],
                                          gfi_b[j], tr, va))
        return (oob_sum, oob_cnt), jnp.stack(packed)

    t_total = fa_all.shape[0]
    tb = max(1, tree_batch)
    main = (t_total // tb) * tb if tb > 1 else 0
    parts = []
    carry = (oob_sum, oob_cnt)
    if main:
        fa_g = fa_all[:main].reshape(main // tb, tb, fa_all.shape[1])
        ti_g = tree_ids[:main].reshape(main // tb, tb)
        carry, packed_g = jax.lax.scan(body_batched, carry, (fa_g, ti_g))
        parts.append(packed_g.reshape(main, -1))
    if main < t_total:
        carry, packed_r = jax.lax.scan(
            body, carry, (fa_all[main:], tree_ids[main:]))
        parts.append(packed_r)
    oob_sum, oob_cnt = carry
    packed = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
    return oob_sum, oob_cnt, packed


_rf_forest = obs.costed_jit("rf.forest", _rf_forest_impl, lazy=True,
                            static_argnames=(
    "n_bins", "depth", "impurity", "loss", "poisson", "n_classes",
    "n_trees", "use_pallas", "max_leaves", "has_cat",
    "mesh", "stats_exact", "tree_batch"))


@lru_cache(maxsize=None)
def _rf_forest_multi(n_bins: int, depth: int, impurity: str, loss: str,
                     poisson: bool, n_classes: int, n_trees: int,
                     use_pallas: bool, max_leaves: int, has_cat: bool,
                     mesh=None, stats_exact: bool = False):
    """vmapped :func:`_rf_forest_impl` over a leading member axis (see
    :func:`_gbt_forest_multi`); members vary in weights, keys, oob state,
    feature subsets, bag rate and the traced scalar hypers."""
    def one(bins, y, w, base_key, tree_ids, bag_rate, oob_sum, oob_cnt,
            fa_all, cat, mi, mg):
        return _rf_forest_impl(bins, y, w, base_key, tree_ids, bag_rate,
                               oob_sum, oob_cnt, fa_all, cat, mi, mg,
                               n_bins, depth, impurity, loss, poisson,
                               n_classes, n_trees, use_pallas, max_leaves,
                               has_cat, mesh, stats_exact)
    return obs.costed_jit(
        "rf.forest_bagged",
        jax.vmap(one, in_axes=(None, None, 0, 0, None, 0, 0, 0, 0,
                               None, 0, 0)))


def _unpack_tree(vec: np.ndarray, total: int, n_bins: int, c: int,
                 depth: int, n_classes: int = 0):
    """Host-side inverse of :func:`_pack_tree`."""
    k = n_classes if n_classes > 2 else 1
    sizes = [total, _mask_nbytes(total, n_bins), total * k, c, 2]
    parts = np.split(vec, np.cumsum(sizes)[:-1])
    lv = parts[2].astype(np.float32)
    if k > 1:
        lv = lv.reshape(total, k)
    tree = TreeArrays(split_feat=parts[0].astype(np.int32),
                      left_mask=_unpack_mask_bits(parts[1], total, n_bins),
                      leaf_value=lv, depth=depth)
    return tree, parts[3].astype(np.float64), float(parts[4][0]), \
        float(parts[4][1])


def _fetch(x) -> np.ndarray:
    """Device→host materialization of packed trainer results — the ONE
    counted host-sync point.  The telemetry counter lets tests (and
    ``analysis --telemetry``) pin that syncs scale with checkpoint/progress
    intervals, not with trees (tentpole: sync-free growth)."""
    obs.counter("train.host_syncs").inc()
    return np.asarray(x)


def _use_pallas(mesh) -> bool:
    """MXU histogram kernel dispatch.  On a multi-device mesh the kernel
    runs per-shard under ``shard_map`` with a psum merge over the data
    axis (``ops.hist_pallas.build_histograms_sharded``) — the trainers
    thread their mesh down so ``build_histograms`` can place it; a single
    device takes the plain kernel.  Gated on the MESH devices' platform
    (a CPU mesh on a TPU-backed host must not take the Mosaic path)."""
    from ..ops.hist_pallas import pallas_available
    return pallas_available(mesh)


def _hist_mesh(mesh):
    """The mesh build_histograms should shard_map over: only a real
    multi-device mesh matters (None keeps jit caches unified)."""
    return mesh if (mesh is not None and mesh.size > 1) else None


def _wire_bins_dtype(n_bins: int):
    """Narrowest host→device wire dtype that holds bin ids 0..n_bins-1
    (``data.shards.bins_wire_dtype`` — uint8 for <=256 bins).  The
    transfer is a real cost (the bench tunnel moves ~20 MB/s; real rigs
    pay PCIe), and the reference itself stores worker rows as short[] bin
    ids (``DTWorker.java:100``) — int32 on the wire is pure waste."""
    from ..data.shards import bins_wire_dtype
    return bins_wire_dtype(n_bins)


def _put_bins(mesh, bins, n_bins: int):
    """bins → device in the compact wire dtype — and KEPT narrow in HBM
    (4x more resident windows per cache budget at uint8); the tree
    kernels widen to int32 in-graph (``ops.tree.build_histograms``).
    Spill-cache windows already arrive in the wire dtype, so the put is a
    zero-copy read straight out of the mmap."""
    bins = np.asarray(bins)
    wire = _wire_bins_dtype(n_bins)
    if wire != bins.dtype and bins.size:
        # a stale clean dir / re-binned ColumnConfig mismatch must fail
        # loudly, not wrap ids into negatives via the narrowing cast
        lo, hi = int(bins.min()), int(bins.max())
        if lo < 0 or hi >= n_bins:
            raise ValueError(
                f"bin ids [{lo}, {hi}] out of range for n_bins={n_bins} — "
                "the materialized clean data does not match the current "
                "ColumnConfig binning; re-run `norm`")
        bins = bins.astype(wire)
    [b] = _device_put_rows(mesh, bins)
    return b


def _device_put_rows(mesh, *arrays):
    """Shard row-indexed arrays over the mesh's data axis (padding rows with
    zeros so the extent divides; padded rows carry zero weight by
    construction of the weight arrays)."""
    if mesh is None:
        return [jnp.asarray(a) for a in arrays]
    from jax.sharding import NamedSharding, PartitionSpec as P
    data_size = mesh.shape["data"]
    n = arrays[0].shape[0]
    extra = (-n) % data_size
    out = []
    for a in arrays:
        a = np.asarray(a)
        if extra:
            pad = np.zeros((extra,) + a.shape[1:], a.dtype)
            a = np.concatenate([a, pad])
        spec = P("data") if a.ndim == 1 else P("data", None)
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return out


def train_gbt(bins, y, w, n_bins: int, cat_mask, settings: DTSettings,
              progress=None, init_trees: Optional[List[TreeArrays]] = None,
              init_score: Optional[float] = None, mesh=None,
              checkpoint_fn: Optional[Callable] = None,
              start_history: Optional[List] = None,
              init_scores: Optional[np.ndarray] = None) -> ForestResult:
    n, c = bins.shape
    vmask = validation_split(n, settings.valid_rate, settings.seed)
    wt = np.asarray(w, np.float64) * ~vmask
    wv = np.asarray(w, np.float64) * vmask
    y64 = np.asarray(y, np.float64)

    if init_score is None:  # continuous runs reuse the saved forest's prior
        prior = float((y64 * wt).sum() / max(wt.sum(), 1e-9))
        if settings.loss == "log":
            prior = np.clip(prior, 1e-6, 1 - 1e-6)
            init_score = float(np.log(prior / (1 - prior)))
        else:
            init_score = prior

    bins_d = _put_bins(mesh, bins, n_bins)
    y_d, tw_d, vw_d = _device_put_rows(
        mesh, y64.astype(np.float32),
        wt.astype(np.float32), wv.astype(np.float32))
    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())

    trees: List[TreeArrays] = list(init_trees or [])
    if trees and init_scores is not None and len(init_scores) == n:
        # checkpointed per-row scores: restore f BYTE-exact.  Replaying
        # trees eagerly is only f32-equivalent — XLA fuses the in-scan
        # `f + lr * predict` differently (FMA), so a replayed f can flip
        # borderline splits and break the bit-identical-resume contract
        [f] = _device_put_rows(mesh,
                               np.asarray(init_scores, np.float32))
    else:
        f = jnp.full(bins_d.shape[0], init_score, jnp.float32)
        from ..ops import tree_quant as tq
        if trees and tq.quant_scoring() and tq.bins_fit_uint8(n_bins) \
                and len({t.depth for t in trees}) == 1:
            # continuous-training replay: ONE batched quantized traversal
            # over the uint8-resident plane instead of a per-tree predict
            # loop; the per-tree adds keep the eager loop's summation
            # order, so the restored f stays bit-identical to it
            preds = tq.predict_forest_quant(
                *tq.stack_forest_quant(trees), bins_d, trees[0].depth)
            for i in range(len(trees)):
                f = f + settings.learning_rate * preds[i]
        else:
            for t in trees:  # heterogeneous depths: per-tree replay
                f = f + settings.learning_rate * predict_tree(
                    jnp.asarray(t.split_feat), jnp.asarray(t.left_mask),
                    jnp.asarray(t.leaf_value), bins_d, t.depth)

    stopper = GBTEarlyStopDecider()
    history: List[Tuple[float, float]] = list(start_history or [])
    replay_stopped = False
    for tr_prev, va_prev in history:
        # a restored forest that already hit its stop must not grow —
        # the checkpointed trees ARE the truncated early-stop forest
        if stopper.add(va_prev) and settings.early_stop:
            replay_stopped = True
    fi = np.zeros(c)
    total = n_tree_nodes(settings.depth)
    imp = "friedmanmse" if settings.impurity == "friedmanmse" else "variance"
    up = _use_pallas(mesh)
    ckpt = settings.checkpoint_every if (checkpoint_fn and
                                         settings.checkpoint_every) else 0

    # whole-forest scan: one executable + one fetch per chunk — zero
    # per-tree host round-trips.  A progress consumer gets its lines in
    # bursts of 8 trees (the progress file is a tail surface, and
    # per-tree fetches cost ~0.8 s each over a remote-device link).
    # Early stop no longer forces a per-tree sync either: errors
    # accumulate ON DEVICE inside the scan and the stop decision is
    # checked every ``early_stop_check`` trees on the bulk-fetched error
    # history; a mid-chunk trigger truncates the forest to the exact tree
    # the per-tree loop would have stopped at (trees are a prefix), so
    # results stay bit-identical at 1/K the syncs.
    ti = len(trees)
    stopped = replay_stopped
    while ti < settings.n_trees and not stopped:
        chunk = settings.n_trees - ti
        if ckpt:
            chunk = min(chunk, ((ti // ckpt) + 1) * ckpt - ti)
        if progress:
            chunk = min(chunk, 8)
        if settings.early_stop:
            chunk = min(chunk, settings.early_stop_check)
        fa_all = jnp.asarray(np.stack(
            [_feat_subset(settings, c, t)
             for t in range(ti, ti + chunk)]))
        f, packed = _gbt_forest(
            bins_d, y_d, tw_d, vw_d, f, fa_all, cat,
            settings.learning_rate, settings.min_instances,
            settings.min_gain, n_bins, settings.depth, imp,
            settings.loss, chunk, up, settings.max_leaves, hc,
            _hist_mesh(mesh))
        for j, vec in enumerate(_fetch(packed)):
            tree, gfi, tr_err, va_err = _unpack_tree(
                vec, total, n_bins, c, settings.depth)
            trees.append(tree)
            fi += gfi
            history.append((tr_err, va_err))
            if progress:
                progress(ti + j, tr_err, va_err)
            if settings.early_stop and stopper.add(va_err):
                # ignore the chunk tail past the trigger — exactly the
                # forest (and FI/history) the per-tree decision loop
                # would have kept
                obs.event("early_stop", trainer="gbt", tree=ti + j + 1)
                log.info("GBT early stop after %d trees", ti + j + 1)
                stopped = True
                break
        ti += chunk
        if ckpt:
            # TreeBatch-boundary checkpointing: every chunk is a commit
            # point (checkpoint_every stays the upper bound via the chunk
            # cap above); an early-stopped chunk checkpoints its
            # TRUNCATED forest so a crash before the final model write
            # resumes to the identical stop state.  Scores ride along so
            # resume restores f byte-exact (None after a stop: f holds
            # the dropped tail trees' updates, and a stopped forest
            # never grows again anyway)
            checkpoint_fn(trees, history, init_score,
                          None if stopped else np.asarray(f)[:n])
    return ForestResult(
        trees=trees,
        spec_kwargs={"algorithm": "GBT", "loss": settings.loss,
                     "learning_rate": settings.learning_rate,
                     "init_score": init_score},
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=fi,
        trees_built=len(trees), history=history)


def train_rf(bins, y, w, n_bins: int, cat_mask, settings: DTSettings,
             progress=None, mesh=None,
             checkpoint_fn: Optional[Callable] = None,
             init_trees: Optional[List[TreeArrays]] = None,
             start_history: Optional[List] = None) -> ForestResult:
    """Independent Poisson-bagged trees; out-of-bag rows score validation
    with the configured loss."""
    n, c = bins.shape
    se = settings.stats_exact or _stats_bf16_exact(w)
    bins_d = _put_bins(mesh, bins, n_bins)
    y_d, w_d = _device_put_rows(
        mesh, np.asarray(y, np.float32), np.asarray(w, np.float32))
    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())
    mc = settings.n_classes > 2
    oob_shape = (bins_d.shape[0], settings.n_classes) if mc \
        else (bins_d.shape[0],)
    oob_sum = jnp.zeros(oob_shape, jnp.float32)
    oob_cnt = jnp.zeros(bins_d.shape[0], jnp.float32)
    trees: List[TreeArrays] = list(init_trees or [])
    history: List[Tuple[float, float]] = list(start_history or [])
    fi = np.zeros(c)
    base_key = jax.random.PRNGKey(settings.seed)
    start = len(trees)
    if start:  # rebuild oob state by replaying stored trees with their bags
        for ti, t_old in enumerate(trees):
            key = jax.random.fold_in(base_key, ti)
            bag = jax.random.poisson(key, settings.bagging_rate,
                                     (bins_d.shape[0],)).astype(jnp.float32) \
                if settings.poisson_bagging else jnp.ones(bins_d.shape[0])
            pred = predict_tree(jnp.asarray(t_old.split_feat),
                                jnp.asarray(t_old.left_mask),
                                jnp.asarray(t_old.leaf_value), bins_d,
                                t_old.depth)
            oob = (bag == 0) & (w_d > 0)
            oob_sum = oob_sum + jnp.where(oob[:, None] if mc else oob,
                                          pred, 0.0)
            oob_cnt = oob_cnt + oob.astype(jnp.float32)
    total = n_tree_nodes(settings.depth)
    up = _use_pallas(mesh)
    ckpt = settings.checkpoint_every if (checkpoint_fn and
                                         settings.checkpoint_every) else 0

    def absorb(flat: np.ndarray, with_history: bool):
        nonlocal fi
        for vec in flat:
            tree, gfi, tr_err, va_err = _unpack_tree(
                vec, total, n_bins, c, settings.depth, settings.n_classes)
            trees.append(tree)
            fi += gfi
            if with_history:
                history.append((tr_err, va_err))

    # whole-forest scan (see _gbt_forest): one executable + one fetch per
    # chunk; progress consumers get their lines in bursts of 8 trees
    ti = start
    while ti < settings.n_trees:
        chunk = settings.n_trees - ti
        if ckpt:
            chunk = min(chunk, ((ti // ckpt) + 1) * ckpt - ti)
        if progress:
            chunk = min(chunk, 8)
        fa_all = jnp.asarray(np.stack(
            [_feat_subset(settings, c, t)
             for t in range(ti, ti + chunk)]))
        tree_ids = jnp.arange(ti, ti + chunk, dtype=jnp.uint32)
        oob_sum, oob_cnt, packed = _rf_forest(
            bins_d, y_d, w_d, base_key, tree_ids,
            settings.bagging_rate, oob_sum, oob_cnt, fa_all, cat,
            settings.min_instances, settings.min_gain, n_bins,
            settings.depth, settings.impurity, settings.loss,
            settings.poisson_bagging, settings.n_classes, chunk, up,
            settings.max_leaves, hc, _hist_mesh(mesh), se,
            _effective_tree_batch(settings))
        before = len(history)
        absorb(_fetch(packed), with_history=True)
        if progress:
            for j, (tr_err, va_err) in enumerate(history[before:],
                                                 start=ti):
                progress(j, tr_err, va_err)
        ti += chunk
        if ckpt:                       # TreeBatch-boundary checkpointing
            checkpoint_fn(trees, history, None)
    spec_kwargs: Dict[str, Any] = {"algorithm": "RF"}
    if mc:
        spec_kwargs["extra"] = {"n_classes": settings.n_classes}
    return ForestResult(
        trees=trees, spec_kwargs=spec_kwargs,
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=fi,
        trees_built=len(trees), history=history)


# ------------------------------------------------- bagged / grid members
def _device_put_members(mesh, *arrays):
    """Shard [B, rows] member matrices over the mesh's data axis (rows =
    axis 1; members replicate)."""
    if mesh is None:
        return [jnp.asarray(a) for a in arrays]
    from jax.sharding import NamedSharding, PartitionSpec as P
    data_size = mesh.shape["data"]
    out = []
    for a in arrays:
        a = np.asarray(a)
        extra = (-a.shape[1]) % data_size
        if extra:
            pad = np.zeros((a.shape[0], extra) + a.shape[2:], a.dtype)
            a = np.concatenate([a, pad], axis=1)
        out.append(jax.device_put(
            a, NamedSharding(mesh, P(None, "data"))))
    return out


def _check_member_structure(settings_list: List[DTSettings]) -> DTSettings:
    s0 = settings_list[0]
    for s in settings_list[1:]:
        same = (s.n_trees == s0.n_trees and s.depth == s0.depth
                and s.impurity == s0.impurity and s.loss == s0.loss
                and s.feature_subset == s0.feature_subset
                and s.max_leaves == s0.max_leaves
                and s.n_classes == s0.n_classes
                and s.poisson_bagging == s0.poisson_bagging)
        if not same:
            raise ValueError("bagged tree members must share structural "
                             "params (TreeNum/MaxDepth/Impurity/Loss/...)")
    return s0


def _member_results(packed_bt, settings_list, total, n_bins, c, alg,
                    n_classes=0) -> List[ForestResult]:
    """Unpack a [B, T, L] stacked-forest fetch into per-member results."""
    out = []
    for b, s in enumerate(settings_list):
        trees, fi = [], np.zeros(c)
        history = []
        for vec in packed_bt[b]:
            tree, gfi, tr_err, va_err = _unpack_tree(
                vec, total, n_bins, c, s.depth, n_classes)
            trees.append(tree)
            fi += gfi
            history.append((tr_err, va_err))
        kw: Dict[str, Any] = {"algorithm": alg}
        if alg == "GBT":
            kw.update({"loss": s.loss, "learning_rate": s.learning_rate})
        if n_classes > 2:
            kw["extra"] = {"n_classes": n_classes}
        out.append(ForestResult(
            trees=trees, spec_kwargs=kw,
            train_error=history[-1][0] if history else float("nan"),
            valid_error=history[-1][1] if history else float("nan"),
            feature_importance=fi, trees_built=len(trees),
            history=history))
    return out


def train_gbt_bagged(bins, y, tw_m, vw_m, n_bins: int, cat_mask,
                     settings_list: List[DTSettings], mesh=None,
                     progress=None) -> List[ForestResult]:
    """B independent GBT forests as ONE vmapped executable (reference
    bagging/grid fan-out, ``TrainModelProcessor.java:768-945``, one Guagua
    job per member).  Members share structure (TreeNum/MaxDepth/...) and
    vary in row weights ``tw_m``/``vw_m`` [B, n], seeds (feature subsets)
    and the traced scalars LearningRate / MinInstancesPerNode /
    MinInfoGain.  Early stop / checkpointing are per-run features of
    :func:`train_gbt`; callers fall back to sequential runs for those."""
    s0 = _check_member_structure(settings_list)
    n, c = bins.shape
    tw_m = np.asarray(tw_m, np.float32)
    vw_m = np.asarray(vw_m, np.float32)
    y64 = np.asarray(y, np.float64)

    init_scores = []
    for b, s in enumerate(settings_list):
        prior = float((y64 * tw_m[b]).sum() / max(tw_m[b].sum(), 1e-9))
        if s.loss == "log":
            prior = float(np.clip(prior, 1e-6, 1 - 1e-6))
            init_scores.append(float(np.log(prior / (1 - prior))))
        else:
            init_scores.append(prior)

    bins_d = _put_bins(mesh, bins, n_bins)
    y_d, = _device_put_rows(mesh, y64.astype(np.float32))
    tw_d, vw_d = _device_put_members(mesh, tw_m, vw_m)
    n_pad = bins_d.shape[0]
    f = jnp.asarray(np.repeat(np.asarray(init_scores, np.float32)[:, None],
                              n_pad, axis=1))
    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())
    fa_all = jnp.asarray(np.stack(
        [[_feat_subset(s, c, t) for t in range(s0.n_trees)]
         for s in settings_list]))                       # [B, T, C]
    # f32 pins the vmapped scan carry dtype under JAX_ENABLE_X64 rigs
    lr = jnp.asarray([s.learning_rate for s in settings_list],
                     jnp.float32)
    mi = jnp.asarray([s.min_instances for s in settings_list],
                     jnp.float32)
    mg = jnp.asarray([s.min_gain for s in settings_list], jnp.float32)
    imp = "friedmanmse" if s0.impurity == "friedmanmse" else "variance"
    fn = _gbt_forest_multi(n_bins, s0.depth, imp, s0.loss, s0.n_trees,
                           _use_pallas(mesh), s0.max_leaves, hc,
                           _hist_mesh(mesh))
    _, packed = fn(bins_d, y_d, tw_d, vw_d, f, fa_all, cat, lr, mi, mg)
    total = n_tree_nodes(s0.depth)
    results = _member_results(np.asarray(packed), settings_list, total,
                              n_bins, c, "GBT")
    for b, (res, s) in enumerate(zip(results, settings_list)):
        res.spec_kwargs["init_score"] = init_scores[b]
        if progress:
            for ti, (tr, va) in enumerate(res.history):
                progress(b, ti, tr, va)
    return results


def train_rf_bagged(bins, y, w_m, n_bins: int, cat_mask,
                    settings_list: List[DTSettings], mesh=None,
                    progress=None) -> List[ForestResult]:
    """B independent RF/DT forests as ONE vmapped executable (see
    :func:`train_gbt_bagged`).  ``w_m`` [B, n]: per-member row weights
    (the bagging sample); validation is per-member out-of-bag."""
    s0 = _check_member_structure(settings_list)
    n, c = bins.shape
    B = len(settings_list)
    mc = s0.n_classes if s0.n_classes > 2 else 0
    bins_d = _put_bins(mesh, bins, n_bins)
    y_d, = _device_put_rows(mesh, np.asarray(y, np.float32))
    w_d, = _device_put_members(mesh, np.asarray(w_m, np.float32))
    n_pad = bins_d.shape[0]
    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())
    oob_shape = (B, n_pad, s0.n_classes) if mc else (B, n_pad)
    oob_sum = jnp.zeros(oob_shape, jnp.float32)
    oob_cnt = jnp.zeros((B, n_pad), jnp.float32)
    base_key = jnp.stack([jax.random.PRNGKey(s.seed)
                          for s in settings_list])
    tree_ids = jnp.arange(s0.n_trees, dtype=jnp.uint32)
    bag_rate = jnp.asarray([s.bagging_rate for s in settings_list],
                           jnp.float32)
    fa_all = jnp.asarray(np.stack(
        [[_feat_subset(s, c, t) for t in range(s0.n_trees)]
         for s in settings_list]))
    mi = jnp.asarray([s.min_instances for s in settings_list],
                     jnp.float32)
    mg = jnp.asarray([s.min_gain for s in settings_list], jnp.float32)
    fn = _rf_forest_multi(n_bins, s0.depth, s0.impurity, s0.loss,
                          s0.poisson_bagging, s0.n_classes, s0.n_trees,
                          _use_pallas(mesh), s0.max_leaves, hc,
                          _hist_mesh(mesh),
                          s0.stats_exact or _stats_bf16_exact(w_m))
    _, _, packed = fn(bins_d, y_d, w_d, base_key, tree_ids, bag_rate,
                      oob_sum, oob_cnt, fa_all, cat, mi, mg)
    total = n_tree_nodes(s0.depth)
    results = _member_results(np.asarray(packed), settings_list, total,
                              n_bins, c, "RF", s0.n_classes)
    if progress:
        for b, res in enumerate(results):
            for ti, (tr, va) in enumerate(res.history):
                progress(b, ti, tr, va)
    return results


# ------------------------------------------------------------- streaming
# streamed/tail executables are cost-attributed under the gbt./rf.
# planes (obs/costs, lazy: module-scope wrap precedes --telemetry)
@partial(obs.costed_jit, "gbt.window_hist", lazy=True,
         static_argnames=("n_nodes", "n_bins", "level", "loss",
                          "use_pallas", "mesh", "left"))
def _gbt_window_hist(hist, bins_w, y_w, tw_w, f_w, sf, lm, n_nodes: int,
                     n_bins: int, level: int, loss: str,
                     use_pallas: bool = False, mesh=None,
                     left: bool = False):
    """Streamed level step: window rows find their level-local node by
    walking the partial tree, then scatter residual-gradient stats.  With
    mesh-sharded window rows the [nodes, C, B, S] sum is XLA's psum over
    the data axis — the DTWorker→DTMaster merge on ICI.

    ``left=True`` accumulates only the LEFT-child histograms of the level
    (parent-slot indexed, ``n_nodes`` halved) — the streamed side of the
    resident grow's histogram subtraction: right children derive as
    parent - left once the level's windows are summed
    (:func:`_derive_level`), halving every re-stream sweep's kernel work.

    ``hist`` (the running accumulator) is an INPUT so consecutive window
    programs chain by data dependency: XLA's CPU in-process collectives
    deadlock when two independent mesh programs overlap on a thread pool
    smaller than 2x the device count (each program's ranks block in the
    rendezvous holding pool threads the other program needs) — chained
    programs can never overlap, on CPU or over a real tunnel."""
    node_idx = node_index_at_level(sf, lm, bins_w, level)
    if left:
        node_idx = _left_child_index(node_idx)
    grad = _loss_grad(y_w, f_w, loss)
    stats = jnp.stack([tw_w, tw_w * grad], axis=1).astype(jnp.float32)
    return hist + build_histograms(bins_w, node_idx, stats, n_nodes,
                                   n_bins, use_pallas, mesh)


@obs.costed_jit("tree.derive_level", lazy=True,
                static_argnames=("n_nodes",))
def _derive_level(full_prev, hl, feat_prev, n_nodes: int):
    """Full level histogram from the parent level + accumulated
    left-child sums: right child = parent - left where the parent split,
    zero where it froze — the cross-window form of the subtraction in
    :func:`shifu_tpu.ops.tree.grow_tree_jit`."""
    split_ok = feat_prev >= 0
    hr = jnp.where(split_ok[:, None, None, None], full_prev - hl, 0.0)
    return jnp.stack([hl, hr], axis=1).reshape(
        n_nodes, hl.shape[1], hl.shape[2], hl.shape[3])


@partial(obs.costed_jit, "gbt.window_leaf_raw", lazy=True,
         static_argnames=("depth", "loss"))
def _gbt_window_leaf_raw(acc, bins_w, y_w, tw_w, f_w, sf, lm, depth: int,
                         loss: str):
    """Bottom-level raw leaf stat sums for one window — replaces the full
    [2^depth, C, B, S] histogram sweep of the deepest level with one
    [S, N] x [N, 2^depth] dot (the resident grow's leaf-sum bottom level,
    streamed)."""
    node_idx = node_index_at_level(sf, lm, bins_w, depth)
    grad = _loss_grad(y_w, f_w, loss)
    stats = jnp.stack([tw_w, tw_w * grad], axis=1).astype(jnp.float32)
    return acc + _level_leaf_raw(stats, node_idx, 1 << depth)


@obs.costed_jit("tree.set_bottom_leaves", lazy=True,
                static_argnames=("depth",))
def _set_bottom_leaves(lv, raw, depth: int):
    return lv.at[(1 << depth) - 1:].set(leaf_values_from_raw(raw))


# ------------------------------------------- coarse-to-fine disk tail
@partial(obs.costed_jit, "gbt.tail_head", lazy=True,
         static_argnames=("n_bins", "depth", "impurity", "loss",
                          "use_pallas", "max_leaves", "has_cat",
                          "mesh", "has_prev", "cand_k"))
def _gbt_tail_head(bins, y, tw, vw, f, sf_p, lm_p, lv_p, fa, cat, lr, mi,
                   mg, tail_extra, valid_upto, n_bins: int, depth: int,
                   impurity: str, loss: str, use_pallas: bool = False,
                   max_leaves: int = 0, has_cat: bool = True, mesh=None,
                   has_prev: bool = True, cand_k: int = 0):
    """The coarse-to-fine tree's RESIDENT head as ONE executable: apply
    the previous tree's score update to the coalesced resident block
    (+ its error sums), then grow the COARSE tree on the resident prefix
    alone — recording its per-level left histograms and bottom leaf sums,
    which ARE the resident contribution to the exact totals along the
    speculated structure (zero recomputation when the speculation holds).

    With ``cand_k > 0`` also picks the top-K candidate features (coarse
    realized gains, coarse split features forced in, indices sorted so
    K >= C degenerates to the identity gather) and narrows the recorded
    histograms to them — the bounded-candidate scan.

    ``tail_extra`` ([depth, half, C, B, S]) is the previous pass's exact
    tail-only evidence (:func:`_tail_extras`) with ``valid_upto`` = the
    level through which the previous speculation was confirmed; the
    coarse grow adds it to each level's split decision while this tree's
    structure still bit-matches the previous tree's (``sf_p``/``lm_p``
    double as the structure reference — they ARE the previous tree), so
    speculated splits pin to near-full-data optima instead of the
    resident prefix's.  One tree stale; exactness comes from the
    verify/repair pass, not from the evidence."""
    if has_prev:
        f = f + lr * predict_tree(sf_p, lm_p, lv_p, bins, depth)
        per = _per_row_loss(y, f, loss)
        sums = jnp.stack([(per * tw).sum(), tw.sum(),
                          (per * vw).sum(), vw.sum()])
    else:
        sums = jnp.zeros(4, jnp.float32)
    grad = _loss_grad(y, f, loss)
    stats = jnp.stack([tw, tw * grad], axis=1).astype(jnp.float32)
    sf_c, lm_c, _, gfi_c, _, hist_left, leaf_raw = grow_tree_jit(
        bins, stats, cat, fa, n_bins, depth, impurity, mi, mg,
        use_pallas=use_pallas, max_leaves=max_leaves, has_cat=has_cat,
        mesh=mesh, record_hists=True, tail_extra=tail_extra,
        prev_sf=sf_p, prev_lm=lm_p, valid_upto=valid_upto)
    if cand_k > 0:
        forced = jnp.zeros(bins.shape[1], jnp.float32).at[
            jnp.maximum(sf_c, 0)].add(
            jnp.where(sf_c >= 0, jnp.float32(1e30), jnp.float32(0.0)))
        _, cand_idx = jax.lax.top_k(gfi_c + forced, cand_k)
        cand_idx = jnp.sort(cand_idx).astype(jnp.int32)
        hist_left = jnp.take(hist_left, cand_idx, axis=2)
    else:
        cand_idx = jnp.zeros(0, jnp.int32)
    return sf_c, lm_c, hist_left, leaf_raw, f, sums, cand_idx


@obs.costed_jit("gbt.tail_extras", lazy=True,
                static_argnames=("c", "cand"))
def _tail_extras(hl_acc, hl_res, cand_idx, c: int, cand: bool = False):
    """The pass's exact TAIL-only evidence ([depth, half, C, B, S], full
    feature width): accumulated totals minus the resident head's recorded
    contribution, scattered back from the candidate set when the scan was
    bounded.  Level 0's slot is the full tail root (routing-free); level
    l is the tail left-child histograms routed along this pass's
    speculated structure — valid next pass exactly up to the level this
    pass CONFIRMED (the caller carries that as ``valid_upto``)."""
    tail = hl_acc - hl_res
    if cand:
        full = jnp.zeros(hl_acc.shape[:2] + (c,) + hl_acc.shape[3:],
                         hl_acc.dtype)
        return full.at[:, :, cand_idx].set(tail)
    return tail


@partial(obs.costed_jit, "gbt.tail_window_pass", lazy=True,
         static_argnames=("n_bins", "depth", "loss", "use_pallas",
                          "mesh", "has_prev", "cand"))
def _gbt_tail_window_pass(hist_left, leaf_raw, sums, bins_w, y_w, tw_w,
                          vw_w, f_w, sf_p, lm_p, lv_p, sf_c, lm_c,
                          cand_idx, lr, n_bins: int, depth: int, loss: str,
                          use_pallas: bool = False, mesh=None,
                          has_prev: bool = True, cand: bool = False):
    """ONE disk pass feeds everything, per tail window: the previous
    tree's score update + its error sums + EVERY level's histograms of
    the current tree along the speculated coarse structure + the bottom
    leaf sums, in a single executable — the O(depth x trees) tail
    re-stream schedule collapses to one re-stream per tree."""
    if has_prev:
        f_w = f_w + lr * predict_tree(sf_p, lm_p, lv_p, bins_w, depth)
        per = _per_row_loss(y_w, f_w, loss)
        sums = sums + jnp.stack([(per * tw_w).sum(), tw_w.sum(),
                                 (per * vw_w).sum(), vw_w.sum()])
    grad = _loss_grad(y_w, f_w, loss)
    stats = jnp.stack([tw_w, tw_w * grad], axis=1).astype(jnp.float32)
    hist_bins = jnp.take(bins_w, cand_idx, axis=1) if cand else None
    hl, lraw = build_path_histograms(bins_w, stats, sf_c, lm_c, depth,
                                     n_bins, use_pallas, mesh,
                                     hist_bins=hist_bins)
    return hist_left + hl, leaf_raw + lraw, sums, f_w


@partial(obs.costed_jit, "gbt.tail_select", lazy=True,
         static_argnames=("n_bins", "depth", "impurity",
                          "max_leaves", "has_cat", "cand"))
def _gbt_tail_select(hist_left, leaf_raw, sf_c, lm_c, cand_idx, cat, fa,
                     mi, mg, n_bins: int, depth: int, impurity: str,
                     max_leaves: int = 0, has_cat: bool = True,
                     cand: bool = False):
    """Exact split selection from the accumulated (resident + tail)
    per-level histograms, verifying the speculation: runs the level steps
    top-down with right-children derived by subtraction, compares each
    level's exact choice against the coarse structure, and reports the
    FIRST level where they diverge (``depth`` = fully confirmed; deeper
    histograms are mis-routed past a divergence and the caller repairs
    those levels with exact per-level sweeps).

    Returns (sf, lm, lv, fi_levels [depth, C], cnt_levels [depth],
    mismatch, full_levels [depth, half, K, B, S]) — per-level FI/
    leaf-budget state plus the exact FULL per-level histograms, so the
    caller can resume a repair from the divergence point without
    trusting the garbage tail AND seed the repair's subtraction chain
    with the exact level-``mis`` parent (bit-parity with the pure exact
    schedule requires the repair to derive right children the same way).
    """
    c_full = fa.shape[0]
    cat_h = jnp.take(cat, cand_idx) if cand else cat
    fa_h = jnp.take(fa, cand_idx) if cand else fa
    total = n_tree_nodes(depth)
    sf = jnp.full(total, -1, jnp.int32)
    lm = jnp.zeros((total, n_bins), bool)
    lv = jnp.zeros(total, jnp.float32)
    nodes_cnt = jnp.int32(1)
    fi_levels, cnt_levels = [], []
    full_hists = []               # exact FULL per-level hists (padded out;
                                  # the repair path's subtraction parents)
    mismatch = jnp.int32(depth)
    full_prev = None
    feat_prev = None
    for level in range(depth):
        n_nodes = 1 << level
        if level == 0:
            hist = hist_left[0][:1]
        else:
            hl = hist_left[level][:n_nodes // 2]
            hist = _derive_level(full_prev, hl, feat_prev, n_nodes)
        full_hists.append(hist)
        gain, feat_l, lmask, leaf, _ = best_splits(
            hist, cat_h, fa_h, impurity, mi, mg, has_cat=has_cat)
        feat = jnp.where(feat_l >= 0,
                         cand_idx[jnp.maximum(feat_l, 0)] if cand
                         else feat_l, -1).astype(jnp.int32)
        if max_leaves > 0:
            feat, lmask, nodes_cnt = cap_splits_by_leaves(
                gain, feat, lmask, nodes_cnt, max_leaves)
        base = n_nodes - 1
        sf = sf.at[base:base + n_nodes].set(feat)
        lm = lm.at[base:base + n_nodes].set(lmask)
        lv = lv.at[base:base + n_nodes].set(leaf)
        fi_levels.append(jax.ops.segment_sum(
            jnp.where(feat >= 0, jnp.maximum(gain, 0.0),
                      0.0).astype(jnp.float32),
            jnp.maximum(feat, 0), num_segments=c_full))
        cnt_levels.append(nodes_cnt)
        diff = jnp.any(feat != jax.lax.dynamic_slice_in_dim(
            sf_c, base, n_nodes)) | jnp.any(
            lmask != jax.lax.dynamic_slice_in_dim(lm_c, base, n_nodes,
                                                  axis=0))
        mismatch = jnp.where((mismatch == depth) & diff,
                             jnp.int32(level), mismatch)
        full_prev = hist
        feat_prev = feat
    lv = _set_bottom_leaves(lv, leaf_raw, depth)
    half = max(1 << (depth - 1), 1)
    full_levels = jnp.stack([
        jnp.concatenate([h, jnp.zeros((half - h.shape[0],) + h.shape[1:],
                                      h.dtype)]) if h.shape[0] < half
        else h
        for h in full_hists])
    return sf, lm, lv, jnp.stack(fi_levels), jnp.stack(cnt_levels), \
        mismatch, full_levels


# tiny packed-fetch glue: ~zero FLOPs, one shape per run — cost
# attribution would only add registry noise
@jax.jit  # shifu-lint: disable=recompile-hazard
def _pack_c2f(sf, lm, lv, fi):
    """[sf, mask-bits, lv, fi] packed fetch for a coarse-to-fine tree —
    errors travel separately (they land one pass later, fused into the
    NEXT tree's tail pass)."""
    return jnp.concatenate([sf.astype(jnp.float32), _pack_mask_bits(lm),
                            lv, fi])


@jax.jit  # shifu-lint: disable=recompile-hazard
def _pack_small(sums, mismatch):
    """The per-tree tiny fetch: [tr_sum, tw, va_sum, vw, mismatch]."""
    return jnp.concatenate([sums, mismatch[None].astype(jnp.float32)])


def _rf_tail_bags(idx_hi, idx_lo, khi_b, klo_b, thi, tlo, n: int,
                  poisson: bool):
    """[TB, n] Poisson bags hashed ON DEVICE for a tail super-batch —
    bit-identical to the host ``_hash_poisson`` stream
    (``ops/hashing.py``), so the wire carries two [n] uint32 index halves
    per window instead of a [TB, n] f32 bag plane (the put that dominated
    tail prep as TB grew).  Rows past ``n_valid`` need no masking here:
    the RF prep hook zeroes ``w`` there, and every consumer multiplies or
    gates by ``w``."""
    if not poisson:
        return jnp.ones((khi_b.shape[0], n), jnp.float32)
    from ..ops.hashing import hash_poisson_traced
    return jax.vmap(lambda kh, kl: hash_poisson_traced(
        idx_hi, idx_lo, kh, kl, thi, tlo))(khi_b, klo_b)


def _rf_stats_batch(y_w, w_w, bags_b, n_classes: int):
    bw_b = w_w[None, :] * bags_b
    if n_classes > 2:      # NATIVE multiclass: per-class weight channels
        return bw_b[:, :, None] * jax.nn.one_hot(
            y_w.astype(jnp.int32), n_classes, dtype=jnp.float32)[None]
    return jnp.stack([bw_b, bw_b * y_w[None, :]], axis=2) \
        .astype(jnp.float32)


@partial(obs.costed_jit, "rf.window_hist_batch", lazy=True,
         static_argnames=("n_nodes", "n_bins", "level",
                          "use_pallas", "mesh", "n_classes",
                          "stats_exact", "left", "poisson"))
def _rf_window_hist_batch(hist_b, bins_w, y_w, w_w, idx_hi, idx_lo,
                          khi_b, klo_b, thi, tlo, sf_b, lm_b,
                          n_nodes: int, n_bins: int, level: int,
                          use_pallas: bool = False, mesh=None,
                          n_classes: int = 0, stats_exact: bool = False,
                          left: bool = False, poisson: bool = True):
    """Super-batch histogram sweep for ONE window as ONE executable — and,
    since the multi-tree kernel round, ONE kernel launch: the TB trees'
    level histograms build through :func:`build_histograms_batch` (the
    bins one-hot is shared across the batch) instead of TB stacked
    single-tree kernels.  Bags hash on device (:func:`_rf_tail_bags`);
    ``left=True`` accumulates left children only for the subtraction
    derivation (:func:`_derive_level_batch`).

    The per-tree histograms of a tail batch are mutually independent, and
    independent mesh programs that overlap deadlock XLA:CPU's in-process
    collectives (see :func:`_gbt_window_hist`) — dispatching them as TB
    separate programs was the round-4 SIGABRT.  The single program keeps
    every collective in one totally-ordered executable and chains across
    windows via the stacked ``hist_b`` accumulator input."""
    bags_b = _rf_tail_bags(idx_hi, idx_lo, khi_b, klo_b, thi, tlo,
                           w_w.shape[0], poisson)
    node_b = jax.vmap(
        lambda sf, lm: node_index_at_level(sf, lm, bins_w, level))(
        sf_b, lm_b)
    if left:
        node_b = jax.vmap(_left_child_index)(node_b)
    stats_b = _rf_stats_batch(y_w, w_w, bags_b, n_classes)
    return hist_b + build_histograms_batch(bins_w, node_b, stats_b,
                                           n_nodes, n_bins, use_pallas,
                                           mesh, stats_exact)


@obs.costed_jit("tree.derive_level_batch", lazy=True,
                static_argnames=("n_nodes",))
def _derive_level_batch(full_prev_b, hl_b, feat_prev_b, n_nodes: int):
    """Batched :func:`_derive_level` (per-tree parent - left)."""
    return jax.vmap(
        lambda fp, hl, f: _derive_level(fp, hl, f, n_nodes))(
        full_prev_b, hl_b, feat_prev_b)


@partial(obs.costed_jit, "rf.window_leaf_batch", lazy=True,
         static_argnames=("depth", "n_classes", "poisson"))
def _rf_window_leaf_batch(raw_b, bins_w, y_w, w_w, idx_hi, idx_lo, khi_b,
                          klo_b, thi, tlo, sf_b, lm_b, depth: int,
                          n_classes: int = 0, poisson: bool = True):
    """Super-batch bottom-level raw leaf sums for one window — the
    leaf-sum bottom level, streamed and tree-batched (the deepest, widest
    histogram sweep of the old schedule becomes one dot per tree)."""
    bags_b = _rf_tail_bags(idx_hi, idx_lo, khi_b, klo_b, thi, tlo,
                           w_w.shape[0], poisson)
    stats_b = _rf_stats_batch(y_w, w_w, bags_b, n_classes)
    node_b = jax.vmap(
        lambda sf, lm: node_index_at_level(sf, lm, bins_w, depth))(
        sf_b, lm_b)
    return raw_b + jax.vmap(
        lambda st, ni: _level_leaf_raw(st, ni, 1 << depth))(stats_b,
                                                            node_b)


@obs.costed_jit("tree.set_bottom_leaves_batch", lazy=True,
                static_argnames=("depth", "n_classes"))
def _set_bottom_leaves_batch(lv_b, raw_b, depth: int, n_classes: int = 0):
    base = (1 << depth) - 1
    vals = jax.vmap(lambda r: leaf_values_from_raw(r, n_classes))(raw_b)
    return lv_b.at[:, base:].set(vals)


@partial(obs.costed_jit, "gbt.window_update", lazy=True,
         static_argnames=("depth", "loss"))
def _gbt_window_update(sums_in, bins_w, y_w, tw_w, vw_w, f_w, sf, lm, lv,
                       lr, depth: int, loss: str):
    """``sums_in`` accumulator as input — see :func:`_gbt_window_hist` on
    why window programs must chain."""
    pred = predict_tree(sf, lm, lv, bins_w, depth)
    f2 = f_w + lr * pred
    per = _per_row_loss(y_w, f2, loss)
    sums = jnp.stack([(per * tw_w).sum(), tw_w.sum(),
                      (per * vw_w).sum(), vw_w.sum()])
    return f2, sums_in + sums


@obs.costed_jit("rf.window_oob_update", lazy=True,
                static_argnames=("depth", "loss", "n_classes"))
def _rf_window_update(sums_in, bins_w, y_w, w_w, bag_w, oob_sum_w,
                      oob_cnt_w, sf, lm, lv, depth: int, loss: str,
                      n_classes: int = 0):
    """RF per-window oob accumulate + loss-consistent error sums on device
    (the round-2 host-numpy loop, jitted).  Multiclass (``n_classes > 2``):
    class-distribution votes + misclassification-rate errors, matching
    :func:`_rf_round_impl`."""
    pred = predict_tree(sf, lm, lv, bins_w, depth)
    oob = (bag_w == 0) & (w_w > 0)
    if n_classes > 2:
        oob_sum2 = oob_sum_w + jnp.where(oob[:, None], pred, 0.0)
        oob_cnt2 = oob_cnt_w + oob.astype(oob_cnt_w.dtype)
        seen = oob_cnt2 > 0
        yi = y_w.astype(jnp.int32)
        per_v = (jnp.argmax(oob_sum2, axis=-1) != yi).astype(jnp.float32)
        per_t = (jnp.argmax(pred, axis=-1) != yi).astype(jnp.float32)
        wv = w_w * seen
        sums = jnp.stack([(per_v * wv).sum(), wv.sum(),
                          (per_t * w_w).sum(), w_w.sum()])
        return oob_sum2, oob_cnt2, sums_in + sums
    oob_sum2 = oob_sum_w + jnp.where(oob, pred, 0.0)
    oob_cnt2 = oob_cnt_w + oob.astype(oob_cnt_w.dtype)
    seen = oob_cnt2 > 0
    oob_pred = oob_sum2 / jnp.maximum(oob_cnt2, 1.0)
    if loss == "log":
        p = jnp.clip(oob_pred, 1e-9, 1 - 1e-9)
        per_v = -(y_w * jnp.log(p) + (1 - y_w) * jnp.log(1 - p))
        pt = jnp.clip(pred, 1e-9, 1 - 1e-9)
        per_t = -(y_w * jnp.log(pt) + (1 - y_w) * jnp.log(1 - pt))
    else:
        per_v = _per_row_loss(y_w, oob_pred, loss)
        per_t = _per_row_loss(y_w, pred, loss)
    wv = w_w * seen
    sums = jnp.stack([(per_v * wv).sum(), wv.sum(),
                      (per_t * w_w).sum(), w_w.sum()])
    return oob_sum2, oob_cnt2, sums_in + sums


@partial(obs.costed_jit, "rf.window_update_batch", lazy=True,
         static_argnames=("depth", "loss", "n_classes", "poisson"))
def _rf_window_update_batch(sums_b, bins_w, y_w, w_w, idx_hi, idx_lo,
                            khi_b, klo_b, thi, tlo, oob_sum_w, oob_cnt_w,
                            sf_b, lm_b, lv_b, depth: int, loss: str,
                            n_classes: int = 0, poisson: bool = True):
    """Super-batch oob/error sweep for ONE window as ONE executable — the
    oob vote caches chain through the batch in tree order exactly as the
    per-tree sequence would (a ``lax.scan`` over the tree axis, so a
    budget-sized super-batch doesn't unroll into a giant program), and
    the single program keeps the row-sum AllReduces totally ordered (see
    :func:`_rf_window_hist_batch`)."""
    bags_b = _rf_tail_bags(idx_hi, idx_lo, khi_b, klo_b, thi, tlo,
                           w_w.shape[0], poisson)

    def body(carry, x):
        osw, ocw = carry
        s_j, bag_j, sf_j, lm_j, lv_j = x
        osw, ocw, s2 = _rf_window_update(
            s_j, bins_w, y_w, w_w, bag_j, osw, ocw, sf_j, lm_j, lv_j,
            depth, loss, n_classes)
        return (osw, ocw), s2

    (osw, ocw), sums = jax.lax.scan(
        body, (oob_sum_w, oob_cnt_w), (sums_b, bags_b, sf_b, lm_b, lv_b))
    return osw, ocw, sums




def _unpack_streamed(packed: np.ndarray, total: int, n_bins: int, c: int,
                     depth: int, n_classes: int = 0):
    """Host-side inverse of the fused/streamed packed layout
    [sf, lm, lv, fi, sums] — the ONE place that knows it."""
    k = n_classes if n_classes > 2 else 1
    sf_h, lm_h, lv_h, fi_h, sums = np.split(
        packed,
        np.cumsum([total, _mask_nbytes(total, n_bins), total * k, c]))
    lv = lv_h.astype(np.float32)
    if k > 1:
        lv = lv.reshape(total, k)
    tree = TreeArrays(split_feat=sf_h.astype(np.int32),
                      left_mask=_unpack_mask_bits(lm_h, total, n_bins),
                      leaf_value=lv, depth=depth)
    return tree, fi_h.astype(np.float32), sums


def _tree_level_step(hist, cat, fa, impurity: str, min_instances,
                     min_gain, has_cat: bool, level: int, depth: int,
                     max_leaves: int, sf, lm, lv, nodes_cnt, fi_add,
                     n_classes: int = 0):
    """One level of streamed tree growth from an aggregated histogram —
    the single implementation behind both the fused-resident executable
    and the disk-tail window loop (they must never drift)."""
    n_nodes = 1 << level
    gain, feat, lmask, leaf, _ = best_splits(
        hist, cat, fa, impurity, min_instances, min_gain,
        n_classes=n_classes, has_cat=has_cat)
    base = n_nodes - 1
    if level == depth:
        feat = jnp.full(n_nodes, -1, jnp.int32)
        lmask = jnp.zeros((n_nodes, hist.shape[2]), bool)
    elif max_leaves > 0:
        feat, lmask, nodes_cnt = cap_splits_by_leaves(
            gain, feat, lmask, nodes_cnt, max_leaves)
    sf = sf.at[base:base + n_nodes].set(feat)
    lm = lm.at[base:base + n_nodes].set(lmask)
    lv = lv.at[base:base + n_nodes].set(leaf)
    fi_add = fi_add + jax.ops.segment_sum(
        jnp.where(feat >= 0, jnp.maximum(gain, 0.0),
                  0.0).astype(jnp.float32),
        jnp.maximum(feat, 0), num_segments=hist.shape[1])
    return sf, lm, lv, nodes_cnt, fi_add


@obs.costed_jit("tree.level_step_batch", lazy=True,
                static_argnames=("impurity", "has_cat", "level", "depth",
                                 "max_leaves", "n_classes"))
def _tree_level_step_batch(hist_b, cat, fa_b, impurity: str, min_instances,
                           min_gain, has_cat: bool, level: int, depth: int,
                           max_leaves: int, sf_b, lm_b, lv_b, cnt_b, fi_b,
                           n_classes: int = 0):
    """Tail-batch level step as ONE executable (one dispatch per level
    for the whole batch; see :func:`_rf_window_hist_batch` on why the
    trees must not run as independent programs).  vmapped over the tree
    axis so a budget-sized super-batch traces once, not SB times."""
    def one(h, fa, sf, lm, lv, cnt, fi):
        return _tree_level_step(h, cat, fa, impurity, min_instances,
                                min_gain, has_cat, level, depth,
                                max_leaves, sf, lm, lv, cnt, fi,
                                n_classes)
    return jax.vmap(one)(hist_b, fa_b, sf_b, lm_b, lv_b, cnt_b, fi_b)




@lru_cache(maxsize=None)
def _row_unstack(k: int):
    return jax.jit(lambda d: tuple(d[i] for i in range(k)))  # shifu-lint: disable=recompile-hazard


def _put_row_floats(mesh, cols: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """A window's per-row f32 columns in ONE wire transfer: host-stack to
    [K, W], put, unstack on device (slices propagate the data sharding).
    Every host→device put pays a fixed protocol cost on top of bandwidth
    (~25 ms on the bench tunnel) — per-column puts made streamed-window
    prep transfer-bound."""
    keys = list(cols)
    stacked = np.stack([np.asarray(cols[k], np.float32) for k in keys])
    if mesh is None:
        d = jnp.asarray(stacked)
    else:
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        d = jax.device_put(stacked, NamedSharding(mesh, P(None, "data")))
    return dict(zip(keys, _row_unstack(len(keys))(d)))


def _require_divisible(stream, mesh) -> None:
    if mesh is not None and stream.window_rows % mesh.shape["data"] != 0:
        raise ValueError(
            f"window_rows {stream.window_rows} must divide the mesh data "
            f"axis ({mesh.shape['data']}) — round it up at the call site")


def _default_cache_budget() -> int:
    from ..config import environment
    return environment.get_int("shifu.train.deviceCacheBytes", 1 << 30)


def _pipeline_depth(mesh) -> Optional[int]:
    """See :func:`data.streaming.pipeline_depth_for` — the shared
    single-device-only pipelined-prep rule (XLA:CPU in-process rendezvous
    deadlock, see :func:`_gbt_window_hist`)."""
    from ..data.streaming import pipeline_depth_for
    return pipeline_depth_for(mesh)


# floor on trees grown per disk-tail sweep in streamed RF.  The actual
# super-batch is budget-derived (:func:`_tail_super_batch`): as many
# trees as the per-level histogram state affords, so disk passes per tree
# scale as (depth+2)/SB instead of the old fixed /8.
RF_TAIL_TREE_BATCH = 8

# hard cap on the tail super-batch: past ~128 trees the batched level
# steps' compile time and the [SB, K, C, B, S] state stop paying for the
# marginal disk-pass amortization
RF_TAIL_SUPER_BATCH_MAX = 128


def _tail_super_batch(settings: DTSettings, c: int, n_bins: int,
                      n_stats: int) -> int:
    """Trees fed by ONE disk pass over the tail in streamed RF — the
    super-batch SB.  ``TailTreeBatch`` train param / ``SHIFU_TAIL_TREE_
    BATCH`` env override; auto derives from ``shifu.tree.
    tailSuperBatchBytes`` (default 256 MiB) against the deepest level's
    histogram state (~2x [SB, 2^(depth-1), C, B, S] f32 for the running
    accumulator + the previous level kept for subtraction, plus the
    per-window [SB, W] bag/stat planes)."""
    env = os.environ.get("SHIFU_TAIL_TREE_BATCH")
    if env:
        return max(1, int(env))
    if settings.tail_tree_batch > 0:
        return settings.tail_tree_batch
    from ..config import environment
    budget = environment.get_int("shifu.tree.tailSuperBatchBytes", 1 << 28)
    width = 1 << max(settings.depth - 1, 0)
    per_tree = 2 * width * c * n_bins * n_stats * 4
    return int(min(RF_TAIL_SUPER_BATCH_MAX,
                   max(RF_TAIL_TREE_BATCH, budget // max(per_tree, 1))))


def _tail_coarse_to_fine() -> bool:
    """The disk-tail coarse-to-fine schedule knob: ``SHIFU_TREE_TAIL_C2F``
    env / ``-Dshifu.tree.tailCoarseToFine`` property.

    Default: ON on accelerator backends, OFF on CPU.  The fused one-pass
    schedule trades recomputation (repair sweeps re-derive diverged
    levels) for disk passes — the winning trade exactly when per-pass
    overhead (H2D puts, dispatch latency, real disk) dominates, i.e. on
    a TPU/GPU driving an out-of-core tail.  On a CPU backend a "pass"
    over the mmap spill cache is nearly free while the repair compute is
    not, so the exact per-level super-batch schedule is faster (measured
    ~40k vs ~29k rows*trees/s on the CI rig at 50% repair rate).  Both
    schedules produce bit-identical forests; only the pass/compute mix
    differs."""
    env = os.environ.get("SHIFU_TREE_TAIL_C2F")
    if env is not None:
        return env.lower() not in ("0", "off", "false")
    from ..config import environment
    default = jax.default_backend() != "cpu"
    return environment.get_bool("shifu.tree.tailCoarseToFine", default)


def _tail_candidate_k(c: int) -> int:
    """Bounded-candidate histogram width for the coarse-to-fine tail
    pass: ``-Dshifu.tree.tailCandidateK`` picks the top-K features (by
    the coarse tree's realized gains, coarse split features always
    included) and the exact tail verification scans only those K columns.
    0 (default) / K >= C = all features — the EXACT contract; K < C is
    the approximate bounded scan (the chosen split is exact-best WITHIN
    the candidate set)."""
    from ..config import environment
    k = environment.get_int("shifu.tree.tailCandidateK", 0)
    if k <= 0 or k >= c:
        return 0
    return k


def _c2f_feasible(settings: DTSettings, c: int, n_bins: int) -> bool:
    """Coarse-to-fine holds every level's left-child histograms at once
    ([depth, 2^(depth-1), K, B, S] f32 x3 live copies: resident head
    record, running accumulator, stale-tail evidence) — gate on
    ``shifu.tree.tailHistBudgetBytes`` (default 256 MiB) so deep/wide
    configs fall back to the exact per-level schedule instead of
    OOMing."""
    if settings.depth < 1 or settings.n_classes > 2:
        return False
    from ..config import environment
    budget = environment.get_int("shifu.tree.tailHistBudgetBytes", 1 << 28)
    k = _tail_candidate_k(c) or c
    width = 1 << max(settings.depth - 1, 0)
    return 3 * settings.depth * width * k * n_bins * 2 * 4 <= budget


@jax.jit  # shifu-lint: disable=recompile-hazard
def _pack_streamed_stacked(sf_b, lm_b, lv_b, fi_b, sums_b):
    """[TB, L] packer for a stacked tail batch — jitted so the
    partitioner reconciles whatever shardings the parts carry (an eager
    concatenate of mixed-sharding parts aborts XLA:CPU)."""
    tb = sf_b.shape[0]
    return jnp.concatenate([
        sf_b.astype(jnp.float32),
        jax.vmap(_pack_mask_bits)(lm_b),
        lv_b.reshape(tb, -1), fi_b, sums_b], axis=1)


def _stream_masks(idx: np.ndarray, n_valid: int, w_w: np.ndarray,
                  valid_rate: float, seed: int):
    """Hash-based train/valid weights for a window (stateless row split)."""
    from ..data.streaming import row_uniform
    vmask = row_uniform(seed, 11, idx) < valid_rate
    live = np.zeros(len(idx), np.float32)
    live[:n_valid] = 1.0
    w = np.asarray(w_w, np.float32) * live
    return (w * ~vmask).astype(np.float32), (w * vmask).astype(np.float32)


def _gbt_prepare(mesh, valid_rate: float, seed: int, n_bins: int,
                 y_transform=None, mask_fn=None, f_ref=None):
    """Window prepare hook for streamed GBT: hash train/valid masks once,
    arrays onto the device (mesh-sharded over the data axis).
    ``y_transform`` maps the raw window targets (one-vs-all binarization,
    reference per-class jobs ``TrainModelProcessor.java:684-714``);
    ``mask_fn(index, targets) -> (train_w, valid_w)`` overrides the plain
    valid-rate split (grid/bagging members supply their member's
    stateless bag/split, ``data.streaming.window_member_masks``).

    ``f_ref`` is a one-slot cell the trainer points at its host score
    cache: when set, the window's score slice ships as ``f_prep`` FROM
    THE PREP THREAD, so the tail path's per-window put overlaps device
    compute instead of serializing on the consumer (safe: a window's
    slice is only written by the consumer AFTER it consumed that window,
    and rows are disjoint across windows).  Resident windows ignore
    ``f_prep`` — their persistent device score cache lives under ``f``."""
    from ..data.streaming import PreparedWindow

    def prep(win):
        y_raw = np.asarray(win.arrays["y"], np.float32)
        if mask_fn is None:
            tw, vw = _stream_masks(win.index, win.n_valid, win.arrays["w"],
                                   valid_rate, seed)
        else:
            live = np.zeros(win.rows, np.float32)
            live[:win.n_valid] = 1.0
            w = np.asarray(win.arrays["w"], np.float32) * live
            t, v = mask_fn(win.index, y_raw)
            tw, vw = (w * t).astype(np.float32), (w * v).astype(np.float32)
        y = y_raw
        if y_transform is not None:
            y = np.asarray(y_transform(y), np.float32)
        dev = _put_row_floats(mesh, {"y": y, "tw": tw, "vw": vw})
        dev["bins"] = _put_bins(mesh, win.arrays["bins"], n_bins)
        fh = f_ref.get("f") if f_ref is not None else None
        if fh is not None:
            dev["f_prep"] = _window_f(fh, win, mesh)
        return PreparedWindow(win.start, win.n_valid, win.rows,
                              win.index, dev)
    return prep


@lru_cache(maxsize=None)
def _init_score_jit(loss: str):
    """Device GBT prior from [sum(w*y), sum(w)] sums — keeps the streamed
    warm pass fetch-free."""
    def f(sums):
        prior = sums[0] / jnp.maximum(sums[1], 1e-9)
        if loss == "log":
            p = jnp.clip(prior, 1e-6, 1 - 1e-6)
            return jnp.log(p / (1 - p))
        return prior
    return jax.jit(f)  # shifu-lint: disable=recompile-hazard


@lru_cache(maxsize=None)
def _bcast_rows(rows: int, mesh=None):
    """jit broadcasting a device scalar to a (sharded) row vector."""
    kw = {}
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        kw["out_shardings"] = NamedSharding(mesh, P("data"))
    return jax.jit(lambda s: jnp.broadcast_to(s, (rows,)), **kw)  # shifu-lint: disable=recompile-hazard


def _progress_flusher(drain, history, progress, idx_off: int):
    """(flush, mark) for batched streamed progress: lines arrive in
    bursts of 8 (a per-tree fetch is a full link round-trip — the
    resident path's convention).  ``idx_off`` maps history positions to
    global tree indices (resume may restore trees without their history,
    e.g. a checkpoint whose .meta.json is missing).  ``mark`` advances
    the cursor after a caller emitted a line itself (per-tree sync
    paths)."""
    state = {"emitted": len(history)}

    def flush() -> None:
        drain()
        if progress:
            for j in range(state["emitted"], len(history)):
                progress(j + idx_off, history[j][0], history[j][1])
        state["emitted"] = len(history)

    def mark() -> None:
        state["emitted"] = len(history)
    return flush, mark


def train_gbt_streamed(stream, n_bins: int, cat_mask,
                       settings: DTSettings, progress=None,
                       init_trees: Optional[List[TreeArrays]] = None,
                       init_score: Optional[float] = None,
                       checkpoint_fn: Optional[Callable] = None,
                       start_history: Optional[List] = None,
                       mesh=None,
                       cache_budget: Optional[int] = None,
                       y_transform=None, mask_fn=None,
                       init_scores: Optional[np.ndarray] = None
                       ) -> ForestResult:
    """Out-of-core GBT over a ResidentCache: windows that fit the device
    budget are mesh-sharded HBM residents (re-sweeping them costs no IO);
    only the tail past the budget re-streams from disk per level.  The
    per-row score cache f (rows × 4B host) is the only global row state.

    When the dataset fits the budget a whole tree costs ZERO disk passes
    (one warm pass total); the round-2 depth+2-passes-per-tree design is
    gone.  (Reference: ``MemoryDiskFloatMLDataSet.java:54-99`` memory tier,
    ``DTWorker.java:763-884`` histogram merge.)"""
    from ..data.streaming import ResidentCache

    _require_divisible(stream, mesh)
    up = _use_pallas(mesh)
    n_rows = stream.num_rows
    total = n_tree_nodes(settings.depth)
    trees: List[TreeArrays] = list(init_trees or [])
    history: List[Tuple[float, float]] = list(start_history or [])
    stopper = GBTEarlyStopDecider()
    replay_stopped = False
    for _, va_prev in history:
        # see train_gbt: a restored forest that already early-stopped
        # must not grow past its truncation point
        if stopper.add(va_prev) and settings.early_stop:
            replay_stopped = True

    f_ref: Dict[str, Any] = {"f": None}   # prep-thread view of host scores
    bytes0 = stream.bytes_read
    cache = ResidentCache(stream,
                          _default_cache_budget() if cache_budget is None
                          else cache_budget,
                          _gbt_prepare(mesh, settings.valid_rate,
                                       settings.seed, n_bins, y_transform,
                                       mask_fn, f_ref),
                          pipeline_depth=_pipeline_depth(mesh))

    # warm pass: width probe + init-score sums in one sweep.  The sums
    # accumulate ON DEVICE (chained adds) and fetch once at the end — a
    # per-window float() fetch is a full link round-trip, and the warm
    # sweep was paying two per window (measured ~100 ms each over the
    # bench tunnel, dominating small streamed runs)
    c = None
    sums_d = None
    for it in cache.items():
        if c is None:
            c = int(it.arrays["bins"].shape[1])
        if init_score is None:
            s = jnp.stack([(it.arrays["tw"] * it.arrays["y"]).sum(),
                           it.arrays["tw"].sum()])
            sums_d = s if sums_d is None else sums_d + s
    if c is None:
        raise RuntimeError("streamed GBT: empty shard stream")
    init_d = None
    if init_score is None:
        if cache.tail is None and not trees:
            # fully-resident fresh run (the common fused path): keep the
            # prior ON DEVICE — the host float() here was a full link
            # round trip blocking the first tree (fetched lazily below
            # only for checkpoints / the final result)
            init_d = _init_score_jit(settings.loss)(sums_d)
        else:
            init_score = float(_init_score_jit(settings.loss)(sums_d))

    def init_host() -> float:
        """The prior as a host float — materialized at most once, off the
        tree-dispatch critical path."""
        nonlocal init_score
        if init_score is None:
            init_score = float(init_d)
        return init_score

    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())
    fi_parts: List[np.ndarray] = []    # per-tree split gains [C] (ride the
                                       # packed fetch; a mid-batch early
                                       # stop drops the tail's parts too)

    f = None if init_d is not None else np.full(n_rows, init_score,
                                                np.float32)
    f_ref["f"] = f
    if trees and init_scores is not None and len(init_scores) == n_rows:
        # checkpointed scores restore f byte-exact (see train_gbt: the
        # eager replay below is only f32-equivalent to the in-stream
        # update and can flip borderline splits)
        f = np.asarray(init_scores, np.float32).copy()
        f_ref["f"] = f
    else:
        for t in trees:  # continuous: replay stored trees over the cache
            sf, lm, lv = (jnp.asarray(t.split_feat),
                          jnp.asarray(t.left_mask),
                          jnp.asarray(t.leaf_value))
            for it in cache.items():
                pred = predict_tree(sf, lm, lv, it.arrays["bins"], t.depth)
                s, e = it.start, it.start + it.n_valid
                f[s:e] += settings.learning_rate * \
                    np.asarray(pred)[:it.n_valid]

    def window_f(it):
        """Resident windows keep their score slice ON DEVICE across trees
        and levels (zero fetches); only tail windows round-trip host f —
        and their slice was already put FROM THE PREP THREAD (``f_prep``,
        see :func:`_gbt_prepare`) so the transfer overlapped compute.
        A deferred device prior broadcasts on device (f is None only on
        the fully-resident fresh path, where no tail window exists)."""
        if it.resident:
            it.arrays.pop("f_prep", None)   # resumed warm pass: free the
            fw = it.arrays.get("f")         # prep-shipped slice, the
            if fw is None:                  # persistent cache wins
                fw = (_window_f(f, it, mesh) if f is not None
                      else _bcast_rows(it.rows, mesh)(init_d))
                it.arrays["f"] = fw
            return fw
        fp = it.arrays.pop("f_prep", None)
        return fp if fp is not None else _window_f(f, it, mesh)

    imp = "friedmanmse" if settings.impurity == "friedmanmse" else "variance"
    pending_fused: List[Any] = []

    def absorb_fused(flat_list) -> None:
        for packed in flat_list:
            tree, fi_h, sums = _unpack_streamed(packed, total, n_bins, c,
                                                settings.depth)
            fi_parts.append(fi_h.astype(np.float64))
            trees.append(tree)
            history.append((float(sums[0]) / max(float(sums[1]), 1e-9),
                            float(sums[2]) / max(float(sums[3]), 1e-9)))

    def drain_fused() -> None:
        if pending_fused:
            absorb_fused(_fetch(jnp.stack(pending_fused)))
            pending_fused.clear()

    # early stop reads the bulk-fetched error stream every
    # ``early_stop_check`` trees; a progress consumer's lines batch
    # through the shared flusher
    flush_progress, mark_progress = _progress_flusher(
        drain_fused, history, progress, len(trees) - len(history))
    es_checked = len(history)       # stopper already replayed these
    h0 = len(history)               # fi_parts align with history[h0:]

    # fully-resident: COALESCE the windows into one device-resident row
    # block once and run the RESIDENT per-tree round on it — the
    # per-(window, level) dispatch pattern cost ~(depth+2) x windows
    # kernel launches per tree (measured ~10x the resident path at bench
    # shapes), and the resident round carries every tree-kernel
    # optimization (histogram subtraction, leaf-sum bottom level, fused
    # predict).  Tail regimes keep the window loop below.
    mega = None
    if cache.warmed and cache.tail is None:
        items = list(cache.items())
        mega = {k: _concat_rows([it.arrays[k] for it in items])
                for k in ("bins", "y", "tw", "vw")}
        mega["f"] = _concat_rows([window_f(it) for it in items])

    # ------------------------------------------------------- disk tail
    # the dataset exceeds the resident budget: one disk pass must feed
    # everything.  The resident prefix coalesces into ONE device block
    # (per-window dispatch gone), and trees grow either coarse-to-fine
    # (speculate the structure on the resident prefix, verify every
    # level's exact histograms in ONE fused tail pass that also carries
    # the previous tree's score update — disk passes per tree drop from
    # depth+2 to ~1, repairs only where the speculation diverges) or, with
    # the knob off / an over-budget histogram state, by exact per-level
    # sweeps with subtraction + a leaf-sum bottom (the resident grow's
    # kernel savings, streamed).
    if mega is None and cache.tail is not None and not replay_stopped \
            and len(trees) < settings.n_trees:
        from ..data.streaming import PreparedWindow
        res_rows = cache.resident_rows
        rmega = None
        mega_it = None
        if cache.cached:
            items_r = list(cache.cached)
            rmega = {k: _concat_rows([it.arrays[k] for it in items_r])
                     for k in ("bins", "y", "tw", "vw")}
            rmega["f"] = _concat_rows([window_f(it) for it in items_r])
            for it in items_r:   # window buffers live on in the block
                it.arrays.clear()
            mega_it = PreparedWindow(0, res_rows, res_rows,
                                     np.arange(res_rows), rmega,
                                     resident=True)

        def sweep_items():
            if mega_it is not None:
                yield mega_it
            yield from cache.tail_items()

        def exact_levels(fa, sf, lm, lv, nodes_cnt, fi_add,
                         start_level: int, full_prev, capture=None):
            """Exact per-level sweeps for levels [start_level..depth-1]
            plus the leaf-sum bottom — the knob-off schedule AND the
            coarse-to-fine repair path (one implementation, they must
            never drift).  Levels with a parent histogram in hand build
            left children only and derive the right by subtraction.

            ``capture`` (optional dict) receives each LEFT-built level's
            tail-only left-child histogram (total minus the resident
            block's prefix sum) — exactly-routed along the FINAL
            structure, so the repair path can refresh the next tree's
            stale-tail evidence below the divergence point."""
            for level in range(start_level, settings.depth):
                n_nodes = 1 << level
                left = level > 0 and full_prev is not None
                width = n_nodes // 2 if left else n_nodes
                hist = jnp.zeros((width, c, n_bins, 2), jnp.float32)
                hist_res = None
                for it in sweep_items():
                    hist = _gbt_window_hist(
                        hist, it.arrays["bins"], it.arrays["y"],
                        it.arrays["tw"], window_f(it), sf, lm, width,
                        n_bins, level, settings.loss, up,
                        _hist_mesh(mesh), left)
                    if up:
                        # the pallas launch inside the program is opaque
                        # to XLA's cost analysis — record the analytic
                        # model (ops/hist_pallas) per window launch
                        obs.record_model_launch(
                            "pallas.hist",
                            rows=int(it.arrays["bins"].shape[0]),
                            n_feat=c, n_bins=n_bins, n_nodes=width)
                    if it.resident:
                        hist_res = hist
                if left:
                    if capture is not None:
                        capture[level] = hist - hist_res \
                            if hist_res is not None else hist
                    feat_prev = jax.lax.dynamic_slice_in_dim(
                        sf, width - 1, width)
                    hist = _derive_level(full_prev, hist, feat_prev,
                                         n_nodes)
                sf, lm, lv, nodes_cnt, fi_add = _tree_level_step(
                    hist, cat, fa, imp, settings.min_instances,
                    settings.min_gain, hc, level, settings.depth,
                    settings.max_leaves, sf, lm, lv, nodes_cnt, fi_add)
                full_prev = hist
            raw = jnp.zeros((2, 1 << settings.depth), jnp.float32)
            for it in sweep_items():
                raw = _gbt_window_leaf_raw(
                    raw, it.arrays["bins"], it.arrays["y"],
                    it.arrays["tw"], window_f(it), sf, lm,
                    settings.depth, settings.loss)
            return sf, lm, _set_bottom_leaves(lv, raw, settings.depth), \
                fi_add

        def update_sweep(sf, lm, lv, want_scores: bool):
            """Previous-tree score update + error sums over every window
            (resident block + tail); tail f slices write back DEFERRED so
            the fetches overlap the in-flight window programs."""
            sums_dev = jnp.zeros(4, jnp.float32)
            wb = []
            for it in sweep_items():
                f2, sums_dev = _gbt_window_update(
                    sums_dev, it.arrays["bins"], it.arrays["y"],
                    it.arrays["tw"], it.arrays["vw"], window_f(it),
                    sf, lm, lv, settings.learning_rate, settings.depth,
                    settings.loss)
                if it.resident:
                    it.arrays["f"] = f2
                else:
                    wb.append((it.start, it.n_valid, f2))
            for s, nv, f2 in wb:
                f[s:s + nv] = np.asarray(f2)[:nv]
            scores = None
            if want_scores:
                scores = tail_scores()
            return sums_dev, scores

        def tail_scores() -> np.ndarray:
            """Full per-row scores for a checkpoint: resident slice from
            the device block, tail rows from the host cache."""
            scores = np.empty(n_rows, np.float32)
            if rmega is not None:
                scores[:res_rows] = np.asarray(rmega["f"])[:res_rows]
            scores[res_rows:] = f[res_rows:n_rows]
            return scores

        use_c2f = (rmega is not None and _tail_coarse_to_fine()
                   and _c2f_feasible(settings, c, n_bins))
        cand_k = _tail_candidate_k(c) if use_c2f else 0
        lr_d = jnp.float32(settings.learning_rate)
        zero_tree = (jnp.zeros(total, jnp.int32),
                     jnp.zeros((total, n_bins), bool),
                     jnp.zeros(total, jnp.float32))
        prev = None                  # device arrays of the last built tree
        pend: List[Any] = []         # device-packed [sf, bits, lv, fi]
        drains = 0

        def drain_pend() -> None:
            nonlocal drains
            if not pend:
                return
            flat = _fetch(jnp.stack(pend))
            pend.clear()
            sizes = [total, _mask_nbytes(total, n_bins), total, c]
            for vec in flat:
                sf_h, lm_h, lv_h, fi_h = np.split(vec,
                                                  np.cumsum(sizes)[:-1])
                trees.append(TreeArrays(
                    split_feat=sf_h.astype(np.int32),
                    left_mask=_unpack_mask_bits(lm_h, total, n_bins),
                    leaf_value=lv_h.astype(np.float32),
                    depth=settings.depth))
                fi_parts.append(fi_h.astype(np.float64))
            drains += 1
            faults.fire("train", "superbatch", drains)

        built = len(trees)
        stopped = False
        f_behind = False             # last built tree's update pending?
        fell_back = False            # speculation gave up -> exact path
        if use_c2f:
            tail_extra = None        # prev pass's exact tail evidence
            valid_upto = jnp.int32(0)
            lowmis_run = 0           # consecutive near-root repairs
            while built < settings.n_trees:
                ti = built
                fa = jnp.asarray(_feat_subset(settings, c, ti))
                has_prev = prev is not None
                p_sf, p_lm, p_lv = prev if prev is not None else zero_tree
                (sf_c, lm_c, hl_res, raw_acc, f_res2, sums_d,
                 cand_idx) = _gbt_tail_head(
                        rmega["bins"], rmega["y"], rmega["tw"],
                        rmega["vw"], rmega["f"], p_sf, p_lm, p_lv, fa,
                        cat, lr_d, settings.min_instances,
                        settings.min_gain,
                        tail_extra if has_prev else None,
                        valid_upto, n_bins,
                        settings.depth, imp, settings.loss, up,
                        settings.max_leaves, hc, _hist_mesh(mesh),
                        has_prev, cand_k)
                rmega["f"] = f_res2
                hl_acc = hl_res
                wb = []
                for it in cache.tail_items():
                    hl_acc, raw_acc, sums_d, f2 = _gbt_tail_window_pass(
                        hl_acc, raw_acc, sums_d, it.arrays["bins"],
                        it.arrays["y"], it.arrays["tw"],
                        it.arrays["vw"], window_f(it), p_sf, p_lm, p_lv,
                        sf_c, lm_c, cand_idx, lr_d, n_bins,
                        settings.depth, settings.loss, up,
                        _hist_mesh(mesh), has_prev, cand_k > 0)
                    wb.append((it.start, it.n_valid, f2))
                sf_t, lm_t, lv_t, fi_lv, cnt_lv, mism_d, full_lv = \
                    _gbt_tail_select(
                        hl_acc, raw_acc, sf_c, lm_c, cand_idx, cat, fa,
                        settings.min_instances, settings.min_gain,
                        n_bins, settings.depth, imp, settings.max_leaves,
                        hc, cand_k > 0)
                tail_extra = _tail_extras(hl_acc, hl_res, cand_idx, c,
                                          cand_k > 0)
                for s, nv, f2 in wb:    # deferred: overlaps the select
                    f[s:s + nv] = np.asarray(f2)[:nv]
                small = _fetch(_pack_small(sums_d, mism_d))
                if has_prev:
                    tr_e = float(small[0]) / max(float(small[1]), 1e-9)
                    va_e = float(small[2]) / max(float(small[3]), 1e-9)
                    history.append((tr_e, va_e))
                    f_behind = False
                    if progress:
                        progress(ti - 1, tr_e, va_e)
                    if settings.early_stop and stopper.add(va_e):
                        # the stop decision lands one pass late; the
                        # in-flight tree ti is exactly the tree the
                        # per-tree loop would never have grown — drop it
                        obs.event("early_stop", trainer="gbt_streamed",
                                  tree=ti)
                        log.info("GBT early stop after %d trees "
                                 "(streamed tail)", ti)
                        drain_pend()
                        if checkpoint_fn and settings.checkpoint_every:
                            checkpoint_fn(trees, history, init_host())
                        stopped = True
                        break
                mis = int(small[4])
                valid_upto = jnp.int32(settings.depth)
                if mis < settings.depth:
                    # speculation diverged at `mis`: its own selection is
                    # exact (routed by confirmed levels), deeper
                    # histograms are mis-routed — repair them with exact
                    # per-level sweeps.  Seeding the repair's subtraction
                    # chain with the select pass's exact level-`mis` FULL
                    # histogram keeps the repair bit-identical to the
                    # pure exact schedule (a direct full rebuild would
                    # round differently than parent-minus-left); the
                    # repair's tail-only left sums refresh the stale
                    # evidence below the divergence so the NEXT tree
                    # speculates from full-depth, exactly-routed
                    # evidence.
                    # repair is the speculation MISS branch — rare
                    # or the schedule auto-falls-back entirely
                    obs.counter("train.tail_repairs").inc()  # shifu-lint: disable=telemetry-guard
                    obs.counter("train.tail_repair_levels").inc(  # shifu-lint: disable=telemetry-guard
                        settings.depth - mis)
                    fi_base = jnp.sum(fi_lv[:mis + 1], axis=0)
                    cap: Dict[int, Any] = {} if cand_k == 0 else None
                    sf_t, lm_t, lv_t, fi_tree = exact_levels(
                        fa, sf_t, lm_t, lv_t, cnt_lv[mis], fi_base,
                        mis + 1, full_lv[mis][:1 << mis]
                        if cand_k == 0 else None, capture=cap)
                    if cap:
                        for lvl, h in cap.items():
                            tail_extra = tail_extra.at[
                                lvl, :h.shape[0]].set(h)
                    elif cand_k > 0:
                        # bounded-candidate mode: deeper evidence stays
                        # routed by the abandoned speculation — invalid
                        valid_upto = jnp.int32(mis)
                else:
                    fi_tree = jnp.sum(fi_lv, axis=0)
                # adaptive surrender: with stale-tail evidence in play the
                # confirmed depth should climb tree over tree; a long run
                # of near-root repairs means this plane's split landscape
                # is speculation-hostile (e.g. label noise) and every c2f
                # tree costs exact + a wasted fused pass — finish the
                # forest on the exact schedule instead (same forest bits;
                # only the pass count changes)
                lowmis_run = lowmis_run + 1 \
                    if (has_prev and mis <= 1) else 0
                prev = (sf_t, lm_t, lv_t)
                pend.append(_pack_c2f(sf_t, lm_t, lv_t, fi_tree))
                built += 1
                f_behind = True
                if len(pend) >= 8:
                    drain_pend()
                if checkpoint_fn and settings.checkpoint_every and \
                        built > 1 and \
                        (built - 1) % settings.checkpoint_every == 0:
                    # super-batch drain boundary: commit the prefix whose
                    # scores are final (the freshly built tree's update
                    # lands fused into the NEXT tree's tail pass)
                    drain_pend()
                    checkpoint_fn(trees[:built - 1],
                                  history[:built - 1], init_host(),
                                  tail_scores())
                if lowmis_run >= 6 and built < settings.n_trees:
                    # fires at most once per train (exits c2f)
                    obs.counter("train.tail_c2f_fallbacks").inc()  # shifu-lint: disable=telemetry-guard
                    log.info("GBT tail: speculation repaired near the "
                             "root %d trees running — falling back to "
                             "the exact per-level schedule at tree %d",
                             lowmis_run, built)
                    fell_back = True
                    break
            if not stopped and f_behind and prev is not None:
                # trailing pass: the last tree's update + error sums
                sums_dev, _ = update_sweep(*prev, want_scores=False)
                sums_h = _fetch(sums_dev)
                tr_e = float(sums_h[0]) / max(float(sums_h[1]), 1e-9)
                va_e = float(sums_h[2]) / max(float(sums_h[3]), 1e-9)
                history.append((tr_e, va_e))
                if progress:
                    progress(built - 1, tr_e, va_e)
                f_behind = False
            drain_pend()
        if not use_c2f or fell_back:
            while built < settings.n_trees and not stopped:
                ti = built
                fa = jnp.asarray(_feat_subset(settings, c, ti))
                sf = jnp.full(total, -1, jnp.int32)
                lm = jnp.zeros((total, n_bins), bool)
                lv = jnp.zeros(total, jnp.float32)
                sf, lm, lv, fi_add = exact_levels(
                    fa, sf, lm, lv, jnp.int32(1),
                    jnp.zeros(c, jnp.float32), 0, None)
                ckpt_due = bool(
                    checkpoint_fn and settings.checkpoint_every and
                    (ti + 1) % settings.checkpoint_every == 0)
                sums_dev, scores = update_sweep(sf, lm, lv, ckpt_due)
                absorb_fused([_fetch(jnp.concatenate([
                    sf.astype(jnp.float32), _pack_mask_bits(lm),
                    lv, fi_add, sums_dev]))])
                built += 1
                tr_err, va_err = history[-1]
                if progress:
                    progress(ti, tr_err, va_err)
                mark_progress()
                if ckpt_due:
                    checkpoint_fn(trees, history, init_host(), scores)
                if settings.early_stop and stopper.add(va_err):
                    obs.event("early_stop", trainer="gbt_streamed",
                              tree=ti + 1)
                    log.info("GBT early stop after %d trees (streamed)",
                             ti + 1)
                    if checkpoint_fn and settings.checkpoint_every:
                        checkpoint_fn(trees, history, init_host())
                    stopped = True
        return ForestResult(
            trees=trees,
            spec_kwargs={"algorithm": "GBT", "loss": settings.loss,
                         "learning_rate": settings.learning_rate,
                         "init_score": init_host()},
            train_error=history[-1][0] if history else float("nan"),
            valid_error=history[-1][1] if history else float("nan"),
            feature_importance=(np.sum(fi_parts, axis=0) if fi_parts
                                else np.zeros(c)),
            trees_built=len(trees), history=history,
            disk_passes=cache.disk_passes,
            tail_sweeps=cache.tail_sweeps,
            bytes_read=stream.bytes_read - bytes0)

    start_ti = settings.n_trees if replay_stopped \
        else len(trees) + len(pending_fused)
    for ti in range(start_ti, settings.n_trees):
        fa = jnp.asarray(_feat_subset(settings, c, ti))
        if mega is not None:
            packed_d, mega["f"] = _gbt_round_streamed(
                mega["bins"], mega["y"], mega["tw"], mega["vw"], mega["f"],
                fa, cat, settings.learning_rate, settings.min_instances,
                settings.min_gain, n_bins, settings.depth, imp,
                settings.loss, up, settings.max_leaves, hc,
                _hist_mesh(mesh))
            pending_fused.append(packed_d)
            # early stop checks the bulk-fetched error stream every
            # ``early_stop_check`` trees (device-side accumulation in
            # between — no per-tree sync); a mid-batch trigger truncates
            # to the exact tree the per-tree decision would have kept
            if settings.early_stop and \
                    (len(pending_fused) >= settings.early_stop_check
                     or ti + 1 == settings.n_trees):
                drain_fused()
                triggered = None
                for j, (_, va_err) in enumerate(history[es_checked:]):
                    if stopper.add(va_err):
                        triggered = es_checked + j
                        break
                if triggered is not None:
                    kept = triggered + 1
                    del trees[kept + len(trees) - len(history):]
                    del fi_parts[kept - h0:]
                    del history[kept:]
                    obs.event("early_stop", trainer="gbt_streamed",
                              tree=len(trees))
                    log.info("GBT early stop after %d trees (streamed)",
                             len(trees))
                    if checkpoint_fn and settings.checkpoint_every:
                        # pin the truncated forest: a crash before the
                        # final model write resumes to this exact state
                        # (no scores — a stopped forest never grows)
                        checkpoint_fn(trees, history, init_host())
                    break
                es_checked = len(history)
                flush_progress()
            elif progress and len(pending_fused) >= 8:
                flush_progress()
            if checkpoint_fn and settings.checkpoint_every and \
                    (ti + 1) % min(settings.checkpoint_every, 8) == 0:
                # TreeBatch-boundary cadence (8 = the fused drain burst)
                flush_progress()
                checkpoint_fn(trees, history, init_host(),
                              np.asarray(mega["f"])[:n_rows])
    flush_progress()
    return ForestResult(
        trees=trees,
        spec_kwargs={"algorithm": "GBT", "loss": settings.loss,
                     "learning_rate": settings.learning_rate,
                     "init_score": init_host()},
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=(np.sum(fi_parts, axis=0) if fi_parts
                            else np.zeros(c)),
        trees_built=len(trees), history=history,
        disk_passes=cache.disk_passes,
        tail_sweeps=cache.tail_sweeps,
        bytes_read=stream.bytes_read - bytes0)


@lru_cache(maxsize=None)
def _concat_rows_jit(k: int):
    """jitted row-concat — eager concatenation of mesh-sharded window
    arrays aborts XLA:CPU (the known eager-reshard SIGABRT); under jit
    the partitioner inserts the reshard."""
    return jax.jit(lambda *xs: jnp.concatenate(xs, axis=0))  # shifu-lint: disable=recompile-hazard


def _concat_rows(xs):
    return xs[0] if len(xs) == 1 else _concat_rows_jit(len(xs))(*xs)


def _gbt_round_streamed_impl(bins, y, tw, vw, f, fa, cat, lr, mi, mg,
                             n_bins, depth, impurity, loss, use_pallas,
                             max_leaves, has_cat, mesh):
    return _pack_round_streamed(*_gbt_round_impl(
        bins, y, tw, vw, f, fa, cat, lr, mi, mg, n_bins, depth, impurity,
        loss, use_pallas, max_leaves, has_cat, mesh))


_gbt_round_streamed = obs.costed_jit(
    "gbt.round_streamed", _gbt_round_streamed_impl, lazy=True,
    static_argnames=("n_bins", "depth", "impurity", "loss", "use_pallas",
                     "max_leaves", "has_cat", "mesh"))


def _pack_round_streamed(sf, lm, lv, gfi, f2, tr, va):
    """Resident-round outputs in the STREAMED packed layout
    ([sf, mask-bits, lv, fi, sums4] — :func:`_unpack_streamed` divides
    sums pairwise, so unit denominators carry the ready-made errors)."""
    one = jnp.ones((), jnp.float32)
    return jnp.concatenate([
        sf.astype(jnp.float32), _pack_mask_bits(lm), lv, gfi,
        jnp.stack([tr, one, va, one])]), f2


@partial(obs.costed_jit, "rf.round_streamed", lazy=True,
         static_argnames=("n_bins", "depth", "impurity", "loss",
                          "poisson", "n_classes", "use_pallas",
                          "max_leaves", "has_cat", "mesh",
                          "stats_exact"))
def _rf_round_streamed(bins, y, w, idx_hi, idx_lo, khi, klo, thi, tlo,
                       oob_sum, oob_cnt, fa, cat, mi, mg, n_bins: int,
                       depth: int, impurity: str, loss: str,
                       poisson: bool, n_classes: int = 0,
                       use_pallas: bool = False, max_leaves: int = 0,
                       has_cat: bool = True, mesh=None,
                       stats_exact: bool = False):
    """Streamed-RF resident round: the per-tree hash bag replays ON
    DEVICE (``ops/hashing.py`` splitmix64, bit-identical to the host
    ``window_bag`` stream), then the shared RF round body runs and packs
    in the streamed layout."""
    from ..ops.hashing import hash_poisson_traced
    bag = hash_poisson_traced(idx_hi, idx_lo, khi, klo, thi, tlo) \
        if poisson else jnp.ones(w.shape[0], jnp.float32)
    sf, lm, lv, gfi, os2, oc2, tr, va = _rf_round_from_bag(
        bins, y, w, bag, oob_sum, oob_cnt, fa, cat, mi, mg, n_bins,
        depth, impurity, loss, n_classes, use_pallas, max_leaves,
        has_cat, mesh, stats_exact)
    one = jnp.ones((), jnp.float32)
    packed = jnp.concatenate([
        sf.astype(jnp.float32), _pack_mask_bits(lm), lv.reshape(-1), gfi,
        jnp.stack([tr, one, va, one])])
    return packed, os2, oc2


def _window_f(f: np.ndarray, win, mesh=None):
    """Slice a per-row cache (1D scores or 2D per-class votes) for a
    window, padding past the end; shard over the mesh data axis so it
    joins the window's arrays' layout."""
    s = win.start
    e = min(s + win.rows, len(f))
    out = np.zeros((win.rows,) + f.shape[1:], np.float32)
    out[:e - s] = f[s:e]
    return _shard_rows(out, mesh)


def _rf_prepare(mesh, n_bins: int, y_transform=None, mask_fn=None):
    """Window prepare hook for streamed RF: zero weights past n_valid once,
    arrays onto the device (mesh-sharded over the data axis).
    ``mask_fn(index, targets) -> (train_w, _)``: bagging/grid members
    multiply their member's stateless row sample into the weights (the
    out-of-bag vote still validates within the member's rows)."""
    from ..data.streaming import PreparedWindow

    def prep(win):
        w = np.asarray(win.arrays["w"], np.float32).copy()
        w[win.n_valid:] = 0.0
        y = np.asarray(win.arrays["y"], np.float32)
        if mask_fn is not None:
            w *= mask_fn(win.index, y)[0].astype(np.float32)
        if y_transform is not None:
            y = np.asarray(y_transform(y), np.float32)
        dev = _put_row_floats(mesh, {"y": y, "w": w})
        dev["bins"] = _put_bins(mesh, win.arrays["bins"], n_bins)
        return PreparedWindow(win.start, win.n_valid, win.rows,
                              win.index, dev)
    return prep


def _shard_rows(a: np.ndarray, mesh=None):
    """Place a per-window row array next to the window's (possibly
    mesh-sharded) arrays so jitted window steps see one layout."""
    if mesh is None:
        return jnp.asarray(a)
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P
    spec = P("data") if a.ndim == 1 else P("data", None)
    return jax.device_put(a, NamedSharding(mesh, spec))


def train_rf_streamed(stream, n_bins: int, cat_mask, settings: DTSettings,
                      progress=None,
                      checkpoint_fn: Optional[Callable] = None,
                      init_trees: Optional[List[TreeArrays]] = None,
                      start_history: Optional[List] = None,
                      mesh=None,
                      cache_budget: Optional[int] = None,
                      y_transform=None, mask_fn=None) -> ForestResult:
    """Out-of-core RF over a ResidentCache: hash-based Poisson bags per
    (tree, row) keep bagging stateless across sweeps; oob vote caches
    (2 host arrays, rows x 4B) carry validation across trees.  Windows
    under the device budget are mesh-sharded HBM residents (re-sweeping
    them costs no IO); only the tail re-streams from disk.  (Reference:
    ``DTWorker.java:763-884`` histogram merge, ``DTMaster.java:274-533``
    split pick, ``MemoryDiskFloatMLDataSet.java:54-99`` memory tier.)"""
    from ..data.streaming import ResidentCache, _hash_poisson, row_uniform

    _require_divisible(stream, mesh)
    up = _use_pallas(mesh)
    n_rows = stream.num_rows
    total = n_tree_nodes(settings.depth)
    trees: List[TreeArrays] = list(init_trees or [])
    history: List[Tuple[float, float]] = list(start_history or [])

    bytes0 = stream.bytes_read
    cache = ResidentCache(stream,
                          _default_cache_budget() if cache_budget is None
                          else cache_budget,
                          _rf_prepare(mesh, n_bins, y_transform, mask_fn),
                          pipeline_depth=_pipeline_depth(mesh))
    c = None
    for win in stream.windows():      # peek the first window for the width;
        c = int(win.arrays["bins"].shape[1])   # cache warms during useful
        break                                  # level-0 work, not here
    if c is None:
        raise RuntimeError("streamed RF: empty shard stream")
    cat = jnp.asarray(cat_mask if cat_mask is not None else np.zeros(c, bool))
    hc = bool(np.asarray(cat).any())
    K = settings.n_classes
    mc = K > 2          # NATIVE multiclass: per-class vote caches
    oob_sum = np.zeros((n_rows, K) if mc else n_rows, np.float32)
    oob_cnt = np.zeros(n_rows, np.float32)
    fi_dev = jnp.zeros(c, jnp.float32)     # device-accumulated split gains

    # per-(tree, window) bags are deterministic; memoized so the depth+2
    # sweeps of a tree hash/upload each window's bag once
    bag_cache: Dict[Tuple[int, int], Any] = {}

    def host_bag(ti: int, it) -> np.ndarray:
        """The per-(tree, window) stateless bag — the ONE place that knows
        the hash stream, shared by the per-tree and tail-batch paths so
        they stay bit-identical."""
        u = row_uniform(settings.seed, 5000 + ti, it.index)
        bag = _hash_poisson(settings.bagging_rate, u) \
            if settings.poisson_bagging else np.ones(it.rows, np.float32)
        bag[it.n_valid:] = 0.0
        return bag.astype(np.float32)

    def window_bag(ti: int, it):
        key = (ti, it.start)
        dev = bag_cache.get(key)
        if dev is None:
            dev = _shard_rows(host_bag(ti, it), mesh)
            if it.resident:      # tail bags would grow with the dataset
                bag_cache[key] = dev
        return dev

    def window_oob(it):
        """Resident windows keep oob vote state ON DEVICE across trees;
        tail windows round-trip the host arrays."""
        if it.resident:
            pair = it.arrays.get("oob")
            if pair is None:
                pair = (_window_f(oob_sum, it, mesh),
                        _window_f(oob_cnt, it, mesh))
                it.arrays["oob"] = pair
            return pair
        return (_window_f(oob_sum, it, mesh), _window_f(oob_cnt, it, mesh))

    def accumulate_oob(ti: int, sf, lm, lv, depth: int):
        """Device-side error sums; only tail windows fetch oob state."""
        sums_dev = jnp.zeros(4, jnp.float32)
        for it in cache.items():
            osw, ocw = window_oob(it)
            os2, oc2, sums_dev = _rf_window_update(
                sums_dev, it.arrays["bins"], it.arrays["y"],
                it.arrays["w"], window_bag(ti, it), osw, ocw, sf, lm, lv,
                depth, settings.loss, settings.n_classes)
            if it.resident:
                it.arrays["oob"] = (os2, oc2)
            else:
                s, e = it.start, it.start + it.n_valid
                oob_sum[s:e] = np.asarray(os2)[:it.n_valid]
                oob_cnt[s:e] = np.asarray(oc2)[:it.n_valid]
        return sums_dev

    # resumed/continuous: replay oob accumulation for stored trees
    for ti, t_old in enumerate(trees):
        bag_cache.clear()
        accumulate_oob(ti, jnp.asarray(t_old.split_feat),
                       jnp.asarray(t_old.left_mask),
                       jnp.asarray(t_old.leaf_value), t_old.depth)

    def absorb_rf(flat_list) -> None:
        nonlocal fi_dev
        for packed in flat_list:
            tree, fi_h, sums = _unpack_streamed(packed, total, n_bins, c,
                                                settings.depth,
                                                settings.n_classes)
            fi_dev = fi_dev + jnp.asarray(fi_h)
            trees.append(tree)
            va_err = float(sums[0]) / max(float(sums[1]), 1e-9) \
                if sums[1] > 0 else float("nan")
            history.append((float(sums[2]) / max(float(sums[3]), 1e-9),
                            va_err))

    pending_rf: List[Any] = []

    def drain_rf() -> None:
        if pending_rf:
            absorb_rf(_fetch(jnp.stack(pending_rf)))
            pending_rf.clear()

    flush_progress_rf, mark_progress_rf = _progress_flusher(
        drain_rf, history, progress, len(trees) - len(history))

    ti = len(trees) + len(pending_rf)
    mega = None                 # fully-resident: ONE coalesced row block
    thi_tlo = None              # device Poisson thresholds (tail batches)
    sb_drains = 0               # super-batch drains (faults site ordinal)
    while ti < settings.n_trees:
        bag_cache.clear()
        if mega is None and cache.warmed and cache.tail is None:
            # fully resident: coalesce windows once and run the resident
            # round per tree (see the GBT mega path).  Bags replay the
            # SAME host hash stream on device, BIT-identical
            # (ops/hashing.py) — resume replays over windows therefore
            # see exactly the bags these trees trained with; the
            # histogram arithmetic itself follows the resident kernel's
            # subtraction order (f32-equivalent, not byte-equal, to the
            # window sweep)
            from ..ops.hashing import split_index_u32, thresholds_u32
            items = list(cache.items())
            mega = {k: _concat_rows([it.arrays[k] for it in items])
                    for k in ("bins", "y", "w")}
            oobs = [window_oob(it) for it in items]
            mega["oob_sum"] = _concat_rows([o[0] for o in oobs])
            mega["oob_cnt"] = _concat_rows([o[1] for o in oobs])
            ih, il = split_index_u32(np.concatenate(
                [np.asarray(it.index, np.uint64) for it in items]))
            mega["idx_hi"] = _shard_rows(ih, mesh)
            mega["idx_lo"] = _shard_rows(il, mesh)
            thi, tlo = thresholds_u32(settings.bagging_rate)
            mega["thi"] = jnp.asarray(thi)
            mega["tlo"] = jnp.asarray(tlo)
        if mega is not None:
            from ..ops.hashing import row_key_u32
            khi, klo = row_key_u32(settings.seed, 5000 + ti)
            packed_d, mega["oob_sum"], mega["oob_cnt"] = _rf_round_streamed(
                mega["bins"], mega["y"], mega["w"], mega["idx_hi"],
                mega["idx_lo"], jnp.uint32(khi), jnp.uint32(klo),
                mega["thi"], mega["tlo"], mega["oob_sum"],
                mega["oob_cnt"], jnp.asarray(_feat_subset(settings, c, ti)),
                cat, settings.min_instances, settings.min_gain, n_bins,
                settings.depth, settings.impurity, settings.loss,
                settings.poisson_bagging, settings.n_classes, up,
                settings.max_leaves, hc, _hist_mesh(mesh),
                settings.stats_exact)
            pending_rf.append(packed_d)
            if progress and len(pending_rf) >= 8:
                flush_progress_rf()
            if checkpoint_fn and settings.checkpoint_every and \
                    (ti + 1) % min(settings.checkpoint_every, 8) == 0:
                # TreeBatch-boundary cadence (8 = the fetch burst)
                flush_progress_rf()
                checkpoint_fn(trees, history, None)
            ti += 1
            continue
        # disk-tail regime: grow a SUPER-BATCH of independent trees per
        # sweep — the reference's DTMaster grows ALL RF trees
        # simultaneously, one stats pass per level for the whole forest
        # (``DTMaster.java:91`` toDoQueue spans trees); per-tree sweeps
        # would re-stream the disk tail TreeNum times per level.  The
        # batch width is budget-derived (:func:`_tail_super_batch`, the
        # TailTreeBatch knob) so disk passes per tree scale as
        # (depth+2)/SB; bags hash ON DEVICE from two [W] uint32 index
        # halves per window (bit-identical to the host stream, and the
        # [SB, W] bag plane never rides the wire); levels > 0 accumulate
        # LEFT children only and derive right = parent - left, and the
        # bottom level is a leaf-sum dot instead of the deepest
        # histogram.  Bit-identical to the per-tree order: bags are
        # stateless per (tree, row) and oob votes chain through the
        # batch in tree order per window.
        from ..ops.hashing import row_key_u32, split_index_u32, \
            thresholds_u32
        n_stats = K if mc else 2
        SB = _tail_super_batch(settings, c, n_bins, n_stats)
        if thi_tlo is None:
            t_hi, t_lo = thresholds_u32(settings.bagging_rate)
            thi_tlo = (jnp.asarray(t_hi), jnp.asarray(t_lo))
        thi_d, tlo_d = thi_tlo

        def window_idx(it):
            """Device uint32 (hi, lo) halves of the window's global row
            indices — cached for resident windows, recomputed for tail
            re-streams (two [W] puts, ~TB x cheaper than bag planes)."""
            pair = it.arrays.get("idx32") if it.resident else None
            if pair is None:
                ih, il = split_index_u32(np.asarray(it.index, np.uint64))
                pair = (_shard_rows(ih, mesh), _shard_rows(il, mesh))
                if it.resident:
                    it.arrays["idx32"] = pair
            return pair

        TB = min(settings.n_trees - ti, SB)
        if checkpoint_fn and settings.checkpoint_every:
            nxt = ((ti // settings.checkpoint_every) + 1) * \
                settings.checkpoint_every
            TB = max(1, min(TB, nxt - ti))
        tis = list(range(ti, ti + TB))
        keys = [row_key_u32(settings.seed, 5000 + t) for t in tis]
        khi_b = jnp.asarray(np.asarray([k[0] for k in keys], np.uint32))
        klo_b = jnp.asarray(np.asarray([k[1] for k in keys], np.uint32))
        fa_b = jnp.asarray(np.stack(
            [np.asarray(_feat_subset(settings, c, t)) for t in tis]))
        sf_b = jnp.full((TB, total), -1, jnp.int32)
        lm_b = jnp.zeros((TB, total, n_bins), bool)
        lv_b = jnp.zeros((TB, total, K) if mc else (TB, total),
                         jnp.float32)
        cnt_b = jnp.ones(TB, jnp.int32)
        fi_b = jnp.zeros((TB, c), jnp.float32)
        hist_prev = None
        for level in range(settings.depth):
            n_nodes = 1 << level
            left = level > 0
            width = n_nodes // 2 if left else n_nodes
            hist_b = jnp.zeros((TB, width, c, n_bins, n_stats),
                               jnp.float32)
            for it in cache.items():
                ih_d, il_d = window_idx(it)
                hist_b = _rf_window_hist_batch(
                    hist_b, it.arrays["bins"], it.arrays["y"],
                    it.arrays["w"], ih_d, il_d, khi_b, klo_b, thi_d,
                    tlo_d, sf_b, lm_b, width, n_bins, level, up,
                    _hist_mesh(mesh), settings.n_classes,
                    settings.stats_exact, left,
                    settings.poisson_bagging)
                if up:
                    obs.record_model_launch(
                        "pallas.hist",
                        rows=int(it.arrays["bins"].shape[0]),
                        n_feat=c, n_bins=n_bins, n_nodes=width,
                        n_stats=n_stats, n_trees=TB)
            if left:
                feat_prev_b = jax.lax.dynamic_slice_in_dim(
                    sf_b, width - 1, width, axis=1)
                hist_b = _derive_level_batch(hist_prev, hist_b,
                                             feat_prev_b, n_nodes)
            sf_b, lm_b, lv_b, cnt_b, fi_b = _tree_level_step_batch(
                hist_b, cat, fa_b, settings.impurity,
                settings.min_instances, settings.min_gain, hc, level,
                settings.depth, settings.max_leaves, sf_b, lm_b, lv_b,
                cnt_b, fi_b, settings.n_classes)
            hist_prev = hist_b
        # bottom level: leaf-sum dots, one sweep
        raw_b = jnp.zeros((TB, n_stats, 1 << settings.depth),
                          jnp.float32)
        for it in cache.items():
            ih_d, il_d = window_idx(it)
            raw_b = _rf_window_leaf_batch(
                raw_b, it.arrays["bins"], it.arrays["y"],
                it.arrays["w"], ih_d, il_d, khi_b, klo_b, thi_d, tlo_d,
                sf_b, lm_b, settings.depth, settings.n_classes,
                settings.poisson_bagging)
        lv_b = _set_bottom_leaves_batch(lv_b, raw_b, settings.depth,
                                        settings.n_classes)
        # one more sweep: oob votes + error sums for the whole batch,
        # trees chained in order per window
        sums_b = jnp.zeros((TB, 4), jnp.float32)
        for it in cache.items():
            osw, ocw = window_oob(it)
            ih_d, il_d = window_idx(it)
            osw, ocw, sums_b = _rf_window_update_batch(
                sums_b, it.arrays["bins"], it.arrays["y"],
                it.arrays["w"], ih_d, il_d, khi_b, klo_b, thi_d, tlo_d,
                osw, ocw, sf_b, lm_b, lv_b, settings.depth,
                settings.loss, settings.n_classes,
                settings.poisson_bagging)
            if it.resident:
                it.arrays["oob"] = (osw, ocw)
            else:
                s, e = it.start, it.start + it.n_valid
                oob_sum[s:e] = np.asarray(osw)[:it.n_valid]
                oob_cnt[s:e] = np.asarray(ocw)[:it.n_valid]
        absorb_rf(_fetch(_pack_streamed_stacked(
            sf_b, lm_b, lv_b, fi_b, sums_b)))
        if progress:
            for j, t in enumerate(tis):
                tr_err, va_err = history[len(history) - TB + j]
                progress(t, tr_err, va_err)
        mark_progress_rf()
        ti += TB
        sb_drains += 1
        faults.fire("train", "superbatch", sb_drains)
        if checkpoint_fn and settings.checkpoint_every:
            # every super-batch drain is a commit boundary
            checkpoint_fn(trees, history, None)
    flush_progress_rf()
    spec_kwargs: Dict[str, Any] = {"algorithm": "RF"}
    if mc:
        spec_kwargs["extra"] = {"n_classes": K}
    return ForestResult(
        trees=trees, spec_kwargs=spec_kwargs,
        train_error=history[-1][0] if history else float("nan"),
        valid_error=history[-1][1] if history else float("nan"),
        feature_importance=np.asarray(fi_dev, np.float64),
        trees_built=len(trees), history=history,
        disk_passes=cache.disk_passes,
        tail_sweeps=cache.tail_sweeps,
        bytes_read=stream.bytes_read - bytes0)


# -------------------------------------------------------- pipeline driver
def _tree_member_masks(mc, n: int, bags: int, kfold: int, rf_like: bool,
                       targets, seed: int, distinct: bool = False):
    """(tw_m, vw_m) member weight matrices for bagged/fold tree members —
    RF-family members take the full bag as train weight (out-of-bag
    validates), GBT members keep the held-out split.

    ``distinct``: each bagging member draws its OWN validation split from
    its own seed (the reference's per-Guagua-job randomness) — without it,
    default-config GBT bags (sampleRate 1, no replacement, subset ALL)
    would be byte-identical forests.  Grid trials must NOT use it: trials
    share one split so the comparison isolates the hypers."""
    from .sampling import member_masks

    def one(b: int, nb: int, sd: int):
        return member_masks(
            n, nb, valid_rate=0.0 if rf_like else mc.train.validSetRate,
            kfold=kfold, sample_rate=mc.train.baggingSampleRate,
            replacement=mc.train.baggingWithReplacement,
            stratified=mc.train.stratifiedSample, targets=targets, seed=sd)

    if distinct and bags > 1 and not rf_like and not (kfold and kfold > 1):
        pairs = [one(b, 1, seed + b) for b in range(bags)]
        tw_m = np.concatenate([p[0] for p in pairs])
        vw_m = np.concatenate([p[1] for p in pairs])
    else:
        tw_m, vw_m = one(0, bags, seed)
    if rf_like and not (kfold and kfold > 1):
        tw_m = tw_m + vw_m
    return tw_m, vw_m


def _write_feature_importance(proc, col_nums, feature_names, fi_total):
    names = feature_names or [str(cn) for cn in col_nums]
    fi_named = sorted(((names[j], float(v)) for j, v in enumerate(fi_total)),
                      key=lambda kv: -kv[1])
    with open(os.path.join(proc.paths.tmp_dir, "feature_importance.json"),
              "w") as fjson:
        json.dump({k: v for k, v in fi_named}, fjson, indent=2)


def _tree_stream(shards, mesh, params=None):
    """A ShardStream with the tree trainers' window geometry (env knobs +
    data-axis rounding) — the ONE place that computes it (main streamed
    path and per-class OVA sweeps must agree).  ``params`` may carry a
    ``StreamPrefetch`` train-param override for the prefetch/pipeline
    depth (else ``SHIFU_TPU_PREFETCH`` / ``-Dshifu.stream.prefetch``)."""
    from ..data.streaming import ShardStream, stream_window_rows
    ncols = len(shards.schema.get("columnNums", [])) or 1
    window_rows = stream_window_rows(2 * ncols + 8, mesh.shape["data"],
                                     shards)
    prefetch = (params or {}).get("StreamPrefetch")
    return ShardStream(shards, ("bins", "y", "w"), window_rows,
                       prefetch=prefetch)


def _streamed_bag_mask_fn(mc, rf_like: bool, bags: int, seed: int,
                          member: int):
    """Streamed bagged member ``member``'s (train_w, valid_w) mask
    function — THE seed/row policy for out-of-core bags (single-class
    bagging and OVA x bagging must never drift): GBT bags draw their own
    validation split from their own seed (the in-RAM ``distinct=True``
    semantics — else default-config bags are identical forests); RF bags
    share masks and differ by the per-tree Poisson bag seed.  Stratified
    validation degrades to Bernoulli (needs a global pass) — callers warn
    once."""
    from ..data.streaming import mask_fn_from_settings
    if rf_like:
        mm = mask_fn_from_settings(
            bags, valid_rate=0.0,
            sample_rate=mc.train.baggingSampleRate,
            replacement=mc.train.baggingWithReplacement, seed=seed)
        row = member
    else:
        mm = mask_fn_from_settings(
            1, valid_rate=mc.train.validSetRate,
            sample_rate=mc.train.baggingSampleRate,
            replacement=mc.train.baggingWithReplacement,
            seed=seed + member)
        row = 0

    def mf(idx, tgt):
        t, v = mm(idx, tgt)
        return t[row], v[row]
    return mf


def _warn_streamed_stratified(mc) -> None:
    if mc.train.stratifiedSample:
        log.warning("streaming: stratified validation degrades to "
                    "Bernoulli split (needs a global pass)")


def _train_streamed_member(alg, shards, mesh, n_bins, cat_mask,
                           settings: DTSettings, mask_fn,
                           y_transform=None) -> ForestResult:
    """One sequential out-of-core member job (the reference's
    one-Guagua-job-per-bag/combo queue shape)."""
    stream = _tree_stream(shards, mesh)
    if alg == Algorithm.GBT:
        return train_gbt_streamed(stream, n_bins, cat_mask, settings,
                                  mesh=mesh, y_transform=y_transform,
                                  mask_fn=mask_fn)
    return train_rf_streamed(stream, n_bins, cat_mask, settings,
                             mesh=mesh, y_transform=y_transform,
                             mask_fn=mask_fn)


def _save_ova_bag_results(proc, results, alg, k: int, K: int,
                          settings: DTSettings, n_bins, col_nums,
                          feature_names, ext: str, pf) -> None:
    """Persist one OVA class's B bagged forests + progress trail (member
    ``b*K + k`` scores class k via its ``class_index`` extra)."""
    for b, res in enumerate(results):
        if alg != Algorithm.GBT:
            res.spec_kwargs["algorithm"] = \
                "RF" if alg != Algorithm.DT else "DT"
        res.spec_kwargs.setdefault("extra", {}).update(
            {"class_index": k, "n_classes": K})
        spec = tree_model.TreeModelSpec(
            n_trees=len(res.trees), depth=settings.depth,
            n_bins=n_bins, column_nums=list(col_nums),
            feature_names=feature_names, **res.spec_kwargs)
        tree_model.save_model(
            proc.paths.model_path(b * K + k, ext), spec, res.trees)
        for ti, (tr, va) in enumerate(res.history):
            pf.write(f"Class {k} Bag {b} Tree #{ti + 1} Train "
                     f"Error: {tr:.6f} Validation Error: "
                     f"{va:.6f}\n")
    pf.flush()
    log.info("train %s OVA class %d/%d: %d bagged forests, valid "
             "errs %s", alg.name, k + 1, K, len(results),
             [round(r.valid_error, 6) for r in results])


def _run_tree_ova_bagged(proc, shards, col_nums, cat_mask, n_bins,
                         settings: DTSettings, alg, K: int,
                         bags: int, streaming: bool = False) -> int:
    """OVA x bagging: B independent forests per class (reference runs one
    FULL bagging job per class, ``TrainModelProcessor.java:684-714``).
    Each class's B bags train as ONE vmapped multi-forest run (in-RAM) or
    as B sequential streamed jobs (``streaming=True``); model files
    follow the NN OVA convention (member ``b*K + k`` scores class k via
    its ``class_index`` extra — the scorer averages contributors per
    class, so file numbering is immaterial).  ``train -resume`` skips
    classes whose B models are all complete (per-class granularity; the
    un-bagged OVA path additionally restores mid-forest checkpoints)."""
    from ..parallel.mesh import device_mesh

    mc = proc.model_config
    mesh = device_mesh(n_ensemble=1)
    ext = alg.name.lower()
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    if not settings.resume:
        for f in os.listdir(proc.paths.models_dir):
            if f.startswith("model"):
                os.remove(os.path.join(proc.paths.models_dir, f))
    if streaming:
        _warn_streamed_stratified(mc)
        bins = y = w = None
        n = 0
    else:
        data = shards.load_all()
        bins, y, w = data["bins"].astype(np.int32), data["y"], data["w"]
        n = len(y)
    rf_like = alg != Algorithm.GBT
    settings_list = [replace(settings, seed=settings.seed + b)
                     for b in range(bags)]
    fi_total = np.zeros(len(col_nums))
    feature_names = shards.schema.get("columnNames")

    def fi_path(k: int) -> str:
        return os.path.join(proc.paths.tmp_dir, f"fi_class{k}.npy")

    def class_complete(k: int) -> bool:
        for b in range(bags):
            p = proc.paths.model_path(b * K + k, ext)
            if not os.path.isfile(p):
                return False
            spec_k, _ = tree_model.load_model(p)
            if spec_k.n_trees < settings.n_trees:
                return False
        return True

    with open(proc.paths.progress_path,
              "a" if settings.resume else "w") as pf:
        for k in range(K):
            if settings.resume and class_complete(k):
                log.info("train %s OVA class %d/%d: all %d bags complete, "
                         "skipping", alg.name, k + 1, K, bags)
                continue
            if streaming:
                # out-of-core: K x B sequential streamed jobs (the
                # reference's per-class bagging job queue); the class
                # binarizes on device via y_transform, the bag is a
                # stateless hash of the global row index
                yt = (lambda yv, k=k:
                      (np.asarray(yv) == k).astype(np.float32))
                results = [
                    _train_streamed_member(
                        alg, shards, mesh, n_bins, cat_mask,
                        settings_list[b],
                        _streamed_bag_mask_fn(mc, rf_like, bags,
                                              settings.seed, b),
                        y_transform=yt)
                    for b in range(bags)]
                ioutil.atomic_save_npy(
                    fi_path(k), np.sum([r.feature_importance
                                        for r in results], axis=0))
                _save_ova_bag_results(proc, results, alg, k, K, settings,
                                      n_bins, col_nums, feature_names,
                                      ext, pf)
                continue
            yk = (np.asarray(y) == k).astype(np.float32)
            tw_m, vw_m = _tree_member_masks(mc, n, bags, -1, rf_like, yk,
                                            settings.seed, distinct=True)
            if settings.early_stop and alg == Algorithm.GBT:
                # early stop is a per-run decision loop; honor it
                # sequentially (train_gbt_bagged trains full forests)
                results = [train_gbt(bins, yk,
                                     w * (tw_m[b] + vw_m[b] > 0), n_bins,
                                     cat_mask, settings_list[b], mesh=mesh)
                           for b in range(bags)]
            elif alg == Algorithm.GBT:
                results = train_gbt_bagged(
                    bins, yk, tw_m * w[None, :], vw_m * w[None, :], n_bins,
                    cat_mask, settings_list, mesh=mesh)
            else:
                results = train_rf_bagged(
                    bins, yk, tw_m * w[None, :], n_bins, cat_mask,
                    settings_list, mesh=mesh)
            ioutil.atomic_save_npy(
                fi_path(k), np.sum([r.feature_importance
                                    for r in results], axis=0))
            _save_ova_bag_results(proc, results, alg, k, K, settings,
                                  n_bins, col_nums, feature_names, ext, pf)
    for k in range(K):      # FI sidecars survive resume-skipped classes
        if os.path.isfile(fi_path(k)):
            fi_total += np.load(fi_path(k))
    _write_feature_importance(proc, col_nums, feature_names, fi_total)
    return 0


def _run_tree_ova(proc, shards, col_nums, cat_mask, n_bins,
                  settings: DTSettings, alg, K: int,
                  streaming: bool = False) -> int:
    """One-vs-all tree multiclass: K binary forests, ``model{k}`` scores
    class k (reference ``TrainModelProcessor.java:684-714`` runs one
    bagging job per class; here each class is a sequential forest on the
    full mesh).  Streamed data trains each class out-of-core over its own
    ResidentCache sweep.  ``train -resume`` restarts at the first
    unfinished class, restoring a mid-forest checkpoint for the class
    that was interrupted (reference combo ``-resume`` semantics)."""
    from ..parallel.mesh import device_mesh
    mesh = device_mesh(n_ensemble=1)
    ext = alg.name.lower()
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    if not settings.resume:
        for f in os.listdir(proc.paths.models_dir):
            if f.startswith("model"):
                os.remove(os.path.join(proc.paths.models_dir, f))
    bins = y = w = None
    if not streaming:
        data = shards.load_all()
        bins, y, w = data["bins"].astype(np.int32), data["y"], data["w"]

    # per-class FI sidecars: a resumed run skips finished classes but must
    # still report ALL classes' gains in feature_importance.json
    def fi_path(k: int) -> str:
        return os.path.join(proc.paths.tmp_dir, f"fi_class{k}.npy")

    with open(proc.paths.progress_path,
              "a" if settings.resume else "w") as pf:
        for k in range(K):
            model_path = proc.paths.model_path(k, ext)
            if settings.resume and os.path.isfile(model_path):
                spec_k, trees_k = tree_model.load_model(model_path)
                if spec_k.n_trees >= settings.n_trees:
                    log.info("train %s OVA class %d/%d: already complete "
                             "(%d trees), skipping", alg.name, k + 1, K,
                             spec_k.n_trees)
                    continue
            init_trees, init_score, start_history = (None, None, None)
            init_scores = None
            if settings.resume:
                ck = _forest_checkpoint_path(proc, f"_c{k}")
                if os.path.isfile(ck):
                    spec_c, init_trees = tree_model.load_model(ck)
                    init_score = spec_c.init_score
                    meta = {}
                    if os.path.isfile(ck + ".meta.json"):
                        with open(ck + ".meta.json") as f:
                            meta = json.load(f)
                    start_history = [tuple(h)
                                     for h in meta.get("history", [])]
                    try:               # byte-exact f restore (see
                        d = np.load(ck + ".scores.npz")  # _restore_or_…)
                        if int(d["trees_done"]) == len(init_trees):
                            init_scores = np.asarray(d["f"], np.float32)
                    except (OSError, ValueError, KeyError):
                        pass
                    log.info("OVA resume: class %d restarts from %d "
                             "checkpointed trees", k, len(init_trees))
            ckpt_fn = _forest_checkpoint_fn(proc, settings, alg, n_bins,
                                            col_nums, shards,
                                            suffix=f"_c{k}")

            def progress(ti, tr, va, k=k):
                pf.write(f"Class {k} Tree #{ti + 1} Train Error: {tr:.6f} "
                         f"Validation Error: {va:.6f}\n")
                pf.flush()

            if streaming:
                def yk_transform(yv, k=k):
                    return (np.asarray(yv) == k).astype(np.float32)
                if alg == Algorithm.GBT:
                    res = train_gbt_streamed(
                        _tree_stream(shards, mesh), n_bins, cat_mask,
                        settings, progress, init_trees=init_trees,
                        init_score=init_score, checkpoint_fn=ckpt_fn,
                        start_history=start_history, mesh=mesh,
                        y_transform=yk_transform,
                        init_scores=init_scores)
                else:
                    res = train_rf_streamed(
                        _tree_stream(shards, mesh), n_bins, cat_mask,
                        settings, progress, checkpoint_fn=ckpt_fn,
                        init_trees=init_trees,
                        start_history=start_history, mesh=mesh,
                        y_transform=yk_transform)
            else:
                yk = (np.asarray(y) == k).astype(np.float32)
                if alg == Algorithm.GBT:
                    res = train_gbt(bins, yk, w, n_bins, cat_mask, settings,
                                    progress, init_trees=init_trees,
                                    init_score=init_score,
                                    checkpoint_fn=ckpt_fn,
                                    start_history=start_history, mesh=mesh,
                                    init_scores=init_scores)
                else:
                    res = train_rf(bins, yk, w, n_bins, cat_mask, settings,
                                   progress, checkpoint_fn=ckpt_fn,
                                   init_trees=init_trees,
                                   start_history=start_history, mesh=mesh)
            if alg != Algorithm.GBT:
                res.spec_kwargs["algorithm"] = \
                    "RF" if alg != Algorithm.DT else "DT"
            res.spec_kwargs.setdefault("extra", {}).update(
                {"class_index": k, "n_classes": K})
            spec = tree_model.TreeModelSpec(
                n_trees=len(res.trees), depth=settings.depth, n_bins=n_bins,
                column_nums=list(col_nums),
                feature_names=shards.schema.get("columnNames"),
                **res.spec_kwargs)
            tree_model.save_model(model_path, spec, res.trees)
            ioutil.atomic_save_npy(fi_path(k),
                                   np.asarray(res.feature_importance))
            log.info("train %s OVA class %d/%d: %d trees, valid err %.6f",
                     alg.name, k + 1, K, res.trees_built, res.valid_error)
    fi_total = np.zeros(len(col_nums))
    for k in range(K):
        if os.path.isfile(fi_path(k)):
            fi_total += np.load(fi_path(k))
        else:                                         # pragma: no cover
            log.warning("OVA class %d has no stored feature importance "
                        "(pre-resume run?); totals omit it", k)
    _write_feature_importance(proc, col_nums,
                              shards.schema.get("columnNames"), fi_total)
    return 0


def _run_tree_multi(proc, shards, col_nums, cat_mask, n_bins, alg,
                    trials, is_gs: bool, kfold: int, bags: int) -> int:
    """Tree grid search / bagging / k-fold (reference
    ``TrainModelProcessor.java:768-945`` runs one Guagua job per
    bag/combo/fold; ``gs/GridSearch.java:62`` is algorithm-agnostic).

    Same-structure members train as ONE vmapped multi-forest executable
    (:func:`train_gbt_bagged` / :func:`train_rf_bagged`); structurally
    different grid trials run group by group.  Streamed data or early
    stop falls back to sequential full runs per member — the reference's
    own job-queue shape."""
    from ..parallel.mesh import device_mesh
    from ..train.grid_search import tree_stackable_groups

    mc = proc.model_config
    mesh = device_mesh(n_ensemble=1)
    streaming = proc._use_streaming(shards, shards.schema) \
        if hasattr(proc, "_use_streaming") else False
    if streaming and kfold and kfold > 1:
        log.warning("k-fold CV ignores streaming mode (the held-out fold "
                    "vote needs full-data passes); folds train in-RAM")
        streaming = False
    if streaming:
        bins = y = w = None
    else:
        data = shards.load_all()
        bins, y, w = data["bins"].astype(np.int32), data["y"], data["w"]
        n = len(y)

    base = settings_from_params(mc.train.params if not is_gs else trials[0],
                                mc.train, alg)
    base.stats_exact = not mc.dataSet.weightColumnName
    if is_gs:
        settings_list = [settings_from_params(t, mc.train, alg)
                         for t in trials]
        for s in settings_list:
            s.stats_exact = base.stats_exact
        member_trials = list(range(len(trials)))
    else:
        B = kfold if (kfold and kfold > 1) else bags
        settings_list = [replace(base, seed=base.seed + b)
                         for b in range(B)]
        member_trials = [None] * B

    ext = alg.name.lower()
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    os.makedirs(proc.paths.tmp_dir, exist_ok=True)

    rf_like = alg != Algorithm.GBT
    if streaming:
        # out-of-core members: sequential full streamed runs — the
        # reference's own shape (one Guagua job per bag/combo over the
        # same HDFS data, SHIFU_TRAIN_BAGGING_INPARALLEL queue); each
        # member's bag/split is a stateless hash of the global row index
        from ..data.streaming import mask_fn_from_settings
        _warn_streamed_stratified(mc)
        B = len(settings_list)

        def member_mask(i: int):
            """Member i's (train_w, valid_w) window mask: grid trials
            share ONE split (isolate the hypers); bagging members follow
            the shared :func:`_streamed_bag_mask_fn` seed/row policy."""
            if not is_gs:
                return _streamed_bag_mask_fn(mc, rf_like, B, base.seed, i)
            mm = mask_fn_from_settings(
                1, valid_rate=0.0 if rf_like else mc.train.validSetRate,
                sample_rate=mc.train.baggingSampleRate,
                replacement=mc.train.baggingWithReplacement,
                seed=base.seed)

            def mf(idx, tgt):
                t, v = mm(idx, tgt)
                return t[0], v[0]
            return mf

        def run_members(idxs: List[int]) -> List[ForestResult]:
            return [_train_streamed_member(alg, shards, mesh, n_bins,
                                           cat_mask, settings_list[i],
                                           member_mask(i))
                    for i in idxs]
    else:
        def run_members(idxs: List[int]) -> List[ForestResult]:
            sl = [settings_list[i] for i in idxs]
            if base.early_stop and alg == Algorithm.GBT:
                # early stop is a per-run decision loop; honor it
                # sequentially
                return [train_gbt(bins, y, w * (tw_m[i] + vw_m[i] > 0),
                                  n_bins, cat_mask, sl[j], mesh=mesh)
                        for j, i in enumerate(idxs)]
            if alg == Algorithm.GBT:
                return train_gbt_bagged(bins, y, tw_m[idxs] * w[None, :],
                                        vw_m[idxs] * w[None, :], n_bins,
                                        cat_mask, sl, mesh=mesh)
            return train_rf_bagged(bins, y, tw_m[idxs] * w[None, :], n_bins,
                                   cat_mask, sl, mesh=mesh)

        # sampling masks: grid trials share ONE split (isolate the
        # hypers); bagging/k-fold members each get their bag/fold
        # (reference bagging sample rate / CV folds)
        if is_gs:
            tw1, vw1 = _tree_member_masks(mc, n, 1, -1, rf_like, y,
                                          base.seed)
            tw_m = np.repeat(tw1, len(trials), axis=0)
            vw_m = np.repeat(vw1, len(trials), axis=0)
        else:
            tw_m, vw_m = _tree_member_masks(mc, n, bags, kfold, rf_like, y,
                                            base.seed, distinct=True)

    results: List[Optional[ForestResult]] = [None] * len(settings_list)
    trees_c = obs.counter("train.trees")
    with open(proc.paths.progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
        groups = tree_stackable_groups(trials) if is_gs \
            else [list(range(len(settings_list)))]
        for group in groups:
            for j, res in zip(group, run_members(group)):
                results[j] = res
                label = f"Trial [{j}]" if is_gs else f"Bag [{j}]"
                for ti, (tr, va) in enumerate(res.history):
                    pf.write(f"{label} Tree #{ti + 1} Train Error: "
                             f"{tr:.6f} Validation Error: {va:.6f}\n")
                pf.flush()
                trees_c.inc(res.trees_built)
                obs.event("forest_member", trainer=alg.name.lower(),
                          member=j, trees=res.trees_built,
                          valid_err=round(res.valid_error, 6))

    if rf_like and kfold and kfold > 1 and not is_gs:
        # RF k-fold: oob error is in-fold; the CV figure of merit is the
        # mean-vote error on the HELD-OUT fold (reference CV semantics)
        from ..ops.tree import predict_forest
        for i, res in enumerate(results):
            fold = vw_m[i] > 0
            vote = predict_forest(res.trees, bins[fold])
            yf, wf = y[fold], (w * vw_m[i])[fold]
            if base.loss == "log":
                p = np.clip(vote, 1e-9, 1 - 1e-9)
                per = -(yf * np.log(p) + (1 - yf) * np.log(1 - p))
            else:
                per = (yf - vote) ** 2
            res.valid_error = float((per * wf).sum() / max(wf.sum(), 1e-9))

    feature_names = shards.schema.get("columnNames")

    def save(res: ForestResult, member: int, s: DTSettings) -> None:
        kw = dict(res.spec_kwargs)
        if alg != Algorithm.GBT:
            kw["algorithm"] = "RF" if alg != Algorithm.DT else "DT"
        spec = tree_model.TreeModelSpec(
            n_trees=len(res.trees), depth=s.depth, n_bins=n_bins,
            column_nums=list(col_nums), feature_names=feature_names, **kw)
        tree_model.save_model(proc.paths.model_path(member, ext), spec,
                              res.trees)

    if is_gs:
        from ..train.grid_search import rank_and_report
        order = rank_and_report(proc.paths.tmp_dir,
                                [r.valid_error for r in results], trials)
        best = order[0]
        log.info("grid search: best trial #%d valid error %.6f params %s",
                 best, results[best].valid_error, trials[best])
        save(results[best], 0, settings_list[best])
    else:
        for i, res in enumerate(results):
            save(res, i, settings_list[i])
        log.info("saved %d bagged %s model(s); valid errors %s", len(results),
                 alg.name, [round(r.valid_error, 6) for r in results])
    _write_feature_importance(
        proc, col_nums, feature_names,
        np.sum([r.feature_importance for r in results], axis=0))
    return 0


def run_tree_training(proc) -> int:
    """Entry called by TrainProcessor for GBT/RF/DT."""
    mc = proc.model_config
    alg = mc.train.algorithm
    shards = proc._open_shards(proc.paths.clean_dir) \
        if hasattr(proc, "_open_shards") \
        else Shards.open(proc.paths.clean_dir)
    col_nums = shards.schema.get("columnNums", [])
    by_num = {c.columnNum: c for c in proc.column_configs}
    cat_mask = np.array([by_num[cn].is_categorical() if cn in by_num else False
                         for cn in col_nums])
    # bin-space width from ColumnConfig (num value bins + the missing bin) —
    # NOT from observed data, which may lack rare bins under sampling and
    # would make eval-time indices overflow the left_mask
    n_bins = max((by_num[cn].num_bins() + 1 for cn in col_nums if cn in by_num),
                 default=2)
    trials = proc._trials(dict(mc.train.params or {}))
    is_gs = len(trials) > 1
    kfold = mc.train.numKFold if mc.train.isCrossValidation else -1
    bags = 1 if is_gs else max(1, mc.train.baggingNum)
    multi = is_gs or bags > 1 or (kfold and kfold > 1)
    # trials[0] == params when no grid axes; raw params may hold lists
    settings = settings_from_params(trials[0], mc.train, alg)
    settings.resume = bool(proc.params.get("resume"))
    settings.checkpoint_dir = proc.paths.checkpoint_dir
    # no weight column -> RF stat channels are small-integer-exact in bf16
    # (streamed windows can't inspect the data up front; resident paths
    # also auto-detect from the weights themselves)
    settings.stats_exact = not mc.dataSet.weightColumnName

    K = len(mc.dataSet.posTags) if mc.is_multi_class() else 0
    if K > 2 and multi:
        from ..config.model_config import MultipleClassification
        ova = mc.train.multiClassifyMethod == \
            MultipleClassification.ONEVSALL or alg == Algorithm.GBT
        if ova and bags > 1 and not is_gs and not (kfold and kfold > 1):
            streaming = proc._use_streaming(shards, shards.schema) \
                if hasattr(proc, "_use_streaming") else False
            return _run_tree_ova_bagged(proc, shards, col_nums, cat_mask,
                                        n_bins, settings, alg, K, bags,
                                        streaming=streaming)
        from ..config.validator import ValidationError
        what = "grid search / k-fold" if (is_gs or (kfold and kfold > 1)) \
            else "bagging with NATIVE multi-class"
        raise ValidationError(
            [f"{what} is not supported with multi-class tree training — "
             "train trials/folds individually, or use ONEVSALL (OVA "
             "bagging is supported)"])
    if multi:
        return _run_tree_multi(proc, shards, col_nums, cat_mask, n_bins,
                               alg, trials, is_gs, kfold, bags)
    streaming = proc._use_streaming(shards, shards.schema) \
        if hasattr(proc, "_use_streaming") else False
    if K > 2:
        from ..config.model_config import MultipleClassification
        # GBT has no NATIVE multiclass mode (reference restricts NATIVE to
        # NN/RF, ``TrainModelProcessor.java:347-349``)
        if mc.train.multiClassifyMethod == MultipleClassification.ONEVSALL \
                or alg == Algorithm.GBT:
            return _run_tree_ova(proc, shards, col_nums, cat_mask, n_bins,
                                 settings, alg, K, streaming=streaming)
        settings.n_classes = K
        settings.loss = "squared"          # errors are misclassification
        if settings.impurity not in ("entropy", "gini"):
            settings.impurity = "entropy"

    ckpt_fn = _forest_checkpoint_fn(proc, settings, alg, n_bins, col_nums,
                                    shards)

    progress_path = proc.paths.progress_path
    with open(progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
        def progress(ti, tr, va):
            line = (f"Tree #{ti + 1} Train Error: {tr:.6f} "
                    f"Validation Error: {va:.6f}")
            pf.write(line + "\n")
            pf.flush()
            obs.counter("train.trees").inc()
            obs.event("tree", trainer=alg.name.lower(), tree=ti + 1,
                      train_err=round(tr, 6), valid_err=round(va, 6))
            faults.fire("train", "tree", ti + 1)
            if (ti + 1) % 5 == 0 or ti == 0:
                log.info(line)

        init_trees, init_score, start_history, init_scores = \
            _restore_or_continuous(proc, alg, settings)
        refresh_extra = int(proc.params.get("refresh_extra") or 0)
        if refresh_extra and init_trees:
            # refresh warm-start: the budget is N MORE trees APPENDED
            # past the restored forest (a plain resume keeps TreeNum);
            # on the new data window the restored scores replay unless
            # the byte-exact sidecar still covers the exact same rows
            settings.n_trees = len(init_trees) + refresh_extra
            # an early-stop that tripped on the OLD stream must not veto
            # appending trees for the new window: don't replay it
            start_history = None
            log.info("refresh warm-start: %d restored trees + %d new "
                     "(target %d)", len(init_trees), refresh_extra,
                     settings.n_trees)
        if init_scores is not None and len(init_scores) != shards.num_rows:
            # the sidecar pinned f for a DIFFERENT plane (data-window
            # cursor sliced it, or new rows landed) — fall back to
            # replaying the restored trees over the current rows
            log.info("checkpoint scores cover %d rows, plane has %d — "
                     "replaying restored trees instead",
                     len(init_scores), shards.num_rows)
            init_scores = None
        from ..parallel.mesh import device_mesh
        mesh = device_mesh(n_ensemble=1)   # trees are sequential: all devices
        if streaming:                      # on the data axis
            stream = _tree_stream(shards, mesh, dict(mc.train.params or {}))
            log.info("train %s STREAMED: %d rows, window %d rows, mesh %s",
                     alg.name, stream.num_rows, stream.window_rows,
                     dict(mesh.shape))
            if alg == Algorithm.GBT:
                res = train_gbt_streamed(stream, n_bins, cat_mask, settings,
                                         progress, init_trees=init_trees,
                                         init_score=init_score,
                                         checkpoint_fn=ckpt_fn,
                                         start_history=start_history,
                                         mesh=mesh,
                                         init_scores=init_scores)
            else:
                res = train_rf_streamed(stream, n_bins, cat_mask, settings,
                                        progress, checkpoint_fn=ckpt_fn,
                                        init_trees=init_trees,
                                        start_history=start_history,
                                        mesh=mesh)
        else:
            data = shards.load_all()
            bins, y, w = data["bins"].astype(np.int32), data["y"], data["w"]
            log.info("train %s: %d rows x %d features, %d bins, %d trees "
                     "depth %d", alg.name, *bins.shape, n_bins,
                     settings.n_trees, settings.depth)
            if alg == Algorithm.GBT:
                res = train_gbt(bins, y, w, n_bins, cat_mask, settings,
                                progress, init_trees=init_trees,
                                init_score=init_score, checkpoint_fn=ckpt_fn,
                                start_history=start_history, mesh=mesh,
                                init_scores=init_scores)
            else:
                res = train_rf(bins, y, w, n_bins, cat_mask, settings,
                               progress, checkpoint_fn=ckpt_fn,
                               init_trees=init_trees,
                               start_history=start_history, mesh=mesh)
        if alg != Algorithm.GBT:
            res.spec_kwargs["algorithm"] = "RF" if alg != Algorithm.DT else "DT"

    spec = tree_model.TreeModelSpec(
        n_trees=len(res.trees), depth=settings.depth, n_bins=n_bins,
        column_nums=list(col_nums),
        feature_names=shards.schema.get("columnNames"),
        **res.spec_kwargs)
    os.makedirs(proc.paths.models_dir, exist_ok=True)
    for f in os.listdir(proc.paths.models_dir):
        if f.startswith("model"):
            os.remove(os.path.join(proc.paths.models_dir, f))
    path = proc.paths.model_path(0, alg.name.lower())
    tree_model.save_model(path, spec, res.trees)

    fi_named = sorted(
        ((shards.schema.get("columnNames", [str(cn) for cn in col_nums])[j],
          float(v)) for j, v in enumerate(res.feature_importance)),
        key=lambda kv: -kv[1])
    with open(os.path.join(proc.paths.tmp_dir, "feature_importance.json"),
              "w") as fjson:
        json.dump({k: v for k, v in fi_named}, fjson, indent=2)
    obs.gauge("train.valid_err").set(res.valid_error)
    obs.gauge("train.trees_built").set(res.trees_built)
    log.info("train %s done: %d trees, train err %.6f valid err %.6f; "
             "top features %s", alg.name, res.trees_built, res.train_error,
             res.valid_error, [n for n, _ in fi_named[:5]])
    return 0


def _forest_checkpoint_path(proc, suffix: str = "") -> str:
    return os.path.join(proc.paths.checkpoint_dir,
                        f"forest_ckpt{suffix}.npz")


def _forest_checkpoint_fn(proc, settings: DTSettings, alg, n_bins, col_nums,
                          shards, suffix: str = ""):
    """Mid-forest checkpoint (reference ``DTMaster.doCheckPoint`` every
    checkpointInterval iterations): partial forest + history persist; a
    killed run resumes from the last saved tree.  ``suffix`` separates
    per-class OVA checkpoints (``forest_ckpt_c{k}.npz``).  ``scores``
    (GBT per-row f) rides a sidecar so resume restores f BYTE-exact
    instead of replaying trees (replay is only f32-equivalent)."""
    def save(trees, history, init_score, scores=None):
        from ..ioutil import atomic_savez, atomic_write_json
        os.makedirs(proc.paths.checkpoint_dir, exist_ok=True)
        spec = tree_model.TreeModelSpec(
            n_trees=len(trees), depth=settings.depth, n_bins=n_bins,
            column_nums=list(col_nums),
            feature_names=shards.schema.get("columnNames"),
            algorithm=alg.name, loss=settings.loss,
            learning_rate=settings.learning_rate,
            init_score=init_score if init_score is not None else 0.0)
        path = _forest_checkpoint_path(proc, suffix)
        tmp = path + ".tmp"
        tree_model.save_model(tmp, spec, trees)
        os.replace(tmp, path)
        spath = path + ".scores.npz"
        if scores is not None:
            atomic_savez(spath, f=np.asarray(scores, np.float32),
                         trees_done=np.asarray(len(trees), np.int64))
        else:
            try:           # never pair stale scores with a newer forest
                os.remove(spath)
            except OSError:
                pass
        atomic_write_json(path + ".meta.json",
                          {"trees_done": len(trees), "history": history,
                           "seed": settings.seed}, indent=0)
        log.info("forest checkpoint: %d trees", len(trees))
    return save


def _restore_or_continuous(proc, alg, settings: DTSettings):
    """Resume order: explicit ``train -resume`` from the mid-forest
    checkpoint, else continuous training from the final saved model.
    Returns (trees, init_score, history, scores) — ``scores`` is the
    checkpointed per-row f (None for continuous / legacy checkpoints;
    the trainers then fall back to tree replay)."""
    if settings.resume:
        path = _forest_checkpoint_path(proc)
        if os.path.isfile(path):
            spec, trees = tree_model.load_model(path)
            meta = {}
            if os.path.isfile(path + ".meta.json"):
                with open(path + ".meta.json") as f:
                    meta = json.load(f)
            history = [tuple(h) for h in meta.get("history", [])]
            scores = None
            try:
                d = np.load(path + ".scores.npz")
                if int(d["trees_done"]) == len(trees):
                    scores = np.asarray(d["f"], np.float32)
            except (OSError, ValueError, KeyError):
                pass
            log.info("resume: restored %d trees from forest checkpoint"
                     "%s", len(trees),
                     " (+ per-row scores)" if scores is not None else "")
            return trees, spec.init_score, history, scores
    init_trees, init_score = _continuous_trees(proc, alg, settings)
    return init_trees, init_score, None, None


def _continuous_trees(proc, alg, settings: DTSettings
                      ) -> Tuple[Optional[List[TreeArrays]], Optional[float]]:
    """GBT continuous training appends trees to the existing forest —
    guarded like reference ``checkContinuousTraining``: the saved forest's
    shrinkage/loss must match or resuming would mis-score the old trees."""
    if not proc.model_config.train.isContinuous or alg != Algorithm.GBT:
        return None, None
    path = proc.paths.model_path(0, alg.name.lower())
    if not os.path.isfile(path):
        return None, None
    spec, trees = tree_model.load_model(path)
    if spec.loss != settings.loss or \
            abs(spec.learning_rate - settings.learning_rate) > 1e-12:
        log.warning("continuous GBT: saved forest used loss=%s lr=%s but "
                    "params now say loss=%s lr=%s — training fresh",
                    spec.loss, spec.learning_rate, settings.loss,
                    settings.learning_rate)
        return None, None
    log.info("continuous GBT: resuming from %d existing trees", len(trees))
    return trees, spec.init_score
